"""Static-graph Executor.

TPU-native re-design of the reference Executor (reference:
python/paddle/fluid/executor.py Executor:916 run:1391,
framework/executor.cc:460 op-by-op loop).  Instead of running the op list
one kernel at a time, the whole Program — forward, backward, and optimizer
update — is interpreted once under ``jax.jit`` and compiled to a single
XLA computation per feed signature (the design the reference approaches
with ParallelExecutor + fuse passes).

Hot path (the donated, device-resident, async-dispatch design):

- After first compile, parameter arrays and optimizer slots live in a
  per-Program ``_ExecState`` as jax buffers threaded run-to-run through
  the compiled step with ``donate_argnums`` (``FLAGS_static_donate``),
  so weights update in place on device and no Python loop touches every
  parameter each step.  ``Parameter.data`` resolves reads through the
  live state lazily (core/tensor.py) and is flushed back on ``close()``
  or program edit; any array a user reads escapes the donated set via a
  copy before the next run, so donation never invalidates user-held
  references.
- ``lr``/step counters/RNG folding are in-graph (donated aux carry):
  ``run`` performs zero per-step host->device scalar uploads (the lr is
  re-uploaded only when the schedule moves it, mirroring jit.TrainStep).
- Dispatch is asynchronous: ``run(..., return_numpy=False)`` returns
  device-array Tensors without ``block_until_ready``; only
  ``return_numpy=True`` syncs.  Feeds that are already jax arrays (or
  Tensors) pass through untouched — no NumPy round-trip.

Training: ``optimizer.minimize(loss)`` under ``paddle.enable_static()``
attaches (optimizer, loss) to the Program; ``run`` then computes grads
with ``jax.grad`` over the program's Parameters and applies the update
in-graph (the scope write-back of the reference's sgd ops is now the
lazy ``Parameter.data`` resolution above).
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import obs_hook
from ..core.flags import get_flag
from ..core.tensor import Tensor
from .program import Program, Variable, default_main_program

__all__ = ["Executor", "global_scope"]


class _Scope:
    """Name → array map shim (reference: framework/scope.h)."""

    def __init__(self):
        self.vars: Dict[str, object] = {}

    def find_var(self, name):
        return self.vars.get(name)


_global_scope = _Scope()


def global_scope() -> _Scope:
    return _global_scope


def _interp(nodes, env, pmap):
    """Run the op list; ``env`` maps Variable name → array, ``pmap`` maps
    id(Parameter) → array.  Composite control-flow nodes re-run user
    closures under a replay scope resolving Variables via ``env``."""
    from ..core import autograd
    from ..core.tensor import Parameter
    from .program import replay_scope

    def lookup(v):
        if isinstance(v, Parameter):
            return pmap.get(id(v), v.data)
        return env[v.name]

    with replay_scope(lookup), autograd.no_grad():
        for node in nodes:
            args = []
            for tag, v in node.in_specs:
                if tag == "v":
                    args.append(env[v.name])
                elif tag == "p":
                    args.append(pmap[id(v)])
                else:  # const / literal
                    args.append(v)
            outs = node.fn(*args, **node.kw)
            outs = list(outs) if node.multi else [outs]
            for var, o in zip(node.out_vars, outs):
                env[var.name] = o
    return env


class _ExecState:
    """Per-Program device-resident execution state (the donated hot path).

    The authoritative parameter arrays (and, once training starts, the
    optimizer slots and the aux carry: run/step counters) live HERE as
    jax buffers, threaded run-to-run through the compiled executable —
    donated under FLAGS_static_donate, so XLA updates weights in place.
    Bound Parameters resolve ``.data`` reads through this object
    (core/tensor.py Parameter.data); ``flush()`` materialises the
    current arrays back into the Parameter slots (close(), program
    edit, or another state taking the params over).

    Aliasing safety: ``fetch_param`` marks the read index as escaped;
    ``shield_escaped`` copies those slots out of the donated set before
    the next donated dispatch, so arrays handed to user code are never
    invalidated.  Binding changes anywhere in the process bump the
    class-wide generation counter; ``refresh`` revalidates bindings only
    when it moved — O(1) steady state while one state owns its params
    exclusively (the single-program train loop).  When two Programs
    SHARE Parameters and alternate runs, each switch deliberately steals
    the bindings back (O(n) rebind + one protective copy per stolen
    param under donation): correctness-first — values flow through, they
    never fork — at the cost of the zero-copy property across the
    switch.  Keep shared-param programs on the same values, or turn
    FLAGS_static_donate off, if that copy matters.
    """

    _GEN = [0]  # process-wide binding generation (shared mutable cell)

    __slots__ = ("serial", "version", "params", "p_arrays", "opt_state",
                 "aux", "t_idx", "escaped", "gen", "lr_value", "lr_device",
                 "seed_val", "base_key", "no_seed", "synced_step",
                 "gc_key", "last_sentry", "__weakref__")

    def __init__(self, program, params):
        self.serial = program._serial
        self.version = program._version
        self.params = list(params)
        self.p_arrays: List = [None] * len(self.params)
        self.opt_state = None
        self.aux = None
        self.t_idx = None
        self.escaped = set()
        self.gen = -1
        self.lr_value = None
        self.lr_device = None
        self.seed_val = None
        self.base_key = None
        self.no_seed = None
        self.synced_step = None
        self.gc_key = None   # plan fingerprint the residual carry is for
        self.last_sentry = None  # (run_i, [flag, nf, extra, norm2])
        self._bind_all()

    # -- binding -----------------------------------------------------------
    def _bind_all(self):
        """(Re)claim every param: keep arrays already bound to us, read
        the rest through ``Parameter.data`` (which resolves a previous
        owner's live state or the raw slot) and bind them here.  Freshly
        read arrays are user-visible, so they start escaped — the first
        donated run copies them instead of invalidating them."""
        changed = False
        for i, p in enumerate(self.params):
            src = getattr(p, "_exec_src", None)
            if src is not None and src[0] is self and src[1] == i:
                continue
            self.p_arrays[i] = jnp.asarray(p.data)
            p._exec_src = (self, i)
            self.escaped.add(i)
            changed = True
        if changed:
            # two Parameters may share one buffer (tied init, user
            # aliasing) — a buffer must appear in the donated set once
            seen: Dict[int, int] = {}
            for i, a in enumerate(self.p_arrays):
                if id(a) in seen:
                    self.p_arrays[i] = jnp.array(a, copy=True)
                else:
                    seen[id(a)] = i
            _ExecState._GEN[0] += 1
        self.gen = _ExecState._GEN[0]

    def refresh(self):
        """O(1) when no binding moved since our last run; revalidates
        (absorbing user writes to ``Parameter.data`` and params stolen
        by another Executor/state) otherwise."""
        if self.gen != _ExecState._GEN[0]:
            self._bind_all()

    def flush(self):
        """Write the current arrays back into the Parameter slots and
        unbind (lazy write-back resolution point)."""
        for i, p in enumerate(self.params):
            src = getattr(p, "_exec_src", None)
            if src is not None and src[0] is self:
                p.data = self.p_arrays[i]  # setter unbinds + writes slot

    # -- Parameter.data protocol (called from core/tensor.py) --------------
    def fetch_param(self, i):
        self.escaped.add(i)
        return self.p_arrays[i]

    def param_written(self, i):
        # the Parameter unbound itself; force revalidation everywhere
        _ExecState._GEN[0] += 1

    # -- donation safety ---------------------------------------------------
    def shield_escaped(self):
        """Copy escaped arrays out of the donated set: the user may hold
        the old reference, and the next donated dispatch would otherwise
        delete its buffer."""
        if self.escaped:
            for i in self.escaped:
                self.p_arrays[i] = jnp.array(self.p_arrays[i], copy=True)
            self.escaped.clear()

    # -- optimizer.state_dict support --------------------------------------
    def export_slots(self):
        """Optimizer slot arrays keyed by the param's position in
        ``program.parameters()`` — static-mode ``optimizer.state_dict``
        reads slots from here (they never live in Optimizer._slots on
        the static path)."""
        out = {}
        if self.opt_state and self.t_idx is not None:
            for pos, i in enumerate(self.t_idx):
                s = self.opt_state[pos]
                if s:
                    out[str(i)] = {k: np.asarray(v) for k, v in s.items()}
        return out


# serializes first-call compiles of sharded executables: the config
# flip below is process-global, so concurrent flips could restore the
# flag mid-compile of the other thread and let a sharded executable
# reach the poisoned persistent cache after all
_CACHE_FLIP_LOCK = threading.Lock()


def _no_persistent_cache_first_call(jitted):
    """jaxlib 0.4.37's persistent compilation cache corrupts the heap
    when it RELOADS an executable that was compiled with explicit
    NamedShardings (repro: two processes running the same sharded
    program with jax_compilation_cache_dir set — the second dies with
    'corrupted double-linked list').  Sharded executables therefore
    compile with the persistent cache disabled: only the first call
    (the one that compiles, and would otherwise serialize/deserialize)
    pays the config flip + lock; steady-state dispatch is untouched."""
    warmed = []

    def compiled(*args):
        if warmed:
            return jitted(*args)
        with _CACHE_FLIP_LOCK:
            prev = jax.config.jax_enable_compilation_cache
            jax.config.update("jax_enable_compilation_cache", False)
            try:
                out = jitted(*args)
            finally:
                jax.config.update("jax_enable_compilation_cache", prev)
            warmed.append(True)
        return out

    return compiled


class Executor:
    """reference: fluid/executor.py:916.  ``place`` is accepted for parity;
    XLA owns device placement."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[tuple, object] = {}
        # keyed by Program._serial (monotonic, never recycled) — id()
        # keys could be reused after GC, handing a new Program a dead
        # program's run counter / optimizer slots.  Serials never
        # repeat, so entries for dead programs must be evicted: stale
        # VERSIONS are dropped on recompile (below); a per-program
        # finalizer reaps counters/state once the Program is
        # collectable (note the compiled cache itself pins the Program
        # through the node closures, so a sweep creating many programs
        # should call close() between trials).
        self._states: Dict[int, _ExecState] = {}
        self._run_counts: Dict[int, int] = {}
        # GSPMD sharding plans per program serial (fleet-marked
        # optimizers / explicit program rules); revalidated against the
        # live mesh + strategy identity each run — O(1) steady state
        self._plans: Dict[int, tuple] = {}
        self._verified: set = set()  # (serial, version) already checked
        # FLAGS_shard_verify: (serial, version, plan fingerprint)
        # triples already shardchecked — once per plan, like _verified
        self._shard_verified: set = set()
        self._tracked: set = set()   # serials with a finalizer attached
        # legacy (pre-change) path bookkeeping — see _run_legacy
        self._legacy_cache: Dict[tuple, object] = {}
        self._opt_states: Dict[int, list] = {}
        # observability: tests/bench/CI assert one compile per feed
        # signature and zero host feed conversions on the donated path
        self._compile_count = 0
        self._host_feed_converts = 0

    @property
    def compile_count(self) -> int:
        """Number of XLA compiles this Executor performed (one per
        (program version, feed signature, fetch set))."""
        return self._compile_count

    @property
    def host_feed_converts(self) -> int:
        """Number of feeds that took the NumPy host round-trip.  Stays 0
        when every feed is already a jax array / Tensor."""
        return self._host_feed_converts

    def _track(self, program):
        serial = program._serial
        if serial in self._tracked:
            return
        self._tracked.add(serial)
        # the closure references the containers, NOT self: the finalizer
        # must not keep the Executor alive
        states, opt, runs, ver, sver, plans = (
            self._states, self._opt_states, self._run_counts,
            self._verified, self._shard_verified, self._plans)

        def _evict():
            states.pop(serial, None)
            opt.pop(serial, None)
            runs.pop(serial, None)
            plans.pop(serial, None)
            for k in [k for k in ver if k[0] == serial]:
                ver.discard(k)
            for k in [k for k in sver if k[0] == serial]:
                sver.discard(k)

        weakref.finalize(program, _evict)

    def close(self):
        """Flush device-resident parameter state back into the
        ``Parameter`` objects, then drop all compiled programs and
        per-program state (run counters, optimizer slots).  Long-lived
        processes that build many throwaway Programs on one Executor
        should call this between trials — the compiled cache pins each
        Program's graph until then."""
        for state in self._states.values():
            state.flush()
        self._states.clear()
        self._cache.clear()
        self._legacy_cache.clear()
        self._opt_states.clear()
        self._run_counts.clear()
        self._verified.clear()
        self._shard_verified.clear()
        self._plans.clear()

    def sentry_stats(self, program=None) -> Optional[dict]:
        """The anomaly sentry's device-side counters for a program's
        live state (one sync), or None when no sentry-compiled step has
        run: ``skipped_steps`` (total sentry-skipped steps, carried in
        the donated aux tree — maintained with zero per-step host
        syncs) and the last step's flag/non-finite counts/grad norm."""
        if program is None:
            program = default_main_program()
        state = self._states.get(program._serial)
        if state is None or state.aux is None \
                or "skipped" not in state.aux:
            return None
        out = {"skipped_steps": int(np.asarray(state.aux["skipped"]))}
        if state.last_sentry is not None:
            run_i, (flag, nf, extra, norm2) = state.last_sentry
            out.update({
                "last_step": run_i,
                "last_flag": int(np.asarray(flag)),
                "last_nonfinite": np.asarray(nf).tolist(),
                "last_nonfinite_extra": int(np.asarray(extra)),
                "last_grad_norm": float(np.sqrt(np.asarray(norm2))),
            })
        return out

    # -- sharding ----------------------------------------------------------
    def _plan_for(self, program, params):
        """ShardingPlan for this program, or None.  A plan exists when
        the attached optimizer went through fleet.distributed_optimizer
        (it carries the DistributedStrategy) or the program carries
        explicit ``_sharding_rules``; the mesh is the global one (fleet
        .init derives it from the strategy).  Cached per serial and
        revalidated against (version, mesh, strategy, rules) identity."""
        pack = program._optimizer
        opt = pack[0] if pack is not None else None
        strategy = getattr(opt, "_dist_strategy", None) \
            if opt is not None else None
        rules = getattr(program, "_sharding_rules", None)
        if strategy is None and rules is None:
            return None
        from ..distributed.mesh import get_mesh, init_mesh
        mesh = get_mesh()
        if mesh is None:
            if strategy is None:
                return None
            mesh = init_mesh(
                strategy.infer_mesh_shape(len(jax.devices())))
        cached = self._plans.get(program._serial)
        if cached is not None:
            ver, cmesh, cstrat, crules, plan = cached
            if (ver == program._version and cmesh is mesh
                    and cstrat is strategy and crules is rules):
                return plan
        from ..distributed import sharding as _sh
        plan = _sh.plan_for_params(
            [(p.name, p) for p in params], strategy=strategy, mesh=mesh,
            rules=rules, label=f"program#{program._serial}")
        self._plans[program._serial] = (program._version, mesh, strategy,
                                        rules, plan)
        return plan

    def sharded_state(self, program=None):
        """The program's live execution state (params + optimizer slots
        + step counters) as a :class:`~paddle_tpu.distributed.sharding.
        ShardedState` — registrable with ``SnapshotStore`` for
        per-shard, digest-verified, *reshardable* checkpoints.  Save
        under one mesh, restore under another: the adapter reshards on
        load (gather-free when the layouts agree), writes arrays back
        into the donated state when it is live, and stages them on the
        Parameters / optimizer otherwise (a fresh process restores
        before its first compile)."""
        from ..distributed.sharding import ShardedState
        if program is None:
            program = default_main_program()

        # params are keyed by their POSITION in program.parameters()
        # (zero-padded so the tree round-trips in order) — the identity
        # the optimizer's pending-slot protocol already uses.  Names
        # from `unique_name` drift when the same model code is rebuilt
        # in one process (counters keep counting), while positions are
        # stable for an identical rebuild; restore validates shapes so
        # a structurally different program can't silently misbind.
        def _key(i):
            return f"{i:04d}"

        def getter():
            from .analysis.liveness import param_array
            params = program.parameters()
            state = self._states.get(program._serial)
            out = {"params": {}, "slots": {}, "aux": {}}
            if state is not None and state.version == program._version:
                for i, a in enumerate(state.p_arrays):
                    out["params"][_key(i)] = a
                if state.opt_state is not None:
                    for pos, i in enumerate(state.t_idx):
                        slots = state.opt_state[pos]
                        if slots:
                            out["slots"][_key(i)] = dict(slots)
                else:
                    # set_state_dict nulled the live opt_state and
                    # staged the checkpoint's slots on the optimizer —
                    # a save between that and the next run must still
                    # carry them
                    pack = program._optimizer
                    pending = (getattr(pack[0], "_static_pending_slots",
                                       None) if pack is not None
                               else None)
                    for k, sl in (pending or {}).items():
                        out["slots"][_key(int(k))] = {
                            sk: np.asarray(v) for sk, v in sl.items()}
                if state.aux is not None:
                    out["aux"] = {
                        "run": np.asarray(state.aux["run"]),
                        "step": np.asarray(state.aux["step"])}
                    # grad_comm error-feedback residuals ride the
                    # snapshot so a SAME-mesh rollback replays exactly
                    # (without them, the replayed quantized steps would
                    # correct against a later carry).  The restore side
                    # applies them only when the live carry's shapes
                    # match — a reshard (the [dp, numel] rows are
                    # per-OLD-device state) starts from a fresh carry,
                    # exactly as before.
                    ef = state.aux.get("grad_comm")
                    if ef:
                        out["ef"] = {_key(i): a
                                     for i, a in enumerate(ef)}
            else:
                for i, p in enumerate(params):
                    out["params"][_key(i)] = param_array(p)
                pack = program._optimizer
                if pack is not None:
                    # slots a restore staged before the first compile
                    # (setter below) must survive a save from this
                    # not-yet-live state — dropping them would silently
                    # reset Adam moments on the next restore
                    pending = getattr(pack[0], "_static_pending_slots",
                                      None)
                    for k, sl in (pending or {}).items():
                        out["slots"][_key(int(k))] = {
                            sk: np.asarray(v) for sk, v in sl.items()}
                    out["aux"] = {"run": np.asarray(
                        self._run_counts.get(program._serial, 0),
                        np.int32),
                        "step": np.asarray(pack[0]._step_count,
                                           np.int32)}
            return {k: v for k, v in out.items() if v}

        def setter(tree):
            params = program.parameters()
            ptree = tree.get("params", {})
            slots = tree.get("slots", {})
            aux = tree.get("aux", {})
            pack = program._optimizer
            opt = pack[0] if pack is not None else None
            for k, arr in ptree.items():
                i = int(k)
                if i >= len(params):
                    raise ValueError(
                        f"sharded checkpoint restore: saved param slot "
                        f"{i} but the program has {len(params)} params "
                        f"— the model structure changed since save")
                want = tuple(params[i].shape_tuple)
                got = tuple(arr.shape)
                if want != got:
                    raise ValueError(
                        f"sharded checkpoint restore: param {i} "
                        f"('{params[i].name}') has shape {want} but the "
                        f"snapshot holds {got} — the model structure "
                        f"changed since save")
            state = self._states.get(program._serial)
            if state is not None and state.version == program._version:
                for k, arr in ptree.items():
                    i = int(k)
                    state.p_arrays[i] = jnp.asarray(arr)
                    state.escaped.discard(i)
                if slots:
                    if state.opt_state is not None:
                        for pos, i in enumerate(state.t_idx):
                            if _key(i) in slots:
                                state.opt_state[pos] = {
                                    k: jnp.asarray(v)
                                    for k, v in slots[_key(i)].items()}
                    elif opt is not None:
                        # live state whose opt_state a set_state_dict
                        # nulled: stage the restored slots so the next
                        # run's functional_init reload picks them up
                        # instead of the stale pre-restore pending ones
                        opt._static_pending_slots = {
                            str(int(k)): {sk: np.asarray(v)
                                          for sk, v in sl.items()}
                            for k, sl in slots.items()}
                ef = tree.get("ef", {})
                if ef:
                    cur = (state.aux.get("grad_comm")
                           if state.aux is not None else None)
                    if (cur and len(ef) == len(cur)
                            and all(tuple(np.asarray(ef[_key(i)]).shape)
                                    == tuple(a.shape)
                                    for i, a in enumerate(cur))):
                        state.aux = dict(state.aux, grad_comm=[
                            jnp.asarray(ef[_key(i)])
                            for i in range(len(cur))])
                    else:
                        import warnings
                        warnings.warn(
                            "sharded checkpoint restore: snapshot "
                            "carries grad_comm error-feedback "
                            "residuals that do not match the live "
                            "carry (mesh or bucket layout changed) — "
                            "starting from a fresh residual carry")
                if aux and state.aux is not None:
                    step = int(np.asarray(aux["step"]))
                    run = int(np.asarray(aux.get(
                        "run", state.aux["run"])))
                    # dict(state.aux, ...) keeps non-counter carries
                    # (grad_comm residuals) a restore must not drop —
                    # they are error accumulators, not checkpoint state
                    state.aux = dict(state.aux,
                                     run=jnp.asarray(run, jnp.int32),
                                     step=jnp.asarray(step, jnp.int32))
                    self._run_counts[program._serial] = run
                    if opt is not None:
                        opt._step_count = step
                        state.synced_step = step
            else:
                for k, arr in ptree.items():
                    params[int(k)].data = arr
                if opt is not None and slots:
                    opt._static_pending_slots = {
                        str(int(k)): {sk: np.asarray(v)
                                      for sk, v in sl.items()}
                        for k, sl in slots.items()}
                if opt is not None and aux:
                    opt._step_count = int(np.asarray(aux["step"]))
                    self._run_counts[program._serial] = int(
                        np.asarray(aux.get("run", 0)))

        def specs(name):
            parts = name.split("/")
            if parts[0] == "ef" and len(parts) >= 2:
                # error-feedback residuals are [dp, numel] rows, one
                # per device — sharded over the dp axis by construction
                plan = self._plan_for(program, program.parameters())
                if plan is None:
                    return None
                from jax.sharding import PartitionSpec
                from ..distributed.mesh import DP_AXIS
                return PartitionSpec(DP_AXIS)
            if parts[0] not in ("params", "slots") or len(parts) < 2:
                return None
            plan = self._plan_for(program, program.parameters())
            if plan is None:
                return None
            try:
                return plan.param_spec(int(parts[1]))
            except (ValueError, IndexError):
                return None

        return ShardedState(getter=getter, setter=setter, specs=specs)

    # -- feeds -------------------------------------------------------------
    def _feed_array(self, a):
        """Feed → device array.  jax arrays and Tensors pass through
        untouched (no device→host→device bounce; also makes feeding a
        previous run's un-synced fetch safe); everything else takes the
        NumPy conversion path once, counted for the hot-path guards."""
        if isinstance(a, Tensor):
            a = a.data
        if isinstance(a, jax.Array):
            return a
        self._host_feed_converts += 1
        return jnp.asarray(np.asarray(a))

    # -- state -------------------------------------------------------------
    def _state_for(self, program, params) -> _ExecState:
        state = self._states.get(program._serial)
        if state is not None and state.version != program._version:
            # program edited since: flush the live values into the
            # Parameters and rebuild (the edit may add/remove params)
            state.flush()
            state = None
        if state is None:
            state = _ExecState(program, params)
            self._states[program._serial] = state
        else:
            state.refresh()
        return state

    # -- main entry --------------------------------------------------------
    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list: Optional[Sequence] = None, return_numpy=True,
            seed=None, **unused):
        # loaded inference programs (load_inference_model) call through
        if hasattr(program, "_run_loaded"):
            return program._run_loaded(feed, fetch_list, return_numpy)
        if program is None:
            program = default_main_program()
        # observability: a span per run when tracing is on (one
        # module-attribute None-check when off), and any exception
        # escaping the step feeds the crash flight recorder before
        # propagating — the executor is where a training step dies
        trc = obs_hook._tracer
        sid = (trc.begin_span("executor.run", program=program._serial)
               if trc is not None else None)
        try:
            return self._run(program, feed, fetch_list, return_numpy,
                             seed)
        except Exception as e:
            h = obs_hook._crash
            if h is not None:
                h(e, f"executor.run(program#{program._serial})")
            raise
        finally:
            if sid is not None:
                trc.end_span(sid)

    def _run(self, program, feed, fetch_list, return_numpy, seed):
        # chaos hook: lets fault specs crash a training step on demand
        # (preemption drills around the checkpoint/restore path)
        from ..testing import fault
        fault.point("executor.run", program._serial)
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        if not program.nodes:
            return []  # startup program: params already initialized eagerly

        fetch_names = []
        for f in fetch_list:
            if isinstance(f, Variable):
                fetch_names.append(f.name)
            elif isinstance(f, str):
                fetch_names.append(f)
            else:
                raise TypeError(f"fetch_list entry {f!r} is not a Variable")

        params = program.parameters()
        feed_items = sorted(feed.items())
        feed_names = tuple(n for n, _ in feed_items)
        # perf observatory (one module-attribute None-check when off):
        # host-side anatomy stamps around feed conversion and dispatch
        perf = obs_hook._perf
        t_h0 = time.perf_counter() if perf is not None else 0.0
        feed_arrays = [self._feed_array(a) for _, a in feed_items]
        t_h1 = time.perf_counter() if perf is not None else 0.0

        self._track(program)
        donate = bool(get_flag("static_donate"))
        # per-run counter doubles as the step correlation id: events
        # this run emits (compiles, checkpoint saves, fault fires)
        # carry it on the trace
        run_i = self._run_counts.get(program._serial, 0) + 1
        self._run_counts[program._serial] = run_i
        trc = obs_hook._tracer
        if trc is not None:
            trc.set_step(run_i)
        # chaos hook: a sleep-action rule here wedges the step mid-run
        # without raising — the hang (not crash) failure mode the
        # supervisor's watchdog exists to detect
        fault.point("executor.step_hang", program._serial,
                    f"step={run_i}")

        plan = self._plan_for(program, params)
        # the Pallas tier state is part of the cache key: flipping
        # FLAGS_use_pallas_kernels / FLAGS_pallas_interpret must
        # recompile (and attribute as new_pallas), never reuse an
        # executable built with the other tier baked in
        from ..ops.pallas.support import tier_enabled
        pallas_on = tier_enabled() and plan is None
        # the anomaly sentry is baked into the executable (select +
        # per-bucket scans): flipping FLAGS_anomaly_sentry must
        # recompile, never reuse a step compiled the other way
        sentry_on = (bool(get_flag("anomaly_sentry"))
                     and program._optimizer is not None)
        key = (program._serial, program._version, feed_names,
               tuple((a.shape, str(a.dtype)) for a in feed_arrays),
               tuple(fetch_names), program._optimizer is not None, donate,
               pallas_on, sentry_on,
               None if plan is None else plan.fingerprint())
        compiled = self._cache.get(key)
        compiled_this_run = compiled is None
        if compiled is None:
            # recompile for a NEW version: executables for older
            # versions of this program can never be requested again
            # (the version only grows), so drop them — each one pins
            # the node graph it closed over
            stale = [k for k in self._cache
                     if k[0] == program._serial and k[1] != key[1]]
            for k in stale:
                del self._cache[k]
            if get_flag("static_verify"):
                vkey = (program._serial, program._version)
                if vkey not in self._verified:
                    program.verify(fetch_list=fetch_list)
                    self._verified.add(vkey)
            if plan is not None and get_flag("shard_verify"):
                # shardcheck preflight: a plan/config the runtime path
                # below would refuse (grad_comm incompatibility, sum
                # fetch, bad spec) fails HERE as a structured
                # GraphVerificationError with the same cause string —
                # before any sharded compile.  Keyed per plan
                # fingerprint; compile keys are untouched, so the
                # 0-recompile contract holds with the flag on or off.
                skey = (program._serial, program._version,
                        plan.fingerprint())
                if skey not in self._shard_verified:
                    program.verify(fetch_list=fetch_list, sharding=plan)
                    self._shard_verified.add(skey)
            compiled = self._build(program, params, feed_names, fetch_names,
                                   donate, plan=plan,
                                   feed_arrays=feed_arrays,
                                   sentry=sentry_on)
            self._cache[key] = compiled
            if plan is not None:
                # replacing the mesh while this executable lives would
                # silently keep the old placement — register the hold
                from ..distributed.mesh import register_mesh_user
                register_mesh_user(
                    compiled, plan.mesh,
                    f"Executor program#{program._serial} "
                    f"(mesh {dict(plan.mesh.shape)})")
            self._compile_count += 1
            # static cost model: predicted FLOPs / peak bytes ride the
            # attribution record (and monitor gauges) so
            # explain_compiles-style tooling can show predicted-vs-
            # measured drift per compiled (program, signature).
            # Best-effort by contract: compile_summary returns None
            # rather than raising on a cost-model gap.
            from .analysis.cost import compile_summary
            predicted = compile_summary(program, donate=donate,
                                        sharding=plan)
            if predicted is not None:
                from ..utils import monitor
                monitor.stat_set("predicted.executor.flops",
                                 predicted["flops"])
                monitor.stat_set("predicted.executor.peak_bytes",
                                 predicted["peak_bytes"])
            # the prediction rides the executable too: cache-hit runs
            # hand it to the perf observatory's drift tracker.  The
            # drift identity is per EXECUTABLE, not per program — two
            # feed signatures of one program are different cache
            # entries with different predictions, and mixing their
            # step times in one rolling window would make the drift
            # number compare shape A's measurement against shape B's
            # prediction (the crc tail separates fetch/donate/plan
            # variants the readable prefix doesn't show)
            import zlib
            shapes = ";".join("x".join(map(str, a.shape))
                              for a in feed_arrays)
            compiled._predicted = predicted
            compiled._perf_identity = (
                f"{program._serial}v{program._version}[{shapes}]"
                f"#{zlib.crc32(repr(key).encode()) & 0xffffff:06x}")
            # recompile attribution: the first changed field (most
            # significant first) names the cause of this compile
            from ..observability import record_compile
            record_compile("executor", program._serial, {
                "program_version": program._version,
                "sharding": (None if plan is None
                             else plan.fingerprint()),
                "feed_signature": tuple(
                    (tuple(a.shape), str(a.dtype)) for a in feed_arrays),
                "feed_names": feed_names,
                "fetch_set": tuple(fetch_names),
                "optimizer": program._optimizer is not None,
                "donate": donate,
                "pallas": pallas_on,
                "sentry": sentry_on,
            }, predicted=predicted,
                kernels=getattr(compiled, "_pallas_kernels", None),
                comm=getattr(compiled, "_comm_record", None))

        state = self._state_for(program, params)

        # per-run randomness (reference: static dropout reseeds per run):
        # random ops fold the per-run key via seed_scope; an explicit
        # ``seed`` reproduces a run, the default auto-increments (the
        # counter lives ON DEVICE for the train path — no upload)
        if state.seed_val != program.random_seed:
            state.seed_val = program.random_seed
            state.base_key = jax.random.PRNGKey(program.random_seed)

        if program._optimizer is not None:
            opt = program._optimizer[0]
            if state.opt_state is None:
                state.t_idx = compiled._t_idx
                state.opt_state = opt.functional_init(
                    [state.p_arrays[i] for i in compiled._t_idx])
                # checkpoint restore: set_state_dict stashed slot arrays
                # keyed by program.parameters() position
                pending = getattr(opt, "_static_pending_slots", None)
                if pending:
                    for pos, i in enumerate(compiled._t_idx):
                        s = pending.get(str(i))
                        if s:
                            state.opt_state[pos] = {
                                k: jnp.asarray(v) for k, v in s.items()}
                    opt._static_pending_slots = None
                state.aux = {
                    "run": jnp.asarray(run_i - 1, jnp.int32),
                    "step": jnp.asarray(opt._step_count, jnp.int32)}
                state.synced_step = opt._step_count
                # static-mode optimizer.state_dict reads slots from here
                opt._static_state_provider = weakref.ref(state)
            # grad_comm error-feedback residuals ride the donated aux
            # carry (one device-varying [dp, numel] array per quantized
            # bucket); (re)zero them when the compiled plan differs from
            # the one the carry was accumulated under (first train run,
            # or ANY grad_comm knob recompile — keyed on the plan
            # fingerprint, not just the flat shapes, so an overlap flip
            # that keeps bucket sizes still starts from a clean carry)
            rs = getattr(compiled, "_residual_shapes", None)
            rk = getattr(compiled, "_residual_key", None)
            cur = state.aux.get("grad_comm")
            if rs:
                if (cur is None or state.gc_key != rk
                        or [tuple(a.shape) for a in cur]
                        != [tuple(s) for s in rs]):
                    state.aux = dict(state.aux, grad_comm=[
                        jnp.zeros(s, jnp.float32) for s in rs])
                    state.gc_key = rk
            elif cur is not None:
                state.aux = {k: v for k, v in state.aux.items()
                             if k != "grad_comm"}
                state.gc_key = None
            # the sentry carries a device-side skipped-step counter in
            # the donated aux tree (no host sync to maintain it); the
            # aux structure must match what this executable compiled
            # against, so add/drop the key on a sentry flip
            n_sentry = getattr(compiled, "_n_sentry", 0)
            if n_sentry:
                if "skipped" not in state.aux:
                    state.aux = dict(state.aux,
                                     skipped=jnp.asarray(0, jnp.int32))
            elif "skipped" in state.aux:
                state.aux = {k: v for k, v in state.aux.items()
                             if k != "skipped"}
            opt._step_count += 1
            if state.synced_step != opt._step_count - 1:
                # the optimizer counter moved outside this loop
                # (set_state_dict / eager steps): resync the device one
                state.aux = dict(
                    state.aux,
                    step=jnp.asarray(opt._step_count - 1, jnp.int32))
            state.synced_step = opt._step_count
            lr_val = float(opt.get_lr())
            if lr_val != state.lr_value:
                # upload the lr only when the schedule moves it
                state.lr_value = lr_val
                state.lr_device = jnp.asarray(lr_val, jnp.float32)
            if seed is None:
                seed_args = state.no_seed
                if seed_args is None:
                    # cached (flag=0, dummy): the common path uploads nothing
                    seed_args = state.no_seed = (
                        jnp.asarray(0, jnp.int32), jnp.asarray(0, jnp.int32))
            else:
                # a separate flag (not a sentinel value) so every seed —
                # including negative ones — reproduces faithfully
                seed_args = (jnp.asarray(1, jnp.int32),
                             jnp.asarray(int(seed), jnp.int32))
            if donate:
                state.shield_escaped()
            t_d0 = time.perf_counter() if perf is not None else 0.0
            fetches, new_p, new_s, new_aux = compiled(
                state.p_arrays, state.opt_state, state.aux,
                state.lr_device, state.base_key, *seed_args, *feed_arrays)
            state.p_arrays = list(new_p)
            state.opt_state = new_s
            state.aux = new_aux
            # host mirror of the compiled-in corruption schedule (stats
            # only; the corruption itself already ran in-graph)
            gc_sites = getattr(compiled, "_graph_corrupts", None)
            if gc_sites:
                fault.mirror_graph_fires(gc_sites, run_i)
            if n_sentry:
                sentry_vals = fetches[-n_sentry:]
                fetches = fetches[:-n_sentry]
                state.last_sentry = (run_i, sentry_vals)
                pol = obs_hook._anomaly
                if pol is not None:
                    # the policy may sync, skip-count, quarantine, roll
                    # the state back through SnapshotStore, or raise
                    # AnomalyEscalation (the supervisor-restart rung)
                    pol.on_step(self, program, run_i, sentry_vals,
                                fetch_names, fetches)
            # wire-byte accounting: the grad_comm plan's per-step bytes
            # and collective choices are static, so the measured stat is
            # the plan total per dispatched step (predict == measure by
            # construction; the cost model reports the same numbers) —
            # including the per-bucket (comm.bucket.<i>.*) and
            # per-algorithm breakdown precomputed at compile
            cs = getattr(compiled, "_comm_stats", None)
            if cs is not None:
                from ..utils import monitor
                for name, val in cs:
                    monitor.stat_add(name, val)
        else:
            rng_key = jax.random.fold_in(
                state.base_key, run_i if seed is None else int(seed))
            t_d0 = time.perf_counter() if perf is not None else 0.0
            fetches = compiled(state.p_arrays, rng_key, *feed_arrays)

        # step anatomy: host lane every run, device fence + memory
        # sample on the observatory's cadence.  The run that compiled
        # is excluded — its dispatch wall is compile time, which the
        # attribution layer already accounts for and would poison the
        # step-time distribution by orders of magnitude.
        if perf is not None and not compiled_this_run:
            perf.step("executor",
                      getattr(compiled, "_perf_identity",
                              program._serial),
                      t_h0, t_h1 - t_h0,
                      t_d0, time.perf_counter() - t_d0, fetches,
                      predicted=getattr(compiled, "_predicted", None))

        # supervised training: stamp the liveness heartbeat every step
        # (one module-attribute None-check when unsupervised).  The beat
        # carries the compile record's predicted_step_s so the parent's
        # watchdog can derive its hang deadline from the cost model.
        hb = obs_hook._heartbeat
        if hb is not None:
            hb.beat(run_i, getattr(compiled, "_predicted", None),
                    fresh_compile=compiled_this_run)

        # fleet telemetry: ride the same per-step cadence (one
        # None-check when not spooling; a time comparison otherwise —
        # the exporter flushes at most once per interval)
        exp = obs_hook._export
        if exp is not None:
            exp.tick()

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    # -- compilation -------------------------------------------------------
    def _shardings(self, plan, params, t_idx, opt, feed_arrays,
                   fetch_names):
        """(in, out) sharding pytrees of the compiled train step under a
        plan: params/slots by their PartitionSpec, batch feeds over the
        data axes, counters/lr/key replicated, fetches replicated (they
        are leaving for the host anyway)."""
        from ..distributed.sharding import specs_for_state
        from .analysis.liveness import param_array
        rep = plan.replicated()
        p_sh = [plan.param_sharding(i) for i in range(len(params))]
        feed_sh = [plan.feed_sharding(a.shape) for a in feed_arrays]
        fetch_sh = [rep] * len(fetch_names)
        s_sh = rep  # pytree prefix: replicate all slots (fallback)
        if opt is not None:
            try:
                avals = [jax.ShapeDtypeStruct(
                    tuple(param_array(params[i]).shape),
                    np.dtype(param_array(params[i]).dtype))
                    for i in t_idx]
                state_shape = jax.eval_shape(opt.functional_init, avals)
                s_specs = specs_for_state(
                    [plan.param_spec(i) for i in t_idx], state_shape,
                    param_shapes=[a.shape for a in avals])
                s_sh = [{k: plan._ns(v) for k, v in e.items()}
                        for e in s_specs]
            except Exception:  # noqa: BLE001 - fall back to replicated
                pass
        aux_sh = {"run": rep, "step": rep}
        return (p_sh, s_sh, aux_sh, rep, feed_sh, fetch_sh)

    # -- grad_comm (quantized/bucketed gradient collectives) ---------------
    def _grad_comm_plan(self, program, plan, params, t_idx, loss_var):
        """Reduction plan for the explicit grad-comm stage, or None when
        the mesh makes it a no-op (dp <= 1).  Raises loudly on meshes /
        param shardings the shard_map grad path cannot carry — the
        activation predicate is grad_comm.plan_status, SHARED with the
        cost model so prediction and runtime agree about which path
        runs.  Buckets assemble in the TRUE backward production order
        (grad_comm.production_order over the DefUseGraph — also shared
        with the cost model), so a bucket's collective is issued at the
        point in backward where its last gradient materializes, not at
        the reverse-creation-order proxy position."""
        from ..distributed import grad_comm as _gc
        from ..distributed.mesh import DP_AXIS
        from .analysis.liveness import param_array
        status, msg = _gc.plan_status(plan)
        if status == "off":
            return None
        if status == "error":
            raise NotImplementedError(msg)
        shapes = [tuple(param_array(params[i]).shape) for i in t_idx]
        order = _gc.production_order(
            program, [params[i] for i in t_idx], loss_var)
        # hybrid layout: which trainable params are FSDP (dp-sharded,
        # dedicated reduce-scatter buckets) or mp-sharded (gathered
        # over mp ahead of forward), plus the forward gather schedule
        # — one derivation shared with cost._comm_block and shardcheck
        named = [(params[i].name, shapes[k])
                 for k, i in enumerate(t_idx)]
        _kinds, fsdp, gathers = _gc.hybrid_layout(plan, named,
                                                  order=order)
        return _gc.plan_reduction(shapes,
                                  dp=plan.mesh.shape[DP_AXIS],
                                  cfg=plan.grad_comm, order=order,
                                  fsdp=fsdp, gathers=gathers)

    def _build_grad_comm(self, params, fetch_names, donate, plan, gplan,
                         feed_arrays, opt, loss_var, t_idx, params_meta,
                         forward_env, sentry=False):
        """Compile the training step with the explicit gradient-
        communication stage: forward+backward run inside a shard_map
        over dp (params replicated and device-varied, batch feeds
        sharded), gradients are reduced by grad_comm.reduce_gradients —
        bucketed in backward production order so each bucket's
        collective is issued where its last gradient materializes and
        overlaps the backward still producing later buckets (the
        lowering follows the plan's resolved overlap path: barriered
        'none', scheduler-split 'xla', or ppermute-chunked 'ring'),
        quantized per the plan, with the per-device error-feedback
        residual carried (and donated) in the aux tree — and the
        optimizer update runs outside on the replicated mean grads.

        Hybrid meshes are first-class: trainable params enter the
        shard_map under their OWN plan specs.  FSDP (dp-sharded, ZeRO-3)
        params are all-gathered over dp ahead of their layer's forward
        — the gather schedule is ``gplan.gathers``, reverse backward
        production order, i.e. forward prefetch order — and their
        gradients reduce-scatter back to shards ('rscatter' buckets,
        per-shard EF residuals).  Tensor-parallel (mp-sharded) params
        gather over mp the same way; because batch feeds ride dp only
        and the RNG folds the dp index alone, every mp replica computes
        bitwise identically, the full mp grad is mp-invariant, and each
        rank keeps its own chunk at the shard_map boundary (the
        composite all_gather+matmul / matmul+reduce_scatter lowering —
        see ops/collective_matmul.py for the fused-kernel form).
        Replicated non-trainables stay closure-captured; if the plan
        shards one, GSPMD reconciles it with an (unaccounted) gather.

        ``sentry`` (FLAGS_anomaly_sentry) fuses the data-plane anomaly
        sentry into the same executable: reduce_gradients scans each
        bucket's existing flat view for non-finite values (one
        reduction per bucket, pre- and post-wire, plus the int8
        quantize-time block guard), the counts collapse to ONE scalar
        anomaly flag that is psum'd over dp — rscatter buckets psum
        their device-varying post counts and norm contributions too, so
        every replica of a hybrid mesh takes the same branch and a skip
        can never diverge or deadlock the mesh — and the
        param/slot/step-counter/EF-residual update is applied through a
        jnp.where select: a flagged step is a bitwise no-op on all
        carried state while donation and the 0-recompile contract stay
        intact."""
        from jax.sharding import PartitionSpec
        from ..core import rng as _rng
        from ..core.jax_compat import pvary, shard_map
        from ..distributed import grad_comm as _gc
        from ..distributed.mesh import DP_AXIS
        from ..distributed.sharding import spec_axes
        from .analysis.liveness import param_array

        mesh = plan.mesh
        dp = gplan.dp
        P = PartitionSpec
        # per-trainable gather directives (hybrid meshes), keyed by
        # position in t_idx; empty on replicated layouts
        gkind = {g["index"]: g for g in gplan.gathers}
        ring_gather = gplan.overlap_path == "ring"
        feed_specs = tuple(plan.feed_spec(a.shape) for a in feed_arrays)

        # fetch reconstruction rules from abstract shapes: a fetch whose
        # per-shard shape equals the global one is pmean'd (exact for
        # the mean-reduced scalars programs fetch); a batch-major fetch
        # reassembles over dp; anything else cannot be rebuilt from
        # shards and must fail at compile, not return wrong numbers
        p_avals = [jax.ShapeDtypeStruct(tuple(param_array(p).shape),
                                        np.dtype(param_array(p).dtype))
                   for p in params]

        def _abstract_fetches(p_arrs, f_arrs):
            with _rng.seed_scope(jax.random.PRNGKey(0)):
                env = forward_env(list(p_arrs), f_arrs)
            return [env[n] for n in fetch_names]

        def _aval(a, local):
            shp = tuple(a.shape)
            if local and spec_axes(plan.feed_spec(shp)):
                shp = (shp[0] // dp,) + shp[1:]
            return jax.ShapeDtypeStruct(shp, np.dtype(a.dtype))

        loc = jax.eval_shape(_abstract_fetches, p_avals,
                             [_aval(a, True) for a in feed_arrays])
        glob = jax.eval_shape(_abstract_fetches, p_avals,
                              [_aval(a, False) for a in feed_arrays])
        fetch_rules = []
        for name, lo, go in zip(fetch_names, loc, glob):
            if tuple(lo.shape) == tuple(go.shape):
                fetch_rules.append("mean")
            elif (lo.shape and tuple(go.shape)
                  == (lo.shape[0] * dp,) + tuple(lo.shape[1:])):
                fetch_rules.append("batch")
            else:
                # shared builder: shardcheck's static diagnostic and
                # this raise print the same cause string
                raise NotImplementedError(
                    _gc.fetch_rule_message(name, go.shape, lo.shape))

        # certify the 'mean' classification numerically: a SUM-reduced
        # fetch (or loss) has the same shape as a mean-reduced one, but
        # pmean of per-shard partials would silently return 1/dp of it
        # — and the grads of a sum loss would be psum'd WITH the /dp
        # this stage applies, training a different model than GSPMD's
        # default.  The probe runs the forward eagerly at compile time
        # (dp shard runs + two global runs, one fixed RNG key) and
        # raises on a certified sum; a program whose randomness defeats
        # the probe gets a warning, not silence.
        probe_names = [n for n, r in zip(fetch_names, fetch_rules)
                       if r == "mean"]
        if loss_var.name not in probe_names:
            probe_names.append(loss_var.name)
        p_conc = [param_array(p) for p in params]

        def _probe(f_arrs, key):
            with _rng.seed_scope(key):
                env = forward_env(list(p_conc), list(f_arrs))
            return {n: np.asarray(env[n]) for n in probe_names}

        feeds_np = [np.asarray(a) for a in feed_arrays]
        k0 = jax.random.PRNGKey(0)
        g1 = _probe(feeds_np, k0)
        _rand_memo: list = []

        def _randomized():
            # only consulted when certification fails — don't pay a
            # full extra forward on the common all-certified compile
            if not _rand_memo:
                _rand_memo.append(any(
                    not np.array_equal(g1[n], v) for n, v in
                    _probe(feeds_np, jax.random.PRNGKey(1)).items()))
            return _rand_memo[0]

        shard_vals = []
        for i in range(dp):
            fs = [a[i * (a.shape[0] // dp):(i + 1) * (a.shape[0] // dp)]
                  if spec_axes(sp) else a
                  for a, sp in zip(feeds_np, feed_specs)]
            shard_vals.append(_probe(fs, k0))
        for n in probe_names:
            g = g1[n].astype(np.float64)
            parts = np.stack([sv[n].astype(np.float64)
                              for sv in shard_vals])
            mean_est, sum_est = parts.mean(0), parts.sum(0)
            scale = max(float(np.abs(g).max()),
                        float(np.abs(sum_est).max()), 1e-6)
            if np.abs(g - mean_est).max() <= 1e-3 * scale:
                continue
            what = ("loss" if n == loss_var.name else "fetch")
            if np.abs(g - sum_est).max() <= 1e-3 * scale:
                raise NotImplementedError(_gc.sum_fetch_message(what, n))
            if _randomized():
                import warnings
                warnings.warn(
                    f"grad_comm: could not certify that {what} '{n}' "
                    f"is a per-shard mean (the program's random ops "
                    f"defeat the compile-time probe); proceeding under "
                    f"the mean assumption — a sum-reduced {what} would "
                    f"be scaled by 1/dp.")
            else:
                raise NotImplementedError(
                    f"grad_comm: {what} '{n}' is neither the mean nor "
                    f"the sum of its per-shard values — it cannot be "
                    f"reconstructed from dp shards.  Fetch batch-major "
                    f"or mean-reduced tensors, or disable grad_comm.")

        n_res = len(gplan.residual_buckets)
        from ..testing import fault as _fault

        def train_fn(p_arrays, opt_state, aux, lr, base_key, sflag,
                     rseed, *feed_arrays):
            p_arrays = list(p_arrays)
            run_i = aux["run"] + 1
            step_i = (aux["step"] + 1).astype(jnp.float32)
            rng_key = jax.random.fold_in(
                base_key, jnp.where(sflag > 0, rseed, run_i))
            t_arrays = [p_arrays[i] for i in t_idx]
            residuals = tuple(aux.get("grad_comm", ()))

            def local(t_shards, res_rows, *local_feeds):
                # decorrelate per-shard random ops (dropout masks) —
                # the dp index ONLY: mp replicas must draw identical
                # masks so the full mp grad stays mp-invariant
                k_local = jax.random.fold_in(
                    rng_key, jax.lax.axis_index(DP_AXIS))
                # forward prefetch: gather each sharded param over its
                # axis in gplan.gathers order (reverse backward
                # production = forward order), so a layer's all-gather
                # is issued ahead of that layer's forward and the
                # scheduler can overlap it with earlier compute.  The
                # gathers run BEFORE differentiation: grads are taken
                # w.r.t. the full gathered values, so AD never
                # transposes the gather into its own (unquantized,
                # unaccounted) reduce-scatter
                t_full = {}
                for gth in gplan.gathers:
                    k = gth["index"]
                    t_full[k] = _gc.gather_param(
                        t_shards[k], gth["axis"], gth["size"],
                        dim=gth["dim"], ring=ring_gather)
                # differentiate w.r.t. device-VARYING copies: grads
                # stay local, the ONLY reduction is grad_comm's below
                t_var = [pvary(t_full.get(k, a), DP_AXIS)
                         for k, a in enumerate(t_shards)]

                def loss_of(tlist):
                    full = list(p_arrays)
                    for j, a in zip(t_idx, tlist):
                        full[j] = a
                    with _rng.seed_scope(k_local):
                        env = forward_env(full, local_feeds)
                    return env[loss_var.name], env

                (loss, env), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(t_var)
                # chaos hook: pre-reduction grad corruption (identity
                # unless a corrupt rule is armed at compile time)
                grads = [_fault.corrupt_in_graph(
                    "executor.grads", g, run_i, tensor=p.name)
                    for g, p in zip(grads, params_meta)]
                res_arg = ([r[0] for r in res_rows]
                           if res_rows else None)
                if sentry:
                    grads, new_res, sinfo = _gc.reduce_gradients(
                        grads, plan=gplan, axis_name=DP_AXIS,
                        residuals=res_arg, sentry=True, step=run_i)
                    # ONE mesh-agreed scalar drives the branch:
                    # non-finite anywhere (local grads, wire, block
                    # scales, loss) or an overflowed grad norm.  The
                    # loss count feeds only the flag — never the
                    # per-bucket or block-guard stat channels
                    loss_nf = jax.lax.psum(
                        (~jnp.isfinite(loss)).astype(jnp.int32),
                        DP_AXIS)
                    nf_bucket = sinfo["pre"] + sinfo["post"]
                    anom = jnp.logical_or(
                        (jnp.sum(nf_bucket) + sinfo["blocks"]
                         + loss_nf) > 0,
                        ~jnp.isfinite(sinfo["norm2"]))
                    sleaves = (anom.astype(jnp.int32), nf_bucket,
                               sinfo["blocks"], sinfo["norm2"])
                else:
                    grads, new_res = _gc.reduce_gradients(
                        grads, plan=gplan, axis_name=DP_AXIS,
                        residuals=res_arg)
                    sleaves = ()
                del loss
                # mp params: the reduced grad is the FULL mp-invariant
                # tensor — each rank keeps its own chunk, the out_spec
                # (the param's own spec) reassembles.  FSDP grads
                # already left reduce_gradients as dim-0 shards.
                from ..distributed.mesh import MP_AXIS
                grads = list(grads)
                for k, gth in gkind.items():
                    if gth["axis"] != MP_AXIS:
                        continue
                    g, d = grads[k], gth["dim"]
                    sh = g.shape[d] // gth["size"]
                    grads[k] = jax.lax.dynamic_slice_in_dim(
                        g, jax.lax.axis_index(MP_AXIS) * sh, sh, d)
                outs = []
                for name, rule in zip(fetch_names, fetch_rules):
                    v = env[name]
                    outs.append(jax.lax.pmean(v, DP_AXIS)
                                if rule == "mean" else v)
                return (tuple(outs), tuple(grads),
                        tuple(r[None] for r in new_res), sleaves)

            t_specs = tuple(plan.param_spec(i) for i in t_idx)
            fetch_vals, grads, new_res, sleaves = shard_map(
                local, mesh=mesh,
                in_specs=((t_specs,)
                          + (tuple(P(DP_AXIS) for _ in residuals),)
                          + feed_specs),
                out_specs=(tuple(P(DP_AXIS) if r == "batch" else P()
                                 for r in fetch_rules),
                           t_specs,
                           tuple(P(DP_AXIS) for _ in residuals),
                           (P(), P(), P(), P()) if sentry else ()),
                check_vma=False)(tuple(t_arrays), residuals,
                                 *feed_arrays)

            new_t, new_s = opt.functional_update(
                t_arrays, list(grads), opt_state, lr, step_i,
                params_meta=params_meta)
            if sentry:
                anom_i, nf_bucket, nf_extra, norm2 = sleaves
                # the select is elementwise, so an un-flagged step is
                # bit-identical to the sentry-less lowering
                anom = anom_i > 0
                ok = jnp.logical_not(anom)
                new_t = [jnp.where(ok, n, o)
                         for n, o in zip(new_t, t_arrays)]
                new_s = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(ok, n, o), new_s, opt_state)
                new_res = [jnp.where(ok, n, o)
                           for n, o in zip(new_res, residuals)]
                step_next = jnp.where(ok, aux["step"] + 1, aux["step"])
            new_p = list(p_arrays)
            for j, a in zip(t_idx, new_t):
                new_p[j] = a
            new_aux = {"run": run_i,
                       "step": step_next if sentry else aux["step"] + 1}
            if sentry:
                new_aux["skipped"] = (aux["skipped"]
                                      + anom.astype(jnp.int32))
            if n_res:
                new_aux["grad_comm"] = list(new_res)
            fetch_out = list(fetch_vals)
            if sentry:
                fetch_out += [anom_i, nf_bucket, nf_extra, norm2]
            return (fetch_out, new_p, new_s, new_aux)

        jit_kw = dict(donate_argnums=(0, 1, 2)) if donate else {}
        p_sh, s_sh, aux_sh, rep, feed_sh, fetch_sh = self._shardings(
            plan, params, t_idx, opt, feed_arrays, fetch_names)
        if n_res:
            aux_sh = dict(aux_sh,
                          grad_comm=[plan._ns(P(DP_AXIS))] * n_res)
        if sentry:
            aux_sh = dict(aux_sh, skipped=rep)
            fetch_sh = list(fetch_sh) + [rep] * 4
        jit_kw["in_shardings"] = (p_sh, s_sh, aux_sh, rep, rep, rep,
                                  rep, *feed_sh)
        jit_kw["out_shardings"] = (fetch_sh, p_sh, s_sh, aux_sh)
        compiled = _no_persistent_cache_first_call(
            jax.jit(train_fn, **jit_kw))
        compiled._t_idx = t_idx
        if sentry:
            compiled._n_sentry = 4
            compiled._sentry_buckets = len(gplan.buckets)
        # in-graph corruption sites with an armed rule at compile time:
        # the host mirrors their deterministic fire schedule per run so
        # fault.fired.* stats stay truthful (the graph never calls back)
        sites = [("executor.grads", p.name) for p in params_meta]
        if sentry:
            # the wire corruption point only lowers when the sentry
            # passes `step` into reduce_gradients — mirroring sites
            # that never compiled in would report fires that never
            # happened
            for i, b in enumerate(gplan.buckets):
                if b.wire_dtype == "int8":
                    sites.append(("grad_comm.wire", f"bucket.{i}.q"))
                    sites.append(("grad_comm.wire",
                                  f"bucket.{i}.scales"))
        compiled._graph_corrupts = _fault.graph_corrupt_sites(sites)
        compiled._gc_plan = gplan
        # rscatter (FSDP) buckets carry their residual over the
        # shard-major padded flat — bucket_flat_numel, not numel
        compiled._residual_shapes = [
            (dp, _gc.bucket_flat_numel(b, dp, gplan.cfg.block_size))
            for b in gplan.residual_buckets]
        # residuals are only meaningful for the exact bucket layout they
        # were accumulated under: a knob recompile (overlap flip, dtype
        # change, re-bucketing) re-zeroes them even when the flat shapes
        # happen to coincide
        compiled._residual_key = plan.fingerprint()
        # per-step wire accounting, precomputed once per compile: the
        # totals, the per-algorithm split, and the per-bucket breakdown
        # (comm.bucket.<i>.*) — every number is static plan state, so
        # measured == predicted per bucket too
        stat_items = [("comm.wire_bytes", gplan.wire_bytes_per_step),
                      ("comm.collectives", gplan.collectives_per_step)]
        for algo, cnt in gplan.algo_counts().items():
            stat_items.append((f"comm.algo.{algo}", cnt))
        for i, b in enumerate(gplan.buckets):
            stat_items.append((f"comm.bucket.{i}.wire_bytes",
                               b.wire_bytes))
            stat_items.append((f"comm.bucket.{i}.collectives",
                               b.collectives))
            stat_items.append((f"comm.algo.{b.algorithm}.wire_bytes",
                               b.wire_bytes))
        # per-mesh-axis accounting (hybrid meshes): grad buckets + dp
        # param gathers ride 'dp', mp param gathers ride 'mp' — same
        # dict the cost model predicts and shardcheck audits, so
        # measured == predicted holds on EVERY axis
        for ax in sorted(gplan.axis_wire_bytes):
            stat_items.append((f"comm.axis.{ax}.wire_bytes",
                               gplan.axis_wire_bytes[ax]))
        if gplan.gathers:
            stat_items.append(("comm.gather.wire_bytes",
                               gplan.gather_wire_bytes_per_step))
            stat_items.append(("comm.gather.collectives",
                               len(gplan.gathers)))
        compiled._comm_stats = stat_items
        # the bucket schedule (size, algo, wire, issue point) + resolved
        # overlap path ride the compile record so overlap decisions are
        # auditable from explain_compiles()
        compiled._comm_record = gplan.schedule()
        # hybrid lowering attribution: mp param gathers compile the
        # whole-layer all_gather+matmul composite into this step (the
        # per-chunk Pallas form is ops/collective_matmul's opt-in for
        # custom layers) — ride kernels= like every tier selection
        if any(g["axis"] != DP_AXIS for g in gplan.gathers):
            compiled._pallas_kernels = ["collective_matmul[composite]"]
        return compiled

    def _build(self, program: Program, params, feed_names, fetch_names,
               donate, plan=None, feed_arrays=(), sentry=False):
        nodes = list(program.nodes)
        opt_pack = program._optimizer

        # -- Pallas tier: epilogue-fusion pass ------------------------
        # Realize the cost model's ranked fusion candidates: matched
        # single-consumer chains (linear anchor + bias/gelu/relu/
        # residual/layer_norm epilogue) rewrite to ONE fused kernel
        # node (ops/pallas/fused_epilogue, fwd + custom-vjp bwd) under
        # the RUN-TIME feed shapes.  Single-device only: pallas_call
        # under an explicit GSPMD sharding plan is not a lowering this
        # tier supports.  The realized kernel list rides the compile
        # record (kernels=) so explain_compiles / the perf observatory
        # can attribute step-time deltas to the tier being on or off.
        realized_kernels: List[str] = []
        from ..ops.pallas.support import tier_enabled
        pallas_on = tier_enabled() and plan is None
        if pallas_on:
            from .analysis import fusion
            fplans = fusion.plan_fusions(
                program, fetch_list=list(fetch_names),
                feed_shapes={n: tuple(a.shape) for n, a in
                             zip(feed_names, feed_arrays)})
            if fplans:
                nodes = fusion.apply_plans(nodes, fplans)
                realized_kernels.extend(
                    f"fused_epilogue[{p.label}]" for p in fplans)

        def forward_env(p_arrays, feed_arrays):
            env = {}
            for name, arr in zip(feed_names, feed_arrays):
                env[name] = arr
            pmap = {id(p): a for p, a in zip(params, p_arrays)}
            return _interp(nodes, env, pmap)

        from ..core import rng as _rng

        if opt_pack is None:
            def run_fn(p_arrays, rng_key, *feed_arrays):
                # random ops (dropout) draw from the per-run key
                with _rng.seed_scope(rng_key):
                    env = forward_env(p_arrays, feed_arrays)
                return [env[n] for n in fetch_names]

            if plan is None:
                jitted = jax.jit(run_fn)
                from ..core import compile_cache as _ccache
                if _ccache.enabled():
                    # persistent AOT cache for the single-device
                    # inference step: key on the hash of the lowered
                    # module (exact program content — process-local
                    # serials never survive a respawn, so they can't
                    # key anything).  The site compiles lazily on first
                    # dispatch, so provenance is annotated onto the
                    # already-written compile record after the fact.
                    import hashlib as _hashlib
                    serial = program._serial
                    holder: dict = {}

                    def compiled(*args):
                        ex = holder.get("ex")
                        if ex is None:
                            lowered = jitted.lower(*args)
                            ex, prov = _ccache.cached_compile(
                                "executor",
                                {"module": _hashlib.sha256(
                                    lowered.as_text().encode()
                                ).hexdigest()},
                                lowered.compile)
                            holder["ex"] = ex
                            if prov is not None:
                                from ..observability import \
                                    annotate_compile
                                annotate_compile("executor", serial,
                                                 prov)
                        return ex(*args)
                else:
                    def compiled(*args):
                        return jitted(*args)

                compiled._pallas_kernels = realized_kernels
                return compiled
            p_sh, _, _, rep, feed_sh, fetch_sh = self._shardings(
                plan, params, [], None, feed_arrays, fetch_names)
            jitted = jax.jit(run_fn,
                             in_shardings=(p_sh, rep, *feed_sh),
                             out_shardings=fetch_sh)
            return _no_persistent_cache_first_call(jitted)

        opt, loss_var, param_filter, no_grad_set = (opt_pack + (None,
                                                                None))[:4]
        # respect stop_gradient / trainable and minimize's parameters= /
        # no_grad_set= (reference: append_backward skips no-grad vars)
        allow = (None if param_filter is None
                 else {id(p) for p in param_filter})
        deny = ({id(p) for p in no_grad_set} if no_grad_set else set())

        def trainable(p):
            return (p.trainable and not p.stop_gradient
                    and (allow is None or id(p) in allow)
                    and id(p) not in deny)

        t_idx = [i for i, p in enumerate(params) if trainable(p)]
        params_meta = [params[i] for i in t_idx]

        # -- Pallas tier: fused Adam over the donated param/slot pairs --
        # One kernel pass reads (p, g, m, v) once and writes (p', m',
        # v') once per param, replacing the composite multi-op update.
        # fused_update_for returns None unless it reproduces THIS
        # optimizer's exact semantics (plain f32 Adam, no clip/decay/
        # master weights) — everything else stays on functional_update.
        fused_update = None
        if pallas_on:
            from .analysis.liveness import param_array
            from ..ops.pallas.fused_adam import fused_update_for
            fused_update = fused_update_for(
                opt, params_meta, [param_array(p) for p in params_meta])
            if fused_update is not None:
                realized_kernels.append("fused_adam")

        # -- grad_comm: explicit quantized/bucketed gradient collectives --
        # When the plan carries a grad_comm spec (strategy.grad_comm /
        # fp16_allreduce through fleet) on a multi-device {dp} or
        # {dp, mp} mesh, the loss+backward runs inside a shard_map over
        # the whole mesh and the gradient reduction is OURS: bucketed,
        # quantized, with the error-feedback residual carried in the
        # donated aux tree.  FSDP/ZeRO-3 params stay sharded at rest
        # (gathered ahead of forward, grads reduce-scattered back);
        # mp-sharded params gather over mp in production order.
        gplan = None
        if plan is not None and plan.grad_comm is not None:
            gplan = self._grad_comm_plan(program, plan, params, t_idx,
                                         loss_var)
        if gplan is not None:
            return self._build_grad_comm(
                params, fetch_names, donate, plan, gplan, feed_arrays,
                opt, loss_var, t_idx, params_meta, forward_env,
                sentry=sentry)

        from ..testing import fault as _fault

        def train_fn(p_arrays, opt_state, aux, lr, base_key, sflag, rseed,
                     *feed_arrays):
            p_arrays = list(p_arrays)
            # counters live in the donated aux carry: no per-step scalar
            # uploads.  'run' keys RNG (advances every run); 'step' is
            # the optimizer update count (Adam bias correction).
            run_i = aux["run"] + 1
            step_i = (aux["step"] + 1).astype(jnp.float32)
            rng_key = jax.random.fold_in(
                base_key, jnp.where(sflag > 0, rseed, run_i))

            def loss_of(tlist):
                full = list(p_arrays)
                for j, a in zip(t_idx, tlist):
                    full[j] = a
                with _rng.seed_scope(rng_key):
                    env = forward_env(full, feed_arrays)
                return env[loss_var.name], env

            t_arrays = [p_arrays[i] for i in t_idx]
            (loss, env), grads = jax.value_and_grad(
                loss_of, has_aux=True)(t_arrays)
            # chaos hook: pre-update grad corruption (identity unless a
            # corrupt rule is armed at compile time)
            grads = [_fault.corrupt_in_graph(
                "executor.grads", g, run_i, tensor=p.name)
                for g, p in zip(grads, params_meta)]
            update = (fused_update if fused_update is not None
                      else opt.functional_update)
            new_t, new_s = update(
                t_arrays, grads, opt_state, lr, step_i,
                params_meta=params_meta)
            new_aux = {"run": run_i, "step": aux["step"] + 1}
            fetch_out = [env[n] for n in fetch_names]
            if sentry:
                # no buckets on this path: the scan is one fused
                # reduction per gradient (still never per element on
                # the host), collapsed to the same one-scalar flag +
                # jnp.where select as the grad_comm lowering.  Under a
                # GSPMD plan the flag is a global reduction over the
                # logical arrays, so every device agrees by
                # construction — mesh-agreed without an explicit psum.
                loss_nf = (~jnp.isfinite(loss)).astype(jnp.int32)
                nf = jnp.asarray(0, jnp.int32)
                norm2 = jnp.asarray(0.0, jnp.float32)
                for g in grads:
                    nf = nf + jnp.sum((~jnp.isfinite(g))
                                      .astype(jnp.int32))
                    norm2 = norm2 + jnp.sum(
                        jnp.asarray(g, jnp.float32) ** 2)
                # the loss count feeds only the flag, never the
                # gradient nonfinite stat channel
                anom = jnp.logical_or(nf + loss_nf > 0,
                                      ~jnp.isfinite(norm2))
                ok = jnp.logical_not(anom)
                new_t = [jnp.where(ok, n, o)
                         for n, o in zip(new_t, t_arrays)]
                new_s = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(ok, n, o), new_s, opt_state)
                new_aux["step"] = jnp.where(ok, aux["step"] + 1,
                                            aux["step"])
                new_aux["skipped"] = (aux["skipped"]
                                      + anom.astype(jnp.int32))
                fetch_out += [anom.astype(jnp.int32), nf[None],
                              jnp.asarray(0, jnp.int32), norm2]
            new_p = list(p_arrays)
            for j, a in zip(t_idx, new_t):
                new_p[j] = a
            return (fetch_out, new_p, new_s, new_aux)

        # donate params, optimizer slots and the aux carry — NOT lr /
        # base_key / seed args (cached and reused across runs) and NOT
        # the feeds (users legitimately feed the same arrays every step)
        jit_kw = dict(donate_argnums=(0, 1, 2)) if donate else {}
        if plan is not None:
            # GSPMD lowering: the donated state carries explicit
            # in/out shardings over the plan's mesh — outputs come back
            # with the same placement as the inputs, so the state is
            # layout-stable run to run (no per-step resharding) and the
            # dp gradient psum / ZeRO collectives fall out of the
            # compiler
            p_sh, s_sh, aux_sh, rep, feed_sh, fetch_sh = self._shardings(
                plan, params, t_idx, opt, feed_arrays, fetch_names)
            if sentry:
                aux_sh = dict(aux_sh, skipped=rep)
                fetch_sh = list(fetch_sh) + [rep] * 4
            jit_kw["in_shardings"] = (p_sh, s_sh, aux_sh, rep, rep, rep,
                                      rep, *feed_sh)
            jit_kw["out_shardings"] = (fetch_sh, p_sh, s_sh, aux_sh)
        jitted = jax.jit(train_fn, **jit_kw)

        if plan is not None:
            compiled = _no_persistent_cache_first_call(jitted)
        else:
            def compiled(*args):
                return jitted(*args)

        compiled._t_idx = t_idx
        compiled._pallas_kernels = realized_kernels
        if sentry:
            compiled._n_sentry = 4
            compiled._sentry_buckets = 1
        compiled._graph_corrupts = _fault.graph_corrupt_sites(
            [("executor.grads", p.name) for p in params_meta])
        return compiled

    # -- pre-change reference path (bench comparison + oracle) -------------
    # The hot loop below is the Executor.run/_build pair as it stood
    # BEFORE the donated device-resident redesign: feeds bounce through
    # NumPy, every Parameter is read and written back per step, lr and
    # step scalars are re-uploaded per run, and fetches always sync.
    # bench.py's static suite measures the speedup against it and tests
    # use it as a numerical oracle.  Not part of the public API.

    def _run_legacy(self, program, feed=None, fetch_list=None,
                    return_numpy=True, seed=None):
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        if not program.nodes:
            return []
        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in fetch_list]
        params = program.parameters()
        feed_items = sorted(feed.items())
        feed_names = tuple(n for n, _ in feed_items)
        feed_arrays = [jnp.asarray(np.asarray(a)) for _, a in feed_items]
        self._track(program)
        key = (program._serial, program._version, feed_names,
               tuple((a.shape, str(a.dtype)) for a in feed_arrays),
               tuple(fetch_names), program._optimizer is not None)
        compiled = self._legacy_cache.get(key)
        if compiled is None:
            compiled = self._build_legacy(program, params, feed_names,
                                          fetch_names)
            self._legacy_cache[key] = compiled
            self._compile_count += 1
            from ..observability import record_compile
            record_compile("executor_legacy", program._serial, {
                "program_version": program._version,
                "feed_signature": tuple(
                    (tuple(a.shape), str(a.dtype)) for a in feed_arrays),
                "feed_names": feed_names,
                "fetch_set": tuple(fetch_names),
                "optimizer": program._optimizer is not None,
            })
        run_i = self._run_counts.get(program._serial, 0) + 1
        self._run_counts[program._serial] = run_i
        rng_key = jax.random.fold_in(
            jax.random.PRNGKey(program.random_seed),
            run_i if seed is None else int(seed))
        p_arrays = [p.data for p in params]
        if program._optimizer is not None:
            opt = program._optimizer[0]
            state = self._opt_states.get(program._serial)
            if state is None:
                state = opt.functional_init(
                    [p_arrays[i] for i in compiled._t_idx])
            opt._step_count += 1
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            step_i = jnp.asarray(opt._step_count, jnp.float32)
            fetches, new_p, new_state = compiled(
                p_arrays, state, lr, step_i, rng_key, *feed_arrays)
            self._opt_states[program._serial] = new_state
            for p, arr in zip(params, new_p):
                p.data = arr
        else:
            fetches = compiled(p_arrays, rng_key, *feed_arrays)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    def _build_legacy(self, program, params, feed_names, fetch_names):
        nodes = list(program.nodes)
        opt_pack = program._optimizer

        def forward_env(p_arrays, feed_arrays):
            env = {}
            for name, arr in zip(feed_names, feed_arrays):
                env[name] = arr
            pmap = {id(p): a for p, a in zip(params, p_arrays)}
            return _interp(nodes, env, pmap)

        from ..core import rng as _rng

        if opt_pack is None:
            @jax.jit
            def run_fn(p_arrays, rng_key, *feed_arrays):
                with _rng.seed_scope(rng_key):
                    env = forward_env(p_arrays, feed_arrays)
                return [env[n] for n in fetch_names]
            return run_fn

        opt, loss_var, param_filter, no_grad_set = (opt_pack + (None,
                                                                None))[:4]
        allow = (None if param_filter is None
                 else {id(p) for p in param_filter})
        deny = ({id(p) for p in no_grad_set} if no_grad_set else set())

        def trainable(p):
            return (p.trainable and not p.stop_gradient
                    and (allow is None or id(p) in allow)
                    and id(p) not in deny)

        t_idx = [i for i, p in enumerate(params) if trainable(p)]
        params_meta = [params[i] for i in t_idx]

        @jax.jit
        def train_fn(p_arrays, opt_state, lr, step_i, rng_key,
                     *feed_arrays):
            p_arrays = list(p_arrays)

            def loss_of(tlist):
                full = list(p_arrays)
                for j, a in zip(t_idx, tlist):
                    full[j] = a
                with _rng.seed_scope(rng_key):
                    env = forward_env(full, feed_arrays)
                return env[loss_var.name], env

            t_arrays = [p_arrays[i] for i in t_idx]
            (loss, env), grads = jax.value_and_grad(
                loss_of, has_aux=True)(t_arrays)
            new_t, new_s = opt.functional_update(
                t_arrays, grads, opt_state, lr, step_i,
                params_meta=params_meta)
            new_p = list(p_arrays)
            for j, a in zip(t_idx, new_t):
                new_p[j] = a
            return [env[n] for n in fetch_names], new_p, new_s

        def compiled(*args):
            return train_fn(*args)

        compiled._t_idx = t_idx
        return compiled
