"""Static-graph IR: Program / Variable / op recording.

TPU-native re-design of the reference's ProgramDesc + Block + OpDesc IR
(reference: paddle/fluid/framework/program_desc.h, block_desc.h,
python/paddle/fluid/framework.py Program:4722, Variable:1453).

Design: the same single dispatch point used by eager mode
(core/dispatch.apply) records ops into the current Program whenever an
input is a symbolic ``Variable``.  A Program is an ordered list of
``_OpNode`` (pure jnp function + input references + output Variables) —
the analog of a Block's op list.  ``Executor`` (executor.py) interprets
the node list inside ONE ``jax.jit``, so a whole static program —
forward, backward, and optimizer update — compiles to a single XLA
computation, which is exactly what the reference's graph passes try to
approximate op-by-op.

Shape semantics: ``data(shape=[None, ...])`` declares dynamic dims; build
time uses 1 as the abstract placeholder (ops re-execute with the real
shapes at run time, so only cosmetic metadata depends on it).
"""
from __future__ import annotations

import itertools
import os
import sys
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype
from ..core.flags import get_flag
from ..core.tensor import Parameter, Tensor

_var_counter = itertools.count(0)
# monotonic program identity: id(program) can be recycled by the
# allocator after GC, silently handing a new Program an old program's
# executor-side state (run counters, optimizer slots) — the serial never
# repeats within a process and doubles as the verifier's program id
_program_serial = itertools.count(0)

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) \
    + os.sep  # trailing sep: .../paddle_tpu_ext must not match


def _caller_loc():
    """file:line of the first frame outside paddle_tpu — the user
    statement that recorded the op (captured only under
    FLAGS_static_verify; the verifier's source anchor)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_DIR):
            return (fn, f.f_lineno)
        f = f.f_back
    return None

# -- replay scope -----------------------------------------------------------
# Composite control-flow ops (ops/control_flow.py) record ONE node whose fn
# re-runs the user's branch/body closures at execution time.  Those closures
# reference symbolic Variables and Parameters; inside a replay scope the
# dispatch point resolves each to its runtime (traced) array instead of
# recording / reading host values.  At record time (shape inference) they
# resolve to abstract zeros / current values while the Parameters are
# collected onto the node, so Program.parameters() sees weights used only
# inside branches.  This is the analog of the reference's
# conditional_block/while ops executing their sub-Block against the
# enclosing Scope (operators/controlflow/conditional_block_op.cc:63).
from ..core.static_hooks import current_replay, replay_scope  # noqa: F401


def resolve_variable(v):
    """Runtime array for a Variable inside a replay scope."""
    lookup = current_replay()
    if lookup is None:
        raise RuntimeError(
            f"symbolic Variable {v.name} used outside a Program execution")
    return lookup(v)


class Variable(Tensor):
    """Symbolic tensor inside a Program (reference: framework.py
    Variable:1453).  ``data`` holds a jax.ShapeDtypeStruct, so all Tensor
    sugar (operators, .reshape, …) routes through the shared dispatch and
    gets recorded instead of executed."""

    __slots__ = ("program", "desc_shape")
    _static_var = True  # checked by core.dispatch.apply

    def __init__(self, aval, program, name=None, desc_shape=None):
        # bypass Tensor.__init__: aval is not an array
        self.data = aval
        self.stop_gradient = True
        self.name = name or f"var_{next(_var_counter)}"
        self.persistable = False
        self._bw_id = 0
        self._produced = True
        self._node = None
        self._grad_data = None
        self._backward_hooks = []
        self.trainable = False
        self.placement = None
        self.program = program
        self.desc_shape = list(desc_shape) if desc_shape is not None else None

    @property
    def shape(self):
        return (list(self.desc_shape) if self.desc_shape is not None
                else list(self.data.shape))

    def __bool__(self):
        raise TypeError(
            "[operator < bool > error] Python `if`/`while` tested a "
            "symbolic static.Variable while building a Program; the "
            "branch cannot be resolved at build time. Use "
            "paddle.static.nn.cond / paddle.where for branches and "
            "paddle.static.nn.while_loop for loops.")

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={self.data.dtype})")


class _OpNode:
    """One recorded op (reference: framework.py Operator / OpDesc)."""

    __slots__ = ("fn", "kw", "op_name", "in_specs", "out_vars",
                 "multi", "extra_params", "extra_vars", "loc")

    def __init__(self, fn, kw, op_name, in_specs, out_vars, multi,
                 extra_params=(), extra_vars=(), loc=None):
        self.fn = fn
        self.kw = kw
        self.op_name = op_name
        self.in_specs = in_specs  # list of ("v", Variable)|("p", Parameter)
        #                           |("c", jax.Array)|("l", literal)
        self.out_vars = out_vars
        self.multi = multi
        # Variables/Parameters referenced only inside composite replay
        # closures (control-flow branches); resolved via the replay scope
        # at run time, but recorded here so dependency walks (pruning,
        # Program.parameters) see them
        self.extra_params = list(extra_params)
        self.extra_vars = list(extra_vars)
        self.loc = loc  # (file, line) source anchor or None


class Program:
    """An ordered op list + feed/fetch metadata (reference: Program:4722).

    Built implicitly by running layer code on ``static.data`` Variables
    under ``paddle.enable_static()``; executed by ``static.Executor``."""

    def __init__(self):
        self.nodes: List[_OpNode] = []
        self.feed_vars: Dict[str, Variable] = {}
        self._optimizer = None       # (optimizer, loss Variable)
        self.random_seed = 0
        self._version = 0
        self._serial = next(_program_serial)
        self._params_cache = None    # (version, [Parameter]) — see parameters()

    # -- recording (called from core.dispatch.apply) ----------------------
    def _aval_of(self, x):
        if isinstance(x, Variable):
            return x.data
        if isinstance(x, Tensor):
            return jax.ShapeDtypeStruct(x.shape_tuple,
                                        np.dtype(x.data.dtype))
        return x

    def record(self, fn: Callable, inputs: Sequence, kw: dict,
               op_name: str):
        seen_params: List[Parameter] = []
        seen_vars: List[Variable] = []

        def _abstract_lookup(v):
            if isinstance(v, Parameter):
                if not any(v is p for p in seen_params):
                    seen_params.append(v)
                return v.data
            if not any(v is u for u in seen_vars):
                seen_vars.append(v)
            return jnp.zeros(v.data.shape, v.data.dtype)

        with replay_scope(_abstract_lookup):
            out_avals = jax.eval_shape(lambda *a: fn(*a, **kw),
                                       *[self._aval_of(x) for x in inputs])
        in_specs = []
        for x in inputs:
            if isinstance(x, Variable):
                in_specs.append(("v", x))
            elif isinstance(x, Parameter):
                in_specs.append(("p", x))
            elif isinstance(x, Tensor):
                in_specs.append(("c", x.data))
            else:
                in_specs.append(("l", x))
        multi = isinstance(out_avals, (tuple, list))
        avals = list(out_avals) if multi else [out_avals]
        out_vars = [Variable(a, self) for a in avals]
        loc = (_caller_loc()
               if (get_flag("static_verify") or get_flag("static_anchors"))
               else None)
        self.nodes.append(_OpNode(fn, kw, op_name, in_specs, out_vars,
                                  multi, extra_params=seen_params,
                                  extra_vars=seen_vars, loc=loc))
        self._version += 1
        if multi:
            return tuple(out_vars)
        return out_vars[0]

    # -- verification (static/analysis) ------------------------------------
    def verify(self, fetch_list=None, raise_on_error=True,
               sharding=None, mesh_shape=None, sharding_rules=None,
               strategy=None):
        """Run the compile-time verifier passes over this program
        (static/analysis: def-use ordering, cross-program leaks, name
        collisions, shape/dtype drift, and — when ``fetch_list`` roots
        are given — dead-op/unused-feed liveness).  Raises
        ``core.enforce.GraphVerificationError`` on errors unless
        ``raise_on_error=False``; returns the Diagnostic list.

        With ``sharding=`` (a ``ShardingPlan`` or ``AbstractPlan``) or
        ``mesh_shape=`` (a plain ``{axis: size}`` dict, optionally with
        ``sharding_rules=``/``strategy=``) the SPMD shardcheck passes
        also run: plan coverage & divisibility, collective
        choreography, device-varying taint, and the wire-byte audit —
        all mesh-offline, zero devices needed."""
        from .analysis import verify as _verify
        return _verify(self, fetch_list=fetch_list,
                       raise_on_error=raise_on_error, sharding=sharding,
                       mesh_shape=mesh_shape,
                       sharding_rules=sharding_rules, strategy=strategy)

    def analyze(self, fetch_list=None, feed_shapes=None, batch_size=None,
                chip=None, top_k=5, sharding=None):
        """Quantitative static analysis (static/analysis/cost.py):
        per-op FLOPs and byte volumes with an explicit ``unmodeled``
        bucket, donation-aware peak-memory bounds, a roofline summary
        per chip spec, TPU-readiness hazards, and top-k fusion
        candidates ranked by HBM traffic saved.  ``batch_size``
        substitutes dynamic feed dims (declared ``None``/-1) and
        re-derives every aval; ``feed_shapes`` overrides specific feeds
        exactly.  Returns a :class:`ProgramReport` (``.render()`` for
        text, ``.to_dict()``/``.to_json()`` for machines).  Enable
        ``FLAGS_static_anchors`` before building the program for
        ``file:line`` anchors in the report.  ``sharding`` (a
        ``distributed.sharding.ShardingPlan``) adds per-shard memory
        accounting — peak bytes per chip, not per fleet."""
        from .analysis import analyze as _analyze
        return _analyze(self, fetch_list=fetch_list,
                        feed_shapes=feed_shapes, batch_size=batch_size,
                        chip=chip, top_k=top_k, sharding=sharding)

    # -- introspection -----------------------------------------------------
    def parameters(self) -> List[Parameter]:
        """Parameters referenced by the program (including ones used only
        inside control-flow branch closures), in first-use order.  Cached
        per version: the Executor calls this every run, and walking the
        node list would put an O(ops) Python loop on the hot path."""
        cached = self._params_cache
        if cached is not None and cached[0] == self._version:
            return cached[1]
        seen, out = set(), []

        def add(p):
            if id(p) not in seen:
                seen.add(id(p))
                out.append(p)

        for node in self.nodes:
            for tag, v in node.in_specs:
                if tag == "p":
                    add(v)
            for p in node.extra_params:
                add(p)
        self._params_cache = (self._version, out)
        return out

    def global_block(self):
        return self

    # Block-protocol shims (reference Block API surface)
    @property
    def ops(self):
        return self.nodes

    def all_parameters(self):
        return self.parameters()

    def __repr__(self):
        lines = [f"Program({len(self.nodes)} ops)"]
        for n in self.nodes[:20]:
            ins = ", ".join(
                (v.name if tag == "v" else
                 getattr(v, "name", tag)) for tag, v in n.in_specs)
            outs = ", ".join(v.name for v in n.out_vars)
            lines.append(f"  {n.op_name}({ins}) -> {outs}")
        if len(self.nodes) > 20:
            lines.append(f"  ... {len(self.nodes) - 20} more")
        return "\n".join(lines)


# -- default programs + guard (reference: framework.py
#    default_main_program:6660, program_guard:7006) -------------------------

_default_main = Program()
_default_startup = Program()
_guard_stack: List[Tuple[Program, Program]] = []


def default_main_program() -> Program:
    if _guard_stack:
        return _guard_stack[-1][0]
    return _default_main


def default_startup_program() -> Program:
    if _guard_stack:
        return _guard_stack[-1][1]
    return _default_startup


class program_guard:
    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self._pair = (main_program, startup_program or Program())

    def __enter__(self):
        _guard_stack.append(self._pair)
        return self._pair[0]

    def __exit__(self, *exc):
        _guard_stack.pop()
        return False


def reset_default_programs():
    global _default_main, _default_startup
    _default_main = Program()
    _default_startup = Program()


def data(name: str, shape: Sequence[Optional[int]], dtype="float32",
         lod_level=0) -> Variable:
    """Declare a feed placeholder (reference: static/input.py data:26)."""
    dt = np.dtype(convert_dtype(dtype))
    concrete = tuple(1 if (s is None or s < 0) else int(s) for s in shape)
    prog = default_main_program()
    v = Variable(jax.ShapeDtypeStruct(concrete, dt), prog, name=name,
                 desc_shape=[-1 if (s is None or s < 0) else int(s)
                             for s in shape])
    prog.feed_vars[name] = v
    return v
