"""save/load_inference_model for static programs.

Reference: python/paddle/static/io.py save_inference_model:231,
load_inference_model:434.  TPU-native: the Program is closed over its
current Parameter values and exported as serialized StableHLO
(jax.export), the same artifact format as paddle_tpu.jit.save — one
deployable file family for both dygraph and static sources.
"""
from __future__ import annotations

import os
import pickle
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from ..core.tensor import Tensor
from ..jit.save_load import SUFFIX_MODEL, SUFFIX_PARAMS
from .executor import _interp
from .program import Program, Variable

__all__ = ["save_inference_model", "load_inference_model"]


def _feed_example(var: Variable, sym_count):
    shape = var.shape
    if any(s is None or s < 0 for s in shape):
        dims = []
        for s in shape:
            if s is None or s < 0:
                sym_count[0] += 1
                dims.append(f"b{sym_count[0]}")
            else:
                dims.append(str(s))
        sym = jax_export.symbolic_shape(", ".join(dims))
        return jax.ShapeDtypeStruct(sym, var.data.dtype)
    return jnp.zeros(tuple(shape), var.data.dtype)


def save_inference_model(path_prefix: str, feed_vars: Sequence[Variable],
                         fetch_vars: Sequence[Variable], executor=None,
                         program: Program = None, **kwargs):
    feed_vars = list(feed_vars)
    fetch_vars = list(fetch_vars)
    program = program or feed_vars[0].program
    fetch_names = [v.name for v in fetch_vars]
    feed_names = [v.name for v in feed_vars]

    # prune to the backward slice of the fetch targets (reference:
    # Program._prune_with_input, framework.py:5603) — training-only nodes
    # (loss, labels) drop out of the inference artifact
    needed = set(fetch_names)
    nodes = []
    for node in reversed(program.nodes):
        if any(v.name in needed for v in node.out_vars):
            nodes.append(node)
            for tag, v in node.in_specs:
                if tag == "v":
                    needed.add(v.name)
            # composite control-flow nodes reference upstream Variables
            # through replay closures — keep their producers too
            for v in node.extra_vars:
                needed.add(v.name)
    nodes.reverse()

    params = program.parameters()
    p_arrays = [p.data for p in params]

    def infer_fn(*feed_arrays):
        env = dict(zip(feed_names, feed_arrays))
        pmap = {id(p): a for p, a in zip(params, p_arrays)}
        env = _interp(nodes, env, pmap)
        return [env[n] for n in fetch_names]

    sym_count = [0]
    examples = [_feed_example(v, sym_count) for v in feed_vars]
    exported = jax_export.export(jax.jit(infer_fn))(*examples)

    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + SUFFIX_MODEL, "wb") as f:
        meta = {
            "format": "paddle_tpu.stablehlo.v1",
            "source": "static",
            "feed_names": feed_names,
            "fetch_names": fetch_names,
            "in_shapes": [tuple(str(d) for d in e.shape) for e in examples],
            "in_dtypes": [str(e.dtype) for e in examples],
        }
        head = pickle.dumps(meta)
        f.write(len(head).to_bytes(8, "little"))
        f.write(head)
        f.write(exported.serialize())


class _LoadedProgram:
    """Stands in for (inference_program, feed_names, fetch_targets) on the
    Executor.run path (reference returns a deserialized ProgramDesc)."""

    def __init__(self, exported, meta):
        self._exported = exported
        self._meta = meta
        self.feed_names = meta.get("feed_names", [])
        self.fetch_names = meta.get("fetch_names", [])

    def _run_loaded(self, feed, fetch_list, return_numpy=True):
        feed = feed or {}
        args = [jnp.asarray(np.asarray(feed[n])) for n in self.feed_names]
        outs = self._exported.call(*args)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """Returns [program, feed_target_names, fetch_targets] — run it with
    ``exe.run(program, feed={...}, fetch_list=program.fetch_names)``."""
    with open(path_prefix + SUFFIX_MODEL, "rb") as f:
        n = int.from_bytes(f.read(8), "little")
        meta = pickle.loads(f.read(n))
        blob = f.read()
    exported = jax_export.deserialize(blob)
    prog = _LoadedProgram(exported, meta)
    return [prog, prog.feed_names, prog.fetch_names]
