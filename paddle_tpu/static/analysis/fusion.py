"""Epilogue-fusion pass: realize the cost model's ranked candidates.

``cost._fusion_candidates`` has ranked maximal single-consumer chains
by HBM traffic saved since PR 6 — "the MPK-style feed for the Pallas
tier" — but nothing consumed them.  This pass is the consumer: it walks
the candidates of a recorded Program, pattern-matches each chain's
prefix against the epilogue recipes ``ops.pallas.fused_epilogue``
implements (linear anchor + bias/gelu/relu/residual-add/layer_norm
stages), checks the kernel's shape/dtype gate against the *run-time*
avals, and hands the static Executor a rewrite plan: the matched nodes
collapse into ONE node calling the fused Pallas kernel (fwd +
custom-vjp bwd), so the candidate's ``saved_bytes`` become real HBM
savings instead of a report line.  The analog of the reference's
``ir/*_fuse_pass.cc`` chain matchers feeding ``operators/fused/``.

Two consumers, one matcher — so prediction and execution can never
disagree about what fuses:

- ``Executor._build`` calls :func:`plan_fusions` + :func:`apply_plans`
  to rewrite the node list before tracing (gated on the Pallas tier
  being active, single-device plans only);
- ``Program.analyze`` calls :func:`annotate_candidates` to mark each
  reported candidate ``realized`` (with the kernel label) or not,
  so the report distinguishes realized from still-unrealized savings.

Everything here is best-effort by contract: a chain the matcher cannot
prove safe (unreadable closure, unexpected kwargs, gate miss) is left
on the composite path untouched.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..program import _OpNode

__all__ = ["plan_fusions", "apply_plans", "annotate_candidates",
           "FusionPlan"]

_MISS = object()


def _free(fn, name, default=_MISS):
    """Read a closure freevar off a recorded op fn (the lint/transform
    layers already rely on these recording closures being plain Python
    functions); ``default`` when absent/unreadable."""
    code = getattr(fn, "__code__", None)
    cells = getattr(fn, "__closure__", None)
    if code is None or cells is None:
        return default
    try:
        return cells[code.co_freevars.index(name)].cell_contents
    except (ValueError, IndexError):  # not a freevar of this fn
        return default


class FusionPlan:
    """One matched chain prefix -> one fused-kernel node."""

    __slots__ = ("node_indices", "stages", "x_spec", "w_spec", "b_spec",
                 "operand_specs", "out_var", "label")

    def __init__(self, node_indices, stages, x_spec, w_spec, b_spec,
                 operand_specs, out_var, label):
        self.node_indices = list(node_indices)
        self.stages = tuple(stages)
        self.x_spec = x_spec
        self.w_spec = w_spec
        self.b_spec = b_spec
        self.operand_specs = list(operand_specs)
        self.out_var = out_var
        self.label = label


def _aval_of(spec, avals):
    """Shape/dtype carrier for an in_spec entry."""
    tag, x = spec
    if tag == "v":
        return avals.get(id(x), x.data)
    if tag == "p":
        from .liveness import param_array
        return param_array(x)
    if tag == "c":
        return x
    return None


def _match_chain(nodes, chain, avals) -> Optional[FusionPlan]:
    """Match the longest realizable prefix of one candidate chain."""
    import numpy as np

    from ...ops.pallas.fused_epilogue import (fused_epilogue_supported,
                                              stage_label)

    anchor = nodes[chain[0]]
    if anchor.op_name != "linear" or anchor.kw:
        return None
    if len(anchor.in_specs) not in (2, 3):
        return None
    x_spec, w_spec = anchor.in_specs[0], anchor.in_specs[1]
    b_spec = anchor.in_specs[2] if len(anchor.in_specs) == 3 else None
    w_aval = _aval_of(w_spec, avals)
    x_aval = _aval_of(x_spec, avals)
    if w_aval is None or x_aval is None or len(w_aval.shape) != 2:
        return None
    n = int(w_aval.shape[1])
    out_aval = avals.get(id(anchor.out_vars[0]), anchor.out_vars[0].data)
    out_shape = tuple(int(s) for s in out_aval.shape)

    stages: List[tuple] = []
    operand_specs: List[tuple] = []
    operand_shapes: List[tuple] = []
    fused = [chain[0]]
    chain_var = anchor.out_vars[0]

    for idx in chain[1:]:
        node = nodes[idx]
        name = node.op_name
        st = None
        ops: List[tuple] = []
        if name == "relu" and len(node.in_specs) == 1 and not node.kw:
            st = ("relu",)
        elif name == "gelu" and len(node.in_specs) == 1 and not node.kw:
            approx = _free(node.fn, "approximate")
            if isinstance(approx, bool):
                st = ("gelu", approx)
        elif name == "add" and len(node.in_specs) == 2 and not node.kw:
            other = [s for s in node.in_specs
                     if not (s[0] == "v" and s[1] is chain_var)]
            if len(other) == 1:
                o_aval = _aval_of(other[0], avals)
                if o_aval is not None:
                    shp = tuple(int(s) for s in o_aval.shape)
                    if shp == out_shape or shp == (n,) or shp == (1, n):
                        st = ("add",) if shp == out_shape else ("bias",)
                        ops = [other[0]]
        elif name == "layer_norm" and not node.kw \
                and 1 <= len(node.in_specs) <= 3 \
                and node.in_specs[0][0] == "v" \
                and node.in_specs[0][1] is chain_var:
            ndims = _free(node.fn, "n")
            eps = _free(node.fn, "epsilon")
            if ndims == 1 and isinstance(eps, float):
                affine = node.in_specs[1:]
                good = all(
                    (a := _aval_of(sp, avals)) is not None
                    and tuple(int(s) for s in a.shape) in ((n,), (1, n))
                    for sp in affine)
                if good:
                    has_w = len(affine) >= 1
                    has_b = len(affine) >= 2
                    st = ("layer_norm", eps, has_w, has_b)
                    ops = list(affine)
        if st is None:
            break
        # the chain var must feed this node (candidates guarantee it,
        # but add's operand filter above is identity-based — re-check)
        if not any(s[0] == "v" and s[1] is chain_var
                   for s in node.in_specs):
            break
        stages.append(st)
        operand_specs.extend(ops)
        operand_shapes.extend(
            tuple(int(s) for s in _aval_of(sp, avals).shape)
            for sp in ops)
        fused.append(idx)
        chain_var = node.out_vars[0]
        out_shape = tuple(int(s) for s in avals.get(
            id(chain_var), chain_var.data).shape)

    if len(fused) < 2:
        return None  # a bare matmul saves nothing — not a realization

    # the "bias" stage synthesized from a broadcast add consumes its
    # operand like the anchor bias does; gate sees the full recipe
    gate_stages = ((("bias",),) if b_spec is not None else ()) \
        + tuple(stages)
    gate_ops = ([tuple(int(s) for s in _aval_of(b_spec, avals).shape)]
                if b_spec is not None else []) + operand_shapes
    x_shape = tuple(int(s) for s in x_aval.shape)
    dtype = np.dtype(x_aval.dtype)
    if not fused_epilogue_supported(x_shape, tuple(
            int(s) for s in w_aval.shape), dtype, gate_stages, gate_ops):
        return None
    return FusionPlan(fused, stages, x_spec, w_spec, b_spec,
                      operand_specs, nodes[fused[-1]].out_vars[0],
                      stage_label(gate_stages))


def _candidates(graph, avals, fetched_ids):
    from .cost import _fusion_candidates, _node_costs
    costs = _node_costs(graph, avals)
    return _fusion_candidates(graph, costs, avals, fetched_ids, None)


def plan_fusions(program, fetch_list=None,
                 feed_shapes: Optional[Dict[str, Sequence[int]]] = None
                 ) -> List[FusionPlan]:
    """Match every ranked candidate of ``program`` against the kernel
    recipes under the given concrete feed shapes (run-time avals — the
    recorded placeholder batch of 1 would fail the row-tile gate).
    Returns the realizable plans; empty on any analysis failure."""
    from .cost import _propagate_avals
    from .graph import DefUseGraph
    try:
        graph = DefUseGraph(program)
        avals = (_propagate_avals(graph, dict(feed_shapes))
                 if feed_shapes else {})
        fetched = set()
        for f in (fetch_list or []):
            v = graph.resolve_fetch(f)
            if v is not None:
                fetched.add(id(v))
        plans = []
        for cand in _candidates(graph, avals, fetched):
            plan = _match_chain(graph.nodes, cand["ops"], avals)
            if plan is not None:
                plans.append(plan)
        return plans
    except Exception:  # noqa: BLE001 - fusion is best-effort by contract
        return []


def apply_plans(nodes: Sequence[_OpNode], plans: Sequence[FusionPlan]
                ) -> List[_OpNode]:
    """Rewrite the node list: each plan's nodes collapse into one fused
    node at the position of the chain's LAST member (every input is
    produced at or before its original position; the dropped
    intermediates have no consumer outside the chain by construction)."""
    from ...ops.pallas.fused_epilogue import fused_linear_epilogue

    drop: Dict[int, FusionPlan] = {}
    last: Dict[int, FusionPlan] = {}
    for p in plans:
        for i in p.node_indices:
            drop[i] = p
        last[p.node_indices[-1]] = p

    out: List[_OpNode] = []
    for i, node in enumerate(nodes):
        p = last.get(i)
        if p is not None:
            has_bias = p.b_spec is not None
            stages = p.stages

            def make_fn(stages=stages, has_bias=has_bias):
                def fused_fn(x, w, *rest):
                    bias = rest[0] if has_bias else None
                    operands = rest[1:] if has_bias else rest
                    return fused_linear_epilogue(
                        x, w, bias, stages, operands)
                return fused_fn

            in_specs = [p.x_spec, p.w_spec]
            if has_bias:
                in_specs.append(p.b_spec)
            in_specs.extend(p.operand_specs)
            out.append(_OpNode(make_fn(), {}, "pallas_fused_epilogue",
                               in_specs, [p.out_var], False,
                               loc=node.loc))
        elif i not in drop:
            out.append(node)
    return out


def annotate_candidates(program, candidates, graph, avals,
                        fetched_ids=(), plan_active=False) -> None:
    """Mark each reported candidate dict with what the executor's pass
    would realize for it right now: ``realized`` (kernel label or
    None) and ``realized_ops`` (the fused prefix).  Gated exactly like
    the executor — tier flags (``ops.pallas.support.tier_enabled``)
    AND no sharding plan (``plan_active``; the executor skips the pass
    under an explicit GSPMD lowering) — so the report states what
    actually happens, not what hypothetically could."""
    from ...ops.pallas.support import tier_enabled
    active = tier_enabled() and not plan_active
    for cand in candidates:
        cand["realized"] = None
        cand["realized_ops"] = []
        if not active:
            continue
        try:
            plan = _match_chain(graph.nodes, cand["ops"], avals)
        except Exception:  # noqa: BLE001 - annotation is best-effort
            plan = None
        if plan is not None:
            cand["realized"] = plan.label
            cand["realized_ops"] = list(plan.node_indices)
