"""Def-use graph over a Program's recorded op list.

Reference: paddle/fluid/framework/ir/graph.h builds Node(op)/Node(var)
bipartite edges from each OpDesc's inputs/outputs; graph_helper.cc walks
them for cycle checks and topological order.  Here the op list is already
topologically ordered by construction (append-only recording), so the
graph's job is the def-use indexing the verifier passes (and every future
transform pass) need: who produces each Variable, who consumes it, and
which ops are reachable backwards from a set of fetch roots.

Variables are keyed by IDENTITY (``id(var)``), not name — name collisions
are one of the defect classes the verifier must detect, so the graph
cannot assume names are unique.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..program import Program, Variable


class DefUseGraph:
    """Producers/consumers index for one Program.

    - ``producer_of[id(v)]`` — node index whose ``out_vars`` contains v;
    - ``consumers_of[id(v)]`` — node indexes reading v, via ``in_specs``
      ("v" entries) or ``extra_vars`` (control-flow replay closures);
    - ``feeds`` — name → Variable roots declared by ``static.data``;
    - ``params_of[i]`` — Parameters node i reads (in_specs "p" entries
      plus ``extra_params``).
    """

    def __init__(self, program: Program):
        self.program = program
        self.nodes = list(program.nodes)
        self.feeds: Dict[str, Variable] = dict(program.feed_vars)
        self.producer_of: Dict[int, int] = {}
        self.consumers_of: Dict[int, List[int]] = {}
        self.params_of: Dict[int, list] = {}
        # (var, first_producer, second_producer): a Variable re-emitted
        # by a later node — a spliced/duplicated transform output
        self.duplicate_producers: List[Tuple[Variable, int, int]] = []
        # id -> Variable for every var that appears anywhere (outputs,
        # inputs, extra replay refs, feeds); ids alone are not enough for
        # diagnostics, which want names/shapes
        self.vars: Dict[int, Variable] = {}

        for v in self.feeds.values():
            self.vars[id(v)] = v
        for i, node in enumerate(self.nodes):
            for v in node.out_vars:
                self.vars[id(v)] = v
                first = self.producer_of.setdefault(id(v), i)
                if first != i:  # re-recorded output: a defect
                    self.duplicate_producers.append((v, first, i))
            params = []
            for tag, x in node.in_specs:
                if tag == "v":
                    self.vars[id(x)] = x
                    self.consumers_of.setdefault(id(x), []).append(i)
                elif tag == "p":
                    params.append(x)
            for x in node.extra_vars:
                self.vars[id(x)] = x
                self.consumers_of.setdefault(id(x), []).append(i)
            params.extend(node.extra_params)
            self.params_of[i] = params

    # -- queries ----------------------------------------------------------
    def node_inputs(self, i: int) -> List[Tuple[Variable, str]]:
        """Variables node ``i`` reads, as (var, kind) with kind "in" for
        direct in_specs operands and "extra" for replay-closure refs."""
        node = self.nodes[i]
        out = [(x, "in") for tag, x in node.in_specs if tag == "v"]
        out.extend((x, "extra") for x in node.extra_vars)
        return out

    def is_feed(self, v: Variable) -> bool:
        return any(f is v for f in self.feeds.values())

    def resolve_fetch(self, f) -> Optional[Variable]:
        """Map a fetch_list entry (Variable or name string) to a Variable
        known to this graph; None when the name resolves nowhere."""
        if isinstance(f, Variable):
            return f
        if isinstance(f, str):
            for v in self.vars.values():
                if v.name == f:
                    return v
        return None

    def live_nodes(self, fetch_vars: Sequence[Variable]) -> Set[int]:
        """Node indexes reachable backwards from ``fetch_vars`` (the
        reference's prune/backward-DFS over the ir::Graph)."""
        live: Set[int] = set()
        stack = [self.producer_of[id(v)] for v in fetch_vars
                 if id(v) in self.producer_of]
        while stack:
            i = stack.pop()
            if i in live:
                continue
            live.add(i)
            for v, _kind in self.node_inputs(i):
                p = self.producer_of.get(id(v))
                if p is not None and p not in live:
                    stack.append(p)
        return live

    def loc_of(self, i: int) -> Optional[str]:
        """file:line anchor recorded for node ``i`` (present when
        FLAGS_static_verify or FLAGS_static_anchors was on at record
        time)."""
        loc = getattr(self.nodes[i], "loc", None)
        if loc is None:
            return None
        return f"{loc[0]}:{loc[1]}"
