"""Verifier passes over the def-use graph.

Reference: paddle/fluid/framework/ir/pass.h (Pass::Apply over ir::Graph)
+ the checking passes the reference runs before execution
(graph_helper.cc HasCircle, lock_free_optimize_pass's def-use checks,
framework.py Program._prune's backward reachability).  Each pass is a
small object with a ``name`` and ``run(graph, fetch_list)`` returning
structured :class:`Diagnostic` records; :func:`check` runs a pass
pipeline, :func:`verify` raises ``GraphVerificationError`` when any
error-severity diagnostic survives.

Defect classes covered (ISSUE: the five the Executor cannot catch before
``jax.jit`` explodes):

- use-before-produce / never-produced operands (broken topological
  order after a transform, or a Variable fabricated outside recording);
- cross-program leaks (a Variable recorded in program A consumed by
  ops of program B — the reference's
  "TensorCopy between different workspaces" bug class);
- dead ops / unused feeds relative to the fetch targets;
- shape/dtype drift (recorded output avals no longer reproducible from
  the inputs — e.g. a Parameter was re-assigned with a new shape after
  recording);
- variable-name collisions (the Executor's env is name-keyed; two
  distinct Variables sharing a name silently alias).
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import numpy as np

from ...core.enforce import GraphVerificationError
from ..program import Program, Variable
from .graph import DefUseGraph

__all__ = [
    "Diagnostic", "AnalysisPass", "UseBeforeProducePass",
    "CrossProgramLeakPass", "DeadCodePass", "ShapeDtypeConsistencyPass",
    "NameCollisionPass", "check", "verify", "default_passes",
    "PASS_REGISTRY",
]


class Diagnostic:
    """One structured finding (severity, pass, message, op/var anchors).

    ``loc`` is a ``file:line`` string when the op recorded a source
    anchor (FLAGS_static_verify or FLAGS_static_anchors on at build
    time), else None.
    """

    __slots__ = ("severity", "pass_name", "message", "op_index",
                 "op_name", "var_name", "loc")

    ERROR = "error"
    WARNING = "warning"
    INFO = "info"

    def __init__(self, severity: str, pass_name: str, message: str,
                 op_index: Optional[int] = None,
                 op_name: Optional[str] = None,
                 var_name: Optional[str] = None,
                 loc: Optional[str] = None):
        self.severity = severity
        self.pass_name = pass_name
        self.message = message
        self.op_index = op_index
        self.op_name = op_name
        self.var_name = var_name
        self.loc = loc

    def __str__(self):
        anchor = ""
        if self.op_index is not None:
            anchor = f" (op #{self.op_index}"
            if self.op_name:
                anchor += f" {self.op_name}"
            if self.loc:
                anchor += f" @ {self.loc}"
            anchor += ")"
        elif self.loc:
            anchor = f" (@ {self.loc})"
        return (f"[{self.pass_name}] {self.severity}: "
                f"{self.message}{anchor}")

    def __repr__(self):
        return f"Diagnostic({self!s})"

    def to_dict(self) -> dict:
        """JSON-able record (tools/lint_program.py --format json and
        ProgramReport.to_dict serialize diagnostics through this)."""
        return {s: getattr(self, s) for s in self.__slots__}


class AnalysisPass:
    """Base pass protocol (reference: ir/pass.h Pass)."""

    name = "analysis-pass"

    def run(self, graph: DefUseGraph,
            fetch_list: Optional[Sequence] = None) -> List[Diagnostic]:
        raise NotImplementedError

    # -- shared helpers ----------------------------------------------------
    def _diag(self, graph, severity, message, op_index=None,
              var_name=None):
        op_name = (graph.nodes[op_index].op_name
                   if op_index is not None else None)
        loc = graph.loc_of(op_index) if op_index is not None else None
        return Diagnostic(severity, self.name, message,
                          op_index=op_index, op_name=op_name,
                          var_name=var_name, loc=loc)


class UseBeforeProducePass(AnalysisPass):
    """Every operand must be a feed root or the output of an EARLIER op.

    Append-only recording guarantees this by construction; graph
    transforms (reordering, pruning, node splicing) are exactly where it
    breaks — and an out-of-order op list makes the Executor's name-keyed
    env raise a bare KeyError mid-jit."""

    name = "use-before-produce"

    def run(self, graph, fetch_list=None):
        out: List[Diagnostic] = []
        prog = graph.program
        for v, first, dup in graph.duplicate_producers:
            out.append(self._diag(
                graph, Diagnostic.ERROR,
                f"Variable '{v.name}' is produced twice (also by op "
                f"#{first} '{graph.nodes[first].op_name}'); the later "
                f"write silently shadows the earlier one",
                op_index=dup, var_name=v.name))
        for i in range(len(graph.nodes)):
            for v, kind in graph.node_inputs(i):
                if v.program is not prog:
                    continue  # CrossProgramLeakPass owns this defect
                if graph.is_feed(v):
                    continue
                p = graph.producer_of.get(id(v))
                if p is None:
                    out.append(self._diag(
                        graph, Diagnostic.ERROR,
                        f"Variable '{v.name}' is consumed but never "
                        f"produced by any op and is not a feed",
                        op_index=i, var_name=v.name))
                elif p >= i:
                    out.append(self._diag(
                        graph, Diagnostic.ERROR,
                        f"Variable '{v.name}' is used before it is "
                        f"produced (producer is op #{p} "
                        f"'{graph.nodes[p].op_name}')",
                        op_index=i, var_name=v.name))
        return out


class CrossProgramLeakPass(AnalysisPass):
    """No operand (or output) may belong to a different Program.

    The defect arises from building two programs without resetting the
    guard, or caching layer outputs across ``program_guard`` blocks; at
    run time the foreign Variable's name is missing from the env and the
    failure points at the wrong program."""

    name = "cross-program-leak"

    def run(self, graph, fetch_list=None):
        out: List[Diagnostic] = []
        prog = graph.program
        for i, node in enumerate(graph.nodes):
            for v, kind in graph.node_inputs(i):
                if v.program is not prog:
                    how = ("replay closure" if kind == "extra"
                           else "operand")
                    out.append(self._diag(
                        graph, Diagnostic.ERROR,
                        f"Variable '{v.name}' belongs to a different "
                        f"Program (leaked across program boundaries as "
                        f"an op {how})",
                        op_index=i, var_name=v.name))
            for v in node.out_vars:
                if v.program is not prog:
                    out.append(self._diag(
                        graph, Diagnostic.ERROR,
                        f"output Variable '{v.name}' belongs to a "
                        f"different Program", op_index=i,
                        var_name=v.name))
        for name, v in graph.feeds.items():
            if v.program is not prog:
                out.append(Diagnostic(
                    Diagnostic.ERROR, self.name,
                    f"feed Variable '{name}' belongs to a different "
                    f"Program", var_name=name))
        return out


class DeadCodePass(AnalysisPass):
    """Ops unreachable backwards from the fetch targets, and feeds no
    live op consumes (reference: Program._prune + the executor's
    'skip_ops' pruning).  Needs fetch targets: without them liveness is
    undefined, so the pass only checks that explicit fetch entries
    resolve.  A Program with an attached optimizer treats the loss as an
    implicit fetch root."""

    name = "dead-code"

    def run(self, graph, fetch_list=None):
        out: List[Diagnostic] = []
        roots: List[Variable] = []
        for f in (fetch_list or []):
            v = graph.resolve_fetch(f)
            if v is None:
                out.append(Diagnostic(
                    Diagnostic.ERROR, self.name,
                    f"fetch target {f!r} does not name any Variable in "
                    f"the program",
                    var_name=f if isinstance(f, str) else None))
            elif v.program is not graph.program:
                out.append(Diagnostic(
                    Diagnostic.ERROR, self.name,
                    f"fetch target '{v.name}' belongs to a different "
                    f"Program", var_name=v.name))
            else:
                roots.append(v)
        opt = graph.program._optimizer
        if opt is not None and isinstance(opt[1], Variable):
            roots.append(opt[1])  # the loss drives the update
        if not roots:
            return out
        live = graph.live_nodes(roots)
        for i in range(len(graph.nodes)):
            if i not in live:
                outs = ", ".join(v.name for v in graph.nodes[i].out_vars)
                out.append(self._diag(
                    graph, Diagnostic.WARNING,
                    f"op is dead relative to the fetch targets "
                    f"(outputs [{outs}] are never fetched nor consumed "
                    f"by a live op)", op_index=i))
        for name, v in graph.feeds.items():
            used = any(i in live for i in graph.consumers_of.get(id(v), ())
                       ) or any(v is r for r in roots)
            if not used:
                out.append(Diagnostic(
                    Diagnostic.WARNING, self.name,
                    f"feed '{name}' is never consumed by a live op "
                    f"(unused relative to the fetch targets)",
                    var_name=name))
        return out


class ShapeDtypeConsistencyPass(AnalysisPass):
    """Re-derive every op's output avals with ``jax.eval_shape`` and
    compare against what recording stored on its out_vars.

    Recording already shape-checked each op once; what this catches is
    DRIFT after recording — a Parameter re-assigned with a different
    shape/dtype, a transform that rewired operands, or a mutated
    ``node.kw`` — before the mismatch detonates inside the whole-program
    jit with an error pointing at XLA internals."""

    name = "shape-dtype"

    def run(self, graph, fetch_list=None):
        from ...core.tensor import Parameter
        from ..program import replay_scope
        import jax.numpy as jnp

        out: List[Diagnostic] = []
        prog = graph.program
        for i, node in enumerate(graph.nodes):
            args = []
            for tag, x in node.in_specs:
                if tag == "v":
                    args.append(x.data)
                elif tag == "p":
                    args.append(jax.ShapeDtypeStruct(
                        x.data.shape, np.dtype(x.data.dtype)))
                elif tag == "c":
                    args.append(jax.ShapeDtypeStruct(
                        x.shape, np.dtype(x.dtype)))
                else:
                    args.append(x)

            def _abstract_lookup(v):
                if isinstance(v, Parameter):
                    return v.data
                return jnp.zeros(v.data.shape, v.data.dtype)

            try:
                with replay_scope(_abstract_lookup):
                    avals = jax.eval_shape(
                        lambda *a, _n=node: _n.fn(*a, **_n.kw), *args)
            except Exception as e:  # noqa: BLE001 - any trace failure
                out.append(self._diag(
                    graph, Diagnostic.ERROR,
                    f"op no longer traces against its recorded input "
                    f"specs: {type(e).__name__}: {e}", op_index=i))
                continue
            avals = list(avals) if node.multi else [avals]
            if len(avals) != len(node.out_vars):
                out.append(self._diag(
                    graph, Diagnostic.ERROR,
                    f"op now produces {len(avals)} outputs; "
                    f"{len(node.out_vars)} were recorded", op_index=i))
                continue
            for v, a in zip(node.out_vars, avals):
                want = (tuple(v.data.shape), np.dtype(v.data.dtype))
                got = (tuple(a.shape), np.dtype(a.dtype))
                if want != got:
                    out.append(self._diag(
                        graph, Diagnostic.ERROR,
                        f"Variable '{v.name}' was recorded as "
                        f"shape={list(want[0])} dtype={want[1]} but now "
                        f"traces to shape={list(got[0])} dtype={got[1]} "
                        f"(inputs changed after recording?)",
                        op_index=i, var_name=v.name))
        return out


class NameCollisionPass(AnalysisPass):
    """Two distinct Variables sharing one name.

    The Executor env and the feed/fetch protocol are name-keyed, so a
    collision silently aliases the later write over the earlier one —
    fetches and downstream ops read the wrong tensor."""

    name = "name-collision"

    def run(self, graph, fetch_list=None):
        out: List[Diagnostic] = []
        by_name: dict = {}
        for v in graph.vars.values():
            if v.program is graph.program:
                by_name.setdefault(v.name, []).append(v)
        for name, vs in sorted(by_name.items()):
            if len(vs) > 1:
                where = []
                for v in vs:
                    p = graph.producer_of.get(id(v))
                    if p is not None:
                        where.append(f"op #{p} {graph.nodes[p].op_name}")
                    elif graph.is_feed(v):
                        where.append("feed")
                    else:
                        where.append("unproduced")
                out.append(Diagnostic(
                    Diagnostic.ERROR, self.name,
                    f"{len(vs)} distinct Variables share the name "
                    f"{name!r} ({', '.join(where)}); the name-keyed "
                    f"executor env would silently alias them",
                    var_name=name))
        return out


def default_passes() -> List[AnalysisPass]:
    return [UseBeforeProducePass(), CrossProgramLeakPass(),
            NameCollisionPass(), ShapeDtypeConsistencyPass(),
            DeadCodePass()]


PASS_REGISTRY = {cls.name: cls for cls in (
    UseBeforeProducePass, CrossProgramLeakPass, DeadCodePass,
    ShapeDtypeConsistencyPass, NameCollisionPass)}


def check(program: Program, fetch_list: Optional[Sequence] = None,
          passes: Optional[Sequence[AnalysisPass]] = None,
          sharding=None, mesh_shape=None, sharding_rules=None,
          strategy=None) -> List[Diagnostic]:
    """Run verifier + TPU-readiness hazard passes; return ALL
    diagnostics (errors, warnings, infos) without raising.
    ``fetch_list`` entries may be Variables or names; liveness analysis
    is skipped when no fetch roots are known.  An explicit ``passes``
    sequence replaces the whole default pipeline (including any
    shardcheck passes).

    SPMD safety (shardcheck) runs when a plan is in scope: pass
    ``sharding=`` a concrete/abstract plan, or ``mesh_shape=`` a plain
    ``{axis: size}`` dict (optionally with ``sharding_rules=`` /
    ``strategy=``) to resolve an abstract plan against a mesh you don't
    have hardware for — zero devices needed."""
    from .hazards import hazard_passes
    graph = DefUseGraph(program)
    out: List[Diagnostic] = []
    plan = sharding
    shard_pipeline: List[AnalysisPass] = []
    if passes is None:
        if plan is None and mesh_shape is not None:
            from .shardcheck import build_abstract_plan
            plan = build_abstract_plan(program, mesh_shape,
                                       rules=sharding_rules,
                                       strategy=strategy)
        if plan is not None:
            from .shardcheck import shardcheck_passes
            shard_pipeline = shardcheck_passes(plan)
    pipeline = (passes if passes is not None
                else list(default_passes()) + hazard_passes()
                + shard_pipeline)
    for p in pipeline:
        out.extend(p.run(graph, fetch_list))
    return out


def verify(program: Program, fetch_list: Optional[Sequence] = None,
           passes: Optional[Sequence[AnalysisPass]] = None,
           raise_on_error: bool = True, sharding=None, mesh_shape=None,
           sharding_rules=None, strategy=None) -> List[Diagnostic]:
    """:func:`check`, raising :class:`GraphVerificationError` when any
    error-severity diagnostic is found.  Returns the diagnostics (the
    warnings, when it does not raise)."""
    diags = check(program, fetch_list, passes, sharding=sharding,
                  mesh_shape=mesh_shape, sharding_rules=sharding_rules,
                  strategy=strategy)
    errors = [d for d in diags if d.severity == Diagnostic.ERROR]
    if errors and raise_on_error:
        serial = getattr(program, "_serial", None)
        lines = [f"Program verification failed "
                 f"(program #{serial}, {len(errors)} error(s), "
                 f"{len(diags) - len(errors)} warning(s)):"]
        lines += [f"  {d}" for d in diags]
        raise GraphVerificationError("\n".join(lines), diagnostics=diags)
    return diags
