"""Shardcheck: static SPMD safety analysis for (program, mesh, plan).

Every sharding mistake this module catches otherwise surfaces only as a
runtime raise — or a silent wrong answer — on a live mesh: a spec whose
axis the mesh doesn't carry, a collective guarded by a device-varying
predicate (a static deadlock), a SUM-reduced fetch under the dp-mean
grad stage, a mis-priced wire byte.  Shardcheck proves the triple on
CPU with ZERO devices: an :class:`AbstractMesh` is just an ordered
``{axis: size}`` dict, so a ``{dp: 4, mp: 2}`` plan lints on a laptop.

Four pass families, all emitting the PR-1 :class:`Diagnostic` records:

==================  =====================================================
pass                proves
==================  =====================================================
shard-plan          every param covered, every spec axis present and
                    divisible, optimizer slots inherit specs, feeds
                    batch-divisible; ``_fit_spec_to_mesh`` silent
                    downgrades promoted to WARN naming the matched rule
shard-choreography  every replica executes the identical collective
                    sequence: known-bad grad_comm configs (pp/sp mesh
                    axes, param specs outside the FSDP/mp forms) via
                    :func:`grad_comm.plan_status` — hybrid {dp, mp} and
                    ZeRO-3 layouts are first-class and report their
                    gather choreography — sum-classified fetches,
                    collectives under device-varying predicates,
                    overlap-knob resolution
shard-taint         device-varying values (axis_index, shard-local
                    collectives, per-shard RNG) reaching fetches,
                    host-sync ops, or step control flow without a
                    cross-replica reduction; unfolded RNG keys
shard-wire          per-bucket wire bytes re-derived INDEPENDENTLY of
                    ``grad_comm._wire_bytes`` and cross-checked against
                    ``cost._comm_block`` — the measured==predicted gate's
                    third, compile-free leg
==================  =====================================================

The cause strings for configs the Executor refuses at compile time come
from the SAME builders the Executor raises with
(``grad_comm.plan_status`` / ``incompatibility`` /
``sum_fetch_message``), so the static and runtime gates can never
disagree.  Surface: ``Program.verify(sharding=plan)`` /
``analysis.check(program, mesh_shape={"dp": 4, "mp": 2})``,
``FLAGS_shard_verify`` Executor preflight, and
``tools/lint_program.py --mesh-shape dp=4,mp=2``.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .graph import DefUseGraph
from .passes import AnalysisPass, Diagnostic

__all__ = [
    "AbstractMesh", "AbstractPlan", "build_abstract_plan",
    "parse_mesh_shape", "device_varying_taint", "classify_reduction",
    "audit_wire_bytes", "PlanCoveragePass", "CollectiveChoreographyPass",
    "DeviceVaryingTaintPass", "WireByteAuditPass", "shardcheck_passes",
    "SHARDCHECK_PASS_REGISTRY",
]


# ---------------------------------------------------------------------------
# abstract mesh / plan — lint a topology you don't have hardware for
# ---------------------------------------------------------------------------

class AbstractMesh:
    """The slice of ``jax.sharding.Mesh`` the analyses consume: an
    ordered ``{axis: size}`` dict and nothing else.  No devices — the
    whole point is certifying a {dp: 4, mp: 2} plan on a CPU laptop
    with zero accelerators attached."""

    __slots__ = ("shape",)

    def __init__(self, shape: Dict[str, int]):
        self.shape = {str(a): int(s) for a, s in dict(shape).items()}

    @property
    def size(self) -> int:
        n = 1
        for s in self.shape.values():
            n *= s
        return n

    def __repr__(self):
        return f"AbstractMesh({self.shape})"


def parse_mesh_shape(text: str) -> Dict[str, int]:
    """``'dp=4,mp=2'`` -> ``{'dp': 4, 'mp': 2}`` (the lint CLI's
    --mesh-shape syntax).  A bare integer means a 1-axis dp mesh."""
    text = str(text).strip()
    if not text:
        return {}
    if re.fullmatch(r"\d+", text):
        return {"dp": int(text)}
    shape: Dict[str, int] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        m = re.fullmatch(r"([A-Za-z_]\w*)\s*=\s*(\d+)", part)
        if m is None:
            raise ValueError(
                f"mesh shape entry {part!r} is not axis=size "
                f"(expected e.g. 'dp=4,mp=2')")
        shape[m.group(1)] = int(m.group(2))
    return shape


class AbstractPlan:
    """A :class:`ShardingPlan` look-alike resolved against an
    :class:`AbstractMesh`, carrying the resolution trail the coverage
    pass reports from: which rule matched each param (``sources``),
    what ``_fit_spec_to_mesh`` downgraded (``downgrades``), and which
    non-scalar params no rule matched (``unmatched``).  Duck-types the
    plan surface the analyses use (``mesh.shape``, ``param_names``,
    ``param_specs``, ``batch_axes``, ``grad_comm``,
    ``spec_by_name``)."""

    __slots__ = ("mesh", "param_names", "param_specs", "batch_axes",
                 "label", "grad_comm", "sources", "downgrades",
                 "unmatched")

    def __init__(self, mesh: AbstractMesh, param_names, param_specs,
                 batch_axes=("dp",), label: str = "", grad_comm=None,
                 sources=None, downgrades=None, unmatched=None):
        self.mesh = mesh
        self.param_names = list(param_names)
        self.param_specs = list(param_specs)
        self.batch_axes = tuple(a for a in batch_axes
                                if a in mesh.shape)
        self.label = label
        self.grad_comm = grad_comm
        self.sources = dict(sources or {})      # name -> how it resolved
        self.downgrades = list(downgrades or [])
        self.unmatched = list(unmatched or [])

    def batch_divisor(self) -> int:
        n = 1
        for a in self.batch_axes:
            n *= self.mesh.shape[a]
        return n

    def spec_by_name(self, name: str):
        try:
            return self.param_specs[self.param_names.index(name)]
        except ValueError:
            return None

    def __repr__(self):
        return (f"AbstractPlan(mesh={self.mesh.shape}, "
                f"params={len(self.param_names)}, "
                f"unmatched={len(self.unmatched)})")


def build_abstract_plan(program, mesh_shape, rules=None, strategy=None,
                        label: str = "abstract") -> AbstractPlan:
    """Resolve ``program``'s parameters against a mesh SHAPE (no
    devices) with the same per-param precedence as
    ``sharding.plan_for_params``: placement metadata, then partition
    rules (first ``re.search`` match wins), then the ZeRO-3 default,
    then replicated — except that an unmatched non-scalar param is
    RECORDED for the coverage pass instead of raising, so one lint run
    reports every hole at once."""
    from ...distributed import grad_comm as _gc
    from ...distributed.mesh import DP_AXIS
    from ...distributed.sharding import (
        _as_spec, _fit_spec_to_mesh, _is_scalar, _nearest_rule)
    from ...parallel.tp_layers import get_placement
    from .liveness import param_array
    from jax.sharding import PartitionSpec

    mesh = AbstractMesh(mesh_shape)
    if rules is None and strategy is not None:
        rules = getattr(strategy, "sharding_rules", None)
    rules_c = [(p, _as_spec(s)) for p, s in (rules or [])]

    z3 = (strategy is not None and getattr(strategy, "sharding", False)
          and strategy.sharding_configs.stage >= 3
          and DP_AXIS in mesh.shape)
    min_numel = strategy.sharding_configs.min_shard_numel if z3 else 0
    dp = mesh.shape.get(DP_AXIS, 1)

    names, specs = [], []
    sources: Dict[str, str] = {}
    downgrades: List[tuple] = []
    unmatched: List[tuple] = []
    for p in program.parameters():
        arr = param_array(p)
        shape = tuple(int(d) for d in getattr(arr, "shape", ()))
        name = p.name
        pl = get_placement(p)
        if pl is not None:
            spec, source = _as_spec(pl), "placement"
        elif rules_c and not _is_scalar(arr):
            for pat, rspec in rules_c:
                if re.search(pat, name) is not None:
                    spec, source = rspec, f"rule r'{pat}'"
                    break
            else:
                unmatched.append((name, shape,
                                  _nearest_rule(name, rules_c),
                                  len(rules_c)))
                spec, source = PartitionSpec(), "unmatched"
        elif rules_c:
            spec, source = PartitionSpec(), "scalar"
        elif (z3 and shape and not _is_scalar(arr)
              and int(np.prod(shape)) >= min_numel
              and shape[0] % max(dp, 1) == 0):
            spec, source = PartitionSpec(DP_AXIS), "zero3-default"
        else:
            spec, source = PartitionSpec(), "default-replicated"
        dg: List[tuple] = []
        fitted = _fit_spec_to_mesh(spec, shape, mesh.shape, name,
                                   downgrades=dg)
        downgrades.extend((name, source) + rec for rec in dg)
        names.append(name)
        specs.append(fitted)
        sources[name] = source
    return AbstractPlan(mesh, names, specs, batch_axes=(DP_AXIS,),
                        label=label, grad_comm=_gc.resolve(strategy),
                        sources=sources, downgrades=downgrades,
                        unmatched=unmatched)


# ---------------------------------------------------------------------------
# shared graph analyses (used by more than one pass)
# ---------------------------------------------------------------------------

# ops whose OUTPUT differs per device even when inputs are replicated
_DEVICE_VARYING_OPS = frozenset({
    "axis_index", "get_rank", "scatter", "reduce_scatter", "alltoall",
    "all_to_all", "collective_permute", "ppermute",
})
# cross-replica reductions: outputs are replica-identical again
_RESYNC_OPS = frozenset({
    "all_reduce", "all_gather", "broadcast", "psum", "pmean", "pmax",
    "pmin",
})
_CONTROL_FLOW_OPS = frozenset({"cond", "case", "switch_case",
                               "while_loop"})
_RNG_OPS = frozenset({"dropout", "alpha_dropout"})
# unary shape/scale wrappers the reduction classifier sees through
_TRANSPARENT_OPS = frozenset({
    "cast", "astype", "scale", "identity", "assign", "reshape",
    "squeeze", "unsqueeze", "clone", "detach",
})
_SUM_OPS = frozenset({"sum", "reduce_sum", "add_n"})
_MEAN_OPS = frozenset({"mean", "reduce_mean"})
# tokens inside a closure that imply a collective runs when it's called
_COLLECTIVE_TOKENS = frozenset({
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "all_reduce", "reduce_scatter",
    "broadcast", "alltoall", "collective_permute", "axis_index",
})


def device_varying_taint(graph: DefUseGraph) -> Dict[int, Tuple[int, str]]:
    """Forward taint over the recorded op list: ``id(var) -> (source op
    index, source op name)`` for every Variable whose value can differ
    across devices of the mesh.  Collectives that REDUCE over the axis
    (all_reduce/all_gather/broadcast) clear taint — their outputs are
    replica-identical by construction."""
    taint: Dict[int, Tuple[int, str]] = {}
    for i, node in enumerate(graph.nodes):
        if node.op_name in _DEVICE_VARYING_OPS:
            src: Optional[Tuple[int, str]] = (i, node.op_name)
        elif node.op_name in _RESYNC_OPS:
            src = None
        else:
            src = None
            for v, _kind in graph.node_inputs(i):
                if id(v) in taint:
                    src = taint[id(v)]
                    break
        for v in node.out_vars:
            if src is not None:
                taint[id(v)] = src
            else:
                taint.pop(id(v), None)
    return taint


def classify_reduction(graph: DefUseGraph, v,
                       _limit: int = 64) -> Tuple[Optional[str],
                                                  Optional[int]]:
    """How ``v`` was reduced over the batch: ``('sum', op_index)`` /
    ``('mean', op_index)`` / ``(None, None)`` (unknown — the Executor's
    runtime numeric probe still guards that case).  Walks the producer
    chain through transparent unary wrappers; a reduction over
    explicitly non-batch axes is not classified."""
    seen = 0
    while v is not None and seen < _limit:
        seen += 1
        i = graph.producer_of.get(id(v))
        if i is None:
            return None, None
        node = graph.nodes[i]
        kw = dict(getattr(node, "kw", None) or {})
        red = kw.get("reduction")
        if red == "sum":
            return "sum", i
        if red == "mean":
            return "mean", i
        if red == "none":
            return None, None
        if node.op_name in _SUM_OPS or node.op_name in _MEAN_OPS:
            axis = kw.get("axis", kw.get("dim"))
            axes = (axis if isinstance(axis, (tuple, list))
                    else None if axis is None else [axis])
            if axes is not None and 0 not in [int(a) for a in axes]:
                return None, None  # reduces non-batch dims only
            return (("sum" if node.op_name in _SUM_OPS else "mean"), i)
        if node.op_name in _TRANSPARENT_OPS:
            ins = [x for x, kind in graph.node_inputs(i)
                   if kind == "in"]
            if len(ins) == 1:
                v = ins[0]
                continue
        return None, None
    return None, None


def _mentions_collective(fn, _depth: int = 0) -> Optional[str]:
    """First collective token referenced by ``fn``'s code object, its
    nested code constants, or its closure cells — how the choreography
    pass sees into control-flow branch closures, which are replayed
    closures, not recorded nodes."""
    if _depth > 4 or fn is None:
        return None
    code = getattr(fn, "__code__", None)
    if code is not None:
        hit = _COLLECTIVE_TOKENS.intersection(code.co_names)
        if hit:
            return sorted(hit)[0]
        for const in code.co_consts:
            if hasattr(const, "co_names"):
                sub = _COLLECTIVE_TOKENS.intersection(const.co_names)
                if sub:
                    return sorted(sub)[0]
    for cell in getattr(fn, "__closure__", None) or ():
        try:
            inner = cell.cell_contents
        except ValueError:  # empty cell
            continue
        if callable(inner) and inner is not fn:
            tok = _mentions_collective(inner, _depth + 1)
            if tok:
                return tok
    return None


def _derive_gplan(program, plan, graph: Optional[DefUseGraph] = None):
    """The GradCommPlan the Executor would compile for (program, plan),
    derived with the SAME production order and bucketer — or None when
    grad_comm is off/error or no optimizer is attached."""
    from ...distributed import grad_comm as _gc
    from ...distributed.mesh import DP_AXIS
    from .liveness import _opt_unpack, param_array
    status, _msg = _gc.plan_status(plan)
    if status != "active" or program._optimizer is None:
        return None
    _opt, trainable = _opt_unpack(program)
    if not trainable:
        return None
    shapes = [tuple(param_array(p).shape) for p in trainable]
    loss = program._optimizer[1]
    order = _gc.production_order(program, trainable, loss, graph=graph)
    dp = dict(plan.mesh.shape).get(DP_AXIS, 1)
    # the SAME hybrid layout the Executor compiles: FSDP positions take
    # rscatter buckets, sharded params get forward gathers
    named = [(p.name, s) for p, s in zip(trainable, shapes)]
    _kinds, fsdp, gathers = _gc.hybrid_layout(plan, named, order=order)
    return _gc.plan_reduction(shapes, dp=dp, cfg=plan.grad_comm,
                              order=order, fsdp=fsdp, gathers=gathers)


def audit_wire_bytes(gplan) -> dict:
    """Independent re-derivation of every bucket's wire bytes from
    first principles — ring all-reduce moves ``2(dp-1)/dp`` of the
    payload, an int8 block adds a 4-byte fp32 scale, scatter pads to a
    dp multiple.  Deliberately does NOT call ``grad_comm._wire_bytes``
    (auditing a formula with itself proves nothing); the shard-wire
    pass cross-checks this against the schedule, ``cost._comm_block``
    and the ``comm.bucket.<i>.wire_bytes`` runtime stats."""
    from ...distributed.mesh import DP_AXIS
    dp, cfg = gplan.dp, gplan.cfg
    itemsize = {"fp32": 4, "bf16": 2, "int8": 1}
    scale_bytes = 4
    ring = 2.0 * (dp - 1) / dp if dp > 1 else 0.0
    one_dir = (dp - 1) / dp if dp > 1 else 0.0
    buckets = []
    for b in gplan.buckets:
        if dp <= 1 or b.algorithm == "none":
            wire, ncoll = 0, 0
        elif b.algorithm == "rscatter":
            # FSDP reduce-scatter only: no all-gather leg, the payload
            # rides ONE ring direction; int8 pads to dp*block so each
            # shard row holds whole blocks (one-shot: no requantize,
            # so half the two-shot collective count too)
            if b.wire_dtype == "int8":
                blk = int(cfg.block_size)
                padded = -(-b.numel // (dp * blk)) * (dp * blk)
                payload = padded * itemsize["int8"]
                payload += (padded // blk) * scale_bytes
                wire, ncoll = int(round(one_dir * payload)), 2
            else:
                padded = -(-b.numel // dp) * dp
                wire = int(round(one_dir * padded
                                 * itemsize[b.wire_dtype]))
                ncoll = 1
        elif b.wire_dtype == "int8":
            # pad to dp*block so every shard holds whole blocks
            blk = int(cfg.block_size)
            padded = -(-b.numel // (dp * blk)) * (dp * blk)
            payload = padded * itemsize["int8"]
            payload += (padded // blk) * scale_bytes
            wire, ncoll = int(round(ring * payload)), 4
        elif b.algorithm == "scatter":
            padded = -(-b.numel // dp) * dp
            wire = int(round(ring * padded * itemsize[b.wire_dtype]))
            ncoll = 2
        else:  # fused psum
            wire = int(round(ring * b.numel * itemsize[b.wire_dtype]))
            ncoll = 1
        buckets.append({
            "wire_bytes": wire, "collectives": ncoll,
            "numel": b.numel, "algorithm": b.algorithm,
            "wire_dtype": b.wire_dtype,
        })
    total_numel = sum(b.numel for b in gplan.buckets)
    # forward param gathers (hybrid meshes): each moves (size-1)/size
    # of the f32 payload through every device's links, per axis
    gathers = []
    for g in getattr(gplan, "gathers", ()) or ():
        size = int(g["size"])
        frac = (size - 1) / size if size > 1 else 0.0
        gathers.append({
            "axis": str(g["axis"]),
            "wire_bytes": int(round(frac * int(g["numel"]) * 4))})
    bucket_wire = sum(x["wire_bytes"] for x in buckets)
    axis_wire = {DP_AXIS: bucket_wire}
    for g in gathers:
        axis_wire[g["axis"]] = axis_wire.get(g["axis"], 0) \
            + g["wire_bytes"]
    return {
        "dp": dp,
        "buckets": buckets,
        "wire_bytes_per_step": bucket_wire,
        "collectives_per_step": sum(x["collectives"] for x in buckets),
        "fp32_wire_bytes_per_step": int(round(ring * total_numel * 4)),
        "gathers": gathers,
        "gather_wire_bytes_per_step": sum(x["wire_bytes"]
                                          for x in gathers),
        "axis_wire_bytes": axis_wire,
    }


# ---------------------------------------------------------------------------
# (a) plan coverage & divisibility
# ---------------------------------------------------------------------------

class PlanCoveragePass(AnalysisPass):
    """Every parameter covered by a spec, every spec axis present in
    the mesh and dividing its dim, optimizer slots shaped to inherit
    their param's spec, feeds batch-divisible.  For an
    :class:`AbstractPlan` the ``_fit_spec_to_mesh`` downgrades are
    promoted to WARN diagnostics naming the rule that matched — the
    scrollback ``warnings.warn`` becomes a structured, greppable
    record."""

    name = "shard-plan"

    def __init__(self, plan):
        self.plan = plan

    def run(self, graph, fetch_list=None):
        plan = self.plan
        mesh_shape = dict(plan.mesh.shape)
        out: List[Diagnostic] = []

        # 1) coverage + axis presence + divisibility for every param
        from .liveness import param_array
        for p in graph.program.parameters():
            arr = param_array(p)
            shape = tuple(int(d) for d in getattr(arr, "shape", ()))
            numel = int(np.prod(shape)) if shape else 1
            spec = plan.spec_by_name(p.name)
            if spec is None:
                if numel > 1:
                    out.append(self._diag(
                        graph, Diagnostic.WARNING,
                        f"parameter '{p.name}' {list(shape)} is not "
                        f"covered by the sharding plan ({len(plan.param_names)} "
                        f"param spec(s)); it would be replicated by "
                        f"default", var_name=p.name))
                continue
            for d, entry in enumerate(tuple(spec)):
                axes = ([entry] if isinstance(entry, str)
                        else list(entry)
                        if isinstance(entry, (tuple, list)) else [])
                if not axes:
                    continue
                if d >= len(shape):
                    out.append(self._diag(
                        graph, Diagnostic.ERROR,
                        f"spec {spec} of '{p.name}' names dim {d} but "
                        f"the parameter has rank {len(shape)}",
                        var_name=p.name))
                    continue
                div = 1
                for a in axes:
                    size = mesh_shape.get(a)
                    if size is None:
                        out.append(self._diag(
                            graph, Diagnostic.ERROR,
                            f"spec {spec} of '{p.name}' shards dim {d} "
                            f"over mesh axis '{a}' which mesh "
                            f"{mesh_shape} does not carry",
                            var_name=p.name))
                    else:
                        div *= int(size)
                if div > 1 and shape[d] % div != 0:
                    out.append(self._diag(
                        graph, Diagnostic.ERROR,
                        f"'{p.name}' dim {d} ({shape[d]}) is not "
                        f"divisible by the {div}-way sharding of spec "
                        f"{spec} on mesh {mesh_shape}",
                        var_name=p.name))

        # 2) the abstract resolution trail: downgrades + unmatched
        if isinstance(plan, AbstractPlan):
            for (name, source, d, axis, size, reason) in plan.downgrades:
                out.append(self._diag(
                    graph, Diagnostic.WARNING,
                    f"{reason} (resolved via {source})", var_name=name))
            for (name, shape, hint, n_rules) in plan.unmatched:
                out.append(self._diag(
                    graph, Diagnostic.ERROR,
                    f"no partition rule matches parameter '{name}' "
                    f"({n_rules} rule(s) tried)"
                    + (f"; nearest rule: r'{hint}'" if hint else "")
                    + " — add an explicit (regex, PartitionSpec) rule "
                    "for it (use r'.*' -> PartitionSpec() as a final "
                    "catch-all to replicate everything unmatched)",
                    var_name=name))

        # 3) optimizer slots must inherit the param's spec (same
        # eval_shape trace the Executor shards state with): a slot
        # whose shape differs from its param replicates instead, and
        # the ZeRO memory saving silently evaporates for it
        out.extend(self._slot_diags(graph))

        # 4) feeds: a static batch dim not divisible by the batch axes
        # makes feed_spec fall back to replicated (correct, not
        # parallel) — worth a WARN at lint time, not a runtime surprise
        bd = plan.batch_divisor() if hasattr(plan, "batch_divisor") else 1
        if bd > 1:
            for fname, v in graph.feeds.items():
                desc = getattr(v, "desc_shape", None)
                dims = (list(desc) if desc is not None
                        else list(getattr(v.data, "shape", ())))
                if not dims or int(dims[0]) < 0:
                    continue  # dynamic batch dim: resolved per feed
                if int(dims[0]) % bd != 0:
                    out.append(self._diag(
                        graph, Diagnostic.WARNING,
                        f"feed '{fname}' batch dim ({dims[0]}) is not "
                        f"divisible by the batch-axes product ({bd}); "
                        f"feed_spec will replicate it — every device "
                        f"computes the full batch", var_name=fname))
        return out

    def _slot_diags(self, graph) -> List[Diagnostic]:
        from ...distributed.sharding import spec_axes
        from .liveness import _opt_unpack, param_array
        import jax
        plan = self.plan
        opt, trainable = _opt_unpack(graph.program)
        if opt is None or not trainable:
            return []
        if not hasattr(opt, "functional_init"):
            return []
        try:
            avals = [jax.ShapeDtypeStruct(
                tuple(param_array(p).shape),
                np.dtype(param_array(p).dtype)) for p in trainable]
            state = jax.eval_shape(opt.functional_init, avals)
        except Exception:  # noqa: BLE001 - analysis must not raise
            return []
        if not (isinstance(state, (list, tuple))
                and len(state) == len(trainable)):
            return []
        out: List[Diagnostic] = []
        for p, aval, slots in zip(trainable, avals, state):
            spec = plan.spec_by_name(p.name)
            if spec is None or not spec_axes(spec):
                continue  # replicated params: nothing to inherit
            if not isinstance(slots, dict):
                continue
            for k, s in slots.items():
                sshape = tuple(getattr(s, "shape", ()))
                if not sshape:
                    continue  # scalar slots (step counts) replicate
                if sshape != tuple(aval.shape):
                    out.append(self._diag(
                        graph, Diagnostic.WARNING,
                        f"optimizer slot '{k}' of '{p.name}' has shape "
                        f"{list(sshape)} != param {list(aval.shape)} — "
                        f"it cannot inherit spec {spec} and replicates "
                        f"instead; the sharded-state memory saving is "
                        f"lost for this slot", var_name=p.name))
        return out


# ---------------------------------------------------------------------------
# (b) collective choreography
# ---------------------------------------------------------------------------

class CollectiveChoreographyPass(AnalysisPass):
    """Prove every replica executes the identical collective sequence.
    Known-bad grad_comm configs (pp/sp meshes, param specs fitting
    neither the FSDP nor the mp form) become ERROR diagnostics with the
    EXACT string the Executor raises (one builder:
    ``grad_comm.incompatibility``, hybrid form); hybrid/FSDP plans get
    their forward param-gather choreography (count, prefetch order,
    per-axis wire) reported as INFO; sum-classified fetches get
    ``sum_fetch_message`` statically, before the runtime numeric probe;
    a collective inside a control-flow branch guarded by a
    device-varying predicate is a static deadlock."""

    name = "shard-choreography"

    def __init__(self, plan, backend: Optional[str] = None):
        self.plan = plan
        self.backend = backend

    def run(self, graph, fetch_list=None):
        from ...distributed import grad_comm as _gc
        plan = self.plan
        out: List[Diagnostic] = []

        status, msg = _gc.plan_status(plan)
        if status == "error":
            out.append(self._diag(graph, Diagnostic.ERROR, msg))
        elif status == "active":
            cfg = plan.grad_comm
            # how the overlap knob resolves on this backend (the
            # auto->xla / ring CPU fallbacks), same text the runtime
            # compile record and cost model print
            out.append(self._diag(
                graph, Diagnostic.INFO,
                _gc.overlap_note(cfg, self.backend)))
            # static sum-classification of the loss and every fetch:
            # the dp-mean stage silently scales SUM reductions by 1/dp
            pack = graph.program._optimizer
            roots = []
            if pack is not None and pack[1] is not None:
                roots.append(("loss", pack[1]))
            for f in (fetch_list or []):
                v = graph.resolve_fetch(f)
                if v is not None:
                    roots.append(("fetch", v))
            seen_ids = set()
            for what, v in roots:
                if id(v) in seen_ids:
                    continue
                seen_ids.add(id(v))
                verdict, op_i = classify_reduction(graph, v)
                if verdict == "sum":
                    out.append(self._diag(
                        graph, Diagnostic.ERROR,
                        _gc.sum_fetch_message(what, v.name),
                        op_index=op_i, var_name=v.name))
            # the bucket schedule itself: statically identical on every
            # replica by construction — report it so the lint output
            # shows WHAT choreography was certified
            gplan = _derive_gplan(graph.program, plan, graph)
            if gplan is not None:
                algos = ", ".join(
                    f"{a}x{n}" for a, n in
                    sorted(gplan.algo_counts().items()))
                out.append(self._diag(
                    graph, Diagnostic.INFO,
                    f"choreography: {len(gplan.buckets)} bucket(s), "
                    f"{gplan.collectives_per_step} collective(s)/step "
                    f"[{algos}] in a static schedule identical on "
                    f"every replica; "
                    f"{len(gplan.residual_buckets)} bucket(s) carry "
                    f"error-feedback residuals; overlap path "
                    f"'{gplan.overlap_path}'"))
                if gplan.gathers:
                    per_axis = ", ".join(
                        f"{a}={v} B" for a, v in
                        sorted(gplan.axis_wire_bytes.items()))
                    out.append(self._diag(
                        graph, Diagnostic.INFO,
                        f"hybrid choreography: "
                        f"{len(gplan.gathers)} forward param "
                        f"gather(s) in production (prefetch) order, "
                        f"{gplan.gather_wire_bytes_per_step} B/step; "
                        f"per-axis wire [{per_axis}]"))

        # collectives under device-varying predicates: replicas take
        # different branches and the collective deadlocks the mesh.
        # Branch bodies are replay closures (not recorded nodes), so
        # look inside the closure's code objects for collective tokens.
        taint = device_varying_taint(graph)
        for i, node in enumerate(graph.nodes):
            if node.op_name not in _CONTROL_FLOW_OPS:
                continue
            tainted = [v for v, _kind in graph.node_inputs(i)
                       if id(v) in taint]
            if not tainted:
                continue
            tok = _mentions_collective(getattr(node, "fn", None))
            for x in getattr(node, "extra_vars", ()) or ():
                if tok:
                    break
                tok = _mentions_collective(x) if callable(x) else tok
            if tok:
                src_i, src_op = taint[id(tainted[0])]
                out.append(self._diag(
                    graph, Diagnostic.ERROR,
                    f"collective '{tok}' inside a '{node.op_name}' "
                    f"branch guarded by device-varying predicate "
                    f"'{tainted[0].name}' (tainted by op #{src_i} "
                    f"'{src_op}'): replicas can take different "
                    f"branches, so the collective deadlocks the mesh",
                    op_index=i, var_name=tainted[0].name))
        return out


# ---------------------------------------------------------------------------
# (c) device-varying taint
# ---------------------------------------------------------------------------

class DeviceVaryingTaintPass(AnalysisPass):
    """Device-varying values (axis_index, shard-local collective
    outputs, per-shard RNG) must be reduced across replicas before they
    reach a fetch, a host-sync op, or the step's control flow —
    otherwise every device reports a different answer, or replicas
    diverge.  Unfolded RNG (no axis_index fold into the key) under an
    active dp mesh is a WARN: every replica draws the SAME mask and
    dropout stops being independent across the batch shards."""

    name = "shard-taint"

    def __init__(self, plan=None):
        self.plan = plan

    def run(self, graph, fetch_list=None):
        from .hazards import _HOST_SYNC_OPS
        taint = device_varying_taint(graph)
        out: List[Diagnostic] = []

        if taint:
            for f in (fetch_list or []):
                v = graph.resolve_fetch(f)
                if v is None or id(v) not in taint:
                    continue
                src_i, src_op = taint[id(v)]
                out.append(self._diag(
                    graph, Diagnostic.ERROR,
                    f"fetch '{v.name}' carries a device-varying value "
                    f"(tainted by op #{src_i} '{src_op}') with no "
                    f"cross-replica reduction on the path — every "
                    f"device fetches a different tensor",
                    op_index=graph.producer_of.get(id(v)),
                    var_name=v.name))
            for i, node in enumerate(graph.nodes):
                if node.op_name in _HOST_SYNC_OPS:
                    for v, _kind in graph.node_inputs(i):
                        if id(v) not in taint:
                            continue
                        src_i, src_op = taint[id(v)]
                        out.append(self._diag(
                            graph, Diagnostic.ERROR,
                            f"host-sync op '{node.op_name}' reads "
                            f"device-varying '{v.name}' (tainted by op "
                            f"#{src_i} '{src_op}') — each host "
                            f"materializes a different value",
                            op_index=i, var_name=v.name))
                elif node.op_name in _CONTROL_FLOW_OPS:
                    for v, _kind in graph.node_inputs(i):
                        if id(v) not in taint:
                            continue
                        src_i, src_op = taint[id(v)]
                        out.append(self._diag(
                            graph, Diagnostic.ERROR,
                            f"'{node.op_name}' is steered by "
                            f"device-varying '{v.name}' (tainted by op "
                            f"#{src_i} '{src_op}') — replicas can "
                            f"diverge on step control flow",
                            op_index=i, var_name=v.name))
                        break

        # unfolded per-shard RNG: the Executor's dp lowering folds the
        # axis index into the key automatically; a program that opts
        # out (_rng_axis_fold=False) draws IDENTICAL randomness on
        # every replica
        out.extend(self._rng_diags(graph))
        return out

    def _rng_diags(self, graph) -> List[Diagnostic]:
        from ...distributed import grad_comm as _gc
        from ...distributed.mesh import DP_AXIS
        plan = self.plan
        if plan is None:
            return []
        dp = dict(plan.mesh.shape).get(DP_AXIS, 1)
        if dp <= 1:
            return []
        if getattr(graph.program, "_rng_axis_fold", True):
            return []
        out: List[Diagnostic] = []
        for i, node in enumerate(graph.nodes):
            if node.op_name in _RNG_OPS:
                out.append(self._diag(
                    graph, Diagnostic.WARNING,
                    f"RNG op '{node.op_name}' with no axis_index fold "
                    f"into its key: all dp={dp} replicas draw the SAME "
                    f"randomness, so masks are correlated across batch "
                    f"shards (fold the mesh axis index into the key, "
                    f"or leave _rng_axis_fold on)", op_index=i))
        return out


# ---------------------------------------------------------------------------
# (d) wire-byte conservation audit
# ---------------------------------------------------------------------------

class WireByteAuditPass(AnalysisPass):
    """Cross-check three derivations of the grad-comm wire bytes that
    must agree byte-for-byte: the GradCommPlan bucket schedule (what
    the Executor compiles and the ``comm.bucket.<i>.wire_bytes`` stats
    report), ``cost._comm_block`` (what ``Program.analyze`` predicts),
    and this pass's INDEPENDENT first-principles re-derivation
    (:func:`audit_wire_bytes`).  A mismatch means the measured ==
    predicted gate would certify a wrong number."""

    name = "shard-wire"

    def __init__(self, plan):
        self.plan = plan

    def run(self, graph, fetch_list=None):
        from ...distributed import grad_comm as _gc
        plan = self.plan
        status, _msg = _gc.plan_status(plan)
        if status != "active":
            return []
        gplan = _derive_gplan(graph.program, plan, graph)
        if gplan is None:
            return []
        audit = audit_wire_bytes(gplan)
        out: List[Diagnostic] = []

        for i, (b, want) in enumerate(zip(gplan.buckets,
                                          audit["buckets"])):
            if b.wire_bytes != want["wire_bytes"]:
                out.append(self._diag(
                    graph, Diagnostic.ERROR,
                    f"wire-byte conservation violated: bucket {i} "
                    f"({b.numel} elems, {b.algorithm}/{b.wire_dtype}, "
                    f"dp={gplan.dp}) schedules {b.wire_bytes} B/step "
                    f"but the independent ring re-derivation gives "
                    f"{want['wire_bytes']} B"))
            if b.collectives != want["collectives"]:
                out.append(self._diag(
                    graph, Diagnostic.ERROR,
                    f"bucket {i} schedules {b.collectives} "
                    f"collective(s) but a {b.algorithm}/{b.wire_dtype} "
                    f"reduction issues {want['collectives']}"))

        if gplan.wire_bytes_per_step != audit["wire_bytes_per_step"]:
            out.append(self._diag(
                graph, Diagnostic.ERROR,
                f"schedule total {gplan.wire_bytes_per_step} B/step != "
                f"audited bucket sum {audit['wire_bytes_per_step']} B"))
        if gplan.fp32_wire_bytes_per_step != \
                audit["fp32_wire_bytes_per_step"]:
            out.append(self._diag(
                graph, Diagnostic.ERROR,
                f"fp32 baseline {gplan.fp32_wire_bytes_per_step} B != "
                f"audited {audit['fp32_wire_bytes_per_step']} B"))
        if gplan.gather_wire_bytes_per_step != \
                audit["gather_wire_bytes_per_step"]:
            out.append(self._diag(
                graph, Diagnostic.ERROR,
                f"wire-byte conservation violated: forward gathers "
                f"schedule {gplan.gather_wire_bytes_per_step} B/step "
                f"but the independent re-derivation gives "
                f"{audit['gather_wire_bytes_per_step']} B"))
        if dict(gplan.axis_wire_bytes) != audit["axis_wire_bytes"]:
            out.append(self._diag(
                graph, Diagnostic.ERROR,
                f"wire-byte conservation violated: per-axis schedule "
                f"{dict(gplan.axis_wire_bytes)} != audited "
                f"{audit['axis_wire_bytes']}"))

        # third leg: the cost model must price the SAME bytes
        from .cost import _comm_block
        try:
            cb = _comm_block(graph.program, plan, graph)
        except Exception:  # noqa: BLE001 - audit must not raise
            cb = None
        if cb is not None and cb.get("enabled"):
            if cb["wire_bytes_per_step"] != audit["wire_bytes_per_step"]:
                out.append(self._diag(
                    graph, Diagnostic.ERROR,
                    f"cost._comm_block predicts "
                    f"{cb['wire_bytes_per_step']} B/step but the audit "
                    f"derives {audit['wire_bytes_per_step']} B — the "
                    f"measured==predicted gate would certify a wrong "
                    f"number"))
            if cb.get("collectives_per_step") != \
                    audit["collectives_per_step"]:
                out.append(self._diag(
                    graph, Diagnostic.ERROR,
                    f"cost._comm_block counts "
                    f"{cb.get('collectives_per_step')} collective(s)/"
                    f"step but the audit derives "
                    f"{audit['collectives_per_step']}"))
            if cb.get("axis_wire_bytes", audit["axis_wire_bytes"]) \
                    != audit["axis_wire_bytes"]:
                out.append(self._diag(
                    graph, Diagnostic.ERROR,
                    f"cost._comm_block predicts per-axis "
                    f"{cb.get('axis_wire_bytes')} but the audit "
                    f"derives {audit['axis_wire_bytes']} — the "
                    f"per-axis measured==predicted gate would certify "
                    f"a wrong number"))

        if not out:
            per_axis = ", ".join(
                f"{a}={v}" for a, v in
                sorted(audit["axis_wire_bytes"].items()))
            out.append(self._diag(
                graph, Diagnostic.INFO,
                f"wire audit: {len(gplan.buckets)} bucket(s), "
                f"{audit['wire_bytes_per_step']} B/step on the wire "
                f"(fp32 baseline {audit['fp32_wire_bytes_per_step']} "
                f"B), {audit['collectives_per_step']} collective(s)/"
                f"step, {len(audit['gathers'])} forward gather(s) "
                f"[per-axis B: {per_axis}] — schedule, cost model and "
                f"independent re-derivation agree"))
        return out


# ---------------------------------------------------------------------------
# pipeline
# ---------------------------------------------------------------------------

def shardcheck_passes(plan, backend: Optional[str] = None
                      ) -> List[AnalysisPass]:
    """The shardcheck pipeline for one plan (concrete ShardingPlan or
    :class:`AbstractPlan`), in dependency order."""
    return [
        PlanCoveragePass(plan),
        CollectiveChoreographyPass(plan, backend=backend),
        DeviceVaryingTaintPass(plan),
        WireByteAuditPass(plan),
    ]


SHARDCHECK_PASS_REGISTRY = {cls.name: cls for cls in (
    PlanCoveragePass, CollectiveChoreographyPass, DeviceVaryingTaintPass,
    WireByteAuditPass)}
