"""Liveness-based peak-memory estimation over a recorded Program.

Reference: the reference's memory_optimize pass family
(paddle/fluid/framework/ir/memory_optimize_pass) computes last-use
intervals over the topologically-ordered op list to reuse buffers; here
the same interval analysis *predicts* peak HBM residency before the
program ever compiles — the quantity the sharding engine (ROADMAP 1)
and the mega-kernel tier (ROADMAP 4) need to reason about placement.

Two bounds are reported:

- ``peak_bytes_donated`` — what the donated, device-resident Executor
  hot path (PR 2) actually holds: parameters + optimizer slots counted
  ONCE (XLA updates them in place via ``donate_argnums``), plus
  gradients and the activations retained for the backward pass;
- ``peak_bytes_no_donation`` — the naive bound with donation off, where
  the old and new parameter/slot buffers are live simultaneously at the
  update.  The gap is exactly what PR 2's donation buys.

For inference programs (no attached optimizer) the two coincide and the
activation term is the true last-use interval peak, not the retained
sum — intermediates die at their last consumer.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..program import Program, Variable
from .graph import DefUseGraph

__all__ = ["MemoryEstimate", "estimate_memory", "aval_bytes",
           "param_array"]


def aval_bytes(aval) -> int:
    """Bytes of one array with the given abstract value (shape/dtype)."""
    n = 1
    for s in aval.shape:
        n *= int(s)
    return n * np.dtype(aval.dtype).itemsize


def param_array(p):
    """The Parameter's current array WITHOUT the escape side effect of
    ``Parameter.data``: a read through the property marks the slot
    escaped, forcing the donated Executor to copy it before its next
    run.  Analysis is read-only and must not tax the hot path."""
    src = getattr(p, "_exec_src", None)
    if src is not None:
        return src[0].p_arrays[src[1]]
    return p.data  # unbound: the property is a raw slot read


def _opt_unpack(program: Program):
    """(optimizer, trainable params) of an attached optimizer, honoring
    minimize(parameters=, no_grad_set=) exactly as the Executor does."""
    pack = program._optimizer
    if pack is None:
        return None, []
    opt, _loss, param_filter, no_grad_set = (tuple(pack) + (None, None))[:4]
    allow = (None if param_filter is None
             else {id(p) for p in param_filter})
    deny = ({id(p) for p in no_grad_set} if no_grad_set else set())
    trainable = [p for p in program.parameters()
                 if p.trainable and not p.stop_gradient
                 and (allow is None or id(p) in allow)
                 and id(p) not in deny]
    return opt, trainable


def _slot_bytes(opt, trainable) -> Optional[int]:
    """Optimizer slot bytes via an abstract ``functional_init`` trace
    (jax.eval_shape allocates nothing); None when the optimizer cannot
    be traced abstractly."""
    per = _slot_bytes_list(opt, trainable)
    return None if per is None else sum(per)


def _slot_bytes_list(opt, trainable) -> Optional[List[int]]:
    """Per-trainable-param slot bytes (the functional state is a
    per-param list of slot dicts); None when untraceable."""
    import jax

    if opt is None or not trainable:
        return []
    try:
        avals = [jax.ShapeDtypeStruct(tuple(param_array(p).shape),
                                      np.dtype(param_array(p).dtype))
                 for p in trainable]
        state = jax.eval_shape(opt.functional_init, avals)
        if isinstance(state, (list, tuple)) and len(state) == len(trainable):
            return [sum(aval_bytes(leaf)
                        for leaf in jax.tree_util.tree_leaves(s))
                    for s in state]
        total = sum(aval_bytes(leaf)
                    for leaf in jax.tree_util.tree_leaves(state))
        # unknown structure: charge everything to the first param
        return [total] + [0] * (len(trainable) - 1)
    except Exception:  # noqa: BLE001 - estimation must not raise
        return None


class MemoryEstimate:
    """Byte-level breakdown of one Program's predicted residency."""

    __slots__ = ("activation_peak_bytes", "peak_op_index",
                 "retained_activation_bytes", "feed_bytes", "param_bytes",
                 "trainable_param_bytes", "grad_bytes", "slot_bytes",
                 "slots_estimated", "peak_bytes_donated",
                 "peak_bytes_no_donation", "training")

    def to_dict(self) -> dict:
        return {s: getattr(self, s) for s in self.__slots__}

    def __repr__(self):
        return (f"MemoryEstimate(peak_donated={self.peak_bytes_donated}, "
                f"peak_no_donation={self.peak_bytes_no_donation}, "
                f"activation_peak={self.activation_peak_bytes})")


def estimate_memory(graph: DefUseGraph,
                    fetch_vars: Sequence[Variable] = (),
                    avals: Optional[Dict[int, object]] = None,
                    param_div: Optional[Dict[int, int]] = None,
                    act_div: int = 1) -> MemoryEstimate:
    """Interval liveness over the recorded (topologically ordered) op
    list.  ``avals`` optionally overrides recorded abstract values
    (id(var) -> aval), e.g. after re-deriving with a concrete batch
    size; ``fetch_vars`` stay live to the end of the program.

    ``param_div`` (``id(param) -> n``) and ``act_div`` switch the
    estimate to *per-shard* accounting for a GSPMD-sharded program:
    each parameter's bytes (and its gradient and optimizer slots —
    they inherit the param's PartitionSpec) are divided by the product
    of the mesh-axis sizes its spec shards over, and activation/feed
    bytes by the batch-axis product.  Divisions round up — a per-shard
    report never undercounts the ragged last shard."""
    avals = avals or {}
    nodes = graph.nodes
    n = len(nodes)
    param_div = param_div or {}

    def _ceil_div(b: int, d: int) -> int:
        return -(-int(b) // max(int(d), 1))

    def bytes_of(v: Variable) -> int:
        return _ceil_div(aval_bytes(avals.get(id(v), v.data)), act_div)

    fetched = {id(v) for v in fetch_vars}

    # birth/death indexes per var: a var is resident for ops
    # birth..death inclusive.  Feeds are uploaded before op 0; a var
    # nobody consumes dies right after its producer; fetched vars
    # survive to the last op.
    birth: Dict[int, int] = {}
    death: Dict[int, int] = {}
    every: Dict[int, Variable] = {}
    for v in graph.feeds.values():
        birth[id(v)] = 0
        every[id(v)] = v
    for i, node in enumerate(nodes):
        for v in node.out_vars:
            birth.setdefault(id(v), i)
            every.setdefault(id(v), v)
    for vid, b in birth.items():
        cons = graph.consumers_of.get(vid, ())
        death[vid] = max(cons) if cons else b
        if vid in fetched:
            death[vid] = n - 1 if n else 0

    # sweep program points with a running byte counter
    start_at: Dict[int, List[int]] = {}
    end_at: Dict[int, List[int]] = {}
    for vid in birth:
        start_at.setdefault(birth[vid], []).append(vid)
        end_at.setdefault(death[vid], []).append(vid)
    live = 0
    peak = 0
    peak_i = 0
    for i in range(n):
        for vid in start_at.get(i, ()):
            live += bytes_of(every[vid])
        if live > peak:
            peak, peak_i = live, i
        for vid in end_at.get(i, ()):
            live -= bytes_of(every[vid])
    if n == 0:
        peak = sum(bytes_of(v) for v in graph.feeds.values())

    est = MemoryEstimate()
    est.activation_peak_bytes = peak
    est.peak_op_index = peak_i
    # retained = op OUTPUTS only (what the backward saves); feeds are
    # accounted separately as feed_bytes — summing them here too would
    # double-count every input through the training peak/traffic math
    feed_ids = {id(v) for v in graph.feeds.values()}
    est.retained_activation_bytes = sum(
        bytes_of(v) for vid, v in every.items() if vid not in feed_ids)
    est.feed_bytes = sum(bytes_of(v) for v in graph.feeds.values())

    params, seen = [], set()
    for plist in graph.params_of.values():
        for p in plist:
            if id(p) not in seen:
                seen.add(id(p))
                params.append(p)
    def p_bytes(p) -> int:
        raw = (param_array(p).size
               * np.dtype(param_array(p).dtype).itemsize)
        return _ceil_div(raw, param_div.get(id(p), 1))

    est.param_bytes = sum(p_bytes(p) for p in params)

    opt, trainable = _opt_unpack(graph.program)
    est.training = opt is not None
    est.trainable_param_bytes = sum(p_bytes(p) for p in trainable)
    est.grad_bytes = est.trainable_param_bytes if est.training else 0
    slots_list = _slot_bytes_list(opt, trainable)
    if slots_list is None:  # untraceable optimizer: assume Adam-like 2 slots
        est.slot_bytes = 2 * est.trainable_param_bytes
        est.slots_estimated = True
    else:
        # slots inherit their param's PartitionSpec (same shape), so
        # the param's divisor prices them per-shard too
        est.slot_bytes = sum(
            _ceil_div(b, param_div.get(id(p), 1))
            for b, p in zip(slots_list, trainable))
        est.slots_estimated = False

    if est.training:
        # the whole-program jit retains forward activations for the
        # backward pass, so the activation term is the retained sum
        # (plus the feeds, resident throughout), not the
        # inference-interval peak
        act = est.retained_activation_bytes + est.feed_bytes
        est.peak_bytes_donated = (est.param_bytes + est.slot_bytes
                                  + est.grad_bytes + act)
        # donation off: old AND new parameter/slot buffers coexist at
        # the in-graph update
        est.peak_bytes_no_donation = (est.peak_bytes_donated
                                      + est.trainable_param_bytes
                                      + est.slot_bytes)
    else:
        est.peak_bytes_donated = est.param_bytes + est.activation_peak_bytes
        est.peak_bytes_no_donation = est.peak_bytes_donated
    return est
