"""Program IR analysis: def-use graph + verifier pass framework.

TPU-native analog of the reference's ``ir::Graph`` + ``Pass`` layer
(reference: paddle/fluid/framework/ir/graph.h, pass.h,
graph_helper.cc HasCircle/TopologySortOperations): a compile-time
analysis tier over the recorded ``_OpNode`` list that catches malformed
programs BEFORE they reach ``jax.jit``, where the same defects surface
as cryptic trace errors deep inside XLA lowering.

Entry points:

- :func:`check` — run verifier passes, return structured
  :class:`Diagnostic` objects (never raises);
- :func:`verify` — run :func:`check` and raise
  :class:`~paddle_tpu.core.enforce.GraphVerificationError` on errors
  (``Program.verify()`` delegates here);
- ``FLAGS_static_verify`` (core/flags.py) — makes ``static.Executor``
  verify each (program, version) once before its first compile.

Every future graph-transform pass (fused computation-collective
scheduling, mega-kernelization) builds on :class:`DefUseGraph`'s
producer/consumer infrastructure.
"""
from .graph import DefUseGraph  # noqa: F401
from .passes import (PASS_REGISTRY, AnalysisPass, CrossProgramLeakPass,  # noqa
                     DeadCodePass, Diagnostic, NameCollisionPass,
                     ShapeDtypeConsistencyPass, UseBeforeProducePass,
                     check, default_passes, verify)

__all__ = [
    "DefUseGraph", "Diagnostic", "AnalysisPass", "UseBeforeProducePass",
    "CrossProgramLeakPass", "DeadCodePass", "ShapeDtypeConsistencyPass",
    "NameCollisionPass", "check", "verify", "default_passes",
    "PASS_REGISTRY",
]
