"""Program IR analysis: def-use graph, verifier passes, cost model.

TPU-native analog of the reference's ``ir::Graph`` + ``Pass`` layer
(reference: paddle/fluid/framework/ir/graph.h, pass.h,
graph_helper.cc HasCircle/TopologySortOperations): a compile-time
analysis tier over the recorded ``_OpNode`` list that catches malformed
programs BEFORE they reach ``jax.jit`` — and, since ISSUE 6, *prices*
well-formed ones before they reach the hardware.

Entry points:

- :func:`check` — run verifier + TPU-readiness hazard passes, return
  structured :class:`Diagnostic` objects (never raises);
- :func:`verify` — run :func:`check` and raise
  :class:`~paddle_tpu.core.enforce.GraphVerificationError` on errors
  (``Program.verify()`` delegates here);
- :func:`analyze` / ``Program.analyze()`` — the quantitative tier
  (cost.py / liveness.py / hazards.py): per-op FLOPs + byte volumes
  with an explicit ``unmodeled`` bucket, donation-aware peak-memory
  bounds, a roofline summary over :data:`CHIP_SPECS`, TPU-readiness
  hazards, and fusion candidates ranked by HBM traffic saved;
- ``FLAGS_static_verify`` (core/flags.py) — makes ``static.Executor``
  verify each (program, version) once before its first compile;
  ``FLAGS_static_anchors`` — the cheap subset that only records
  file:line anchors so analyzer reports carry source locations.

Every future graph-transform pass (fused computation-collective
scheduling, mega-kernelization) builds on :class:`DefUseGraph`'s
producer/consumer infrastructure; the Pallas kernel tier consumes
``ProgramReport.fusion_candidates``.
"""
from .cost import (CHIP_SPECS, ChipSpec, OpCost, ProgramReport,  # noqa: F401
                   analyze, compile_summary)
from .graph import DefUseGraph  # noqa: F401
from .hazards import (HAZARD_PASS_REGISTRY, DonationAliasPass,  # noqa: F401
                      HostTransferPass, WideDtypePass, hazard_passes)
from .liveness import MemoryEstimate, aval_bytes, estimate_memory  # noqa
from .passes import (PASS_REGISTRY, AnalysisPass, CrossProgramLeakPass,  # noqa
                     DeadCodePass, Diagnostic, NameCollisionPass,
                     ShapeDtypeConsistencyPass, UseBeforeProducePass,
                     check, default_passes, verify)
from .shardcheck import (SHARDCHECK_PASS_REGISTRY, AbstractMesh,  # noqa
                         AbstractPlan, CollectiveChoreographyPass,
                         DeviceVaryingTaintPass, PlanCoveragePass,
                         WireByteAuditPass, audit_wire_bytes,
                         build_abstract_plan, parse_mesh_shape,
                         shardcheck_passes)

__all__ = [
    "DefUseGraph", "Diagnostic", "AnalysisPass", "UseBeforeProducePass",
    "CrossProgramLeakPass", "DeadCodePass", "ShapeDtypeConsistencyPass",
    "NameCollisionPass", "check", "verify", "default_passes",
    "PASS_REGISTRY",
    # quantitative tier (ISSUE 6)
    "analyze", "compile_summary", "ProgramReport", "OpCost", "ChipSpec",
    "CHIP_SPECS", "MemoryEstimate", "estimate_memory", "aval_bytes",
    "hazard_passes", "HostTransferPass", "WideDtypePass",
    "DonationAliasPass", "HAZARD_PASS_REGISTRY",
    # SPMD safety tier (ISSUE 16)
    "AbstractMesh", "AbstractPlan", "build_abstract_plan",
    "parse_mesh_shape", "audit_wire_bytes", "shardcheck_passes",
    "PlanCoveragePass", "CollectiveChoreographyPass",
    "DeviceVaryingTaintPass", "WireByteAuditPass",
    "SHARDCHECK_PASS_REGISTRY",
]
