"""TPU-readiness hazard passes.

The verifier passes (passes.py) prove a Program *well-formed*; these
prove it *TPU-shaped*.  Each is an :class:`AnalysisPass` emitting the
same structured :class:`Diagnostic` records, so ``check``/``verify``,
``Program.analyze()`` and ``tools/lint_program.py`` surface them with
no extra wiring.  Severity policy: a hazard that silently corrupts
scale-out behavior (a megabyte of training data baked into the
executable) is an ``error``; a perf/precision surprise is a
``warning``; a benign-but-worth-knowing canonicalization is ``info``.

Covered hazard classes (ISSUE 6 tentpole d):

- **host-transfer** — eager Tensors / NumPy arrays captured as op
  constants.  The value is frozen into the compiled executable: it is
  re-uploaded at every compile, silently forks from the live host
  object, and a scalar that changes across program builds forces a
  recompile per value (the "recompile-prone scalar feed").  Also flags
  any recorded op whose name is a known host-sync (``numpy``/``item``/
  ``tolist``) — the device pipeline stalls at that point every run.
- **wide-dtype** — float64 avals the TPU runtime silently canonicalizes
  to float32 (jax x64 off), and int64/uint64 avals that land as int32.
- **donation-alias** — distinct Parameters sharing one buffer (tied
  weights by array aliasing).  A buffer may appear in the donated set
  once, so the Executor's dup-buffer guard copies every extra alias
  each run — donation quietly stops being zero-copy for them.
"""
from __future__ import annotations

from typing import List

import numpy as np

from .graph import DefUseGraph
from .liveness import aval_bytes, param_array
from .passes import AnalysisPass, Diagnostic

__all__ = ["HostTransferPass", "WideDtypePass", "DonationAliasPass",
           "hazard_passes", "HAZARD_PASS_REGISTRY"]

# a baked constant this large is training data in the executable
_CONST_ERROR_BYTES = 1 << 20
# above this it is at least a perf smell worth a warning
_CONST_WARN_BYTES = 4 << 10

_HOST_SYNC_OPS = frozenset({"numpy", "item", "tolist", "asnumpy"})


class HostTransferPass(AnalysisPass):
    """Captured host/device constants and host-sync points."""

    name = "host-transfer"

    def run(self, graph: DefUseGraph, fetch_list=None) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for i, node in enumerate(graph.nodes):
            if node.op_name in _HOST_SYNC_OPS:
                out.append(self._diag(
                    graph, Diagnostic.ERROR,
                    f"op '{node.op_name}' forces a device->host sync "
                    f"every run, stalling the async dispatch pipeline; "
                    f"fetch the value through fetch_list instead",
                    op_index=i))
            for tag, x in node.in_specs:
                if tag == "c":
                    a = x
                elif tag == "l" and isinstance(x, np.ndarray):
                    a = x
                else:
                    continue
                nb = aval_bytes(a)
                if nb >= _CONST_ERROR_BYTES:
                    sev, why = Diagnostic.ERROR, (
                        "baked into the compiled executable — this is "
                        "tensor data riding the program, re-uploaded on "
                        "every compile and invisible to checkpoints")
                elif nb >= _CONST_WARN_BYTES:
                    sev, why = Diagnostic.WARNING, (
                        "captured as a compile-time constant; it forks "
                        "silently from the live host value and bloats "
                        "the executable")
                else:
                    sev, why = Diagnostic.INFO, (
                        "captured as a compile-time constant; rebuilding "
                        "the program with a different value forces a "
                        "recompile (recompile-prone scalar feed) — "
                        "declare it with static.data and feed it instead")
                kind = ("host ndarray" if isinstance(x, np.ndarray)
                        else "eager Tensor")
                out.append(self._diag(
                    graph, sev,
                    f"{kind} constant ({nb} bytes, shape "
                    f"{list(a.shape)}) {why}", op_index=i))
        return out


class WideDtypePass(AnalysisPass):
    """64-bit avals the TPU runtime will canonicalize narrower."""

    name = "wide-dtype"

    def run(self, graph: DefUseGraph, fetch_list=None) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        seen = set()

        def flag(v, op_index=None):
            if id(v) in seen:
                return
            seen.add(id(v))
            dt = np.dtype(v.data.dtype)
            if dt in (np.float64, np.complex128):
                out.append(self._diag(
                    graph, Diagnostic.WARNING,
                    f"Variable '{v.name}' is {dt.name}: the TPU runtime "
                    f"canonicalizes it to {np.dtype(np.float32).name if dt == np.float64 else 'complex64'} "
                    f"silently (jax x64 disabled) — declare float32, or "
                    f"expect doubled memory/bandwidth if x64 is forced "
                    f"on", op_index=op_index, var_name=v.name))
            elif dt in (np.int64, np.uint64):
                out.append(self._diag(
                    graph, Diagnostic.INFO,
                    f"Variable '{v.name}' is {dt.name}: runtime arrays "
                    f"land as {'int32' if dt == np.int64 else 'uint32'} "
                    f"under the default jax config; declare the narrow "
                    f"dtype to make the program say what it runs",
                    op_index=op_index, var_name=v.name))

        for v in graph.feeds.values():
            flag(v)
        for i, node in enumerate(graph.nodes):
            for v in node.out_vars:
                flag(v, op_index=i)
        return out


class DonationAliasPass(AnalysisPass):
    """Distinct Parameters aliasing one buffer: un-donatable."""

    name = "donation-alias"

    def run(self, graph: DefUseGraph, fetch_list=None) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        by_buf: dict = {}
        for plist in graph.params_of.values():
            for p in plist:
                group = by_buf.setdefault(id(param_array(p)), [])
                if not any(q is p for q in group):
                    group.append(p)
        for group in by_buf.values():
            if len(group) > 1:
                names = ", ".join(repr(p.name) for p in group)
                out.append(Diagnostic(
                    Diagnostic.WARNING, self.name,
                    f"{len(group)} Parameters ({names}) share one "
                    f"underlying buffer: a buffer may enter the donated "
                    f"set once, so the Executor copies every extra "
                    f"alias per run — tie weights through one Parameter "
                    f"object (or accept the copy)",
                    var_name=group[0].name))
        return out


def hazard_passes() -> List[AnalysisPass]:
    """The TPU-readiness pass family, in reporting order."""
    return [HostTransferPass(), WideDtypePass(), DonationAliasPass()]


HAZARD_PASS_REGISTRY = {cls.name: cls for cls in (
    HostTransferPass, WideDtypePass, DonationAliasPass)}
