"""Static cost model: per-op FLOPs/bytes, roofline, fusion candidates.

Quantitative sibling of the verifier passes: everything here is
computed from the *recorded avals* of the op list — no execution, no
profiler.  The outputs are the facts the remaining ROADMAP items
consume: the Pallas mega-kernel tier (ROADMAP 4) picks fusion
candidates by per-chain memory-traffic savings (the MPK selection
criterion), and the sharding engine (ROADMAP 1) needs per-op byte
volumes to price resharding.

Honesty contract: every op lands in exactly one of *modeled* (a rule in
the table below priced it) or the explicit ``unmodeled`` bucket, whose
op count and byte volume ride every total — a report never silently
undercounts because an op had no rule.

Entry points:

- :func:`analyze` / ``Program.analyze(...)`` -> :class:`ProgramReport`
  (per-op table, totals, liveness memory, roofline, hazards, top-k
  fusion candidates);
- :func:`compile_summary` — the light always-on slice the static
  Executor attaches to every compile via
  ``observability.record_compile`` (predicted FLOPs/peak bytes next to
  the attribution record, so predicted-vs-measured drift is visible);
- :data:`CHIP_SPECS` — default roofline specs (cpu / v4 / v5e / v5p).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..program import Program, Variable
from .graph import DefUseGraph
from .liveness import MemoryEstimate, aval_bytes, estimate_memory
from .passes import Diagnostic

__all__ = ["ChipSpec", "CHIP_SPECS", "OpCost", "ProgramReport",
           "analyze", "compile_summary"]


# ---------------------------------------------------------------------------
# chip specs (public peak numbers; bf16/fp32-mixed systolic peak, HBM BW)
# ---------------------------------------------------------------------------

class ChipSpec:
    """Roofline corner of one accelerator.  ``ici_bw`` is the nominal
    per-chip interconnect bandwidth (bytes/s through one device's
    links) that turns the grad-comm plan's wire bytes into seconds —
    the exposed-comm model divides per-bucket wire bytes by it."""

    __slots__ = ("name", "peak_flops", "hbm_bw", "hbm_bytes", "ici_bw")

    def __init__(self, name: str, peak_flops: float, hbm_bw: float,
                 hbm_bytes: int, ici_bw: float = 0.0):
        self.name = name
        self.peak_flops = float(peak_flops)
        self.hbm_bw = float(hbm_bw)
        self.hbm_bytes = int(hbm_bytes)
        self.ici_bw = float(ici_bw)

    def to_dict(self) -> dict:
        return {"name": self.name, "peak_flops": self.peak_flops,
                "hbm_bw": self.hbm_bw, "hbm_bytes": self.hbm_bytes,
                "ici_bw": self.ici_bw}


CHIP_SPECS: Dict[str, ChipSpec] = {
    # nominal host CPU: AVX-512-ish core complex + DDR5 channel pair;
    # 'interconnect' between virtual devices is a memcpy
    "cpu": ChipSpec("cpu", 200e9, 40e9, 16 << 30, 20e9),
    "v4": ChipSpec("v4", 275e12, 1228e9, 32 << 30, 300e9),
    "v5e": ChipSpec("v5e", 197e12, 819e9, 16 << 30, 186e9),
    "v5p": ChipSpec("v5p", 459e12, 2765e9, 95 << 30, 600e9),
}


# ---------------------------------------------------------------------------
# per-op FLOP rules
# ---------------------------------------------------------------------------

def _numel(aval) -> int:
    n = 1
    for s in aval.shape:
        n *= int(s)
    return n


# elementwise ops: flops = factor * output elements
_ELEMENTWISE: Dict[str, int] = {
    "add": 1, "subtract": 1, "multiply": 1, "divide": 1, "pow": 1,
    "scale": 2, "clip": 2, "abs": 1, "negative": 1, "sign": 1,
    "maximum": 1, "minimum": 1, "floor": 1, "ceil": 1, "round": 1,
    "square": 1, "reciprocal": 1, "remainder": 1, "floor_divide": 1,
    "equal": 1, "not_equal": 1, "greater_than": 1, "greater_equal": 1,
    "less_than": 1, "less_equal": 1, "logical_and": 1, "logical_or": 1,
    "logical_not": 1, "logical_xor": 1, "bitwise_not": 1, "where": 1,
    "isnan": 1, "isinf": 1, "isfinite": 1, "isclose": 4, "add_n": 1,
    "relu": 1, "relu6": 2, "leaky_relu": 2, "prelu": 2, "hardtanh": 2,
    "hardshrink": 2, "softshrink": 2, "thresholded_relu": 2,
    "hardsigmoid": 3, "maxout": 2, "masked_fill": 1, "increment": 1,
    "exp": 10, "log": 10, "log2": 10, "log10": 10, "log1p": 10,
    "expm1": 10, "sqrt": 10, "rsqrt": 10, "sin": 10, "cos": 10,
    "tan": 10, "asin": 10, "acos": 10, "atan": 10, "sinh": 10,
    "cosh": 10, "tanh": 10, "asinh": 10, "acosh": 10, "atanh": 10,
    "sigmoid": 10, "log_sigmoid": 12, "softplus": 12, "silu": 11,
    "swish": 11, "gelu": 14, "elu": 11, "selu": 12, "celu": 11,
    "stanh": 11, "mish": 14, "erf": 10, "erfinv": 12,
    "dropout": 3, "alpha_dropout": 4, "label_smooth": 2,
    "lerp": 3, "logaddexp": 12, "nan_to_num": 2, "one_hot": 1,
    "gumbel_softmax": 15, "deg2rad": 1, "rad2deg": 1, "cast": 0,
}

# reductions: flops = factor * input elements
_REDUCE: Dict[str, int] = {
    "sum": 1, "mean": 1, "max": 1, "min": 1, "prod": 1, "all": 1,
    "any": 1, "argmax": 1, "argmin": 1, "count_nonzero": 1,
    "nansum": 2, "nanmean": 2, "norm": 2, "std": 4, "var": 3,
    "logsumexp": 12, "cumsum": 1, "cumprod": 1, "cummax": 1,
    "logcumsumexp": 12, "trace": 1, "median": 8, "kthvalue": 8,
    "mode": 8, "sort": 16, "argsort": 16, "topk": 8, "dist": 3,
    "allclose": 4, "histogram": 2, "bincount": 1, "diff": 1,
    "searchsorted": 8, "pool": None,  # pool priced by its window below
}

# pure data movement / indexing: modeled, zero FLOPs
_MOVEMENT = frozenset({
    "reshape", "flatten", "squeeze", "unsqueeze", "transpose", "t",
    "swapaxes", "moveaxis", "slice", "strided_slice", "split", "unbind",
    "concat", "stack", "tile", "expand", "expand_as", "broadcast_to",
    "broadcast_tensors", "gather", "gather_nd", "index_select",
    "index_sample", "take_along_axis", "put_along_axis", "scatter",
    "scatter_nd_add", "embedding", "pad", "flip", "roll", "rot90",
    "clone", "crop_tensor", "diag", "diag_embed", "diagflat", "tril",
    "triu", "repeat_interleave", "shard_index", "sequence_mask",
    "multiplex", "set_value", "assign", "identity", "numel", "shape",
})

# normalizations: flops = factor * input elements (stats + affine)
_NORMALIZE: Dict[str, int] = {
    "batch_norm": 8, "layer_norm": 8, "instance_norm": 8,
    "group_norm": 8, "local_response_norm": 10, "normalize": 6,
    "spectral_norm": 10, "softmax": 5, "log_softmax": 6,
    "sequence_softmax": 5,
}

# losses: factor * first-input elements
_LOSS: Dict[str, int] = {
    "mse_loss": 4, "l1_loss": 3, "smooth_l1_loss": 5,
    "square_error_cost": 3, "cross_entropy": 8,
    "linear_cross_entropy": 8, "binary_cross_entropy": 12,
    "bce_with_logits": 14, "nll_loss": 3, "kl_div": 12, "log_loss": 12,
    "hinge_embedding_loss": 4, "margin_ranking_loss": 4,
    "cosine_embedding_loss": 8, "ctc_loss": 32, "dice_loss": 6,
    "npair_loss": 8, "sigmoid_focal_loss": 16, "hsigmoid_loss": 10,
}


def _contracted_dim(in_avals, kw) -> int:
    """K of a matmul from the lhs aval, honoring transpose kwargs."""
    a = in_avals[0]
    if not a.shape:
        return 1
    tx = bool(kw.get("transpose_x", kw.get("transpose_a", False)))
    return int(a.shape[-2] if (tx and len(a.shape) >= 2) else a.shape[-1])


class OpCost:
    """One op's modeled cost (or its explicit unmodeled admission)."""

    __slots__ = ("op_index", "op_name", "rule", "flops", "in_bytes",
                 "out_bytes", "param_bytes", "modeled", "loc")

    def __init__(self, op_index, op_name, rule, flops, in_bytes,
                 out_bytes, param_bytes, modeled, loc=None):
        self.op_index = op_index
        self.op_name = op_name
        self.rule = rule
        self.flops = int(flops)
        self.in_bytes = int(in_bytes)
        self.out_bytes = int(out_bytes)
        self.param_bytes = int(param_bytes)
        self.modeled = modeled
        self.loc = loc

    @property
    def total_bytes(self) -> int:
        return self.in_bytes + self.out_bytes + self.param_bytes

    def to_dict(self) -> dict:
        return {s: getattr(self, s) for s in self.__slots__}

    def __repr__(self):
        return (f"OpCost(#{self.op_index} {self.op_name}: "
                f"flops={self.flops}, bytes={self.total_bytes}, "
                f"modeled={self.modeled})")


def _op_flops(node, in_avals, param_avals, out_avals
              ) -> Tuple[Optional[int], str]:
    """(flops, rule name) or (None, 'unmodeled')."""
    name = node.op_name
    out_n = sum(_numel(a) for a in out_avals)
    in_n = _numel(in_avals[0]) if in_avals else 0

    if name in ("linear", "addmm"):
        k = _contracted_dim(in_avals or param_avals, node.kw)
        bias = out_n if (len(param_avals) > 1 or name == "addmm") else 0
        return 2 * out_n * k + bias, "matmul"
    if name in ("matmul", "matmul_transpose", "mm", "bmm", "mv",
                "inner", "outer", "dot"):
        k = (_contracted_dim(in_avals, node.kw) if in_avals else 1)
        if name == "outer":
            k = 1
        return 2 * out_n * k, "matmul"
    if name in ("conv2d", "conv3d", "conv1d", "sequence_conv"):
        # weight [Co, Ci/g, *k]: each output element costs one dot of
        # length Ci/g * prod(kernel)
        if param_avals:
            w = param_avals[0]
            dot = _numel(w) // max(int(w.shape[0]), 1)
            bias = out_n if len(param_avals) > 1 else 0
            return 2 * out_n * dot + bias, "conv"
        return None, "unmodeled"
    if name in ("conv2d_transpose", "conv3d_transpose"):
        # every input element scatters one weight-sized stencil
        if param_avals:
            w = param_avals[0]
            dot = _numel(w) // max(int(w.shape[0]), 1)
            bias = out_n if len(param_avals) > 1 else 0
            return 2 * in_n * dot + bias, "conv"
        return None, "unmodeled"
    if name == "pool":
        win = node.kw.get("window", ())
        wn = 1
        for s in win:
            wn *= int(s)
        return out_n * max(wn, 1), "reduce"
    if name in ("adaptive_avg_pool1d", "adaptive_avg_pool2d",
                "adaptive_avg_pool3d", "adaptive_max_pool1d",
                "adaptive_max_pool2d", "adaptive_max_pool3d",
                "interpolate", "pixel_shuffle", "unfold", "grid_sample",
                "affine_grid", "temporal_shift"):
        return 2 * max(in_n, out_n), "sample"
    if name in ("scaled_dot_product_attention", "flash_attention"):
        # q,k,v avals: 2 * numel(q) * Lk for QK^T plus the same for PV,
        # plus a softmax over the score matrix (approximate)
        if len(in_avals) >= 2 and len(in_avals[1].shape) >= 2:
            q, kv = in_avals[0], in_avals[1]
            lk = int(kv.shape[-2]) if len(kv.shape) >= 2 else 1
            scores = _numel(q) // max(int(q.shape[-1]), 1) * lk
            return 4 * _numel(q) * lk + 5 * scores, "attention"
        return None, "unmodeled"
    if name == "paged_attention":
        # serving decode attention over a page-table-indexed KV pool:
        # q [S, H, D], pool [(L,) N, page, Hkv, D], page_table [S, P].
        # Logical context T = P * page; QK^T + PV cost 4*numel(q)*T,
        # softmax ~5 per score.  (Input bytes are corrected to the
        # page GATHER volume in _node_costs — the op reads S*T rows of
        # K and V, not the whole physical pool.)
        if len(in_avals) >= 4 and len(in_avals[0].shape) == 3 \
                and len(in_avals[3].shape) == 2:
            q, kp, pt = in_avals[0], in_avals[1], in_avals[3]
            page = int(kp.shape[-3])
            T = int(pt.shape[1]) * page
            scores = _numel(q) // max(int(q.shape[-1]), 1) * T
            return 4 * _numel(q) * T + 5 * scores, "attention"
        return None, "unmodeled"
    if name in _NORMALIZE:
        return _NORMALIZE[name] * max(in_n, out_n), "normalize"
    if name in _LOSS:
        return _LOSS[name] * in_n, "loss"
    if name in _ELEMENTWISE:
        return _ELEMENTWISE[name] * out_n, "elementwise"
    if name in _REDUCE:
        return (_REDUCE[name] or 1) * in_n, "reduce"
    if name in _MOVEMENT:
        return 0, "movement"
    return None, "unmodeled"


def _node_costs(graph: DefUseGraph,
                avals: Optional[Dict[int, object]] = None) -> List[OpCost]:
    import jax

    from .liveness import param_array

    avals = avals or {}

    def aval_of(v):
        return avals.get(id(v), v.data)

    out: List[OpCost] = []
    for i, node in enumerate(graph.nodes):
        in_avals, param_avals = [], []
        in_bytes = param_bytes = 0
        for tag, x in node.in_specs:
            if tag == "v":
                a = aval_of(x)
                in_avals.append(a)
                in_bytes += aval_bytes(a)
            elif tag == "p":
                arr = param_array(x)
                a = jax.ShapeDtypeStruct(tuple(arr.shape),
                                         np.dtype(arr.dtype))
                param_avals.append(a)
                param_bytes += aval_bytes(a)
            elif tag == "c":
                in_avals.append(x)
                in_bytes += aval_bytes(x)
            elif isinstance(x, np.ndarray):
                in_avals.append(x)
                in_bytes += aval_bytes(x)
        out_avals = [aval_of(v) for v in node.out_vars]
        out_bytes = sum(aval_bytes(a) for a in out_avals)
        flops, rule = _op_flops(node, in_avals, param_avals, out_avals)
        if node.op_name == "paged_attention" and len(in_avals) >= 5 \
                and len(in_avals[3].shape) == 2:
            # traffic = the page GATHER (K and V rows the table names),
            # not the whole physical pool the aval describes
            q, kp, pt = in_avals[0], in_avals[1], in_avals[3]
            page, hkv, d = (int(s) for s in kp.shape[-3:])
            S, P = (int(s) for s in pt.shape)
            item = np.dtype(kp.dtype).itemsize
            gather = 2 * S * P * page * hkv * d * item      # K + V
            in_bytes = (aval_bytes(q) + gather
                        + aval_bytes(pt) + aval_bytes(in_avals[4]))
        out.append(OpCost(i, node.op_name, rule,
                          flops if flops is not None else 0,
                          in_bytes, out_bytes, param_bytes,
                          modeled=flops is not None,
                          loc=graph.loc_of(i)))
    return out


# per-parameter-element FLOPs of the in-graph optimizer update
_OPT_FLOPS_PER_ELEM = {
    "SGD": 2, "Momentum": 4, "Adagrad": 8, "RMSProp": 10,
    "Adadelta": 10, "Adam": 18, "AdamW": 20, "Lamb": 24,
}


def _optimizer_flops(program: Program, trainable_bytes: int,
                     elem_size: int = 4) -> int:
    pack = program._optimizer
    if pack is None:
        return 0
    per = _OPT_FLOPS_PER_ELEM.get(type(pack[0]).__name__, 10)
    return per * (trainable_bytes // max(elem_size, 1))


# ---------------------------------------------------------------------------
# gradient-collective prediction (grad_comm wire bytes)
# ---------------------------------------------------------------------------

def _comm_block(program: Program, plan,
                graph: Optional[DefUseGraph] = None) -> Optional[dict]:
    """Predicted per-step gradient-communication cost of a training
    program under a sharding plan: per-collective wire bytes (quantized
    payload + scales), latency-vs-bandwidth classification, and the
    fp32 baseline.  With an active ``grad_comm`` spec the numbers come
    from the SAME ``plan_reduction`` the Executor compiles, so
    prediction and the runtime ``comm.wire_bytes`` stat agree exactly;
    without one, the block models GSPMD's default fp32 grad psum."""
    if program._optimizer is None or plan is None:
        return None
    from ...distributed import grad_comm as _gc
    from ...distributed.mesh import DP_AXIS
    from .liveness import _opt_unpack, param_array
    dp = dict(plan.mesh.shape).get(DP_AXIS, 1)
    # the SAME trainable filter the Executor differentiates with
    # (honors minimize's parameters=/no_grad_set) — the measured ==
    # predicted contract depends on the grad list matching exactly
    _opt, trainable = _opt_unpack(program)
    shapes = [tuple(param_array(p).shape) for p in trainable]
    grad_bytes = sum(4 * int(np.prod(s)) if s else 4 for s in shapes)
    ring = (2.0 * (dp - 1) / dp) if dp > 1 else 0.0
    fp32_wire = int(round(ring * grad_bytes))
    # the Executor's OWN activation predicate (shared, so measured and
    # predicted can never disagree about which path runs); a configured-
    # but-impossible spec is reported, not silently priced as fp32 —
    # the Executor will refuse to compile that program
    status, err = _gc.plan_status(plan)
    if status != "active":
        return {
            "enabled": False, "dp": dp, "dtype": "fp32",
            **({"error": err} if err else {}),
            # GSPMD's default grad psum sits after backward in the
            # schedule the compiler emits without a latency-hiding
            # scheduler — modeled as fully exposed (issue_frac 1)
            "overlap": "none", "overlap_path": "none",
            "wire_bytes_per_step": fp32_wire,
            "fp32_wire_bytes_per_step": fp32_wire,
            "gathers": [], "gather_wire_bytes_per_step": 0,
            "axis_wire_bytes": ({DP_AXIS: fp32_wire} if dp > 1
                                else {}),
            "collectives": ([] if dp <= 1 else [{
                "params": list(range(len(shapes))),
                "numel": grad_bytes // 4, "algorithm": "gspmd_psum",
                "wire_dtype": "fp32", "wire_bytes": fp32_wire,
                "collectives": 1, "classification": "bandwidth",
                "error_feedback": False, "issue_frac": 1.0}]),
        }
    cfg = plan.grad_comm
    # the SAME production order the Executor buckets with (backward
    # levels over the DefUseGraph) — bucket contents, and therefore
    # per-bucket wire bytes and issue points, cannot drift apart
    pack = program._optimizer
    order = _gc.production_order(program, trainable,
                                 pack[1] if pack is not None else None,
                                 graph=graph)
    # the SAME hybrid layout the Executor compiles (FSDP rscatter
    # buckets + forward gather schedule from the plan's own specs) —
    # per-axis prediction and the runtime comm.axis.<name>.wire_bytes
    # stats read one derivation
    named = [(p.name, s) for p, s in zip(trainable, shapes)]
    _kinds, fsdp, gathers = _gc.hybrid_layout(plan, named, order=order)
    gplan = _gc.plan_reduction(shapes, dp=dp, cfg=cfg, order=order,
                               fsdp=fsdp, gathers=gathers)
    return {
        "enabled": True, "dp": dp, "dtype": cfg.dtype,
        "block_size": cfg.block_size,
        "error_feedback": cfg.error_feedback,
        "overlap": cfg.overlap,
        "overlap_path": gplan.overlap_path,
        "wire_bytes_per_step": gplan.wire_bytes_per_step,
        "fp32_wire_bytes_per_step": gplan.fp32_wire_bytes_per_step,
        "collectives_per_step": gplan.collectives_per_step,
        "collectives": [b.to_dict() for b in gplan.buckets],
        "gathers": list(gplan.gathers),
        "gather_wire_bytes_per_step": gplan.gather_wire_bytes_per_step,
        "axis_wire_bytes": dict(gplan.axis_wire_bytes),
    }


def _comm_seconds(comm: dict, backward_s: float, ici_bw: float
                  ) -> Tuple[float, float]:
    """(total comm seconds, predicted EXPOSED comm seconds) of one
    comm block on a chip with ``ici_bw`` interconnect bandwidth.

    The exposed share follows the bucket schedule: bucket i's grads
    are complete at ``backward_s * issue_frac_i``, its collective then
    occupies the link after any earlier bucket's finishes, and
    whatever the link is still moving when backward ends is exposed —
    ``max(0, link_end - backward_s)``.  For a single bucket this is
    exactly ``max(0, comm_s - overlappable_backward_s)``.  With
    ``overlap_path == 'none'`` (or no overlap info) the whole stage is
    serialized after backward: exposed == total.

    Hybrid meshes add the forward param gathers
    (``gather_wire_bytes_per_step``): they always count toward the
    total; on an overlapping path they are issued ahead of each
    layer's forward in production order and hide behind forward
    compute, on the barriered path they serialize like everything
    else."""
    if ici_bw <= 0:
        return 0.0, 0.0
    gather_s = comm.get("gather_wire_bytes_per_step", 0) / ici_bw
    total = comm["wire_bytes_per_step"] / ici_bw + gather_s
    if not comm.get("enabled") or comm.get("overlap_path") == "none":
        return total, total
    link_end = 0.0
    for b in comm.get("collectives", ()):
        ready = backward_s * float(b.get("issue_frac", 1.0))
        link_end = max(link_end, ready) + b["wire_bytes"] / ici_bw
    return total, max(0.0, link_end - backward_s)


# ---------------------------------------------------------------------------
# shape re-derivation (concrete batch size)
# ---------------------------------------------------------------------------

def _propagate_avals(graph: DefUseGraph,
                     feed_shapes: Dict[str, Sequence[int]]
                     ) -> Dict[int, object]:
    """Re-derive every aval with concrete feed shapes by replaying each
    op through ``jax.eval_shape`` in topological order (the recorded
    placeholder for a dynamic dim is 1; costs scale with the real batch
    only when re-derived).  Falls back to the recorded aval for any op
    that fails to re-trace — the verifier owns reporting that."""
    import jax
    import jax.numpy as jnp

    from ...core.tensor import Parameter
    from ..program import replay_scope
    from .liveness import param_array

    avals: Dict[int, object] = {}
    for name, v in graph.feeds.items():
        shape = feed_shapes.get(name)
        if shape is None:
            avals[id(v)] = v.data
        else:
            avals[id(v)] = jax.ShapeDtypeStruct(
                tuple(int(s) for s in shape), np.dtype(v.data.dtype))

    def lookup(x):
        if isinstance(x, Parameter):
            arr = param_array(x)
            return jnp.zeros(arr.shape, arr.dtype)
        a = avals.get(id(x), x.data)
        return jnp.zeros(a.shape, a.dtype)

    for node in graph.nodes:
        args = []
        for tag, x in node.in_specs:
            if tag == "v":
                args.append(avals.get(id(x), x.data))
            elif tag == "p":
                arr = param_array(x)
                args.append(jax.ShapeDtypeStruct(tuple(arr.shape),
                                                 np.dtype(arr.dtype)))
            elif tag == "c":
                args.append(jax.ShapeDtypeStruct(tuple(x.shape),
                                                 np.dtype(x.dtype)))
            else:
                args.append(x)
        try:
            with replay_scope(lookup):
                derived = jax.eval_shape(
                    lambda *a, _n=node: _n.fn(*a, **_n.kw), *args)
        except Exception:  # noqa: BLE001 - verifier reports this class
            continue
        derived = list(derived) if node.multi else [derived]
        for v, a in zip(node.out_vars, derived):
            avals[id(v)] = a
    return avals


def _shapes_from_batch(graph: DefUseGraph, batch_size: int
                       ) -> Dict[str, Sequence[int]]:
    out = {}
    for name, v in graph.feeds.items():
        desc = v.desc_shape
        if desc and any(s == -1 for s in desc):
            out[name] = tuple(int(batch_size) if s == -1 else int(s)
                              for s in desc)
    return out


# ---------------------------------------------------------------------------
# fusion candidates
# ---------------------------------------------------------------------------

# an op that can ride a fused kernel's epilogue/prologue
_FUSABLE = (set(_ELEMENTWISE) | set(_REDUCE) | set(_NORMALIZE)
            | set(_LOSS) | _MOVEMENT | {"pool"})


def _fusion_candidates(graph: DefUseGraph, costs: List[OpCost],
                       avals: Dict[int, object], fetched: set,
                       top_k: int) -> List[dict]:
    """Maximal single-consumer chains, ranked by the HBM traffic a
    fused kernel saves: every intermediate that today is written by one
    op and read back by the next (2x its bytes) stays in registers/VMEM
    when the chain compiles as one kernel (the MPK selection rule)."""
    nodes = graph.nodes

    def bytes_of(v):
        return aval_bytes(avals.get(id(v), v.data))

    in_chain: set = set()
    cands: List[dict] = []
    for i in range(len(nodes)):
        if i in in_chain:
            continue
        chain = [i]
        j = i
        while True:
            outs = nodes[j].out_vars
            if len(outs) != 1:
                break
            v = outs[0]
            if id(v) in fetched:
                break
            cons = graph.consumers_of.get(id(v), [])
            if len(cons) != 1:
                break
            k = cons[0]
            if k <= j or k in in_chain or nodes[k].op_name not in _FUSABLE:
                break
            chain.append(k)
            j = k
        if len(chain) < 2:
            continue
        in_chain.update(chain)
        saved = sum(2 * bytes_of(nodes[j].out_vars[0])
                    for j in chain[:-1])
        unfused = sum(costs[j].total_bytes for j in chain)
        cands.append({
            "ops": chain,
            "op_names": [nodes[j].op_name for j in chain],
            "flops": sum(costs[j].flops for j in chain),
            "unfused_traffic_bytes": unfused,
            "fused_traffic_bytes": unfused - saved,
            "saved_bytes": saved,
            "loc": graph.loc_of(chain[0]),
        })
    cands.sort(key=lambda c: -c["saved_bytes"])
    return cands if top_k is None else cands[:top_k]


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GiB"


def _fmt_flops(n: float) -> str:
    for unit in ("", "K", "M", "G", "T"):
        if abs(n) < 1000 or unit == "T":
            return f"{n:.2f}{unit}F" if unit else f"{int(n)}F"
        n /= 1000.0
    return f"{n:.2f}TF"


class ProgramReport:
    """Everything :func:`analyze` learned about one Program."""

    __slots__ = ("program_serial", "n_ops", "fetch_names", "per_op",
                 "totals", "memory", "memory_per_shard", "roofline",
                 "fusion_candidates", "hazards", "batch_hint")

    def to_dict(self) -> dict:
        return {
            "program": self.program_serial,
            "ops": self.n_ops,
            "fetch": list(self.fetch_names),
            "batch_hint": self.batch_hint,
            "per_op": [c.to_dict() for c in self.per_op],
            "totals": self.totals,
            "memory": self.memory.to_dict(),
            "memory_per_shard": (None if self.memory_per_shard is None
                                 else self.memory_per_shard.to_dict()),
            "roofline": self.roofline,
            "fusion_candidates": self.fusion_candidates,
            "hazards": [d.to_dict() for d in self.hazards],
        }

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), **kw)

    # -- text rendering ----------------------------------------------------
    def render(self, max_rows: Optional[int] = 40) -> str:
        t, m = self.totals, self.memory
        lines = [f"Program #{self.program_serial}: {self.n_ops} ops, "
                 f"fetch={list(self.fetch_names)}"]
        lines.append(
            f"  flops: fwd {_fmt_flops(t['flops_fwd'])}"
            + (f", train {_fmt_flops(t['flops_train'])}"
               if t["flops_train"] is not None else "")
            + f" | min HBM traffic {_fmt_bytes(t['min_traffic_bytes'])}"
            f" | arithmetic intensity {t['arithmetic_intensity']:.1f}")
        un = t["unmodeled"]
        lines.append(
            f"  unmodeled: {un['count']} op(s), {_fmt_bytes(un['bytes'])}"
            + (f" ({', '.join(sorted(set(un['ops'])))})" if un["ops"]
               else ""))
        lines.append(
            f"  memory: peak {_fmt_bytes(m.peak_bytes_donated)} donated / "
            f"{_fmt_bytes(m.peak_bytes_no_donation)} no-donation "
            f"(params {_fmt_bytes(m.param_bytes)}, slots "
            f"{_fmt_bytes(m.slot_bytes)}, grads {_fmt_bytes(m.grad_bytes)}, "
            f"activations {_fmt_bytes(m.retained_activation_bytes if m.training else m.activation_peak_bytes)})")
        ms = self.memory_per_shard
        if ms is not None:
            lines.append(
                f"  per-shard ({self.totals.get('mesh_devices', '?')} "
                f"devices): peak {_fmt_bytes(ms.peak_bytes_donated)} "
                f"donated / {_fmt_bytes(ms.peak_bytes_no_donation)} "
                f"no-donation (params {_fmt_bytes(ms.param_bytes)}, "
                f"slots {_fmt_bytes(ms.slot_bytes)}, grads "
                f"{_fmt_bytes(ms.grad_bytes)})")
        comm = self.totals.get("comm")
        if comm is not None:
            ratio = (comm["wire_bytes_per_step"]
                     / max(comm["fp32_wire_bytes_per_step"], 1))
            lines.append(
                f"  comm (dp={comm['dp']}, "
                f"{'grad_comm ' + str(comm['dtype']) if comm['enabled'] else 'gspmd fp32'}): "
                f"{_fmt_bytes(comm['wire_bytes_per_step'])}/step wire "
                f"({ratio:.2f}x fp32), "
                f"{len(comm['collectives'])} collective group(s), "
                f"overlap {comm.get('overlap', 'none')}"
                f"->{comm.get('overlap_path', 'none')}")
        if self.roofline:
            lines.append("  roofline (predicted):")
            for name, r in self.roofline.items():
                split = ""
                if r.get("predicted_comm_s") is not None:
                    split = (
                        f", comm {r['predicted_comm_s'] * 1e3:.3f} ms "
                        f"(exposed "
                        f"{r['predicted_exposed_comm_s'] * 1e3:.3f} / "
                        f"hidden "
                        f"{r['predicted_hidden_comm_s'] * 1e3:.3f})")
                lines.append(
                    f"    {name:>4}: step {r['predicted_step_s'] * 1e3:.3f} ms, "
                    f"MFU {r['predicted_mfu']:.3f}, {r['bound']}-bound"
                    + split)
        if self.fusion_candidates:
            n_real = sum(1 for c in self.fusion_candidates
                         if c.get("realized"))
            lines.append(
                f"  fusion candidates (by HBM traffic saved; "
                f"{n_real}/{len(self.fusion_candidates)} realized by "
                f"the Pallas tier):")
            for c in self.fusion_candidates:
                loc = f" @ {c['loc']}" if c.get("loc") else ""
                real = (f" [realized: {c['realized']}]"
                        if c.get("realized") else "")
                lines.append(
                    f"    {'+'.join(c['op_names'])} (ops {c['ops']}): "
                    f"saves {_fmt_bytes(c['saved_bytes'])}{loc}{real}")
        if self.hazards:
            lines.append("  hazards:")
            for d in self.hazards:
                lines.append(f"    {d}")
        rows = self.per_op if max_rows is None \
            else self.per_op[:max_rows]
        lines.append("  per-op:")
        lines.append("    idx  op                    flops        bytes"
                     "      rule")
        for c in rows:
            star = " " if c.modeled else "*"
            lines.append(
                f"    {c.op_index:>3}{star} {c.op_name:<20} "
                f"{_fmt_flops(c.flops):>10} {_fmt_bytes(c.total_bytes):>10}"
                f"  {c.rule}" + (f"  @ {c.loc}" if c.loc else ""))
        if max_rows is not None and len(self.per_op) > max_rows:
            lines.append(f"    ... {len(self.per_op) - max_rows} more "
                         f"(render(max_rows=None))")
        return "\n".join(lines)

    def __str__(self):
        return self.render()

    def __repr__(self):
        t = self.totals
        return (f"ProgramReport(#{self.program_serial}, {self.n_ops} ops, "
                f"fwd={_fmt_flops(t['flops_fwd'])}, "
                f"peak={_fmt_bytes(self.memory.peak_bytes_donated)})")


def analyze(program: Program, fetch_list: Optional[Sequence] = None,
            feed_shapes: Optional[Dict[str, Sequence[int]]] = None,
            batch_size: Optional[int] = None,
            chip: Optional[str] = None, top_k: Optional[int] = 5,
            include_hazards: bool = True,
            sharding=None) -> ProgramReport:
    """Quantitative analysis of one recorded Program.

    ``fetch_list`` (Variables or names) roots the liveness analysis;
    with an attached optimizer the loss is an implicit root.
    ``batch_size`` substitutes every dynamic feed dim (declared None/-1)
    and re-derives all avals; ``feed_shapes`` overrides specific feeds
    exactly.  ``chip`` selects one roofline spec from
    :data:`CHIP_SPECS` (default: the whole table).  ``top_k`` bounds
    the ranked fusion candidates (0 = none, None = all).  ``sharding``
    (a :class:`~paddle_tpu.distributed.sharding.ShardingPlan`) adds
    ``memory_per_shard``: each tensor's bytes divided by the mesh-axis
    sizes its PartitionSpec shards over — the report then prices the
    program per-chip, not per-fleet."""
    graph = DefUseGraph(program)

    shapes = dict(feed_shapes or {})
    if batch_size is not None:
        derived = _shapes_from_batch(graph, batch_size)
        derived.update(shapes)
        shapes = derived
    avals = _propagate_avals(graph, shapes) if shapes else {}

    fetch_vars: List[Variable] = []
    fetch_names: List[str] = []
    for f in (fetch_list or []):
        v = graph.resolve_fetch(f)
        if v is not None:
            fetch_vars.append(v)
            fetch_names.append(v.name)
    opt_pack = program._optimizer
    if opt_pack is not None and isinstance(opt_pack[1], Variable) \
            and not any(v is opt_pack[1] for v in fetch_vars):
        fetch_vars.append(opt_pack[1])

    costs = _node_costs(graph, avals)
    memory = estimate_memory(graph, fetch_vars, avals)
    memory_per_shard = None
    if sharding is not None:
        # per-shard accounting: params (and their grads + slots) divide
        # by their spec's axis-size product, activations/feeds by the
        # batch-axis product — but only when the plan actually shards
        # every feed (a non-divisible feed replicates: each chip holds
        # the FULL array, so dividing would underreport per-chip peak)
        seen_p: Dict[int, int] = {}
        all_params = graph.program.parameters()
        spec_of = dict(zip(sharding.param_names, sharding.param_specs))
        for pos, p in enumerate(all_params):
            spec = spec_of.get(p.name)
            if spec is None and pos < len(sharding.param_specs):
                spec = sharding.param_specs[pos]
            seen_p[id(p)] = sharding.divisor(spec) if spec is not None \
                else 1

        from ...distributed.sharding import spec_axes

        def _feed_shape(v):
            a = avals.get(id(v), v.data)
            return tuple(a.shape)

        feeds_sharded = all(
            len(spec_axes(sharding.feed_spec(_feed_shape(v)))) > 0
            for v in graph.feeds.values()) if graph.feeds else True
        memory_per_shard = estimate_memory(
            graph, fetch_vars, avals, param_div=seen_p,
            act_div=sharding.batch_divisor() if feeds_sharded else 1)

    flops_fwd = sum(c.flops for c in costs)
    unmodeled = [c for c in costs if not c.modeled]
    training = opt_pack is not None
    opt_flops = _optimizer_flops(program, memory.trainable_param_bytes)
    flops_train = (3 * flops_fwd + opt_flops) if training else None

    def bytes_of(v):
        return aval_bytes(avals.get(id(v), v.data))

    feed_bytes = memory.feed_bytes
    fetch_bytes = sum(bytes_of(v) for v in fetch_vars)
    unfused_traffic = sum(c.total_bytes for c in costs)
    if training:
        # fwd reads params+feeds, bwd writes grads, update reads grads +
        # params + slots and writes params + slots; retained activations
        # (op outputs only — feeds ride feed_bytes once) are written
        # once and read back once by the backward.  The fetched loss is
        # both an op output and a fetch: epsilon double-count for the
        # scalar losses this models.
        min_traffic = (feed_bytes + fetch_bytes
                       + 3 * memory.trainable_param_bytes
                       + (memory.param_bytes
                          - memory.trainable_param_bytes)
                       + 2 * memory.slot_bytes
                       + 2 * memory.retained_activation_bytes)
        roof_flops = flops_train
    else:
        min_traffic = feed_bytes + fetch_bytes + memory.param_bytes
        roof_flops = flops_fwd
    intensity = roof_flops / max(min_traffic, 1)

    comm = _comm_block(program, sharding, graph=graph) \
        if sharding is not None else None

    if chip is not None:
        if chip not in CHIP_SPECS:
            raise KeyError(
                f"unknown chip {chip!r}; known: {sorted(CHIP_SPECS)}")
        specs = {chip: CHIP_SPECS[chip]}
    else:
        specs = CHIP_SPECS
    roofline = {}
    for name, spec in specs.items():
        t_comp = roof_flops / spec.peak_flops
        t_mem = min_traffic / spec.hbm_bw
        step = max(t_comp, t_mem)
        entry = {
            "peak_flops": spec.peak_flops,
            "hbm_bw": spec.hbm_bw,
            "predicted_step_s": step,
            "predicted_mfu": (t_comp / step) if step > 0 else 0.0,
            "bound": "compute" if t_comp >= t_mem else "memory",
            "fits_hbm": memory.peak_bytes_donated <= spec.hbm_bytes,
        }
        if comm is not None and training and comm.get("dp", 1) > 1:
            # overlap-aware step time: only the EXPOSED share of the
            # gradient collectives adds to the step — comm that hides
            # behind backward costs nothing.  Backward's window is its
            # FLOP share of the compute-only step (2x the forward of
            # the 3x-fwd training total).
            backward_s = step * (2.0 * flops_fwd / max(roof_flops, 1))
            comm_s, exposed_s = _comm_seconds(comm, backward_s,
                                              spec.ici_bw)
            entry["predicted_comm_s"] = comm_s
            entry["predicted_exposed_comm_s"] = exposed_s
            entry["predicted_hidden_comm_s"] = comm_s - exposed_s
            entry["predicted_step_s"] = step + exposed_s
            if entry["predicted_step_s"] > 0:
                entry["predicted_mfu"] = (
                    t_comp / entry["predicted_step_s"])
        roofline[name] = entry

    fetched_ids = {id(v) for v in fetch_vars}
    cands = _fusion_candidates(graph, costs, avals, fetched_ids, top_k)
    if cands:
        # mark what the executor's epilogue-fusion pass realizes for
        # each candidate under the current flags (same matcher, same
        # gates — prediction and execution cannot disagree); the
        # report then separates realized from still-unrealized savings.
        # Under a sharding plan the executor skips the pass entirely
        # (pallas_call below an explicit GSPMD lowering is unsupported)
        # — the report must say so too, hence plan_active.
        from .fusion import annotate_candidates
        annotate_candidates(program, cands, graph, avals, fetched_ids,
                            plan_active=sharding is not None)

    hazards: List[Diagnostic] = []
    if include_hazards:
        from .hazards import hazard_passes
        for p in hazard_passes():
            hazards.extend(p.run(graph, fetch_list))

    rep = ProgramReport()
    rep.program_serial = program._serial
    rep.n_ops = len(graph.nodes)
    rep.fetch_names = fetch_names
    rep.batch_hint = batch_size
    rep.per_op = costs
    rep.memory_per_shard = memory_per_shard
    rep.totals = {
        **({"mesh_devices": sharding.n_devices}
           if sharding is not None else {}),
        **({"comm": comm} if comm is not None else {}),
        "flops_fwd": flops_fwd,
        "flops_train": flops_train,
        "optimizer_flops": opt_flops if training else 0,
        "feed_bytes": feed_bytes,
        "fetch_bytes": fetch_bytes,
        "param_bytes": memory.param_bytes,
        "unfused_traffic_bytes": unfused_traffic,
        "min_traffic_bytes": min_traffic,
        "arithmetic_intensity": intensity,
        "unmodeled": {
            "count": len(unmodeled),
            "ops": [c.op_name for c in unmodeled],
            "bytes": sum(c.total_bytes for c in unmodeled),
            "flops_unknown": bool(unmodeled),
        },
    }
    rep.memory = memory
    rep.roofline = roofline
    rep.fusion_candidates = cands
    rep.hazards = hazards
    return rep


def resolve_perf_chip() -> str:
    """The ``CHIP_SPECS`` key runtime predictions are priced against:
    ``FLAGS_perf_chip`` when set to a known spec, else auto-detected
    from the jax backend (``cpu`` on CPU, ``v5e`` on TPU).  The single
    policy both ``compile_summary`` and the perf observatory's drift
    fallback use — one place to extend when a backend is added."""
    from ...core.flags import get_flag
    chip = get_flag("perf_chip")
    if chip:
        if chip in CHIP_SPECS:
            return chip
        import warnings
        warnings.warn(
            f"FLAGS_perf_chip={chip!r} is not a known chip spec "
            f"(choose from {sorted(CHIP_SPECS)}); falling back to "
            f"backend auto-detection — drift predictions will be "
            f"priced against the wrong roofline otherwise silently",
            RuntimeWarning)
    try:
        import jax
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001
        return "cpu"
    if backend == "cpu":
        return "cpu"
    if backend == "tpu":
        return "v5e"
    import warnings
    warnings.warn(
        f"no roofline chip spec for jax backend {backend!r}; pricing "
        f"predictions against 'cpu' — set FLAGS_perf_chip to a "
        f"CHIP_SPECS key to choose explicitly", RuntimeWarning)
    return "cpu"


def compile_summary(program: Program, donate: bool = True,
                    sharding=None) -> Optional[dict]:
    """The light, always-on slice the Executor records per compile:
    predicted FLOPs per step + peak bytes from the recorded avals (no
    re-derivation, no hazard passes), plus the roofline's predicted
    step time for the chip this process is actually running on
    (``FLAGS_perf_chip``, auto-detected backend by default) — the
    number the perf observatory's drift tracker compares measured
    steps against.  With a ``sharding`` plan the summary also carries
    ``peak_bytes_per_shard`` — what one chip actually holds.  Returns
    None instead of raising — a cost-model gap must never break a
    compile."""
    try:
        # inside the try: resolve_perf_chip warns on a misconfigured
        # flag/backend, and under warnings-as-errors (pytest/CI -W
        # error) that warning RAISES — it must not break a compile
        chip = resolve_perf_chip()
        rep = analyze(program, include_hazards=False, chip=chip,
                      top_k=0, sharding=sharding)
    except Exception:  # noqa: BLE001 - prediction is best-effort
        return None
    t = rep.totals
    peak = (rep.memory.peak_bytes_donated if donate
            else rep.memory.peak_bytes_no_donation)
    out = {
        "flops": (t["flops_train"] if t["flops_train"] is not None
                  else t["flops_fwd"]),
        "flops_fwd": t["flops_fwd"],
        "peak_bytes": peak,
        "min_traffic_bytes": t["min_traffic_bytes"],
        "chip": chip,
        "predicted_step_s": rep.roofline[chip]["predicted_step_s"],
        "unmodeled_ops": t["unmodeled"]["count"],
        "unmodeled_bytes": t["unmodeled"]["bytes"],
    }
    if rep.memory_per_shard is not None:
        ms = rep.memory_per_shard
        out["peak_bytes_per_shard"] = (
            ms.peak_bytes_donated if donate
            else ms.peak_bytes_no_donation)
        out["mesh_devices"] = t.get("mesh_devices")
    comm = t.get("comm")
    if comm is not None:
        # predicted gradient wire bytes per step ride the compile
        # record next to predicted_step_s — the number the runtime's
        # comm.wire_bytes stat is compared against
        out["predicted_wire_bytes"] = comm["wire_bytes_per_step"]
        out["comm_enabled"] = comm["enabled"]
        # per-mesh-axis prediction (hybrid meshes): what the runtime's
        # comm.axis.<name>.wire_bytes stats must measure, axis by axis
        if comm.get("axis_wire_bytes"):
            out["predicted_axis_wire_bytes"] = dict(
                comm["axis_wire_bytes"])
        if comm.get("gather_wire_bytes_per_step"):
            out["predicted_gather_wire_bytes"] = \
                comm["gather_wire_bytes_per_step"]
        # the overlap prediction (total/exposed/hidden comm seconds on
        # the running chip + the resolved path) — what the perf
        # observatory's exposed-vs-hidden split reads per step
        out["comm_overlap"] = comm.get("overlap_path", "none")
        r = rep.roofline[chip]
        for k in ("predicted_comm_s", "predicted_exposed_comm_s",
                  "predicted_hidden_comm_s"):
            if k in r:
                out[k] = r[k]
    return out
