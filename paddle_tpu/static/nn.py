"""paddle.static.nn — static-graph layer helpers.

Reference: python/paddle/static/nn/common.py (fc:28), control_flow ops
re-exported from the shared implementation (ops/control_flow.py works in
both regimes — eager predicates run one branch, symbolic Variables record
lax.cond/while into the Program via the dispatch point).
"""
from __future__ import annotations

import numpy as np

from ..core.dtype import get_default_dtype
from ..core.tensor import Parameter
from ..nn import functional as F
from ..nn import initializer as I
from ..ops.control_flow import case, cond, switch_case, while_loop  # noqa

__all__ = ["fc", "batch_norm", "cond", "case", "switch_case", "while_loop"]


def _make_param(shape, is_bias=False, initializer=None):
    init = initializer or (I.Constant(0.0) if is_bias else I.XavierNormal())
    return Parameter(init(tuple(shape), get_default_dtype()))


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """reference: static/nn/common.py fc:28 — creates its own weights."""
    in_features = int(np.prod(x.shape[num_flatten_dims:]))
    w = _make_param([in_features, size])
    b = _make_param([size], is_bias=True)
    # trailing dims are concrete (in_features); at most the one dynamic
    # leading dim may stay -1 in the reshape
    flat = (x.reshape(list(x.shape[:num_flatten_dims]) + [in_features])
            if len(x.shape) > num_flatten_dims + 1 else x)
    out = F.linear(flat, w, b)
    if activation is not None:
        out = getattr(F, activation)(out)
    return out


def batch_norm(input, is_test=False, momentum=0.9, epsilon=1e-5,
               data_layout="NCHW", **kwargs):
    """Static BN shim: batch-stat normalization with fresh affine params
    (running stats are a dygraph-layer feature; use nn.BatchNorm2D in
    dygraph for the full behavior)."""
    C = input.shape[1 if data_layout.startswith("NC") else -1]
    w = _make_param([C], initializer=I.Constant(1.0))
    b = _make_param([C], is_bias=True)
    return F.batch_norm(input, None, None, w, b, training=not is_test,
                        momentum=momentum, epsilon=epsilon,
                        data_format=data_layout)
