#!/usr/bin/env python
"""Shardcheck smoke gate: static SPMD safety analysis, executably.

The correctness promises of ``static/analysis/shardcheck.py`` (ISSUE
16), as a CI gate:

- **clean plans verify clean**: the shard_smoke GPT-tiny/BERT-tiny
  configs produce zero shardcheck errors/warnings on a 1-device mesh,
  an 8-device dp mesh (against the Executor's OWN ShardingPlan), and an
  ABSTRACT {dp: 4, mp: 2} mesh — the last with zero devices involved,
  which is the whole point;
- **seeded-defect matrix**: one injected defect per pass family
  (non-divisible rule spec, grad_comm on a pipeline mesh,
  device-varying fetch, corrupted wire formula) produces exactly the
  expected diagnostic — and the choreography error carries the SAME
  cause string ``grad_comm.incompatibility`` builds for the Executor's
  runtime raise.  ISSUE 17's narrowed rejection is covered from both
  sides: grad_comm on the abstract {dp:4, mp:2} mesh (rejected before)
  now verifies clean, while a pp axis and a multi-axis param spec
  still fail with their shared cause strings;
- **wire-byte audit closes the triangle**: on all four comm_smoke
  overlap configs (fp32/auto, int8/auto, int8/none, int8/ring) the
  measured ``comm.wire_bytes`` monitor delta == the cost model's
  prediction == shardcheck's independent first-principles
  re-derivation;
- **lint CLI round trip**: ``lint_program.py --mesh-shape dp=2,mp=3
  --sharding-rules ... --format json`` emits the new diagnostics as
  JSON records that reconstruct into ``Diagnostic`` objects verbatim.

Usage::

    python tools/shardcheck_smoke.py [--steps 2] [--verbose]

CI treats a non-zero exit as a shardcheck regression.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# env BEFORE jax initialises: 8 virtual CPU devices
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

from tools.shard_smoke import (_feeds, build_bert_tiny,  # noqa: E402
                               build_gpt_tiny)


def _shard_diags(diags):
    return [d for d in diags if d.pass_name.startswith("shard-")]


def _tiny_program(reduction="mean"):
    """A minimal trainable Program for the defect matrix."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn, optimizer

    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [8, 16], "float32")
        lab = paddle.static.data("lab", [8], "int64")
        loss = F.cross_entropy(nn.Linear(16, 4)(x), lab,
                               reduction=reduction)
        optimizer.AdamW(learning_rate=1e-3).minimize(loss)
    return main, loss


def check_clean(problems, verbose):
    """Part 1: GPT/BERT-tiny verify clean on {1}, {dp:8} (Executor's
    own plan) and the abstract {dp:4, mp:2} mesh."""
    import paddle_tpu as paddle
    from paddle_tpu import distributed as dist, optimizer
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.static import analysis

    for name, build in (("gpt", build_gpt_tiny),
                        ("bert", build_bert_tiny)):
        # concrete meshes: the plan the Executor itself compiles with
        for shape in ({"dp": 1}, {"dp": 8}):
            init_mesh(shape)
            paddle.seed(7)
            main, loss, _ = build()
            with paddle.static.program_guard(main):
                f = dist.fleet
                f.init(is_collective=True,
                       strategy=dist.DistributedStrategy())
                opt = f.distributed_optimizer(
                    optimizer.AdamW(learning_rate=1e-3))
                opt.minimize(loss)
            init_mesh(shape)
            exe = paddle.static.Executor()
            exe.run(main, feed=_feeds(name), fetch_list=[loss])
            plan = exe._plan_for(main, main.parameters())
            if plan is None:
                problems.append(f"{name} mesh{shape}: Executor built "
                                f"no ShardingPlan to check")
            else:
                bad = [d for d in _shard_diags(
                    analysis.check(main, fetch_list=[loss],
                                   sharding=plan))
                    if d.severity != "info"]
                if bad:
                    problems.append(
                        f"{name} mesh{shape}: clean config produced "
                        f"{len(bad)} shardcheck finding(s): {bad[0]}")
                elif verbose:
                    print(f"  {name} mesh{shape}: clean")
            exe.close()
            paddle.static.reset_default_programs()
        # abstract mesh: no devices of this topology exist
        paddle.seed(7)
        main, loss, _ = build()
        with paddle.static.program_guard(main):
            from paddle_tpu import optimizer as _opt
            _opt.AdamW(learning_rate=1e-3).minimize(loss)
        bad = [d for d in _shard_diags(
            analysis.check(main, fetch_list=[loss],
                           mesh_shape={"dp": 4, "mp": 2}))
            if d.severity != "info"]
        if bad:
            problems.append(f"{name} abstract dp=4,mp=2: "
                            f"{len(bad)} finding(s): {bad[0]}")
        elif verbose:
            print(f"  {name} abstract dp=4,mp=2: clean (0 devices)")
        paddle.static.reset_default_programs()


def check_defect_matrix(problems, verbose):
    """Part 2: one seeded defect per pass family -> exactly the
    expected diagnostic."""
    import paddle_tpu as paddle
    from paddle_tpu import distributed as dist
    from paddle_tpu.distributed import grad_comm as _gc
    from paddle_tpu.static import analysis

    def expect(label, diags, pass_name, severity, needle,
               exact=None):
        hits = [d for d in diags if d.pass_name == pass_name
                and d.severity == severity]
        if exact is not None:
            hits = [d for d in hits if d.message == exact]
        else:
            hits = [d for d in hits if needle in d.message]
        if len(hits) != 1:
            problems.append(
                f"defect[{label}]: expected exactly one {severity} "
                f"from {pass_name} matching {needle!r}, got "
                f"{len(hits)} (all: "
                f"{[str(d) for d in _shard_diags(diags)]})")
        elif verbose:
            print(f"  defect[{label}]: caught -> {hits[0]}")

    # (a) shard-plan: rule shards a dim mp=3 cannot divide -> one WARN
    # naming the rule and the axis
    main, loss = _tiny_program()
    diags = analysis.check(
        main, fetch_list=[loss], mesh_shape={"dp": 2, "mp": 3},
        sharding_rules=[(r"w_0", (None, "mp")), (r".*", ())])
    expect("plan/non-divisible", diags, "shard-plan", "warning",
           "not divisible by mesh axis 'mp' (size 3)")
    if not any("rule r'w_0'" in d.message for d in diags
               if d.pass_name == "shard-plan"):
        problems.append("defect[plan/non-divisible]: the WARN does not "
                        "name the rule that matched")
    paddle.static.reset_default_programs()

    # (b) shard-choreography: grad_comm on a pp mesh (cross-stage
    # collectives) -> the EXACT string grad_comm.incompatibility
    # builds in its hybrid form (the Executor's runtime raise and the
    # static diagnostic share one builder).  ISSUE 17 narrowed this
    # rejection: {dp, mp} meshes and FSDP/mp shards are now legal, so
    # the genuinely-bad config is a pipeline axis.
    main, loss = _tiny_program()
    strat = dist.DistributedStrategy()
    strat.grad_comm = {"dtype": "int8", "error_feedback": True,
                       "block_size": 256}
    cfg = _gc.resolve(strat)
    want = _gc.incompatibility(cfg, {"dp": 4, "pp": 2}, hybrid=True)
    diags = analysis.check(main, fetch_list=[loss],
                           mesh_shape={"dp": 4, "pp": 2},
                           strategy=strat)
    expect("choreography/pp-mesh", diags, "shard-choreography",
           "error", "", exact=want)
    if want is None or "pp=2" not in (want or ""):
        problems.append("defect[choreography/pp-mesh]: the shared "
                        "formatter does not name the axis+degree "
                        "(expected 'pp=2' in the cause)")
    paddle.static.reset_default_programs()

    # (b-legal) the narrowed rejection's flip side: the SAME grad_comm
    # strategy on the abstract {dp:4, mp:2} mesh — rejected before
    # ISSUE 17 — now verifies with zero errors
    main, loss = _tiny_program()
    diags = analysis.check(main, fetch_list=[loss],
                           mesh_shape={"dp": 4, "mp": 2},
                           strategy=strat)
    newly_bad = [d for d in _shard_diags(diags)
                 if d.severity == "error"]
    if newly_bad:
        problems.append(f"defect[choreography/hybrid-now-legal]: "
                        f"grad_comm on the abstract {{dp:4, mp:2}} "
                        f"mesh must verify clean after ISSUE 17, got: "
                        f"{newly_bad[0]}")
    elif verbose:
        print("  defect[choreography/hybrid-now-legal]: grad_comm + "
              "{dp:4, mp:2} verifies clean (restriction lifted)")
    # an unsupported param spec still rejects, with the spec named
    bad_spec = _gc.incompatibility(
        cfg, {"dp": 4, "mp": 2},
        [("w_0", ("dp", "mp"))], hybrid=True)
    if not bad_spec or "fit neither form" not in bad_spec:
        problems.append("defect[choreography/bad-spec]: a multi-axis "
                        "param spec must still reject with the "
                        "'fit neither form' cause")
    paddle.static.reset_default_programs()

    # (b2) shard-choreography: SUM-reduced loss under the dp-mean
    # stage, classified statically with the shared cause builder
    main, loss = _tiny_program(reduction="sum")
    diags = analysis.check(main, fetch_list=[loss],
                           mesh_shape={"dp": 4}, strategy=strat)
    expect("choreography/sum-loss", diags, "shard-choreography",
           "error", "", exact=_gc.sum_fetch_message("loss", loss.name))
    paddle.static.reset_default_programs()

    # (c) shard-taint: a device-varying value fetched with no reduction
    main, loss = _tiny_program()
    with paddle.static.program_guard(main):
        y = main.record(lambda a: a, [loss], {}, "axis_index")
    diags = analysis.check(main, fetch_list=[y],
                           mesh_shape={"dp": 4}, strategy=strat)
    expect("taint/varying-fetch", diags, "shard-taint", "error",
           "device-varying")
    paddle.static.reset_default_programs()

    # (d) shard-wire: corrupt the schedule's wire formula -> the
    # INDEPENDENT re-derivation refuses to conserve (cost._comm_block
    # shares the corrupted formula, so only the audit leg can catch it)
    main, loss = _tiny_program()
    real = _gc._wire_bytes
    try:
        _gc._wire_bytes = lambda *a, **k: real(*a, **k) + 7
        diags = analysis.check(main, fetch_list=[loss],
                               mesh_shape={"dp": 4}, strategy=strat)
    finally:
        _gc._wire_bytes = real
    expect("wire/conservation", diags, "shard-wire", "error",
           "wire-byte conservation violated: bucket")
    # the ISSUE-17 per-axis ledger is an independent gate over the
    # same corruption: the per-axis schedule must disagree too
    expect("wire/per-axis-conservation", diags, "shard-wire", "error",
           "wire-byte conservation violated: per-axis schedule")
    paddle.static.reset_default_programs()


def check_wire_triangle(problems, steps, verbose):
    """Part 3: measured == predicted == audited wire bytes on the four
    comm_smoke overlap configs."""
    import paddle_tpu as paddle
    from paddle_tpu import distributed as dist, optimizer
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.static.analysis.shardcheck import (_derive_gplan,
                                                       audit_wire_bytes)
    from paddle_tpu.utils import monitor

    for dtype, overlap in (("fp32", "auto"), ("int8", "auto"),
                           ("int8", "none"), ("int8", "ring")):
        init_mesh({"dp": 8})
        paddle.seed(7)
        main, loss, _ = build_gpt_tiny()
        with paddle.static.program_guard(main):
            f = dist.fleet
            strategy = dist.DistributedStrategy()
            strategy.fuse_grad_size_in_MB = 0.05
            strategy.grad_comm = {"dtype": dtype,
                                  "error_feedback": True,
                                  "block_size": 256,
                                  "scatter_threshold_KB": 4.0,
                                  "overlap": overlap}
            f.init(is_collective=True, strategy=strategy)
            opt = f.distributed_optimizer(
                optimizer.AdamW(learning_rate=1e-3))
            opt.minimize(loss)
        init_mesh({"dp": 8})
        exe = paddle.static.Executor()
        feed = _feeds("gpt")
        w0 = monitor.get_stat("comm.wire_bytes") or 0
        for _ in range(steps):
            exe.run(main, feed=feed, fetch_list=[loss])
        measured = ((monitor.get_stat("comm.wire_bytes") or 0)
                    - w0) / steps
        plan = exe._plan_for(main, main.parameters())
        rep = main.analyze(fetch_list=[loss], sharding=plan)
        predicted = rep.totals["comm"]["wire_bytes_per_step"]
        audit = audit_wire_bytes(_derive_gplan(main, plan))
        audited = audit["wire_bytes_per_step"]
        if not (measured == predicted == audited):
            problems.append(
                f"wire triangle {dtype}/{overlap}: measured "
                f"{measured} != predicted {predicted} != audited "
                f"{audited} B/step — the three legs must agree "
                f"exactly")
        elif verbose:
            print(f"  wire {dtype}/{overlap}: measured == predicted "
                  f"== audited == {audited:.0f} B/step "
                  f"({len(audit['buckets'])} buckets)")
        exe.close()
        paddle.static.reset_default_programs()


def check_lint_roundtrip(problems, verbose):
    """Part 4: lint_program.py --mesh-shape emits shardcheck
    diagnostics as JSON records that reconstruct verbatim."""
    from paddle_tpu.static.analysis import Diagnostic

    src = (
        "import paddle_tpu as paddle\n"
        "from paddle_tpu import nn, optimizer, static\n"
        "import paddle_tpu.nn.functional as F\n"
        "main = static.Program()\n"
        "with static.program_guard(main):\n"
        "    x = static.data('x', [8, 16], 'float32')\n"
        "    lab = static.data('lab', [8], 'int64')\n"
        "    loss = F.cross_entropy(nn.Linear(16, 4)(x), lab)\n"
        "    optimizer.AdamW(learning_rate=1e-3).minimize(loss)\n"
    )
    with tempfile.TemporaryDirectory(prefix="shardcheck_lint_") as tmp:
        path = os.path.join(tmp, "lint_target.py")
        with open(path, "w") as fh:
            fh.write(src)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "lint_program.py"),
             path, "--format", "json", "--mesh-shape", "dp=2,mp=3",
             "--sharding-rules",
             '[["w_0", [null, "mp"]], [".*", []]]'],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
    if proc.returncode != 0:
        problems.append(f"lint round trip: exit {proc.returncode} "
                        f"(a WARN-only lint must exit 0): "
                        f"{proc.stderr.strip()[:300]}")
        return
    try:
        report = json.loads(proc.stdout)
    except ValueError:
        problems.append(f"lint round trip: --format json printed "
                        f"non-JSON: {proc.stdout[:200]!r}")
        return
    recs = [d for prog in report["programs"]
            for d in prog["diagnostics"]
            if d["pass_name"].startswith("shard-")]
    if not recs:
        problems.append("lint round trip: no shard-* diagnostics in "
                        "the JSON report")
        return
    rebuilt = [Diagnostic(**d) for d in recs]
    for d, r in zip(recs, rebuilt):
        if r.to_dict() != d:
            problems.append(f"lint round trip: Diagnostic(**record) "
                            f"!= record for {d}")
            return
    if not any(r.pass_name == "shard-plan"
               and "not divisible by mesh axis 'mp'" in r.message
               for r in rebuilt):
        problems.append("lint round trip: the seeded non-divisible "
                        "WARN did not survive the JSON hop")
    elif verbose:
        print(f"  lint round trip: {len(recs)} shard-* record(s) "
              f"reconstruct verbatim")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Shardcheck smoke gate: static SPMD safety "
                    "analysis on clean + seeded-defect configs.")
    ap.add_argument("--steps", type=int, default=2,
                    help="training steps per wire-triangle config")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle

    problems: list = []
    paddle.enable_static()
    try:
        check_clean(problems, args.verbose)
        check_defect_matrix(problems, args.verbose)
        check_wire_triangle(problems, args.steps, args.verbose)
    finally:
        paddle.disable_static()
    check_lint_roundtrip(problems, args.verbose)

    if problems:
        for p in problems:
            print(f"FAIL: {p}", file=sys.stderr)
        return 1
    print("shardcheck_smoke OK: GPT/BERT-tiny verify clean on mesh "
          "{1}, {dp:8} and abstract {dp:4,mp:2} (zero devices); every "
          "seeded defect produced exactly its expected diagnostic "
          "with the Executor's own cause string (pp mesh + bad spec "
          "still reject, hybrid {dp,mp} now verifies clean); measured "
          "== predicted == audited wire bytes on all four overlap "
          "configs; lint --format json round-trips the diagnostics")
    return 0


if __name__ == "__main__":
    sys.exit(main())
