#!/usr/bin/env python
"""Gradient-collective smoke gate: quantized grad_comm on multichip GPT.

The collective-efficiency AND compute-collective-overlap promises of
``paddle_tpu.distributed.grad_comm`` (ISSUE 10 + ISSUE 14 / ROADMAP
item 2), executably: the GPT-tiny causal LM from ``tools/shard_smoke``,
trained through ``fleet.distributed_optimizer`` + the static
``Executor`` on 8 virtual devices, eight configurations — fp32 wire
(the measured baseline), block-scaled int8 + error feedback with
``overlap="auto"``, the same int8 config with ``overlap="none"``
(comm barriered after backward), with ``overlap="ring"`` (the
ppermute-chunked lowering forced, so the explicit fallback path is
exercised end-to-end even on backends where auto picks the fused
form), on the hybrid ``{dp: 4, mp: 2}`` mesh with every 2-D weight
tensor-parallel (auto + none — forward mp gathers composed with the
dp reduction, ISSUE 17), and with ZeRO-3 (auto + none — params
dp-sharded at rest, grads reduce-scattered back to shards):

- **wire bytes**: int8 ``comm.wire_bytes``/step < 0.35x the fp32 run's
  (quantized payload + scales, both measured from monitor stats);
- **prediction closes**: measured wire bytes == the static cost model's
  ``predicted_wire_bytes`` exactly, in EVERY overlap mode — the plan is
  the single source of both numbers and the overlap lowering moves the
  same bytes; on hybrid/FSDP configs the same closure holds PER MESH
  AXIS (``comm.axis.<name>.wire_bytes`` == predicted
  ``axis_wire_bytes``) and for the forward param-gather schedule;
- **loss parity**: int8-with-error-feedback trajectories (ALL overlap
  modes — the ring's ascending accumulation keeps numerics) within
  2e-3 of the fp32 baseline after every step;
- **overlap**: median step time with ``overlap="auto"`` is at most
  1.15x max(compute, comm) estimated from the ``overlap="none"`` run's
  anatomy (compute = its measured step minus its predicted comm
  seconds) — at `none` the step pays compute + comm, at `auto` the
  wire hides behind backward;
- **exposed-vs-hidden split sanity**: the perf observatory reports
  hidden == 0 for the ``overlap="none"`` run (structural: the lowering
  barriers the stage) and a well-formed split for ``auto``;
- **0 steady-state recompiles** (one XLA compile per knob config),
  ``explain_compiles()`` reports no unexplained executor compiles, and
  every grad_comm compile record carries the auditable bucket schedule
  (size, algorithm, issue point, resolved overlap path);
- **bucketing + algorithm selection**: the small fuse budget forces
  multiple buckets, and every bucket records a psum/scatter choice.

Usage::

    python tools/comm_smoke.py [--steps 8] [--json] [--verbose]

``--json`` prints one JSON line (consumed by ``bench.py --suite
multichip``, which embeds the exposed-vs-hidden split next to the
wire-byte ratio).  CI treats a non-zero exit as a regression.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# env BEFORE jax initialises: 8 virtual CPU devices
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

from tools.shard_smoke import _feeds, build_gpt_tiny  # noqa: E402


_AXIS_STATS = ("comm.axis.dp.wire_bytes", "comm.axis.mp.wire_bytes",
               "comm.gather.wire_bytes", "comm.gather.collectives")


def _train(dtype, steps, overlap="auto", verbose=False,
           mesh_shape=None, zero3=False, mp_shard=False):
    """GPT-tiny on ``mesh_shape`` (default {dp: 8}) with the given
    grad_comm wire dtype and overlap mode.  ``zero3`` shards params
    over dp at rest (FSDP reduce-scatter grad route); ``mp_shard``
    shards every 2-D weight on its output dim over 'mp' (hybrid
    tensor-parallel gathers).  Returns a result dict (losses, wire
    stats incl. per-axis, prediction, per-step timing, perf comm
    split)."""
    import re

    import paddle_tpu as paddle
    from paddle_tpu import distributed as dist, optimizer
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.observability import (disable_perf, enable_perf,
                                          perf_report)
    from paddle_tpu.utils import monitor

    mesh_shape = dict(mesh_shape or {"dp": 8})
    init_mesh(mesh_shape)
    paddle.seed(7)
    main, loss, _ = build_gpt_tiny()
    with paddle.static.program_guard(main):
        f = dist.fleet
        strategy = dist.DistributedStrategy()
        # small fuse budget -> several buckets (overlap-shaped), low
        # threshold -> the big buckets take the bandwidth route
        strategy.fuse_grad_size_in_MB = 0.05
        strategy.grad_comm = {"dtype": dtype, "error_feedback": True,
                              "block_size": 256,
                              "scatter_threshold_KB": 4.0,
                              "overlap": overlap}
        if zero3:
            strategy.sharding = True
            strategy.sharding_configs = {"stage": 3,
                                         "min_shard_numel": 1}
        f.init(is_collective=True, strategy=strategy)
        opt = f.distributed_optimizer(optimizer.AdamW(learning_rate=1e-3))
        opt.minimize(loss)
    init_mesh(mesh_shape)  # fleet.init infers over ALL devices; pin it
    if mp_shard:
        # every 2-D weight tensor-parallel on its output dim; 1-D
        # params (biases, norms) replicate via the fallback rule
        main._sharding_rules = [
            (re.escape(p.name) + "$", (None, "mp"))
            for p in main.parameters() if len(p.data.shape) == 2
        ] + [(r".*", ())]
    exe = paddle.static.Executor()
    feed = _feeds("gpt")
    # fence every step: exposed-vs-hidden needs the device wall, and
    # this harness reads the fetch per step anyway
    enable_perf(sample_every=1, memory=False)
    w0 = monitor.get_stat("comm.wire_bytes") or 0
    c0 = monitor.get_stat("comm.collectives") or 0
    ax0 = {k: monitor.get_stat(k) or 0 for k in _AXIS_STATS}
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])]
    step_s = []
    for _ in range(steps - 1):
        t0 = time.perf_counter()
        losses.append(float(exe.run(main, feed=feed,
                                    fetch_list=[loss])[0]))
        step_s.append(time.perf_counter() - t0)
    wire = ((monitor.get_stat("comm.wire_bytes") or 0) - w0) / steps
    colls = ((monitor.get_stat("comm.collectives") or 0) - c0) / steps
    ax = {k: ((monitor.get_stat(k) or 0) - ax0[k]) / steps
          for k in _AXIS_STATS}
    measured_axis = {k.split(".")[2]: v for k, v in ax.items()
                     if k.startswith("comm.axis.") and v}
    plan = exe._plan_for(main, main.parameters())
    rep = main.analyze(fetch_list=[loss], sharding=plan)
    comm = rep.totals["comm"]
    from paddle_tpu.static.analysis.cost import compile_summary
    cs = compile_summary(main, sharding=plan)
    # the executor identity's comm split as the observatory learned it
    perf = perf_report()
    split = next((r.get("comm") for r in perf.get("identities", [])
                  if r["component"] == "executor" and r.get("comm")),
                 None)
    disable_perf()
    state = exe._states[main._serial]
    out = {
        "losses": losses,
        "compiles": exe.compile_count,
        "wire_bytes_per_step": wire,
        "collectives_per_step": colls,
        "predicted_wire_bytes": comm["wire_bytes_per_step"],
        "predicted_fp32_wire_bytes": comm["fp32_wire_bytes_per_step"],
        "predicted_comm_s": cs.get("predicted_comm_s", 0.0),
        "axis_wire_bytes_per_step": measured_axis,
        "predicted_axis_wire_bytes": dict(
            comm.get("axis_wire_bytes") or {}),
        "gather_wire_bytes_per_step": ax["comm.gather.wire_bytes"],
        "predicted_gather_wire_bytes": comm.get(
            "gather_wire_bytes_per_step", 0),
        "gather_collectives_per_step": ax["comm.gather.collectives"],
        "peak_bytes_per_shard": cs.get("peak_bytes_per_shard"),
        "mesh_shape": mesh_shape,
        "overlap": overlap,
        "overlap_path": comm.get("overlap_path"),
        "buckets": len(comm["collectives"]),
        "algorithms": sorted({c["algorithm"]
                              for c in comm["collectives"]}),
        "residual_buckets": len(state.aux.get("grad_comm", [])),
        # anomaly sentry (FLAGS_anomaly_sentry, compiled into the
        # step): clean training must never skip — a false positive
        # here would silently stall convergence
        "sentry_skipped_steps": (exe.sentry_stats(main)
                                 or {}).get("skipped_steps"),
        "step_ms_median": statistics.median(step_s) * 1e3,
        # the overlap gate compares MINIMA: on oversubscribed CI hosts
        # the 8 virtual devices' thread scheduling adds multi-ms noise
        # to individual steps (measured +-35% between identical runs);
        # additive noise never makes a step faster, so the min is the
        # honest estimate of what the schedule costs
        "step_ms_min": min(step_s) * 1e3,
        "steps_per_sec": (steps - 1) / max(sum(step_s), 1e-9),
        "perf_comm": split,
    }
    if verbose:
        print(f"  {dtype}/{overlap}->{out['overlap_path']}: losses "
              f"{['%.4f' % v for v in losses]} wire {wire:.0f}B/step "
              f"({out['buckets']} buckets, {out['algorithms']}), "
              f"step {out['step_ms_median']:.2f} ms")
    exe.close()
    paddle.static.reset_default_programs()
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Gradient-collective smoke gate: quantized grad_comm"
                    " + compute-collective overlap on multichip GPT.")
    ap.add_argument("--steps", type=int, default=16,
                    help="steps per config (>= 2: the first run compiles"
                         " and is excluded from the step timings)")
    ap.add_argument("--json", action="store_true",
                    help="print one JSON result line on stdout")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    if args.steps < 2:
        ap.error("--steps must be >= 2 (step 1 compiles; the timing "
                 "gates need at least one steady-state step)")

    import paddle_tpu as paddle
    from paddle_tpu.observability import explain_compiles

    problems = []
    paddle.enable_static()
    # the multichip suite runs as production would: with the anomaly
    # sentry compiled into every step — the overlap/wire gates then
    # also prove the sentry costs no recompiles and never false-fires
    old_sentry = paddle.get_flags("anomaly_sentry")
    paddle.set_flags({"anomaly_sentry": True})
    try:
        fp32 = _train("fp32", args.steps, verbose=args.verbose)
        int8 = _train("int8", args.steps, verbose=args.verbose)
        none = _train("int8", args.steps, overlap="none",
                      verbose=args.verbose)
        ring = _train("int8", args.steps, overlap="ring",
                      verbose=args.verbose)
        # hybrid {dp, mp}: every 2-D weight mp-sharded, forward param
        # gathers + bucketed dp reduction composed in one shard_map
        hyb = _train("int8", args.steps, verbose=args.verbose,
                     mesh_shape={"dp": 4, "mp": 2}, mp_shard=True)
        hyb_none = _train("int8", args.steps, overlap="none",
                          verbose=args.verbose,
                          mesh_shape={"dp": 4, "mp": 2}, mp_shard=True)
        # ZeRO-3: params sharded at rest, grads reduce-scatter back
        z3 = _train("int8", args.steps, zero3=True,
                    verbose=args.verbose)
        z3_none = _train("int8", args.steps, overlap="none", zero3=True,
                         verbose=args.verbose)
    finally:
        paddle.set_flags(old_sentry)
        paddle.disable_static()

    runs = (("fp32", fp32), ("int8", int8), ("int8/none", none),
            ("int8/ring", ring), ("hybrid", hyb),
            ("hybrid/none", hyb_none), ("zero3", z3),
            ("zero3/none", z3_none))
    for name, r in runs:
        if r["compiles"] != 1:
            problems.append(f"{name}: {r['compiles']} compiles for one "
                            f"feed signature — recompiles after warmup")
        if r["wire_bytes_per_step"] != r["predicted_wire_bytes"]:
            problems.append(
                f"{name}: measured wire bytes/step "
                f"{r['wire_bytes_per_step']} != predicted "
                f"{r['predicted_wire_bytes']} — the cost model and the "
                f"runtime disagree")
        if r["axis_wire_bytes_per_step"] != r["predicted_axis_wire_bytes"]:
            problems.append(
                f"{name}: per-axis wire bytes/step "
                f"{r['axis_wire_bytes_per_step']} != predicted "
                f"{r['predicted_axis_wire_bytes']} — an axis is "
                f"unaccounted")
        if r["gather_wire_bytes_per_step"] != \
                r["predicted_gather_wire_bytes"]:
            problems.append(
                f"{name}: forward gather bytes/step "
                f"{r['gather_wire_bytes_per_step']} != predicted "
                f"{r['predicted_gather_wire_bytes']}")
        if r["sentry_skipped_steps"] != 0:
            problems.append(
                f"{name}: anomaly sentry skipped "
                f"{r['sentry_skipped_steps']} step(s) of a CLEAN run "
                f"(false positive — or the sentry carry is missing)")
    # hybrid: the mp axis must actually carry gather traffic
    if "mp" not in hyb["axis_wire_bytes_per_step"]:
        problems.append("hybrid: no wire bytes measured on the 'mp' "
                        "axis — the tensor-parallel gathers did not run")
    if hyb["gather_collectives_per_step"] <= 0:
        problems.append("hybrid: no forward param gathers measured")
    # zero3: the FSDP route must be selected, and sharding params at
    # rest must shrink what one chip holds vs the replicated run
    if "rscatter" not in z3["algorithms"]:
        problems.append(f"zero3: no rscatter bucket in "
                        f"{z3['algorithms']} — the FSDP reduce-scatter "
                        f"route was not planned")
    if not (z3["peak_bytes_per_shard"] and int8["peak_bytes_per_shard"]
            and z3["peak_bytes_per_shard"]
            < int8["peak_bytes_per_shard"]):
        problems.append(
            f"zero3: peak_bytes_per_shard "
            f"{z3['peak_bytes_per_shard']} is not below the replicated "
            f"run's {int8['peak_bytes_per_shard']} — params are not "
            f"sharded at rest")
    ratio = int8["wire_bytes_per_step"] / max(fp32["wire_bytes_per_step"],
                                              1)
    if ratio >= 0.35:
        problems.append(f"int8 wire bytes are {ratio:.3f}x of fp32 "
                        f"(gate: < 0.35x)")
    delta = max(abs(a - b) for run in (int8, none, ring, hyb, hyb_none,
                                       z3, z3_none)
                for a, b in zip(fp32["losses"], run["losses"]))
    if delta > 2e-3:
        problems.append(f"int8+error-feedback loss trajectory diverges "
                        f"{delta:.2e} from fp32 (gate: <= 2e-3, all "
                        f"overlap modes AND axis layouts — hybrid/FSDP "
                        f"included)")
    if int8["buckets"] < 2:
        problems.append("fuse_grad_size_in_MB did not produce multiple "
                        "buckets — bucketing is inert")
    if int8["residual_buckets"] < 1:
        problems.append("error feedback on but no residual carry in the "
                        "donated state")

    # overlap gate: auto approaches max(compute, comm) estimated from
    # the none run's anatomy (its step = compute + comm by construction)
    # — on every axis layout, not just pure dp
    def overlap_gate(label, auto_r, none_r, slack=1.15):
        comm_s = none_r["predicted_comm_s"]
        none_s = none_r["step_ms_min"] / 1e3
        auto_s = auto_r["step_ms_min"] / 1e3
        compute_est = max(none_s - comm_s, 0.0)
        bound_s = slack * max(compute_est, comm_s)
        if auto_s > bound_s:
            problems.append(
                f"{label}: overlap=auto step {auto_s * 1e3:.2f} ms "
                f"exceeds {slack}x max(compute "
                f"{compute_est * 1e3:.2f}, comm {comm_s * 1e3:.2f}) = "
                f"{bound_s * 1e3:.2f} ms from the overlap=none "
                f"anatomy — the wire is not hiding")
        return auto_s, none_s, bound_s, comm_s

    auto_s, none_s, bound_s, comm_s = overlap_gate("dp", int8, none)
    # the hybrid/zero3 overlap gates share the anatomy check but run
    # with a looser multiplier: on the CPU smoke their comm term is
    # microseconds, so the bound degenerates to comparing two noisy
    # step minima — the precise 1.15x gate is already enforced on the
    # dp pair above, and the per-axis wire gates are exact regardless
    overlap_gate("hybrid", hyb, hyb_none, slack=1.6)
    overlap_gate("zero3", z3, z3_none, slack=1.6)
    if none["overlap_path"] != "none":
        problems.append(f"overlap='none' resolved to path "
                        f"{none['overlap_path']!r}")
    if int8["overlap_path"] not in ("xla", "ring"):
        problems.append(f"overlap='auto' resolved to path "
                        f"{int8['overlap_path']!r} — no overlap lowering")
    if ring["overlap_path"] != "ring":
        problems.append(f"overlap='ring' resolved to path "
                        f"{ring['overlap_path']!r} — the forced chunked "
                        f"lowering did not run")
    ns = none.get("perf_comm")
    if not ns:
        problems.append("perf observatory reported no comm split for "
                        "the overlap=none run")
    elif ns["hidden_ms"] != 0.0:
        problems.append(f"overlap=none hidden comm {ns['hidden_ms']} ms "
                        f"!= 0 — the split must be structural at none")
    if not int8.get("perf_comm"):
        problems.append("perf observatory reported no comm split for "
                        "the overlap=auto run")

    ec = explain_compiles("executor")
    unex = ec["by_cause"].get("executor.unexplained", 0)
    if unex:
        problems.append(f"{unex} unexplained executor compile(s)")
    scheduled = [r for r in ec["records"]
                 if r.get("comm", {}).get("buckets")]
    if len(scheduled) < 8:
        problems.append(f"only {len(scheduled)} executor compile "
                        f"record(s) carry the grad_comm bucket schedule "
                        f"(expected 8 — overlap decisions must be "
                        f"auditable on every axis layout)")

    result = {
        "metric": "multichip_gpt_int8_wire_ratio_vs_fp32",
        "value": round(ratio, 4),
        "unit": "x (lower is better; gate < 0.35)",
        "loss_delta_max": delta,
        "steps": args.steps,
        "fp32": {k: v for k, v in fp32.items() if k != "losses"},
        "int8": {k: v for k, v in int8.items() if k != "losses"},
        "int8_overlap_none": {k: v for k, v in none.items()
                              if k != "losses"},
        "int8_overlap_ring": {k: v for k, v in ring.items()
                              if k != "losses"},
        "hybrid_dp4_mp2": {k: v for k, v in hyb.items()
                           if k != "losses"},
        "hybrid_dp4_mp2_none": {k: v for k, v in hyb_none.items()
                                if k != "losses"},
        "zero3": {k: v for k, v in z3.items() if k != "losses"},
        "zero3_none": {k: v for k, v in z3_none.items()
                       if k != "losses"},
        "overlap_gate": {
            "auto_step_ms": round(auto_s * 1e3, 3),  # min over steps
            "none_step_ms": round(none_s * 1e3, 3),
            "predicted_comm_ms": round(comm_s * 1e3, 6),
            "bound_ms": round(bound_s * 1e3, 3),
            "auto_path": int8["overlap_path"],
            "exposed_hidden_auto": int8.get("perf_comm"),
            "exposed_hidden_none": none.get("perf_comm"),
        },
        "ok": not problems,
    }
    if args.json:
        print(json.dumps(result))
    if problems:
        for p in problems:
            print(f"FAIL: {p}", file=sys.stderr)
        return 1
    if not args.json:
        print(f"comm_smoke OK: int8 grad_comm wire bytes {ratio:.3f}x "
              f"of fp32 ({int8['wire_bytes_per_step']:.0f} vs "
              f"{fp32['wire_bytes_per_step']:.0f} B/step, predicted "
              f"exactly in every overlap mode), loss parity {delta:.1e} "
              f"<= 2e-3 with error feedback, {int8['buckets']} buckets "
              f"{int8['algorithms']}, overlap auto->"
              f"{int8['overlap_path']} step {auto_s * 1e3:.2f} ms <= "
              f"{bound_s * 1e3:.2f} ms bound (none: "
              f"{none_s * 1e3:.2f} ms), hidden==0 at none, 1 compile "
              f"each, schedules on all records; hybrid {{dp:4, mp:2}} "
              f"per-axis B/step {hyb['axis_wire_bytes_per_step']} == "
              f"predicted with "
              f"{hyb['gather_collectives_per_step']:.0f} gather(s)/"
              f"step; zero3 {z3['algorithms']} per-shard peak "
              f"{z3['peak_bytes_per_shard']} < replicated "
              f"{int8['peak_bytes_per_shard']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
