#!/usr/bin/env python
"""Gradient-collective smoke gate: quantized grad_comm on multichip GPT.

The collective-efficiency promise of ``paddle_tpu.distributed.grad_comm``
(ISSUE 10 / ROADMAP item 2), executably: the GPT-tiny causal LM from
``tools/shard_smoke.py``, trained through ``fleet.distributed_optimizer``
+ the static ``Executor`` on an 8-device dp mesh, once with fp32 wire
(the measured baseline — same math as GSPMD's default, but with the
explicit bucketed stage so ``comm.*`` stats exist) and once with
block-scaled int8 + error feedback:

- **wire bytes**: int8 ``comm.wire_bytes``/step < 0.35x the fp32 run's
  (quantized payload + scales, both measured from monitor stats);
- **prediction closes**: measured wire bytes == the static cost model's
  ``predicted_wire_bytes`` (``Program.analyze(sharding=plan)`` comm
  block) exactly — the plan is the single source of both numbers;
- **loss parity**: int8-with-error-feedback loss trajectory within
  2e-3 of the fp32 baseline after every step;
- **0 steady-state recompiles** (one XLA compile per run) and
  ``explain_compiles()`` reports no unexplained executor compiles;
- **bucketing + algorithm selection**: the small fuse budget forces
  multiple buckets, and every bucket records a psum/scatter choice.

Usage::

    python tools/comm_smoke.py [--steps 8] [--json] [--verbose]

``--json`` prints one JSON line (consumed by ``bench.py --suite
multichip``).  CI treats a non-zero exit as a regression.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# env BEFORE jax initialises: 8 virtual CPU devices
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

from tools.shard_smoke import _feeds, build_gpt_tiny  # noqa: E402


def _train(dtype, steps, verbose=False):
    """GPT-tiny on mesh {dp: 8} with the given grad_comm wire dtype.
    Returns a result dict (losses, wire stats, prediction, timing)."""
    import paddle_tpu as paddle
    from paddle_tpu import distributed as dist, optimizer
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.utils import monitor

    init_mesh({"dp": 8})
    paddle.seed(7)
    main, loss, _ = build_gpt_tiny()
    with paddle.static.program_guard(main):
        f = dist.fleet
        strategy = dist.DistributedStrategy()
        # small fuse budget -> several buckets (overlap-shaped), low
        # threshold -> the big buckets take the bandwidth route
        strategy.fuse_grad_size_in_MB = 0.05
        strategy.grad_comm = {"dtype": dtype, "error_feedback": True,
                              "block_size": 256,
                              "scatter_threshold_KB": 4.0}
        f.init(is_collective=True, strategy=strategy)
        opt = f.distributed_optimizer(optimizer.AdamW(learning_rate=1e-3))
        opt.minimize(loss)
    init_mesh({"dp": 8})  # fleet.init infers over ALL devices; pin it
    exe = paddle.static.Executor()
    feed = _feeds("gpt")
    w0 = monitor.get_stat("comm.wire_bytes") or 0
    c0 = monitor.get_stat("comm.collectives") or 0
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])]
    t0 = time.perf_counter()
    for _ in range(steps - 1):
        losses.append(float(exe.run(main, feed=feed,
                                    fetch_list=[loss])[0]))
    dt = time.perf_counter() - t0
    wire = ((monitor.get_stat("comm.wire_bytes") or 0) - w0) / steps
    colls = ((monitor.get_stat("comm.collectives") or 0) - c0) / steps
    plan = exe._plan_for(main, main.parameters())
    rep = main.analyze(fetch_list=[loss], sharding=plan)
    comm = rep.totals["comm"]
    state = exe._states[main._serial]
    out = {
        "losses": losses,
        "compiles": exe.compile_count,
        "wire_bytes_per_step": wire,
        "collectives_per_step": colls,
        "predicted_wire_bytes": comm["wire_bytes_per_step"],
        "predicted_fp32_wire_bytes": comm["fp32_wire_bytes_per_step"],
        "buckets": len(comm["collectives"]),
        "algorithms": sorted({c["algorithm"]
                              for c in comm["collectives"]}),
        "residual_buckets": len(state.aux.get("grad_comm", [])),
        "steps_per_sec": (steps - 1) / max(dt, 1e-9),
    }
    if verbose:
        print(f"  {dtype}: losses {['%.4f' % v for v in losses]} "
              f"wire {wire:.0f}B/step ({out['buckets']} buckets, "
              f"{out['algorithms']}), {out['steps_per_sec']:.1f} steps/s")
    exe.close()
    paddle.static.reset_default_programs()
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--json", action="store_true",
                    help="print one JSON result line on stdout")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu.observability import explain_compiles

    problems = []
    paddle.enable_static()
    try:
        fp32 = _train("fp32", args.steps, args.verbose)
        int8 = _train("int8", args.steps, args.verbose)
    finally:
        paddle.disable_static()

    for name, r in (("fp32", fp32), ("int8", int8)):
        if r["compiles"] != 1:
            problems.append(f"{name}: {r['compiles']} compiles for one "
                            f"feed signature — recompiles after warmup")
        if r["wire_bytes_per_step"] != r["predicted_wire_bytes"]:
            problems.append(
                f"{name}: measured wire bytes/step "
                f"{r['wire_bytes_per_step']} != predicted "
                f"{r['predicted_wire_bytes']} — the cost model and the "
                f"runtime disagree")
    ratio = int8["wire_bytes_per_step"] / max(fp32["wire_bytes_per_step"],
                                              1)
    if ratio >= 0.35:
        problems.append(f"int8 wire bytes are {ratio:.3f}x of fp32 "
                        f"(gate: < 0.35x)")
    delta = max(abs(a - b) for a, b in zip(fp32["losses"],
                                           int8["losses"]))
    if delta > 2e-3:
        problems.append(f"int8+error-feedback loss trajectory diverges "
                        f"{delta:.2e} from fp32 (gate: <= 2e-3)")
    if int8["buckets"] < 2:
        problems.append("fuse_grad_size_in_MB did not produce multiple "
                        "buckets — bucketing is inert")
    if int8["residual_buckets"] < 1:
        problems.append("error feedback on but no residual carry in the "
                        "donated state")
    ec = explain_compiles("executor")
    unex = ec["by_cause"].get("executor.unexplained", 0)
    if unex:
        problems.append(f"{unex} unexplained executor compile(s)")

    result = {
        "metric": "multichip_gpt_int8_wire_ratio_vs_fp32",
        "value": round(ratio, 4),
        "unit": "x (lower is better; gate < 0.35)",
        "loss_delta_max": delta,
        "steps": args.steps,
        "fp32": {k: v for k, v in fp32.items() if k != "losses"},
        "int8": {k: v for k, v in int8.items() if k != "losses"},
        "ok": not problems,
    }
    if args.json:
        print(json.dumps(result))
    if problems:
        for p in problems:
            print(f"FAIL: {p}", file=sys.stderr)
        return 1
    if not args.json:
        print(f"comm_smoke OK: int8 grad_comm wire bytes {ratio:.3f}x "
              f"of fp32 ({int8['wire_bytes_per_step']:.0f} vs "
              f"{fp32['wire_bytes_per_step']:.0f} B/step, predicted "
              f"exactly), loss parity {delta:.1e} <= 2e-3 with error "
              f"feedback, {int8['buckets']} buckets "
              f"{int8['algorithms']}, 1 compile each, all compiles "
              f"attributed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
