#!/usr/bin/env python
"""CI gate: the static cost model must stay honest on the bench programs.

Builds the exact `bench.py --suite static` model configs (the MLP
hot-path micro and LeNet) as static Programs and asserts, in order:

1. predicted forward FLOPs within 20% of an INDEPENDENT hand count
   (per-layer 2*M*K*N matmuls + bias/activation terms, conv im2col
   dots — written out below, not derived from the analyzer's tables);
2. zero `unmodeled` ops/bytes on these programs — the op tables cover
   the whole bench surface;
3. liveness: peak memory with donation strictly below the no-donation
   bound (what PR 2's donation buys must be visible statically);
4. at least one ranked fusion candidate (the MPK-style selection the
   Pallas tier will consume), with positive traffic savings;
5. TPU-readiness hazard passes clean: no error- or warning-severity
   hazards (int64 label feeds are info, allowed);
6. `tools/analyze_program.py --format json` on the same MLP module
   parses and reproduces the in-process FLOP count exactly;
7. the Executor records the same prediction per compile
   (`explain_compiles()` record carries `predicted`, monitor gauges
   `predicted.executor.*` are set).

Exit 0 on success, 1 with a reason on any violation.
"""
from __future__ import annotations

import contextlib
import io
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# bench.py --small static-suite configs (bench_static)
MLP_HIDDEN, MLP_DEPTH, MLP_BATCH = 128, 8, 32
LENET_BATCH = 16

_MLP_MODULE = """
import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import optimizer

paddle.enable_static()
paddle.seed(7)
main = paddle.static.Program()
with paddle.static.program_guard(main):
    x = paddle.static.data("x", [None, {hidden}], "float32")
    y = paddle.static.data("y", [None, 1], "float32")
    h = x
    for _ in range({depth}):
        h = paddle.static.nn.fc(h, {hidden}, activation="relu")
    pred = paddle.static.nn.fc(h, 1)
    loss = F.mse_loss(pred, y)
    optimizer.Adam(learning_rate=1e-3).minimize(loss)
loss_name = loss.name
"""


def _fail(msg: str) -> int:
    print(f"analyze_smoke: FAIL - {msg}")
    return 1


def _mlp_hand_flops(batch: int) -> int:
    """Forward FLOPs of the bench MLP, counted from the layer algebra:
    each fc is a [B,K]x[K,N] matmul (2*B*K*N) + bias add (B*N); relu is
    one op per element; mse is a handful per output element."""
    h, fl = MLP_HIDDEN, 0
    for _ in range(MLP_DEPTH):
        fl += 2 * batch * h * h + batch * h + batch * h
    fl += 2 * batch * h * 1 + batch * 1   # head fc
    fl += 5 * batch * 1                   # mse (sub, square, mean)
    return fl


def _lenet_hand_flops(batch: int) -> int:
    """LeNet forward: conv dots are 2*out_elems*(Cin*kh*kw) + bias."""
    b, fl = batch, 0
    fl += 2 * b * 6 * 28 * 28 * (1 * 3 * 3) + b * 6 * 28 * 28  # conv1
    fl += b * 6 * 28 * 28                                      # relu
    fl += b * 6 * 14 * 14 * 4                                  # pool 2x2
    fl += 2 * b * 16 * 10 * 10 * (6 * 5 * 5) + b * 16 * 10 * 10
    fl += b * 16 * 10 * 10
    fl += b * 16 * 5 * 5 * 4
    fl += 2 * b * 120 * 400 + b * 120
    fl += 2 * b * 84 * 120 + b * 84
    fl += 2 * b * 10 * 84 + b * 10
    fl += 10 * b * 10                     # softmax + nll
    return fl


def main() -> int:
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import optimizer
    from paddle_tpu.observability import explain_compiles
    from paddle_tpu.static.analysis import Diagnostic
    from paddle_tpu.utils import monitor
    from paddle_tpu.vision.models import LeNet

    paddle.enable_static()
    reports = {}
    try:
        paddle.seed(7)
        mlp = paddle.static.Program()
        with paddle.static.program_guard(mlp):
            x = paddle.static.data("x", [None, MLP_HIDDEN], "float32")
            y = paddle.static.data("y", [None, 1], "float32")
            h = x
            for _ in range(MLP_DEPTH):
                h = paddle.static.nn.fc(h, MLP_HIDDEN, activation="relu")
            pred = paddle.static.nn.fc(h, 1)
            mlp_loss = F.mse_loss(pred, y)
            optimizer.Adam(learning_rate=1e-3).minimize(mlp_loss)

        paddle.seed(9)
        lenet = paddle.static.Program()
        with paddle.static.program_guard(lenet):
            lx = paddle.static.data("x", [None, 1, 28, 28], "float32")
            ly = paddle.static.data("y", [None], "int64")
            lenet_loss = F.cross_entropy(LeNet()(lx), ly)
            optimizer.Adam(learning_rate=1e-3).minimize(lenet_loss)

        for name, prog, loss, batch, hand in (
                ("static_mlp", mlp, mlp_loss, MLP_BATCH,
                 _mlp_hand_flops(MLP_BATCH)),
                ("static_lenet", lenet, lenet_loss, LENET_BATCH,
                 _lenet_hand_flops(LENET_BATCH))):
            rep = prog.analyze(fetch_list=[loss], batch_size=batch)
            reports[name] = rep
            got = rep.totals["flops_fwd"]
            rel = abs(got - hand) / hand
            if rel > 0.20:
                return _fail(
                    f"{name}: predicted fwd FLOPs {got} vs hand-counted "
                    f"{hand} ({rel:.1%} off, gate is 20%)")
            print(f"analyze_smoke: {name} fwd FLOPs {got} "
                  f"(hand {hand}, {rel:.2%} off)")
            un = rep.totals["unmodeled"]
            if un["count"] or un["bytes"]:
                return _fail(f"{name}: unmodeled bucket not empty: {un}")
            m = rep.memory
            if not m.peak_bytes_donated < m.peak_bytes_no_donation:
                return _fail(
                    f"{name}: donated peak {m.peak_bytes_donated} not "
                    f"strictly below no-donation bound "
                    f"{m.peak_bytes_no_donation}")
            print(f"analyze_smoke: {name} peak "
                  f"{m.peak_bytes_donated}B donated < "
                  f"{m.peak_bytes_no_donation}B no-donation")
            if not rep.fusion_candidates:
                return _fail(f"{name}: no fusion candidates ranked")
            if rep.fusion_candidates[0]["saved_bytes"] <= 0:
                return _fail(f"{name}: top fusion candidate saves "
                             f"nothing")
            bad = [d for d in rep.hazards
                   if d.severity in (Diagnostic.ERROR,
                                     Diagnostic.WARNING)]
            if bad:
                return _fail(f"{name}: hazard passes not clean: "
                             + "; ".join(str(d) for d in bad))
            # the JSON surface round-trips with the load-bearing keys
            d = json.loads(rep.to_json())
            for k in ("per_op", "totals", "memory", "roofline",
                      "fusion_candidates", "hazards"):
                if k not in d:
                    return _fail(f"{name}: to_json missing {k!r}")

        # -- CLI reproduces the in-process numbers ------------------------
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import analyze_program
        finally:
            sys.path.remove(os.path.join(REPO, "tools"))
        with tempfile.TemporaryDirectory(prefix="analyze_smoke_") as td:
            script = os.path.join(td, "mlp_module.py")
            with open(script, "w") as f:
                f.write(_MLP_MODULE.format(hidden=MLP_HIDDEN,
                                           depth=MLP_DEPTH))
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                rc = analyze_program.main(
                    [script, "--fetch", "loss", "--format", "json",
                     "--batch-size", str(MLP_BATCH)])
            if rc != 0:
                return _fail(f"analyze_program CLI exited {rc}")
            cli = json.loads(buf.getvalue())
            cli_main = next(
                (p for p in cli["programs"] if p["name"] == "main"), None)
            if cli_main is None:
                return _fail("CLI JSON has no report for 'main'")
            cli_flops = cli_main["report"]["totals"]["flops_fwd"]
            want = reports["static_mlp"].totals["flops_fwd"]
            if cli_flops != want:
                return _fail(f"CLI fwd FLOPs {cli_flops} != in-process "
                             f"{want}")
            print(f"analyze_smoke: CLI JSON parses, flops_fwd "
                  f"{cli_flops} == in-process")

        # -- the Executor records the same prediction per compile ---------
        exe = paddle.static.Executor()
        feed = {"x": np.zeros((MLP_BATCH, MLP_HIDDEN), np.float32),
                "y": np.zeros((MLP_BATCH, 1), np.float32)}
        exe.run(mlp, feed=feed, fetch_list=[mlp_loss])
        recs = [r for r in explain_compiles("executor")["records"]
                if r["identity"] == mlp._serial]
        if not recs or "predicted" not in recs[-1]:
            return _fail("executor compile record carries no "
                         "'predicted' cost summary")
        pred = recs[-1]["predicted"]
        want_fwd = reports["static_mlp"].totals["flops_fwd"]
        # the per-compile summary uses recorded avals (batch placeholder
        # 1); forward FLOPs scale linearly with the batch in this MLP,
        # so the batched report must be exactly batch x the compile one
        if pred["flops_fwd"] * MLP_BATCH != want_fwd:
            return _fail(
                f"executor-predicted fwd FLOPs {pred['flops_fwd']} x "
                f"batch {MLP_BATCH} != analyze() {want_fwd}")
        if monitor.get_stat("predicted.executor.flops") != pred["flops"]:
            return _fail("monitor gauge predicted.executor.flops not "
                         "set to the compile prediction")
        if pred["peak_bytes"] >= \
                reports["static_mlp"].memory.peak_bytes_no_donation:
            return _fail("executor-predicted donated peak not below "
                         "the no-donation bound")
        exe.close()
        print("analyze_smoke: executor compile carries predicted "
              f"flops={pred['flops']} peak_bytes={pred['peak_bytes']}")
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()

    print("analyze_smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
