#!/usr/bin/env python
"""CI smoke gate for the serving engine (sibling of bench_smoke.py /
chaos_smoke.py).

Drives a short engine run on CPU — tiny model, burst of ragged
concurrent requests — and exits non-zero when the serving hot path
regresses:

1. **recompiles** — after ``warmup()`` the dispatcher must always pad
   into a precompiled bucket; any hot-path compile means the
   bucket/padding strategy broke (``recompiles_after_warmup != 0``).
2. **batch occupancy** — coalescing must actually happen: burst-submitted
   requests have to ride shared micro-batches (mean occupancy above a
   floor AND > 1 request per batch on average).
3. **stuck futures** — after ``close()`` every accepted request's future
   must be resolved (result or clean error); a pending future is a hang
   a real client would have felt.
4. **correctness under load** — every response must match the
   single-request Predictor answer bitwise (dyadic weights/inputs make
   float accumulation exact, so batching/padding cannot hide behind
   tolerance).

The **decode gate** (``run_decode_checks``) covers the generative path
(continuous batching over the paged KV cache) the same way:

5. **steady-state decode recompiles** — after ``warmup()`` every
   prefill bucket and the decode step are AOT-compiled; a ragged burst
   must finish with ``recompiles_after_warmup == 0``.
6. **slot occupancy** — continuous batching must actually fill the
   decode batch: mean slot occupancy >= 0.5 under the burst.
7. **page reclamation** — after drain the page pool must be fully
   reclaimed (``in_use == 0``, allocated == freed): a leaked page is a
   capacity regression a long-lived server would die from.

The **hot-swap gate** (``run_hotswap_checks``) covers the zero-downtime
weight swap path:

8. **zero recompiles across a swap** — publishing a new weights
   snapshot and committing it through the
   :class:`~paddle_tpu.serving.WeightWatcher` must not compile
   anything on either engine (the replacement predictor prewarms off
   the dispatch thread; generation weights are executable *arguments*).
9. **readiness green** — ``/healthz`` answers 200/ready before,
   during, and after the swap, and its ``weights_version`` advances.
10. **per-version bitwise** — responses before the swap match the old
    artifact's single-request answers exactly; responses after match
    the new artifact's.

The **compile-cache gate** (``run_compile_cache_checks``) covers the
persistent AOT executable cache (``FLAGS_compile_cache_dir``):

11. **zero fresh compiles on a warm cache** — two *subprocess* cold
    starts against one cache dir; the second must warm up entirely
    from deserialized executables (``compile_cache.hits`` only — no
    misses, rejects, or stores).
12. **>=5x faster warm start** — the second process's ``warmup()``
    wall time must be at least 5x faster than the first's (XLA
    compiles are seconds; deserializes are milliseconds).
13. **bitwise across the cache** — a loaded executable answers exactly
    like the freshly compiled one.

The **WFQ gate** (``run_wfq_checks``) covers multi-model fair
admission through the :class:`~paddle_tpu.serving.ModelRegistry`:

14. **isolation under saturation** — a tenant flooding model A past
    the shared in-flight pool must be clamped to A's weighted share
    (``registry.wfq_shed`` > 0) while model B's p99 latency stays
    within 1.5x of its solo baseline (+ a small absolute floor), with
    every B response bitwise-correct.

Usage:  python tools/serve_smoke.py [--requests N] [--clients C]
"""
from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

OCCUPANCY_FLOOR = 0.5
COALESCE_FLOOR = 1.5        # mean requests per batch under burst load


def run_checks(requests: int = 64, clients: int = 8,
               verbose: bool = False) -> list:
    """Returns a list of failure strings (empty = healthy)."""
    import tempfile
    import threading

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import inference, jit, nn, serving
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.testing.chaos import make_dyadic_model

    failures = []
    paddle.seed(11)
    model = make_dyadic_model(in_dim=8, hidden=16, out_dim=4)
    prefix = os.path.join(tempfile.mkdtemp(prefix="serve_smoke_"), "m")
    jit.save(model, prefix, input_spec=[InputSpec([None, 8], "float32")])
    pred = inference.create_predictor(inference.Config(prefix))

    engine = serving.InferenceEngine(pred, max_batch_size=8,
                                     batch_timeout_ms=10.0,
                                     max_queue=2 * requests)
    warm = engine.warmup()
    if verbose:
        print(f"warmed buckets {engine.buckets}: {warm} variants")

    rng = np.random.RandomState(3)
    reqs = [(rng.randint(-8, 9, (rng.randint(1, 5), 8)) / 4.0)
            .astype(np.float32) for _ in range(requests)]
    refs = [np.asarray(pred.run([x])[0]) for x in reqs]
    base_variants = pred.num_compiled_variants()

    # burst submission: every client enqueues its whole share before
    # waiting, so the dispatcher always has a populated queue to
    # coalesce from — makes the occupancy gate deterministic
    futures = [None] * requests
    def client(idx):
        for i in range(idx, requests, clients):
            futures[i] = engine.infer(reqs[i])
    threads = [threading.Thread(target=client, args=(c,))
               for c in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    results = []
    for f in futures:
        try:
            results.append(f.result(timeout=60))
        except Exception as e:      # noqa: BLE001 - recorded, gated below
            results.append(e)

    engine.drain(timeout=30)
    stats = engine.stats()
    engine.close()

    for i, (res, ref) in enumerate(zip(results, refs)):
        if isinstance(res, Exception):
            failures.append(f"request {i} failed: "
                            f"{type(res).__name__}: {res}")
        elif not np.array_equal(res[0], ref):
            failures.append(
                f"request {i}: batched response differs from the "
                f"single-request answer (max "
                f"|d|={np.abs(res[0] - ref).max():.3e})")
    if pred.num_compiled_variants() != base_variants \
            or stats["recompiles_after_warmup"] != 0:
        failures.append(
            f"hot-path recompiles: {stats['recompiles_after_warmup']} "
            f"after warmup (bucket padding must keep the compile cache "
            f"hot)")
    if stats["mean_batch_occupancy"] < OCCUPANCY_FLOOR:
        failures.append(
            f"batch occupancy {stats['mean_batch_occupancy']:.2f} below "
            f"floor {OCCUPANCY_FLOOR} (padding waste too high)")
    if stats["requests_per_batch"] < COALESCE_FLOOR:
        failures.append(
            f"coalescing regression: {stats['requests_per_batch']:.2f} "
            f"requests/batch under burst load (floor {COALESCE_FLOOR})")
    unresolved = [i for i, f in enumerate(futures) if not f.done()]
    if unresolved:
        failures.append(f"stuck futures after close(): {unresolved}")
    if verbose:
        print(f"occupancy={stats['mean_batch_occupancy']:.2f} "
              f"reqs/batch={stats['requests_per_batch']:.2f} "
              f"batches={stats['counters']['batches']} "
              f"p95={stats['latency_ms']['p95']:.1f}ms")
    return failures


def run_decode_checks(requests: int = 20, clients: int = 5,
                      verbose: bool = False) -> list:
    """Generative decode gate; returns failure strings (empty = healthy)."""
    import threading

    import numpy as np

    from paddle_tpu import serving
    from paddle_tpu.testing.chaos import make_dyadic_lm

    failures = []
    model = make_dyadic_lm()
    engine = serving.GenerationEngine(model, num_slots=4, page_size=4,
                                      max_context=64,
                                      max_queue=4 * requests)
    warm = engine.warmup()
    if verbose:
        print(f"decode warmup: {warm} variants "
              f"(buckets {engine.prompt_buckets} + decode)")

    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, 32, rng.randint(1, 9)).tolist()
               for _ in range(requests)]
    budgets = [int(rng.randint(4, 10)) for _ in range(requests)]

    # ragged burst: every client enqueues its whole share before
    # waiting, so the scheduler always has queued work to backfill
    # freed slots with — the occupancy gate's precondition
    streams = [None] * requests

    def client(idx):
        for i in range(idx, requests, clients):
            streams[i] = engine.generate(prompts[i],
                                         max_new_tokens=budgets[i])
    threads = [threading.Thread(target=client, args=(c,))
               for c in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    results = []
    for s in streams:
        try:
            results.append(s.result(timeout=60))
        except Exception as e:      # noqa: BLE001 - recorded, gated below
            results.append(e)

    engine.drain(timeout=60)
    stats = engine.stats()
    engine.close()

    for i, res in enumerate(results):
        if isinstance(res, Exception):
            failures.append(f"sequence {i} failed: "
                            f"{type(res).__name__}: {res}")
        elif len(res) != budgets[i]:
            failures.append(f"sequence {i}: {len(res)} tokens, "
                            f"budget {budgets[i]}")
    if stats["recompiles_after_warmup"] != 0:
        failures.append(
            f"steady-state decode recompiled "
            f"{stats['recompiles_after_warmup']}x after warmup (bucketed "
            f"prefill + static decode shapes must keep the cache hot)")
    if stats["mean_slot_occupancy"] < OCCUPANCY_FLOOR:
        failures.append(
            f"slot occupancy {stats['mean_slot_occupancy']:.2f} below "
            f"floor {OCCUPANCY_FLOOR} under a ragged burst (continuous "
            f"batching is not backfilling freed slots)")
    pool = stats["page_pool"]
    if pool["in_use"] != 0:
        failures.append(f"page pool not reclaimed after drain: "
                        f"{pool['in_use']} pages still held")
    if stats["counters"]["pages_allocated"] \
            != stats["counters"]["pages_freed"]:
        failures.append(
            f"page accounting: {stats['counters']['pages_allocated']} "
            f"allocated vs {stats['counters']['pages_freed']} freed")
    unresolved = [i for i, s in enumerate(streams)
                  if not s.future.done()]
    if unresolved:
        failures.append(f"stuck generation futures after close(): "
                        f"{unresolved}")
    if verbose:
        print(f"decode: occupancy={stats['mean_slot_occupancy']:.2f} "
              f"steps={stats['counters']['decode_steps']} "
              f"tokens={stats['counters']['tokens']} "
              f"prefill/decode={stats['prefill_decode_ratio']:.2f} "
              f"ttft_p95={stats['ttft_ms']['p95']:.1f}ms")
    return failures


def run_hotswap_checks(verbose: bool = False) -> list:
    """Hot-swap gate; returns failure strings (empty = healthy)."""
    import tempfile

    import numpy as np

    from paddle_tpu import inference, serving
    from paddle_tpu.serving.hotswap import WeightWatcher, publish_weights
    from paddle_tpu.testing.chaos import (_scaled_artifact,
                                          make_dyadic_lm)
    from paddle_tpu.utils.checkpoint import SnapshotStore

    failures = []
    workdir = tempfile.mkdtemp(prefix="serve_smoke_swap_")
    prefixes = {v: _scaled_artifact(s, workdir, f"v{v}")
                for v, s in ((1, 1.0), (2, 0.5))}
    preds = {v: inference.create_predictor(inference.Config(p))
             for v, p in prefixes.items()}
    rng = np.random.RandomState(7)
    reqs = [(rng.randint(-8, 9, (rng.randint(1, 5), 8)) / 4.0)
            .astype(np.float32) for _ in range(8)]
    refs = {v: [np.asarray(preds[v].run([x])[0]) for x in reqs]
            for v in preds}

    base = {k: np.asarray(a).copy()
            for k, a in make_dyadic_lm().params.items()}
    engine = serving.InferenceEngine(preds[1], max_batch_size=8,
                                     batch_timeout_ms=5.0)
    engine.warmup()
    gen = serving.GenerationEngine(make_dyadic_lm(), num_slots=4,
                                   page_size=4, max_context=64)
    gen.warmup()
    srv = serving.ServingServer(engine, generation=gen, port=0).start()
    client = serving.Client(srv.url)

    def healthz_green(when):
        h = client.healthz()
        if not h.get("ready") or h.get("status") != "running":
            failures.append(f"readiness not green {when}: {h}")
        return h

    healthz_green("before the swap")
    for i, x in enumerate(reqs):
        out = engine.infer_sync([x], timeout=30)
        if not np.array_equal(out[0], refs[1][i]):
            failures.append(f"pre-swap response {i} not bitwise at "
                            f"version 1")

    store = SnapshotStore(f"{workdir}/weights")
    watcher = WeightWatcher(store, engine=engine, generation=gen)
    publish_weights(store, 2, artifact_prefix=prefixes[2],
                    params={k: a * 0.5 for k, a in base.items()})
    applied = watcher.check_once()
    if applied != 2:
        failures.append(f"swap not applied (got {applied}, last_error="
                        f"{watcher.last_error})")
    h = healthz_green("after the swap")
    if h.get("weights_version") != 2:
        failures.append(f"/healthz weights_version="
                        f"{h.get('weights_version')} after the swap, "
                        f"expected 2")
    for i, x in enumerate(reqs):
        out = engine.infer_sync([x], timeout=30)
        if not np.array_equal(out[0], refs[2][i]):
            failures.append(f"post-swap response {i} not bitwise at "
                            f"version 2")
    gen.generate_sync([1, 2, 3], timeout=60, max_new_tokens=4)

    srv.close()
    engine.drain(timeout=30)
    gen.drain(timeout=30)
    stats = engine.stats()
    gen_stats = gen.stats()
    engine.close()
    gen.close()
    if stats["recompiles_after_warmup"] != 0:
        failures.append(f"inference recompiled "
                        f"{stats['recompiles_after_warmup']}x across "
                        f"the swap")
    if gen_stats["recompiles_after_warmup"] != 0:
        failures.append(f"decode recompiled "
                        f"{gen_stats['recompiles_after_warmup']}x "
                        f"across the swap")
    if verbose:
        print(f"hotswap: applied v{applied}, engine swaps="
              f"{stats['counters']['weight_swaps']}, decode swaps="
              f"{gen_stats['counters']['weight_swaps']}, recompiles=0")
    import shutil
    shutil.rmtree(workdir, ignore_errors=True)
    return failures


# the child driver for the compile-cache gate: one cold start in a
# fresh process — build the predictor, time warmup, answer one request,
# report the cache counters.  Run twice against one cache dir; the
# second incarnation must warm from deserialized executables only.
_CACHE_CHILD = r"""
import json, os, sys, time
sys.path.insert(0, sys.argv[1])
import numpy as np
from paddle_tpu import inference, serving
from paddle_tpu.core import compile_cache

pred = inference.create_predictor(inference.Config(sys.argv[2]))
engine = serving.InferenceEngine(pred, max_batch_size=8,
                                 batch_timeout_ms=5.0)
t0 = time.perf_counter()
n = engine.warmup()
warmup_s = time.perf_counter() - t0
x = (np.arange(64, dtype=np.float32).reshape(2, 32) / 16.0)
out = engine.infer_sync([x], timeout=60)
engine.close()
print(json.dumps({"warmup_s": warmup_s, "variants": n,
                  "stats": compile_cache.stats(),
                  "out": np.asarray(out[0]).tolist()}))
"""

CACHE_SPEEDUP_FLOOR = 5.0


def run_compile_cache_checks(verbose: bool = False) -> list:
    """Compile-cache gate; returns failure strings (empty = healthy)."""
    import json
    import shutil
    import subprocess
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu import jit, nn
    from paddle_tpu.jit import InputSpec

    failures = []
    workdir = tempfile.mkdtemp(prefix="serve_smoke_cache_")
    paddle.seed(11)
    # deep enough that XLA compile time dominates warmup — the ratio
    # this gate measures is compile-vs-deserialize, and a one-layer toy
    # would hide a cache regression inside fixed engine overhead
    layers = []
    for _ in range(8):
        layers += [nn.Linear(32, 32), nn.ReLU()]
    layers.append(nn.Linear(32, 4))
    model = nn.Sequential(*layers)
    prefix = os.path.join(workdir, "m")
    jit.save(model, prefix, input_spec=[InputSpec([None, 32], "float32")])

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["FLAGS_compile_cache_dir"] = os.path.join(workdir, "xcache")
    runs = []
    for i in range(2):
        r = subprocess.run([sys.executable, "-c", _CACHE_CHILD, REPO,
                            prefix], env=env, capture_output=True,
                           text=True, timeout=600)
        if r.returncode != 0:
            failures.append(f"cold start {i} crashed (rc={r.returncode}):"
                            f" {r.stderr.strip()[-500:]}")
            shutil.rmtree(workdir, ignore_errors=True)
            return failures
        runs.append(json.loads(r.stdout.strip().splitlines()[-1]))
    first, second = runs

    if first["stats"]["stores"] < 1:
        failures.append(f"first cold start stored nothing: "
                        f"{first['stats']}")
    s2 = second["stats"]
    if s2["misses"] or s2["rejects"] or s2["stores"]:
        failures.append(
            f"second cold start paid fresh compiles with a warm cache: "
            f"{s2} (every bucket must load)")
    if s2["hits"] < second["variants"]:
        failures.append(f"only {s2['hits']} cache hits for "
                        f"{second['variants']} warmed variants")
    speedup = (first["warmup_s"] / second["warmup_s"]
               if second["warmup_s"] > 0 else float("inf"))
    if speedup < CACHE_SPEEDUP_FLOOR:
        failures.append(
            f"warm-cache warmup only {speedup:.1f}x faster "
            f"({first['warmup_s']:.3f}s -> {second['warmup_s']:.3f}s; "
            f"floor {CACHE_SPEEDUP_FLOOR}x)")
    if first["out"] != second["out"]:
        failures.append("loaded executable's response is not bitwise-"
                        "identical to the freshly compiled one")
    if verbose:
        print(f"compile cache: cold {first['warmup_s']:.3f}s "
              f"({first['stats']['stores']} stored) -> warm "
              f"{second['warmup_s']:.3f}s ({s2['hits']} hits, "
              f"{speedup:.1f}x)")
    shutil.rmtree(workdir, ignore_errors=True)
    return failures


WFQ_P99_RATIO = 1.5
WFQ_P99_FLOOR_MS = 25.0


def run_wfq_checks(verbose: bool = False) -> list:
    """WFQ isolation gate; returns failure strings (empty = healthy)."""
    import tempfile
    import threading
    import time

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import inference, jit, serving
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.testing.chaos import make_dyadic_model
    from paddle_tpu.utils import monitor

    failures = []
    workdir = tempfile.mkdtemp(prefix="serve_smoke_wfq_")
    paddle.seed(11)
    model = make_dyadic_model(in_dim=8, hidden=16, out_dim=4)
    prefix = os.path.join(workdir, "m")
    jit.save(model, prefix, input_spec=[InputSpec([None, 8], "float32")])

    def engine(name):
        pred = inference.create_predictor(inference.Config(prefix))
        e = serving.InferenceEngine(pred, max_batch_size=8,
                                    batch_timeout_ms=1.0, max_queue=512,
                                    name=name)
        e.warmup()
        return e

    monitor.stat_reset("registry.wfq_shed")
    reg = serving.ModelRegistry(max_inflight=16)
    reg.register("hot", engine=engine("hot"))
    reg.register("quiet", engine=engine("quiet"))

    x = (np.arange(16, dtype=np.float32).reshape(2, 8) / 4.0)
    ref = np.asarray(reg.infer_sync("quiet", [x], timeout=30)[0])

    def quiet_p99(samples=60):
        lat = []
        for _ in range(samples):
            t0 = time.perf_counter()
            out = reg.infer_sync("quiet", [x], timeout=30)
            lat.append((time.perf_counter() - t0) * 1e3)
            if not np.array_equal(np.asarray(out[0]), ref):
                failures.append("quiet-model response not bitwise "
                                "under load")
        return float(np.percentile(lat, 99))

    solo = quiet_p99()

    stop = threading.Event()
    shed = [0]

    def flooder():
        pending = []
        while not stop.is_set():
            try:
                pending.append(reg.infer("hot", [x]))
            except serving.QueueFull:
                shed[0] += 1
                time.sleep(0.0005)
            pending = [f for f in pending if not f.done()]
        for f in pending:
            try:
                f.result(30)
            except Exception:  # noqa: BLE001 - teardown only
                pass

    threads = [threading.Thread(target=flooder, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.2)             # let the flood saturate the pool
    loaded = quiet_p99()
    stop.set()
    for t in threads:
        t.join(60)

    if shed[0] < 1 or monitor.get_stat("registry.wfq_shed") < 1:
        failures.append(
            f"the saturating tenant was never clamped to its weighted "
            f"share (shed={shed[0]}) — the pool did not saturate, so "
            f"the isolation measurement is vacuous")
    bound = max(solo * WFQ_P99_RATIO, solo + WFQ_P99_FLOOR_MS)
    if loaded > bound:
        failures.append(
            f"quiet model's p99 {loaded:.1f}ms under a saturating "
            f"co-tenant exceeds {bound:.1f}ms (solo {solo:.1f}ms x "
            f"{WFQ_P99_RATIO} + {WFQ_P99_FLOOR_MS}ms floor): WFQ is "
            f"not isolating models")
    if verbose:
        print(f"wfq: quiet p99 {solo:.1f}ms solo -> {loaded:.1f}ms "
              f"under flood (bound {bound:.1f}ms), hot shed "
              f"{shed[0]}x")
    reg.close(timeout=30)
    import shutil
    shutil.rmtree(workdir, ignore_errors=True)
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    failures = run_checks(requests=args.requests, clients=args.clients,
                          verbose=args.verbose)
    failures += [f"decode: {f}" for f in run_decode_checks(
        verbose=args.verbose)]
    failures += [f"hotswap: {f}" for f in run_hotswap_checks(
        verbose=args.verbose)]
    failures += [f"compile-cache: {f}" for f in run_compile_cache_checks(
        verbose=args.verbose)]
    failures += [f"wfq: {f}" for f in run_wfq_checks(
        verbose=args.verbose)]
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("serve_smoke: engine healthy (0 hot-path recompiles, coalesced "
          "batches, bitwise-correct responses, no stuck futures; decode: "
          "0 steady-state recompiles, slots backfilled, page pool "
          "reclaimed; hotswap: applied with 0 recompiles, readiness "
          "green, bitwise per version; compile cache: warm start >=5x "
          "with 0 fresh compiles, bitwise; wfq: quiet model isolated "
          "from a saturating co-tenant)")
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
