#!/usr/bin/env python
"""CI gate: the Pallas kernel tier must be numerically safe and actually
engaged — under ``JAX_PLATFORMS=cpu`` (interpret mode), the same gate a
TPU deployment relies on.

Asserts, in order:

1.  **Fused Adam trajectory** — the one-pass kernel tracks the unfused
    ``Adam.update_param`` within 1e-6 over a multi-step trajectory on
    ragged (pad-exercising) shapes;
2.  **MLP train parity + engagement** — the bench MLP trains with the
    tier ON vs OFF to matching loss trajectories (1e-4 relative), the
    compile record names the selected kernels (fused epilogues + fused
    Adam), and 0 recompiles happen after warmup with the tier on;
3.  **BERT-tiny realization** — ``Program.analyze()`` on the bench
    BERT-tiny static training program marks >= 1 fusion candidate
    ``realized`` with a kernel name, and the executor's record agrees;
4.  **Clean composite fallback** — a program whose shapes fail the
    kernel gates (non-tile-aligned widths, AdamW) realizes NOTHING and
    reproduces the tier-off run bitwise;
5.  **Decode parity** — ``GenerationEngine`` decode over the Pallas
    paged-attention kernel emits bitwise-identical tokens to the gather
    reference (dyadic model), with 0 recompiles after warmup;
6.  **OFF contract** — with ``FLAGS_use_pallas_kernels`` disabled, zero
    Pallas kernels are selected anywhere.

Exit 0 on success, 1 with reasons on any violation.
"""
from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

BERT_TINY = dict(vocab=1000, hidden=128, layers=2, heads=4, ffn=512,
                 seq=128, batch=8)


def _build_mlp(hidden=128, depth=3, activation="relu", out_width=128):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import optimizer

    paddle.seed(7)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, hidden], "float32")
        y = paddle.static.data("y", [None, out_width], "float32")
        h = x
        for _ in range(depth):
            h = paddle.static.nn.fc(h, hidden, activation=activation)
        pred = paddle.static.nn.fc(h, out_width)
        loss = F.mse_loss(pred, y)
        optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main, loss


def _train(main, loss, feed, steps):
    import numpy as np

    import paddle_tpu as paddle
    exe = paddle.static.Executor()
    losses = []
    for _ in range(steps):
        losses.append(float(np.asarray(
            exe.run(main, feed=feed, fetch_list=[loss])[0])))
    cc = exe.compile_count
    exe.close()
    return losses, cc


def _check_fused_adam(failures):
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.ops.pallas.fused_adam import fused_adam_update
    from paddle_tpu.optimizer.optimizer import Adam

    r = np.random.RandomState(0)
    opt = Adam(learning_rate=1e-3)
    for shape in [(33,), (257, 3), (128, 128)]:
        p = jnp.asarray(r.randn(*shape), jnp.float32)
        s = opt.init_slots(p)
        pf, mf, vf = p, s["m"], s["v"]
        pr, sr = p, dict(s)
        for step in range(1, 9):
            g = jnp.asarray(r.randn(*shape), jnp.float32)
            pf, mf, vf = fused_adam_update(pf, g, mf, vf, 1e-3,
                                           float(step), interpret=True)
            pr, sr = opt.update_param(
                pr, g, sr, jnp.asarray(1e-3, jnp.float32),
                jnp.asarray(step, jnp.float32))
        err = float(jnp.max(jnp.abs(pf - pr)))
        if err > 1e-6:
            failures.append(
                f"fused Adam trajectory drifted {err:.2e} > 1e-6 on "
                f"shape {shape} after 8 steps")


def run_checks():
    import jax.numpy as jnp
    import numpy as np

    import bench
    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.core.flags import get_flag, set_flags
    from paddle_tpu.observability import explain_compiles
    from paddle_tpu.ops import attention as _attn
    from paddle_tpu.ops.pallas.support import kernel_selections

    failures: list = []
    _check_fused_adam(failures)

    prev = {k: get_flag(k) for k in ("use_pallas_kernels",
                                     "pallas_interpret")}
    paddle.enable_static()
    try:
        r = np.random.RandomState(0)
        feed = {"x": jnp.asarray(r.standard_normal(
                    (32, 128)).astype(np.float32)),
                "y": jnp.asarray(r.standard_normal(
                    (32, 128)).astype(np.float32))}

        # -- 6. OFF contract: no Pallas selection anywhere ------------
        set_flags({"use_pallas_kernels": False, "pallas_interpret": True})
        before_calls = dict(kernel_selections)
        main, loss = _build_mlp()
        off_losses, _ = _train(main, loss, feed, 6)
        if dict(kernel_selections) != before_calls:
            failures.append(
                f"FLAGS_use_pallas_kernels=False still selected Pallas "
                f"kernels: {kernel_selections} vs {before_calls}")

        # -- 2. MLP parity + engagement + 0 recompiles ----------------
        set_flags({"use_pallas_kernels": True, "pallas_interpret": True})
        main_on, loss_on = _build_mlp()
        on_losses, cc = _train(main_on, loss_on, feed, 6)
        scale = max(abs(v) for v in off_losses) or 1.0
        drift = max(abs(a - b) for a, b in zip(on_losses, off_losses))
        if drift > 1e-4 * max(scale, 1.0):
            failures.append(
                f"MLP tier-on loss trajectory drifted {drift:.2e} from "
                f"tier-off (losses {on_losses} vs {off_losses})")
        if cc != 1:
            failures.append(
                f"MLP with the tier on recompiled: {cc} compiles for "
                f"one feed signature (expected 1 -> 0 after warmup)")
        recs = [rec for rec in explain_compiles("executor")["records"]
                if rec["identity"] == main_on._serial]
        kernels = recs[-1].get("kernels", []) if recs else []
        if not any(k.startswith("fused_epilogue") for k in kernels):
            failures.append(
                f"no fused epilogue on the MLP compile record: {kernels}")
        if "fused_adam" not in kernels:
            failures.append(
                f"fused Adam not selected on the MLP compile record: "
                f"{kernels}")

        # -- 3. BERT-tiny: >= 1 candidate realized --------------------
        bmain, bloss, bfeeds = bench.build_bert_static(**BERT_TINY)
        bfeed = bfeeds(np.random.RandomState(1))
        rep = bmain.analyze(fetch_list=[bloss], top_k=None)
        realized = [c for c in rep.fusion_candidates if c.get("realized")]
        if not realized:
            failures.append(
                "BERT-tiny: Program.analyze() marks no fusion candidate "
                "realized with the tier on")
        _, bcc = _train(bmain, bloss, bfeed, 3)
        brecs = [rec for rec in explain_compiles("executor")["records"]
                 if rec["identity"] == bmain._serial]
        bkernels = brecs[-1].get("kernels", []) if brecs else []
        if not any(k.startswith("fused_epilogue") for k in bkernels):
            failures.append(
                f"BERT-tiny compile record names no fused epilogue: "
                f"{bkernels}")
        if bcc != 1:
            failures.append(f"BERT-tiny recompiled: {bcc} compiles")

        # -- 4. gated-out shapes: clean composite fallback, bitwise --
        # width 100 fails the N%128 tile gate; AdamW (decoupled decay)
        # fails the fused-Adam eligibility -> tier-on == tier-off
        # bitwise because NOTHING may be selected
        import paddle_tpu.nn.functional as F
        from paddle_tpu import optimizer as _opt

        def build_gated():
            paddle.seed(9)
            m = paddle.static.Program()
            with paddle.static.program_guard(m):
                x = paddle.static.data("x", [None, 100], "float32")
                y = paddle.static.data("y", [None, 1], "float32")
                h = paddle.static.nn.fc(x, 100, activation="relu")
                l = F.mse_loss(paddle.static.nn.fc(h, 1), y)
                _opt.AdamW(learning_rate=1e-3,
                           weight_decay=0.01).minimize(l)
            return m, l

        gfeed = {"x": jnp.asarray(r.standard_normal(
                     (16, 100)).astype(np.float32)),
                 "y": jnp.asarray(r.standard_normal(
                     (16, 1)).astype(np.float32))}
        gm, gl = build_gated()
        g_on, _ = _train(gm, gl, gfeed, 4)
        grecs = [rec for rec in explain_compiles("executor")["records"]
                 if rec["identity"] == gm._serial]
        gk = grecs[-1].get("kernels", []) if grecs else []
        if gk:
            failures.append(
                f"gated-out program still selected kernels: {gk}")
        set_flags({"use_pallas_kernels": False})
        gm2, gl2 = build_gated()
        g_off, _ = _train(gm2, gl2, gfeed, 4)
        if g_on != g_off:
            failures.append(
                f"gated-out fallback is not bitwise: {g_on} vs {g_off}")

        # -- 5. decode parity over the paged kernel -------------------
        def decode_tokens(tier_on):
            set_flags({"use_pallas_kernels": tier_on,
                       "pallas_interpret": tier_on})
            _attn.register_paged_attention_kernel(None)
            model = serving.PagedDecoderLM(
                vocab_size=64, hidden=256, num_layers=2, num_heads=2,
                seed=5, dyadic=True)
            eng = serving.GenerationEngine(model, num_slots=2,
                                           page_size=8, max_context=64,
                                           num_pages=32)
            eng.warmup()
            outs = [eng.generate_sync([1, 2, 3], max_new_tokens=5,
                                      timeout=300),
                    eng.generate_sync([7, 8], max_new_tokens=5,
                                      timeout=300)]
            rc = eng.stats()["recompiles_after_warmup"]
            eng.close()
            _attn.register_paged_attention_kernel(None)
            return outs, rc

        ref_toks, _ = decode_tokens(False)
        calls0 = kernel_selections.get("paged_attention", 0)
        pal_toks, rc = decode_tokens(True)
        if kernel_selections.get("paged_attention", 0) <= calls0:
            failures.append("paged-attention kernel never selected "
                            "with the tier on")
        if pal_toks != ref_toks:
            failures.append(
                f"paged decode tokens diverge from the gather "
                f"reference: {pal_toks} vs {ref_toks}")
        if rc:
            failures.append(
                f"decode with the paged kernel recompiled after "
                f"warmup: {rc}")
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()
        _attn.register_paged_attention_kernel(None)
        set_flags(prev)
    return failures


def main(argv=None):
    failures = run_checks()
    if failures:
        for f in failures:
            print(f"kernel_smoke: FAIL: {f}")
        return 1
    print("kernel_smoke: PASS — fused Adam 1e-6 trajectory, MLP/"
          "BERT-tiny candidates realized with 0 recompiles after "
          "warmup, bitwise composite fallback on gated-out shapes, "
          "bitwise paged-decode parity, zero Pallas selections with "
          "the tier off")
    return 0


if __name__ == "__main__":
    sys.exit(main())
