#!/usr/bin/env python
"""Public-API compatibility checker.

Reference: ``tools/check_api_compatible.py`` + ``print_signatures.py`` —
CI diffs every public API signature against the develop branch and
blocks silent breaking changes.

Here the recorded truth is ``tools/api_spec.json`` (checked in):
  python tools/check_api_compatible.py --dump     # refresh the spec
  python tools/check_api_compatible.py            # verify current API

Compatibility rules (reference semantics):
- removing a public name is a BREAK;
- removing a parameter, renaming one, or reordering existing
  positionals is a BREAK;
- removing ``*args``/``**kwargs`` (VAR_POSITIONAL/VAR_KEYWORD) is a
  BREAK — callers passing extra positionals/keywords stop working;
- ADDING a trailing parameter with a default, or adding new public
  names, is allowed.
"""
from __future__ import annotations

import argparse
import importlib
import inspect
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # run as `python tools/check_api_compatible.py`
    sys.path.insert(0, _REPO)

SPEC_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "api_spec.json")

# the public import surface a user of the reference would reach for
_MODULES = [
    "paddle_tpu", "paddle_tpu.nn", "paddle_tpu.nn.functional",
    "paddle_tpu.nn.initializer", "paddle_tpu.optimizer",
    "paddle_tpu.optimizer.lr", "paddle_tpu.io", "paddle_tpu.amp",
    "paddle_tpu.jit", "paddle_tpu.static", "paddle_tpu.distributed",
    "paddle_tpu.distributed.fleet", "paddle_tpu.metric",
    "paddle_tpu.vision.transforms", "paddle_tpu.vision.datasets",
    "paddle_tpu.vision.ops", "paddle_tpu.text.datasets",
    "paddle_tpu.distribution", "paddle_tpu.profiler",
    "paddle_tpu.observability",
    "paddle_tpu.inference", "paddle_tpu.serving",
    "paddle_tpu.ops.pallas",
    "paddle_tpu.quantization",
    "paddle_tpu.utils", "paddle_tpu.onnx",
]


def _sig_of(obj):
    try:
        sig = inspect.signature(obj)
    except (ValueError, TypeError):
        return None
    return [
        {"name": p.name, "kind": p.kind.name,
         "has_default": p.default is not inspect.Parameter.empty}
        for p in sig.parameters.values()
    ]


def collect():
    spec = {}
    for mod_name in _MODULES:
        try:
            mod = importlib.import_module(mod_name)
        except ImportError as e:  # a missing module IS an API break
            spec[mod_name] = {"__import_error__": str(e)}
            continue
        entry = {}
        for name in sorted(dir(mod)):
            if name.startswith("_"):
                continue
            obj = getattr(mod, name)
            if inspect.ismodule(obj):
                continue
            if getattr(obj, "__module__", "") == "typing":
                # typing re-exports (Any, Optional, ...) leaked into a
                # namespace: their introspection shape varies by Python
                # version, producing spurious class<->function "breaks"
                continue
            if inspect.isclass(obj):
                entry[name] = {"type": "class",
                               "init": _sig_of(obj.__init__)}
                # public methods ANYWHERE in the MRO: moving a method to
                # a base class is not an API change
                for m in sorted(dir(obj)):
                    if m.startswith("_"):
                        continue
                    f = getattr(obj, m, None)
                    if inspect.isfunction(f) or inspect.ismethod(f):
                        entry[f"{name}.{m}"] = {"type": "method",
                                                "sig": _sig_of(f)}
            elif callable(obj):
                entry[name] = {"type": "function", "sig": _sig_of(obj)}
            else:
                entry[name] = {"type": "value"}
        spec[mod_name] = entry
    return spec


def _params_compatible(old, new, where, problems):
    if old is None or new is None:
        return
    old_named = [p for p in old if p["kind"] in
                 ("POSITIONAL_ONLY", "POSITIONAL_OR_KEYWORD",
                  "KEYWORD_ONLY")]
    # removing *args / **kwargs breaks every caller that passed extra
    # positionals/keywords, even though no NAMED parameter disappeared
    for var_kind, spelled in (("VAR_POSITIONAL", "*args"),
                              ("VAR_KEYWORD", "**kwargs")):
        if any(p["kind"] == var_kind for p in old) and not any(
                p["kind"] == var_kind for p in new):
            name = next(p["name"] for p in old if p["kind"] == var_kind)
            problems.append(
                f"{where}: variadic parameter {spelled} "
                f"({name!r}) removed")
    new_by_name = {p["name"]: p for p in new}
    new_order = [p["name"] for p in new]
    for i, p in enumerate(old_named):
        if p["name"] not in new_by_name:
            problems.append(f"{where}: parameter {p['name']!r} removed")
            continue
        q = new_by_name[p["name"]]
        if (p["kind"] in ("POSITIONAL_ONLY", "POSITIONAL_OR_KEYWORD")
                and q["kind"] == "KEYWORD_ONLY"):
            problems.append(
                f"{where}: parameter {p['name']!r} became keyword-only")
        if p["has_default"] and not q["has_default"]:
            problems.append(
                f"{where}: parameter {p['name']!r} lost its default")
        if p["kind"] != "KEYWORD_ONLY":
            # positional order of pre-existing params must not change
            old_pos = [q["name"] for q in old_named
                       if q["kind"] != "KEYWORD_ONLY"]
            new_pos = [n for n in new_order
                       if n in set(old_pos)
                       and new_by_name[n]["kind"] != "KEYWORD_ONLY"]
            if [n for n in old_pos if n in set(new_pos)] != new_pos:
                problems.append(f"{where}: positional order changed")
                break
    for p in new:
        if (p["name"] not in {q["name"] for q in old}
                and not p["has_default"]
                and p["kind"] not in ("VAR_POSITIONAL", "VAR_KEYWORD")):
            problems.append(
                f"{where}: new parameter {p['name']!r} has no default")


def compare(spec, current):
    problems = []
    for mod, names in spec.items():
        cur = current.get(mod)
        if cur is None or "__import_error__" in (cur or {}):
            problems.append(f"{mod}: module no longer imports")
            continue
        if "__import_error__" in names:
            continue  # was broken when dumped; nothing to hold it to
        for name, info in names.items():
            if name not in cur:
                problems.append(f"{mod}.{name}: removed")
                continue
            now = cur[name]
            if info["type"] != now["type"]:
                problems.append(
                    f"{mod}.{name}: {info['type']} -> {now['type']}")
                continue
            if info["type"] == "class":
                _params_compatible(info.get("init"), now.get("init"),
                                   f"{mod}.{name}.__init__", problems)
            elif info["type"] in ("function", "method"):
                _params_compatible(info.get("sig"), now.get("sig"),
                                   f"{mod}.{name}", problems)
    return problems


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dump", action="store_true",
                    help="write the current API to the spec file")
    ap.add_argument("--spec", default=SPEC_PATH)
    args = ap.parse_args(argv)

    current = collect()
    if args.dump:
        with open(args.spec, "w") as f:
            json.dump(current, f, indent=1, sort_keys=True)
        n = sum(len(v) for v in current.values())
        print(f"wrote {n} public APIs across {len(current)} modules to "
              f"{args.spec}")
        return 0

    if not os.path.exists(args.spec):
        print(f"no spec at {args.spec}; run with --dump first",
              file=sys.stderr)
        return 2
    with open(args.spec) as f:
        spec = json.load(f)
    problems = compare(spec, current)
    if problems:
        print("API compatibility problems:")
        for p in problems:
            print("  -", p)
        return 1
    n = sum(len(v) for v in spec.values())
    print(f"API compatible: {n} recorded public APIs intact")
    return 0


if __name__ == "__main__":
    sys.exit(main())
