#!/usr/bin/env python
"""Chaos smoke test: training and serving under injected faults.

Scenarios (``--scenario``, default ``all``):

- ``training`` — :func:`paddle_tpu.testing.chaos.main`: a tiny train
  loop twice (fault-free vs under the canned chaos spec: checkpoint-fs
  write flakes, one DataLoader worker hard-killed mid-epoch, SIGTERM
  mid-training); fails unless the faulted run resumes to completion
  with bitwise-identical final parameters.
- ``serving`` — :func:`paddle_tpu.testing.chaos.serving_main`: the
  dynamic-batching engine under injected dispatcher flakes, queue-full
  shedding, and in-queue deadline expiry; fails unless every accepted
  request gets a bitwise-correct response or a clean shed/deadline
  error — never a hang or a wrong answer.
- ``generation`` — :func:`paddle_tpu.testing.chaos.generation_main`:
  the continuous-batching GenerationEngine under injected decode-step
  flakes and a mid-generation deadline expiry; fails unless every
  admitted sequence streams to a clean finish with tokens bitwise-
  identical to a fault-free serial run (admission order must not leak
  into results) or errors cleanly, with the page pool fully reclaimed.
- ``reshard`` — :func:`paddle_tpu.testing.chaos.reshard_main`: a
  fleet-sharded static training run on mesh ``{dp: 8}`` killed mid-run
  by an injected ``executor.run`` fault, then restored from its
  per-shard digest-verified SnapshotStore checkpoint onto mesh
  ``{dp: 2}``; fails unless the restore is bitwise and the
  post-restore loss trajectory matches the uninterrupted run
  (ROADMAP item 1's success criterion).
- ``supervise`` — :func:`paddle_tpu.testing.chaos.supervise_main`: one
  TrainingSupervisor-managed job survives an injected mid-step hang
  (watchdog misses heartbeats → SIGTERM→SIGKILL → resume from the
  step-cadence snapshot) and then an injected hard crash whose
  replacement sees only 4 of the original 8 devices (reshard-restore
  restart); fails unless the assembled loss trajectory matches the
  fault-free run with zero manual intervention and the kill, restart
  reasons and snapshot resumes are visible in ``supervisor.*`` stats,
  the exit history and the kill-time flight dump.
- ``swap`` — :func:`paddle_tpu.testing.chaos.swap_main`: digest-verified
  zero-downtime weight hot swap under fire — a WeightWatcher applies
  three live swaps to an InferenceEngine and a GenerationEngine while
  concurrent clients hammer both, then one deliberately corrupted
  snapshot must be rejected with the old weights still serving, and a
  ServingSupervisor-managed replica hard-crashes mid-traffic and is
  restarted; fails unless every response is bitwise-correct for its
  weights version, readiness stays green through every applied swap,
  the hot paths never recompile, no future is stranded, the page pool
  is reclaimed, and clients ride through the restart via the reconnect
  path.
- ``registry`` — :func:`paddle_tpu.testing.chaos.registry_main`: the
  multi-model control plane under fire — two models behind one
  ModelRegistry/HTTP plane while clients route to both: a live weight
  swap on model A (bitwise per version, B unmoved), model B unloaded
  mid-traffic (clean 404s, drained, no stranded futures) then
  reloaded, generation pages fully reclaimed at unload, and a
  supervised two-model replica hard-crash with clients riding through
  and both models bitwise after the restart.
- ``anomaly`` — :func:`paddle_tpu.testing.chaos.anomaly_main`: the
  data-plane counterpart on mesh ``{dp: 8}`` with int8+error-feedback
  grad_comm: injected NaN batches, a non-finite gradient bucket, one
  corrupted int8 wire payload and a poisoned-feed burst; fails unless
  the in-graph anomaly sentry skips every poisoned step as a bitwise
  no-op, the burst escalates to a batch quarantine and a snapshot
  rollback, the applied-step loss trajectory ends at parity with the
  fault-free run with zero manual intervention, and the
  skips/quarantines/rollbacks are all asserted from ``anomaly.*``
  stats and the annotated rollback flight dump.

- ``fleet`` — :func:`paddle_tpu.testing.chaos.fleet_main`: fleet
  observability under fire — a supervised generation replica spooling
  telemetry (``FLAGS_obs_spool_dir`` staged into the child env by the
  supervisor) hard-crashes mid-traffic while a client with a pinned
  trace id keeps hitting ``/generate``; fails unless the spool holds
  parent + BOTH child incarnations, the merged chrome-trace has
  aligned named lanes for all three plus the supervisor restart event
  with the crash reason, every fleet-Prometheus sample carries a
  ``{proc=...}`` label, and the pinned request's span tree assembles
  into ONE connected component across the process hop.

Usage::

    python tools/chaos_smoke.py [--scenario all|training|serving|generation|swap|registry|reshard|supervise|anomaly|fleet]
                                [--epochs 4] [--verbose]

CI treats a non-zero exit as a robustness regression.  The same flows
run in-process from tests/test_fault_tolerance.py and
tests/test_serving.py.
"""
from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    ap.add_argument("--scenario", default="all",
                    choices=["all", "training", "serving", "generation",
                             "swap", "registry", "reshard", "supervise",
                             "anomaly", "fleet"])
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    if args.scenario in ("reshard", "supervise", "anomaly"):
        # these drills need a multi-device mesh; set env BEFORE
        # anything initialises jax.  Scoped to these scenarios only —
        # the other drills must keep exercising the host's real device
        # config (under --scenario all each drill runs in a subprocess).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from paddle_tpu.testing import chaos
    rc = 0
    if args.scenario in ("all", "training"):
        rc |= chaos.main(epochs=args.epochs, verbose=args.verbose)
    if args.scenario in ("all", "serving"):
        rc |= chaos.serving_main(verbose=args.verbose)
    if args.scenario in ("all", "generation"):
        rc |= chaos.generation_main(verbose=args.verbose)
    if args.scenario in ("all", "swap"):
        rc |= chaos.swap_main(verbose=args.verbose)
    if args.scenario in ("all", "registry"):
        rc |= chaos.registry_main(verbose=args.verbose)
    if args.scenario == "reshard":
        rc |= chaos.reshard_main(verbose=args.verbose)
    if args.scenario == "supervise":
        rc |= chaos.supervise_main(verbose=args.verbose)
    if args.scenario == "anomaly":
        rc |= chaos.anomaly_main(verbose=args.verbose)
    if args.scenario == "fleet":
        rc |= chaos.fleet_main(verbose=args.verbose)
    if args.scenario == "all":
        import subprocess
        for sub_scenario in ("reshard", "supervise", "anomaly", "fleet"):
            sub = [sys.executable, os.path.abspath(__file__),
                   "--scenario", sub_scenario]
            if args.verbose:
                sub.append("--verbose")
            rc |= subprocess.run(sub).returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
