#!/usr/bin/env python
"""Chaos smoke test: preemption-safe training under injected faults.

Runs :func:`paddle_tpu.testing.chaos.main` — a tiny train loop twice
(fault-free vs under the canned chaos spec: checkpoint-fs write flakes,
one DataLoader worker hard-killed mid-epoch, SIGTERM mid-training) —
and exits non-zero unless the faulted run resumes to completion with
bitwise-identical final parameters.

Usage::

    python tools/chaos_smoke.py [--epochs 4] [--verbose]

CI treats a non-zero exit as a robustness regression.  The same flow
runs in-process from tests/test_fault_tolerance.py.
"""
from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    from paddle_tpu.testing import chaos
    return chaos.main(epochs=args.epochs, verbose=args.verbose)


if __name__ == "__main__":
    sys.exit(main())
