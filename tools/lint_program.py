#!/usr/bin/env python
"""Build-and-verify CLI: import a module, analyse its static Programs,
lint its to_static functions.

Reference: the spirit of tools/check_file_diff_approvals.sh +
dygraph_to_static's error tier, as a standalone pre-flight: run this
over a training script BEFORE burning a TPU slice on a compile that was
always going to fail.

Usage:
  python tools/lint_program.py my_train_script.py
  python tools/lint_program.py mypkg.model --fetch loss
  python tools/lint_program.py script.py --lint-all --strict
  python tools/lint_program.py script.py --format json   # CI annotation
  # SPMD shardcheck against an ABSTRACT mesh (zero devices needed):
  python tools/lint_program.py script.py --mesh-shape dp=4,mp=2 \
      --sharding-rules '[["w_0$", [null, "mp"]], [".*", []]]'

The module is imported under ``paddle.enable_static()`` with
``FLAGS_static_verify`` on (so recorded ops carry file:line anchors); a
reference-style script therefore builds its Programs at import time.
Every ``static.Program`` found in the module namespace is run through
``static.analysis.check`` — the verifier passes AND the TPU-readiness
hazard passes (host-transfer, wide-dtype, donation-alias); every
``jit.to_static`` function (and, with ``--lint-all``, every plain
module-level function) is run through the dy2static lint.
``--format json`` prints one machine-readable object (per-program and
per-function diagnostic records) instead of the text report.  Exit
status: 1 when any error-severity finding exists — verifier errors and
analyzer hazards alike (warnings too with ``--strict``), else 0.
"""
from __future__ import annotations

import argparse
import importlib
import importlib.util
import os
import sys
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _import_target(target: str) -> types.ModuleType:
    if target.endswith(".py") or os.sep in target:
        path = os.path.abspath(target)
        name = os.path.splitext(os.path.basename(path))[0]
        spec = importlib.util.spec_from_file_location(name, path)
        if spec is None or spec.loader is None:
            raise ImportError(f"cannot load {target!r}")
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
        return mod
    return importlib.import_module(target)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="verify static Programs + lint dy2static hazards")
    ap.add_argument("module",
                    help="dotted module name or path to a .py file")
    ap.add_argument("--fetch", default="",
                    help="comma-separated Variable names used as fetch "
                         "roots for dead-code analysis on each Program")
    ap.add_argument("--lint-all", action="store_true",
                    help="lint every module-level function, not only "
                         "to_static-wrapped ones")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on warnings too")
    ap.add_argument("--no-verify-flag", action="store_true",
                    help="do not force FLAGS_static_verify during "
                         "import (ops then record no source anchors)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="'json' prints one machine-readable object "
                         "(for CI annotation) instead of the report")
    ap.add_argument("--mesh-shape", default="",
                    help="abstract mesh shape ('dp=4,mp=2' or a bare "
                         "device count) — runs the SPMD shardcheck "
                         "passes (plan coverage, collective "
                         "choreography, device-varying taint, "
                         "wire-byte audit) against it, no devices "
                         "needed")
    ap.add_argument("--sharding-rules", default="",
                    help="JSON list of [regex, partition-spec] pairs "
                         "(spec in spec_to_json form, e.g. "
                         "'[[\"w_0$\", [null, \"mp\"]], [\".*\", []]]') "
                         "resolved per-param for --mesh-shape linting")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.jit.lint import lint
    from paddle_tpu.jit.static_function import StaticFunction
    from paddle_tpu.static import Program, analysis
    from paddle_tpu.static.analysis import Diagnostic

    if not args.no_verify_flag:
        set_flags({"FLAGS_static_verify": True})
    paddle.enable_static()
    try:
        mod = _import_target(args.module)
    except Exception as e:  # noqa: BLE001 - report, don't traceback
        print(f"error: importing {args.module!r} failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2

    mesh_shape = None
    sharding_rules = None
    if args.mesh_shape:
        from paddle_tpu.static.analysis import parse_mesh_shape
        try:
            mesh_shape = parse_mesh_shape(args.mesh_shape)
        except ValueError as e:
            print(f"error: --mesh-shape: {e}", file=sys.stderr)
            return 2
    if args.sharding_rules:
        import json as _json
        from paddle_tpu.distributed.sharding import spec_from_json
        try:
            sharding_rules = [(pat, spec_from_json(spec)) for pat, spec
                              in _json.loads(args.sharding_rules)]
        except (ValueError, TypeError) as e:
            print(f"error: --sharding-rules is not a JSON list of "
                  f"[regex, spec] pairs: {e}", file=sys.stderr)
            return 2

    fetch = [n for n in args.fetch.split(",") if n]
    resolved_somewhere = set()
    n_err = n_warn = n_info = 0
    as_json = args.format == "json"
    report = {"programs": [], "functions": [], "unresolved_fetch": []}

    def tally(sev):
        nonlocal n_err, n_warn, n_info
        if sev == Diagnostic.ERROR:
            n_err += 1
        elif sev == Diagnostic.INFO:
            n_info += 1
        else:
            n_warn += 1

    # -- Programs ---------------------------------------------------------
    programs = [(nm, v) for nm, v in sorted(vars(mod).items())
                if isinstance(v, Program)]
    default_main = paddle.static.default_main_program()
    if default_main.nodes and not any(p is default_main
                                      for _, p in programs):
        programs.append(("<default_main_program>", default_main))
    for nm, prog in programs:
        # each program only sees the fetch names IT defines (one --fetch
        # list serves all programs); names resolving in NO program are
        # reported as errors after the loop
        graph = analysis.DefUseGraph(prog)
        roots = [f for f in fetch
                 if graph.resolve_fetch(f) is not None]
        resolved_somewhere.update(roots)
        diags = analysis.check(prog, fetch_list=roots or None,
                               mesh_shape=mesh_shape,
                               sharding_rules=sharding_rules)
        report["programs"].append({
            "name": nm, "serial": prog._serial, "ops": len(prog.nodes),
            "diagnostics": [d.to_dict() for d in diags]})
        if not as_json:
            print(f"Program {nm!r} (#{prog._serial}, "
                  f"{len(prog.nodes)} ops): {len(diags)} finding(s)")
            for d in diags:
                print(f"  {d}")
        for d in diags:
            tally(d.severity)

    # -- functions --------------------------------------------------------
    fns = []
    for nm, v in sorted(vars(mod).items()):
        if isinstance(v, StaticFunction):
            fns.append((nm, v))
        elif args.lint_all and isinstance(v, types.FunctionType) \
                and v.__module__ == mod.__name__:
            fns.append((nm, v))
    for nm, fn in fns:
        diags = lint(fn)
        report["functions"].append({
            "name": nm, "diagnostics": [d.to_dict() for d in diags]})
        if not as_json:
            print(f"function {nm!r}: {len(diags)} finding(s)")
            for d in diags:
                print(f"  {d}")
        for d in diags:
            tally(d.severity)

    for f in fetch:
        if f not in resolved_somewhere:
            report["unresolved_fetch"].append(f)
            if not as_json:
                print(f"error: --fetch {f!r} does not name a Variable "
                      f"in any analysed Program (typo?); dead-code "
                      f"analysis ran without it")
            n_err += 1

    if not programs and not fns and not as_json:
        print("nothing to analyse: module defines no static.Program and "
              "no to_static function (try --lint-all)")

    report.update(errors=n_err, warnings=n_warn, infos=n_info)
    if as_json:
        import json
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(f"lint_program: {n_err} error(s), {n_warn} warning(s), "
              f"{n_info} info(s)")
    return 1 if (n_err or (args.strict and n_warn)) else 0


if __name__ == "__main__":
    sys.exit(main())
