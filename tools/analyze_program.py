#!/usr/bin/env python
"""Static cost/memory analysis CLI: import a module, price its Programs.

The quantitative companion of tools/lint_program.py: where lint answers
"will this compile?", this answers "what will it cost?" — per-op FLOPs
and byte volumes (with the explicit `unmodeled` bucket), donation-aware
peak-memory bounds, a roofline prediction per chip spec, TPU-readiness
hazards, and the top fusion candidates by HBM traffic saved — all
before burning a TPU slice on the real compile.

Usage:
  python tools/analyze_program.py train_script.py --fetch loss
  python tools/analyze_program.py train_script.py --batch-size 32
  python tools/analyze_program.py mypkg.model --format json
  python tools/analyze_program.py s.py --feed-shape x=32x128 --chip v5e

The module is imported under ``paddle.enable_static()`` with
``FLAGS_static_anchors`` on (the cheap anchor-only flag — no per-run
verification), so reports carry ``file:line`` anchors.  Exit status: 1
when any error-severity hazard exists (warnings too with ``--strict``),
else 0.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _parse_feed_shape(spec: str):
    """NAME=2x3x4 -> ("NAME", (2, 3, 4))."""
    name, _, dims = spec.partition("=")
    if not name or not dims:
        raise argparse.ArgumentTypeError(
            f"--feed-shape wants NAME=DxDxD, got {spec!r}")
    try:
        shape = tuple(int(d) for d in dims.replace(",", "x").split("x"))
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--feed-shape dims must be integers, got {spec!r}")
    return name, shape


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="static cost/memory model + TPU-readiness report "
                    "for every Program a module builds")
    ap.add_argument("module",
                    help="dotted module name or path to a .py file")
    ap.add_argument("--fetch", default="",
                    help="comma-separated Variable names used as fetch "
                         "roots (liveness + fusion-candidate pruning)")
    ap.add_argument("--batch-size", type=int, default=None,
                    help="substitute every dynamic feed dim (declared "
                         "None/-1) and re-derive all avals")
    ap.add_argument("--feed-shape", action="append", default=[],
                    type=_parse_feed_shape, metavar="NAME=DxDxD",
                    help="exact shape for one feed (repeatable); "
                         "overrides --batch-size for that feed")
    ap.add_argument("--chip", default=None,
                    help="one roofline spec (cpu/v4/v5e/v5p); default: "
                         "the whole table")
    ap.add_argument("--top-k", type=int, default=5,
                    help="fusion candidates to rank (default 5)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--per-op", dest="per_op", action="store_true",
                    help="print the FULL per-op table (text format "
                         "truncates to 40 rows by default)")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero on warning-severity hazards too")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.static import Program
    from paddle_tpu.static.analysis import Diagnostic

    set_flags({"FLAGS_static_anchors": True})
    paddle.enable_static()
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        from lint_program import _import_target
        mod = _import_target(args.module)
    except Exception as e:  # noqa: BLE001 - report, don't traceback
        print(f"error: importing {args.module!r} failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    finally:
        sys.path.remove(os.path.join(_REPO, "tools"))

    programs = [(nm, v) for nm, v in sorted(vars(mod).items())
                if isinstance(v, Program)]
    default_main = paddle.static.default_main_program()
    if default_main.nodes and not any(p is default_main
                                      for _, p in programs):
        programs.append(("<default_main_program>", default_main))

    fetch = [n for n in args.fetch.split(",") if n]
    feed_shapes = dict(args.feed_shape) or None
    n_err = n_warn = 0
    out = {"programs": []}
    for nm, prog in programs:
        # analyze() resolves fetch names itself and silently drops ones
        # this program does not define (one --fetch list serves all)
        try:
            rep = prog.analyze(fetch_list=fetch or None,
                               feed_shapes=feed_shapes,
                               batch_size=args.batch_size,
                               chip=args.chip, top_k=args.top_k)
        except Exception as e:  # noqa: BLE001 - per-program isolation
            print(f"error: analyzing Program {nm!r} failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            n_err += 1
            continue
        out["programs"].append({"name": nm, "report": rep.to_dict()})
        for d in rep.hazards:
            if d.severity == Diagnostic.ERROR:
                n_err += 1
            elif d.severity == Diagnostic.WARNING:
                n_warn += 1
        if args.format == "text":
            print(f"== {nm} ==")
            print(rep.render(max_rows=None if args.per_op else 40))
            print()

    if not programs:
        if args.format == "text":
            print("nothing to analyse: module defines no static.Program")
    out.update(errors=n_err, warnings=n_warn)
    if args.format == "json":
        print(json.dumps(out, indent=1, sort_keys=True))
    elif programs:
        print(f"analyze_program: {n_err} error hazard(s), {n_warn} "
              f"warning hazard(s) across {len(programs)} program(s)")
    return 1 if (n_err or (args.strict and n_warn)) else 0


if __name__ == "__main__":
    sys.exit(main())
