#!/usr/bin/env python
"""Drift-report CLI for the runtime performance observatory.

`observability.perf_report()` is the in-process view; this CLI renders
the same report from wherever it was persisted or is being served:

- a **flight-recorder dump** (the ``perf`` block every black box
  embeds when the observatory was live at crash time),
- a **metrics JSONL** file (``observability.dump_metrics`` /
  ``hapi.callbacks.MetricsDump`` lines — the last line carrying a
  ``perf`` block wins by default, or ``--line N`` picks a literal
  line index, negatives Python-style: ``--line -1`` is the actual
  last line even when it has no perf block),
- a **live serving server** (``GET /perf`` on the HTTP front-end).

Each source also carries the SLO evaluation taken at the same moment,
which is printed below the drift table (``--json`` emits the raw
report object instead).

Usage:
  python tools/perf_report.py flight_record.json
  python tools/perf_report.py metrics.jsonl [--line N]
  python tools/perf_report.py http://127.0.0.1:8000
  python tools/perf_report.py ... --json

Exit status: 1 when the source carries no perf block (observatory was
never enabled) or any SLO rule is breached in the embedded evaluation,
else 0.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _load(source: str, line) -> dict:
    """-> {"perf": report|None, "slo": status|None} from any source."""
    if source.startswith(("http://", "https://")):
        import urllib.request
        url = source.rstrip("/") + "/perf"
        with urllib.request.urlopen(url, timeout=10.0) as r:
            return json.load(r)
    with open(source) as f:
        text = f.read()
    try:                            # JSONL (flight dumps are one line
        rows = [json.loads(ln)      # of JSON, so they parse here too)
                for ln in text.splitlines() if ln.strip()]
    except json.JSONDecodeError:    # pretty-printed single document
        rows = [json.loads(text)]
    if not rows:
        raise SystemExit(f"{source}: empty JSONL")
    if line is not None:            # explicit index, -1 = literal last
        try:
            row = rows[line]
        except IndexError:
            raise SystemExit(f"{source}: --line {line} out of range "
                             f"({len(rows)} lines)")
    else:                           # last line with a perf block, else last
        row = next((r for r in reversed(rows) if r.get("perf")), rows[-1])
    return {"perf": row.get("perf"), "slo": row.get("slo")}


def _render_slo(slo) -> str:
    if not slo:
        return "slo: no monitor installed"
    lines = [f"slo: {slo.get('status', '?')}"]
    for r in slo.get("rules", []):
        m = r.get("measured")       # non-finite values arrive as the
        b = r.get("burn", 0.0)      # JSON-safe string "inf"
        lines.append(
            f"  {r['name']}: measured "
            f"{'n/a' if m is None else m if isinstance(m, str) else round(m, 3)} "
            f"vs objective {r['objective']} over {r['window']}s "
            f"(burn {b if isinstance(b, str) else format(b, '.2f')}x"
            f"{', BREACHED' if r.get('breached') else ''})")
    for reason in slo.get("reasons", []):
        lines.append(f"  ! {reason}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render the perf observatory's predicted-vs-"
                    "measured drift report from a flight dump, a "
                    "metrics JSONL, or a live server URL")
    ap.add_argument("source",
                    help="flight_record.json | metrics.jsonl | "
                         "http://host:port")
    ap.add_argument("--line", type=int, default=None,
                    help="JSONL line index to render (negatives "
                         "Python-style; default: the last line "
                         "carrying a perf block)")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw report object, not text")
    args = ap.parse_args(argv)

    payload = _load(args.source, args.line)
    rep, slo = payload.get("perf"), payload.get("slo")
    # a live /perf with the observatory off answers {"enabled": false}
    # — that is "no report" for the exit contract, or a CI gate built
    # on this code silently passes with the observatory disabled
    has_rep = bool(rep) and bool(rep.get("enabled"))
    if args.json:
        print(json.dumps(payload, indent=2, default=str))
    else:
        from paddle_tpu.observability import render_perf_report
        if has_rep:
            print(render_perf_report(rep))
        else:
            print("perf observatory: no report in source (was "
                  "observability.enable_perf() on?)")
        print(_render_slo(slo))
    breached = bool(slo and slo.get("breached"))
    return 1 if (not has_rep or breached) else 0


if __name__ == "__main__":
    sys.exit(main())
