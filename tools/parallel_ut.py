#!/usr/bin/env python
"""Parallel unit-test runner.

Reference: the CI tier built around ``tools/parallel_UT_rule.py`` +
``paddle_build.sh`` — unit tests partitioned into parallel batches with
per-batch timeouts and a serial retry for flaky failures.

TPU-native notes: test shards are separate *processes* (each gets its
own jax runtime; the suite's conftest pins a virtual 8-device CPU mesh
per process, so shards don't fight over a chip), files are partitioned
by a static weight table (the long-pole files the suite is known to
have) + size heuristic, and failures rerun ONCE serially before being
reported — the reference CI's retry_unittests flow.

Measured honestly: the build sandbox exposes ONE core (nproc=1), so
``-j4`` there matches the serial 9-minute wall time — the speedup only
exists on multi-core CI machines (the default ``-j`` follows
``os.cpu_count()``).  The serial flaky-retry pass is load-tested either
way: timeslicing-induced failures rerun and pass.

Usage:
  python tools/parallel_ut.py [-j N] [--timeout S] [tests_dir] [-- <pytest args>]
  python tools/parallel_ut.py --collect-only       # show the shards
  python tools/parallel_ut.py tests -- -k smoke -x
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

# known long-pole files (seconds, rough): balance shards by these
_WEIGHTS = {
    "test_multihost.py": 60,
    "test_dataloader_mp.py": 60,
    "test_distributed.py": 120,
    "test_pipeline_memory.py": 90,
    "test_static.py": 45,
    "test_highlevel.py": 60,
    "test_text_e2e.py": 30,
    "test_pallas.py": 40,
    "test_optimizer.py": 30,
}
_DEFAULT_WEIGHT = 10


def discover(tests_dir: str):
    return sorted(f for f in os.listdir(tests_dir)
                  if f.startswith("test_") and f.endswith(".py"))


def partition(files, n_shards):
    """Greedy longest-processing-time partition by weight.

    Callers should over-partition (more shards than workers) and let the
    worker pool drain shards as they finish — dynamic balancing beats
    any static weight table; the weights only keep known long-pole files
    in separate shards."""
    weighted = sorted(files, key=lambda f: -_WEIGHTS.get(f, _DEFAULT_WEIGHT))
    shards = [[] for _ in range(n_shards)]
    loads = [0] * n_shards
    for f in weighted:
        i = loads.index(min(loads))
        shards[i].append(f)
        loads[i] += _WEIGHTS.get(f, _DEFAULT_WEIGHT)
    return [s for s in shards if s], loads


def run_shard(tests_dir, files, timeout, extra):
    cmd = [sys.executable, "-m", "pytest", "-q", *extra,
           *[os.path.join(tests_dir, f) for f in files]]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout)
        rc = proc.returncode
        if rc == 5:  # pytest: no tests collected (e.g. -k deselected all)
            rc = 0
        return rc, proc.stdout + proc.stderr
    except subprocess.TimeoutExpired as e:
        return 124, (e.stdout or "") + f"\nSHARD TIMEOUT after {timeout}s"


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("tests_dir", nargs="?",
                    default=os.path.join(os.path.dirname(
                        os.path.dirname(os.path.abspath(__file__))),
                        "tests"))
    ap.add_argument("-j", "--jobs", type=int,
                    default=min(4, os.cpu_count() or 1))
    ap.add_argument("--timeout", type=float, default=1200.0,
                    help="per-shard timeout (seconds)")
    ap.add_argument("--collect-only", action="store_true")
    ap.add_argument("--no-retry", action="store_true",
                    help="skip the serial flaky retry")
    raw = list(sys.argv[1:] if argv is None else argv)
    # everything after "--" passes to pytest verbatim (dash flags would
    # otherwise be eaten by argparse)
    pytest_args = []
    if "--" in raw:
        i = raw.index("--")
        raw, pytest_args = raw[:i], raw[i + 1:]
    args = ap.parse_args(raw)
    args.pytest_args = pytest_args

    files = discover(args.tests_dir)
    if not files:
        print(f"no test files under {args.tests_dir}", file=sys.stderr)
        return 2
    # over-partition ~3 shards per worker: the pool drains them as they
    # finish, so a mis-weighted long file can't serialize the whole run
    n_shards = max(args.jobs, min(len(files), args.jobs * 3))
    shards, loads = partition(files, n_shards)
    if args.collect_only:
        for i, (s, w) in enumerate(zip(shards, loads)):
            print(f"shard {i} (~{w}s): {' '.join(s)}")
        return 0

    t0 = time.time()
    import concurrent.futures as cf
    import re
    failed_files = []
    with cf.ThreadPoolExecutor(max_workers=args.jobs) as ex:
        futs = {ex.submit(run_shard, args.tests_dir, s, args.timeout,
                          args.pytest_args): s for s in shards}
        for fut in cf.as_completed(futs):
            shard = futs[fut]
            rc, out = fut.result()
            tail = "\n".join(out.strip().splitlines()[-3:])
            print(f"[shard {' '.join(shard[:2])}"
                  f"{'...' if len(shard) > 2 else ''}] rc={rc}\n{tail}\n")
            if rc != 0:
                # retry only the files pytest reports failing; fall back
                # to the whole shard when nothing parses (timeout/crash)
                bad = {os.path.basename(m) for m in re.findall(
                    r"(?:FAILED|ERROR)\s+(\S+?\.py)", out)}
                hit = [f for f in shard if f in bad]
                failed_files.extend(hit if hit else shard)

    if failed_files and not args.no_retry:
        # serial retry isolates flaky parallel interactions (the
        # reference CI's retry_unittests pass)
        print(f"retrying {len(failed_files)} file(s) serially...")
        still = []
        for f in failed_files:
            rc, out = run_shard(args.tests_dir, [f], args.timeout,
                                args.pytest_args)
            if rc != 0:
                still.append(f)
                print(f"FAIL {f}\n" + "\n".join(
                    out.strip().splitlines()[-15:]))
        failed_files = still

    dt = time.time() - t0
    if failed_files:
        print(f"FAILED ({dt:.0f}s): {' '.join(sorted(set(failed_files)))}")
        return 1
    print(f"OK: {len(files)} files in {dt:.0f}s across "
          f"{len(shards)} shards")
    return 0


if __name__ == "__main__":
    sys.exit(main())
