#!/usr/bin/env python
"""CI smoke gate for unified observability (sibling of bench_smoke.py /
chaos_smoke.py / serve_smoke.py).

Drives a short train + serve loop on CPU with tracing ON and exits
non-zero when the observability contract regresses:

1. **flight recorder** — an injected crash (``fault`` rule on
   ``executor.run``) must leave a readable flight-recorder dump that
   contains the injected fault event, the exception, and a full
   metrics snapshot.
2. **recompile attribution** — ``explain_compiles()`` must report ZERO
   unexplained compiles across the run; the executor's second feed
   signature must be attributed to ``new_feed_signature``; every
   Predictor compile in the serve loop must carry a named cause and
   their count must equal ``num_compiled_variants()`` (100%
   attribution).
3. **metrics export** — the HTTP ``/metrics`` endpoint must serve the
   Prometheus text exposition under an Accept: text/plain header
   (every line must parse) while keeping the JSON stats for default
   clients; the JSONL metrics dump must append parseable lines.
4. **trace integrity** — the chrome-trace export must satisfy the
   trace-event schema (name/ph/ts/pid/tid per event, dur on complete
   events) and carry span, op, compile and serving events.

Usage:  python tools/obs_smoke.py [--verbose]
"""
from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# metric_name{labels} value  — the text exposition grammar subset we emit
PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+naif]+$")

_CHROME_PH = {"X", "i", "C", "B", "E", "M"}


def _check_chrome_schema(trace: dict, failures: list) -> None:
    evs = trace.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        failures.append("chrome trace has no traceEvents")
        return
    for ev in evs:
        probs = []
        if not isinstance(ev.get("name"), str):
            probs.append("name")
        if ev.get("ph") not in _CHROME_PH:
            probs.append("ph")
        if not isinstance(ev.get("ts"), (int, float)) or ev["ts"] < 0:
            probs.append("ts")
        if not isinstance(ev.get("pid"), int):
            probs.append("pid")
        if not isinstance(ev.get("tid"), int):
            probs.append("tid")
        if ev.get("ph") == "X" and not (
                isinstance(ev.get("dur"), (int, float)) and ev["dur"] >= 0):
            probs.append("dur")
        if probs:
            failures.append(f"trace event violates schema ({probs}): "
                            f"{ev}")
            return


def run_checks(verbose: bool = False) -> list:
    """Returns a list of failure strings (empty = healthy)."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import inference, jit, observability as obs
    from paddle_tpu import optimizer, serving
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.serving.http import Client, ServingServer
    from paddle_tpu.testing import fault
    from paddle_tpu.testing.chaos import make_dyadic_model
    from paddle_tpu.utils import monitor

    failures: list = []
    workdir = tempfile.mkdtemp(prefix="obs_smoke_")
    obs.reset_compiles()
    tracer = obs.enable(capacity=8192)
    flight = os.path.join(workdir, "flight_record.json")
    obs.install_flight_recorder(path=flight)
    try:
        # -- short static train loop (two feed signatures) ----------------
        paddle.enable_static()
        try:
            paddle.seed(7)
            main = paddle.static.Program()
            with paddle.static.program_guard(main):
                x = paddle.static.data("x", [None, 8], "float32")
                y = paddle.static.data("y", [None, 1], "float32")
                h = paddle.static.nn.fc(x, 16, activation="relu")
                pred = paddle.static.nn.fc(h, 1)
                loss = F.mse_loss(pred, y)
                optimizer.SGD(learning_rate=0.01).minimize(loss)
            exe = paddle.static.Executor()
            rng = np.random.RandomState(0)

            def feed(n):
                return {"x": rng.randn(n, 8).astype(np.float32),
                        "y": rng.randn(n, 1).astype(np.float32)}

            for _ in range(4):
                exe.run(main, feed=feed(8), fetch_list=[loss])
            exe.run(main, feed=feed(4), fetch_list=[loss])

            # -- injected crash must leave a black box --------------------
            crashed = False
            with fault.inject("executor.run:count=1"):
                try:
                    exe.run(main, feed=feed(8), fetch_list=[loss])
                except fault.FaultInjected:
                    crashed = True
            if not crashed:
                failures.append("injected executor.run fault never fired")
            if not os.path.exists(flight):
                failures.append("no flight-recorder dump after the "
                                "injected crash")
            else:
                box = json.load(open(flight))
                kinds = {e.get("kind") for e in box.get("events", [])}
                if "fault" not in kinds:
                    failures.append(f"flight dump lacks the injected "
                                    f"fault event (kinds: {kinds})")
                if (box.get("exception") or {}).get("type") \
                        != "FaultInjected":
                    failures.append("flight dump lacks the exception")
                if not box.get("stats") or "histograms" not in box:
                    failures.append("flight dump lacks the metrics "
                                    "snapshot")
            exe.close()
        finally:
            paddle.disable_static()
            paddle.static.reset_default_programs()

        rep = obs.explain_compiles("executor")
        causes = [r["cause"] for r in rep["records"]]
        if "new_feed_signature" not in causes:
            failures.append(f"feed-signature recompile not attributed "
                            f"(causes: {causes})")

        # -- serve loop: every compile must carry a named cause -----------
        paddle.seed(5)
        model = make_dyadic_model()
        prefix = os.path.join(workdir, "m")
        jit.save(model, prefix,
                 input_spec=[InputSpec([None, 8], "float32")])
        pred = inference.create_predictor(inference.Config(prefix))
        engine = serving.InferenceEngine(pred, max_batch_size=8,
                                         batch_timeout_ms=5.0,
                                         max_queue=64)
        engine.warmup()
        reqs = [(rng.randint(-8, 9, (int(rng.randint(1, 5)), 8)) / 4.0)
                .astype(np.float32) for _ in range(24)]
        futures = [engine.infer([r]) for r in reqs]
        for f in futures:
            f.result(60)

        prep = obs.explain_compiles("predictor")
        n_attr = len([r for r in prep["records"]
                      if r["cause"] != "unexplained"])
        if n_attr != pred.num_compiled_variants():
            failures.append(
                f"predictor compiles not 100% attributed: "
                f"{n_attr} records vs {pred.num_compiled_variants()} "
                f"variants")
        total = obs.explain_compiles()
        if total["unexplained"] != 0:
            failures.append(f"{total['unexplained']} unexplained "
                            f"compile(s): {total['by_cause']}")
        if total["total"] == 0:
            failures.append("no compiles recorded at all")

        # -- /metrics content negotiation + Prometheus grammar ------------
        srv = ServingServer(engine, port=0).start()
        try:
            client = Client(srv.url)
            js = client.metrics()
            if "counters" not in js or "latency_ms" not in js:
                failures.append("JSON /metrics lost the engine stats")
            text = client.metrics_text()
            bad = [ln for ln in text.splitlines()
                   if ln and not ln.startswith("#")
                   and not PROM_LINE.match(ln)]
            if bad:
                failures.append(f"unparseable Prometheus lines: "
                                f"{bad[:3]}")
            if "paddle_tpu_serving_latency_ms" not in text:
                failures.append("Prometheus output lacks the serving "
                                "latency summary")
            if "paddle_tpu_serving_engine_queue_depth" not in text:
                failures.append("Prometheus output lacks the engine "
                                "gauges")
        finally:
            srv.close()
            engine.close()

        # -- JSONL metrics dump -------------------------------------------
        dump_path = os.path.join(workdir, "metrics.jsonl")
        obs.dump_metrics(dump_path)
        obs.dump_metrics(dump_path)
        lines = open(dump_path).read().splitlines()
        if len(lines) != 2 or not all(
                "stats" in json.loads(ln) for ln in lines):
            failures.append("metrics JSONL dump is malformed")

        # -- trace integrity ----------------------------------------------
        trace = tracer.chrome_trace()
        _check_chrome_schema(trace, failures)
        kinds = {e.get("kind") for e in tracer.events()}
        for want in ("span", "op", "compile", "serving", "fault"):
            if want not in kinds:
                failures.append(f"tracer recorded no '{want}' events "
                                f"(kinds: {kinds})")
        if verbose:
            print(f"events={len(tracer.events())} kinds={sorted(kinds)} "
                  f"compiles={total['by_cause']} "
                  f"flight={os.path.exists(flight)}")
        _ = monitor.get_stat("flight.dumps")
    finally:
        obs.uninstall_flight_recorder()
        obs.disable()
        shutil.rmtree(workdir, ignore_errors=True)
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    failures = run_checks(verbose=args.verbose)
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("obs_smoke: observability healthy (crash black box written, "
          "100% of compiles attributed, Prometheus + JSON /metrics "
          "served, trace schema valid)")
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
