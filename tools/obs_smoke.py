#!/usr/bin/env python
"""CI smoke gate for unified observability (sibling of bench_smoke.py /
chaos_smoke.py / serve_smoke.py).

Drives a short train + serve loop on CPU with tracing ON and exits
non-zero when the observability contract regresses:

1. **flight recorder** — an injected crash (``fault`` rule on
   ``executor.run``) must leave a readable flight-recorder dump that
   contains the injected fault event, the exception, and a full
   metrics snapshot.
2. **recompile attribution** — ``explain_compiles()`` must report ZERO
   unexplained compiles across the run; the executor's second feed
   signature must be attributed to ``new_feed_signature``; every
   Predictor compile in the serve loop must carry a named cause and
   their count must equal ``num_compiled_variants()`` (100%
   attribution).
3. **metrics export** — the HTTP ``/metrics`` endpoint must serve the
   Prometheus text exposition under an Accept: text/plain header
   (every line must parse) while keeping the JSON stats for default
   clients; the JSONL metrics dump must append parseable lines.
4. **trace integrity** — the chrome-trace export must satisfy the
   trace-event schema (name/ph/ts/pid/tid per event, dur on complete
   events) and carry span, op, compile and serving events.
5. **closed perf loop** — with the runtime performance observatory on
   (``observability.enable_perf``), the bench-MLP train loop must
   yield fenced device-time samples, a finite measured-vs-predicted
   drift per compile identity, and nonzero device-memory gauges.
6. **SLO burn-rate alerting** — a serving run with injected predictor
   latency must breach the declared p99 objective: ``/healthz``
   degrades to 503 with the breach reasons, the breach event and the
   degraded SLO block land in a flight-recorder dump (with the ring's
   drop accounting), the engine-labelled Prometheus gauges carry
   ``{engine="..."}``, and the endpoint recovers to 200 once the
   rolling window clears.
7. **disabled-path contract** — every new emitting site (Executor.run,
   the serving dispatch/decode steps) reaches the observatory through
   ``core.obs_hook`` module attributes only — no per-call
   ``observability`` import anywhere in the hot path; the fleet
   exporter tick rides the same contract (``obs_hook._export``
   None-check in Executor._run, InferenceEngine._execute and
   GenerationEngine._decode_step).
8. **fleet gate** — ``chaos_smoke --scenario fleet`` in a subprocess:
   a supervised generation replica spooling telemetry hard-crashes
   mid-traffic; the merged chrome-trace must carry aligned lanes for
   the parent and BOTH child incarnations plus the restart reason, and
   a pinned ``/generate`` trace must assemble into one connected span
   tree across the process hop.

Usage:  python tools/obs_smoke.py [--verbose]
"""
from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# metric_name{labels} value  — the text exposition grammar subset we emit
PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+naif]+$")

_CHROME_PH = {"X", "i", "C", "B", "E", "M"}


def _check_chrome_schema(trace: dict, failures: list) -> None:
    evs = trace.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        failures.append("chrome trace has no traceEvents")
        return
    for ev in evs:
        probs = []
        if not isinstance(ev.get("name"), str):
            probs.append("name")
        if ev.get("ph") not in _CHROME_PH:
            probs.append("ph")
        if not isinstance(ev.get("ts"), (int, float)) or ev["ts"] < 0:
            probs.append("ts")
        if not isinstance(ev.get("pid"), int):
            probs.append("pid")
        if not isinstance(ev.get("tid"), int):
            probs.append("tid")
        if ev.get("ph") == "X" and not (
                isinstance(ev.get("dur"), (int, float)) and ev["dur"] >= 0):
            probs.append("dur")
        if probs:
            failures.append(f"trace event violates schema ({probs}): "
                            f"{ev}")
            return


def _check_disabled_contract(failures: list) -> None:
    """Every new emitting site pays one obs_hook attribute check when
    the observatory is off — never a per-call observability import."""
    from paddle_tpu.serving.engine import InferenceEngine
    from paddle_tpu.serving.generation import GenerationEngine
    from paddle_tpu.static.executor import Executor
    for fn in (Executor.run, InferenceEngine._execute,
               GenerationEngine._decode_step):
        names = fn.__code__.co_names
        if "obs_hook" not in names:
            failures.append(f"{fn.__qualname__} lost its obs_hook "
                            f"disabled-path check")
        if "observability" in names:
            failures.append(f"{fn.__qualname__} imports observability "
                            f"on the hot path: {names}")
    # the fleet exporter tick is a hot-path site too: one _export
    # attribute None-check per dispatch/decode step when not spooling
    for fn in (InferenceEngine._execute, GenerationEngine._decode_step):
        if "_export" not in fn.__code__.co_names:
            failures.append(f"{fn.__qualname__} lost its obs_hook."
                            f"_export disabled-path check")
    # the perf anatomy lives in Executor._run (run is a thin span
    # wrapper) — it must reach the observatory through the obs_hook
    # attribute, not an import.  _run legitimately imports
    # observability on the COMPILE-ONLY path (record_compile), so the
    # per-call-import assertion above can't apply; the _perf attribute
    # access is the contract co_names CAN see.
    run_names = Executor._run.__code__.co_names
    if "obs_hook" not in run_names or "_perf" not in run_names:
        failures.append("Executor._run lost its obs_hook._perf "
                        "disabled-path check")
    # supervised-training heartbeat rides the same contract: one
    # module-attribute check per step, nothing more, when unsupervised
    if "_heartbeat" not in run_names:
        failures.append("Executor._run lost its obs_hook._heartbeat "
                        "disabled-path check")
    # ... and so does the fleet exporter's per-step tick
    if "_export" not in run_names:
        failures.append("Executor._run lost its obs_hook._export "
                        "disabled-path check")


def run_checks(verbose: bool = False) -> list:
    """Returns a list of failure strings (empty = healthy)."""
    import math
    import time

    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import inference, jit, observability as obs
    from paddle_tpu import optimizer, serving
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.serving.http import Client, ServingServer
    from paddle_tpu.testing import fault
    from paddle_tpu.testing.chaos import make_dyadic_model
    from paddle_tpu.utils import monitor

    failures: list = []
    workdir = tempfile.mkdtemp(prefix="obs_smoke_")
    obs.reset_compiles()
    tracer = obs.enable(capacity=8192)
    # runtime performance observatory: fence every 2nd step so the
    # short smoke loop still yields device-time samples + memory gauges
    obs.enable_perf(sample_every=2)
    flight = os.path.join(workdir, "flight_record.json")
    obs.install_flight_recorder(path=flight)
    try:
        # -- short static train loop (two feed signatures) ----------------
        paddle.enable_static()
        try:
            paddle.seed(7)
            main = paddle.static.Program()
            with paddle.static.program_guard(main):
                x = paddle.static.data("x", [None, 8], "float32")
                y = paddle.static.data("y", [None, 1], "float32")
                h = paddle.static.nn.fc(x, 16, activation="relu")
                pred = paddle.static.nn.fc(h, 1)
                loss = F.mse_loss(pred, y)
                optimizer.SGD(learning_rate=0.01).minimize(loss)
            exe = paddle.static.Executor()
            rng = np.random.RandomState(0)

            def feed(n):
                return {"x": rng.randn(n, 8).astype(np.float32),
                        "y": rng.randn(n, 1).astype(np.float32)}

            for _ in range(4):
                exe.run(main, feed=feed(8), fetch_list=[loss])
            exe.run(main, feed=feed(4), fetch_list=[loss])

            # -- injected crash must leave a black box --------------------
            crashed = False
            with fault.inject("executor.run:count=1"):
                try:
                    exe.run(main, feed=feed(8), fetch_list=[loss])
                except fault.FaultInjected:
                    crashed = True
            if not crashed:
                failures.append("injected executor.run fault never fired")
            if not os.path.exists(flight):
                failures.append("no flight-recorder dump after the "
                                "injected crash")
            else:
                box = json.load(open(flight))
                kinds = {e.get("kind") for e in box.get("events", [])}
                if "fault" not in kinds:
                    failures.append(f"flight dump lacks the injected "
                                    f"fault event (kinds: {kinds})")
                if (box.get("exception") or {}).get("type") \
                        != "FaultInjected":
                    failures.append("flight dump lacks the exception")
                if not box.get("stats") or "histograms" not in box:
                    failures.append("flight dump lacks the metrics "
                                    "snapshot")
            exe.close()
        finally:
            paddle.disable_static()
            paddle.static.reset_default_programs()

        rep = obs.explain_compiles("executor")
        causes = [r["cause"] for r in rep["records"]]
        if "new_feed_signature" not in causes:
            failures.append(f"feed-signature recompile not attributed "
                            f"(causes: {causes})")

        # -- closed perf loop: drift per identity + memory gauges ---------
        perf_rep = obs.perf_report()
        idents = [r for r in perf_rep.get("identities", [])
                  if r["component"] == "executor" and r["sampled"]]
        if not idents:
            failures.append("perf observatory recorded no fenced "
                            "executor samples on the MLP run")
        else:
            r0 = idents[0]
            m, d = r0["measured"], r0["drift"]
            p50 = m.get("step_ms_p50")
            # sane-bounds gate: the measured step exists and is a
            # plausible wall time (1 us .. 10 s), and both drift axes
            # are computed and finite against the compile record's
            # prediction — the closed loop the ISSUE demands
            if not p50 or not 1e-3 <= p50 <= 1e4:
                failures.append(f"measured device step implausible: "
                                f"{p50} ms")
            for axis in ("step_time_pct", "peak_bytes_pct"):
                v = d.get(axis)
                if v is None or not math.isfinite(v):
                    failures.append(f"drift axis {axis} not computed "
                                    f"vs the prediction: {d}")
                elif v <= -99.9:
                    failures.append(f"{axis} drift {v:.1f}% — measured "
                                    f"~0 vs prediction (clock bug?)")
        if not monitor.get_stat("mem.live_bytes_total"):
            failures.append("device-memory gauges are zero after the "
                            "fenced samples")

        # -- serve loop: every compile must carry a named cause -----------
        paddle.seed(5)
        model = make_dyadic_model()
        prefix = os.path.join(workdir, "m")
        jit.save(model, prefix,
                 input_spec=[InputSpec([None, 8], "float32")])
        pred = inference.create_predictor(inference.Config(prefix))
        engine = serving.InferenceEngine(pred, max_batch_size=8,
                                         batch_timeout_ms=5.0,
                                         max_queue=64)
        engine.warmup()
        reqs = [(rng.randint(-8, 9, (int(rng.randint(1, 5)), 8)) / 4.0)
                .astype(np.float32) for _ in range(24)]
        futures = [engine.infer([r]) for r in reqs]
        for f in futures:
            f.result(60)

        prep = obs.explain_compiles("predictor")
        n_attr = len([r for r in prep["records"]
                      if r["cause"] != "unexplained"])
        if n_attr != pred.num_compiled_variants():
            failures.append(
                f"predictor compiles not 100% attributed: "
                f"{n_attr} records vs {pred.num_compiled_variants()} "
                f"variants")
        total = obs.explain_compiles()
        if total["unexplained"] != 0:
            failures.append(f"{total['unexplained']} unexplained "
                            f"compile(s): {total['by_cause']}")
        if total["total"] == 0:
            failures.append("no compiles recorded at all")

        # -- /metrics content negotiation + Prometheus grammar ------------
        srv = ServingServer(engine, port=0).start()
        try:
            client = Client(srv.url)
            js = client.metrics()
            if "counters" not in js or "latency_ms" not in js:
                failures.append("JSON /metrics lost the engine stats")
            text = client.metrics_text()
            bad = [ln for ln in text.splitlines()
                   if ln and not ln.startswith("#")
                   and not PROM_LINE.match(ln)]
            if bad:
                failures.append(f"unparseable Prometheus lines: "
                                f"{bad[:3]}")
            if "paddle_tpu_serving_latency_ms" not in text:
                failures.append("Prometheus output lacks the serving "
                                "latency summary")
            if "paddle_tpu_serving_engine_queue_depth" not in text:
                failures.append("Prometheus output lacks the engine "
                                "gauges")
        finally:
            srv.close()
            engine.close()

        # -- SLO breach under injected latency + /healthz degradation -----
        eng2 = serving.InferenceEngine(pred, max_batch_size=8,
                                       batch_timeout_ms=1.0,
                                       max_queue=64, name="slo")
        eng2.warmup()
        obs.install_slo_monitor([obs.SLORule(
            "serving.latency_ms", 60.0, window=1.5, quantile=0.99,
            name="p99_latency_ms")])
        obs.slo_status()                    # base window snapshot
        srv2 = ServingServer(eng2, port=0).start()
        try:
            client2 = Client(srv2.url)
            h = client2.healthz()
            if h.get("status") != "running" or h.get("slo") != "ok":
                failures.append(f"healthy probe should be running+slo "
                                f"ok, got {h}")
            # inject latency at the predictor: every dispatch now blows
            # the 60 ms objective
            orig_run = pred.run
            pred.run = lambda feeds: (time.sleep(0.15),
                                      orig_run(feeds))[1]
            try:
                for f in [eng2.infer([r]) for r in reqs[:5]]:
                    f.result(60)
            finally:
                pred.run = orig_run
            h = client2.healthz()
            if h.get("status") != "degraded":
                failures.append(f"/healthz did not degrade under the "
                                f"injected latency: {h}")
            elif "p99_latency_ms" not in h["slo"]["breached"]:
                failures.append(f"degraded /healthz lacks the breached "
                                f"rule: {h}")
            # the breach must land in the black box, with the ring's
            # drop accounting riding along
            slo_flight = os.path.join(workdir, "slo_flight.json")
            obs.dump_flight(slo_flight, reason="slo_breach")
            box2 = json.load(open(slo_flight))
            if (box2.get("slo") or {}).get("status") != "degraded":
                failures.append("flight dump lacks the degraded SLO "
                                "status block")
            if "events_dropped" not in (box2.get("obs") or {}):
                failures.append("flight dump lacks the tracer ring "
                                "drop accounting")
            if "slo" not in {e.get("kind") for e in tracer.events()}:
                failures.append("no slo breach event on the tracer")
            # recovery: fast traffic until the rolling window clears
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                for f in [eng2.infer([r]) for r in reqs[:2]]:
                    f.result(60)
                h = client2.healthz()
                if h.get("status") == "running":
                    break
                time.sleep(0.3)
            if h.get("status") != "running":
                failures.append(f"/healthz never recovered after the "
                                f"window cleared: {h}")
            # per-engine labelled gauges on the Prometheus exposition
            text2 = client2.metrics_text()
            if ('paddle_tpu_serving_engine_queue_depth{engine="slo"}'
                    not in text2):
                failures.append("Prometheus output lacks the "
                                "engine-labelled gauges")
            if "paddle_tpu_serving_engine_slo_requests" not in text2:
                failures.append("per-engine mirrored stats "
                                "(serving.engine.slo.*) missing")
        finally:
            srv2.close()
            eng2.close()
            obs.uninstall_slo_monitor()

        # -- JSONL metrics dump -------------------------------------------
        dump_path = os.path.join(workdir, "metrics.jsonl")
        obs.dump_metrics(dump_path)
        obs.dump_metrics(dump_path)
        lines = open(dump_path).read().splitlines()
        if len(lines) != 2 or not all(
                "stats" in json.loads(ln) for ln in lines):
            failures.append("metrics JSONL dump is malformed")

        # -- trace integrity ----------------------------------------------
        trace = tracer.chrome_trace()
        _check_chrome_schema(trace, failures)
        kinds = {e.get("kind") for e in tracer.events()}
        for want in ("span", "op", "compile", "serving", "fault", "perf"):
            if want not in kinds:
                failures.append(f"tracer recorded no '{want}' events "
                                f"(kinds: {kinds})")
        _check_disabled_contract(failures)

        # -- fleet gate: cross-process spool + trace, own interpreter -----
        # (the drill supervises real child processes and stages obs
        # flags into their env, so it gets a subprocess of its own
        # rather than fighting this process's live tracer)
        import subprocess
        fleet = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "chaos_smoke.py"),
             "--scenario", "fleet"],
            capture_output=True, text=True, timeout=600)
        if fleet.returncode != 0:
            tail = (fleet.stdout + fleet.stderr).strip().splitlines()
            failures.append(f"fleet observability gate failed: "
                            f"{tail[-6:]}")
        if verbose:
            print(f"events={len(tracer.events())} kinds={sorted(kinds)} "
                  f"compiles={total['by_cause']} "
                  f"flight={os.path.exists(flight)}")
        _ = monitor.get_stat("flight.dumps")
    finally:
        obs.uninstall_flight_recorder()
        obs.uninstall_slo_monitor()
        obs.disable_perf()
        obs.disable()
        shutil.rmtree(workdir, ignore_errors=True)
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    failures = run_checks(verbose=args.verbose)
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("obs_smoke: observability healthy (crash black box written, "
          "100% of compiles attributed, Prometheus + JSON /metrics "
          "served, trace schema valid, drift loop closed, SLO breach "
          "degraded + recovered /healthz, disabled path one-check, "
          "fleet spool + cross-process trace gate green)")
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
