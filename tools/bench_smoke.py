#!/usr/bin/env python
"""CI smoke guard for the static Executor hot path.

Runs a tiny static train loop on CPU and exits non-zero when the donated
hot path regresses:

1. **recompiles** — more than one XLA compile per (feed signature,
   fetch set): something put per-step-varying data into the compile key
   (``Executor.compile_count``, the jit cache-miss counter equivalent).
2. **host feeds** — an already-on-device feed took the NumPy
   device→host→device round-trip (``Executor.host_feed_converts``).
3. **per-step host sync** (optional, ``--timing``) — the async-dispatch
   loop (``return_numpy=False``, sync once at the end) must be faster
   than the per-step-synced loop (``return_numpy=True``).  If dispatch
   itself started blocking on device work, both loops time the same and
   the check fails.  Wall-clock checks are retried once to ride out CI
   noise; ``--no-timing`` (default under pytest) skips them.

Usage:  python tools/bench_smoke.py [--steps N] [--timing]
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build(hidden=64, depth=3):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import optimizer

    paddle.seed(0)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, hidden], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        h = x
        for _ in range(depth):
            h = paddle.static.nn.fc(h, hidden, activation="relu")
        loss = F.mse_loss(paddle.static.nn.fc(h, 1), y)
        optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main, loss


def run_checks(steps: int = 30, timing: bool = False) -> list:
    """Returns a list of failure strings (empty = healthy)."""
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle

    failures = []
    paddle.enable_static()
    try:
        main, loss = _build()
        exe = paddle.static.Executor()
        rng = np.random.RandomState(0)
        feed = {"x": jnp.asarray(rng.standard_normal(
                    (8, 64)).astype(np.float32)),
                "y": jnp.asarray(rng.standard_normal(
                    (8, 1)).astype(np.float32))}

        for _ in range(steps):
            last = exe.run(main, feed=feed, fetch_list=[loss],
                           return_numpy=False)[0]
        float(np.asarray(last.data))

        if exe.compile_count != 1:
            failures.append(
                f"recompile regression: {exe.compile_count} compiles for "
                f"ONE feed signature across {steps} steps (expected 1)")
        if exe.host_feed_converts != 0:
            failures.append(
                f"host-feed regression: {exe.host_feed_converts} NumPy "
                f"round-trips for already-on-device feeds (expected 0)")

        if timing:
            def loop(sync):
                t0 = time.perf_counter()
                for _ in range(steps):
                    out = exe.run(main, feed=feed, fetch_list=[loss],
                                  return_numpy=sync)[0]
                if not sync:
                    float(np.asarray(out.data))
                return time.perf_counter() - t0

            for _ in range(2):  # one retry against CI noise
                t_async, t_sync = loop(False), loop(True)
                if t_async < t_sync:
                    break
            if t_async >= t_sync:
                failures.append(
                    f"host-sync regression: async-dispatch loop "
                    f"({t_async * 1000:.1f} ms) is not faster than the "
                    f"per-step-synced loop ({t_sync * 1000:.1f} ms) — "
                    f"run() appears to block per step")
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--timing", dest="timing", action="store_true",
                    default=True)
    ap.add_argument("--no-timing", dest="timing", action="store_false")
    args = ap.parse_args(argv)

    failures = run_checks(steps=args.steps, timing=args.timing)
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("bench_smoke: static hot path healthy "
          f"(1 compile, 0 host feeds{', async < synced' if args.timing else ''})")
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
