#!/usr/bin/env python
"""Sharding smoke gate: GPT/BERT-tiny on mesh {1, 8}, unchanged code.

The multichip promise of `paddle_tpu.distributed.sharding` (ISSUE 8 /
ROADMAP item 1), executably: the SAME static training script — a
GPT-shaped causal LM and a BERT-shaped classifier, built through
``fleet.distributed_optimizer`` + the static ``Executor`` — runs on a
1-device mesh and an 8-device mesh (virtual CPU devices) with

- **zero recompiles after warmup** on both meshes (one XLA compile per
  program; the donated ``_ExecState`` threads through
  ``jit(in_shardings=..., out_shardings=...)`` run to run),
- **loss-trajectory parity** between the two mesh sizes (the GSPMD
  grad psum must be the same math as single-device),
- a **mesh-8 → mesh-1 → mesh-8 sharded-checkpoint round trip** through
  ``SnapshotStore`` restoring bitwise-identical gathered params
  (per-shard sha256 digests verified on every restore),
- fully **attributed compiles** (``explain_compiles`` has no
  'unexplained' executor entries).

Usage::

    python tools/shard_smoke.py [--steps 6] [--verbose]

CI treats a non-zero exit as a sharding regression.  The same flows run
in-process from tests/test_distributed.py.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# env BEFORE jax initialises: 8 virtual CPU devices
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()


def _build_encoder(H, S, NH, layers, causal):
    """Transformer encoder stack recorded into the ambient Program."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn

    Dh = H // NH

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.ln1 = nn.LayerNorm(H)
            self.ln2 = nn.LayerNorm(H)
            self.qkv = nn.Linear(H, 3 * H)
            self.proj = nn.Linear(H, H)
            self.fc1 = nn.Linear(H, 4 * H)
            self.fc2 = nn.Linear(4 * H, H)

        def forward(self, x):
            qkv = self.qkv(self.ln1(x)).reshape([-1, S, 3, NH, Dh])
            q, k, v = qkv.unbind(axis=2)
            att = paddle.matmul(q.transpose([0, 2, 1, 3]),
                                k.transpose([0, 2, 3, 1]))
            att = att * (1.0 / np.sqrt(Dh))
            if causal:
                mask = paddle.to_tensor(np.triu(
                    np.full((S, S), -1e9, np.float32), k=1))
                att = att + mask
            att = F.softmax(att, axis=-1)
            o = paddle.matmul(att, v.transpose([0, 2, 1, 3]))
            o = o.transpose([0, 2, 1, 3]).reshape([-1, S, H])
            x = x + self.proj(o)
            return x + self.fc2(F.gelu(self.fc1(self.ln2(x))))

    return [Block() for _ in range(layers)], nn.LayerNorm(H)


def build_gpt_tiny():
    """GPT-shaped causal LM (tiny dims), static Program + loss."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn

    H, V, S, NH = 32, 128, 16, 4
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        ids = paddle.static.data("ids", [None, S], "int64")
        labels = paddle.static.data("labels", [None, S], "int64")
        x = nn.Embedding(V, H)(ids) \
            + nn.Embedding(S, H)(paddle.arange(S).unsqueeze(0))
        blocks, ln_f = _build_encoder(H, S, NH, layers=2, causal=True)
        for blk in blocks:
            x = blk(x)
        logits = nn.Linear(H, V)(ln_f(x))
        loss = F.cross_entropy(logits.reshape([-1, V]),
                               labels.reshape([-1]))
    return main, loss, ("ids", "labels")


def build_bert_tiny():
    """BERT-shaped bidirectional classifier (tiny dims)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn

    H, V, S, NH = 32, 128, 16, 4
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        ids = paddle.static.data("ids", [None, S], "int64")
        labels = paddle.static.data("labels", [None], "int64")
        x = nn.Embedding(V, H)(ids) \
            + nn.Embedding(S, H)(paddle.arange(S).unsqueeze(0))
        blocks, ln_f = _build_encoder(H, S, NH, layers=2, causal=False)
        for blk in blocks:
            x = blk(x)
        pooled = paddle.tanh(nn.Linear(H, H)(ln_f(x)[:, 0]))
        loss = F.cross_entropy(nn.Linear(H, 2)(pooled), labels)
    return main, loss, ("ids", "labels")


def _feeds(name):
    import numpy as np
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (16, 16)).astype(np.int64)
    if name == "gpt":
        labels = rng.randint(0, 128, (16, 16)).astype(np.int64)
    else:
        labels = rng.randint(0, 2, (16,)).astype(np.int64)
    return {"ids": ids, "labels": labels}


def _train(build, name, mesh_shape, steps, store=None, save=False):
    """The unchanged user code: fleet + static Executor on whatever
    mesh is live.  Returns (losses, compile_count, steps_per_sec,
    gathered_params)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import distributed as dist, optimizer
    from paddle_tpu.distributed.mesh import init_mesh

    init_mesh(mesh_shape)
    paddle.seed(7)
    main, loss, _ = build()
    with paddle.static.program_guard(main):
        f = dist.fleet
        f.init(is_collective=True, strategy=dist.DistributedStrategy())
        opt = f.distributed_optimizer(
            optimizer.AdamW(learning_rate=1e-3))
        opt.minimize(loss)
    init_mesh(mesh_shape)  # fleet.init infers over ALL devices; pin it
    exe = paddle.static.Executor()
    feed = _feeds(name)
    losses = [float(exe.run(main, feed=feed, fetch_list=[loss])[0])]
    t0 = time.perf_counter()
    for _ in range(steps - 1):
        losses.append(float(exe.run(main, feed=feed,
                                    fetch_list=[loss])[0]))
    dt = time.perf_counter() - t0
    if save:
        store.save(0, {"train": exe.sharded_state(main)})
    gathered = {k: np.asarray(v).copy() for k, v in
                exe.sharded_state(main)._getter()["params"].items()}
    compiles = exe.compile_count
    exe.close()
    paddle.static.reset_default_programs()
    return losses, compiles, (steps - 1) / max(dt, 1e-9), gathered


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.observability import explain_compiles
    from paddle_tpu.utils.checkpoint import SnapshotStore

    problems = []
    paddle.enable_static()
    try:
        with tempfile.TemporaryDirectory(prefix="shard_smoke_") as tmp:
            for name, build in (("gpt", build_gpt_tiny),
                                ("bert", build_bert_tiny)):
                store = SnapshotStore(os.path.join(tmp, name))
                l8, c8, sps8, p8 = _train(build, name, {"dp": 8},
                                          args.steps, store, save=True)
                l1, c1, sps1, p1 = _train(build, name, {"dp": 1},
                                          args.steps)
                if args.verbose:
                    print(f"{name}: mesh8 {['%.4f' % v for v in l8]} "
                          f"({sps8:.1f} steps/s), mesh1 "
                          f"{['%.4f' % v for v in l1]} "
                          f"({sps1:.1f} steps/s)")
                for mesh, c in (("8", c8), ("1", c1)):
                    if c != 1:
                        problems.append(
                            f"{name} mesh{mesh}: {c} compiles for one "
                            f"feed signature — recompiles after warmup")
                if not np.allclose(l8, l1, rtol=2e-4):
                    problems.append(
                        f"{name}: mesh-8 loss trajectory diverges from "
                        f"mesh-1 ({l8} vs {l1})")
                # reshard round trip: 8 -> 1 -> 8, pure restores,
                # bitwise-equal gathered params each hop
                for shape, label in (({"dp": 1}, "mesh1"),
                                     ({"dp": 8}, "mesh8")):
                    from paddle_tpu.distributed.mesh import init_mesh
                    init_mesh(shape)
                    paddle.seed(7)
                    main_r, loss_r, _ = build()
                    with paddle.static.program_guard(main_r):
                        from paddle_tpu import distributed as dist
                        from paddle_tpu import optimizer
                        f = dist.fleet
                        f.init(is_collective=True,
                               strategy=dist.DistributedStrategy())
                        opt = f.distributed_optimizer(
                            optimizer.AdamW(learning_rate=1e-3))
                        opt.minimize(loss_r)
                    init_mesh(shape)
                    exe_r = paddle.static.Executor()
                    ss = exe_r.sharded_state(main_r)
                    store.restore({"train": ss})
                    got = {k: np.asarray(v) for k, v in
                           ss._getter()["params"].items()}
                    for k in p8:
                        if not np.array_equal(got[k], p8[k]):
                            problems.append(
                                f"{name} {label}: restored param {k} "
                                f"not bitwise-identical to the mesh-8 "
                                f"snapshot")
                            break
                    exe_r.close()
                    paddle.static.reset_default_programs()
        ec = explain_compiles("executor")
        unex = ec["by_cause"].get("executor.unexplained", 0)
        if unex:
            problems.append(f"{unex} unexplained executor compile(s)")
    finally:
        paddle.disable_static()

    if problems:
        for p in problems:
            print(f"FAIL: {p}", file=sys.stderr)
        return 1
    print("shard_smoke OK: GPT/BERT-tiny ran unchanged on mesh {1,8} "
          "(1 compile each, loss parity) and the mesh-8 -> mesh-1 -> "
          "mesh-8 sharded-checkpoint round trip restored bitwise-"
          "identical gathered params")
    return 0


if __name__ == "__main__":
    sys.exit(main())
