#!/usr/bin/env python
"""Serve a saved inference artifact over HTTP.

Load the artifact (the ``.pdmodel`` prefix written by ``paddle.jit.save``
/ ``paddle.static.save_inference_model``), warm up the batch buckets so
the hot path never compiles, and serve:

    python tools/serve.py /path/to/model_prefix --port 8000

    curl localhost:8000/healthz
    curl localhost:8000/metrics
    curl -X POST localhost:8000/predict \
         -H 'Content-Type: application/json' \
         -d '{"inputs": [[[0.1, 0.2, 0.3, 0.4]]]}'

``inputs`` is a list of per-input arrays (or a name->array dict), each
with a leading batch dim.  SIGINT/SIGTERM drain in-flight work before
exit.  See README "Serving" for bucket/padding and backpressure
semantics.

The HTTP plane binds *before* warmup with readiness down: ``/healthz``
answers 503 + ``Retry-After`` (``"warming"``) until the buckets are
compiled, then flips to 200 — a supervisor or load balancer holds
traffic instead of timing out on a compiling replica.  With
``--weights-dir`` a :class:`~paddle_tpu.serving.WeightWatcher` polls
that :class:`~paddle_tpu.utils.checkpoint.SnapshotStore` directory and
hot-swaps newly published, digest-verified weights into the live
engine with zero downtime and zero recompiles (see README "Serving
operations").

**Multi-model mode**: ``--models manifest.json`` starts the full
control plane instead — every entry in the manifest is loaded into a
:class:`~paddle_tpu.serving.ModelRegistry` (each model warms before
its name becomes routable; readiness flips when ALL manifest models
are ready), requests route by the JSON ``"model"`` field / ``X-Model``
header, and ``/admin/models`` loads/unloads/aliases more models at
runtime.  Manifest shape::

    {"models": {
        "prod-resnet": {"artifact": "/path/prefix",
                         "weights_dir": "/path/snapshots",
                         "aliases": ["prod"], "weight": 2.0,
                         "rest_shapes": [[3, 224, 224]]},
        "canary":      {"artifact": "/other/prefix"}},
     "default": "prod-resnet",
     "max_inflight": 128,
     "quotas": {"tenant-a": {"rate": 50, "burst": 100}}}

``max_inflight`` is the weighted-fair-queuing pool; ``quotas`` are
per-tenant token buckets.  With ``FLAGS_compile_cache_dir`` set the
per-model warmups deserialize previously compiled buckets instead of
paying XLA again (see README "Multi-model control plane").
"""
from __future__ import annotations

import argparse
import os
import signal
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[1],
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    ap.add_argument("model", nargs="?", default=None,
                    help="artifact path prefix (as passed to jit.save / "
                         "save_inference_model); omit with --models")
    ap.add_argument("--models", default=None, metavar="MANIFEST.json",
                    help="multi-model manifest (see module docstring): "
                         "serve a ModelRegistry with per-model engines, "
                         "admin endpoints, WFQ and quotas instead of a "
                         "single engine")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--max-batch-size", type=int, default=32)
    ap.add_argument("--batch-timeout-ms", type=float, default=2.0)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="default per-request in-queue deadline")
    ap.add_argument("--buckets", default=None,
                    help="comma-separated batch buckets to pad to "
                         "(default: powers of two up to max batch)")
    ap.add_argument("--rest-shape", action="append", default=None,
                    metavar="D0,D1,...",
                    help="per-input shape without the batch dim, once per "
                         "input (only needed when the artifact's non-batch "
                         "dims are symbolic)")
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip AOT warmup (first requests will compile)")
    ap.add_argument("--weights-dir", default=None,
                    help="SnapshotStore directory to watch for hot-swap "
                         "weight snapshots (publish_weights); new "
                         "digest-verified versions swap in with zero "
                         "downtime")
    ap.add_argument("--weights-poll-s", type=float, default=2.0,
                    help="meta-poll cadence of the weight watcher")
    ap.add_argument("--verbose", action="store_true",
                    help="log every HTTP request")
    args = ap.parse_args(argv)

    from paddle_tpu import inference, serving

    if args.models:
        return _serve_registry(args)
    if not args.model:
        ap.error("need an artifact prefix (or --models MANIFEST.json)")

    config = inference.Config(args.model)
    predictor = inference.create_predictor(config)
    buckets = ([int(b) for b in args.buckets.split(",")]
               if args.buckets else None)
    engine = serving.InferenceEngine(
        predictor, max_batch_size=args.max_batch_size,
        batch_timeout_ms=args.batch_timeout_ms, max_queue=args.max_queue,
        default_deadline_ms=args.deadline_ms, buckets=buckets)
    # bind the HTTP plane first, not-ready: liveness probes answer (503
    # "warming" + Retry-After) while the buckets compile, and readiness
    # flips only when the hot path is warm
    srv = serving.ServingServer(engine, host=args.host, port=args.port,
                                verbose=args.verbose, ready=False).start()
    rest = ([tuple(int(d) for d in s.split(","))
             for s in args.rest_shape] if args.rest_shape else None)
    if not args.no_warmup:
        n = engine.warmup(rest_shapes=rest)
        print(f"warmed {len(engine.buckets)} buckets "
              f"{engine.buckets} -> {n} compiled variants", flush=True)
    srv.mark_ready()

    watcher = None
    if args.weights_dir:
        watcher = serving.WeightWatcher(
            args.weights_dir, engine=engine,
            poll_s=args.weights_poll_s, rest_shapes=rest).start()
        print(f"watching {args.weights_dir} for weight snapshots",
              flush=True)

    stop = {"sig": None}

    def _on_signal(signum, frame):
        stop["sig"] = signum
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _on_signal)
    print(f"serving {args.model} on {srv.url}  "
          f"(POST /predict, GET /healthz, GET /metrics)", flush=True)
    try:
        signal.pause()
    except KeyboardInterrupt:
        pass
    finally:
        print("draining...", flush=True)
        if watcher is not None:
            watcher.stop()
        srv.close()
        engine.drain(timeout=30.0)
        engine.close()
        c = engine.stats()["counters"]
        print(f"served {c['responses']}/{c['requests']} requests in "
              f"{c['batches']} batches (shed={c['shed']}, "
              f"expired={c['deadline_expired']}, "
              f"weight_swaps={c['weight_swaps']})", flush=True)
    return 0


def _serve_registry(args) -> int:
    """--models mode: a ModelRegistry behind one HTTP plane."""
    import json

    from paddle_tpu import serving

    with open(args.models) as f:
        manifest = json.load(f)
    models = manifest.get("models") or {}
    if not models:
        print(f"manifest {args.models} has no models", file=sys.stderr)
        return 2

    reg = serving.ModelRegistry(
        max_inflight=manifest.get("max_inflight"),
        default_model=manifest.get("default"))
    for tenant, q in (manifest.get("quotas") or {}).items():
        reg.set_quota(tenant, float(q["rate"]), q.get("burst"))

    # bind first, not-ready: the readiness gate holds traffic while
    # every manifest model loads + warms (each name becomes routable
    # the moment ITS warmup finishes — a late model never blocks an
    # early one from serving admin/metrics probes)
    srv = serving.ServingServer(None, host=args.host, port=args.port,
                                verbose=args.verbose, ready=False,
                                registry=reg).start()
    for name, spec in models.items():
        rest = ([tuple(int(d) for d in s) for s in spec["rest_shapes"]]
                if spec.get("rest_shapes") else None)
        entry = reg.load(
            name, spec["artifact"],
            weights_dir=spec.get("weights_dir"),
            weights_poll_s=float(spec.get("weights_poll_s", 2.0)),
            aliases=spec.get("aliases", ()),
            weight=float(spec.get("weight", 1.0)),
            warmup=not args.no_warmup, rest_shapes=rest,
            engine_kwargs={
                "max_batch_size": args.max_batch_size,
                "batch_timeout_ms": args.batch_timeout_ms,
                "max_queue": args.max_queue,
                "default_deadline_ms": args.deadline_ms,
            })
        print(f"loaded {name} <- {spec['artifact']} "
              f"(weight={entry.weight}, "
              f"aliases={list(spec.get('aliases', ()))})", flush=True)
    srv.mark_ready()

    stop = {"sig": None}

    def _on_signal(signum, frame):
        stop["sig"] = signum
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _on_signal)
    print(f"serving {len(reg.models())} models {reg.models()} on "
          f"{srv.url}  (POST /predict {{\"model\": ...}}, "
          f"GET/POST /admin/models)", flush=True)
    try:
        signal.pause()
    except KeyboardInterrupt:
        pass
    finally:
        print("draining...", flush=True)
        srv.close()
        reg.close(timeout=30.0)
        c = reg.stats()["counters"]
        print(f"routed {c['requests']} requests across "
              f"{c['loads']} loads / {c['unloads']} unloads "
              f"(wfq_shed={c['wfq_shed']}, quota_shed={c['quota_shed']}, "
              f"unknown_model={c['unknown_model']})", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
