#!/usr/bin/env python
"""Fleet observatory report: one view of every process's telemetry.

Reads a telemetry spool directory (``FLAGS_obs_spool_dir`` — written by
per-process exporters, staged into supervised children automatically)
OR asks a live serving replica over HTTP, then renders:

- a human summary: one line per process (role, pid, segments, event
  count, corruption), plus fleet-wide build-skew detection;
- ``--prometheus``: the merged text exposition, every sample labelled
  ``{proc="<role>-<pid>"}`` (parseable by the PR-9 grammar gate);
- ``--trace OUT.json``: the merged chrome-trace — one named lane per
  process, wall-time aligned, loadable straight into Perfetto;
- ``--trace-id ID``: assemble one distributed request's span tree
  across every process in the spool and report whether it is
  connected.

Usage::

    python tools/fleet_report.py --spool /var/run/paddle-obs
    python tools/fleet_report.py --spool DIR --trace merged.json
    python tools/fleet_report.py --spool DIR --trace-id 7f3a...
    python tools/fleet_report.py --spool DIR --prometheus
    python tools/fleet_report.py --url http://127.0.0.1:8080

``--url`` hits ``GET /admin/fleet`` (and ``POST /admin/trace`` when
``--trace`` is also given) — useful against a replica whose spool dir
is not mounted locally.  Exits non-zero when the spool is empty/
unreadable, any document is corrupt, or a requested trace id does not
assemble into one connected tree.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _report_spool(args) -> int:
    from paddle_tpu.observability import fleet

    procs = fleet.read_spool(args.spool)
    if not procs:
        print(f"fleet_report: no telemetry under {args.spool!r} "
              f"(is FLAGS_obs_spool_dir set on the fleet?)",
              file=sys.stderr)
        return 1
    rc = 0
    snap = fleet.fleet_snapshot(procs=procs)
    print(f"fleet: {len(snap['procs'])} process(es)")
    for label in sorted(snap["procs"]):
        p = snap["procs"][label]
        line = (f"  {label:<24} role={p['role']} pid={p['pid']} "
                f"segments={p['segments']} events={p['events']}")
        if p["corrupt"]:
            line += f"  CORRUPT={p['corrupt']}"
            rc = 1
        print(line)
    if snap["build_skew"]:
        rc = 1
        print(f"BUILD SKEW: {snap['build_skew']}", file=sys.stderr)

    if args.prometheus:
        sys.stdout.write(fleet.fleet_prometheus_text(procs=procs))
    if args.trace:
        merged = fleet.merged_chrome_trace(procs=procs)
        with open(args.trace, "w") as f:
            json.dump(merged, f)
        print(f"merged chrome-trace: {len(merged['traceEvents'])} "
              f"events -> {args.trace}")
    if args.trace_id:
        asm = fleet.assemble_trace(procs, args.trace_id)
        print(f"trace {args.trace_id}: {asm['events']} span(s) across "
              f"{len(asm['pids'])} process(es), "
              f"{asm['components']} component(s), "
              f"connected={asm['connected']}")
        if not asm["connected"] or not asm["events"]:
            rc = 1
    return rc


def _report_url(args) -> int:
    from paddle_tpu.serving.http import Client

    client = Client(args.url, timeout=args.timeout)
    snap = client._get_json("/admin/fleet")
    print(json.dumps(snap, indent=2, sort_keys=True))
    if args.trace:
        merged = json.loads(client._post(
            "/admin/trace?secs=0", b"",
            {"Content-Type": "application/json"}))
        with open(args.trace, "w") as f:
            json.dump(merged, f)
        print(f"merged chrome-trace: "
              f"{len(merged.get('traceEvents', []))} events "
              f"-> {args.trace}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--spool", help="telemetry spool directory "
                     "(FLAGS_obs_spool_dir)")
    src.add_argument("--url", help="live replica base URL "
                     "(GET /admin/fleet)")
    ap.add_argument("--prometheus", action="store_true",
                    help="print the merged {proc=...} exposition")
    ap.add_argument("--trace", metavar="OUT.json",
                    help="write the merged chrome-trace here")
    ap.add_argument("--trace-id", help="assemble this request's "
                    "cross-process span tree")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)
    if args.url and (args.trace_id or args.prometheus):
        ap.error("--trace-id/--prometheus need --spool (the raw "
                 "segments); --url serves the aggregated JSON view")
    return _report_url(args) if args.url else _report_spool(args)


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
