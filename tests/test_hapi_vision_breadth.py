"""hapi.Model breadth + vision transforms/folders (VERDICT r4 weak #8/#7).

Reference: hapi/model_summary.py (summary), hapi/model.py multi-input
handling, vision/transforms/transforms.py, vision/datasets/folder.py.
"""
import io
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import hapi, nn, optimizer
from paddle_tpu.hapi import Model
from paddle_tpu.io import Dataset
import paddle_tpu.nn.functional as F
import paddle_tpu.vision.transforms as T
from paddle_tpu.vision.datasets import DatasetFolder, ImageFolder


def test_model_summary_output_shapes(capsys):
    net = nn.Sequential(nn.Conv2D(1, 4, 3, padding=1), nn.ReLU(),
                        nn.Flatten(), nn.Linear(4 * 8 * 8, 10))
    res = hapi.summary(net, input_size=(1, 1, 8, 8))
    out = capsys.readouterr().out
    assert "Output Shape" in out
    assert "[1, 4, 8, 8]" in out          # conv output captured by hook
    assert "[1, 10]" in out               # head output
    w = 4 * 3 * 3 * 1 + 4
    fc = 4 * 8 * 8 * 10 + 10
    assert res["total_params"] == w + fc
    assert res["trainable_params"] == res["total_params"]
    assert "Non-trainable params: 0" in out


class _TwoInputDs(Dataset):
    def __init__(self, n=32):
        r = np.random.RandomState(5)
        self.a = r.randn(n, 4).astype(np.float32)
        self.b = r.randn(n, 4).astype(np.float32)
        self.y = (self.a.sum(1) + self.b.sum(1) > 0).astype(np.int64)

    def __len__(self):
        return len(self.a)

    def __getitem__(self, i):
        return self.a[i], self.b[i], self.y[i]


class _TwoTower(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fa = nn.Linear(4, 8)
        self.fb = nn.Linear(4, 8)
        self.head = nn.Linear(8, 2)

    def forward(self, a, b):
        return self.head(F.relu(self.fa(a)) + F.relu(self.fb(b)))


def test_model_multi_input_and_multi_loss():
    """Two declared inputs + a loss returning a LIST (summed), through
    the compiled TrainStep path."""
    from paddle_tpu.static import InputSpec
    paddle.seed(100)
    net = _TwoTower()
    model = Model(net, inputs=[InputSpec([None, 4], "float32"),
                               InputSpec([None, 4], "float32")],
                  labels=[InputSpec([None], "int64")])

    def multi_loss(out, y):
        ce = F.cross_entropy(out, y)
        reg = 1e-3 * (out ** 2).mean()
        return [ce, reg]

    opt = optimizer.Adam(learning_rate=5e-3, parameters=net.parameters())
    model.prepare(opt, multi_loss)
    hist = model.fit(_TwoInputDs(), batch_size=8, epochs=3, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]


def test_model_eager_adapter_matches_compiled():
    """prepare(jit_compile=False) runs the eager tape adapter; both
    adapters must train to similar numbers (the reference's dygraph vs
    static adapters)."""
    def build():
        paddle.seed(101)
        net = _TwoTower()
        opt = optimizer.SGD(learning_rate=0.05,
                            parameters=net.parameters())
        return net, opt

    from paddle_tpu.static import InputSpec
    specs = dict(inputs=[InputSpec([None, 4], "float32"),
                         InputSpec([None, 4], "float32")])
    loss = lambda out, y: F.cross_entropy(out, y)
    ds = _TwoInputDs()

    net1, opt1 = build()
    m1 = Model(net1, **specs)
    m1.prepare(opt1, loss, jit_compile=True)
    h1 = m1.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0)

    net2, opt2 = build()
    m2 = Model(net2, **specs)
    m2.prepare(opt2, loss, jit_compile=False)
    h2 = m2.fit(ds, batch_size=8, epochs=2, shuffle=False, verbose=0)
    np.testing.assert_allclose(h1["loss"][-1], h2["loss"][-1], rtol=2e-3)


def test_new_transforms_behave():
    import random
    random.seed(7)     # rejection-sampling transforms use `random`
    r = np.random.RandomState(7)
    img = (r.rand(8, 8, 3) * 255).astype(np.uint8)

    g = T.Grayscale(3)(img)
    assert g.shape == img.shape
    ch = np.asarray(g, np.float32)
    assert np.allclose(ch[..., 0], ch[..., 1])

    rc = T.RandomResizedCrop(4)(img)
    assert rc.shape[:2] == (4, 4)

    rot = T.RandomRotation(0.0)(img)       # 0 degrees == identity
    np.testing.assert_array_equal(rot, img)

    er = T.RandomErasing(prob=1.0, value=0)(img.astype(np.float32))
    assert (er == 0).sum() > (img.astype(np.float32) == 0).sum()

    cj = T.ColorJitter(brightness=0.2, contrast=0.2, saturation=0.2,
                       hue=0.1)(img)
    assert cj.shape == img.shape and cj.dtype == img.dtype

    ct = T.ContrastTransform(0.0)(img)     # 0 value == identity
    np.testing.assert_array_equal(ct, img)


def test_dataset_folder_and_image_folder(tmp_path):
    root = tmp_path / "ds"
    for cls in ("cat", "dog"):
        d = root / cls
        os.makedirs(d)
        for i in range(3):
            np.save(d / f"{i}.npy",
                    np.full((2, 2, 3), fill_value=hash(cls) % 7 + i,
                            dtype=np.float32))
    ds = DatasetFolder(str(root))
    assert ds.classes == ["cat", "dog"]
    assert len(ds) == 6
    img, label = ds[0]
    assert img.shape == (2, 2, 3) and int(label) == 0
    labels = sorted(int(ds[i][1]) for i in range(6))
    assert labels == [0, 0, 0, 1, 1, 1]

    flat = ImageFolder(str(root))
    assert len(flat) == 6
    assert flat[0].shape == (2, 2, 3)

    with pytest.raises(ValueError, match="no class"):
        empty = tmp_path / "empty"
        os.makedirs(empty)
        DatasetFolder(str(empty))


def test_dataset_folder_with_transform_trains(tmp_path):
    root = tmp_path / "imgs"
    r = np.random.RandomState(8)
    for ci, cls in enumerate(("a", "b")):
        d = root / cls
        os.makedirs(d)
        for i in range(8):
            arr = (r.rand(8, 8, 3) + ci).astype(np.float32)
            np.save(d / f"{i}.npy", arr)
    tf = T.Compose([T.Transpose(), T.Normalize(mean=[0.5] * 3,
                                               std=[0.5] * 3)])
    ds = DatasetFolder(str(root), transform=tf)
    paddle.seed(102)
    net = nn.Sequential(nn.Flatten(), nn.Linear(3 * 8 * 8, 2))
    model = Model(net)
    opt = optimizer.Adam(learning_rate=1e-2, parameters=net.parameters())
    model.prepare(opt, lambda o, y: F.cross_entropy(o, y))
    hist = model.fit(ds, batch_size=4, epochs=3, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]


def test_summary_counts_tied_and_root_params(capsys):
    """r4 review: tied parameters count once; root-registered params are
    included."""
    class Tied(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(10, 4)
            self.fc = nn.Linear(4, 4)
            self.scale = self.create_parameter([4])   # root-direct

        def forward(self, ids):
            h = self.fc(self.emb(ids)) * self.scale
            return h @ self.emb.weight.t()            # tied head

    net = Tied()
    res = hapi.summary(net)
    out = capsys.readouterr().out
    expect = 10 * 4 + (4 * 4 + 4) + 4
    assert res["total_params"] == expect
    assert "(Tied)" in out                            # root row present
