"""Quantitative static analysis (ISSUE 6): cost model, liveness,
TPU-readiness hazards, Program.analyze, D2S104 lint, CLIs, and the
executor's per-compile predictions.

Hand counts in these tests are written out from the layer algebra
(2*M*K*N matmuls etc.), independent of the analyzer's rule tables."""
import contextlib
import io
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, optimizer
from paddle_tpu.static import analysis
from paddle_tpu.static.analysis import (CHIP_SPECS, Diagnostic,
                                        MemoryEstimate, ProgramReport)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()
    paddle.static.reset_default_programs()
    paddle.set_flags({"FLAGS_static_verify": False,
                      "FLAGS_static_anchors": False})


def _mlp_program(hidden=8, depth=2, with_opt=True):
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, hidden], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        h = x
        for _ in range(depth):
            h = paddle.static.nn.fc(h, hidden, activation="relu")
        pred = paddle.static.nn.fc(h, 1)
        loss = F.mse_loss(pred, y)
        if with_opt:
            optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main, loss


# ------------------------------------------------------------- cost --
def test_linear_flops_exact():
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [4, 16], "float32")
        lin = nn.Linear(16, 8)
        out = lin(x)
    rep = main.analyze(fetch_list=[out])
    # one linear: 2*B*K*N matmul + B*N bias
    assert rep.totals["flops_fwd"] == 2 * 4 * 16 * 8 + 4 * 8
    c = rep.per_op[0]
    assert c.op_name == "linear" and c.rule == "matmul" and c.modeled
    # bytes: in 4x16, params 16x8 + 8, out 4x8 (float32)
    assert c.in_bytes == 4 * 16 * 4
    assert c.param_bytes == (16 * 8 + 8) * 4
    assert c.out_bytes == 4 * 8 * 4


def test_matmul_reduce_and_elementwise_rules():
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        a = paddle.static.data("a", [3, 5], "float32")
        b = paddle.static.data("b", [5, 7], "float32")
        m = paddle.matmul(a, b)        # 2*3*7*5
        r = (m * 2.0).sum()            # 21 mul + 21 reduce
    rep = main.analyze(fetch_list=[r])
    by_name = {c.op_name: c for c in rep.per_op}
    assert by_name["matmul"].flops == 2 * 3 * 7 * 5
    assert by_name["multiply"].flops == 21
    assert by_name["sum"].flops == 21
    assert rep.totals["flops_fwd"] == 2 * 3 * 7 * 5 + 42


def test_conv_flops_formula():
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [2, 3, 8, 8], "float32")
        conv = nn.Conv2D(3, 4, 3, padding=1)
        out = conv(x)
    rep = main.analyze(fetch_list=[out])
    c = rep.per_op[0]
    # out [2,4,8,8]; dot = 3*3*3; + bias
    out_n = 2 * 4 * 8 * 8
    assert c.flops == 2 * out_n * 27 + out_n
    assert c.rule == "conv"


def test_unmodeled_bucket_is_explicit():
    from paddle_tpu.core import dispatch

    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [4, 4], "float32")
        y = dispatch.apply(lambda a: a @ a, x, op_name="frobnicate")
    rep = main.analyze(fetch_list=[y])
    c = rep.per_op[0]
    assert not c.modeled and c.rule == "unmodeled" and c.flops == 0
    un = rep.totals["unmodeled"]
    assert un["count"] == 1 and un["ops"] == ["frobnicate"]
    assert un["bytes"] == c.total_bytes > 0
    assert un["flops_unknown"] is True


def test_batch_size_rederives_avals():
    main, loss = _mlp_program(hidden=8, depth=1, with_opt=False)
    r1 = main.analyze(fetch_list=[loss])            # placeholder batch 1
    r32 = main.analyze(fetch_list=[loss], batch_size=32)
    # this MLP's forward scales exactly linearly with the batch
    assert r32.totals["flops_fwd"] == 32 * r1.totals["flops_fwd"]
    assert r32.batch_hint == 32
    # feed_shapes overrides one feed exactly
    r8 = main.analyze(fetch_list=[loss],
                      feed_shapes={"x": (8, 8), "y": (8, 1)})
    assert r8.totals["flops_fwd"] == 8 * r1.totals["flops_fwd"]


# --------------------------------------------------------- liveness --
def test_activation_peak_tracks_last_use():
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [256], "float32")   # 1 KiB
        a = x * 2.0
        b = a + 1.0
        c = b - 0.5
    rep = main.analyze(fetch_list=[c])
    m = rep.memory
    # at any point at most 2 of {x,a,b,c} are live (producer + consumer)
    assert m.activation_peak_bytes == 2 * 1024
    assert m.peak_bytes_donated == m.peak_bytes_no_donation  # inference
    assert not m.training
    assert isinstance(m, MemoryEstimate)


def test_fetched_var_stays_live_to_the_end():
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [256], "float32")
        a = x * 2.0        # fetched: must stay live through c
        b = a + 1.0
        c = b - 0.5
    peak_ab = main.analyze(fetch_list=[a, c]).memory
    peak_c = main.analyze(fetch_list=[c]).memory
    assert peak_ab.activation_peak_bytes == 3 * 1024
    assert peak_c.activation_peak_bytes == 2 * 1024


def test_training_memory_donation_bound():
    main, loss = _mlp_program(hidden=8, depth=2)
    rep = main.analyze(fetch_list=[loss], batch_size=4)
    m = rep.memory
    # retained = op outputs only (feeds are accounted once, separately):
    # 4 hidden activations (4,8) + pred (4,1) + scalar loss ()
    assert m.retained_activation_bytes == (4 * 4 * 8 * 4) + 16 + 4
    assert m.feed_bytes == 4 * (8 + 1) * 4
    assert m.training
    # Adam: m+v slots = 2x trainable bytes (exact, via eval_shape)
    assert m.slot_bytes == 2 * m.trainable_param_bytes
    assert not m.slots_estimated
    assert m.grad_bytes == m.trainable_param_bytes
    # donated peak strictly below the naive bound, by exactly the
    # second copy of params + slots that donation avoids
    assert m.peak_bytes_donated < m.peak_bytes_no_donation
    assert (m.peak_bytes_no_donation - m.peak_bytes_donated
            == m.trainable_param_bytes + m.slot_bytes)


def test_sgd_has_no_slots():
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 8], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        loss = F.mse_loss(paddle.static.nn.fc(x, 1), y)
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    m = main.analyze(fetch_list=[loss]).memory
    assert m.slot_bytes == 0 and m.trainable_param_bytes > 0


# ----------------------------------------------------------- fusion --
def test_fusion_candidates_ranked_by_saved_traffic():
    main, loss = _mlp_program(hidden=16, depth=2)
    rep = main.analyze(fetch_list=[loss], batch_size=8)
    assert rep.fusion_candidates, "linear+relu chains must be found"
    top = rep.fusion_candidates[0]
    assert top["op_names"] == ["linear", "relu"]
    # saved = intermediate written+read once each: 2 * 8*16*4 bytes
    assert top["saved_bytes"] == 2 * 8 * 16 * 4
    assert (top["unfused_traffic_bytes"] - top["fused_traffic_bytes"]
            == top["saved_bytes"])
    saved = [c["saved_bytes"] for c in rep.fusion_candidates]
    assert saved == sorted(saved, reverse=True)


def test_fetched_intermediate_breaks_the_chain():
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [64], "float32")
        a = x * 2.0
        b = F.relu(a)
        c = b + 1.0
    # unfetched middle: one x*2+relu+add chain
    rep = main.analyze(fetch_list=[c])
    assert [c_["op_names"] for c_ in rep.fusion_candidates] == [
        ["multiply", "relu", "add"]]
    # fetching the intermediate forbids fusing across it
    rep2 = main.analyze(fetch_list=[b, c])
    assert [c_["op_names"] for c_ in rep2.fusion_candidates] == [
        ["multiply", "relu"]]


# --------------------------------------------------------- roofline --
def test_roofline_specs_and_selection():
    main, loss = _mlp_program()
    rep = main.analyze(fetch_list=[loss], batch_size=4)
    assert set(rep.roofline) == set(CHIP_SPECS)
    for r in rep.roofline.values():
        assert r["predicted_step_s"] > 0
        assert 0 < r["predicted_mfu"] <= 1.0
        assert r["bound"] in ("compute", "memory")
        assert r["fits_hbm"] is True
    one = main.analyze(fetch_list=[loss], chip="v5e")
    assert list(one.roofline) == ["v5e"]
    with pytest.raises(KeyError, match="unknown chip"):
        main.analyze(fetch_list=[loss], chip="v9000")


# ----------------------------------------------------------- report --
def test_report_json_roundtrip_and_render():
    main, loss = _mlp_program()
    rep = main.analyze(fetch_list=[loss], batch_size=4)
    assert isinstance(rep, ProgramReport)
    d = json.loads(rep.to_json())
    assert d["ops"] == len(main.nodes)
    assert d["totals"]["flops_train"] == rep.totals["flops_train"]
    assert len(d["per_op"]) == len(main.nodes)
    assert d["memory"]["peak_bytes_donated"] > 0
    text = rep.render()
    for token in ("flops:", "memory:", "roofline", "fusion candidates",
                  "per-op:", "linear"):
        assert token in text, text


def test_anchors_flag_records_loc_without_verification():
    paddle.set_flags({"FLAGS_static_anchors": True})
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [4], "float32")
        y = x * 2.0  # <- anchor line
    assert main.nodes[0].loc is not None
    assert main.nodes[0].loc[0].endswith("test_analysis_cost.py")
    rep = main.analyze(fetch_list=[y])
    assert rep.per_op[0].loc and "test_analysis_cost.py:" in rep.per_op[0].loc
    # anchors alone never enable per-run verification
    exe = paddle.static.Executor()
    exe.run(main, feed={"x": np.zeros(4, np.float32)}, fetch_list=[y])
    assert exe._verified == set()


# ---------------------------------------------------------- hazards --
def test_wide_dtype_hazards():
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        a = paddle.static.data("a", [4], "float64")
        i = paddle.static.data("i", [4], "int64")
        b = a * 2.0
    diags = analysis.check(main)
    wide = [d for d in diags if d.pass_name == "wide-dtype"]
    sev = {d.var_name: d.severity for d in wide}
    assert sev["a"] == Diagnostic.WARNING       # f64: silently narrowed
    assert sev["i"] == Diagnostic.INFO          # i64: lands as int32
    # the recorded OUTPUT is already float32 — jnp canonicalized the
    # f64 away at record time, which is exactly the hazard's point
    assert str(b.data.dtype) == "float32" and b.name not in sev
    # hazards are warnings/infos: verify() must not raise on them
    main.verify()


def test_captured_const_hazard_severity_scales_with_bytes():
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [300000], "float32")
        x2 = paddle.static.data("x2", [2048], "float32")
        small = x + paddle.to_tensor(3.0)                   # scalar
        mid = x2 * paddle.to_tensor(
            np.ones(2048, np.float32))                      # 8 KiB
        big = x + paddle.to_tensor(
            np.ones(300000, np.float32))                    # ~1.2 MiB
    diags = [d for d in analysis.check(main)
             if d.pass_name == "host-transfer"]
    sevs = [d.severity for d in diags]
    assert sevs.count(Diagnostic.INFO) == 1      # recompile-prone scalar
    assert sevs.count(Diagnostic.WARNING) == 1   # 8 KiB const
    assert sevs.count(Diagnostic.ERROR) == 1     # data baked in program
    err = next(d for d in diags if d.severity == Diagnostic.ERROR)
    assert "baked into the compiled executable" in err.message
    # error-severity hazard fails verify(), like a verifier error
    from paddle_tpu.core.enforce import GraphVerificationError
    with pytest.raises(GraphVerificationError, match="host-transfer"):
        main.verify()


def test_donation_alias_hazard_on_tied_params():
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 8], "float32")
        l1, l2 = nn.Linear(8, 8), nn.Linear(8, 8)
        l2.weight.data = l1.weight.data          # tie by aliasing
        out = l2(l1(x))
    diags = [d for d in analysis.check(main)
             if d.pass_name == "donation-alias"]
    assert len(diags) == 1 and diags[0].severity == Diagnostic.WARNING
    assert "share one" in diags[0].message


def test_clean_program_has_no_hazards_and_check_stays_empty():
    main, loss = _mlp_program()
    assert [d for d in analysis.check(main, fetch_list=[loss])] == []
    rep = main.analyze(fetch_list=[loss])
    assert rep.hazards == []


# ------------------------------------------- executor integration --
def test_executor_records_prediction_per_compile():
    from paddle_tpu.observability import explain_compiles
    from paddle_tpu.utils import monitor

    main, loss = _mlp_program(hidden=8, depth=1)
    exe = paddle.static.Executor()
    feed = {"x": np.zeros((4, 8), np.float32),
            "y": np.zeros((4, 1), np.float32)}
    exe.run(main, feed=feed, fetch_list=[loss])
    recs = [r for r in explain_compiles("executor")["records"]
            if r["identity"] == main._serial]
    assert recs and "predicted" in recs[-1]
    pred = recs[-1]["predicted"]
    want = main.analyze(fetch_list=[loss])
    assert pred["flops_fwd"] == want.totals["flops_fwd"]
    assert pred["flops"] == want.totals["flops_train"]
    assert pred["peak_bytes"] == want.memory.peak_bytes_donated
    assert pred["unmodeled_ops"] == 0
    # prediction stays OUT of the attribution signature: a second feed
    # signature compiles with cause new_feed_signature, not unexplained
    feed2 = {"x": np.zeros((8, 8), np.float32),
             "y": np.zeros((8, 1), np.float32)}
    exe.run(main, feed=feed2, fetch_list=[loss])
    recs = [r for r in explain_compiles("executor")["records"]
            if r["identity"] == main._serial]
    assert recs[-1]["cause"] == "new_feed_signature"
    assert monitor.get_stat("predicted.executor.flops") == pred["flops"]
    assert monitor.get_stat("predicted.executor.peak_bytes") > 0
    exe.close()


def test_analyze_does_not_perturb_donated_training():
    """Reading shapes through the analyzer must not unbind or escape
    the executor-resident params (param_array peeks, never fetches)."""
    main, loss = _mlp_program(hidden=8, depth=1)
    exe = paddle.static.Executor()
    feed = {"x": np.ones((4, 8), np.float32),
            "y": np.ones((4, 1), np.float32)}
    exe.run(main, feed=feed, fetch_list=[loss])
    state = exe._states[main._serial]
    state.escaped.clear()
    main.analyze(fetch_list=[loss])       # peeks at bound params
    assert state.escaped == set()         # no slot was marked escaped
    l1, = exe.run(main, feed=feed, fetch_list=[loss])
    assert np.isfinite(l1).all()
    exe.close()


# ------------------------------------------------------ D2S104 lint --
def _fx_numpy_sync(x):
    v = x.sum()
    arr = v.numpy()
    return arr


def _fx_float_sync(x):
    s = float(x.sum())
    return s * 2


def _fx_concrete_conversions(x, n=3):
    b = int(x.shape[0])      # shape metadata: concrete, fine
    m = float(len(x))        # len() is concrete
    k = int(n)               # plain python param use... tainted too,
    return x * (b + m + k)   # but n is a param -> conservatively flagged


def test_lint_d2s104_numpy_and_item():
    from paddle_tpu.jit.lint import lint
    diags = lint(_fx_numpy_sync)
    assert [d.code for d in diags] == ["D2S104"]
    assert diags[0].severity == "error"  # nothing rewrites .numpy()
    assert "v.numpy()" in diags[0].message
    src = open(__file__).read().splitlines()[diags[0].line - 1]
    assert "v.numpy()" in src


def test_lint_d2s104_float_conversion_is_a_warning():
    from paddle_tpu.jit.lint import lint
    diags = lint(_fx_float_sync)
    assert [d.code for d in diags] == ["D2S104"]
    # the cast transformer LOWERS float() to astype — the code runs,
    # it just never yields a Python scalar; warning, not error
    assert diags[0].severity == "warning"
    assert "astype" in diags[0].message


def test_lint_d2s104_skips_concrete_metadata():
    from paddle_tpu.jit.lint import lint
    diags = lint(_fx_concrete_conversions)
    # int(x.shape[0]) and float(len(x)) are concrete; only int(n) (a
    # parameter, conservatively tensor-tainted) is flagged
    assert [d.code for d in diags] == ["D2S104"]
    assert "int(n)" in diags[0].message


def _fx_shadowed_float(x, float=None):
    return float(x)


def test_lint_d2s104_not_doubled_on_shadowed_builtin():
    from paddle_tpu.jit.lint import lint
    diags = lint(_fx_shadowed_float)
    # the shadowed builtin is D2S103's finding, not a host sync
    assert [d.code for d in diags] == ["D2S103"]


# ------------------------------------------------------------- CLIs --
_HAZARD_MODULE = """
import numpy as np
import paddle_tpu as paddle

main = paddle.static.Program()
with paddle.static.program_guard(main):
    x = paddle.static.data("x", [300000], "float32")
    big = x + paddle.to_tensor(np.ones(300000, np.float32))
    loss = big.sum()
"""


def _run_cli(mod, argv):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = mod.main(argv)
    return rc, buf.getvalue()


def _tool(name):
    sys.path.insert(0, TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.remove(TOOLS)


def test_lint_program_json_and_hazard_exit(tmp_path):
    mod = tmp_path / "hazard_script.py"
    mod.write_text(_HAZARD_MODULE)
    lint_program = _tool("lint_program")
    rc, out = _run_cli(lint_program, [str(mod), "--format", "json"])
    rep = json.loads(out)          # machine-readable: parses as one doc
    assert rc == 1                 # error-severity HAZARD fails the run
    assert rep["errors"] == 1 and rep["programs"]
    diags = rep["programs"][0]["diagnostics"]
    err = next(d for d in diags if d["severity"] == "error")
    assert err["pass_name"] == "host-transfer"
    assert err["loc"] and "hazard_script.py" in err["loc"]


def test_analyze_program_cli_text_and_json(tmp_path):
    mod = tmp_path / "train_mod.py"
    mod.write_text(
        "import paddle_tpu as paddle\n"
        "import paddle_tpu.nn.functional as F\n"
        "from paddle_tpu import optimizer\n"
        "main = paddle.static.Program()\n"
        "with paddle.static.program_guard(main):\n"
        "    x = paddle.static.data('x', [None, 8], 'float32')\n"
        "    y = paddle.static.data('y', [None, 1], 'float32')\n"
        "    loss = F.mse_loss(paddle.static.nn.fc(x, 1), y)\n"
        "    optimizer.Adam(learning_rate=1e-3).minimize(loss)\n"
        "loss.name = 'loss'\n")
    analyze_program = _tool("analyze_program")
    rc, out = _run_cli(analyze_program,
                       [str(mod), "--fetch", "loss", "--batch-size", "4"])
    assert rc == 0, out
    assert "roofline (predicted):" in out and "fusion candidates" in out
    assert "train_mod.py:" in out      # FLAGS_static_anchors anchored
    rc, out = _run_cli(
        analyze_program,
        [str(mod), "--format", "json", "--batch-size", "4", "--chip",
         "v5e"])
    assert rc == 0
    doc = json.loads(out)
    rep = doc["programs"][0]["report"]
    # fc: 2*B*8*1 matmul + B bias; mse: 4 per output element (B=4)
    assert rep["totals"]["flops_fwd"] == 2 * 4 * 8 + 4 + 4 * 4
    assert list(rep["roofline"]) == ["v5e"]


def test_analyze_smoke_in_process():
    smoke = _tool("analyze_smoke")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = smoke.main()
    assert rc == 0, buf.getvalue()
    assert "analyze_smoke: PASS" in buf.getvalue()
