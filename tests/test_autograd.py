"""Autograd tape tests, modelled on the reference's dygraph autograd suite
(test_imperative_basic.py, test_imperative_auto_prune.py) plus numeric
gradient checking in the OpTest style (op_test.py:110 get_numeric_gradient).
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 4, 6])


def test_chain_rule_two_ops():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x          # 4
    z = y * x          # 8  => dz/dx = 3x^2 = 12
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [12.0])


def test_grad_accumulation_across_backwards():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    x.clear_grad()
    assert x.grad is None


def test_stop_gradient_blocks_flow():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * x
    d = y.detach()
    z = (d * x).sum()
    z.backward()
    # only the direct x factor contributes: dz/dx = d = 9
    np.testing.assert_allclose(x.grad.numpy(), [9.0])


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * x
    assert y.stop_gradient


def test_matmul_grad():
    a = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(np.random.rand(4, 5).astype(np.float32),
                         stop_gradient=False)
    out = paddle.matmul(a, b).sum()
    out.backward()
    np.testing.assert_allclose(a.grad.numpy(),
                               np.ones((3, 5)) @ b.numpy().T, rtol=1e-5)
    np.testing.assert_allclose(b.grad.numpy(),
                               a.numpy().T @ np.ones((3, 5)), rtol=1e-5)


def test_broadcast_grad():
    a = paddle.to_tensor(np.ones((3, 4), np.float32), stop_gradient=False)
    b = paddle.to_tensor(np.ones((4,), np.float32), stop_gradient=False)
    (a + b).sum().backward()
    np.testing.assert_allclose(b.grad.numpy(), [3, 3, 3, 3])


def test_non_scalar_backward_needs_grad():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        y.backward()
    y = x * 2
    y.backward(paddle.to_tensor([1.0, 0.5]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 1.0])


def test_paddle_grad_api():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x * x
    (gx,) = paddle.grad(y, [x])
    np.testing.assert_allclose(gx.numpy(), [12.0])
    assert x.grad is None  # paddle.grad must not touch .grad


def test_grad_allow_unused():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    z = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        paddle.grad(y, [x, z], retain_graph=True)
    gx, gz = paddle.grad(y, [x, z], allow_unused=True)
    np.testing.assert_allclose(gx.numpy(), [2.0])
    assert gz is None


def test_register_hook():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    seen = []
    x.register_hook(lambda g: seen.append(g.numpy()))
    (x * 5).backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [5.0])


def test_hook_modifies_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    x.register_hook(lambda g: g * 2)
    (x * 5).backward()
    np.testing.assert_allclose(x.grad.numpy(), [10.0])


def numeric_grad(f, x, eps=1e-3):
    """Finite-difference oracle, OpTest-style (op_test.py:110)."""
    g = np.zeros_like(x)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        f1 = f(x)
        flat[i] = orig - eps
        f0 = f(x)
        flat[i] = orig
        gf[i] = (f1 - f0) / (2 * eps)
    return g


@pytest.mark.parametrize("op,np_op", [
    ("exp", np.exp),
    ("tanh", np.tanh),
    ("sqrt", np.sqrt),
    ("log", np.log),
    ("sigmoid", lambda v: 1 / (1 + np.exp(-v))),
])
def test_numeric_gradient_match(op, np_op):
    xv = np.random.rand(3, 4).astype(np.float64) * 0.8 + 0.1
    x = paddle.to_tensor(xv.astype(np.float32), stop_gradient=False)
    y = getattr(paddle, op)(x).sum()
    y.backward()
    ng = numeric_grad(lambda a: np_op(a).sum(), xv.copy())
    np.testing.assert_allclose(x.grad.numpy(), ng, rtol=1e-2, atol=1e-3)


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32), stop_gradient=False)
    parts = paddle.split(x, 3)
    loss = (parts[0] * 1 + parts[1] * 2 + parts[2] * 3).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [1, 1, 2, 2, 3, 3])


def test_setitem_grad_flow():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = x * 2
    y[0] = 0.0
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [0.0, 2.0, 2.0])


def test_second_use_of_intermediate():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 3
    z = y + y        # dz/dx = 6
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_two_independent_graphs():
    """Regression: one backward must not clobber other live graphs."""
    a = paddle.to_tensor([1.0], stop_gradient=False)
    l1 = (a * 2).sum()
    l2 = (a * 3).sum()
    l1.backward()
    l2.backward()
    np.testing.assert_allclose(a.grad.numpy(), [5.0])


def test_second_backward_raises():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0])
    with pytest.raises(RuntimeError):
        y.backward()


def test_grad_wrt_intermediate():
    """Regression: paddle.grad w.r.t. a non-leaf tensor."""
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * 2
    z = (y * y).sum()
    (gy,) = paddle.grad(z, [y])
    np.testing.assert_allclose(gy.numpy(), [12.0])  # 2y = 12


def test_forward_only_does_not_leak_graph():
    import gc
    import weakref
    from paddle_tpu.core.autograd import Node
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    ref = weakref.ref(y._node)
    del y
    gc.collect()
    assert ref() is None  # node died with its output tensor
