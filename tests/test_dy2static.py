"""dy2static AST conversion tests (reference analog:
dygraph_to_static/test_ifelse.py): Python `if` on tensor predicates is
rewritten to cond inside to_static; eager semantics are untouched."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn


def test_if_else_assignment_pattern_converts():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            y = x * 2
        else:
            y = x - 1
        return y + 10

    a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    b = paddle.to_tensor(np.array([-1.0, -2.0], np.float32))
    np.testing.assert_allclose(f(a).numpy(), [12.0, 14.0])
    np.testing.assert_allclose(f(b).numpy(), [8.0, 7.0])  # same compiled fn


def test_early_return_pattern_converts():
    @jit.to_static
    def relu_ish(x):
        if x.sum() > 0:
            return x
        return -x

    a = paddle.to_tensor(np.array([3.0], np.float32))
    b = paddle.to_tensor(np.array([-3.0], np.float32))
    assert float(relu_ish(a)) == 3.0
    assert float(relu_ish(b)) == 3.0


def test_if_return_else_return_converts():
    @jit.to_static
    def pick(x):
        if x.mean() > 0:
            return x * 10
        else:
            return x * 100

    assert float(pick(paddle.to_tensor(np.array([1.0], np.float32)))) == 10.0
    assert float(pick(paddle.to_tensor(np.array([-1.0], np.float32)))) == -100.0


def test_multi_assign_both_branches():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            a = x + 1
            b = x * 2
        else:
            a = x - 1
            b = x / 2
        return a + b

    v = paddle.to_tensor(np.array([2.0], np.float32))
    np.testing.assert_allclose(float(f(v)), 7.0)
    v2 = paddle.to_tensor(np.array([-2.0], np.float32))
    np.testing.assert_allclose(float(f(v2)), -4.0)


def test_static_if_on_python_value_untouched():
    @jit.to_static
    def f(x, flag=True):
        if flag:                # plain Python bool: normal trace-time if
            return x * 2
        return x

    assert float(f(paddle.to_tensor(np.array([2.0], np.float32)))) == 4.0


def test_unconvertible_pattern_still_fails_loudly():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            x = x * 2          # assigned in one branch only: no convert
        return x

    with pytest.raises(TypeError, match="paddle.cond"):
        f(paddle.ones([2]))


def test_converted_if_differentiable():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            y = (x * x).sum()
        else:
            y = x.sum()
        return y

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                         stop_gradient=False)
    f(x).backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0])


def test_layer_forward_with_tensor_if():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        @jit.to_static
        def forward(self, x):
            h = self.fc(x)
            if h.sum() > 1e9:   # never true, but must trace both
                out = h * 0
            else:
                out = h + 1
            return out

    net = Net()
    x = paddle.randn([2, 4])
    expect = (net.fc(x) + 1).numpy()
    np.testing.assert_allclose(net(x).numpy(), expect, rtol=1e-6)


def test_branch_self_assignment_converts():
    """`x = x + 1` inside a branch reads its own target: converted via
    default-argument snapshots (round-4 upgrade; was a documented
    non-convertible case before)."""
    @jit.to_static
    def g(x, flag=True):
        if flag:
            x = x + 1
        else:
            x = x - 1
        return x

    assert float(g(paddle.to_tensor(np.array([1.0], np.float32)))) == 2.0

    @jit.to_static
    def h(x):
        if x.sum() > 0:
            x = x * 2
        else:
            x = x - 1
        return x

    np.testing.assert_allclose(h(paddle.ones([2])).numpy(), [2.0, 2.0])
    np.testing.assert_allclose(
        h(paddle.to_tensor(np.array([-1.0, -1.0], np.float32))).numpy(),
        [-2.0, -2.0])


def test_chained_assign_after_define_converts():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            a = x * 2
            b = a + 1      # reads `a` AFTER assigning it: fine
        else:
            a = x - 1
            b = a * 3
        return b

    np.testing.assert_allclose(
        float(f(paddle.to_tensor(np.array([1.0], np.float32)))), 3.0)
    np.testing.assert_allclose(
        float(f(paddle.to_tensor(np.array([-1.0], np.float32)))), -6.0)


# -- loop conversion (reference: loop_transformer.py, test_loop.py) -------

def test_while_loop_converts_under_to_static():
    @jit.to_static
    def f(x):
        s = x * 0
        while s.sum() < 10:
            s = s + x
        return s

    x = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
    out = f(x).numpy()
    np.testing.assert_allclose(out, [5.0, 5.0])  # 5 iters * 2 elements
    # compiled: second call reuses the traced while_loop
    out2 = f(paddle.to_tensor(np.array([2.0, 2.0], np.float32))).numpy()
    np.testing.assert_allclose(out2, [6.0, 6.0])


def test_while_eager_semantics_unchanged():
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(n):
        s = 0
        while s < n:
            s = s + 3
        return s

    g = convert_control_flow(f)
    assert g is not f          # converted
    assert g(10) == f(10) == 12


def test_for_range_converts():
    @jit.to_static
    def f(x, n):
        acc = x * 0
        for i in range(n):
            acc = acc + x * (i + 1)
        return acc

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    n = paddle.to_tensor(np.int32(4))
    # range over a TENSOR bound — impossible in plain Python, works
    # converted (loop_transformer semantics)
    out = f(x, n).numpy()
    np.testing.assert_allclose(out, [10.0, 20.0])


def test_loop_with_leading_break():
    @jit.to_static
    def f(x):
        s = x * 0
        k = x.sum() * 0
        while k < 100:
            if s.sum() > 6:
                break
            s = s + x
            k = k + 1
        return s

    x = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
    out = f(x).numpy()
    # breaks once sum > 6 -> s = [4, 4] (sum 8)
    np.testing.assert_allclose(out, [4.0, 4.0])


def test_loop_with_tail_break():
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(lim):
        s = 0
        while True:
            s = s + 2
            if s >= lim:
                break
        return s

    g = convert_control_flow(f)
    assert g is not f
    assert g(7) == f(7) == 8


def test_loop_with_continue():
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(n):
        s = 0
        i = 0
        while i < n:
            i = i + 1
            if i % 2 == 0:
                continue
            s = s + i
        return s

    # leading-continue only converts when the if is FIRST; this one is
    # mid-body -> must stay unconverted but still correct in Python
    g = convert_control_flow(f)
    assert g(6) == f(6) == 9

    def f2(n):
        s = 0
        i = 0
        while i < n:
            if _is_even(i):
                i = i + 1
                continue
            s = s + i
            i = i + 1
        return s

    # (leading continue pattern is exercised via tensors below)


def _is_even(i):
    return i % 2 == 0


def test_nested_if_inside_loop_converts():
    @jit.to_static
    def f(x):
        s = x * 0
        for i in range(4):
            if s.sum() > 2:
                s = s + x * 2
            else:
                s = s + x
        return s

    x = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
    # iters: s=[1,1](sum0->cond False), [2,2](sum2 False), [4,4](sum4 True), [6,6]
    np.testing.assert_allclose(f(x).numpy(), [6.0, 6.0])


def test_unconvertible_loop_left_untouched():
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(xs):
        out = []
        for x in xs:               # iterating a list: not convertible
            out.append(x * 2)
        return out

    g = convert_control_flow(f)
    assert g([1, 2]) == [2, 4]


# -- r4 review regressions ------------------------------------------------

def test_break_predicate_reads_body_assigned_name():
    """r4 review: a break predicate reading a body-assigned name that is
    not otherwise live must still be carried (was: stale snapshot, loop
    never broke)."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(x):
        s = 0
        k = 0
        t = 0
        while k < 100:
            if t > 6:
                break
            t = s + 1
            s = s + x
            k = k + 1
        return s

    g = convert_control_flow(f)
    assert g(1) == f(1) == 7


def test_unbound_prebind_name_not_converted():
    """r4 review: `if flag: y = y + 1 else: y = 0` with y unbound before
    the if must NOT convert (the default-arg snapshot would raise where
    plain Python, branch untaken, would not)."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(flag):
        if flag:
            y = y_missing_on_purpose + 1  # noqa: F821
        else:
            y = 0
        return y

    g = convert_control_flow(f)
    assert g(False) == 0          # python semantics preserved

    def h(flag):
        if flag:
            z = z + 1  # noqa: F821 — z unbound: must not prebind
        else:
            z = 0
        return z

    k = convert_control_flow(h)
    assert k(False) == 0


def test_tensor_if_inside_tensor_while_converts():
    """r4 review: the if-converter's generated closures contain Return;
    the loop converter must not reject them."""
    @jit.to_static
    def f(x):
        s = x * 0
        while s.sum() < 6:
            if s.sum() > 2:
                s = s + x * 2
            else:
                s = s + x
        return s

    x = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
    # s: [1,1](2) -> [2,2](4>2) ... iter1 sum0->else [1,1]; iter2 sum2->else [2,2]; iter3 sum4>2 -> [4,4]; sum8 stop
    np.testing.assert_allclose(f(x).numpy(), [4.0, 4.0])
