"""dy2static AST conversion tests (reference analog:
dygraph_to_static/test_ifelse.py): Python `if` on tensor predicates is
rewritten to cond inside to_static; eager semantics are untouched."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn


def test_if_else_assignment_pattern_converts():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            y = x * 2
        else:
            y = x - 1
        return y + 10

    a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    b = paddle.to_tensor(np.array([-1.0, -2.0], np.float32))
    np.testing.assert_allclose(f(a).numpy(), [12.0, 14.0])
    np.testing.assert_allclose(f(b).numpy(), [8.0, 7.0])  # same compiled fn


def test_early_return_pattern_converts():
    @jit.to_static
    def relu_ish(x):
        if x.sum() > 0:
            return x
        return -x

    a = paddle.to_tensor(np.array([3.0], np.float32))
    b = paddle.to_tensor(np.array([-3.0], np.float32))
    assert float(relu_ish(a)) == 3.0
    assert float(relu_ish(b)) == 3.0


def test_if_return_else_return_converts():
    @jit.to_static
    def pick(x):
        if x.mean() > 0:
            return x * 10
        else:
            return x * 100

    assert float(pick(paddle.to_tensor(np.array([1.0], np.float32)))) == 10.0
    assert float(pick(paddle.to_tensor(np.array([-1.0], np.float32)))) == -100.0


def test_multi_assign_both_branches():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            a = x + 1
            b = x * 2
        else:
            a = x - 1
            b = x / 2
        return a + b

    v = paddle.to_tensor(np.array([2.0], np.float32))
    np.testing.assert_allclose(float(f(v)), 7.0)
    v2 = paddle.to_tensor(np.array([-2.0], np.float32))
    np.testing.assert_allclose(float(f(v2)), -4.0)


def test_static_if_on_python_value_untouched():
    @jit.to_static
    def f(x, flag=True):
        if flag:                # plain Python bool: normal trace-time if
            return x * 2
        return x

    assert float(f(paddle.to_tensor(np.array([2.0], np.float32)))) == 4.0


def test_single_arm_if_converts():
    """`if c: x = x * 2` with x pre-bound synthesizes an identity else
    (round-5 extension; this used to bail)."""
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            x = x * 2
        return x

    assert float(np.asarray(f(paddle.to_tensor(
        np.array([3.0], np.float32))).data)[0]) == 6.0
    assert float(np.asarray(f(paddle.to_tensor(
        np.array([-3.0], np.float32))).data)[0]) == -3.0


def test_unconvertible_pattern_still_fails_loudly():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            y = x * 2      # branches assign DIFFERENT names: no convert
        else:
            z = x
        return x

    with pytest.raises(TypeError, match="paddle.cond"):
        f(paddle.ones([2]))


def test_converted_if_differentiable():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            y = (x * x).sum()
        else:
            y = x.sum()
        return y

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                         stop_gradient=False)
    f(x).backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0])


def test_layer_forward_with_tensor_if():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        @jit.to_static
        def forward(self, x):
            h = self.fc(x)
            if h.sum() > 1e9:   # never true, but must trace both
                out = h * 0
            else:
                out = h + 1
            return out

    net = Net()
    x = paddle.randn([2, 4])
    expect = (net.fc(x) + 1).numpy()
    np.testing.assert_allclose(net(x).numpy(), expect, rtol=1e-6)


def test_branch_self_assignment_converts():
    """`x = x + 1` inside a branch reads its own target: converted via
    default-argument snapshots (round-4 upgrade; was a documented
    non-convertible case before)."""
    @jit.to_static
    def g(x, flag=True):
        if flag:
            x = x + 1
        else:
            x = x - 1
        return x

    assert float(g(paddle.to_tensor(np.array([1.0], np.float32)))) == 2.0

    @jit.to_static
    def h(x):
        if x.sum() > 0:
            x = x * 2
        else:
            x = x - 1
        return x

    np.testing.assert_allclose(h(paddle.ones([2])).numpy(), [2.0, 2.0])
    np.testing.assert_allclose(
        h(paddle.to_tensor(np.array([-1.0, -1.0], np.float32))).numpy(),
        [-2.0, -2.0])


def test_chained_assign_after_define_converts():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            a = x * 2
            b = a + 1      # reads `a` AFTER assigning it: fine
        else:
            a = x - 1
            b = a * 3
        return b

    np.testing.assert_allclose(
        float(f(paddle.to_tensor(np.array([1.0], np.float32)))), 3.0)
    np.testing.assert_allclose(
        float(f(paddle.to_tensor(np.array([-1.0], np.float32)))), -6.0)


# -- loop conversion (reference: loop_transformer.py, test_loop.py) -------

def test_while_loop_converts_under_to_static():
    @jit.to_static
    def f(x):
        s = x * 0
        while s.sum() < 10:
            s = s + x
        return s

    x = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
    out = f(x).numpy()
    np.testing.assert_allclose(out, [5.0, 5.0])  # 5 iters * 2 elements
    # compiled: second call reuses the traced while_loop
    out2 = f(paddle.to_tensor(np.array([2.0, 2.0], np.float32))).numpy()
    np.testing.assert_allclose(out2, [6.0, 6.0])


def test_while_eager_semantics_unchanged():
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(n):
        s = 0
        while s < n:
            s = s + 3
        return s

    g = convert_control_flow(f)
    assert g is not f          # converted
    assert g(10) == f(10) == 12


def test_for_range_converts():
    @jit.to_static
    def f(x, n):
        acc = x * 0
        for i in range(n):
            acc = acc + x * (i + 1)
        return acc

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    n = paddle.to_tensor(np.int32(4))
    # range over a TENSOR bound — impossible in plain Python, works
    # converted (loop_transformer semantics)
    out = f(x, n).numpy()
    np.testing.assert_allclose(out, [10.0, 20.0])


def test_loop_with_leading_break():
    @jit.to_static
    def f(x):
        s = x * 0
        k = x.sum() * 0
        while k < 100:
            if s.sum() > 6:
                break
            s = s + x
            k = k + 1
        return s

    x = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
    out = f(x).numpy()
    # breaks once sum > 6 -> s = [4, 4] (sum 8)
    np.testing.assert_allclose(out, [4.0, 4.0])


def test_loop_with_tail_break():
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(lim):
        s = 0
        while True:
            s = s + 2
            if s >= lim:
                break
        return s

    g = convert_control_flow(f)
    assert g is not f
    assert g(7) == f(7) == 8


def test_loop_with_continue():
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(n):
        s = 0
        i = 0
        while i < n:
            i = i + 1
            if i % 2 == 0:
                continue
            s = s + i
        return s

    # leading-continue only converts when the if is FIRST; this one is
    # mid-body -> must stay unconverted but still correct in Python
    g = convert_control_flow(f)
    assert g(6) == f(6) == 9

    def f2(n):
        s = 0
        i = 0
        while i < n:
            if _is_even(i):
                i = i + 1
                continue
            s = s + i
            i = i + 1
        return s

    # (leading continue pattern is exercised via tensors below)


def _is_even(i):
    return i % 2 == 0


def test_nested_if_inside_loop_converts():
    @jit.to_static
    def f(x):
        s = x * 0
        for i in range(4):
            if s.sum() > 2:
                s = s + x * 2
            else:
                s = s + x
        return s

    x = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
    # iters: s=[1,1](sum0->cond False), [2,2](sum2 False), [4,4](sum4 True), [6,6]
    np.testing.assert_allclose(f(x).numpy(), [6.0, 6.0])


def test_unconvertible_loop_left_untouched():
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(xs):
        out = []
        for x in xs:               # iterating a list: not convertible
            out.append(x * 2)
        return out

    g = convert_control_flow(f)
    assert g([1, 2]) == [2, 4]


# -- r4 review regressions ------------------------------------------------

def test_break_predicate_reads_body_assigned_name():
    """r4 review: a break predicate reading a body-assigned name that is
    not otherwise live must still be carried (was: stale snapshot, loop
    never broke)."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(x):
        s = 0
        k = 0
        t = 0
        while k < 100:
            if t > 6:
                break
            t = s + 1
            s = s + x
            k = k + 1
        return s

    g = convert_control_flow(f)
    assert g(1) == f(1) == 7


def test_unbound_prebind_name_not_converted():
    """r4 review: `if flag: y = y + 1 else: y = 0` with y unbound before
    the if must NOT convert (the default-arg snapshot would raise where
    plain Python, branch untaken, would not)."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def f(flag):
        if flag:
            y = y_missing_on_purpose + 1  # noqa: F821
        else:
            y = 0
        return y

    g = convert_control_flow(f)
    assert g(False) == 0          # python semantics preserved

    def h(flag):
        if flag:
            z = z + 1  # noqa: F821 — z unbound: must not prebind
        else:
            z = 0
        return z

    k = convert_control_flow(h)
    assert k(False) == 0


def test_tensor_if_inside_tensor_while_converts():
    """r4 review: the if-converter's generated closures contain Return;
    the loop converter must not reject them."""
    @jit.to_static
    def f(x):
        s = x * 0
        while s.sum() < 6:
            if s.sum() > 2:
                s = s + x * 2
            else:
                s = s + x
        return s

    x = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
    # s: [1,1](2) -> [2,2](4>2) ... iter1 sum0->else [1,1]; iter2 sum2->else [2,2]; iter3 sum4>2 -> [4,4]; sum8 stop
    np.testing.assert_allclose(f(x).numpy(), [4.0, 4.0])


# ---- round-5 breadth (VERDICT r4 #5): break/continue anywhere, early
# return in loops, converted nested calls --------------------------------

def _eager_vs_static(fn, *inputs):
    """Run eager and to_static on the same inputs; outputs must match."""
    eager = fn(*inputs)
    static = jit.to_static(fn)(*inputs)
    np.testing.assert_allclose(np.asarray(static.data),
                               np.asarray(eager.data), rtol=1e-6)
    return static


def test_mid_body_break():
    def f(x):
        s = paddle.zeros([2])
        i = 0
        while i < 10:
            s = s + x
            if s.sum() > 6:
                break
            s = s * 1.5
            i = i + 1
        return s

    x = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
    _eager_vs_static(f, x)


def test_mid_body_continue_in_for():
    def f(x):
        s = x * 0
        for i in range(6):
            s = s + x
            if s.sum() > 4:
                continue
            s = s + 100 * x  # skipped once the running sum passes 4
        return s

    x = paddle.to_tensor(np.array([1.0], np.float32))
    _eager_vs_static(f, x)


def test_multiple_exits_mixed():
    def f(x):
        s = x * 0
        for i in range(8):
            if s.sum() > 20:
                break
            s = s + x
            if s.sum() < 2:
                continue
            s = s * 2
        return s

    for v in (0.5, 1.0, 3.0):
        x = paddle.to_tensor(np.array([v], np.float32))
        _eager_vs_static(f, x)


def test_break_with_payload_assignment():
    def f(x):
        s = x * 0
        flag = paddle.zeros([1])
        for i in range(5):
            s = s + x
            if s.sum() > 2:
                flag = flag + 1
                break
        return s + flag * 10

    x = paddle.to_tensor(np.array([1.0], np.float32))
    _eager_vs_static(f, x)


def test_early_return_inside_loop():
    def f(x):
        s = x * 0
        for i in range(10):
            s = s + x
            if s.sum() > 3:
                return s * 100
        return s

    # one input that trips the early return, one that does not
    hit = paddle.to_tensor(np.array([1.0], np.float32))
    miss = paddle.to_tensor(np.array([0.1], np.float32))
    _eager_vs_static(f, hit)
    _eager_vs_static(f, miss)


def test_early_return_inside_while():
    def f(x):
        s = x * 0
        i = 0
        while i < 20:
            s = s + x
            if s.sum() > 5:
                return -s
            i = i + 1
        return s

    _eager_vs_static(f, paddle.to_tensor(np.array([2.0], np.float32)))
    _eager_vs_static(f, paddle.to_tensor(np.array([0.1], np.float32)))


def _helper_double_or_neg(v):
    # module-level helper with a tensor if: must be converted when
    # called from a to_static fn (call_transformer parity)
    if v.sum() > 0:
        return v * 2
    return -v


def test_nested_call_converts():
    def f(x):
        y = _helper_double_or_neg(x)
        return y + 1

    pos = paddle.to_tensor(np.array([2.0], np.float32))
    neg = paddle.to_tensor(np.array([-2.0], np.float32))
    _eager_vs_static(f, pos)
    _eager_vs_static(f, neg)


def test_nested_call_inside_loop_converts():
    def f(x):
        s = x * 0
        for i in range(4):
            s = _helper_double_or_neg(s + x)
        return s

    _eager_vs_static(f, paddle.to_tensor(np.array([1.0], np.float32)))
    _eager_vs_static(f, paddle.to_tensor(np.array([-1.0], np.float32)))


def test_nested_call_shadowed_name_stays_loud():
    """A call through a local alias cannot be resolved at conversion
    time: the callee runs UNCONVERTED, and its tensor-if raises the
    loud trace error instead of silently mistracing (design rule)."""
    def f(x):
        _local = _helper_double_or_neg
        return _local(x)

    x = paddle.to_tensor(np.array([1.5], np.float32))
    assert float(f(x).data[0]) == 3.0  # eager path unaffected
    with pytest.raises(TypeError, match="paddle.cond"):
        jit.to_static(f)(x)


def test_jst_call_passthrough():
    from paddle_tpu.jit.dy2static import _jst_call
    assert _jst_call(len) is len            # builtin
    assert _jst_call(range) is range        # type
    assert _jst_call(np.sum) is np.sum      # library fn
    obj = object()
    assert _jst_call(obj) is obj            # arbitrary value
    # user helper converts and is memoized
    c1 = _jst_call(_helper_double_or_neg)
    c2 = _jst_call(_helper_double_or_neg)
    assert c1 is c2 and c1 is not _helper_double_or_neg


def test_traced_loop_break_lowers_to_while():
    """The converted loop must lower to ONE lax.while under to_static:
    the body traces once, it does not run per iteration or unroll."""
    calls = [0]

    def probe(v):
        calls[0] += 1  # python side effect: fires once per TRACE
        return v

    def f(x):
        s = x * 0
        for i in range(100):
            s = s + probe(x)
            if s.sum() > 10:
                break
        return s

    g = jit.to_static(f)
    out = g(paddle.to_tensor(np.array([3.0], np.float32)))
    assert float(np.asarray(out.data)[0]) == 12.0  # 3,6,9,12 -> break
    # bounded tracing (lax.while traces the body twice for the carry
    # fixed-point) — NOT 4 eager iterations, not 100 unrolled
    assert calls[0] <= 2, calls[0]


def test_return_of_body_temp_bails_loudly():
    """Early return of a body-local temp can't init the carry pre-loop:
    the loop must stay unconverted and raise the LOUD trace error, never
    a NameError from generated code."""
    def f(x):
        s = x * 0
        for i in range(5):
            t = x * 2.0
            if t.sum() > 3:
                return t
            s = s + t
        return s

    x = paddle.to_tensor(np.array([2.0], np.float32))
    assert float(f(x).data[0]) == 4.0  # eager: t=4 > 3 on iter 0
    with pytest.raises(TypeError, match="paddle.cond"):
        jit.to_static(f)(x)


def test_return_reading_loop_index_bails_loudly():
    def f(x):
        s = x * 0
        for i in range(5):
            s = s + x
            if s.sum() > 2:
                return s * i
        return s

    x = paddle.to_tensor(np.array([1.0], np.float32))
    with pytest.raises(TypeError, match="paddle.cond"):
        jit.to_static(f)(x)


def test_payload_name_without_preloop_binding_bails_loudly():
    def f(x):
        s = x * 0
        for i in range(5):
            s = s + x
            if s.sum() > 2:
                msg = s * 0
                break
        return s + msg  # noqa: F821 - bound only when the break fires

    x = paddle.to_tensor(np.array([1.0], np.float32))
    assert float(f(x).data[0]) == 3.0  # eager: break fires, msg bound
    with pytest.raises(TypeError, match="paddle.cond"):
        jit.to_static(f)(x)


def test_return_in_loop_with_nontrailing_return_bails_loudly():
    def f(x):
        s = x * 0
        for i in range(10):
            s = s + x
            if s.sum() > 3:
                return s * 100
        y = s * 2
        return y

    x = paddle.to_tensor(np.array([1.0], np.float32))
    assert float(f(x).data[0]) == 400.0
    with pytest.raises(TypeError, match="paddle.cond"):
        jit.to_static(f)(x)


# ---- print / cast / assert transformers (reference: print_transformer,
# cast_transformer, assert_transformer) ----------------------------------

def test_print_inside_traced_fn(capfd):
    @jit.to_static
    def f(x):
        y = x * 2
        print("value:", y)
        return y + 1

    out = f(paddle.to_tensor(np.array([3.0], np.float32)))
    assert float(out.data[0]) == 7.0
    # jax.debug.print emits the RUNTIME value (not a tracer repr)
    captured = capfd.readouterr()
    text = captured.out + captured.err
    assert "6." in text and "Tracer" not in text


def test_print_in_converted_loop(capfd):
    @jit.to_static
    def f(x):
        s = x * 0
        for i in range(3):
            s = s + x
            print(s)
        return s

    out = f(paddle.to_tensor(np.array([1.0], np.float32)))
    assert float(out.data[0]) == 3.0
    cap = capfd.readouterr()
    text = cap.out + cap.err
    # one print per ITERATION at runtime (3 values), not one per trace
    assert text.count("[") >= 3, text


def test_cast_on_traced_tensor():
    @jit.to_static
    def f(x):
        i = int(x)          # -> astype int64 under trace
        fl = float(i)       # -> astype float32
        return fl * 2

    out = f(paddle.to_tensor(np.array([3.7], np.float32)))
    assert float(out.data[0]) == 6.0  # trunc to 3 then *2
    # eager parity: builtin semantics preserved (python scalar)
    assert int(np.asarray(paddle.to_tensor(
        np.array([3.7], np.float32)).data)[0] * 0 + 3.7) == 3


def test_cast_concrete_passthrough():
    @jit.to_static
    def f(x, k):
        n = int(k)          # concrete python value -> builtin int
        return x * n

    out = f(paddle.to_tensor(np.array([2.0], np.float32)), 3.9)
    assert float(out.data[0]) == 6.0


def test_assert_traced_checks_at_runtime():
    @jit.to_static
    def f(x):
        assert x.sum() > 0, "must be positive"
        return x * 2

    ok = f(paddle.to_tensor(np.array([1.0], np.float32)))
    assert float(ok.data[0]) == 2.0
    with pytest.raises(Exception, match="must be positive"):
        out = f(paddle.to_tensor(np.array([-1.0], np.float32)))
        np.asarray(out.data)  # force execution on async backends


def test_assert_concrete_keeps_python_semantics():
    def g(flag):
        assert flag, "nope"
        return 1

    conv = jit.to_static(g)
    assert conv(True) == 1
    with pytest.raises(AssertionError, match="nope"):
        conv(False)


def test_shadowed_builtin_names_untouched():
    """A param/local/module binding named int/float/bool/print must NOT
    be hijacked by the builtin transformer (review-confirmed repro)."""
    def h(x, int):
        if x.sum() > 0:  # force conversion
            y = x
        else:
            y = -x
        return y * int(x)

    out = jit.to_static(h)(
        paddle.to_tensor(np.array([2.0], np.float32)), lambda v: 10.0)
    assert float(np.asarray(out.data)[0]) == 20.0


def test_bt_only_conversion_keeps_live_closures():
    """A function whose only convertible construct is a print must not
    be recompiled when it has a closure — recompiling snapshots cells
    and freezes live nonlocals (review-confirmed repro).  Checked at
    the convert_control_flow level: under to_static's jit cache,
    closures are trace-time constants anyway."""
    from paddle_tpu.jit.dy2static import convert_control_flow

    def outer():
        factor = [2.0]
        state = {"factor": 2.0}

        def set_factor(v):
            state["factor"] = v
            nonlocal_set(v)

        def nonlocal_set(v):
            nonlocal real_factor
            real_factor = v

        real_factor = 2.0

        def inner(x):
            print("factor is", real_factor)
            return x * real_factor

        return inner, set_factor

    inner, set_factor = outer()
    conv = convert_control_flow(inner)
    assert conv is inner  # closure-bearing, bt-only: left untouched
    assert conv(1.0) == 2.0
    set_factor(5.0)
    assert conv(1.0) == 5.0  # closure stays LIVE


def test_assert_msg_lazy():
    calls = [0]

    def expensive():
        calls[0] += 1
        return "boom"

    @jit.to_static
    def f(x):
        assert x.sum() > 0, expensive()
        return x * 2

    f(paddle.to_tensor(np.array([1.0], np.float32)))
    assert calls[0] == 0  # passing assert never evaluates the message


def test_print_sep_honored_and_file_falls_back(capfd):
    @jit.to_static
    def f(x):
        print("v", x, sep="|")
        return x

    f(paddle.to_tensor(np.array([1.0], np.float32)))
    cap = capfd.readouterr()
    assert "v|" in (cap.out + cap.err)


def test_print_assert_fallback_without_host_callbacks(monkeypatch):
    """Backends without host callbacks (axon tunnel) degrade to the
    pre-conversion behavior: trace-time print, loud assert error."""
    from paddle_tpu.jit import dy2static as d2s
    monkeypatch.setattr(d2s, "_CALLBACKS_OK", False)

    @jit.to_static
    def f(x):
        print("trace-time ok", x)
        return x * 2

    out = f(paddle.to_tensor(np.array([2.0], np.float32)))
    assert float(np.asarray(out.data)[0]) == 4.0

    @jit.to_static
    def g(x):
        assert x.sum() > 0
        return x

    with pytest.raises(TypeError, match="paddle.cond"):
        g(paddle.to_tensor(np.array([1.0], np.float32)))


# ---- logical transformer (reference: logical_transformer.py) -----------

def test_logical_and_or_not_on_tensors():
    @jit.to_static
    def f(x):
        if (x.sum() > 0) and (x.max() < 10):
            y = x * 2
        else:
            y = x * 0
        if not (x.sum() > 100) or (x.min() < -50):
            y = y + 1
        return y

    out = f(paddle.to_tensor(np.array([2.0], np.float32)))
    assert float(np.asarray(out.data)[0]) == 5.0  # 2*2 + 1
    out2 = f(paddle.to_tensor(np.array([20.0], np.float32)))
    assert float(np.asarray(out2.data)[0]) == 1.0  # else branch, +1


def test_logical_short_circuit_preserved_eager():
    from paddle_tpu.jit.dy2static import convert_control_flow
    calls = []

    def right():
        calls.append(1)
        return "rhs"

    def f(flag):
        a = flag and right()
        b = flag or right()
        return a, b

    conv = convert_control_flow(f)
    a, b = conv(False)
    # `and` short-circuits (rhs NOT evaluated), returns the operand
    assert a is False and len(calls) == 1  # only the `or` ran rhs
    assert b == "rhs"
    calls.clear()
    a, b = conv(True)
    assert a == "rhs" and b is True and len(calls) == 1


def test_logical_in_while_test():
    @jit.to_static
    def f(x):
        s = x * 0
        i = 0
        while (i < 10) and (s.sum() < 5):
            s = s + x
            i = i + 1
        return s

    out = f(paddle.to_tensor(np.array([2.0], np.float32)))
    assert float(np.asarray(out.data)[0]) == 6.0  # 2,4,6 -> stop
