"""dy2static AST conversion tests (reference analog:
dygraph_to_static/test_ifelse.py): Python `if` on tensor predicates is
rewritten to cond inside to_static; eager semantics are untouched."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn


def test_if_else_assignment_pattern_converts():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            y = x * 2
        else:
            y = x - 1
        return y + 10

    a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    b = paddle.to_tensor(np.array([-1.0, -2.0], np.float32))
    np.testing.assert_allclose(f(a).numpy(), [12.0, 14.0])
    np.testing.assert_allclose(f(b).numpy(), [8.0, 7.0])  # same compiled fn


def test_early_return_pattern_converts():
    @jit.to_static
    def relu_ish(x):
        if x.sum() > 0:
            return x
        return -x

    a = paddle.to_tensor(np.array([3.0], np.float32))
    b = paddle.to_tensor(np.array([-3.0], np.float32))
    assert float(relu_ish(a)) == 3.0
    assert float(relu_ish(b)) == 3.0


def test_if_return_else_return_converts():
    @jit.to_static
    def pick(x):
        if x.mean() > 0:
            return x * 10
        else:
            return x * 100

    assert float(pick(paddle.to_tensor(np.array([1.0], np.float32)))) == 10.0
    assert float(pick(paddle.to_tensor(np.array([-1.0], np.float32)))) == -100.0


def test_multi_assign_both_branches():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            a = x + 1
            b = x * 2
        else:
            a = x - 1
            b = x / 2
        return a + b

    v = paddle.to_tensor(np.array([2.0], np.float32))
    np.testing.assert_allclose(float(f(v)), 7.0)
    v2 = paddle.to_tensor(np.array([-2.0], np.float32))
    np.testing.assert_allclose(float(f(v2)), -4.0)


def test_static_if_on_python_value_untouched():
    @jit.to_static
    def f(x, flag=True):
        if flag:                # plain Python bool: normal trace-time if
            return x * 2
        return x

    assert float(f(paddle.to_tensor(np.array([2.0], np.float32)))) == 4.0


def test_unconvertible_pattern_still_fails_loudly():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            x = x * 2          # assigned in one branch only: no convert
        return x

    with pytest.raises(TypeError, match="paddle.cond"):
        f(paddle.ones([2]))


def test_converted_if_differentiable():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            y = (x * x).sum()
        else:
            y = x.sum()
        return y

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                         stop_gradient=False)
    f(x).backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0])


def test_layer_forward_with_tensor_if():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        @jit.to_static
        def forward(self, x):
            h = self.fc(x)
            if h.sum() > 1e9:   # never true, but must trace both
                out = h * 0
            else:
                out = h + 1
            return out

    net = Net()
    x = paddle.randn([2, 4])
    expect = (net.fc(x) + 1).numpy()
    np.testing.assert_allclose(net(x).numpy(), expect, rtol=1e-6)


def test_branch_self_assignment_not_converted():
    """`x = x + 1` inside a branch reads its own target: must NOT convert
    (would be UnboundLocalError in the branch closure); plain-Python
    predicates keep working, tensor predicates fail loudly."""
    @jit.to_static
    def g(x, flag=True):
        if flag:
            x = x + 1
        else:
            x = x - 1
        return x

    assert float(g(paddle.to_tensor(np.array([1.0], np.float32)))) == 2.0

    @jit.to_static
    def h(x):
        if x.sum() > 0:
            x = x * 2
        else:
            x = x - 1
        return x

    with pytest.raises(TypeError, match="paddle.cond"):
        h(paddle.ones([2]))


def test_chained_assign_after_define_converts():
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            a = x * 2
            b = a + 1      # reads `a` AFTER assigning it: fine
        else:
            a = x - 1
            b = a * 3
        return b

    np.testing.assert_allclose(
        float(f(paddle.to_tensor(np.array([1.0], np.float32)))), 3.0)
    np.testing.assert_allclose(
        float(f(paddle.to_tensor(np.array([-1.0], np.float32)))), -6.0)
