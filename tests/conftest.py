"""Test harness config.

Tests run on a virtual 8-device CPU mesh (SURVEY §4 implication: XLA gives
true single-process multi-device, unlike the reference's subprocess-based
TestDistBase) — set env BEFORE jax initialises.
"""
import os

# Force CPU: the shell may preset JAX_PLATFORMS=axon (the real TPU tunnel),
# which is single-chip and slow for unit tests.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The axon sitecustomize imports jax at interpreter start with
# JAX_PLATFORMS=axon, so the env var above may be read too late — force the
# platform through the config API as well.
jax.config.update("jax_platforms", "cpu")

# exact-ish matmuls for numeric checks (bench sets its own precision)
jax.config.update("jax_default_matmul_precision", "highest")
# NO persistent compile cache: jaxlib 0.4.37 corrupts the heap when it
# reloads cached executables built with NamedShardings (glibc 'corrupted
# double-linked list' / segfault inside pjit __call__ on the reloading
# run) — with GSPMD-sharded programs now first-class in the suite, a
# warm cache made tier-1 crash nondeterministically.  The measured
# speedup was ~8%; determinism wins.  (static/executor.py additionally
# compiles sharded executables with the cache off for product runs
# where users enable jax_compilation_cache_dir themselves.)


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu
    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: mark tests that duplicate a tools/
    # smoke gate (chaos_smoke, serve_smoke) so they stay runnable
    # without charging the tier-1 time budget twice.
    config.addinivalue_line(
        "markers", "slow: excluded from tier-1; covered by a tools/ gate")
