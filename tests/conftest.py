"""Test harness config.

Tests run on a virtual 8-device CPU mesh (SURVEY §4 implication: XLA gives
true single-process multi-device, unlike the reference's subprocess-based
TestDistBase) — set env BEFORE jax initialises.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# exact-ish matmuls for numeric checks (bench sets its own precision)
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu
    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield
