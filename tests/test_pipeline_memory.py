"""Pipeline activation-memory discipline, measured (VERDICT r3 #3).

The claim under test (parallel/pipeline.py module docstring): with
``remat=True`` each scan tick stores one microbatch boundary activation
instead of every stage-internal activation, so compiled backward temp
memory drops by roughly the stage depth, and grows linearly in M with a
small per-tick constant.  Reference bar: section_worker.cc:128-165 1F1B +
recompute_optimizer.py.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu import distributed as dist
from paddle_tpu.parallel import pipelined_fn, pipeline_train_fn, \
    stack_stage_params

S = 4          # pipeline stages
D = 64         # width (small: param-grad accumulators must not dominate)
DEPTH = 10     # sublayers per stage — the factor remat should save


def _build(seed=5):
    paddle.seed(seed)
    stages = [nn.Sequential(*[nn.Linear(D, D) for _ in range(DEPTH)])
              for _ in range(S)]
    stacked, _ = stack_stage_params(stages)
    return stages, stacked


def _temp_bytes(M, remat, mb=64):
    dist.init_mesh({"pp": S})
    stages, stacked = _build()
    fn = pipeline_train_fn(
        stages[0], lambda out, y: jnp.mean((out - y) ** 2), S, M,
        remat=remat)
    B = M * mb
    x = jnp.zeros((B, D), jnp.float32)
    y = jnp.zeros((B, D), jnp.float32)
    g = jax.jit(jax.grad(lambda p, x, y: fn(p, x, y)))
    compiled = g.lower(stacked, x, y).compile()
    ma = compiled.memory_analysis()
    if ma is None:
        pytest.skip("backend reports no memory analysis")
    return ma.temp_size_in_bytes


def test_remat_cuts_backward_memory_by_depth_factor():
    """remat must store ~one boundary activation per tick instead of all
    DEPTH sublayer activations: expect a multiple-x temp reduction."""
    M = 8
    t_remat = _temp_bytes(M, remat=True)
    t_plain = _temp_bytes(M, remat=False)
    assert t_remat < t_plain / 2, (
        f"remat gave only {t_plain / max(t_remat, 1):.2f}x "
        f"(remat={t_remat}, plain={t_plain})")


def test_remat_memory_grows_linearly_with_small_constant():
    """Per-tick residual is one microbatch activation: doubling M (fixed
    microbatch size) must scale temp close to linearly, not worse."""
    t16 = _temp_bytes(16, remat=True)
    t32 = _temp_bytes(32, remat=True)
    growth = t32 / max(t16, 1)
    assert growth < 2.6, (t16, t32, growth)


def test_remat_numerics_match_unrematted():
    dist.init_mesh({"pp": S})
    stages, stacked = _build(seed=9)
    M, mb = 8, 4
    r = np.random.RandomState(9)
    x = jnp.asarray(r.randn(M * mb, D), jnp.float32)
    y = jnp.asarray(r.randn(M * mb, D), jnp.float32)
    loss_fn = lambda out, yy: jnp.mean((out - yy) ** 2)
    outs = {}
    for remat in (True, False):
        fn = pipeline_train_fn(stages[0], loss_fn, S, M, remat=remat)
        l, g = jax.value_and_grad(lambda p: fn(p, x, y))(stacked)
        outs[remat] = (float(l), g)
    np.testing.assert_allclose(outs[True][0], outs[False][0], rtol=1e-5)
    for a, b in zip(outs[True][1], outs[False][1]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
