"""Donated, device-resident, async-dispatch static Executor hot path
(ISSUE 2): compile-count invariants, donation aliasing safety, async ==
sync fetches, interleaved-program state, lazy Parameter.data resolution,
the legacy-path oracle, and the riding satellites (VJP-cache LRU,
profiler sync mode, bench smoke guard)."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, optimizer


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()
    paddle.static.reset_default_programs()


def _mlp_program(seed=0, in_dim=8, hidden=16, lr=0.05, opt_cls=None):
    paddle.seed(seed)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, in_dim], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        h = paddle.static.nn.fc(x, hidden, activation="relu")
        pred = paddle.static.nn.fc(h, 1)
        loss = F.mse_loss(pred, y)
        (opt_cls or optimizer.SGD)(learning_rate=lr).minimize(loss)
    return main, loss


def _batch(seed=0, n=16, in_dim=8):
    rng = np.random.RandomState(seed)
    xs = rng.standard_normal((n, in_dim)).astype(np.float32)
    ys = (xs @ rng.standard_normal((in_dim, 1))).astype(np.float32)
    return xs, ys


# -- (a) compile-count invariants -------------------------------------------

def test_one_compile_across_n_steps_per_feed_signature():
    main, loss = _mlp_program()
    exe = paddle.static.Executor()
    xs, ys = _batch()
    feed = {"x": jnp.asarray(xs), "y": jnp.asarray(ys)}
    for _ in range(12):
        exe.run(main, feed=feed, fetch_list=[loss])
    assert exe.compile_count == 1


def test_new_feed_signature_compiles_once_more():
    main, loss = _mlp_program()
    exe = paddle.static.Executor()
    for bs in (16, 16, 4, 4, 16):
        xs, ys = _batch(n=bs)
        exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    assert exe.compile_count == 2  # one per batch-size signature


def test_zero_host_feed_converts_on_device_feeds():
    main, loss = _mlp_program()
    exe = paddle.static.Executor()
    xs, ys = _batch()
    jf = {"x": jnp.asarray(xs), "y": jnp.asarray(ys)}
    for _ in range(5):
        exe.run(main, feed=jf, fetch_list=[loss], return_numpy=False)
    assert exe.host_feed_converts == 0
    exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    assert exe.host_feed_converts == 2  # numpy feeds are counted


# -- (b) donation aliasing safety -------------------------------------------

def test_donation_does_not_corrupt_user_held_references():
    main, loss = _mlp_program()
    w = main.parameters()[0]
    exe = paddle.static.Executor()
    xs, ys = _batch()
    feed = {"x": jnp.asarray(xs), "y": jnp.asarray(ys)}
    exe.run(main, feed=feed, fetch_list=[loss])

    held = w.data                      # escapes the donated set
    snapshot = np.asarray(held).copy()
    fetched = exe.run(main, feed=feed, fetch_list=[loss],
                      return_numpy=False)[0]
    fetched_np = np.asarray(fetched.data).copy()
    for _ in range(5):                 # donated runs after the escape
        exe.run(main, feed=feed, fetch_list=[loss])

    np.testing.assert_array_equal(np.asarray(held), snapshot)
    np.testing.assert_array_equal(np.asarray(fetched.data), fetched_np)
    # and training really progressed under donation
    assert not np.array_equal(np.asarray(w.data), snapshot)


def test_feeding_a_previous_unsynced_fetch():
    """A return_numpy=False fetch feeds straight back in (the jax-array
    passthrough fix: no np.asarray bounce, no deleted-buffer use)."""
    paddle.seed(3)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 4], "float32")
        out = F.relu(x) * 2.0
    exe = paddle.static.Executor()
    a = np.array([[1.0, -1.0, 2.0, -2.0]], np.float32)
    first = exe.run(main, feed={"x": a}, fetch_list=[out],
                    return_numpy=False)[0]
    second, = exe.run(main, feed={"x": first}, fetch_list=[out],
                      return_numpy=True)
    np.testing.assert_allclose(second, np.maximum(a, 0) * 4.0)


# -- (c) async == sync ------------------------------------------------------

def test_return_numpy_false_matches_sync_path():
    main, loss = _mlp_program(seed=1)
    main2, loss2 = _mlp_program(seed=1)
    exe = paddle.static.Executor()
    xs, ys = _batch(1)
    for i in range(6):
        a = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss],
                    return_numpy=False)[0]
        s, = exe.run(main2, feed={"x": xs, "y": ys}, fetch_list=[loss2],
                     return_numpy=True)
        np.testing.assert_allclose(np.asarray(a.data), s, rtol=1e-6)


def test_fast_path_matches_legacy_oracle():
    """The donated in-graph-counter hot path computes the same training
    trajectory as the preserved pre-change executor (_run_legacy)."""
    main, loss = _mlp_program(seed=2, opt_cls=optimizer.Adam, lr=1e-2)
    main2, loss2 = _mlp_program(seed=2, opt_cls=optimizer.Adam, lr=1e-2)
    exe = paddle.static.Executor()
    exe2 = paddle.static.Executor()
    xs, ys = _batch(2)
    for _ in range(8):
        fast, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        legacy, = exe2._run_legacy(main2, feed={"x": xs, "y": ys},
                                   fetch_list=[loss2])
        np.testing.assert_allclose(fast, legacy, rtol=1e-5, atol=1e-7)


# -- (d) interleaved programs ----------------------------------------------

def test_executor_state_survives_interleaved_programs():
    """Two Programs alternating on ONE Executor (global_shuffle-style
    interleaving) keep independent device-resident states and both
    train to convergence."""
    main_a, loss_a = _mlp_program(seed=4, lr=0.1)
    main_b, loss_b = _mlp_program(seed=5, lr=0.1)
    exe = paddle.static.Executor()
    xa, ya = _batch(4)
    xb, yb = _batch(5)
    first_a = first_b = last_a = last_b = None
    for _ in range(40):
        la, = exe.run(main_a, feed={"x": xa, "y": ya}, fetch_list=[loss_a])
        lb, = exe.run(main_b, feed={"x": xb, "y": yb}, fetch_list=[loss_b])
        first_a = first_a if first_a is not None else float(la)
        first_b = first_b if first_b is not None else float(lb)
        last_a, last_b = float(la), float(lb)
    assert last_a < first_a * 0.2, (first_a, last_a)
    assert last_b < first_b * 0.2, (first_b, last_b)
    assert exe.compile_count == 2  # one per program


def test_shared_parameter_across_programs_stays_consistent():
    """A Parameter used by two Programs: each executor state steals the
    binding in turn; values must flow through, not fork."""
    paddle.seed(6)
    lin = nn.Linear(4, 1)
    progs = []
    for s in (0, 1):
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [None, 4], "float32")
            y = paddle.static.data("y", [None, 1], "float32")
            loss = F.mse_loss(lin(x), y)
            optimizer.SGD(learning_rate=0.05).minimize(loss)
        progs.append((main, loss))
    exe = paddle.static.Executor()
    xs, ys = _batch(6, in_dim=4)
    l0 = None
    for i in range(40):
        main, loss = progs[i % 2]
        lv, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        l0 = l0 if l0 is not None else float(lv)
    assert float(lv) < l0 * 0.2, (l0, float(lv))


# -- lazy Parameter.data ----------------------------------------------------

def test_param_data_reads_see_training_progress_lazily():
    main, loss = _mlp_program(seed=7)
    w = main.parameters()[0]
    exe = paddle.static.Executor()
    xs, ys = _batch(7)
    exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    v1 = np.asarray(w.data).copy()
    exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    v2 = np.asarray(w.data).copy()
    assert not np.array_equal(v1, v2)  # resolved through the live state


def test_user_write_to_param_data_is_respected():
    main, loss = _mlp_program(seed=8, lr=0.0)  # lr=0: params frozen
    w, b = main.parameters()[:2]
    exe = paddle.static.Executor()
    xs, ys = _batch(8)
    base, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    w.data = jnp.zeros_like(w.data)  # direct write while state is live
    b.data = jnp.zeros_like(b.data)
    changed, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    # zeroing the first layer changes the loss deterministically
    assert not np.allclose(base, changed)
    np.testing.assert_allclose(np.asarray(w.data), 0.0)


def test_executor_close_flushes_state_into_parameters():
    main, loss = _mlp_program(seed=9)
    w = main.parameters()[0]
    exe = paddle.static.Executor()
    xs, ys = _batch(9)
    for _ in range(3):
        exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    live = np.asarray(w.data).copy()
    exe.close()
    assert w._exec_src is None  # unbound: value now lives in the slot
    np.testing.assert_array_equal(np.asarray(w.data), live)


def test_static_optimizer_state_dict_exports_executor_slots():
    main, loss = _mlp_program(seed=10, opt_cls=optimizer.Adam, lr=1e-3)
    opt = main._optimizer[0]
    exe = paddle.static.Executor()
    xs, ys = _batch(10)
    for _ in range(3):
        exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    sd = opt.state_dict()
    assert sd["step"] == 3
    assert sd["slots"], "executor-resident Adam slots should be exported"
    some = next(iter(sd["slots"].values()))
    assert len(some) >= 1 and all(
        isinstance(v, np.ndarray) for v in some.values())


def test_static_set_state_dict_restores_executor_slots():
    """Checkpoint round-trip: a fresh process-equivalent (new Program +
    optimizer, params copied, set_state_dict) continues training with
    the SAME Adam moments — the post-restore update matches bit-for-bit
    the update the original would have taken."""
    main, loss = _mlp_program(seed=13, opt_cls=optimizer.Adam, lr=1e-2)
    opt = main._optimizer[0]
    exe = paddle.static.Executor()
    xs, ys = _batch(13)
    feed = {"x": xs, "y": ys}
    for _ in range(5):
        exe.run(main, feed=feed, fetch_list=[loss])
    ckpt = opt.state_dict()
    snap = [np.asarray(p.data).copy() for p in main.parameters()]
    exe.run(main, feed=feed, fetch_list=[loss])  # original's 6th step
    want = [np.asarray(p.data) for p in main.parameters()]

    main2, loss2 = _mlp_program(seed=99, opt_cls=optimizer.Adam, lr=1e-2)
    opt2 = main2._optimizer[0]
    for p2, arr in zip(main2.parameters(), snap):
        p2.data = jnp.asarray(arr)
    opt2.set_state_dict(ckpt)
    exe2 = paddle.static.Executor()
    exe2.run(main2, feed=feed, fetch_list=[loss2])  # restored 6th step
    for p2, w in zip(main2.parameters(), want):
        np.testing.assert_allclose(np.asarray(p2.data), w,
                                   rtol=1e-6, atol=1e-8)


# -- rng / donate-off -------------------------------------------------------

def test_explicit_seed_reproduces_dropout_run():
    paddle.seed(11)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 8], "float32")
        y = paddle.static.data("y", [None, 8], "float32")
        h = F.dropout(paddle.static.nn.fc(x, 8), p=0.5, training=True)
        loss = F.mse_loss(h, y)
        optimizer.SGD(learning_rate=0.0).minimize(loss)
    exe = paddle.static.Executor()
    xs = np.ones((4, 8), np.float32)
    ys = np.zeros((4, 8), np.float32)
    a, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss], seed=7)
    b, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss], seed=7)
    c, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    np.testing.assert_allclose(a, b)
    assert not np.allclose(a, c)  # auto-incrementing in-graph run counter
    # negative seeds are honored too (flag-gated, not a -1 sentinel)
    d, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss], seed=-3)
    e, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss], seed=-3)
    np.testing.assert_allclose(d, e)


def test_donate_flag_off_still_trains():
    paddle.set_flags({"FLAGS_static_donate": False})
    try:
        main, loss = _mlp_program(seed=12, lr=0.1)
        exe = paddle.static.Executor()
        xs, ys = _batch(12)
        l0 = float(exe.run(main, feed={"x": xs, "y": ys},
                           fetch_list=[loss])[0])
        for _ in range(30):
            lv, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        assert float(lv) < l0 * 0.2
    finally:
        paddle.set_flags({"FLAGS_static_donate": True})


# -- satellite: VJP cache LRU eviction --------------------------------------

def test_vjp_cache_evicts_oldest_half_not_everything(monkeypatch):
    from paddle_tpu.core import dispatch

    monkeypatch.setattr(dispatch, "_VJP_CACHE_CAP", 8)
    monkeypatch.setattr(dispatch, "_VJP_CACHE", type(dispatch._VJP_CACHE)())
    for i in range(8):
        dispatch._cache_store(("k", i), i)
    assert len(dispatch._VJP_CACHE) == 8
    dispatch._cache_lookup(("k", 0))          # touch: now most-recent
    dispatch._cache_store(("k", 8), 8)        # triggers eviction
    cache = dispatch._VJP_CACHE
    assert len(cache) == 5                     # half evicted, one added
    assert ("k", 0) in cache                   # LRU-touched survivor
    assert ("k", 8) in cache
    assert ("k", 1) not in cache               # oldest half gone


def test_eager_training_after_cache_pressure(monkeypatch):
    """Eviction at the cap must not break live compiled rules."""
    from paddle_tpu.core import dispatch
    monkeypatch.setattr(dispatch, "_VJP_CACHE_CAP", 4)
    paddle.disable_static()
    try:
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
        opt = optimizer.SGD(learning_rate=0.05,
                            parameters=model.parameters())
        x = paddle.randn([8, 4])
        y = paddle.randn([8, 1])
        losses = []
        for _ in range(6):
            loss = F.mse_loss(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]
    finally:
        paddle.enable_static()


# -- satellite: bench smoke guard ------------------------------------------

def test_bench_smoke_tool_passes_in_process():
    import os
    import sys
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    sys.path.insert(0, tools)
    try:
        import bench_smoke
    finally:
        sys.path.remove(tools)
    paddle.disable_static()
    try:
        failures = bench_smoke.run_checks(steps=8, timing=False)
        assert failures == [], failures
    finally:
        paddle.enable_static()
