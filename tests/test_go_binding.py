"""Go binding (reference: go/paddle over paddle_inference_c).

Two tiers:
- no Go toolchain (this sandbox): static contract checks — the cgo
  sources must reference only PT_* symbols the C header declares, and
  the header must match the symbols libpaddle_tpu_capi.so exports;
- with Go: `go vet` + `go build` and the example binary end-to-end
  against a jit.save'd model."""
import os
import re
import shutil
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GO_DIR = os.path.join(REPO, "go")
HEADER = os.path.join(REPO, "paddle_tpu", "inference", "csrc",
                      "paddle_tpu_capi.h")


def _header_symbols():
    src = open(HEADER).read()
    fns = set(re.findall(r"\b(PT_[A-Za-z]+)\s*\(", src))
    types = set(re.findall(r"\b(?:struct|typedef struct)\s+"
                           r"(PT_[A-Za-z]+)", src))
    return fns | types


def _go_sources():
    out = []
    for root, _, files in os.walk(GO_DIR):
        out += [os.path.join(root, f) for f in files if f.endswith(".go")]
    return out


def test_go_sources_reference_only_declared_symbols():
    declared = _header_symbols()
    assert {"PT_NewPredictor", "PT_PredictorRun", "PT_GetOutput",
            "PT_FreeOutput", "PT_DeletePredictor"} <= declared
    used = set()
    for path in _go_sources():
        used |= set(re.findall(r"C\.(PT_[A-Za-z]+)", open(path).read()))
    assert used, "go sources must call the C ABI"
    assert used <= declared, used - declared


def test_header_matches_compiled_abi(tmp_path):
    """The header must compile as C and agree with the .so's exports."""
    if shutil.which("gcc") is None and shutil.which("g++") is None:
        pytest.skip("no C toolchain")
    probe = tmp_path / "probe.c"
    probe.write_text(
        '#include "paddle_tpu_capi.h"\n'
        "int main(void) {\n"
        "  PT_Output o; o.ndim = 0; (void)o;\n"
        "  void* fns[] = {(void*)PT_NewPredictor, (void*)PT_PredictorRun,\n"
        "                 (void*)PT_GetOutput, (void*)PT_FreeOutput,\n"
        "                 (void*)PT_DeletePredictor};\n"
        "  (void)fns; return 0;\n"
        "}\n")
    from paddle_tpu.inference.capi import load_capi
    load_capi()  # ensures the .so exists
    so_dir = os.path.dirname(HEADER)
    cc = shutil.which("gcc") or shutil.which("g++")
    out = tmp_path / "probe"
    r = subprocess.run(
        [cc, str(probe), f"-I{so_dir}", f"-L{so_dir}",
         "-lpaddle_tpu_capi", "-o", str(out)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


@pytest.mark.skipif(shutil.which("go") is None,
                    reason="no Go toolchain in this image")
def test_go_build_and_run_example(tmp_path):
    import sysconfig

    import paddle_tpu as paddle
    from paddle_tpu import inference, jit, nn
    from paddle_tpu.inference.capi import load_capi
    from paddle_tpu.jit import InputSpec

    load_capi()
    paddle.seed(0)
    net = nn.Sequential(nn.Flatten(), nn.Linear(784, 10))
    prefix = str(tmp_path / "m")
    jit.save(net, prefix,
             input_spec=[InputSpec([None, 1, 28, 28], "float32")])

    ver = f"{os.sys.version_info.major}.{os.sys.version_info.minor}"
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    so_dir = os.path.dirname(HEADER)
    env = dict(os.environ,
               CGO_CFLAGS=f"-I{so_dir}",
               CGO_LDFLAGS=(f"-L{so_dir} -lpaddle_tpu_capi "
                            f"-L{libdir} -lpython{ver}"),
               PYTHONPATH=REPO,
               LD_LIBRARY_PATH=f"{so_dir}:{libdir}")
    # module setup + vet + build
    if not os.path.exists(os.path.join(GO_DIR, "go.mod")):
        subprocess.run(["go", "mod", "init", "paddle_tpu/go"],
                       cwd=GO_DIR, env=env, check=True,
                       capture_output=True)
    r = subprocess.run(["go", "vet", "./..."], cwd=GO_DIR, env=env,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    exe = str(tmp_path / "example")
    r = subprocess.run(["go", "build", "-o", exe, "./example"],
                       cwd=GO_DIR, env=env, capture_output=True,
                       text=True)
    assert r.returncode == 0, r.stderr
    r = subprocess.run([exe, prefix], env=env, capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "output 0 shape=[1 10]" in r.stdout
    # numerics: example feeds zeros -> logits equal the bias
    first = float(r.stdout.split("first=")[1].split()[0])
    bias = np.asarray(net[1].bias.data)[0]
    np.testing.assert_allclose(first, bias, rtol=1e-5)
