"""Native custom-op path: compile a C++ XLA-FFI kernel in-test, register
it through register_custom_op with a native backward, and check fwd+bwd
numerics (reference: custom_operator.cc + utils/cpp_extension — the
custom relu example from the reference docs).

Host kernels register for the CPU platform (the conftest pins
JAX_PLATFORMS=cpu)."""
import functools
import os
import shutil

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")

_SRC = r"""
#include <cstddef>
#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static ffi::Error LeakyReluFwdImpl(ffi::Buffer<ffi::F32> x,
                                   ffi::ResultBuffer<ffi::F32> y,
                                   float alpha) {
  const float* xi = x.typed_data();
  float* yo = y->typed_data();
  for (size_t i = 0; i < x.element_count(); ++i)
    yo[i] = xi[i] > 0.0f ? xi[i] : alpha * xi[i];
  return ffi::Error::Success();
}
XLA_FFI_DEFINE_HANDLER_SYMBOL(LeakyReluFwd, LeakyReluFwdImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>()
                                  .Attr<float>("alpha"));

static ffi::Error LeakyReluBwdImpl(ffi::Buffer<ffi::F32> x,
                                   ffi::Buffer<ffi::F32> ct,
                                   ffi::ResultBuffer<ffi::F32> dx,
                                   float alpha) {
  const float* xi = x.typed_data();
  const float* g = ct.typed_data();
  float* out = dx->typed_data();
  for (size_t i = 0; i < x.element_count(); ++i)
    out[i] = xi[i] > 0.0f ? g[i] : alpha * g[i];
  return ffi::Error::Success();
}
XLA_FFI_DEFINE_HANDLER_SYMBOL(LeakyReluBwd, LeakyReluBwdImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>()
                                  .Attr<float>("alpha"));
"""


@pytest.fixture(scope="module")
def ext(tmp_path_factory):
    from paddle_tpu.utils.cpp_extension import load
    root = tmp_path_factory.mktemp("ext")
    src = root / "leaky.cpp"
    src.write_text(_SRC)
    return load(
        "leaky_ext", [str(src)],
        functions={
            "leaky_fwd": {"symbol": "LeakyReluFwd", "out": "like:0"},
            "leaky_bwd": {"symbol": "LeakyReluBwd", "out": "like:0"},
        },
        build_directory=str(root / "build"))


def test_ffi_forward_numerics(ext):
    x = np.array([-2.0, -0.5, 0.0, 1.5], np.float32)
    y = np.asarray(ext.leaky_fwd(x, alpha=np.float32(0.1)))
    np.testing.assert_allclose(y, np.where(x > 0, x, 0.1 * x), rtol=1e-6)


def test_ffi_under_jit_and_vmap(ext):
    import jax
    import jax.numpy as jnp

    f = jax.jit(functools.partial(ext.leaky_fwd, alpha=np.float32(0.2)))
    x = jnp.asarray(np.linspace(-2, 2, 16, dtype=np.float32))
    np.testing.assert_allclose(np.asarray(f(x)),
                               np.where(x > 0, x, 0.2 * np.asarray(x)),
                               rtol=1e-6)
    xb = jnp.stack([x, -x])
    vb = jax.vmap(lambda a: ext.leaky_fwd(a, alpha=np.float32(0.2)))(xb)
    assert np.asarray(vb).shape == (2, 16)


def test_register_custom_op_with_native_vjp(ext):
    """The cpp_extension analog end-to-end: native fwd + native bwd wired
    through register_custom_op's custom_vjp, driven by the eager tape."""
    import paddle_tpu as paddle
    from paddle_tpu.utils.custom_op import register_custom_op

    alpha = np.float32(0.1)
    fwd = functools.partial(ext.leaky_fwd, alpha=alpha)

    def bwd(res, ct):
        (x,) = res
        return (ext.leaky_bwd(x, ct, alpha=alpha),)

    op = register_custom_op("native_leaky_relu", fwd, backward=bwd)

    xv = np.array([-3.0, -1.0, 2.0, 4.0], np.float32)
    x = paddle.to_tensor(xv, stop_gradient=False)
    y = op(x)
    np.testing.assert_allclose(np.asarray(y.data),
                               np.where(xv > 0, xv, 0.1 * xv), rtol=1e-6)
    # backward through the tape uses the NATIVE bwd kernel
    (y * paddle.to_tensor(np.array([1.0, 2.0, 3.0, 4.0], np.float32))
     ).sum().backward()
    expect = np.where(xv > 0, 1.0, 0.1) * np.array([1, 2, 3, 4],
                                                   np.float32)
    np.testing.assert_allclose(np.asarray(x.grad.data), expect, rtol=1e-6)


def test_rebuild_only_when_stale(ext, tmp_path):
    from paddle_tpu.utils.cpp_extension import load
    so = ext.__so_path__
    mtime = os.path.getmtime(so)
    # same sources, same build dir: no recompilation
    src_dir = os.path.dirname(so)
    # (reload through the public API with an out spec callable)
    import jax
    mod = load("leaky_ext",
               [os.path.join(os.path.dirname(src_dir), "leaky.cpp")],
               functions={"leaky_fwd": {
                   "symbol": "LeakyReluFwd",
                   "out": lambda a: jax.ShapeDtypeStruct(a.shape,
                                                         a.dtype)}},
               build_directory=src_dir)
    assert os.path.getmtime(so) == mtime
    x = np.array([-1.0, 1.0], np.float32)
    np.testing.assert_allclose(
        np.asarray(mod.leaky_fwd(x, alpha=np.float32(0.5))),
        [-0.5, 1.0], rtol=1e-6)


def test_load_errors_are_loud(tmp_path):
    from paddle_tpu.utils.cpp_extension import CppExtension, load
    bad = tmp_path / "bad.cpp"
    bad.write_text("this is not C++")
    with pytest.raises(RuntimeError, match="compiler failed"):
        load("badext", [str(bad)], functions={},
             build_directory=str(tmp_path / "b"))
    with pytest.raises(FileNotFoundError):
        load("missing", [str(tmp_path / "nope.cpp")], functions={})
    with pytest.raises(NotImplementedError, match="cpp_extension.load"):
        CppExtension("x")
