"""ONNX export (VERDICT r4 missing #8).

Reference: python/paddle/onnx/export.py (delegates to paddle2onnx).  The
bytes here are hand-encoded protobuf (no onnx package in this image), so
conformance is proven by re-decoding with ``protoc --decode`` against a
vendored subset of the official onnx.proto schema, plus initializer
round-trip checks against the live model weights.
"""
import os
import shutil
import struct
import subprocess

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.jit import InputSpec

_PROTO_DIR = os.path.join(os.path.dirname(__file__), "data")


def _decode(path):
    if shutil.which("protoc") is None:
        pytest.skip("protoc not available")
    with open(path, "rb") as f:
        out = subprocess.run(
            ["protoc", "--decode=onnx.ModelProto",
             f"--proto_path={_PROTO_DIR}", "onnx_subset.proto"],
            stdin=f, capture_output=True, text=True)
    assert out.returncode == 0, out.stderr[-500:]
    return out.stdout


def test_mlp_export_protoc_verified(tmp_path):
    paddle.seed(96)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    p = paddle.onnx.export(net, str(tmp_path / "mlp"),
                           input_spec=[InputSpec([None, 8], "float32")])
    assert p.endswith(".onnx") and os.path.exists(p)
    txt = _decode(p)
    assert 'op_type: "MatMul"' in txt
    assert 'op_type: "Max"' in txt          # relu = max(x, 0)
    assert 'producer_name: "paddle_tpu"' in txt
    assert txt.count("initializer") >= 4     # 2 weights + 2 biases
    assert 'input: "input_0"' in txt
    # opset import present
    assert "opset_import" in txt and "version: 13" in txt


def test_cnn_export_has_conv_and_pool(tmp_path):
    paddle.seed(97)
    cnn = nn.Sequential(nn.Conv2D(1, 4, 3, padding=1), nn.ReLU(),
                        nn.MaxPool2D(2), nn.Flatten(),
                        nn.Linear(4 * 4 * 4, 3))
    p = paddle.onnx.export(cnn, str(tmp_path / "cnn"),
                           input_spec=[InputSpec([1, 1, 8, 8], "float32")])
    txt = _decode(p)
    assert 'op_type: "Conv"' in txt
    assert 'op_type: "MaxPool"' in txt
    assert "kernel_shape" in txt and "strides" in txt


def test_initializer_bytes_roundtrip(tmp_path):
    """The exported initializer raw_data must be the live weight bytes."""
    paddle.seed(98)
    net = nn.Linear(4, 3)
    p = paddle.onnx.export(net, str(tmp_path / "lin"),
                           input_spec=[InputSpec([2, 4], "float32")])
    blob = open(p, "rb").read()
    w = np.asarray(net.weight.data, np.float32)
    assert w.tobytes() in blob
    b = np.asarray(net.bias.data, np.float32)
    assert b.tobytes() in blob


def test_unsupported_primitive_is_loud(tmp_path):
    class Weird(nn.Layer):
        def forward(self, x):
            import paddle_tpu
            return paddle_tpu.sort(x)     # 'sort' is outside the subset

    with pytest.raises(NotImplementedError, match="sort"):
        paddle.onnx.export(Weird(), str(tmp_path / "w"),
                           input_spec=[InputSpec([4], "float32")])


def test_sigmoid_tanh_softmax_graph(tmp_path):
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            import paddle_tpu.nn.functional as F
            return F.softmax(paddle.tanh(self.fc(x)), axis=-1)

    paddle.seed(99)
    p = paddle.onnx.export(Net(), str(tmp_path / "act"),
                           input_spec=[InputSpec([2, 4], "float32")])
    txt = _decode(p)
    assert 'op_type: "Tanh"' in txt
    # softmax decomposes into exp / reduce / div in the jaxpr
    assert 'op_type: "Exp"' in txt or 'op_type: "Softmax"' in txt


def test_reduce_sum_axes_as_input_opset13(tmp_path):
    """r4 review: opset 13 ReduceSum takes axes as an INPUT, not an
    attribute."""
    class MeanNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 6)

        def forward(self, x):
            return self.fc(x).sum(axis=-1)

    paddle.seed(103)
    p = paddle.onnx.export(MeanNet(), str(tmp_path / "rs"),
                           input_spec=[InputSpec([2, 4], "float32")])
    txt = _decode(p)
    block = txt.split('op_type: "ReduceSum"')[0].rsplit("node {", 1)[1]
    assert block.count("input:") == 2, block     # data + axes input
    assert 'name: "axes' in txt                  # axes initializer
