"""Text end-to-end (VERDICT r4 #9): dataset -> tokenizer -> classifier
training, and a seq2seq encode/beam-decode smoke.

Reference flow: python/paddle/text/datasets/imdb.py feeding an LSTM
classifier (the reference book's sentiment example), wmt16.py feeding an
attention seq2seq with BeamSearchDecoder (machine_translation example).
"""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.io import DataLoader
from paddle_tpu.text import Imdb, WMT16, UCIHousing
import paddle_tpu.nn.functional as F
from paddle_tpu.ops import sequence as SEQ


class LstmClassifier(nn.Layer):
    def __init__(self, vocab, emb=32, hidden=32, classes=2):
        super().__init__()
        self.embedding = nn.Embedding(vocab, emb)
        self.lstm = nn.LSTM(emb, hidden)
        self.head = nn.Linear(hidden, classes)

    def forward(self, ids):
        x = self.embedding(ids)
        out, _ = self.lstm(x)
        # masked mean over time via the sequence-op tier
        lens = paddle.to_tensor(
            np.full((ids.shape[0],), ids.shape[1], np.int32))
        pooled = SEQ.sequence_pool(out, lens, "average")
        return self.head(pooled)


def test_imdb_lstm_classifier_trains():
    ds = Imdb(mode="train")
    assert len(ds) == 2000 and ds.vocab_size > 0
    loader = DataLoader(ds, batch_size=32, shuffle=True, num_workers=0)
    paddle.seed(60)
    model = LstmClassifier(ds.vocab_size)
    opt = optimizer.Adam(learning_rate=2e-3,
                         parameters=model.parameters())
    losses = []
    it = iter(loader)
    for step in range(8):
        ids, labels = next(it)
        logits = model(ids)
        loss = F.cross_entropy(logits, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert min(losses[4:]) < losses[0], losses


def test_imdb_tokenizer_pipeline():
    """Raw strings -> native tokenizer -> Imdb-vocab ids -> model input
    shapes (the reference's imdb word_idx flow)."""
    from paddle_tpu.text.fast_tokenizer import FastWordPieceTokenizer
    ds = Imdb(mode="test")
    vocab = {"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3}
    for w in list(ds.word_idx)[:50]:
        vocab.setdefault(w, len(vocab))
    tk = FastWordPieceTokenizer(vocab=vocab)
    ids, lens = tk.encode_batch(["w1 w2 w3", "w5 w4"], max_len=16)
    assert ids.shape == (2, 16) and lens.tolist() == [5, 4]
    model = LstmClassifier(len(vocab))
    out = model(paddle.to_tensor(ids.astype(np.int64)))
    assert out.shape_tuple == (2, 2)


class Seq2Seq(nn.Layer):
    def __init__(self, vocab, emb=24, hidden=24):
        super().__init__()
        self.src_emb = nn.Embedding(vocab, emb)
        self.encoder = nn.LSTM(emb, hidden)
        self.cell = nn.LSTMCell(emb, hidden)
        self.tgt_emb = nn.Embedding(vocab, emb)
        self.out = nn.Linear(hidden, vocab)

    def encode(self, src):
        _, (h, c) = self.encoder(self.src_emb(src))
        return h[0], c[0]


def test_wmt16_seq2seq_beam_decode_smoke():
    ds = WMT16(mode="test", dict_size=200)
    src, tgt_in, tgt_out = ds[0]
    assert src.shape == (24,) and tgt_in.shape == (23,)

    paddle.seed(61)
    model = Seq2Seq(200)
    src_b = paddle.to_tensor(np.stack([ds[i][0] for i in range(4)]))
    h, c = model.encode(src_b)

    class _Cell(nn.Layer):
        def __init__(self, m):
            super().__init__()
            self.m = m

        def __call__(self, ids, states):
            x = self.m.tgt_emb(ids)
            h, (hn, cn) = self.m.cell(x, states)
            return self.m.out(h), (hn, cn)

    dec = nn.BeamSearchDecoder(_Cell(model), start_token=1, end_token=0,
                               beam_size=3)
    seq, scores = nn.dynamic_decode(dec, (h, c), max_step_num=6)
    s = np.asarray(seq.data if hasattr(seq, "data") else seq)
    assert s.shape[0] == 4           # batch preserved
    assert np.isfinite(np.asarray(scores.data
                                  if hasattr(scores, "data")
                                  else scores)).all()


def test_uci_housing_regression_trains():
    ds = UCIHousing(mode="train")
    x = paddle.to_tensor(np.stack([ds[i][0] for i in range(64)]))
    y = paddle.to_tensor(np.stack([ds[i][1] for i in range(64)]))
    paddle.seed(62)
    net = nn.Linear(13, 1)
    opt = optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
    first = last = None
    for _ in range(20):
        loss = F.mse_loss(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss)
        last = float(loss)
    assert last < first * 0.5
