"""Text end-to-end (VERDICT r4 #9): dataset -> tokenizer -> classifier
training, and a seq2seq encode/beam-decode smoke.

Reference flow: python/paddle/text/datasets/imdb.py feeding an LSTM
classifier (the reference book's sentiment example), wmt16.py feeding an
attention seq2seq with BeamSearchDecoder (machine_translation example).

Corpora are tiny REAL-FORMAT archives generated per session (aclImdb
tarball, wmt16 bitext tar, housing.data floats) and parsed through the
real paddle.text.datasets loaders — the zero-egress stand-in for the
reference's downloads."""
import io
import os
import tarfile

import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.io import DataLoader
from paddle_tpu.text import Imdb, WMT16, UCIHousing
import paddle_tpu.nn.functional as F
from paddle_tpu.ops import sequence as SEQ

DOC_LEN = 32  # fixed-length docs so default DataLoader collation batches


def _add_bytes(tf, name, data):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


@pytest.fixture(scope="module")
def imdb_file(tmp_path_factory):
    """aclImdb-format tarball: class-correlated fixed-length docs (pos
    docs draw from the first half of a 24-word vocab, neg from the
    second) so the classifier has signal to learn."""
    root = tmp_path_factory.mktemp("imdb")
    path = str(root / "aclImdb_v1.tar.gz")
    rs = np.random.RandomState(0)
    vocab = [f"word{i:02d}" for i in range(24)]
    with tarfile.open(path, "w:gz") as tf:
        for mode, n in (("train", 120), ("test", 24)):
            for i in range(n):
                sub = "pos" if i % 2 == 0 else "neg"
                lo, hi = (0, 16) if sub == "pos" else (8, 24)
                words = [vocab[j]
                         for j in rs.randint(lo, hi, DOC_LEN)]
                _add_bytes(tf, f"aclImdb/{mode}/{sub}/{i}.txt",
                           " ".join(words).encode())
    return path


@pytest.fixture(scope="module")
def wmt16_file(tmp_path_factory):
    """wmt16-format tar with fixed 22-token lines -> (24,)/(23,) ids."""
    root = tmp_path_factory.mktemp("wmt16")
    path = str(root / "wmt16.tar")
    rs = np.random.RandomState(1)
    en = [f"en{i:02d}" for i in range(40)]
    de = [f"de{i:02d}" for i in range(40)]
    def lines(n):
        out = []
        for _ in range(n):
            s = " ".join(en[j] for j in rs.randint(0, 40, 22))
            t = " ".join(de[j] for j in rs.randint(0, 40, 22))
            out.append(f"{s}\t{t}")
        return ("\n".join(out) + "\n").encode()
    with tarfile.open(path, "w") as tf:
        _add_bytes(tf, "wmt16/train", lines(60))
        _add_bytes(tf, "wmt16/test", lines(12))
        _add_bytes(tf, "wmt16/val", lines(6))
    return path


@pytest.fixture(scope="module")
def housing_file(tmp_path_factory):
    """housing.data floats with a linear feature->target relation."""
    root = tmp_path_factory.mktemp("uci")
    path = str(root / "housing.data")
    rs = np.random.RandomState(2)
    X = rs.rand(120, 13) * 10
    w = rs.rand(13)
    y = X @ w + 0.1 * rs.rand(120)
    with open(path, "w") as f:
        for xi, yi in zip(X, y):
            f.write(" ".join(f"{v:.6f}" for v in xi) + f" {yi:.6f}\n")
    return path


class LstmClassifier(nn.Layer):
    def __init__(self, vocab, emb=32, hidden=32, classes=2):
        super().__init__()
        self.embedding = nn.Embedding(vocab, emb)
        self.lstm = nn.LSTM(emb, hidden)
        self.head = nn.Linear(hidden, classes)

    def forward(self, ids):
        x = self.embedding(ids)
        out, _ = self.lstm(x)
        # masked mean over time via the sequence-op tier
        lens = paddle.to_tensor(
            np.full((ids.shape[0],), ids.shape[1], np.int32))
        pooled = SEQ.sequence_pool(out, lens, "average")
        return self.head(pooled)


def test_imdb_lstm_classifier_trains(imdb_file):
    ds = Imdb(data_file=imdb_file, mode="train", cutoff=5)
    vocab_size = len(ds.word_idx)
    assert len(ds) == 120 and vocab_size > 2
    loader = DataLoader(ds, batch_size=32, shuffle=True, num_workers=0)
    paddle.seed(60)
    model = LstmClassifier(vocab_size)
    opt = optimizer.Adam(learning_rate=2e-3,
                         parameters=model.parameters())
    losses = []
    it = iter(loader)
    for step in range(8):
        try:
            ids, labels = next(it)
        except StopIteration:  # new epoch over the 120-doc corpus
            it = iter(loader)
            ids, labels = next(it)
        logits = model(ids)
        loss = F.cross_entropy(logits, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert min(losses[4:]) < losses[0], losses


def test_imdb_tokenizer_pipeline(imdb_file):
    """Raw strings -> native tokenizer -> Imdb-vocab ids -> model input
    shapes (the reference's imdb word_idx flow)."""
    from paddle_tpu.text.fast_tokenizer import FastWordPieceTokenizer
    ds = Imdb(data_file=imdb_file, mode="test", cutoff=5)
    vocab = {"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3}
    for w in list(ds.word_idx)[:50]:
        # reference word_idx keys are bytes (tarball tokens)
        vocab.setdefault(w.decode() if isinstance(w, bytes) else w,
                         len(vocab))
    tk = FastWordPieceTokenizer(vocab=vocab)
    ids, lens = tk.encode_batch(["w1 w2 w3", "w5 w4"], max_len=16)
    assert ids.shape == (2, 16) and lens.tolist() == [5, 4]
    model = LstmClassifier(len(vocab))
    out = model(paddle.to_tensor(ids.astype(np.int64)))
    assert out.shape_tuple == (2, 2)


class Seq2Seq(nn.Layer):
    def __init__(self, vocab, emb=24, hidden=24):
        super().__init__()
        self.src_emb = nn.Embedding(vocab, emb)
        self.encoder = nn.LSTM(emb, hidden)
        self.cell = nn.LSTMCell(emb, hidden)
        self.tgt_emb = nn.Embedding(vocab, emb)
        self.out = nn.Linear(hidden, vocab)

    def encode(self, src):
        _, (h, c) = self.encoder(self.src_emb(src))
        return h[0], c[0]


def test_wmt16_seq2seq_beam_decode_smoke(wmt16_file):
    ds = WMT16(data_file=wmt16_file, mode="test", src_dict_size=200,
               trg_dict_size=200,
               dict_cache_dir=os.path.dirname(wmt16_file))
    src, tgt_in, tgt_out = ds[0]
    assert src.shape == (24,) and tgt_in.shape == (23,)

    paddle.seed(61)
    model = Seq2Seq(200)
    src_b = paddle.to_tensor(np.stack([ds[i][0] for i in range(4)]))
    h, c = model.encode(src_b)

    class _Cell(nn.Layer):
        def __init__(self, m):
            super().__init__()
            self.m = m

        def __call__(self, ids, states):
            x = self.m.tgt_emb(ids)
            h, (hn, cn) = self.m.cell(x, states)
            return self.m.out(h), (hn, cn)

    dec = nn.BeamSearchDecoder(_Cell(model), start_token=1, end_token=0,
                               beam_size=3)
    seq, scores = nn.dynamic_decode(dec, (h, c), max_step_num=6)
    s = np.asarray(seq.data if hasattr(seq, "data") else seq)
    assert s.shape[0] == 4           # batch preserved
    assert np.isfinite(np.asarray(scores.data
                                  if hasattr(scores, "data")
                                  else scores)).all()


def test_uci_housing_regression_trains(housing_file):
    ds = UCIHousing(data_file=housing_file, mode="train")
    x = paddle.to_tensor(np.stack([ds[i][0] for i in range(64)]))
    y = paddle.to_tensor(np.stack([ds[i][1] for i in range(64)]))
    paddle.seed(62)
    net = nn.Linear(13, 1)
    opt = optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
    first = last = None
    for _ in range(20):
        loss = F.mse_loss(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss)
        last = float(loss)
    assert last < first * 0.5
