"""In-graph AMP loss scaling, gradient accumulation, ZeRO-2
(reference analogs: operators/amp/check_finite_and_unscale_op.cu +
update_loss_scaling_op.cu; gradient_merge_optimizer.py:18;
sharding_optimizer.py:103-171)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import amp, nn, optimizer
from paddle_tpu.jit import TrainStep


def _problem(seed=0):
    paddle.seed(seed)
    net = nn.Linear(4, 2)
    r = np.random.RandomState(seed)
    x = jnp.asarray(r.randn(8, 4), jnp.float32)
    y = jnp.asarray(r.randn(8, 2), jnp.float32)
    loss_fn = lambda out, lab: F.mse_loss(out, lab)
    return net, x, y, loss_fn


def test_ingraph_loss_scaling_trains():
    net, x, y, loss_fn = _problem()
    scaler = amp.GradScaler(init_loss_scaling=256.0)
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = TrainStep(net, loss_fn, opt, scaler=scaler)
    losses = [float(step(x, y)) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5
    assert step.loss_scale == 256.0  # no overflow, incr_every not reached


def test_ingraph_scaling_skips_update_on_overflow():
    net, x, y, loss_fn = _problem()
    scaler = amp.GradScaler(init_loss_scaling=64.0,
                            decr_every_n_nan_or_inf=1)

    def bad_loss(out, lab):
        return F.mse_loss(out, lab) * float("inf")

    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = TrainStep(net, bad_loss, opt, scaler=scaler)
    w0 = np.asarray(net.weight.data).copy()
    step(x, y)
    np.testing.assert_allclose(np.asarray(net.weight.data), w0)  # skipped
    assert step.loss_scale == 32.0  # halved in-graph
    step(x, y)
    assert step.loss_scale == 16.0


def test_ingraph_scaling_grows_scale():
    net, x, y, loss_fn = _problem()
    scaler = amp.GradScaler(init_loss_scaling=2.0, incr_every_n_steps=3)
    opt = optimizer.SGD(learning_rate=0.01, parameters=net.parameters())
    step = TrainStep(net, loss_fn, opt, scaler=scaler)
    for _ in range(3):
        step(x, y)
    assert step.loss_scale == 4.0


def test_gradient_accumulation_matches_full_batch():
    # mean-loss microbatch average == full-batch gradient
    net, x, y, loss_fn = _problem(3)
    init = {k: np.asarray(v.data).copy() for k, v in net.state_dict().items()}

    opt1 = optimizer.Momentum(learning_rate=0.05,
                              parameters=net.parameters())
    full = TrainStep(net, loss_fn, opt1)
    full_losses = [float(full(x, y)) for _ in range(3)]
    w_full = np.asarray(net.weight.data).copy()

    net.set_state_dict(init)
    opt2 = optimizer.Momentum(learning_rate=0.05,
                              parameters=net.parameters())
    acc = TrainStep(net, loss_fn, opt2, accumulate_steps=4)
    acc_losses = [float(acc(x, y)) for _ in range(3)]
    np.testing.assert_allclose(acc_losses, full_losses, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(net.weight.data), w_full,
                               rtol=1e-5)


def test_accumulation_with_scaler():
    net, x, y, loss_fn = _problem(4)
    scaler = amp.GradScaler(init_loss_scaling=128.0)
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = TrainStep(net, loss_fn, opt, scaler=scaler, accumulate_steps=2)
    losses = [float(step(x, y)) for _ in range(20)]
    assert losses[-1] < losses[0] * 0.7


def test_zero2_parity_and_reduce_scatter():
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.distributed.strategy import DistributedStrategy
    from paddle_tpu.parallel import SpmdTrainStep

    paddle.seed(11)
    net = nn.Sequential(nn.Linear(8, 32), nn.GELU(), nn.Linear(32, 8))
    r = np.random.RandomState(11)
    x = jnp.asarray(r.randn(8, 8), jnp.float32)
    y = jnp.asarray(r.randn(8, 8), jnp.float32)
    loss_fn = lambda out, lab: F.mse_loss(out, lab)
    init = {k: np.asarray(v.data).copy() for k, v in net.state_dict().items()}

    mesh = init_mesh({"dp": 4})
    strat = DistributedStrategy()
    strat.sharding = True
    strat.sharding_configs = {"stage": 2}
    opt = optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    step = SpmdTrainStep(net, loss_fn, opt, mesh=mesh, strategy=strat)
    z2_losses = [float(step(x, y)) for _ in range(3)]

    # the compiled step must actually reduce-scatter gradients
    compiled = step._compiled[True]
    p_arr = tuple(p.data for p in step._params)
    hlo = compiled.lower(p_arr, tuple(),
                         step._opt_state, step._scaler_state,
                         jnp.float32(0.01), (x,), (y,)).compile().as_text()
    # TPU lowers the sharded-grad constraint as reduce-scatter; the CPU
    # backend decomposes it to all-reduce + dynamic-slice.  Either way the
    # update must be shard-local with an all-gather of the new params.
    assert ("reduce-scatter" in hlo
            or ("dynamic-slice" in hlo and "all-gather" in hlo)), (
        "ZeRO-2 must lower to a reduce-scatter(-equivalent) + all-gather")

    net.set_state_dict(init)
    opt2 = optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    local = TrainStep(net, loss_fn, opt2)
    local_losses = [float(local(x, y)) for _ in range(3)]
    np.testing.assert_allclose(z2_losses, local_losses, rtol=2e-4)


def test_spmd_gradient_merge_from_strategy():
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.distributed.strategy import DistributedStrategy
    from paddle_tpu.parallel import SpmdTrainStep

    paddle.seed(12)
    net = nn.Linear(4, 2)
    r = np.random.RandomState(12)
    x = jnp.asarray(r.randn(8, 4), jnp.float32)
    y = jnp.asarray(r.randn(8, 2), jnp.float32)
    loss_fn = lambda out, lab: F.mse_loss(out, lab)

    mesh = init_mesh({"dp": 2})
    strat = DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2}
    opt = optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
    step = SpmdTrainStep(net, loss_fn, opt, mesh=mesh, strategy=strat)
    assert step.accumulate_steps == 2
    losses = [float(step(x, y)) for _ in range(80)]
    assert losses[-1] < losses[0] * 0.5


def test_scaler_state_dict_reflects_ingraph_state():
    net, x, y, loss_fn = _problem(7)
    scaler = amp.GradScaler(init_loss_scaling=64.0,
                            decr_every_n_nan_or_inf=1)
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = TrainStep(net, lambda o, l: F.mse_loss(o, l) * float("inf"),
                     opt, scaler=scaler)
    step(x, y)  # overflow -> in-graph scale halves to 32
    assert scaler.state_dict()["scale"] == 32.0
    scaler.load_state_dict({"scale": 8.0, "good_steps": 0, "bad_steps": 0})
    step(x, y)  # reinitialised from loaded values, halves again
    assert step.loss_scale == 4.0


def test_accumulate_steps_divisibility_error():
    net, x, y, loss_fn = _problem(8)
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = TrainStep(net, loss_fn, opt, accumulate_steps=3)
    with pytest.raises(ValueError, match="accumulate_steps"):
        step(x, y)  # batch of 8 not divisible by 3
