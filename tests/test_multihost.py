"""Multi-process (multi-"host") bootstrap test: paddle-tpu-launch starts
2 workers, jax.distributed rendezvous over the launcher's coordinator
env, a global 4-device mesh spans both processes, collectives cross the
process boundary, and SPMD training matches a single-process oracle.

Reference analog: the fleet launch + gen_comm_id TCP rendezvous +
multi-node allreduce path (test_dist_base.py's subprocess pattern)."""
import os
import socket

import numpy as np


def _free_port():
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_two_process_bootstrap_and_training(tmp_path):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn, optimizer
    from paddle_tpu.jit import TrainStep

    # single-process oracle for the worker's training losses
    paddle.seed(7)
    net = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    r = np.random.RandomState(7)
    x = jnp.asarray(r.randn(8, 8), jnp.float32)
    y = jnp.asarray(r.randint(0, 4, (8,)), jnp.int32)
    opt = optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    local = TrainStep(net, lambda o, l: F.cross_entropy(o, l), opt)
    expect = [float(local(x, y)) for _ in range(2)]

    from paddle_tpu.distributed.launch import launch
    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_worker.py")
    env_backup = dict(os.environ)
    os.environ["EXPECT_LOSSES"] = ",".join(f"{v:.8f}" for v in expect)
    # workers must not inherit this process's single-chip/cpu jax state
    os.environ.pop("XLA_FLAGS", None)

    # dataset fixture for the cross-process global shuffle leg
    data_dir = tmp_path / "dataset"
    (data_dir / "spool").mkdir(parents=True)
    all_recs = []
    for i in range(5):
        lines = [f"f{i}r{j}" for j in range(4)]
        (data_dir / f"part-{i:03d}.txt").write_text(
            "\n".join(lines) + "\n")
        all_recs.extend(lines)
    os.environ["DATASET_DIR"] = str(data_dir)
    try:
        # retry once with a fresh port: _free_port has a TOCTOU window
        # under parallel test runs
        rc = launch(worker, nproc_per_node=2,
                    master_port=_free_port(), timeout=240)
        if rc != 0:
            rc = launch(worker, nproc_per_node=2,
                        master_port=_free_port(), timeout=240)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)
    assert rc == 0, f"multihost workers failed (exit {rc})"

    # GlobalShuffle contract across two REAL processes (data_set.h:111):
    # per-epoch the two shards are a disjoint exactly-once cover of the
    # dataset, deterministic in the epoch seed, re-shuffled across epochs
    import json
    epochs = {}
    for e in (0, 1):
        shards = [json.loads((data_dir / f"out_e{e}_r{r}.json")
                             .read_text()) for r in (0, 1)]
        union = shards[0] + shards[1]
        assert sorted(union) == sorted(all_recs)
        assert len(set(union)) == len(all_recs)
        assert abs(len(shards[0]) - len(shards[1])) <= 1
        epochs[e] = union
    assert epochs[0] != epochs[1]  # epoch seed reshuffles
