"""Every DistributedStrategy toggle is real or loud (VERDICT r3 #2).

Reference analogs: fleet/meta_optimizers/localsgd_optimizer.py (LocalSGD +
AdaptiveLocalSGD), fp16_allreduce_optimizer.py, recompute_optimizer.py,
dgc_optimizer.py, distributed_strategy.proto:106-118 (a_sync).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu import distributed as dist
from paddle_tpu.distributed.strategy import DistributedStrategy
from paddle_tpu.parallel import LocalSGDTrainStep, SpmdTrainStep
import paddle_tpu.nn.functional as F


@pytest.fixture(autouse=True)
def _mesh_dp8():
    dist.init_mesh({"dp": 8})
    yield


def _toy(seed=7, din=4, dout=3, bs=16):
    paddle.seed(seed)
    net = nn.Linear(din, dout)
    r = np.random.RandomState(seed)
    x = jnp.asarray(r.randn(bs, din), jnp.float32)
    y = jnp.asarray(r.randn(bs, dout), jnp.float32)
    loss_fn = lambda out, lab: F.mse_loss(out, lab)
    return net, x, y, loss_fn


def _weights(net):
    return {k: np.asarray(v.data).copy() for k, v in net.state_dict().items()}


# -- LocalSGD ------------------------------------------------------------

def _localsgd_oracle(w0, b0, x, y, lr, dp, k_steps, begin, n_steps):
    """NumPy oracle: per-replica SGD on its batch shard, mean every-step
    during warmup then every k steps (reference cond, :188-190)."""
    W = [w0.copy() for _ in range(dp)]
    B = [b0.copy() for _ in range(dp)]
    xs = x.reshape(dp, -1, x.shape[1])
    ys = y.reshape(dp, -1, y.shape[1])
    last = 0
    for t in range(1, n_steps + 1):
        for r in range(dp):
            pred = xs[r] @ W[r] + B[r]
            e = pred - ys[r]
            n = e.size
            gW = 2.0 / n * xs[r].T @ e
            gB = 2.0 / n * e.sum(0)
            W[r] = W[r] - lr * gW
            B[r] = B[r] - lr * gB
        sync = (t <= begin) or (t - last >= k_steps)
        if sync:
            Wm, Bm = np.mean(W, 0), np.mean(B, 0)
            W = [Wm.copy() for _ in range(dp)]
            B = [Bm.copy() for _ in range(dp)]
            last = t
    return np.mean(W, 0), np.mean(B, 0)


def test_localsgd_matches_numpy_oracle():
    net, x, y, loss_fn = _toy()
    w0 = np.asarray(net.weight.data).copy()
    b0 = np.asarray(net.bias.data).copy()
    strat = DistributedStrategy()
    strat.localsgd = True
    strat.localsgd_configs = {"k_steps": 3, "begin_step": 2}
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = LocalSGDTrainStep(net, loss_fn, opt, strategy=strat)
    for _ in range(7):
        step(x, y)
    step.sync_to_model()
    We, Be = _localsgd_oracle(w0, b0, np.asarray(x), np.asarray(y),
                              0.1, 8, 3, 2, 7)
    np.testing.assert_allclose(np.asarray(net.weight.data), We, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(net.bias.data), Be, rtol=1e-4,
                               atol=1e-5)


def test_localsgd_k1_equals_plain_dp():
    """k_steps=1 syncs every step — must match the SpmdTrainStep DP
    baseline (grad-mean == param-mean for SGD on a linear model)."""
    net, x, y, loss_fn = _toy(seed=11)
    init = _weights(net)
    strat = DistributedStrategy()
    strat.localsgd = True
    strat.localsgd_configs = {"k_steps": 1, "begin_step": 0}
    opt = optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
    step = LocalSGDTrainStep(net, loss_fn, opt, strategy=strat)
    for _ in range(4):
        step(x, y)
    step.sync_to_model()
    w_local = np.asarray(net.weight.data).copy()

    net.set_state_dict(init)
    opt2 = optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
    base = SpmdTrainStep(net, loss_fn, opt2)
    for _ in range(4):
        base(x, y)
    np.testing.assert_allclose(w_local, np.asarray(net.weight.data),
                               rtol=1e-4, atol=1e-5)


def test_localsgd_diverges_between_syncs():
    """With k=4 the replicas genuinely diverge mid-interval (the toggle
    changes numerics — VERDICT: no silent no-op)."""
    net, x, y, loss_fn = _toy(seed=13)
    strat = DistributedStrategy()
    strat.localsgd = True
    strat.localsgd_configs = {"k_steps": 4, "begin_step": 0}
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = LocalSGDTrainStep(net, loss_fn, opt, strategy=strat)
    step(x, y)   # step 1: no sync (1-0 < 4)
    rep = np.asarray(step._p_rep[0])
    spread = np.abs(rep - rep.mean(0, keepdims=True)).max()
    assert spread > 1e-6, "replicas did not diverge — localsgd inert"


def test_adaptive_localsgd_adapts_k():
    net, x, y, loss_fn = _toy(seed=17)
    strat = DistributedStrategy()
    strat.adaptive_localsgd = True
    strat.adaptive_localsgd_configs = {"init_k_steps": 4, "begin_step": 2}
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = LocalSGDTrainStep(net, loss_fn, opt, strategy=strat)
    assert step.adaptive
    ks = []
    for _ in range(12):
        step(x, y)
        ks.append(step.k_steps)
    assert all(1 <= k <= 16 for k in ks)
    # loss decreases on this convex problem → k should shrink from init
    assert ks[-1] <= 4
    assert len(set(ks)) > 1, "k never adapted"


def test_fleet_routes_localsgd():
    from paddle_tpu.distributed.fleet import Fleet
    f = Fleet()
    strat = DistributedStrategy()
    strat.localsgd = True
    f.init(strategy=strat)
    net, x, y, loss_fn = _toy(seed=19)
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    f.distributed_optimizer(opt)
    step = f.get_train_step(net, loss_fn)
    assert isinstance(step, LocalSGDTrainStep)
    l0 = float(step(x, y))
    l1 = float(step(x, y))
    assert l1 < l0


# -- fp16_allreduce ------------------------------------------------------

def test_fp16_allreduce_quantises_grads():
    """The toggle must change numerics (bf16-quantised grad reduction)
    while staying close to the f32 baseline."""
    net, x, y, loss_fn = _toy(seed=23, din=8, dout=8, bs=32)
    init = _weights(net)
    strat = DistributedStrategy()
    strat.fp16_allreduce = True
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = SpmdTrainStep(net, loss_fn, opt, strategy=strat)
    for _ in range(3):
        step(x, y)
    w_half = np.asarray(net.weight.data).copy()

    net.set_state_dict(init)
    opt2 = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    base = SpmdTrainStep(net, loss_fn, opt2)
    for _ in range(3):
        base(x, y)
    w_full = np.asarray(net.weight.data).copy()
    # close (bf16 has ~3 decimal digits) but NOT bitwise identical
    np.testing.assert_allclose(w_half, w_full, rtol=3e-2, atol=3e-3)
    assert not np.array_equal(w_half, w_full), \
        "fp16_allreduce changed nothing — silent no-op"


def test_fp16_allreduce_rejects_model_sharding():
    net, x, y, loss_fn = _toy()
    dist.init_mesh({"dp": 4, "mp": 2})
    strat = DistributedStrategy()
    strat.fp16_allreduce = True
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    with pytest.raises(NotImplementedError, match="fp16_allreduce"):
        SpmdTrainStep(net, loss_fn, opt, strategy=strat)


# -- recompute -----------------------------------------------------------

def test_recompute_toggle_remats_and_matches():
    net, x, y, loss_fn = _toy(seed=29)
    init = _weights(net)
    strat = DistributedStrategy()
    strat.recompute = True
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = SpmdTrainStep(net, loss_fn, opt, strategy=strat)
    assert step._recompute
    # the jaxpr of the compiled step contains a remat call
    fn = step._make_step_fn()
    p_arr = tuple(p.data for p in step._params)
    state = opt.functional_init(list(p_arr))
    aux = step._init_scaler_state()
    jaxpr = jax.make_jaxpr(fn)(p_arr, (), state, aux,
                               jnp.float32(0.1), (x,), (y,))
    assert "remat" in str(jaxpr), "strategy.recompute did not remat"
    losses = [float(step(x, y)) for _ in range(3)]

    net.set_state_dict(init)
    opt2 = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    base = SpmdTrainStep(net, loss_fn, opt2)
    base_losses = [float(base(x, y)) for _ in range(3)]
    np.testing.assert_allclose(losses, base_losses, rtol=1e-5)


# -- dead toggles raise --------------------------------------------------

def test_dgc_raises():
    net, x, y, loss_fn = _toy()
    strat = DistributedStrategy()
    strat.dgc = True
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    with pytest.raises(NotImplementedError, match="dgc"):
        SpmdTrainStep(net, loss_fn, opt, strategy=strat)
    from paddle_tpu.distributed.fleet import Fleet
    f = Fleet()
    f.init(strategy=strat)
    with pytest.raises(NotImplementedError, match="dgc"):
        f.distributed_optimizer(opt)


def test_a_sync_raises():
    net, x, y, loss_fn = _toy()
    strat = DistributedStrategy()
    strat.a_sync = True
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    with pytest.raises(NotImplementedError, match="a_sync"):
        SpmdTrainStep(net, loss_fn, opt, strategy=strat)


# -- ZeRO-3 padded sharding (VERDICT r3 #10) -----------------------------

def test_zero3_pads_odd_params():
    """Params whose dim0 % dp != 0 must still shard at stage 3 (the
    reference pads by numel, meta_optimizers/sharding/shard.py) and train
    to the same numbers as the unsharded baseline."""
    paddle.seed(31)
    net = nn.Sequential(nn.Linear(7, 13), nn.Tanh(), nn.Linear(13, 5))
    r = np.random.RandomState(31)
    x = jnp.asarray(r.randn(16, 7), jnp.float32)
    y = jnp.asarray(r.randn(16, 5), jnp.float32)
    loss_fn = lambda out, lab: F.mse_loss(out, lab)
    init = {k: np.asarray(v.data).copy()
            for k, v in net.state_dict().items()}

    strat = DistributedStrategy()
    strat.sharding = True
    strat.sharding_configs = {"stage": 3, "min_shard_numel": 1}
    opt = optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    step = SpmdTrainStep(net, loss_fn, opt, strategy=strat)
    # every param is sharded over dp — none silently replicated
    from jax.sharding import PartitionSpec
    for i, p in enumerate(step._params):
        assert step._param_spec(i, p) == PartitionSpec("dp"), (
            i, p.shape_tuple)
    # (7,13) and (13,) and (5,) need padding to multiples of 8
    assert len(step._padded) >= 3
    z3 = [float(step(x, y)) for _ in range(3)]
    # stored arrays really carry padded dim0 and dp sharding
    for i, (d0, pd0) in step._padded.items():
        arr = step._p_store[i]
        assert arr.shape[0] == pd0 and pd0 % 8 == 0
        assert arr.sharding.spec == PartitionSpec("dp")
    # pad rows stay zero (optimizer must not leak into padding)
    i0 = next(iter(step._padded))
    d0, pd0 = step._padded[i0]
    pad_rows = np.asarray(step._p_store[i0][d0:])
    assert np.all(pad_rows == 0)

    # sync back to model and compare against unsharded baseline
    step.sync_params()
    w_z3 = {k: np.asarray(v.data).copy()
            for k, v in net.state_dict().items()}

    net.set_state_dict(init)
    from paddle_tpu.jit import TrainStep
    opt2 = optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    base = TrainStep(net, loss_fn, opt2)
    base_losses = [float(base(x, y)) for _ in range(3)]
    np.testing.assert_allclose(z3, base_losses, rtol=2e-4, atol=1e-6)
    for k, v in net.state_dict().items():
        np.testing.assert_allclose(w_z3[k], np.asarray(v.data),
                                   rtol=2e-4, atol=1e-6)


# -- fleet.save_inference_model is real ----------------------------------

def test_fleet_save_inference_model(tmp_path):
    from paddle_tpu.distributed.fleet import Fleet
    from paddle_tpu.static import InputSpec
    f = Fleet()
    f.init()
    net, x, y, loss_fn = _toy(seed=37)
    f.distributed_model(net)
    path = f.save_inference_model(
        dirname=str(tmp_path),
        input_spec=[InputSpec([None, 4], "float32")])
    loaded = paddle.jit.load(path)
    out = loaded(paddle.to_tensor(np.asarray(x)))
    ref = net(paddle.to_tensor(np.asarray(x)))
    np.testing.assert_allclose(np.asarray(out.data), np.asarray(ref.data),
                               rtol=1e-5, atol=1e-6)


def test_localsgd_optimizer_checkpoint_roundtrip():
    """Regression (r4 review): optimizer.state_dict/set_state_dict must
    work with a bound LocalSGDTrainStep, and a restore must reset the
    replica store so loaded weights win."""
    net, x, y, loss_fn = _toy(seed=41)
    strat = DistributedStrategy()
    strat.localsgd = True
    strat.localsgd_configs = {"k_steps": 2, "begin_step": 0}
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = LocalSGDTrainStep(net, loss_fn, opt, strategy=strat)
    for _ in range(3):
        step(x, y)
    sd = opt.state_dict()
    assert sd["step"] == 3
    step.sync_to_model()
    w3 = np.asarray(net.weight.data).copy()
    for _ in range(2):
        step(x, y)
    # restore: loaded weights + counter must win over diverged replicas
    net.weight.data = paddle.to_tensor(w3).data
    opt.set_state_dict(sd)
    assert step._p_rep is None      # replica store dropped
    step(x, y)
    assert int(step._aux["step"]) == 4


def test_localsgd_rejects_silently_droppable_toggles():
    net, x, y, loss_fn = _toy()
    strat = DistributedStrategy()
    strat.localsgd = True
    strat.sharding = True
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    with pytest.raises(NotImplementedError, match="sharding"):
        LocalSGDTrainStep(net, loss_fn, opt, strategy=strat)


def test_zero3_padded_honors_external_load():
    """Regression (r4 review): set_state_dict on a model bound to a live
    padded stage-3 step must not be silently ignored."""
    paddle.seed(43)
    net = nn.Linear(7, 5)
    r = np.random.RandomState(43)
    x = jnp.asarray(r.randn(16, 7), jnp.float32)
    y = jnp.asarray(r.randn(16, 5), jnp.float32)
    loss_fn = lambda out, lab: F.mse_loss(out, lab)
    strat = DistributedStrategy()
    strat.sharding = True
    strat.sharding_configs = {"stage": 3, "min_shard_numel": 1}
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = SpmdTrainStep(net, loss_fn, opt, strategy=strat)
    step(x, y)
    # external load of fresh weights
    w_new = r.randn(7, 5).astype(np.float32)
    net.weight.data = paddle.to_tensor(w_new).data
    step(x, y)
    step.sync_params()
    got = np.asarray(net.weight.data)
    # one SGD step from w_new, NOT from the old trajectory
    expect_g = 2.0 / y.size * np.asarray(x).T @ (
        np.asarray(x) @ w_new + np.asarray(net.bias.data) * 0
        + np.asarray(net.bias.data) - np.asarray(y))
    # bias also trained a step before the load; just assert the weight
    # moved from w_new by one lr-sized step, not from the old weights
    assert np.abs(got - w_new).max() < 0.1 * np.abs(expect_g).max() * 3
    assert np.abs(got - w_new).max() > 0


def test_fleet_save_inference_model_loud_without_model():
    from paddle_tpu.distributed.fleet import Fleet
    f = Fleet()
    with pytest.raises(ValueError, match="no model"):
        f.save_inference_model(dirname="/tmp/x")


# ---- round-5 knob kills (VERDICT r4 #4): work or raise, never silent ----

def test_schedule_mode_f_then_b_raises():
    from paddle_tpu.distributed.strategy import (DistributedStrategy,
                                                 validate_toggles)
    s = DistributedStrategy()
    s.pipeline = True
    s.pipeline_configs.schedule_mode = "F-then-B"
    with pytest.raises(NotImplementedError, match="F-then-B"):
        validate_toggles(s)
    # default 1F1B passes; unknown value rejected outright
    s.pipeline_configs.schedule_mode = "1F1B"
    validate_toggles(s)
    s.pipeline_configs.schedule_mode = "zigzag"
    with pytest.raises(ValueError, match="schedule_mode"):
        validate_toggles(s)


def test_build_strategy_absorbed_vs_unsupported():
    from paddle_tpu import static
    bs = static.BuildStrategy()
    bs.fuse_elewise_add_act_ops = True     # XLA does this: accepted
    bs.memory_optimize = True
    prog = static.Program()
    cp = static.CompiledProgram(prog, build_strategy=bs)
    assert cp._build_strategy is bs
    with pytest.raises(NotImplementedError, match="reduce_strategy"):
        bs.reduce_strategy = 1
    with pytest.raises(AttributeError, match="no toggle"):
        bs.totally_made_up = True
    with pytest.raises(TypeError, match="BuildStrategy"):
        static.CompiledProgram(prog, build_strategy=object())
    with pytest.raises(NotImplementedError, match="with_data_parallel"):
        cp.with_data_parallel(loss_name="loss")


def test_static_dropout_reseeds_per_run():
    import paddle_tpu as paddle
    from paddle_tpu import static
    import paddle_tpu.nn.functional as F

    paddle.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [64, 64], "float32")
            y = F.dropout(x, p=0.5, training=True)
        exe = static.Executor()
        exe.run(startup)
        feed = {"x": np.ones((64, 64), np.float32)}
        a = exe.run(main, feed=feed, fetch_list=[y])[0]
        b = exe.run(main, feed=feed, fetch_list=[y])[0]
        # per-run reseed: masks differ between runs (4096 cells — equal
        # masks would mean the key was baked at build time)
        assert (a != b).any()
        assert set(np.unique(a)) <= {0.0, 2.0}
        # explicit seed reproduces a run exactly
        c = exe.run(main, feed=feed, fetch_list=[y], seed=123)[0]
        d = exe.run(main, feed=feed, fetch_list=[y], seed=123)[0]
        np.testing.assert_array_equal(c, d)
        e = exe.run(main, feed=feed, fetch_list=[y], seed=124)[0]
        assert (c != e).any()
    finally:
        paddle.disable_static()


# -- grad_comm knob validation (ISSUE 10 satellites) ---------------------

def test_fuse_grad_size_rejects_nonsense():
    """fuse_grad_size_in_MB is wired to bucketing now; <=0 must fail
    with an actionable message instead of silently disabling reduction."""
    from paddle_tpu.core.enforce import InvalidArgumentError
    from paddle_tpu.distributed.strategy import validate_toggles
    for bad in (0, -3, 0.0):
        s = DistributedStrategy()
        s.fuse_grad_size_in_MB = bad
        with pytest.raises(InvalidArgumentError,
                           match="fuse_grad_size_in_MB"):
            validate_toggles(s)
    s = DistributedStrategy()
    s.fuse_grad_size_in_MB = 16
    validate_toggles(s)  # positive passes


def test_grad_comm_knob_validation():
    from paddle_tpu.core.enforce import InvalidArgumentError
    from paddle_tpu.distributed.strategy import validate_toggles
    s = DistributedStrategy()
    s.grad_comm = {"dtype": "fp8"}
    with pytest.raises(InvalidArgumentError, match="wire dtype"):
        validate_toggles(s)
    s = DistributedStrategy()
    s.grad_comm = {"dtype": "int8", "block_size": 0}
    with pytest.raises(InvalidArgumentError, match="block"):
        validate_toggles(s)
    s = DistributedStrategy()
    s.grad_comm = {"dtype": "int8", "scatter_threshold_KB": -1}
    with pytest.raises(InvalidArgumentError, match="scatter_threshold"):
        validate_toggles(s)
    # the alias conflicts with an explicit non-bf16 dtype
    s = DistributedStrategy()
    s.fp16_allreduce = True
    s.grad_comm = {"dtype": "int8"}
    with pytest.raises(InvalidArgumentError, match="alias"):
        validate_toggles(s)
    # alias + explicit bf16 agree; every valid dtype passes
    for d in (None, "fp32", "bf16", "int8"):
        s = DistributedStrategy()
        s.grad_comm = {"dtype": d}
        validate_toggles(s)


def test_grad_comm_rejects_model_sharded_mesh():
    """Same guard the fp16_allreduce graft had: the explicit dp
    reduction cannot run on a mesh carrying model axes."""
    net, x, y, loss_fn = _toy()
    dist.init_mesh({"dp": 4, "mp": 2})
    strat = DistributedStrategy()
    strat.grad_comm = {"dtype": "int8", "error_feedback": False}
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    with pytest.raises(NotImplementedError, match="grad_comm"):
        SpmdTrainStep(net, loss_fn, opt, strategy=strat)


def test_grad_comm_spmd_int8_trains_close_to_fp32():
    """int8 block-scaled reduction on the SpmdTrainStep path changes
    numerics (no silent no-op) while staying close to fp32."""
    net, x, y, loss_fn = _toy(seed=23, din=8, dout=8, bs=32)
    init = _weights(net)
    strat = DistributedStrategy()
    strat.grad_comm = {"dtype": "int8", "error_feedback": False,
                       "scatter_threshold_KB": 0.01, "block_size": 32}
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = SpmdTrainStep(net, loss_fn, opt, strategy=strat)
    for _ in range(3):
        step(x, y)
    assert step._comm_plan is not None  # set at first compile
    assert any(b.wire_dtype == "int8" for b in step._comm_plan.buckets)
    w_q = np.asarray(net.weight.data).copy()

    net.set_state_dict(init)
    opt2 = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    base = SpmdTrainStep(net, loss_fn, opt2)
    for _ in range(3):
        base(x, y)
    w_full = np.asarray(net.weight.data).copy()
    np.testing.assert_allclose(w_q, w_full, rtol=3e-2, atol=3e-3)
    assert not np.array_equal(w_q, w_full), \
        "grad_comm int8 changed nothing — silent no-op"


def test_fp16_allreduce_zero3_still_raises():
    """Satellite guard kept through the grad_comm retirement: the alias
    + ZeRO-3 (dp-sharded params) is still a loud incompatibility."""
    net, x, y, loss_fn = _toy()
    strat = DistributedStrategy()
    strat.fp16_allreduce = True
    strat.sharding = True
    strat.sharding_configs = {"stage": 3, "min_shard_numel": 1}
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    with pytest.raises(NotImplementedError, match="fp16_allreduce"):
        SpmdTrainStep(net, loss_fn, opt, strategy=strat)


def test_grad_comm_overlap_knob_validation():
    """ISSUE 14: the overlap knob validates like every other grad_comm
    knob — a typo'd mode fails loudly, every real mode passes."""
    from paddle_tpu.core.enforce import InvalidArgumentError
    from paddle_tpu.distributed.strategy import validate_toggles
    s = DistributedStrategy()
    s.grad_comm = {"dtype": "int8", "overlap": "eager"}
    with pytest.raises(InvalidArgumentError, match="overlap"):
        validate_toggles(s)
    for ov in ("none", "auto", "ring"):
        s = DistributedStrategy()
        s.grad_comm = {"dtype": "int8", "overlap": ov}
        validate_toggles(s)
    # the knob rides the spec fingerprint: flips must recompile
    from paddle_tpu.distributed import grad_comm as gcx
    fps = set()
    for ov in ("none", "auto", "ring"):
        s = DistributedStrategy()
        s.grad_comm = {"dtype": "int8", "overlap": ov}
        fps.add(gcx.resolve(s).fingerprint())
    assert len(fps) == 3


def test_grad_comm_hybrid_degree_combos_validate():
    """ISSUE 17: grad_comm now composes with tensor_parallel and
    ZeRO-3 degree combos at validation time; pp/sp remain rejected
    with an actionable message; infer_mesh_shape covers the composed
    cases."""
    from paddle_tpu.distributed.strategy import validate_toggles
    # fsdp + mp + grad_comm: accepted, mesh composes {dp, mp}
    s = DistributedStrategy()
    s.grad_comm = {"dtype": "int8"}
    s.sharding = True
    s.sharding_configs = {"stage": 3, "min_shard_numel": 1}
    s.tensor_parallel = True
    s.tensor_parallel_configs = {"tensor_parallel_degree": 2}
    validate_toggles(s, n_devices=8)
    assert s.infer_mesh_shape(8) == {"dp": 4, "mp": 2}
    # pp/sp corners: loud, actionable, name the offending toggle
    for toggle in ("pipeline", "sequence_parallel"):
        s = DistributedStrategy()
        s.grad_comm = {"dtype": "bf16"}
        setattr(s, toggle, True)
        with pytest.raises(NotImplementedError, match=toggle):
            validate_toggles(s)
    # the alias spelling hits the same guard
    s = DistributedStrategy()
    s.fp16_allreduce = True
    s.pipeline = True
    with pytest.raises(NotImplementedError, match="cross-stage"):
        validate_toggles(s)
    # without grad_comm the same pp strategy validates fine
    s = DistributedStrategy()
    s.pipeline = True
    validate_toggles(s)
