"""vision.ops detection primitive tests (reference analog: test_nms_op,
test_iou_similarity_op): IoU math and greedy NMS vs a naive oracle."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.vision import ops


def _naive_nms(boxes, scores, thr):
    order = np.argsort(-scores)
    keep, suppressed = [], np.zeros(len(boxes), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        for j in order:
            if j == i or suppressed[j]:
                continue
            xa1, ya1, xa2, ya2 = boxes[i]
            xb1, yb1, xb2, yb2 = boxes[j]
            iw = max(0, min(xa2, xb2) - max(xa1, xb1))
            ih = max(0, min(ya2, yb2) - max(ya1, yb1))
            inter = iw * ih
            ua = ((xa2 - xa1) * (ya2 - ya1) + (xb2 - xb1) * (yb2 - yb1)
                  - inter)
            if inter / max(ua, 1e-9) > thr:
                suppressed[j] = True
    return keep


def test_box_iou_known_values():
    a = paddle.to_tensor(np.array([[0, 0, 2, 2]], np.float32))
    b = paddle.to_tensor(np.array([[1, 1, 3, 3], [0, 0, 2, 2],
                                   [5, 5, 6, 6]], np.float32))
    iou = ops.box_iou(a, b).numpy()
    np.testing.assert_allclose(iou[0], [1 / 7, 1.0, 0.0], rtol=1e-6)


def test_nms_matches_naive_oracle():
    rng = np.random.RandomState(0)
    for _ in range(5):
        xy = rng.rand(40, 2) * 10
        wh = rng.rand(40, 2) * 4 + 0.5
        boxes = np.concatenate([xy, xy + wh], 1).astype(np.float32)
        scores = rng.rand(40).astype(np.float32)
        want = _naive_nms(boxes, scores, 0.4)
        got = ops.nms(paddle.to_tensor(boxes), 0.4,
                      paddle.to_tensor(scores)).numpy().tolist()
        assert got == want, (got, want)


def test_nms_static_topk_under_jit():
    import jax
    boxes = np.array([[0, 0, 2, 2], [0, 0, 2.1, 2.1], [5, 5, 7, 7]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)

    @jax.jit
    def jitted(b, s):
        return ops.nms(paddle.to_tensor(b), 0.5, paddle.to_tensor(s),
                       top_k=3).data

    got = np.asarray(jitted(boxes, scores)).tolist()
    assert got == [0, 2, -1]  # box1 suppressed by box0; padded with -1


def test_nms_class_aware():
    boxes = np.array([[0, 0, 2, 2], [0, 0, 2, 2]], np.float32)
    scores = np.array([0.9, 0.8], np.float32)
    cats = np.array([0, 1])
    got = ops.nms(paddle.to_tensor(boxes), 0.5, paddle.to_tensor(scores),
                  category_idxs=paddle.to_tensor(cats),
                  categories=[0, 1]).numpy().tolist()
    assert got == [0, 1]  # different classes never suppress each other
