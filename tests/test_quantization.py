"""paddle.quantization tests (reference analogs: test_quant_aware.py,
test_post_training_quantization_*): fake-quant numerics, STE gradients,
QAT training, PTQ calibrate->convert accuracy."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, optimizer
from paddle_tpu.quantization import (PTQ, QAT, QuantConfig,
                                     fake_quantize_abs_max)


def test_fake_quant_roundtrip_accuracy():
    paddle.seed(0)
    x = paddle.randn([64, 32])
    q = fake_quantize_abs_max(x, bit_length=8)
    err = np.abs(q.numpy() - x.numpy()).max()
    step = np.abs(x.numpy()).max() / 127
    assert err <= step * 0.51 + 1e-7  # within half a quant step


def test_fake_quant_ste_gradient():
    x = paddle.to_tensor(np.linspace(-1, 1, 16).astype(np.float32),
                         stop_gradient=False)
    fake_quantize_abs_max(x).sum().backward()
    # straight-through: gradient of round is identity inside the range
    np.testing.assert_allclose(x.grad.numpy(), np.ones(16), rtol=1e-5)


def test_qat_model_trains():
    paddle.seed(1)
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 1))
    QAT(QuantConfig()).quantize(model)
    opt = optimizer.Adam(learning_rate=0.01,
                         parameters=model.parameters())
    x = paddle.randn([64, 8])
    y = x.matmul(paddle.randn([8, 1]))
    losses = []
    for _ in range(50):
        loss = F.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])


def test_ptq_calibrate_convert_close_to_float():
    paddle.seed(2)
    fl = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    x = paddle.randn([32, 16])
    ref = fl(x).numpy()

    q = PTQ().quantize(fl)
    for _ in range(4):   # calibration forwards
        q(x)
    PTQ.convert(q)
    got = q(x).numpy()
    denom = np.abs(ref).max()
    assert np.abs(got - ref).max() / denom < 0.1, (
        np.abs(got - ref).max() / denom)


def test_qat_conv_swap():
    paddle.seed(3)
    from paddle_tpu.quantization import QuantizedConv2D
    m = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU())
    QAT().quantize(m)
    assert isinstance(m[0], QuantizedConv2D)
    out = m(paddle.randn([2, 3, 8, 8]))
    assert out.shape == [2, 8, 8, 8]


# -- int8 deployment (VERDICT r4 #8) -------------------------------------

def test_convert_to_int8_accuracy_and_serving(tmp_path):
    """PTQ -> convert_to_int8 -> jit.save -> Predictor: the served int8
    model must stay close to the float model, and the artifact must store
    int8 weights (reference: contrib/slim quant2_int8 flow)."""
    import os
    import jax.numpy as jnp
    from paddle_tpu import inference, jit
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.quantization import PTQ, convert_to_int8, Int8Linear

    paddle.seed(50)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    r = np.random.RandomState(50)
    x = paddle.to_tensor(r.randn(8, 16).astype(np.float32))
    ref = net(x).numpy()

    q = PTQ().quantize(net)
    for _ in range(4):          # calibration
        net(x)
    PTQ.convert(net)
    convert_to_int8(net)
    assert any(isinstance(m, Int8Linear) for m in net.sublayers())
    got = net(x).numpy()
    # int8 close to float on this scale of model
    assert np.abs(got - ref).max() < 0.12 * np.abs(ref).max() + 0.05

    # int8 weights live in the state dict (small artifact)
    sd = net.state_dict()
    qw = [v for k, v in sd.items() if k.endswith("qweight")]
    assert qw and all(np.asarray(v.data).dtype == np.int8 for v in qw)
    assert not any(k.endswith(".weight") for k in sd)  # f32 weights gone

    pfx = os.path.join(str(tmp_path), "int8")
    jit.save(net, pfx, input_spec=[InputSpec([None, 16], "float32")])
    pred = inference.create_predictor(inference.Config(pfx))
    out = np.asarray(pred.run([np.asarray(x.data)])[0])
    np.testing.assert_allclose(out, got, rtol=2e-3, atol=1e-3)


def test_int8_static_activation_matmul_path():
    """With a calibrated activation scale the linear runs the int8 x int8
    -> int32 dot (static path), and still tracks the float result."""
    from paddle_tpu.quantization import QuantConfig, Int8Linear

    paddle.seed(51)
    lin = nn.Linear(8, 4)
    r = np.random.RandomState(51)
    x = paddle.to_tensor(r.randn(4, 8).astype(np.float32))
    ref = lin(x).numpy()
    i8 = Int8Linear(lin, act_scale=float(np.abs(x.numpy()).max()))
    assert i8._static_act
    got = i8(x).numpy()
    assert np.abs(got - ref).max() < 0.1 * np.abs(ref).max() + 0.05
    # weight-only dynamic path too
    i8d = Int8Linear(lin, act_scale=0.0)
    assert not i8d._static_act
    got_d = i8d(x).numpy()
    assert np.abs(got_d - ref).max() < 0.05 * np.abs(ref).max() + 0.02
