"""paddle.quantization tests (reference analogs: test_quant_aware.py,
test_post_training_quantization_*): fake-quant numerics, STE gradients,
QAT training, PTQ calibrate->convert accuracy."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, optimizer
from paddle_tpu.quantization import (PTQ, QAT, QuantConfig,
                                     fake_quantize_abs_max)


def test_fake_quant_roundtrip_accuracy():
    paddle.seed(0)
    x = paddle.randn([64, 32])
    q = fake_quantize_abs_max(x, bit_length=8)
    err = np.abs(q.numpy() - x.numpy()).max()
    step = np.abs(x.numpy()).max() / 127
    assert err <= step * 0.51 + 1e-7  # within half a quant step


def test_fake_quant_ste_gradient():
    x = paddle.to_tensor(np.linspace(-1, 1, 16).astype(np.float32),
                         stop_gradient=False)
    fake_quantize_abs_max(x).sum().backward()
    # straight-through: gradient of round is identity inside the range
    np.testing.assert_allclose(x.grad.numpy(), np.ones(16), rtol=1e-5)


def test_qat_model_trains():
    paddle.seed(1)
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 1))
    QAT(QuantConfig()).quantize(model)
    opt = optimizer.Adam(learning_rate=0.01,
                         parameters=model.parameters())
    x = paddle.randn([64, 8])
    y = x.matmul(paddle.randn([8, 1]))
    losses = []
    for _ in range(50):
        loss = F.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])


def test_ptq_calibrate_convert_close_to_float():
    paddle.seed(2)
    fl = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    x = paddle.randn([32, 16])
    ref = fl(x).numpy()

    q = PTQ().quantize(fl)
    for _ in range(4):   # calibration forwards
        q(x)
    PTQ.convert(q)
    got = q(x).numpy()
    denom = np.abs(ref).max()
    assert np.abs(got - ref).max() / denom < 0.1, (
        np.abs(got - ref).max() / denom)


def test_qat_conv_swap():
    paddle.seed(3)
    from paddle_tpu.quantization import QuantizedConv2D
    m = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU())
    QAT().quantize(m)
    assert isinstance(m[0], QuantizedConv2D)
    out = m(paddle.randn([2, 3, 8, 8]))
    assert out.shape == [2, 8, 8, 8]
