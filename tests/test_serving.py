"""paddle_tpu.serving tests (ISSUE 4): dynamic-batching engine semantics
(bitwise batched-vs-unbatched equivalence, zero recompiles after warmup,
deadlines, shedding, drain), the HTTP front-end under concurrent
clients, the Predictor pad-to-bucket satellite, and monitor histograms.
ISSUE 18 adds the self-healing rails: close() hard deadline under a
wedged dispatcher, SIGTERM during warmup, the readiness split, and the
client restart ride-through.
"""
import os
import signal
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference, jit, nn, serving
from paddle_tpu.jit import InputSpec
from paddle_tpu.testing import fault
from paddle_tpu.testing.chaos import make_dyadic_lm, make_dyadic_model
from paddle_tpu.utils import monitor


def _dyadic_requests(rng, n, in_dim=8, max_rows=4):
    """Inputs that are small dyadic rationals: float accumulation is
    exact, so batched/padded results are bitwise-equal to unbatched."""
    return [(rng.randint(-8, 9, (rng.randint(1, max_rows + 1), in_dim))
             / 4.0).astype(np.float32) for _ in range(n)]


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    paddle.seed(7)
    model = make_dyadic_model(in_dim=8, hidden=16, out_dim=4)
    prefix = os.path.join(str(tmp_path_factory.mktemp("serving")), "m")
    jit.save(model, prefix, input_spec=[InputSpec([None, 8], "float32")])
    return prefix


def _engine(prefix, **kw):
    pred = inference.create_predictor(inference.Config(prefix))
    kw.setdefault("max_batch_size", 8)
    kw.setdefault("batch_timeout_ms", 5.0)
    eng = serving.InferenceEngine(pred, **kw)
    eng.warmup()
    return eng, pred


# ------------------------------------------------------------- engine --
def test_batched_equals_unbatched_bitwise(artifact):
    eng, pred = _engine(artifact)
    try:
        rng = np.random.RandomState(0)
        reqs = _dyadic_requests(rng, 24)
        refs = [np.asarray(pred.run([x])[0]) for x in reqs]
        futs = [eng.infer([x]) for x in reqs]   # burst: forces coalescing
        for f, ref, x in zip(futs, refs, reqs):
            out = f.result(timeout=30)
            assert out[0].shape == (x.shape[0], 4)
            np.testing.assert_array_equal(out[0], ref)
    finally:
        eng.close()


def test_zero_recompiles_after_warmup(artifact):
    eng, pred = _engine(artifact)
    try:
        base = pred.num_compiled_variants()
        rng = np.random.RandomState(1)
        futs = [eng.infer([x]) for x in _dyadic_requests(rng, 32)]
        for f in futs:
            f.result(timeout=30)
        assert pred.num_compiled_variants() == base
        st = eng.stats()
        assert st["recompiles_after_warmup"] == 0
        assert st["counters"]["batches"] < 32  # coalescing happened
    finally:
        eng.close()


def test_input_validation(artifact):
    eng, _ = _engine(artifact)
    try:
        with pytest.raises(ValueError, match="leading batch dim"):
            eng.infer([np.float32(1.0)])        # scalar input
        with pytest.raises(ValueError, match="max_batch_size"):
            eng.infer([np.zeros((64, 8), np.float32)])
        with pytest.raises(ValueError, match="expected 1 inputs"):
            eng.infer([np.zeros((2, 8), np.float32)] * 2)
        with pytest.raises(ValueError, match="empty request"):
            eng.infer([np.zeros((0, 8), np.float32)])
    finally:
        eng.close()


def test_mismatched_rest_dims_rejected_at_admission(artifact):
    """A mis-shaped request must be rejected at infer(), never reach a
    coalesced batch (where np.concatenate would kill the dispatcher)."""
    eng, _ = _engine(artifact)
    try:
        with pytest.raises(ValueError, match="per-row shape"):
            eng.infer([np.ones((2, 9), np.float32)])    # model wants 8
        # dispatcher unharmed: a good request still serves
        assert eng.infer_sync([np.ones((2, 8), np.float32)],
                              timeout=30)[0].shape == (2, 4)
    finally:
        eng.close()


def test_dispatcher_survives_execute_crash(artifact):
    """Defense in depth: even an exception outside the retry loop fails
    only that batch's futures — the dispatcher thread lives on."""
    eng, _ = _engine(artifact)
    try:
        orig = eng._bucket_for        # called in _execute BEFORE the
        eng._bucket_for = lambda rows: (_ for _ in ()).throw(
            RuntimeError("boom outside retry"))  # dispatch-retry loop
        f = eng.infer([np.ones((1, 8), np.float32)])
        with pytest.raises(RuntimeError, match="boom"):
            f.result(timeout=30)
        eng._bucket_for = orig
        assert eng.infer_sync([np.ones((1, 8), np.float32)],
                              timeout=30)[0].shape == (1, 4)
    finally:
        eng.close()


def test_dict_and_bare_array_inputs(artifact):
    eng, pred = _engine(artifact)
    try:
        x = (np.arange(16).reshape(2, 8) / 4.0).astype(np.float32)
        name = pred.get_input_names()[0]
        a = eng.infer_sync({name: x}, timeout=30)
        b = eng.infer_sync(x, timeout=30)       # bare array = only input
        np.testing.assert_array_equal(a[0], b[0])
    finally:
        eng.close()


def test_deadline_expires_in_queue(artifact):
    eng, _ = _engine(artifact)
    try:
        eng.pause()
        x = np.ones((1, 8), np.float32)
        doomed = eng.infer([x], deadline_ms=1.0)
        ok = eng.infer([x])                     # no deadline: survives
        time.sleep(0.02)
        eng.resume()
        with pytest.raises(serving.DeadlineExceeded):
            doomed.result(timeout=30)
        assert ok.result(timeout=30)[0].shape == (1, 4)
        assert eng.stats()["counters"]["deadline_expired"] == 1
    finally:
        eng.close()


def test_default_deadline(artifact):
    eng, _ = _engine(artifact, default_deadline_ms=1.0)
    try:
        eng.pause()
        f = eng.infer([np.ones((1, 8), np.float32)])
        time.sleep(0.02)
        eng.resume()
        with pytest.raises(serving.DeadlineExceeded):
            f.result(timeout=30)
    finally:
        eng.close()


def test_queue_full_sheds(artifact):
    eng, _ = _engine(artifact, max_queue=4)
    try:
        eng.pause()
        x = np.ones((1, 8), np.float32)
        futs = [eng.infer([x]) for _ in range(4)]
        for _ in range(3):
            with pytest.raises(serving.QueueFull):
                eng.infer([x])
        assert eng.stats()["counters"]["shed"] == 3
        eng.resume()
        for f in futs:                          # accepted ones all serve
            assert f.result(timeout=30)[0].shape == (1, 4)
    finally:
        eng.close()


def test_invalid_buckets_rejected(artifact):
    pred = inference.create_predictor(inference.Config(artifact))
    with pytest.raises(ValueError, match="exceeds max_batch_size"):
        serving.InferenceEngine(pred, max_batch_size=8, buckets=[48])
    with pytest.raises(ValueError, match="positive"):
        serving.InferenceEngine(pred, max_batch_size=8, buckets=[0, 4])


def test_drain_unpauses(artifact):
    eng, _ = _engine(artifact)
    eng.pause()
    f = eng.infer([np.ones((1, 8), np.float32)])
    assert eng.drain(timeout=30)            # must not livelock
    assert f.result(timeout=0)[0].shape == (1, 4)
    eng.close()


def test_expired_slots_do_not_shed_live_traffic(artifact):
    """Deadline-lapsed requests stuck behind a long in-flight batch must
    be swept at admission instead of causing spurious QueueFull."""
    eng, _ = _engine(artifact, max_queue=2)
    try:
        gate = threading.Event()
        orig = eng._pred.run
        def slow_run(feeds):
            gate.wait(10)
            return orig(feeds)
        eng._pred.run = slow_run
        x = np.ones((1, 8), np.float32)
        f1 = eng.infer([x])                 # occupies the dispatcher
        time.sleep(0.1)                     # now blocked inside run()
        dead = [eng.infer([x], deadline_ms=1.0) for _ in range(2)]
        time.sleep(0.02)                    # both queued slots expired
        f4 = eng.infer([x])                 # swept at admission: admitted
        eng._pred.run = orig
        gate.set()
        assert f1.result(timeout=30)[0].shape == (1, 4)
        assert f4.result(timeout=30)[0].shape == (1, 4)
        for d in dead:
            with pytest.raises(serving.DeadlineExceeded):
                d.result(timeout=30)
    finally:
        gate.set()
        eng.close()


def test_graceful_drain_and_close(artifact):
    eng, _ = _engine(artifact)
    rng = np.random.RandomState(2)
    futs = [eng.infer([x]) for x in _dyadic_requests(rng, 16)]
    assert eng.drain(timeout=30)
    assert all(f.done() for f in futs)
    with pytest.raises(serving.EngineClosed):
        eng.infer([np.ones((1, 8), np.float32)])    # draining: no admission
    eng.close()
    assert eng.stats()["state"] == "closed"
    eng.close()                                     # idempotent


def test_close_never_strands_futures(artifact):
    eng, _ = _engine(artifact)
    eng.pause()
    x = np.ones((2, 8), np.float32)
    futs = [eng.infer([x]) for _ in range(6)]
    eng.close()         # close unpauses, flushes, then stops
    for f in futs:
        assert f.done()
        f.result(timeout=0)     # flushed batches resolved with results


def test_dispatch_fault_is_retried(artifact):
    eng, _ = _engine(artifact, dispatch_retries=2)
    try:
        with fault.inject("serving.dispatch:count=2"):
            out = eng.infer_sync([np.ones((1, 8), np.float32)],
                                 timeout=30)
        assert out[0].shape == (1, 4)
        assert eng.stats()["counters"]["dispatch_retries"] == 2
    finally:
        eng.close()


def test_dispatch_retries_exhausted_fails_cleanly(artifact):
    eng, _ = _engine(artifact, dispatch_retries=1)
    try:
        with fault.inject("serving.dispatch"):      # unlimited fires
            f = eng.infer([np.ones((1, 8), np.float32)])
            with pytest.raises(fault.FaultInjected):
                f.result(timeout=30)
        assert eng.stats()["counters"]["failed"] == 1
        # engine survives: next request serves normally
        assert eng.infer_sync([np.ones((1, 8), np.float32)],
                              timeout=30)[0].shape == (1, 4)
    finally:
        eng.close()


def test_enqueue_fault_propagates_to_caller(artifact):
    eng, _ = _engine(artifact)
    try:
        with fault.inject("serving.enqueue:count=1"):
            with pytest.raises(fault.FaultInjected):
                eng.infer([np.ones((1, 8), np.float32)])
        assert eng.infer_sync([np.ones((1, 8), np.float32)],
                              timeout=30)[0].shape == (1, 4)
    finally:
        eng.close()


def test_concurrent_clients_engine(artifact):
    eng, pred = _engine(artifact)
    try:
        rng = np.random.RandomState(3)
        reqs = _dyadic_requests(rng, 40)
        refs = [np.asarray(pred.run([x])[0]) for x in reqs]
        results = [None] * len(reqs)

        def client(idx):
            for i in range(idx, len(reqs), 8):
                results[i] = eng.infer_sync([reqs[i]], timeout=30)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for out, ref in zip(results, refs):
            np.testing.assert_array_equal(out[0], ref)
        st = eng.stats()
        assert st["counters"]["responses"] == 40
        assert st["recompiles_after_warmup"] == 0
    finally:
        eng.close()


def test_engine_stats_fields(artifact):
    eng, _ = _engine(artifact)
    try:
        eng.infer_sync([np.ones((3, 8), np.float32)], timeout=30)
        st = eng.stats()
        assert st["state"] == "running"
        assert st["buckets"] == [1, 2, 4, 8]
        assert st["counters"]["rows"] == 3
        assert st["counters"]["padded_rows"] == 1   # 3 -> bucket 4
        assert 0 < st["mean_batch_occupancy"] <= 1
        assert st["latency_ms"]["count"] >= 1
        assert st["latency_ms"]["p99"] >= st["latency_ms"]["p50"]
    finally:
        eng.close()


# --------------------------------------------------------------- http --
def test_http_concurrent_clients(artifact):
    eng, pred = _engine(artifact)
    srv = serving.ServingServer(eng, port=0).start()
    try:
        client = serving.Client(srv.url)
        assert client.healthz()["status"] == "running"
        rng = np.random.RandomState(4)
        reqs = _dyadic_requests(rng, 24)
        refs = [np.asarray(pred.run([x])[0]) for x in reqs]
        results = [None] * len(reqs)

        def worker(idx):
            for i in range(idx, len(reqs), 6):
                results[i] = client.predict(reqs[i])

        threads = [threading.Thread(target=worker, args=(c,))
                   for c in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for out, ref in zip(results, refs):
            assert out[0].dtype == np.float32
            np.testing.assert_array_equal(out[0], ref)

        m = client.metrics()
        assert m["counters"]["responses"] >= 24
        assert m["recompiles_after_warmup"] == 0
        assert {"p50", "p95", "p99"} <= set(m["latency_ms"])
    finally:
        srv.close()
        eng.close()


def test_http_npy_roundtrip(artifact):
    eng, pred = _engine(artifact)
    srv = serving.ServingServer(eng, port=0).start()
    try:
        client = serving.Client(srv.url)
        x = (np.arange(24).reshape(3, 8) / 4.0).astype(np.float32)
        out = client.predict_npy(x)
        np.testing.assert_array_equal(out, np.asarray(pred.run([x])[0]))
    finally:
        srv.close()
        eng.close()


def test_http_error_mapping(artifact):
    eng, _ = _engine(artifact, max_queue=1)
    srv = serving.ServingServer(eng, port=0).start()
    try:
        client = serving.Client(srv.url)
        with pytest.raises(serving.ServingError, match="400"):
            client.predict([np.ones((2, 8)), np.ones((2, 8))])  # 2 inputs
        eng.pause()
        # fill the 1-slot queue, then expect a shed mapped to QueueFull
        f = eng.infer([np.ones((1, 8), np.float32)])
        with pytest.raises(serving.QueueFull):
            client.predict(np.ones((1, 8), np.float32))
        eng.resume()
        f.result(timeout=30)
        # draining/closed healthz flips to 503 payload
        eng.drain(timeout=30)
        assert client.healthz()["status"] in ("draining", "closed")
    finally:
        srv.close()
        eng.close()


def test_http_registry_error_mapping(artifact):
    """ISSUE 19: routing errors get their own status codes — unknown
    model is a literal 404 mapped to UnknownModel, an exhausted tenant
    quota is a literal 429 (+ Retry-After) mapped to QuotaExceeded, and
    neither is confused with the 503 shed path."""
    import http.client
    import json

    eng, _ = _engine(artifact)
    reg = serving.ModelRegistry()
    reg.register("solo", engine=eng)
    reg.set_quota("capped", rate=0.001, burst=1)
    srv = serving.ServingServer(None, port=0, registry=reg).start()
    try:
        client = serving.Client(srv.url)
        x = np.ones((1, 8), np.float32)
        client.predict([x], model="solo")        # sanity: routes fine
        with pytest.raises(serving.UnknownModel):
            client.predict([x], model="nope")
        client.predict([x], model="solo", tenant="capped")  # burst spent
        with pytest.raises(serving.QuotaExceeded):
            client.predict([x], model="solo", tenant="capped")

        # literal status codes on the wire, not just client exceptions
        host, port = srv.url.split("//")[1].split(":")
        body = json.dumps({"inputs": [x.tolist()], "model": "nope"})
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        conn.request("POST", "/predict", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 404, resp.read()
        assert json.loads(resp.read())["error"] == "UnknownModel"
        body = json.dumps({"inputs": [x.tolist()], "model": "solo",
                           "tenant": "capped"})
        conn.request("POST", "/predict", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 429, resp.read()
        assert resp.getheader("Retry-After") is not None
        assert json.loads(resp.read())["error"] == "QuotaExceeded"
        conn.close()
    finally:
        srv.close()
        reg.close()


# -------------------------------------------- predictor pad-to-bucket --
def _save_plain(tmp_path, seed=0):
    paddle.seed(seed)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    prefix = os.path.join(str(tmp_path), "pad")
    jit.save(model, prefix, input_spec=[InputSpec([None, 4], "float32")])
    return model, prefix


def test_predictor_pads_to_pow2_bucket(tmp_path):
    model, prefix = _save_plain(tmp_path)
    pred = inference.create_predictor(inference.Config(prefix))
    monitor.stat_reset("inference.pad_hits")
    monitor.stat_reset("inference.compile_misses")
    model.eval()
    for n in (3, 5, 6, 3):
        x = np.random.RandomState(n).standard_normal(
            (n, 4)).astype(np.float32)
        got, = pred.run([x])
        assert np.asarray(got).shape == (n, 2)      # sliced back
        want = model(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3,
                                   atol=1e-5)
    # 3 -> compile 4; 5,6 -> compile 8; second 3 -> pad hit, no compile
    assert pred.num_compiled_variants() == 2
    assert monitor.get_stat("inference.compile_misses") == 2
    assert monitor.get_stat("inference.pad_hits") == 2  # 6->8 and 3->4


def test_predictor_pad_prefers_declared_bucket(tmp_path):
    _, prefix = _save_plain(tmp_path)
    config = inference.Config(prefix)
    config.add_shape_bucket((6, 4))
    pred = inference.create_predictor(config)
    n0 = pred.num_compiled_variants()
    got, = pred.run([np.ones((5, 4), np.float32)])
    # 5 fits the declared 6-bucket: served from it, not from pow2(5)=8
    assert pred.num_compiled_variants() == n0
    assert np.asarray(got).shape == (5, 2)


def test_predictor_pad_policy_none_restores_legacy(tmp_path):
    _, prefix = _save_plain(tmp_path)
    config = inference.Config(prefix)
    config.set_batch_pad_policy("none")
    pred = inference.create_predictor(config)
    n0 = pred.num_compiled_variants()
    for n in (3, 5, 6):
        pred.run([np.ones((n, 4), np.float32)])
    assert pred.num_compiled_variants() == n0 + 3   # one per size
    with pytest.raises(ValueError, match="pad policy"):
        config.set_batch_pad_policy("bogus")


def test_predictor_share_external_data_accepts_list(tmp_path):
    model, prefix = _save_plain(tmp_path)
    pred = inference.create_predictor(inference.Config(prefix))
    name = pred.get_input_names()[0]
    pred.get_input_handle(name).share_external_data(
        [[0.5, 1.0, -0.25, 2.0]])          # bare list, no .dtype
    out, = pred.run()
    assert np.asarray(out).shape == (1, 2)


def test_predictor_int64_bucket_aot_hits(tmp_path):
    """AOT bucket keys must canonicalize dtypes (i64->i32) exactly like
    run(), or int64 artifacts recompile on first serve."""
    paddle.seed(3)
    model = nn.Embedding(10, 4)
    prefix = os.path.join(str(tmp_path), "emb")
    jit.save(model, prefix, input_spec=[InputSpec([None], "int64")])
    config = inference.Config(prefix)
    config.add_shape_bucket((6,))
    pred = inference.create_predictor(config)
    n0 = pred.num_compiled_variants()
    assert n0 >= 1
    out, = pred.run([np.arange(6, dtype=np.int64)])
    assert pred.num_compiled_variants() == n0   # AOT variant hit
    assert np.asarray(out).shape == (6, 4)
    out, = pred.run([np.arange(5, dtype=np.int64)])
    assert pred.num_compiled_variants() == n0   # padded into the bucket
    assert np.asarray(out).shape == (5, 4)


def test_predictor_float64_input_canonicalized(tmp_path):
    """f64 feeds must land in the SAME f32 variant jnp.asarray produces
    (x64-disabled jax), not compile a phantom f64 signature."""
    _, prefix = _save_plain(tmp_path)
    pred = inference.create_predictor(inference.Config(prefix))
    pred.run([np.ones((4, 4), np.float32)])
    n0 = pred.num_compiled_variants()
    out, = pred.run([np.ones((4, 4), np.float64)])
    assert pred.num_compiled_variants() == n0
    assert np.asarray(out).shape == (4, 2)


def test_predictor_pad_flag_default():
    assert paddle.get_flags("inference_pad_policy")[
        "inference_pad_policy"] == "bucket"
    assert inference.Config().batch_pad_policy() == "bucket"


class _TwoHead(nn.Layer):
    """Batched output + a fixed [8, 3] output whose leading dim equals
    the pad bucket — the trap for shape-heuristic output slicing."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 2)

    def forward(self, x):
        return self.fc(x), paddle.ones([8, 3])


def _save_two_head(tmp_path):
    paddle.seed(1)
    model = _TwoHead()
    prefix = os.path.join(str(tmp_path), "twohead")
    jit.save(model, prefix, input_spec=[InputSpec([None, 4], "float32")])
    return prefix


def test_predictor_pad_does_not_slice_unbatched_output(tmp_path):
    prefix = _save_two_head(tmp_path)
    pred = inference.create_predictor(inference.Config(prefix))
    assert pred.batched_output_mask() == [True, False]
    outs = pred.run([np.ones((5, 4), np.float32)])  # pads 5 -> 8
    assert np.asarray(outs[0]).shape == (5, 2)      # sliced back
    assert np.asarray(outs[1]).shape == (8, 3)      # NOT mis-sliced
    np.testing.assert_array_equal(np.asarray(outs[1]), np.ones((8, 3)))


def test_engine_does_not_slice_unbatched_output(tmp_path):
    prefix = _save_two_head(tmp_path)
    pred = inference.create_predictor(inference.Config(prefix))
    eng = serving.InferenceEngine(pred, max_batch_size=8,
                                  batch_timeout_ms=5.0)
    try:
        eng.warmup()
        assert eng._out_mask == [True, False]
        futs = [eng.infer([np.ones((n, 4), np.float32)])
                for n in (3, 5)]                    # coalesce to 8 rows
        for n, f in zip((3, 5), futs):
            out = f.result(timeout=30)
            assert out[0].shape == (n, 2)
            assert out[1].shape == (8, 3)           # whole fixed output
    finally:
        eng.close()


# ------------------------------------------- self-healing rails ------
def test_close_deadline_with_wedged_dispatcher(artifact):
    """ISSUE 18 regression: a dispatcher wedged inside a faulted
    dispatch must not hold close(timeout=) past its budget — the wedged
    batch's futures fail in-band and nothing is stranded."""
    eng, _ = _engine(artifact)
    with fault.inject("serving.dispatch:action=sleep,secs=5,count=1"):
        f = eng.infer([np.ones((1, 8), np.float32)])
        time.sleep(0.2)             # dispatcher picks it up and wedges
        t0 = time.monotonic()
        eng.close(timeout=1.0)
        elapsed = time.monotonic() - t0
    assert elapsed < 4.0            # hard deadline, not the 5 s wedge
    assert f.done()
    with pytest.raises(serving.EngineClosed):
        f.result(timeout=0)
    assert eng.stats()["counters"]["closed_stranded"] == 1


def _sigterm_raises():
    """Install the serving CLI's SIGTERM semantics (raise to unwind);
    returns the handler to restore."""
    def handler(signum, frame):
        raise KeyboardInterrupt
    return signal.signal(signal.SIGTERM, handler)


def test_sigterm_during_inference_warmup(artifact):
    """SIGTERM landing inside warmup() (not just mid-stream): requests
    accepted before the signal still serve through the standard
    drain/close path — in-band, no stranded future."""
    pred = inference.create_predictor(inference.Config(artifact))
    eng = serving.InferenceEngine(pred, max_batch_size=8,
                                  batch_timeout_ms=5.0)
    orig_feeds = eng._bucket_feeds

    def feeds_then_sigterm(rest_shapes):
        it = orig_feeds(rest_shapes)
        yield next(it)              # first bucket compiles...
        os.kill(os.getpid(), signal.SIGTERM)    # ...then the signal
        yield from it

    eng._bucket_feeds = feeds_then_sigterm
    x = (np.ones((2, 8)) / 4.0).astype(np.float32)
    futs = [eng.infer([x]) for _ in range(4)]   # accepted pre-warmup
    prev = _sigterm_raises()
    try:
        with pytest.raises(KeyboardInterrupt):
            eng.warmup()
    finally:
        signal.signal(signal.SIGTERM, prev)
    eng._bucket_feeds = orig_feeds
    assert eng.drain(timeout=60)    # the serve.py shutdown sequence
    ref = np.asarray(pred.run([x])[0])
    for f in futs:
        assert f.done()
        np.testing.assert_array_equal(f.result(timeout=0)[0], ref)
    eng.close()
    assert eng.stats()["counters"]["closed_stranded"] == 0


def test_sigterm_during_generation_warmup():
    """The generation twin: sequences admitted before the signal finish
    (or fail in-band) and the page pool is fully reclaimed."""
    eng = serving.GenerationEngine(make_dyadic_lm(), num_slots=2,
                                   page_size=4, max_context=64)
    results = []

    def client(i):
        try:
            results.append(eng.generate_sync(
                [1, 2, 3, 4], timeout=120, max_new_tokens=4,
                temperature=0.7, seed=i))
        except serving.ServingError as e:
            results.append(e)       # in-band is acceptable; silence not

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(2)]
    for t in threads:
        t.start()
    orig_exec = eng._get_exec
    fired = []

    def exec_then_sigterm(kind, bucket):
        r = orig_exec(kind, bucket)
        if not fired and threading.current_thread() \
                is threading.main_thread():
            fired.append(1)         # only interrupt the warmup caller,
            os.kill(os.getpid(), signal.SIGTERM)    # not the scheduler
        return r

    eng._get_exec = exec_then_sigterm
    prev = _sigterm_raises()
    try:
        with pytest.raises(KeyboardInterrupt):
            eng.warmup()
    finally:
        signal.signal(signal.SIGTERM, prev)
    eng._get_exec = orig_exec
    for t in threads:
        t.join(120)
    assert eng.drain(timeout=60)
    eng.close()
    st = eng.stats()
    assert len(results) == 2
    for r in results:
        assert isinstance(r, (list, serving.ServingError))
    assert st["page_pool"]["in_use"] == 0
    assert st["counters"]["pages_allocated"] \
        == st["counters"]["pages_freed"]


def test_healthz_readiness_split(artifact):
    """Liveness vs readiness: a live-but-warming replica answers 503 +
    Retry-After (hold traffic, don't restart); mark_ready flips 200."""
    eng, _ = _engine(artifact)
    srv = serving.ServingServer(eng, port=0, ready=False).start()
    try:
        client = serving.Client(srv.url)
        h = client.healthz()
        assert h == {"status": "warming", "engine_state": "running",
                     "ready": False, "weights_version": 0}
        assert client._retry_after > 0      # Retry-After noted: it
        # floors the reconnect backoff during a restart window
        srv.mark_ready()
        assert client.healthz()["ready"] is True
        srv.mark_unready()          # drain window: down without dying
        assert client.healthz()["ready"] is False
        srv.mark_ready()
        assert client.healthz()["ready"] is True
    finally:
        srv.close()
        eng.close()


def test_client_rides_through_replica_restart(artifact):
    """Satellite b: connection-refused on an idempotent request retries
    on a fresh connection with backoff — a supervised restart window is
    a pause, not a hard failure — counted in client.reconnects."""
    eng, pred = _engine(artifact)
    srv = serving.ServingServer(eng, port=0).start()
    port = srv.port
    x = (np.ones((2, 8)) / 4.0).astype(np.float32)
    ref = np.asarray(pred.run([x])[0])
    srv.close()                         # the replica goes down
    # a fresh client: both initial attempts hit the refused port, the
    # jittered backoff (>= 0.5 s here) spans the restart, the final
    # attempt lands on the reborn replica
    client = serving.Client(srv.url)
    client.reconnect_backoff_s = 1.0
    box = {}

    def restart():
        time.sleep(0.1)                 # well inside the backoff window
        box["srv"] = serving.ServingServer(eng, port=port).start()

    t = threading.Thread(target=restart)
    t.start()
    try:
        out = client.predict([x])
        np.testing.assert_array_equal(out[0], ref)
        assert client.reconnects >= 1
        assert monitor.get_stat("client.reconnects") >= 1
    finally:
        t.join()
        box["srv"].close()
        eng.close()


# ------------------------------------------------- monitor histograms --
def test_stat_observe_and_quantile():
    monitor.stat_reset("t.lat")
    for v in [1.0] * 50 + [10.0] * 45 + [100.0] * 5:
        monitor.stat_observe("t.lat", v)
    # rank-linear interpolation: each estimate lands inside the bucket
    # owning its rank ([1, 1.334) / [10, 13.34) for 8-per-decade log
    # buckets), clamped to the exactly-tracked [min, max]
    assert 1.0 <= monitor.quantile("t.lat", 0.5) < 10.0 ** 0.125 + 1e-9
    assert 10.0 <= monitor.quantile("t.lat", 0.9) < 10.0 ** 1.125 + 1e-9
    assert 80.0 < monitor.quantile("t.lat", 0.99) <= 100.0
    s = monitor.histogram_summary("t.lat")
    assert s["count"] == 100
    assert s["min"] == 1.0 and s["max"] == 100.0
    assert abs(s["mean"] - (50 + 450 + 500) / 100.0) < 1e-9
    monitor.stat_reset("t.lat")
    assert monitor.histogram_summary("t.lat")["count"] == 0
    assert monitor.quantile("t.lat", 0.5) == 0.0


def test_histograms_do_not_disturb_counters():
    monitor.stat_reset()
    monitor.stat_add("c", 2)
    monitor.stat_observe("h", 3.0)
    assert monitor.all_stats() == {"c": 2}      # counters only
    assert "h" in monitor.all_histograms()
    monitor.stat_reset()
    assert monitor.all_histograms() == {}


def test_quantile_extremes_are_exact():
    monitor.stat_reset("t.q")
    for v in (0.5, 2.0, 7.0):
        monitor.stat_observe("t.q", v)
    assert monitor.quantile("t.q", 0.0) == 0.5
    assert monitor.quantile("t.q", 1.0) == 7.0


# ------------------------------------------------------- smoke gates --
def test_serve_smoke_in_process():
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import serve_smoke
        failures = serve_smoke.run_checks(requests=32, clients=4)
    finally:
        sys.path.pop(0)
    assert failures == [], failures


def test_serving_chaos_in_process():
    from paddle_tpu.testing import chaos
    assert chaos.serving_main(requests=24, clients=3) == 0


@pytest.mark.slow
def test_serve_smoke_hotswap_in_process():
    """Kept out of tier-1 for runtime; CI runs tools/serve_smoke.py."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import serve_smoke
        failures = serve_smoke.run_hotswap_checks()
    finally:
        sys.path.pop(0)
    assert failures == [], failures


@pytest.mark.slow
def test_swap_chaos_in_process(tmp_path):
    """Swap-under-fire part one: three live swaps + a corrupted
    snapshot under concurrent clients (the supervised-replica leg runs
    in tools/chaos_smoke.py, which spawns real child processes).  Kept
    out of tier-1 for runtime; CI runs chaos_smoke --scenario swap."""
    from paddle_tpu.testing import chaos
    assert chaos.swap_main(supervised=False, workdir=str(tmp_path)) == 0
