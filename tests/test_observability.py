"""Unified observability (ISSUE 5): structured tracer, recompile
attribution, Prometheus/JSON metrics export, crash flight recorder —
plus the satellite contracts (disabled-path overhead, histogram
quantile interpolation, RecordEvent robustness)."""
import json
import os
import re
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, observability as obs, optimizer, profiler
from paddle_tpu.core import dispatch, obs_hook
from paddle_tpu.testing import fault
from paddle_tpu.utils import monitor


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.uninstall_flight_recorder()
    yield
    obs.uninstall_flight_recorder()
    obs.disable()


def _static_mlp(seed=7, in_dim=8):
    paddle.seed(seed)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, in_dim], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        h = paddle.static.nn.fc(x, 16, activation="relu")
        pred = paddle.static.nn.fc(h, 1)
        loss = F.mse_loss(pred, y)
        optimizer.SGD(learning_rate=0.01).minimize(loss)
    return main, loss


def _feed(n, in_dim=8, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(n, in_dim).astype(np.float32),
            "y": rng.randn(n, 1).astype(np.float32)}


# ---------------------------------------------------------------- tracer --
def test_disabled_path_contract():
    """The tier-1 overhead contract: off means ONE module-attribute
    check, and the monitor hot paths never grew an observability hook."""
    assert obs_hook.current() is None
    assert not obs.enabled()
    # the hook read is a bare module-global load — nothing else
    assert obs_hook.current.__code__.co_names == ("_tracer",)
    # instrumented hot paths read obs_hook._tracer directly and never
    # import the observability package per call
    assert "obs_hook" in dispatch.apply.__code__.co_names
    assert "observability" not in dispatch.apply.__code__.co_names
    # stat_add / stat_observe hot paths are untouched (no tracer refs)
    for fn in (monitor.stat_add, monitor.stat_observe,
               monitor.StatRegistry.add, monitor.StatRegistry.observe,
               monitor._Histogram.observe):
        names = fn.__code__.co_names
        assert not any(n in ("obs_hook", "_tracer", "observability",
                             "tracer", "emit") for n in names), \
            f"{fn.__qualname__} grew an observability reference: {names}"
    # module-level helpers are no-ops while disabled
    obs.emit("instant", "nope")
    obs.counter("nope", 1)
    obs.set_step(3)
    with obs.span("nope"):
        pass


def test_tracer_records_ops_and_spans_with_nesting():
    t = obs.enable(capacity=256)
    x = paddle.to_tensor(np.ones((2, 3), np.float32))
    _ = (x * 2.0).sum()
    with profiler.RecordEvent("outer"):
        with profiler.RecordEvent("inner"):
            pass
    evs = t.events()
    kinds = {e["kind"] for e in evs}
    assert "op" in kinds and "span" in kinds
    spans = {e["name"]: e for e in evs if e["kind"] == "span"}
    assert spans["inner"]["parent"] == spans["outer"]["id"]
    assert spans["outer"].get("parent") is None
    ops = [e for e in evs if e["kind"] == "op"]
    assert all(e["dur"] >= 0 for e in ops)


def test_tracer_ring_buffer_bounds_memory():
    t = obs.enable(capacity=16)
    for i in range(100):
        t.emit("instant", f"e{i}")
    evs = t.events()
    assert len(evs) == 16
    assert evs[-1]["name"] == "e99"     # newest kept
    assert t.emitted == 100


def test_chrome_trace_schema_and_jsonl(tmp_path):
    t = obs.enable(capacity=256)
    with t.span("phase", detail=1):
        t.counter("c", 2)
        t.emit("instant", "marker")
    trace = t.chrome_trace()
    assert trace["traceEvents"]
    phs = set()
    for ev in trace["traceEvents"]:
        assert isinstance(ev["name"], str)
        assert ev["ph"] in {"X", "i", "C", "B", "E", "M"}
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert isinstance(ev["pid"], int)
        assert isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
        phs.add(ev["ph"])
    assert {"X", "i", "C"} <= phs
    p = tmp_path / "trace.json"
    t.export_chrome_trace(str(p))
    json.load(open(p))                          # parses
    jsonl = t.export_jsonl(str(tmp_path / "t.jsonl"))
    rows = [json.loads(ln) for ln in jsonl.splitlines()]
    assert rows and all("kind" in r and "time" in r for r in rows)


def test_step_correlation_from_executor():
    t = obs.enable()
    paddle.enable_static()
    try:
        main, loss = _static_mlp()
        exe = paddle.static.Executor()
        for _ in range(3):
            exe.run(main, feed=_feed(8), fetch_list=[loss])
        exe.close()
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()
    runs = [e for e in t.events()
            if e["kind"] == "span" and e["name"] == "executor.run"]
    assert [e["step"] for e in runs] == [1, 2, 3]


# ------------------------------------------------- RecordEvent satellite --
def test_record_event_end_without_begin_is_noop():
    r = profiler.RecordEvent("never")
    r.end()                     # was: TypeError on perf_counter() - None
    r.end()                     # idempotent too


def test_record_event_exception_safe_and_nested_under_tracer():
    t = obs.enable()
    with pytest.raises(ValueError):
        with profiler.RecordEvent("outer"):
            with profiler.RecordEvent("inner"):
                raise ValueError("boom")
    spans = {e["name"]: e for e in t.events() if e["kind"] == "span"}
    # both spans closed despite the raise, nesting preserved
    assert spans["inner"]["parent"] == spans["outer"]["id"]
    ev = profiler.RecordEvent("twice").begin()
    ev.end()
    ev.end()                    # second end is a no-op
    assert len([e for e in t.events() if e["name"] == "twice"]) == 1


# ------------------------------------------------- quantile satellite ----
def test_quantile_linear_interpolation_exact_at_bucket_edges():
    monitor.stat_reset("q.edge")
    # 4 samples in the [1, 10^(1/8)) bucket and 4 in [1000, 10^3.125)
    # (at 1200, so the max-clamp stays out of the way)
    for _ in range(4):
        monitor.stat_observe("q.edge", 1.0)
    for _ in range(4):
        monitor.stat_observe("q.edge", 1200.0)
    # rank at the lower bucket's LAST sample reads its upper edge exactly
    assert monitor.quantile("q.edge", 0.5) == pytest.approx(
        10.0 ** (1.0 / 8.0))
    # a rank just inside the upper bucket reads its lower edge (1000)
    assert monitor.quantile("q.edge", 0.5001) == pytest.approx(
        1000.0, rel=1e-3)
    # one sample deep into a 4-sample bucket: lo + (hi-lo)/4 by rank
    lo, hi = 1000.0, 10.0 ** 3.125
    assert monitor.quantile("q.edge", 5.0 / 8.0) == pytest.approx(
        lo + (hi - lo) * 0.25)
    monitor.stat_reset("q.edge")


def test_quantile_single_valued_bucket_is_exact():
    monitor.stat_reset("q.single")
    for _ in range(10):
        monitor.stat_observe("q.single", 3.7)
    # min==max clamp: every interior quantile is exactly the value
    for q in (0.1, 0.25, 0.5, 0.9, 0.99):
        assert monitor.quantile("q.single", q) == 3.7
    monitor.stat_reset("q.single")


def test_quantile_interpolates_by_rank_within_bucket():
    monitor.stat_reset("q.lin")
    # 8 samples in one bucket [10, 10^(9/8)): rank q*8 moves linearly
    # from lo to hi across the bucket
    for _ in range(8):
        monitor.stat_observe("q.lin", 10.5)
    lo, hi = 10.0, 10.0 ** (9.0 / 8.0)
    est = lo + (hi - lo) * 0.5
    # min/max clamp to the single observed value wins here
    assert monitor.quantile("q.lin", 0.5) == 10.5
    monitor.stat_reset("q.lin")
    # mixed values spread inside the same bucket: interpolation lands
    # between them, clamped within [vmin, vmax]
    for v in (10.1, 10.4, 10.8, 12.0):
        monitor.stat_observe("q.lin", v)
    q50 = monitor.quantile("q.lin", 0.5)
    assert 10.1 <= q50 <= 12.0
    assert q50 == pytest.approx(lo + (hi - lo) * (2.0 / 4.0))
    assert est  # silence linters: est documents the formula
    monitor.stat_reset("q.lin")


def test_quantile_extremes_and_empty_unchanged():
    monitor.stat_reset("q.ext")
    for v in (0.5, 2.0, 7.0):
        monitor.stat_observe("q.ext", v)
    assert monitor.quantile("q.ext", 0.0) == 0.5
    assert monitor.quantile("q.ext", 1.0) == 7.0
    monitor.stat_reset("q.ext")
    assert monitor.quantile("q.ext", 0.5) == 0.0


# ---------------------------------------------- recompile attribution ----
def test_executor_compile_attribution_causes():
    obs.reset_compiles()
    paddle.enable_static()
    try:
        main, loss = _static_mlp()
        exe = paddle.static.Executor()
        exe.run(main, feed=_feed(8), fetch_list=[loss])
        exe.run(main, feed=_feed(8, seed=1), fetch_list=[loss])  # cached
        exe.run(main, feed=_feed(4), fetch_list=[loss])
        # edit the program: another op bumps the version
        with paddle.static.program_guard(main):
            _ = paddle.static.nn.fc(main.feed_vars["x"], 4)
        exe.run(main, feed=_feed(4), fetch_list=[loss])
        exe.close()
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()
    rep = obs.explain_compiles("executor")
    causes = [r["cause"] for r in rep["records"]]
    assert causes == ["first_compile", "new_feed_signature",
                      "new_program_version"]
    assert rep["unexplained"] == 0
    # the diff names what changed, old -> new
    sig_change = rep["records"][1]["changed"]
    assert "feed_signature" in sig_change
    assert monitor.get_stat("compiles.executor.new_feed_signature") >= 1


def test_predictor_compile_attribution_new_bucket(tmp_path):
    from paddle_tpu import inference, jit
    from paddle_tpu.jit import InputSpec

    obs.reset_compiles()
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 2))
    prefix = str(tmp_path / "m")
    jit.save(model, prefix, input_spec=[InputSpec([None, 4], "float32")])
    pred = inference.create_predictor(inference.Config(prefix))
    for n in (1, 2, 3, 5):
        pred.run([np.zeros((n, 4), np.float32)])
    rep = obs.explain_compiles("predictor")
    causes = [r["cause"] for r in rep["records"]]
    assert causes[0] == "first_compile"
    assert set(causes[1:]) == {"new_bucket"}
    assert len(rep["records"]) == pred.num_compiled_variants()
    assert rep["unexplained"] == 0


def test_jit_compile_attribution():
    from paddle_tpu.jit import to_static

    obs.reset_compiles()

    @to_static
    def f(a, scale):
        return a * scale

    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    f(x, 2.0)
    f(x, 2.0)               # cache hit: no new record
    f(x, 3.0)               # new static-leaf value
    rep = obs.explain_compiles("jit")
    causes = [r["cause"] for r in rep["records"]]
    assert causes == ["first_compile", "new_input_structure"]
    assert rep["unexplained"] == 0


# ------------------------------------------------------ metrics export ---
PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+naif]+$")


def test_prometheus_text_parses_and_covers_registry():
    monitor.stat_reset()
    monitor.stat_add("obs.test.counter", 5)
    monitor.stat_observe("obs.test.lat", 2.5)
    text = obs.prometheus_text({"extra_gauge": 1.25})
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert PROM_LINE.match(line), f"bad exposition line: {line!r}"
    assert "paddle_tpu_obs_test_counter 5" in text
    assert 'paddle_tpu_obs_test_lat{quantile="0.5"} 2.5' in text
    assert "paddle_tpu_obs_test_lat_count 1" in text
    assert "paddle_tpu_extra_gauge 1.25" in text
    monitor.stat_reset()


def test_prometheus_name_collision_between_stat_and_histogram():
    monitor.stat_reset()
    monitor.stat_add("clash", 1)
    monitor.stat_observe("clash", 2.0)
    text = obs.prometheus_text()
    # the gauge renames rather than colliding with the summary family
    assert "paddle_tpu_clash_stat 1" in text
    assert "paddle_tpu_clash_count 1" in text
    monitor.stat_reset()


def test_metrics_snapshot_and_jsonl_dump(tmp_path):
    monitor.stat_add("snap.c", 2)
    snap = obs.metrics_snapshot()
    assert snap["stats"]["snap.c"] >= 2 and "histograms" in snap
    p = str(tmp_path / "metrics.jsonl")
    obs.dump_metrics(p, extra={"tag": "t1"})
    obs.dump_metrics(p, extra={"tag": "t2"})
    rows = [json.loads(ln) for ln in open(p).read().splitlines()]
    assert [r["tag"] for r in rows] == ["t1", "t2"]
    assert all("stats" in r for r in rows)
    with pytest.raises(ValueError):
        obs.dump_metrics()      # no path, no flag


def test_metrics_dump_callback(tmp_path):
    from paddle_tpu.hapi.callbacks import MetricsDump
    p = str(tmp_path / "fit_metrics.jsonl")
    cb = MetricsDump(path=p, save_freq=2)
    cb.on_epoch_end(0)
    cb.on_epoch_end(1)          # (1+1) % 2 == 0 -> dumps
    cb.on_train_end()
    rows = [json.loads(ln) for ln in open(p).read().splitlines()]
    assert [r["tag"] for r in rows] == ["epoch_end", "train_end"]
    assert rows[0]["epoch"] == 1


def test_http_metrics_content_negotiation(tmp_path):
    from paddle_tpu import inference, jit, serving
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.serving.http import Client, ServingServer

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 2))
    prefix = str(tmp_path / "m")
    jit.save(model, prefix, input_spec=[InputSpec([None, 4], "float32")])
    pred = inference.create_predictor(inference.Config(prefix))
    engine = serving.InferenceEngine(pred, max_batch_size=4,
                                     batch_timeout_ms=1.0)
    engine.warmup()
    engine.infer_sync([np.zeros((1, 4), np.float32)], timeout=30)
    with ServingServer(engine, port=0) as srv:
        client = Client(srv.url)
        js = client.metrics()           # default stays JSON
        assert js["counters"]["responses"] >= 1
        text = client.metrics_text()    # Accept: text/plain -> Prometheus
        assert text.startswith("# TYPE")
        assert "paddle_tpu_serving_engine_queue_depth" in text
        for line in text.splitlines():
            if line and not line.startswith("#"):
                assert PROM_LINE.match(line), line
    engine.close()


# --------------------------------------------------- flight recorder -----
def test_flight_recorder_on_executor_crash(tmp_path):
    t = obs.enable()
    flight = str(tmp_path / "flight.json")
    obs.install_flight_recorder(path=flight)
    paddle.enable_static()
    try:
        main, loss = _static_mlp()
        exe = paddle.static.Executor()
        exe.run(main, feed=_feed(8), fetch_list=[loss])
        with fault.inject("executor.run:count=1"):
            with pytest.raises(fault.FaultInjected):
                exe.run(main, feed=_feed(8), fetch_list=[loss])
        exe.close()
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()
    box = json.load(open(flight))
    assert box["exception"]["type"] == "FaultInjected"
    assert "executor.run" in box["reason"]
    kinds = {e["kind"] for e in box["events"]}
    assert "fault" in kinds             # the injected fault is on tape
    assert "compile" in kinds
    assert box["stats"] and "histograms" in box
    assert box["compiles"]["total"] >= 1
    assert t.events()                   # tracer survived the dump


def test_flight_recorder_on_enforce_error(tmp_path):
    from paddle_tpu.core.enforce import InvalidArgumentError, enforce
    flight = str(tmp_path / "flight.json")
    obs.install_flight_recorder(path=flight)
    with pytest.raises(InvalidArgumentError):
        enforce(False, "observability test failure")
    box = json.load(open(flight))
    assert box["reason"].startswith("enforce.")
    assert box["exception"]["type"] == "InvalidArgumentError"
    assert "observability test failure" in box["exception"]["message"]


def test_flight_recorder_same_exception_dumps_once(tmp_path):
    flight = str(tmp_path / "flight.json")
    obs.install_flight_recorder(path=flight)
    monitor.stat_reset("flight.dumps")
    from paddle_tpu.core.enforce import InvalidArgumentError
    paddle.enable_static()
    try:
        main, loss = _static_mlp()
        exe = paddle.static.Executor()
        with fault.inject(
                "executor.run:count=1,exc=FaultInjected"):
            with pytest.raises(fault.FaultInjected):
                exe.run(main, feed=_feed(8), fetch_list=[loss])
        exe.close()
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()
    assert monitor.get_stat("flight.dumps") == 1
    assert InvalidArgumentError  # imported for taxonomy visibility


def test_flight_recorder_distinct_exceptions_each_dump(tmp_path):
    # dedup must be per live OBJECT: a freed exception's recycled id
    # must not swallow dumps for later, distinct errors
    from paddle_tpu.core.enforce import InvalidArgumentError
    flight = str(tmp_path / "flight.json")
    obs.install_flight_recorder(path=flight)
    monitor.stat_reset("flight.dumps")
    for i in range(5):
        InvalidArgumentError(f"err {i}")     # constructed, then freed
    assert monitor.get_stat("flight.dumps") == 5
    box = json.load(open(flight))
    assert "err 4" in box["exception"]["message"]   # the LATEST error


def test_flight_recorder_traceback_upgrades_dump(tmp_path):
    # EnforceError dumps at construction (no stack yet); the re-report
    # from the raise boundary carries the traceback and must overwrite
    from paddle_tpu.core.enforce import NotFoundError
    flight = str(tmp_path / "flight.json")
    obs.install_flight_recorder(path=flight)
    monitor.stat_reset("flight.dumps")

    def deep():
        raise NotFoundError("lost thing")

    try:
        deep()
    except NotFoundError as e:
        obs_hook.crash_handler()(e, "executor.run(test)")
        # a third report of the same traceback'd object stays deduped
        obs_hook.crash_handler()(e, "executor.run(test)")
    assert monitor.get_stat("flight.dumps") == 2
    box = json.load(open(flight))
    tb = "".join(box["exception"]["traceback"])
    assert "deep" in tb                     # stack frames present


def test_end_span_with_foreign_id_does_not_drain_stack():
    t = obs.enable()
    outer = t.begin_span("outer")
    inner = t.begin_span("inner")
    t.end_span(inner)
    t.end_span(inner)       # double end: ignored
    t.end_span(99999)       # never-begun id: ignored
    assert not [e for e in t.events() if e["name"] == "outer"]
    t.end_span(outer)
    spans = {e["name"]: e for e in t.events() if e["kind"] == "span"}
    assert spans["inner"]["parent"] == spans["outer"]["id"]
    assert len([e for e in t.events() if e["name"] == "inner"]) == 1


def test_flight_recorder_uninstall_restores_hooks(tmp_path):
    prev_hook = sys.excepthook
    obs.install_flight_recorder(path=str(tmp_path / "f.json"))
    assert sys.excepthook is not prev_hook
    assert obs_hook.crash_handler() is not None
    assert obs.flight_recorder_path() == str(tmp_path / "f.json")
    obs.uninstall_flight_recorder()
    assert sys.excepthook is prev_hook
    assert obs_hook.crash_handler() is None
    assert obs.flight_recorder_path() is None


def test_manual_dump_flight(tmp_path):
    obs.enable()
    obs.emit("instant", "before_dump")
    p = str(tmp_path / "manual.json")
    out = obs.dump_flight(path=p, reason="manual-test")
    assert out == p
    box = json.load(open(p))
    assert box["reason"] == "manual-test"
    assert box["exception"] is None
    assert any(e["name"] == "before_dump" for e in box["events"])


# ----------------------------------------------------- serving events ----
def test_serving_events_carry_request_ids(tmp_path):
    from paddle_tpu import inference, jit, serving
    from paddle_tpu.jit import InputSpec

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 2))
    prefix = str(tmp_path / "m")
    jit.save(model, prefix, input_spec=[InputSpec([None, 4], "float32")])
    pred = inference.create_predictor(inference.Config(prefix))
    engine = serving.InferenceEngine(pred, max_batch_size=4,
                                     batch_timeout_ms=1.0)
    engine.warmup()
    t = obs.enable()
    engine.infer_sync([np.zeros((2, 4), np.float32)], timeout=30)
    engine.drain(timeout=10)
    engine.close()
    sv = [e for e in t.events() if e["kind"] == "serving"]
    enq = [e for e in sv if e["name"] == "enqueue"]
    disp = [e for e in sv if e["name"] == "dispatch"]
    assert enq and disp
    rid = enq[0]["args"]["rid"]
    assert rid in disp[0]["args"]["rids"]       # request correlation
    assert disp[0]["args"]["ok"] is True
    assert disp[0]["dur"] >= 0


# ------------------------------------------------------------ CI gate ----
def test_obs_smoke_in_process():
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import obs_smoke
    failures = obs_smoke.run_checks()
    assert failures == []
