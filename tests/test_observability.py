"""Unified observability (ISSUE 5): structured tracer, recompile
attribution, Prometheus/JSON metrics export, crash flight recorder —
plus the satellite contracts (disabled-path overhead, histogram
quantile interpolation, RecordEvent robustness)."""
import json
import os
import re
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, observability as obs, optimizer, profiler
from paddle_tpu.core import dispatch, obs_hook
from paddle_tpu.testing import fault
from paddle_tpu.utils import monitor


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.uninstall_flight_recorder()
    obs.disable_perf()
    obs.uninstall_slo_monitor()
    yield
    obs.uninstall_flight_recorder()
    obs.uninstall_slo_monitor()
    obs.disable_perf()
    obs.disable()


def _static_mlp(seed=7, in_dim=8):
    paddle.seed(seed)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, in_dim], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        h = paddle.static.nn.fc(x, 16, activation="relu")
        pred = paddle.static.nn.fc(h, 1)
        loss = F.mse_loss(pred, y)
        optimizer.SGD(learning_rate=0.01).minimize(loss)
    return main, loss


def _feed(n, in_dim=8, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(n, in_dim).astype(np.float32),
            "y": rng.randn(n, 1).astype(np.float32)}


# ---------------------------------------------------------------- tracer --
def test_disabled_path_contract():
    """The tier-1 overhead contract: off means ONE module-attribute
    check, and the monitor hot paths never grew an observability hook."""
    assert obs_hook.current() is None
    assert not obs.enabled()
    # the hook read is a bare module-global load — nothing else
    assert obs_hook.current.__code__.co_names == ("_tracer",)
    # instrumented hot paths read obs_hook._tracer directly and never
    # import the observability package per call
    assert "obs_hook" in dispatch.apply.__code__.co_names
    assert "observability" not in dispatch.apply.__code__.co_names
    # stat_add / stat_observe hot paths are untouched (no tracer refs)
    for fn in (monitor.stat_add, monitor.stat_observe,
               monitor.StatRegistry.add, monitor.StatRegistry.observe,
               monitor._Histogram.observe):
        names = fn.__code__.co_names
        assert not any(n in ("obs_hook", "_tracer", "observability",
                             "tracer", "emit") for n in names), \
            f"{fn.__qualname__} grew an observability reference: {names}"
    # module-level helpers are no-ops while disabled
    obs.emit("instant", "nope")
    obs.counter("nope", 1)
    obs.set_step(3)
    with obs.span("nope"):
        pass


def test_tracer_records_ops_and_spans_with_nesting():
    t = obs.enable(capacity=256)
    x = paddle.to_tensor(np.ones((2, 3), np.float32))
    _ = (x * 2.0).sum()
    with profiler.RecordEvent("outer"):
        with profiler.RecordEvent("inner"):
            pass
    evs = t.events()
    kinds = {e["kind"] for e in evs}
    assert "op" in kinds and "span" in kinds
    spans = {e["name"]: e for e in evs if e["kind"] == "span"}
    assert spans["inner"]["parent"] == spans["outer"]["id"]
    assert spans["outer"].get("parent") is None
    ops = [e for e in evs if e["kind"] == "op"]
    assert all(e["dur"] >= 0 for e in ops)


def test_tracer_ring_buffer_bounds_memory():
    t = obs.enable(capacity=16)
    for i in range(100):
        t.emit("instant", f"e{i}")
    evs = t.events()
    assert len(evs) == 16
    assert evs[-1]["name"] == "e99"     # newest kept
    assert t.emitted == 100


def test_chrome_trace_schema_and_jsonl(tmp_path):
    t = obs.enable(capacity=256)
    with t.span("phase", detail=1):
        t.counter("c", 2)
        t.emit("instant", "marker")
    trace = t.chrome_trace()
    assert trace["traceEvents"]
    phs = set()
    for ev in trace["traceEvents"]:
        assert isinstance(ev["name"], str)
        assert ev["ph"] in {"X", "i", "C", "B", "E", "M"}
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert isinstance(ev["pid"], int)
        assert isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
        phs.add(ev["ph"])
    assert {"X", "i", "C"} <= phs
    p = tmp_path / "trace.json"
    t.export_chrome_trace(str(p))
    json.load(open(p))                          # parses
    jsonl = t.export_jsonl(str(tmp_path / "t.jsonl"))
    rows = [json.loads(ln) for ln in jsonl.splitlines()]
    assert rows and all("kind" in r and "time" in r for r in rows)


def test_step_correlation_from_executor():
    t = obs.enable()
    paddle.enable_static()
    try:
        main, loss = _static_mlp()
        exe = paddle.static.Executor()
        for _ in range(3):
            exe.run(main, feed=_feed(8), fetch_list=[loss])
        exe.close()
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()
    runs = [e for e in t.events()
            if e["kind"] == "span" and e["name"] == "executor.run"]
    assert [e["step"] for e in runs] == [1, 2, 3]


# ------------------------------------------------- RecordEvent satellite --
def test_record_event_end_without_begin_is_noop():
    r = profiler.RecordEvent("never")
    r.end()                     # was: TypeError on perf_counter() - None
    r.end()                     # idempotent too


def test_record_event_exception_safe_and_nested_under_tracer():
    t = obs.enable()
    with pytest.raises(ValueError):
        with profiler.RecordEvent("outer"):
            with profiler.RecordEvent("inner"):
                raise ValueError("boom")
    spans = {e["name"]: e for e in t.events() if e["kind"] == "span"}
    # both spans closed despite the raise, nesting preserved
    assert spans["inner"]["parent"] == spans["outer"]["id"]
    ev = profiler.RecordEvent("twice").begin()
    ev.end()
    ev.end()                    # second end is a no-op
    assert len([e for e in t.events() if e["name"] == "twice"]) == 1


# ------------------------------------------------- quantile satellite ----
def test_quantile_linear_interpolation_exact_at_bucket_edges():
    monitor.stat_reset("q.edge")
    # 4 samples in the [1, 10^(1/8)) bucket and 4 in [1000, 10^3.125)
    # (at 1200, so the max-clamp stays out of the way)
    for _ in range(4):
        monitor.stat_observe("q.edge", 1.0)
    for _ in range(4):
        monitor.stat_observe("q.edge", 1200.0)
    # rank at the lower bucket's LAST sample reads its upper edge exactly
    assert monitor.quantile("q.edge", 0.5) == pytest.approx(
        10.0 ** (1.0 / 8.0))
    # a rank just inside the upper bucket reads its lower edge (1000)
    assert monitor.quantile("q.edge", 0.5001) == pytest.approx(
        1000.0, rel=1e-3)
    # one sample deep into a 4-sample bucket: lo + (hi-lo)/4 by rank
    lo, hi = 1000.0, 10.0 ** 3.125
    assert monitor.quantile("q.edge", 5.0 / 8.0) == pytest.approx(
        lo + (hi - lo) * 0.25)
    monitor.stat_reset("q.edge")


def test_quantile_single_valued_bucket_is_exact():
    monitor.stat_reset("q.single")
    for _ in range(10):
        monitor.stat_observe("q.single", 3.7)
    # min==max clamp: every interior quantile is exactly the value
    for q in (0.1, 0.25, 0.5, 0.9, 0.99):
        assert monitor.quantile("q.single", q) == 3.7
    monitor.stat_reset("q.single")


def test_quantile_interpolates_by_rank_within_bucket():
    monitor.stat_reset("q.lin")
    # 8 samples in one bucket [10, 10^(9/8)): rank q*8 moves linearly
    # from lo to hi across the bucket
    for _ in range(8):
        monitor.stat_observe("q.lin", 10.5)
    lo, hi = 10.0, 10.0 ** (9.0 / 8.0)
    est = lo + (hi - lo) * 0.5
    # min/max clamp to the single observed value wins here
    assert monitor.quantile("q.lin", 0.5) == 10.5
    monitor.stat_reset("q.lin")
    # mixed values spread inside the same bucket: interpolation lands
    # between them, clamped within [vmin, vmax]
    for v in (10.1, 10.4, 10.8, 12.0):
        monitor.stat_observe("q.lin", v)
    q50 = monitor.quantile("q.lin", 0.5)
    assert 10.1 <= q50 <= 12.0
    assert q50 == pytest.approx(lo + (hi - lo) * (2.0 / 4.0))
    assert est  # silence linters: est documents the formula
    monitor.stat_reset("q.lin")


def test_quantile_extremes_and_empty_unchanged():
    monitor.stat_reset("q.ext")
    for v in (0.5, 2.0, 7.0):
        monitor.stat_observe("q.ext", v)
    assert monitor.quantile("q.ext", 0.0) == 0.5
    assert monitor.quantile("q.ext", 1.0) == 7.0
    monitor.stat_reset("q.ext")
    assert monitor.quantile("q.ext", 0.5) == 0.0


# ---------------------------------------------- recompile attribution ----
def test_executor_compile_attribution_causes():
    obs.reset_compiles()
    paddle.enable_static()
    try:
        main, loss = _static_mlp()
        exe = paddle.static.Executor()
        exe.run(main, feed=_feed(8), fetch_list=[loss])
        exe.run(main, feed=_feed(8, seed=1), fetch_list=[loss])  # cached
        exe.run(main, feed=_feed(4), fetch_list=[loss])
        # edit the program: another op bumps the version
        with paddle.static.program_guard(main):
            _ = paddle.static.nn.fc(main.feed_vars["x"], 4)
        exe.run(main, feed=_feed(4), fetch_list=[loss])
        exe.close()
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()
    rep = obs.explain_compiles("executor")
    causes = [r["cause"] for r in rep["records"]]
    assert causes == ["first_compile", "new_feed_signature",
                      "new_program_version"]
    assert rep["unexplained"] == 0
    # the diff names what changed, old -> new
    sig_change = rep["records"][1]["changed"]
    assert "feed_signature" in sig_change
    assert monitor.get_stat("compiles.executor.new_feed_signature") >= 1


def test_predictor_compile_attribution_new_bucket(tmp_path):
    from paddle_tpu import inference, jit
    from paddle_tpu.jit import InputSpec

    obs.reset_compiles()
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 2))
    prefix = str(tmp_path / "m")
    jit.save(model, prefix, input_spec=[InputSpec([None, 4], "float32")])
    pred = inference.create_predictor(inference.Config(prefix))
    for n in (1, 2, 3, 5):
        pred.run([np.zeros((n, 4), np.float32)])
    rep = obs.explain_compiles("predictor")
    causes = [r["cause"] for r in rep["records"]]
    assert causes[0] == "first_compile"
    assert set(causes[1:]) == {"new_bucket"}
    assert len(rep["records"]) == pred.num_compiled_variants()
    assert rep["unexplained"] == 0


def test_jit_compile_attribution():
    from paddle_tpu.jit import to_static

    obs.reset_compiles()

    @to_static
    def f(a, scale):
        return a * scale

    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    f(x, 2.0)
    f(x, 2.0)               # cache hit: no new record
    f(x, 3.0)               # new static-leaf value
    rep = obs.explain_compiles("jit")
    causes = [r["cause"] for r in rep["records"]]
    assert causes == ["first_compile", "new_input_structure"]
    assert rep["unexplained"] == 0


# ------------------------------------------------------ metrics export ---
PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+naif]+$")


def test_prometheus_text_parses_and_covers_registry():
    monitor.stat_reset()
    monitor.stat_add("obs.test.counter", 5)
    monitor.stat_observe("obs.test.lat", 2.5)
    text = obs.prometheus_text({"extra_gauge": 1.25})
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert PROM_LINE.match(line), f"bad exposition line: {line!r}"
    assert "paddle_tpu_obs_test_counter 5" in text
    assert 'paddle_tpu_obs_test_lat{quantile="0.5"} 2.5' in text
    assert "paddle_tpu_obs_test_lat_count 1" in text
    assert "paddle_tpu_extra_gauge 1.25" in text
    monitor.stat_reset()


def test_prometheus_name_collision_between_stat_and_histogram():
    monitor.stat_reset()
    monitor.stat_add("clash", 1)
    monitor.stat_observe("clash", 2.0)
    text = obs.prometheus_text()
    # the gauge renames rather than colliding with the summary family
    assert "paddle_tpu_clash_stat 1" in text
    assert "paddle_tpu_clash_count 1" in text
    monitor.stat_reset()


def test_metrics_snapshot_and_jsonl_dump(tmp_path):
    monitor.stat_add("snap.c", 2)
    snap = obs.metrics_snapshot()
    assert snap["stats"]["snap.c"] >= 2 and "histograms" in snap
    p = str(tmp_path / "metrics.jsonl")
    obs.dump_metrics(p, extra={"tag": "t1"})
    obs.dump_metrics(p, extra={"tag": "t2"})
    rows = [json.loads(ln) for ln in open(p).read().splitlines()]
    assert [r["tag"] for r in rows] == ["t1", "t2"]
    assert all("stats" in r for r in rows)
    with pytest.raises(ValueError):
        obs.dump_metrics()      # no path, no flag


def test_metrics_dump_rotation_bounds_file_growth(tmp_path):
    """Satellite (ISSUE 20): a long-lived replica's JSONL flight file
    rotates at FLAGS_metrics_dump_max_mb into .1..N, never one
    unbounded file — and the live file is the rename's LAST step."""
    old = paddle.get_flags(["metrics_dump_max_mb", "metrics_dump_keep"])
    # threshold of ~100 bytes: every dump line (several KB) trips it
    paddle.set_flags({"metrics_dump_max_mb": 1e-4,
                      "metrics_dump_keep": 2})
    p = str(tmp_path / "metrics.jsonl")
    try:
        for _ in range(4):
            obs.dump_metrics(p)
        assert os.path.exists(p)
        assert os.path.exists(p + ".1") and os.path.exists(p + ".2")
        assert not os.path.exists(p + ".3")     # keep=2 drops the rest
        # every generation is intact JSONL, one snapshot per line
        for path in (p, p + ".1", p + ".2"):
            rows = [json.loads(ln)
                    for ln in open(path).read().splitlines()]
            assert rows and all("stats" in r for r in rows)
        # the live file holds only the newest line
        assert len(open(p).read().splitlines()) == 1
    finally:
        paddle.set_flags(old)


def test_metrics_dump_no_rotation_when_flag_unset(tmp_path):
    p = str(tmp_path / "metrics.jsonl")
    for _ in range(3):
        obs.dump_metrics(p)
    assert len(open(p).read().splitlines()) == 3
    assert not os.path.exists(p + ".1")


def test_build_info_gauge_in_snapshot_and_prometheus():
    """Satellite (ISSUE 20): every process exports its version/backend
    identity — the fleet aggregator diffs it across replicas."""
    info = obs.build_info()
    assert info["jax"] and info["jaxlib"] and info["framework"]
    assert info["backend"] == "cpu"
    assert obs.metrics_snapshot()["build"] == info
    text = obs.prometheus_text()
    line = next(ln for ln in text.splitlines()
                if ln.startswith("paddle_tpu_build_info{"))
    assert PROM_LINE.match(line) and line.endswith(" 1")
    assert f'jax="{info["jax"]}"' in line
    assert f'backend="{info["backend"]}"' in line


def test_metrics_dump_callback(tmp_path):
    from paddle_tpu.hapi.callbacks import MetricsDump
    p = str(tmp_path / "fit_metrics.jsonl")
    cb = MetricsDump(path=p, save_freq=2)
    cb.on_epoch_end(0)
    cb.on_epoch_end(1)          # (1+1) % 2 == 0 -> dumps
    cb.on_train_end()
    rows = [json.loads(ln) for ln in open(p).read().splitlines()]
    assert [r["tag"] for r in rows] == ["epoch_end", "train_end"]
    assert rows[0]["epoch"] == 1


def test_http_metrics_content_negotiation(tmp_path):
    from paddle_tpu import inference, jit, serving
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.serving.http import Client, ServingServer

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 2))
    prefix = str(tmp_path / "m")
    jit.save(model, prefix, input_spec=[InputSpec([None, 4], "float32")])
    pred = inference.create_predictor(inference.Config(prefix))
    engine = serving.InferenceEngine(pred, max_batch_size=4,
                                     batch_timeout_ms=1.0)
    engine.warmup()
    engine.infer_sync([np.zeros((1, 4), np.float32)], timeout=30)
    with ServingServer(engine, port=0) as srv:
        client = Client(srv.url)
        js = client.metrics()           # default stays JSON
        assert js["counters"]["responses"] >= 1
        text = client.metrics_text()    # Accept: text/plain -> Prometheus
        assert text.startswith("# TYPE")
        assert "paddle_tpu_serving_engine_queue_depth" in text
        for line in text.splitlines():
            if line and not line.startswith("#"):
                assert PROM_LINE.match(line), line
    engine.close()


# --------------------------------------------------- flight recorder -----
def test_flight_recorder_on_executor_crash(tmp_path):
    t = obs.enable()
    flight = str(tmp_path / "flight.json")
    obs.install_flight_recorder(path=flight)
    paddle.enable_static()
    try:
        main, loss = _static_mlp()
        exe = paddle.static.Executor()
        exe.run(main, feed=_feed(8), fetch_list=[loss])
        with fault.inject("executor.run:count=1"):
            with pytest.raises(fault.FaultInjected):
                exe.run(main, feed=_feed(8), fetch_list=[loss])
        exe.close()
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()
    box = json.load(open(flight))
    assert box["exception"]["type"] == "FaultInjected"
    assert "executor.run" in box["reason"]
    kinds = {e["kind"] for e in box["events"]}
    assert "fault" in kinds             # the injected fault is on tape
    assert "compile" in kinds
    assert box["stats"] and "histograms" in box
    assert box["compiles"]["total"] >= 1
    assert t.events()                   # tracer survived the dump


def test_flight_recorder_on_enforce_error(tmp_path):
    from paddle_tpu.core.enforce import InvalidArgumentError, enforce
    flight = str(tmp_path / "flight.json")
    obs.install_flight_recorder(path=flight)
    with pytest.raises(InvalidArgumentError):
        enforce(False, "observability test failure")
    box = json.load(open(flight))
    assert box["reason"].startswith("enforce.")
    assert box["exception"]["type"] == "InvalidArgumentError"
    assert "observability test failure" in box["exception"]["message"]


def test_flight_recorder_same_exception_dumps_once(tmp_path):
    flight = str(tmp_path / "flight.json")
    obs.install_flight_recorder(path=flight)
    monitor.stat_reset("flight.dumps")
    from paddle_tpu.core.enforce import InvalidArgumentError
    paddle.enable_static()
    try:
        main, loss = _static_mlp()
        exe = paddle.static.Executor()
        with fault.inject(
                "executor.run:count=1,exc=FaultInjected"):
            with pytest.raises(fault.FaultInjected):
                exe.run(main, feed=_feed(8), fetch_list=[loss])
        exe.close()
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()
    assert monitor.get_stat("flight.dumps") == 1
    assert InvalidArgumentError  # imported for taxonomy visibility


def test_flight_recorder_distinct_exceptions_each_dump(tmp_path):
    # dedup must be per live OBJECT: a freed exception's recycled id
    # must not swallow dumps for later, distinct errors
    from paddle_tpu.core.enforce import InvalidArgumentError
    flight = str(tmp_path / "flight.json")
    obs.install_flight_recorder(path=flight)
    monitor.stat_reset("flight.dumps")
    for i in range(5):
        InvalidArgumentError(f"err {i}")     # constructed, then freed
    assert monitor.get_stat("flight.dumps") == 5
    box = json.load(open(flight))
    assert "err 4" in box["exception"]["message"]   # the LATEST error


def test_flight_recorder_traceback_upgrades_dump(tmp_path):
    # EnforceError dumps at construction (no stack yet); the re-report
    # from the raise boundary carries the traceback and must overwrite
    from paddle_tpu.core.enforce import NotFoundError
    flight = str(tmp_path / "flight.json")
    obs.install_flight_recorder(path=flight)
    monitor.stat_reset("flight.dumps")

    def deep():
        raise NotFoundError("lost thing")

    try:
        deep()
    except NotFoundError as e:
        obs_hook.crash_handler()(e, "executor.run(test)")
        # a third report of the same traceback'd object stays deduped
        obs_hook.crash_handler()(e, "executor.run(test)")
    assert monitor.get_stat("flight.dumps") == 2
    box = json.load(open(flight))
    tb = "".join(box["exception"]["traceback"])
    assert "deep" in tb                     # stack frames present


def test_end_span_with_foreign_id_does_not_drain_stack():
    t = obs.enable()
    outer = t.begin_span("outer")
    inner = t.begin_span("inner")
    t.end_span(inner)
    t.end_span(inner)       # double end: ignored
    t.end_span(99999)       # never-begun id: ignored
    assert not [e for e in t.events() if e["name"] == "outer"]
    t.end_span(outer)
    spans = {e["name"]: e for e in t.events() if e["kind"] == "span"}
    assert spans["inner"]["parent"] == spans["outer"]["id"]
    assert len([e for e in t.events() if e["name"] == "inner"]) == 1


def test_flight_recorder_uninstall_restores_hooks(tmp_path):
    prev_hook = sys.excepthook
    obs.install_flight_recorder(path=str(tmp_path / "f.json"))
    assert sys.excepthook is not prev_hook
    assert obs_hook.crash_handler() is not None
    assert obs.flight_recorder_path() == str(tmp_path / "f.json")
    obs.uninstall_flight_recorder()
    assert sys.excepthook is prev_hook
    assert obs_hook.crash_handler() is None
    assert obs.flight_recorder_path() is None


def test_manual_dump_flight(tmp_path):
    obs.enable()
    obs.emit("instant", "before_dump")
    p = str(tmp_path / "manual.json")
    out = obs.dump_flight(path=p, reason="manual-test")
    assert out == p
    box = json.load(open(p))
    assert box["reason"] == "manual-test"
    assert box["exception"] is None
    assert any(e["name"] == "before_dump" for e in box["events"])


# ----------------------------------------------------- serving events ----
def test_serving_events_carry_request_ids(tmp_path):
    from paddle_tpu import inference, jit, serving
    from paddle_tpu.jit import InputSpec

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 2))
    prefix = str(tmp_path / "m")
    jit.save(model, prefix, input_spec=[InputSpec([None, 4], "float32")])
    pred = inference.create_predictor(inference.Config(prefix))
    engine = serving.InferenceEngine(pred, max_batch_size=4,
                                     batch_timeout_ms=1.0)
    engine.warmup()
    t = obs.enable()
    engine.infer_sync([np.zeros((2, 4), np.float32)], timeout=30)
    engine.drain(timeout=10)
    engine.close()
    sv = [e for e in t.events() if e["kind"] == "serving"]
    enq = [e for e in sv if e["name"] == "enqueue"]
    disp = [e for e in sv if e["name"] == "dispatch"]
    assert enq and disp
    rid = enq[0]["args"]["rid"]
    assert rid in disp[0]["args"]["rids"]       # request correlation
    assert disp[0]["args"]["ok"] is True
    assert disp[0]["dur"] >= 0


# -------------------------------------------- perf observatory (ISSUE 9) --
def test_perf_disabled_path_contract():
    """Every observatory emitting site pays one obs_hook attribute
    check when off — no observability import on any hot path.  The
    co_names assertions live in tools/obs_smoke.py (the CI gate);
    calling them here keeps the two from silently diverging."""
    assert obs_hook.current_perf() is None
    assert not obs.perf_enabled()
    assert obs_hook.current_perf.__code__.co_names == ("_perf",)
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import obs_smoke
    failures = []
    obs_smoke._check_disabled_contract(failures)
    assert failures == []
    assert obs.perf_report() == {"enabled": False}
    assert "disabled" in obs.render_perf_report()


def test_tracer_ring_drop_accounting(tmp_path):
    t = obs.enable(capacity=16)
    for i in range(100):
        t.emit("instant", f"e{i}")
    assert t.emitted == 100
    assert t.dropped == 84              # 100 emitted, 16 buffered
    assert t.high_watermark == 16
    rs = t.ring_stats()
    assert rs == {"events_emitted": 100, "events_dropped": 84,
                  "ring_capacity": 16, "ring_high_watermark": 16}
    # mirrored into monitor for the Prometheus exposition
    assert monitor.get_stat("obs.events_dropped") == 84
    assert monitor.get_stat("obs.ring_high_watermark") == 16
    text = obs.prometheus_text()
    assert "paddle_tpu_obs_events_dropped 84" in text
    # flight dumps carry the accounting so a truncated tape says so
    box = json.load(open(obs.dump_flight(
        path=str(tmp_path / "f.json"), reason="drop-test")))
    # the dump's own crash event lands in the full ring too: >= 84
    assert box["obs"]["events_dropped"] >= 84
    # an unwrapped ring reports a sub-capacity high watermark
    t2 = obs.enable(capacity=64)
    for i in range(5):
        t2.emit("instant", f"x{i}")
    assert t2.dropped == 0 and t2.high_watermark == 5


def test_perf_step_anatomy_and_memory_from_executor():
    t = obs.enable(capacity=512)
    obs.enable_perf(sample_every=2)
    monitor.stat_reset("perf.fences")
    paddle.enable_static()
    try:
        main, loss = _static_mlp()
        exe = paddle.static.Executor()
        for _ in range(5):
            exe.run(main, feed=_feed(8), fetch_list=[loss])
        exe.close()
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()
    rep = obs.perf_report()
    assert rep["enabled"] and rep["sample_every"] == 2
    idents = [r for r in rep["identities"]
              if r["component"] == "executor"]
    assert len(idents) == 1
    r0 = idents[0]
    # the compiling run is excluded (its wall is compile time):
    # 5 runs -> 4 measured steps, fenced on steps 2 and 4
    assert r0["steps"] == 4 and r0["sampled"] == 2
    assert r0["host_ms_mean"] > 0
    assert r0["measured"]["step_ms_p50"] > 0
    assert r0["measured"]["peak_bytes"] > 0
    assert r0["predicted"]["peak_bytes"] > 0
    assert np.isfinite(r0["drift"]["step_time_pct"])
    assert np.isfinite(r0["drift"]["peak_bytes_pct"])
    # histograms: host lane every step, device lane on fences only
    assert monitor.histogram_summary("step.host_ms")["count"] >= 4
    assert monitor.histogram_summary("step.device_ms")["count"] >= 2
    assert monitor.get_stat("perf.fences") == 2
    assert monitor.get_stat("mem.live_bytes_total") > 0
    # tracer lanes: host feed/dispatch + device events, truthful
    # intervals (feed and dispatch are separated by cache-lookup work)
    perf_evs = [e for e in t.events() if e["kind"] == "perf"]
    names = {e["name"] for e in perf_evs}
    assert {"step.host.feed", "step.host.dispatch",
            "step.device"} <= names
    dev = [e for e in perf_evs if e["name"] == "step.device"]
    assert all(e["dur"] > 0 for e in dev)
    # the rendered report names the identity
    assert "executor#" in obs.render_perf_report()


def test_drift_math_hand_computed():
    from paddle_tpu.observability.perf import (_IdentityPerf,
                                               _predicted_step_s)
    idp = _IdentityPerf("executor", 7)
    idp.steps = 10
    idp.sampled = 3
    idp.host_sum_s = 0.05               # 5 ms/step mean
    idp.device_s.extend([0.002, 0.004, 0.003])
    idp.peak_bytes = 1500
    idp.predicted = {"predicted_step_s": 0.002, "peak_bytes": 1000}
    d = idp.drift()
    assert d["host_ms_mean"] == pytest.approx(5.0)
    assert d["measured"]["step_ms_p50"] == pytest.approx(3.0)
    assert d["measured"]["step_ms_min"] == pytest.approx(2.0)
    assert d["measured"]["step_ms_max"] == pytest.approx(4.0)
    # (3 ms measured - 2 ms predicted) / 2 ms = +50%
    assert d["drift"]["step_time_pct"] == pytest.approx(50.0)
    # (1500 - 1000) / 1000 = +50%
    assert d["drift"]["peak_bytes_pct"] == pytest.approx(50.0)
    # a sharded prediction compares per-shard, not per-fleet
    idp.predicted = {"predicted_step_s": 0.002, "peak_bytes": 4000,
                     "peak_bytes_per_shard": 750}
    d = idp.drift()
    assert d["drift"]["peak_bytes_pct"] == pytest.approx(100.0)
    # no prediction -> drift axes absent, never fabricated
    idp.predicted = None
    assert idp.drift()["drift"] == {}
    # predicted step re-derived from the roofline when the record
    # carries only FLOPs/traffic
    from paddle_tpu.static.analysis.cost import CHIP_SPECS
    spec = CHIP_SPECS["cpu"]
    est = _predicted_step_s({"flops": spec.peak_flops,
                             "min_traffic_bytes": 0})
    assert est == pytest.approx(1.0)    # exactly one peak-FLOPs second


def test_quantile_from_counts_windowed_delta():
    monitor.stat_reset("q.win")
    for _ in range(10):
        monitor.stat_observe("q.win", 1.0)
    base = monitor.histogram_raw("q.win")
    for _ in range(10):
        monitor.stat_observe("q.win", 1200.0)
    cur = monitor.histogram_raw("q.win")
    counts = [a - b for a, b in zip(cur["counts"], base["counts"])]
    n = cur["count"] - base["count"]
    assert n == 10
    # the window sees ONLY the second batch: its p50 sits in the
    # [1000, 10^3.125) bucket, rank-interpolated to the bucket middle
    lo, hi = 1000.0, 10.0 ** 3.125
    q50 = monitor.quantile_from_counts(counts, n, 0.5)
    assert q50 == pytest.approx(lo + (hi - lo) * 0.5)
    # whereas the cumulative histogram's p50 still reads batch A
    assert monitor.quantile("q.win", 0.4) < 100.0
    assert monitor.quantile_from_counts(counts, 0, 0.5) == 0.0
    monitor.stat_reset("q.win")


# --------------------------------------------------- SLO monitors --------
def test_slo_rule_validation():
    with pytest.raises(ValueError):
        obs.SLORule("m", objective=0.0)
    with pytest.raises(ValueError):
        obs.SLORule("m", 1.0, window=0.0)
    with pytest.raises(ValueError):
        obs.SLORule("m", 1.0, burn_rate=0.0)
    with pytest.raises(ValueError):
        obs.SLORule("m", 1.0, quantile=1.0)
    with pytest.raises(ValueError):
        obs.SLOMonitor([])
    with pytest.raises(ValueError):
        obs.SLOMonitor([obs.SLORule("a", 1.0, name="dup"),
                        obs.SLORule("b", 1.0, name="dup")])
    rules = obs.standard_serving_rules(p99_latency_ms=50.0,
                                       shed_ratio=0.01)
    assert [r.name for r in rules] == ["serving_p99_latency_ms",
                                       "serving_shed_ratio"]


def test_slo_quantile_window_breach_and_recovery():
    t = obs.enable(capacity=128)
    monitor.stat_reset("slo.t.lat")
    monitor.stat_reset("slo.breaches")
    m = obs.install_slo_monitor([obs.SLORule(
        "slo.t.lat", 10.0, window=5.0, quantile=0.5, name="lat")])
    # first poll: no base snapshot -> the whole cumulative history is
    # NOT evaluated as a window; no data = healthy
    st = m.poll(now=100.0)
    assert st["status"] == "ok"
    assert st["rules"][0]["measured"] is None
    for _ in range(4):
        monitor.stat_observe("slo.t.lat", 100.0)
    st = m.poll(now=101.0)
    assert st["status"] == "degraded" and st["breached"] == ["lat"]
    assert st["rules"][0]["measured"] > 10.0
    assert st["rules"][0]["burn"] > 1.0
    assert st["reasons"] and "lat" in st["reasons"][0]
    assert monitor.get_stat("slo.breaches") == 1
    assert monitor.get_stat("slo.lat.breached") == 1
    assert monitor.get_stat("slo.degraded") == 1
    # still breached while the burst stays inside the 5 s window
    st = m.poll(now=103.0)
    assert st["status"] == "degraded"
    assert monitor.get_stat("slo.breaches") == 1    # no re-fire
    # once every base candidate postdates the burst: no data -> recover
    st = m.poll(now=109.0)
    assert st["status"] == "ok"
    assert monitor.get_stat("slo.lat.breached") == 0
    evs = [e for e in t.events() if e["kind"] == "slo"]
    assert [e["name"] for e in evs] == ["breach", "recover"]
    assert evs[0]["args"]["rule"] == "lat"
    # status() replays the last poll without re-snapshotting
    assert m.status()["status"] == "ok"
    assert obs.slo_status(poll=False)["status"] == "ok"
    monitor.stat_reset("slo.t.lat")


def test_slo_burn_rate_threshold():
    monitor.stat_reset("slo.t.burn")
    m = obs.install_slo_monitor([obs.SLORule(
        "slo.t.burn", 10.0, window=5.0, quantile=0.5, burn_rate=2.0,
        name="fast_burn")])
    m.poll(now=10.0)
    for _ in range(4):
        monitor.stat_observe("slo.t.burn", 15.0)    # burn ~1.5x
    st = m.poll(now=11.0)
    r = st["rules"][0]
    assert r["measured"] > 10.0                     # over objective...
    assert 1.0 < r["burn"] < 2.0
    assert not r["breached"]                        # ...but a slow burn
    assert st["status"] == "ok"
    for _ in range(16):
        monitor.stat_observe("slo.t.burn", 100.0)   # now a fast burn
    st = m.poll(now=12.0)
    assert st["rules"][0]["breached"]
    monitor.stat_reset("slo.t.burn")


def test_slo_ratio_and_rate_rules():
    monitor.stat_reset("slo.t.shed")
    monitor.stat_reset("slo.t.reqs")
    monitor.stat_reset("slo.t.evts")
    m = obs.install_slo_monitor([
        obs.SLORule("slo.t.shed", 0.10, window=60.0, per="slo.t.reqs",
                    name="shed_ratio"),
        obs.SLORule("slo.t.evts", 1.0, window=60.0, name="evt_rate"),
    ])
    monitor.stat_add("slo.t.reqs", 100)     # predates the base snapshot
    m.poll(now=0.0)
    monitor.stat_add("slo.t.shed", 5)
    monitor.stat_add("slo.t.reqs", 40)      # windowed ratio: 5/40
    monitor.stat_add("slo.t.evts", 10)      # windowed rate: 10/2s = 5/s
    st = m.poll(now=2.0)
    ratio, rate = st["rules"]
    assert ratio["kind"] == "ratio"
    assert ratio["measured"] == pytest.approx(0.125)
    assert ratio["breached"]
    assert rate["kind"] == "rate"
    assert rate["measured"] == pytest.approx(5.0)
    assert rate["breached"]
    # shed events against ZERO denominator traffic burn unambiguously:
    # take a clean base past the earlier traffic, then shed with no
    # requests inside the evaluated window
    m.poll(now=4.0)
    monitor.stat_add("slo.t.shed", 3)
    st = m.poll(now=70.0)               # base = the now-4.0 snapshot
    # non-finite measurements serialize as the JSON-safe string "inf"
    # (the status dict lands verbatim in /perf bodies and JSONL lines)
    assert st["rules"][0]["measured"] == "inf"
    assert st["rules"][0]["breached"]
    json.dumps(st)      # the whole status stays strict-JSON-parseable
    # an idle window (no deltas at all) is healthy, not unknown
    st = m.poll(now=200.0)
    assert st["status"] == "ok"
    assert st["rules"][0]["measured"] is None
    for n in ("slo.t.shed", "slo.t.reqs", "slo.t.evts"):
        monitor.stat_reset(n)


def test_slo_status_without_monitor_is_ok():
    assert obs.get_slo_monitor() is None
    st = obs.slo_status()
    assert st == {"installed": False, "status": "ok", "rules": [],
                  "breached": [], "reasons": []}


def test_healthz_slo_degradation_and_recovery(tmp_path):
    import time as _time

    from paddle_tpu import inference, jit, serving
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.serving.http import Client, ServingServer

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 2))
    prefix = str(tmp_path / "m")
    jit.save(model, prefix, input_spec=[InputSpec([None, 4], "float32")])
    pred = inference.create_predictor(inference.Config(prefix))
    engine = serving.InferenceEngine(pred, max_batch_size=4,
                                     batch_timeout_ms=1.0, name="h")
    engine.warmup()
    monitor.stat_reset("slo.h.lat")
    obs.install_slo_monitor([obs.SLORule(
        "slo.h.lat", 10.0, window=0.5, quantile=0.5, name="h_lat")])
    obs.slo_status()                    # base snapshot
    with ServingServer(engine, port=0) as srv:
        client = Client(srv.url)
        h = client.healthz()
        assert h["status"] == "running" and h["slo"] == "ok"
        for _ in range(4):
            monitor.stat_observe("slo.h.lat", 500.0)
        h = client.healthz()            # probe polls -> degraded 503
        assert h["status"] == "degraded"
        assert h["engine_state"] == "running"   # liveness unaffected
        assert h["slo"]["breached"] == ["h_lat"]
        assert any("h_lat" in r for r in h["slo"]["reasons"])
        # the breach ages out of the 0.5 s window -> 200 again
        deadline = _time.monotonic() + 5.0
        while _time.monotonic() < deadline:
            _time.sleep(0.2)
            h = client.healthz()
            if h["status"] == "running":
                break
        assert h["status"] == "running" and h["slo"] == "ok"
        # /perf endpoint: report disabled, SLO block present
        p = client.perf()
        assert p["perf"] == {"enabled": False}
        assert p["slo"]["installed"] is True
    engine.close()
    monitor.stat_reset("slo.h.lat")


# ------------------------------------------- per-engine serving labels ----
def test_engine_name_mirrors_stats_and_labels_gauges(tmp_path):
    from paddle_tpu import inference, jit, serving
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.serving.http import Client, ServingServer

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 2))
    prefix = str(tmp_path / "m")
    jit.save(model, prefix, input_spec=[InputSpec([None, 4], "float32")])
    pred = inference.create_predictor(inference.Config(prefix))
    monitor.stat_reset("serving.engine.bert.requests")
    engine = serving.InferenceEngine(pred, max_batch_size=4,
                                     batch_timeout_ms=1.0, name="bert")
    engine.warmup()
    engine.infer_sync([np.zeros((2, 4), np.float32)], timeout=30)
    assert engine.stats()["engine"] == "bert"
    # named engines mirror their counters under serving.engine.<name>.*
    assert monitor.get_stat("serving.engine.bert.requests") == 1
    assert monitor.get_stat("serving.engine.bert.batches") == 1
    assert monitor.histogram_summary(
        "serving.engine.bert.latency_ms")["count"] == 1
    with ServingServer(engine, port=0) as srv:
        text = Client(srv.url).metrics_text()
        assert ('paddle_tpu_serving_engine_queue_depth{engine="bert"}'
                in text)
        assert "paddle_tpu_serving_engine_bert_requests 1" in text
        for line in text.splitlines():
            if line and not line.startswith("#"):
                assert PROM_LINE.match(line), line
    engine.close()
    # an unnamed engine keeps the unprefixed layout (no mirror)
    e2 = serving.InferenceEngine(pred, max_batch_size=4,
                                 batch_timeout_ms=1.0)
    assert e2.name is None and e2.stats()["engine"] is None
    e2.close()


def test_metrics_snapshot_carries_slo_perf_and_drop_blocks():
    t = obs.enable(capacity=32)
    obs.enable_perf(sample_every=0)     # host anatomy only, no fences
    monitor.stat_reset("slo.t.snap")
    m = obs.install_slo_monitor([obs.SLORule(
        "slo.t.snap", 1.0, window=5.0, name="snap_rate")])
    m.poll(now=1.0)
    t.emit("instant", "x")
    snap = obs.metrics_snapshot()
    # one JSONL line is a complete offline record: distributions AND
    # objective state, not just counters
    assert "histograms" in snap and "stats" in snap
    assert snap["obs"]["ring_capacity"] == 32
    assert snap["slo"]["installed"] is True
    assert snap["slo"]["rules"][0]["name"] == "snap_rate"
    assert snap["perf"]["enabled"] is True
    monitor.stat_reset("slo.t.snap")


def test_prometheus_extra_gauges_join_families_one_type_line():
    monitor.stat_reset("promfam.reqs")
    monitor.stat_add("promfam.reqs", 3)
    try:
        text = obs.prometheus_text({
            'promfam_reqs{engine="a"}': 1,
            'promfam_reqs{engine="b"}': 2,
            "promfam_reqs": 9,          # duplicate of the registry stat
        })
    finally:
        monitor.stat_reset("promfam.reqs")
    lines = text.splitlines()
    fam = [i for i, ln in enumerate(lines)
           if ln.startswith("paddle_tpu_promfam_reqs")
           or ln == "# TYPE paddle_tpu_promfam_reqs gauge"]
    # exactly one TYPE line, and the whole family is contiguous —
    # strict scrapers reject repeated or split metric families
    assert sum(ln.startswith("# TYPE paddle_tpu_promfam_reqs")
               for ln in lines) == 1
    assert fam == list(range(fam[0], fam[0] + len(fam)))
    assert 'paddle_tpu_promfam_reqs{engine="a"} 1' in lines
    assert 'paddle_tpu_promfam_reqs{engine="b"} 2' in lines
    # the unlabeled extra duplicates the registry series: skipped, the
    # registry's value wins
    assert "paddle_tpu_promfam_reqs 3" in lines
    assert "paddle_tpu_promfam_reqs 9" not in lines


def test_slo_explicit_per_wins_over_histogram_metric():
    # quantile= and per= contradict each other: rejected up front
    with pytest.raises(ValueError):
        obs.SLORule("m", 1.0, quantile=0.99, per="n")
    monitor.stat_reset("slo.t.hist_ms")
    monitor.stat_reset("slo.t.den")
    m = obs.install_slo_monitor([obs.SLORule(
        "slo.t.hist_ms", 0.5, window=60.0, per="slo.t.den",
        name="hist_ratio")])
    m.poll(now=0.0)
    for _ in range(4):                  # 4 windowed observations...
        monitor.stat_observe("slo.t.hist_ms", 100.0)
    monitor.stat_add("slo.t.den", 16)   # ...per 16 denominator events
    st = m.poll(now=1.0)
    r = st["rules"][0]
    # the explicit denominator makes this a ratio of observation
    # counts (4/16), NOT a p99 of the 100 ms samples
    assert r["kind"] == "ratio"
    assert r["measured"] == pytest.approx(0.25)
    assert not r["breached"]
    for n in ("slo.t.hist_ms", "slo.t.den"):
        monitor.stat_reset(n)


def test_slo_uninstall_clears_rule_gauges():
    monitor.stat_reset("slo.t.stale")
    m = obs.install_slo_monitor([obs.SLORule(
        "slo.t.stale", 1.0, window=60.0, name="stale_rate")])
    m.poll(now=0.0)
    monitor.stat_add("slo.t.stale", 1000)
    st = m.poll(now=1.0)
    assert st["rules"][0]["breached"]
    assert monitor.get_stat("slo.stale_rate.breached") == 1
    # a dashboard must not keep seeing the breach after the monitor
    # that produced it is gone
    obs.uninstall_slo_monitor()
    assert monitor.get_stat("slo.stale_rate.breached") == 0
    assert monitor.get_stat("slo.stale_rate.burn") == 0
    assert monitor.get_stat("slo.degraded") == 0
    monitor.stat_reset("slo.t.stale")


def test_perf_identity_split_per_feed_signature():
    # two feed shapes of ONE program are two executables with two
    # predictions — their step times must not mix in one rolling
    # window, or drift compares shape A's measurement against shape
    # B's prediction
    obs.enable_perf(sample_every=0)
    paddle.enable_static()
    try:
        main, loss = _static_mlp()
        exe = paddle.static.Executor()
        for n in (4, 16):
            for _ in range(3):
                exe.run(main, feed=_feed(n), fetch_list=[loss])
        exe.close()
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()
    idents = [r for r in obs.perf_report()["identities"]
              if r["component"] == "executor"]
    assert len(idents) == 2
    assert all(r["steps"] == 2 for r in idents)     # compile excluded
    names = {str(r["identity"]) for r in idents}
    assert any("[4x8;4x1]" in n for n in names), names
    assert any("[16x8;16x1]" in n for n in names), names


def test_slo_min_count_gates_quantile_windows():
    with pytest.raises(ValueError):
        obs.SLORule("m", 1.0, min_count=0)
    monitor.stat_reset("slo.t.mc_ms")
    m = obs.install_slo_monitor([obs.SLORule(
        "slo.t.mc_ms", 1.0, window=60.0, quantile=0.99,
        min_count=5, name="mc")])
    m.poll(now=0.0)
    for _ in range(4):
        monitor.stat_observe("slo.t.mc_ms", 100.0)
    st = m.poll(now=1.0)
    # 4 observations < min_count: no data, healthy — a fresh monitor
    # can't degrade /healthz off a handful of samples
    assert st["rules"][0]["measured"] is None
    assert st["status"] == "ok"
    monitor.stat_observe("slo.t.mc_ms", 100.0)
    st = m.poll(now=2.0)
    assert st["rules"][0]["measured"] is not None
    assert st["rules"][0]["breached"]
    assert monitor.get_stat("slo.mc.measured") > 0
    # window goes idle: the measured gauge is dropped, not frozen at
    # the breach-level value
    st = m.poll(now=200.0)
    assert st["rules"][0]["measured"] is None
    assert monitor.get_stat("slo.mc.measured") == 0
    monitor.stat_reset("slo.t.mc_ms")


def test_resolve_perf_chip_warns_on_unknown_flag():
    from paddle_tpu.core.flags import get_flag, set_flags
    from paddle_tpu.static.analysis.cost import resolve_perf_chip
    old = get_flag("perf_chip")
    try:
        set_flags({"perf_chip": "v5"})      # typo for v5p
        with pytest.warns(RuntimeWarning, match="perf_chip"):
            chip = resolve_perf_chip()
        assert chip == "cpu"                # backend auto-detection
    finally:
        set_flags({"perf_chip": old})


def test_engine_label_escapes_prometheus_value():
    from paddle_tpu.serving.http import _engine_label
    assert _engine_label(None) == "" and _engine_label("") == ""
    assert _engine_label("bert") == '{engine="bert"}'
    assert _engine_label('a"b\\c\nd') == r'{engine="a\"b\\c\nd"}'


def test_perf_report_cli_multiline_jsonl_and_flight(tmp_path, capsys):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import perf_report as cli

    obs.enable(capacity=64)
    obs.enable_perf(sample_every=0)
    monitor.stat_reset("slo.t.cli")
    m = obs.install_slo_monitor([obs.SLORule(
        "slo.t.cli", 1.0, window=60.0, per="slo.t.cli_den",
        name="cli_ratio")])
    m.poll(now=0.0)
    jsonl = str(tmp_path / "metrics.jsonl")
    obs.dump_metrics(jsonl)
    # breach with zero denominator: measured serializes as "inf"
    monitor.stat_add("slo.t.cli", 3)
    m.poll(now=1.0)
    # gauges peg at a finite sentinel instead of going stale (a
    # dashboard must not show a healthy burn while breached=1)
    assert monitor.get_stat("slo.cli_ratio.burn") == 1e12
    assert monitor.get_stat("slo.cli_ratio.measured") == 1e12
    obs.dump_metrics(jsonl)             # line 2: every line is JSON-{
    rc = cli.main([jsonl])              # regression: multi-line JSONL
    out = capsys.readouterr().out       # was misread as ONE document
    assert rc == 1                      # breached in the embedded eval
    assert "perf observatory" in out
    assert "measured inf" in out and "BREACHED" in out
    # a flight dump renders through the same loader, and stays strict
    # JSON even with the inf breach in flight — the breach tracer
    # event and the embedded status must never serialize the bare
    # Infinity token (jq / JSON.parse / chrome trace viewer reject it)
    flight = str(tmp_path / "box.json")
    obs.dump_flight(flight, reason="test")
    raw = open(flight).read()
    assert "Infinity" not in raw
    assert "Infinity" not in json.dumps(obs_hook._tracer.chrome_trace())
    assert cli.main([flight]) == 1
    assert "perf observatory" in capsys.readouterr().out
    # a source whose observatory was off is "no report" for the exit
    # contract — a CI gate must not pass with the observatory disabled
    disabled = str(tmp_path / "disabled.json")
    with open(disabled, "w") as f:
        json.dump({"perf": {"enabled": False}}, f)
    assert cli.main([disabled]) == 1
    capsys.readouterr()
    for n in ("slo.t.cli", "slo.t.cli_den"):
        monitor.stat_reset(n)


def test_perf_identity_lru_cap():
    from paddle_tpu.observability import perf as perf_mod
    p = obs.enable_perf(sample_every=0)     # host anatomy only
    for i in range(perf_mod._MAX_IDENTITIES + 10):
        p.step("executor", f"id{i}", 0.0, 0.0, 0.0, 0.0, None)
    t = p.report()["totals"]
    # stale identities are LRU-evicted, not retained forever (the
    # Executor drops stale-version cache entries; their perf state
    # must not accumulate across a long-lived process)
    assert t["identities"] == perf_mod._MAX_IDENTITIES
    assert t["identities_evicted"] == 10


def test_serving_step_histogram_mirrors_per_engine():
    p = obs.enable_perf(sample_every=0)
    for n in ("perf.serving.dispatch_ms", "perf.serving.bert.dispatch_ms"):
        monitor.stat_reset(n)
    p.serving_step("bert", "dispatch", 0.01)
    p.serving_step(None, "dispatch", 0.02)          # unnamed: no mirror
    assert monitor.histogram_summary(
        "perf.serving.dispatch_ms")["count"] == 2
    assert monitor.histogram_summary(
        "perf.serving.bert.dispatch_ms")["count"] == 1
    for n in ("perf.serving.dispatch_ms", "perf.serving.bert.dispatch_ms"):
        monitor.stat_reset(n)


# ------------------------------------------------------------ CI gate ----
def test_obs_smoke_in_process():
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import obs_smoke
    failures = obs_smoke.run_checks()
    assert failures == []
