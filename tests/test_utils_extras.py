"""utils (custom ops, monitor, auto-checkpoint) + optimizer extras tests
(reference analogs: test_custom_op.py, test_monitor.py,
test_auto_checkpoint.py, test_ema.py, test_lookahead.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, optimizer
from paddle_tpu.utils import monitor, register_custom_op, train_epoch_range


# -- custom ops --------------------------------------------------------------

def test_custom_op_forward_and_autodiff():
    import jax.numpy as jnp
    relu3 = register_custom_op("relu_cubed", lambda a: jnp.maximum(a, 0) ** 3)
    x = paddle.to_tensor(np.array([-1.0, 2.0], np.float32),
                         stop_gradient=False)
    y = relu3(x)
    np.testing.assert_allclose(y.numpy(), [0.0, 8.0])
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [0.0, 12.0])  # 3x^2


def test_custom_op_custom_vjp():
    import jax.numpy as jnp
    # straight-through sign: forward sign(x), backward passes grad through
    st_sign = register_custom_op(
        "st_sign", lambda a: jnp.sign(a),
        backward=lambda res, ct: (ct,))
    x = paddle.to_tensor(np.array([-2.0, 3.0], np.float32),
                         stop_gradient=False)
    y = st_sign(x)
    np.testing.assert_allclose(y.numpy(), [-1.0, 1.0])
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0])


# -- monitor -----------------------------------------------------------------

def test_monitor_gauges():
    monitor.stat_reset()
    monitor.stat_add("STAT_total_feasign_num_in_mem", 5)
    monitor.stat_add("STAT_total_feasign_num_in_mem", 2)
    monitor.stat_set("STAT_epoch", 3)
    assert monitor.get_stat("STAT_total_feasign_num_in_mem") == 7
    assert monitor.all_stats()["STAT_epoch"] == 3
    monitor.stat_reset("STAT_epoch")
    assert monitor.get_stat("STAT_epoch") == 0


# -- auto checkpoint ---------------------------------------------------------

def test_train_epoch_range_resume(tmp_path):
    paddle.seed(0)
    d = str(tmp_path / "acp")

    def make():
        paddle.seed(0)
        m = nn.Linear(4, 2)
        o = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
        return m, o

    x = paddle.randn([8, 4])
    y = paddle.randn([8, 2])

    m1, o1 = make()
    ran = []
    for epoch in train_epoch_range(5, d, model=m1, opt=o1):
        ran.append(epoch)
        F.mse_loss(m1(x), y).backward()
        o1.step()
        o1.clear_grad()
        if epoch == 2:
            break  # simulated preemption AFTER epoch-2 body but pre-save
    assert ran == [0, 1, 2]

    # restart: epochs 0-1 were snapshotted; epoch 2 (interrupted before
    # its save) re-runs
    m2, o2 = make()
    ran2 = [e for e in train_epoch_range(5, d, model=m2, opt=o2)
            if True]
    assert ran2 == [2, 3, 4]


# -- optimizer extras --------------------------------------------------------

def test_ema_apply_restore():
    paddle.seed(1)
    m = nn.Linear(4, 2)
    ema = optimizer.ExponentialMovingAverage(
        0.9, parameters=list(m.parameters()))
    w0 = m.weight.numpy().copy()
    m.weight.data = m.weight.data + 1.0
    ema.update()
    live = m.weight.numpy().copy()
    with ema.apply():
        applied = m.weight.numpy().copy()
    np.testing.assert_allclose(m.weight.numpy(), live)  # restored
    # shadow is between w0 and live
    assert np.all(applied > w0 - 1e-6) and np.all(applied < live + 1e-6)
    assert not np.allclose(applied, live)


def test_model_average():
    paddle.seed(2)
    m = nn.Linear(2, 2)
    ma = optimizer.ModelAverage(parameters=list(m.parameters()))
    vals = []
    for i in range(4):
        m.weight.data = m.weight.data * 0 + float(i)
        ma.step()
        vals.append(float(i))
    with ma.apply():
        np.testing.assert_allclose(m.weight.numpy(),
                                   np.full((2, 2), np.mean(vals)),
                                   rtol=1e-6)
    np.testing.assert_allclose(m.weight.numpy(), np.full((2, 2), 3.0))


def test_lookahead_converges_and_syncs():
    paddle.seed(3)
    m = nn.Linear(8, 1)
    inner = optimizer.SGD(learning_rate=0.05, parameters=m.parameters())
    opt = optimizer.Lookahead(inner, alpha=0.5, k=5)
    x = paddle.randn([64, 8])
    w = paddle.randn([8, 1])
    y = x.matmul(w)
    losses = []
    for _ in range(60):
        loss = F.mse_loss(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])


def test_need_weights_returns_real_weights():
    paddle.seed(4)
    mha = nn.MultiHeadAttention(16, 4, need_weights=True)
    x = paddle.randn([2, 5, 16])
    out, w = mha(x, x, x)
    assert w is not None
    assert w.shape == [2, 4, 5, 5]
    np.testing.assert_allclose(w.numpy().sum(-1),
                               np.ones((2, 4, 5)), rtol=1e-5)
    # parity with the fused (no-weights) path
    mha.need_weights = False
    out2 = mha(x, x, x)
    np.testing.assert_allclose(out.numpy(), out2.numpy(), rtol=2e-3,
                               atol=1e-5)


def test_launch_watches_and_terminates(tmp_path):
    """launch() parity with launch_utils child-watching: a failing worker
    takes the pod down with a non-zero exit code."""
    from paddle_tpu.distributed.launch import launch
    ok = tmp_path / "ok.py"
    ok.write_text("import os\n"
                  "assert os.environ['PADDLE_TRAINERS_NUM'] == '2'\n"
                  "assert os.environ['PADDLE_TRAINER_ID'] in '01'\n"
                  "assert 'COORDINATOR_ADDRESS' in os.environ\n")
    assert launch(str(ok), nproc_per_node=2) == 0
    bad = tmp_path / "bad.py"
    bad.write_text("import sys, os, time\n"
                   "if os.environ['PADDLE_TRAINER_ID'] == '1':\n"
                   "    sys.exit(3)\n"
                   "time.sleep(60)\n")
    assert launch(str(bad), nproc_per_node=2) == 3


def test_need_weights_respects_bool_mask():
    """Bool attn_mask (True=keep) must mask weights to zero on the
    need_weights path exactly like the fused path."""
    paddle.seed(5)
    mha = nn.MultiHeadAttention(8, 2, need_weights=True)
    x = paddle.randn([1, 4, 8])
    mask = np.ones((1, 1, 4, 4), bool)
    mask[..., -1] = False  # nobody may attend to the last position
    out, w = mha(x, x, x, attn_mask=paddle.to_tensor(mask))
    assert np.allclose(w.numpy()[..., -1], 0.0)
    mha.need_weights = False
    out2 = mha(x, x, x, attn_mask=paddle.to_tensor(mask))
    np.testing.assert_allclose(out.numpy(), out2.numpy(), rtol=2e-3,
                               atol=1e-5)


def test_spawn_runs_module_level_fn(tmp_path):
    from paddle_tpu.distributed.launch import spawn
    marker = str(tmp_path)
    spawn(_spawn_probe, args=(marker,), nprocs=2)
    got = sorted(os.listdir(marker))
    assert got == ["rank0", "rank1"], got


def _spawn_probe(marker):
    rank = os.environ["PADDLE_TRAINER_ID"]
    assert os.environ["PADDLE_TRAINERS_NUM"] == "2"
    open(os.path.join(marker, f"rank{rank}"), "w").close()
