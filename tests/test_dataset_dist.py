"""Distributed dataset / global shuffle (reference: data_set.h:43-211,
GlobalShuffle :111; fluid/dataset.py DatasetFactory).

The cross-worker protocol is exercised two ways: simulated workers in
threads here (shared tmpdir spool), and two REAL launched processes in
test_multihost.py."""
import os
import threading

import numpy as np
import pytest

from paddle_tpu.io import DatasetFactory, InMemoryDataset, QueueDataset


def _write_files(tmp_path, n_files=4, per_file=5):
    files, all_recs = [], []
    for i in range(n_files):
        p = os.path.join(str(tmp_path), f"part-{i:03d}.txt")
        lines = [f"f{i}r{j}" for j in range(per_file)]
        with open(p, "w") as f:
            f.write("\n".join(lines) + "\n")
        files.append(p)
        all_recs.extend(lines)
    return files, all_recs


def test_factory_and_load(tmp_path):
    files, recs = _write_files(tmp_path)
    ds = DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist(files)
    ds.load_into_memory()
    assert list(ds) == recs
    assert len(ds) == len(recs) and ds[0] == "f0r0"
    assert ds.get_memory_data_size() == len(recs)
    with pytest.raises(ValueError):
        DatasetFactory().create_dataset("NopeDataset")


def test_single_worker_global_shuffle_is_seeded_permutation(tmp_path):
    files, recs = _write_files(tmp_path)
    ds = InMemoryDataset()
    ds.set_filelist(files)
    ds.load_into_memory()
    ds.global_shuffle(seed=3)
    out1 = list(ds)
    assert out1 != recs and sorted(out1) == sorted(recs)
    ds.release_memory()
    ds.load_into_memory()
    ds.global_shuffle(seed=3)
    assert list(ds) == out1  # deterministic
    ds.load_into_memory()
    ds.global_shuffle(seed=4)
    assert list(ds) != out1  # seed-sensitive


def test_requires_load_before_shuffle(tmp_path):
    ds = InMemoryDataset()
    ds.set_filelist([])
    with pytest.raises(RuntimeError):
        ds.global_shuffle(seed=0)


def _run_workers(files, tmp_path, world, seed, epoch=None):
    """Run `world` simulated workers concurrently; return per-rank
    records.  Threads are required: the spool protocol has real
    sentinel-file barriers."""
    results = [None] * world
    errors = []

    def work(rank):
        try:
            ds = InMemoryDataset(rank=rank, world_size=world)
            ds.set_filelist(files)
            ds.load_into_memory()
            if epoch is not None:
                ds.set_epoch(epoch)
                ds.global_shuffle(spool_dir=str(tmp_path))
            else:
                ds.global_shuffle(seed=seed, spool_dir=str(tmp_path))
            results[rank] = list(ds)
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=work, args=(r,))
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert all(r is not None for r in results)
    return results


@pytest.mark.parametrize("world", [2, 3])
def test_multiworker_global_shuffle_exact_once(tmp_path, world):
    files, recs = _write_files(tmp_path, n_files=5, per_file=4)
    spool = tmp_path / "spool1"
    spool.mkdir()
    shards = _run_workers(files, spool, world, seed=11)
    union = [r for shard in shards for r in shard]
    # disjoint, exactly-once union — the GlobalShuffle contract
    assert sorted(union) == sorted(recs)
    assert len(set(union)) == len(recs)
    # balanced within 1
    sizes = [len(s) for s in shards]
    assert max(sizes) - min(sizes) <= 1
    # deterministic: same seed in a fresh spool -> identical shards
    spool2 = tmp_path / "spool2"
    spool2.mkdir()
    again = _run_workers(files, spool2, world, seed=11)
    assert again == shards
    # a different epoch seed reshuffles
    spool3 = tmp_path / "spool3"
    spool3.mkdir()
    other = _run_workers(files, spool3, world, seed=12)
    assert other != shards
    assert sorted(r for s in other for r in s) == sorted(recs)


def test_repeated_shuffle_same_spool_same_seed(tmp_path):
    """Persistent datasets re-shuffling with the SAME seed in the SAME
    spool dir: the generation counter must keep the sentinel barriers
    from matching a previous call's files, and rank 0 reaps the finished
    previous generation."""
    files, recs = _write_files(tmp_path, n_files=4, per_file=3)
    spool = tmp_path / "spool"
    spool.mkdir()
    world = 2
    dss = [InMemoryDataset(rank=r, world_size=world) for r in range(world)]
    for ds in dss:
        ds.set_filelist(files)

    rounds = []
    for _ in range(3):
        results = [None] * world
        def work(rank):
            dss[rank].load_into_memory()
            dss[rank].global_shuffle(seed=42, spool_dir=str(spool))
            results[rank] = list(dss[rank])
        ts = [threading.Thread(target=work, args=(r,))
              for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert all(r is not None for r in results)
        union = [x for s in results for x in s]
        assert sorted(union) == sorted(recs)
        rounds.append(results)
    assert rounds[0] == rounds[1] == rounds[2]  # same seed, same result
    # generations 0 and 1 were reaped after later rounds completed
    # (roots are namespaced per dataset: <ns>_gs_<gen>_<seed>)
    left = sorted(os.listdir(spool))
    assert len(left) == 1 and left[0].endswith("_gs_2_42"), left


def test_reap_follows_namespace_across_filelist_change(tmp_path):
    """set_filelist between shuffles changes the spool fingerprint; the
    reaper must delete the previous generation under the namespace it
    was WRITTEN with, not the current one."""
    files_a, _ = _write_files(tmp_path, n_files=2, per_file=3)
    d2 = tmp_path / "second"
    d2.mkdir()
    files_b, _ = _write_files(d2, n_files=2, per_file=3)
    spool = tmp_path / "spool"
    spool.mkdir()
    world = 2
    dss = [InMemoryDataset(rank=r, world_size=world) for r in range(world)]

    def shuffle_round(files):
        def work(rank):
            dss[rank].set_filelist(files)
            dss[rank].load_into_memory()
            dss[rank].global_shuffle(seed=9, spool_dir=str(spool))
        ts = [threading.Thread(target=work, args=(r,))
              for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)

    shuffle_round(files_a)   # gen 0 under ns(files_a)
    ns_a = dss[0]._spool_namespace()
    shuffle_round(files_b)   # gen 1 under ns(files_b) reaps gen 0
    left = sorted(os.listdir(spool))
    assert not any(d.startswith(f"{ns_a}_gs_0_") for d in left), left
    assert len(left) == 1 and left[0].endswith("_gs_1_9"), left


def test_epoch_folded_seed(tmp_path):
    files, recs = _write_files(tmp_path, n_files=4, per_file=3)
    spool_a = tmp_path / "ea"
    spool_a.mkdir()
    e0 = _run_workers(files, spool_a, 2, seed=None, epoch=0)
    spool_b = tmp_path / "eb"
    spool_b.mkdir()
    e1 = _run_workers(files, spool_b, 2, seed=None, epoch=1)
    assert e0 != e1
    assert (sorted(r for s in e1 for r in s) == sorted(recs))


def test_pipe_command_and_parse_fn(tmp_path):
    files, _ = _write_files(tmp_path, n_files=1, per_file=3)
    ds = InMemoryDataset()
    ds.set_filelist(files)
    # reference pipe semantics: file bytes | shell command -> lines
    ds.set_pipe_command("sed s/^f/F/")
    ds.set_parse_fn(lambda ln: ln.upper())
    ds.load_into_memory()
    assert list(ds) == ["F0R0", "F0R1", "F0R2"]


def test_queue_dataset_streams_shard(tmp_path):
    files, recs = _write_files(tmp_path, n_files=4, per_file=2)
    a = QueueDataset(rank=0, world_size=2)
    b = QueueDataset(rank=1, world_size=2)
    for ds in (a, b):
        ds.set_filelist(files)
    got = list(a) + list(b)
    assert sorted(got) == sorted(recs)
    with pytest.raises(RuntimeError):
        a.global_shuffle()
    with pytest.raises(RuntimeError):
        a.local_shuffle()


def test_local_shuffle_decorrelates_ranks(tmp_path):
    files, _ = _write_files(tmp_path, n_files=2, per_file=50)
    a = InMemoryDataset(rank=0, world_size=2)
    b = InMemoryDataset(rank=1, world_size=2)
    for ds in (a, b):
        ds.set_filelist(files)
        ds.load_into_memory()
        ds.local_shuffle(seed=5)
    # same seed, different ranks -> different orders (decorrelated)
    assert [r[1:] for r in a] != [r[1:] for r in b]


def test_dataloader_interop(tmp_path):
    from paddle_tpu.io import DataLoader
    files, recs = _write_files(tmp_path, n_files=2, per_file=8)
    ds = InMemoryDataset()
    ds.set_filelist(files)
    ds.set_parse_fn(lambda ln: np.float32(len(ln)))
    ds.load_into_memory()
    ds.global_shuffle(seed=1)
    dl = DataLoader(ds, batch_size=4, shuffle=False)
    batches = list(dl)
    assert len(batches) == 4
    total = sum(float(np.asarray(b).sum()) for b in batches)
    assert total == sum(float(len(r)) for r in recs)
