"""Multi-model control plane tests (ISSUE 19): routing/alias/quota/WFQ
semantics on stub engines, deterministic SLO-driven elasticity with an
injected clock, and concurrent registry mutation under live traffic on
real dyadic artifacts (bitwise results, no stranded futures, pages
reclaimed)."""
import concurrent.futures as cf
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference, jit, serving
from paddle_tpu.jit import InputSpec
from paddle_tpu.serving import (ElasticityController, EngineClosed,
                                ModelRegistry, QueueFull, QuotaExceeded,
                                UnknownModel)
from paddle_tpu.testing.chaos import make_dyadic_lm, make_dyadic_model
from paddle_tpu.utils import monitor


class StubEngine:
    """Duck-typed InferenceEngine: futures the test resolves itself, so
    WFQ occupancy is fully controlled."""

    def __init__(self):
        self.weights_version = 1
        self.pending = []
        self.closed = False

    def infer(self, inputs, deadline_ms=None):
        f = cf.Future()
        self.pending.append(f)
        return f

    def release_all(self):
        for f in self.pending:
            if not f.done():
                f.set_result("ok")
        self.pending = []

    def drain(self, timeout=None):
        self.release_all()
        return True

    def close(self, timeout=10.0):
        self.release_all()
        self.closed = True


# ------------------------------------------------------------ routing --
def test_routing_aliases_default_unknown():
    reg = ModelRegistry()
    reg.register("alpha", engine=StubEngine())
    reg.register("beta", engine=StubEngine(), aliases=["prod"])
    try:
        assert reg.default_model == "alpha"     # first ready model
        assert reg.resolve(None).name == "alpha"
        assert reg.resolve("beta").name == "beta"
        assert reg.resolve("prod").name == "beta"
        with pytest.raises(UnknownModel):
            reg.resolve("nope")
        # canary flip: re-point the alias, routing follows atomically
        reg.alias("prod", "alpha")
        assert reg.resolve("prod").name == "alpha"
        reg.set_default("beta")
        assert reg.resolve(None).name == "beta"
    finally:
        reg.close()


def test_not_ready_model_is_unroutable_until_marked():
    reg = ModelRegistry()
    reg.register("gamma", engine=StubEngine(), ready=False)
    try:
        with pytest.raises(EngineClosed):       # 503, not 404
            reg.resolve("gamma")
        reg.mark_ready("gamma")
        assert reg.resolve("gamma").state == "ready"
    finally:
        reg.close()


def test_close_refuses_late_register():
    reg = ModelRegistry()
    reg.register("alpha", engine=StubEngine())
    reg.close()
    with pytest.raises(EngineClosed):
        reg.register("late", engine=StubEngine())


# ---------------------------------------------------------------- WFQ --
def test_wfq_clamps_at_saturation_only():
    shed0 = monitor.get_stat("registry.wfq_shed") or 0
    reg = ModelRegistry(max_inflight=8)
    a, b = StubEngine(), StubEngine()
    reg.register("alpha", engine=a, weight=3.0)
    reg.register("beta", engine=b, weight=1.0)
    try:
        # weights 3:1 over a pool of 8 -> shares 6 and 2
        for _ in range(6):
            reg.infer("alpha", [1])
        for _ in range(2):
            reg.infer("beta", [1])
        # saturated: both models sit exactly at share -> both shed
        with pytest.raises(QueueFull):
            reg.infer("alpha", [1])
        with pytest.raises(QueueFull):
            reg.infer("beta", [1])
        assert (monitor.get_stat("registry.wfq_shed") or 0) - shed0 == 2
        # release one slot -> below saturation the share does NOT bind
        # (work-conserving): beta admits beyond its share of 2
        a.pending[0].set_result("ok")
        _wait(lambda: reg.stats()["inflight"]["alpha"] == 5)
        reg.infer("beta", [1])
        assert reg.stats()["inflight"]["beta"] == 3
        a.release_all()
        b.release_all()
        _wait(lambda: sum(reg.stats()["inflight"].values()) == 0)
    finally:
        reg.close()


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, "condition never settled"
        time.sleep(0.01)


# -------------------------------------------------------------- quota --
def test_tenant_quota_token_bucket():
    reg = ModelRegistry()
    a = StubEngine()
    reg.register("alpha", engine=a)
    reg.set_quota("t1", rate=0.1, burst=2)
    try:
        reg.infer("alpha", [1], tenant="t1")
        reg.infer("alpha", [1], tenant="t1")
        with pytest.raises(QuotaExceeded, match="retry in"):
            reg.infer("alpha", [1], tenant="t1")
        # the quota is per-tenant, not per-model-wide
        reg.infer("alpha", [1], tenant="t2")
        reg.infer("alpha", [1])                  # anonymous unaffected
        reg.clear_quota("t1")
        reg.infer("alpha", [1], tenant="t1")
        a.release_all()
    finally:
        reg.close()


# ------------------------------------------------------------- unload --
def test_unload_drains_resolves_futures_cleans_aliases():
    reg = ModelRegistry()
    b = StubEngine()
    reg.register("beta", engine=b, aliases=["prod"])
    try:
        f = reg.infer("beta", [1])
        summary = reg.unload("beta")
        assert summary["engine_drained"] is True
        assert f.result(1) == "ok"              # in-flight NOT stranded
        assert b.closed
        with pytest.raises(UnknownModel):
            reg.resolve("beta")
        with pytest.raises(UnknownModel):       # alias went with it
            reg.resolve("prod")
    finally:
        reg.close()


# --------------------------------------------------------- elasticity --
def test_elasticity_deterministic_scale_shed_recover():
    reg = ModelRegistry()
    a = StubEngine()
    reg.register("el-alpha", engine=a)
    scales = []
    ctl = ElasticityController(
        reg, scaler=lambda name, n: scales.append((name, n)),
        objective_ms=50.0, window=5.0, min_count=1,
        max_replicas=2, breach_polls=2, clear_polls=2, cooldown_s=0.0)
    stat = "serving.engine.el-alpha.latency_ms"
    try:
        now = 1000.0
        for _ in range(40):
            monitor.stat_observe(stat, 500.0)
        ctl.poll(now=now)                        # baseline snapshot
        for _ in range(5):                       # sustained burn
            for _ in range(40):
                monitor.stat_observe(stat, 500.0)
            now += 5.0
            r = ctl.poll(now=now)
        entry = reg.resolve("el-alpha")
        assert ("el-alpha", 2) in scales, (scales, r)
        assert entry.shedding, r                 # at max and burning
        with pytest.raises(QueueFull, match="shedding"):
            reg.infer("el-alpha", [1])
        for _ in range(6):                       # burn clears
            for _ in range(40):
                monitor.stat_observe(stat, 1.0)
            now += 5.0
            r = ctl.poll(now=now)
        assert not entry.shedding, r
        assert ("el-alpha", 1) in scales, scales
        reg.infer("el-alpha", [1])               # admits again
        a.release_all()
        st = ctl.status()
        assert st["el-alpha"]["desired"] == 1
    finally:
        reg.close()


# ----------------------------------- concurrent mutation under fire --
@pytest.fixture(scope="module")
def dyadic_artifacts(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("registry_models")
    prefixes = {}
    for name, seed, scale in (("a", 7, 1.0), ("b", 11, 0.5)):
        paddle.seed(seed)
        model = make_dyadic_model(in_dim=8, hidden=16, out_dim=4)
        for p in model.parameters():
            p.set_value(p.numpy() * scale)
        prefix = str(tmp / f"m_{name}")
        jit.save(model, prefix,
                 input_spec=[InputSpec([None, 8], "float32")])
        prefixes[name] = prefix
    return prefixes


def test_concurrent_mutation_under_traffic(dyadic_artifacts):
    """Satellite (c): unload/reload + alias flip while traffic is in
    flight.  Dyadic weights make every successful response bitwise-
    checkable; the drain contract means the only acceptable failures
    are clean UnknownModel/EngineClosed in the mutation window."""
    rng = np.random.RandomState(29)
    reqs = [(rng.randint(-8, 9, (rng.randint(1, 4), 8)) / 4.0)
            .astype(np.float32) for _ in range(8)]
    preds = {k: inference.create_predictor(inference.Config(p))
             for k, p in dyadic_artifacts.items()}
    refs = {k: [np.asarray(p.run([x])[0]) for x in reqs]
            for k, p in preds.items()}
    prompts = [rng.randint(0, 32, rng.randint(1, 7)).tolist()
               for _ in range(3)]
    ref_gen = serving.GenerationEngine(make_dyadic_lm(), num_slots=4,
                                       page_size=4, max_context=64)
    ref_gen.warmup()
    gen_refs = [ref_gen.generate_sync(prompts[i], timeout=60,
                                      max_new_tokens=4,
                                      temperature=0.7, seed=i)
                for i in range(len(prompts))]
    ref_gen.close()

    reg = ModelRegistry(max_inflight=64)
    eng_a = serving.InferenceEngine(preds["a"], max_batch_size=8,
                                    batch_timeout_ms=2.0,
                                    max_queue=256, name="mutA")
    eng_a.warmup()
    gen_a = serving.GenerationEngine(make_dyadic_lm(), num_slots=4,
                                     page_size=4, max_context=64,
                                     max_queue=256, name="mutA")
    gen_a.warmup()
    reg.register("mutA", engine=eng_a, generation=gen_a, weight=2.0)
    eng_b = serving.InferenceEngine(preds["b"], max_batch_size=8,
                                    batch_timeout_ms=2.0,
                                    max_queue=256, name="mutB")
    eng_b.warmup()
    reg.register("mutB", engine=eng_b, aliases=["prod"])

    stop = threading.Event()
    a_out, b_out, g_out = [], [], []

    def a_client():
        k = 0
        while not stop.is_set():
            i = k % len(reqs)
            k += 1
            try:
                got = reg.infer_sync("mutA", [reqs[i]], timeout=30)
                a_out.append((i, np.asarray(got[0], np.float32)))
            except Exception as e:  # noqa: BLE001 - gated below
                a_out.append((i, e))

    def b_client():
        k = 0
        while not stop.is_set():
            i = k % len(reqs)
            k += 1
            try:
                got = reg.infer_sync("mutB", [reqs[i]], timeout=30)
                b_out.append((i, np.asarray(got[0], np.float32)))
            except Exception as e:  # noqa: BLE001 - gated below
                b_out.append((i, e))
            time.sleep(0.005)

    def g_client():
        k = 0
        while not stop.is_set():
            i = k % len(prompts)
            k += 1
            try:
                s = reg.generate("mutA", prompts[i], max_new_tokens=4,
                                 temperature=0.7, seed=i)
                g_out.append((i, s.result(timeout=60)))
            except Exception as e:  # noqa: BLE001 - gated below
                g_out.append((i, e))

    threads = [threading.Thread(target=f, daemon=True)
               for f in (a_client, b_client, g_client)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.3)
        # mutation 1: canary alias flip under fire
        reg.alias("prod", "mutA")
        assert reg.resolve("prod").name == "mutA"
        # mutation 2: unload mutB mid-traffic, then reload it
        summary = reg.unload("mutB", timeout=30)
        assert summary["engine_drained"] is True
        window_end = len(b_out)
        reg.load("mutB", dyadic_artifacts["b"], warmup=True,
                 engine_kwargs={"max_batch_size": 8,
                                "batch_timeout_ms": 2.0,
                                "max_queue": 256})
        time.sleep(0.3)                          # fire on the reload
    finally:
        stop.set()
        for t in threads:
            t.join(30)

    assert len(a_out) >= 5 and len(g_out) >= 1, (len(a_out), len(g_out))
    for i, res in a_out:
        assert not isinstance(res, Exception), \
            f"mutA request {i} failed under mutation: {res!r}"
        np.testing.assert_array_equal(res, refs["a"][i])
    clean = (UnknownModel, EngineClosed)
    failures = [r for _, r in b_out if isinstance(r, Exception)]
    assert all(isinstance(r, clean) for r in failures), failures[:3]
    for i, res in b_out:
        if not isinstance(res, Exception):
            np.testing.assert_array_equal(res, refs["b"][i])
    post_reload = [r for _, r in b_out[window_end:]
                   if not isinstance(r, Exception)]
    assert post_reload, "no successful mutB traffic after the reload"
    for i, res in g_out:
        assert not isinstance(res, Exception), \
            f"generation {i} failed under mutation: {res!r}"
        assert list(res) == list(gen_refs[i]), \
            f"generation {i} not bitwise vs serial reference"

    # teardown contracts: pages reclaimed, nothing stranded
    summary_a = reg.unload("mutA", timeout=60)
    assert summary_a["pages_reclaimed"] is True, summary_a
    assert eng_a.stats()["counters"].get("closed_stranded", 0) == 0
    gc = gen_a.stats()["counters"]
    assert gc["pages_allocated"] == gc["pages_freed"], gc
    assert eng_a.stats()["recompiles_after_warmup"] == 0
    reg.close(timeout=30.0)
