"""Multiprocess DataLoader (VERDICT r4 #6) + distributed global shuffle.

Reference: fluid/reader.py:91-149 (worker processes + shared-memory
tensors + SIGCHLD cleanup), framework/data_set.h:111 (GlobalShuffle).
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import BatchSampler, DataLoader, Dataset, \
    DistributedBatchSampler


class _ArrayDs(Dataset):
    def __init__(self, n=64, d=8):
        self.x = np.arange(n * d, dtype=np.float32).reshape(n, d)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], np.int64(i)


class _SlowPythonDs(Dataset):
    """GIL-bound __getitem__: pure-Python work that threads cannot
    parallelise but processes can."""

    def __init__(self, n=32, work=1500000):
        self.n = n
        self.work = work

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        acc = 0
        for k in range(self.work):       # deliberately GIL-bound
            acc += (i * k) % 7
        return np.asarray([float(acc), float(i)], np.float32)


class _FailingDs(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at 5")
        return np.zeros(2, np.float32)


def _collect(loader):
    out = []
    for xb, ib in loader:
        out.append((np.asarray(xb.data), np.asarray(ib.data)))
    return out


def test_mp_loader_matches_sync_loader():
    ds = _ArrayDs()
    sync = _collect(DataLoader(ds, batch_size=16, num_workers=0))
    mp = _collect(DataLoader(ds, batch_size=16, num_workers=3,
                             use_shared_memory=True))
    assert len(sync) == len(mp) == 4
    for (xs, is_), (xm, im) in zip(sync, mp):
        np.testing.assert_allclose(xs, xm)
        np.testing.assert_array_equal(is_, im)


def test_mp_loader_beats_thread_pool_on_python_transforms():
    """The whole point of process workers (reference reader.py:91): a
    GIL-bound transform must scale with processes, not threads.  Workers
    are persistent across epochs, so epoch 1 pays the forkserver start
    and the steady state (epoch 2+) is what training sees — that is what
    gets timed."""
    ds = _SlowPythonDs()

    def timed(**kw):
        loader = DataLoader(ds, batch_size=4, **kw)
        assert sum(1 for _ in loader) == 8     # epoch 1: pool warm-up
        t0 = time.perf_counter()
        n = sum(1 for _ in loader)             # epoch 2: steady state
        dt = time.perf_counter() - t0
        assert n == 8
        return dt

    import os
    t_threads = timed(num_workers=4, use_shared_memory=False)
    t_procs = timed(num_workers=4, use_shared_memory=True)
    if (os.cpu_count() or 1) >= 3:
        # require a decisive win (2x in VERDICT; CI slack at 1.5x)
        assert t_procs < t_threads / 1.5, (t_procs, t_threads)
    else:
        # single-core machine (this sandbox has nproc=1): no parallelism
        # exists for EITHER backend; assert the process path at least
        # does not regress materially at steady state
        assert t_procs < t_threads * 1.3, (t_procs, t_threads)


def test_mp_loader_surfaces_worker_errors():
    ds = _FailingDs()
    loader = DataLoader(ds, batch_size=4, num_workers=2,
                        use_shared_memory=True)
    with pytest.raises(RuntimeError, match="boom at 5"):
        list(loader)


def _dict_collate(samples):
    xs = np.stack([s[0] for s in samples])
    return {"x2": xs * 2.0, "n": np.int64(len(samples))}


def test_mp_loader_custom_collate():
    ds = _ArrayDs(n=8)
    loader = DataLoader(ds, batch_size=4, num_workers=2,
                        collate_fn=_dict_collate, use_shared_memory=True)
    got = list(loader)
    assert len(got) == 2
    np.testing.assert_allclose(np.asarray(got[0]["x2"].data), ds.x[:4] * 2)
    assert int(got[0]["n"].data) == 4


def test_distributed_global_shuffle():
    """DistributedBatchSampler(shuffle=True) is the in-memory GlobalShuffle
    (data_set.h:111): one epoch-seeded GLOBAL permutation, then the rank
    shard — so samples migrate across ranks between epochs."""
    ds = _ArrayDs(n=32)
    per_epoch_assignment = {}
    for epoch in (0, 1):
        owners = {}
        for rank in range(4):
            s = DistributedBatchSampler(ds, batch_size=4, num_replicas=4,
                                        rank=rank, shuffle=True)
            s.set_epoch(epoch)
            for batch in s:
                for idx in batch:
                    owners[idx] = rank
        assert len(owners) == 32          # full cover, no dup loss
        per_epoch_assignment[epoch] = owners
    moved = sum(per_epoch_assignment[0][i] != per_epoch_assignment[1][i]
                for i in range(32))
    assert moved > 8, f"only {moved}/32 samples changed rank across epochs"
    # and all ranks agree on the permutation (same seed -> disjoint shards)
    all_idx = []
    for rank in range(4):
        s = DistributedBatchSampler(ds, batch_size=4, num_replicas=4,
                                    rank=rank, shuffle=True)
        s.set_epoch(3)
        all_idx += [i for b in s for i in b]
    assert sorted(all_idx) == list(range(32))
