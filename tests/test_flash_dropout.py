"""In-kernel flash-attention dropout tests (reference analog: the fused
attention dropout path, fused_attention_op.cu).  The Pallas TPU PRNG has
no CPU lowering, so these run on real TPU only (the driver's bench
exercises them every round); CPU CI covers the p=0 path via
test_pallas.py."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.flash_attention import (flash_attention,
                                                   flash_attention_supported)

TPU = jax.default_backend() == "tpu"
pytestmark = pytest.mark.skipif(not TPU, reason="Pallas TPU PRNG is "
                                "TPU-only (no interpret lowering)")


def _qkv(L=256):
    ks = jax.random.split(jax.random.key(0), 3)
    return [jax.random.normal(k, (2, L, 2, 64), jnp.float32) for k in ks]


def test_p0_with_seed_matches_no_dropout_exactly():
    q, k, v = _qkv()
    base = np.asarray(flash_attention(q, k, v, causal=True))
    z = np.asarray(flash_attention(q, k, v, causal=True, dropout_p=0.0,
                                   seed=jnp.ones((1, 1), jnp.int32)))
    np.testing.assert_array_equal(z, base)


def test_deterministic_per_seed_and_varies_across_seeds():
    q, k, v = _qkv()
    f = lambda s: np.asarray(flash_attention(
        q, k, v, causal=True, dropout_p=0.2,
        seed=jnp.full((1, 1), s, jnp.int32)))
    a, b, c = f(7), f(7), f(8)
    np.testing.assert_array_equal(a, b)
    assert np.abs(a - c).max() > 1e-4


def test_expectation_unbiased():
    q, k, v = _qkv(128)
    base = np.asarray(flash_attention(q, k, v, causal=True))
    g = jax.jit(lambda s: flash_attention(q, k, v, causal=True,
                                          dropout_p=0.3, seed=s))
    acc = np.zeros_like(base)
    S = 96
    for i in range(S):
        acc += np.asarray(g(jnp.full((1, 1), 100 + i, jnp.int32)))
    rel = np.abs(acc / S - base).mean() / np.abs(base).mean()
    assert rel < 0.12, rel  # ~1/sqrt(S) sampling noise


@pytest.mark.parametrize("wrt", [0, 1, 2])
def test_custom_vjp_matches_finite_difference(wrt):
    qkv = _qkv(128)
    seed = jnp.full((1, 1), 42, jnp.int32)

    def f(x):
        args = list(qkv)
        args[wrt] = x
        return jnp.sum(flash_attention(*args, causal=True, dropout_p=0.25,
                                       seed=seed) ** 2)

    x0 = qkv[wrt]
    g = jax.grad(f)(x0)
    d = jax.random.normal(jax.random.key(9), x0.shape, jnp.float32)
    eps = 1e-3
    num = (float(f(x0 + eps * d)) - float(f(x0 - eps * d))) / (2 * eps)
    ana = float(jnp.vdot(g, d))
    assert abs(num - ana) / max(abs(num), 1e-6) < 2e-2, (num, ana)


def test_supported_thresholds_differ_for_dropout():
    # no-dropout threshold is 1024; dropout path kicks in at 512
    shp = (2, 512, 4, 64)
    assert not flash_attention_supported(shp, shp, jnp.bfloat16, None, 0.0)
    assert flash_attention_supported(shp, shp, jnp.bfloat16, None, 0.1)


def test_dropout_p1_drops_everything():
    q, k, v = _qkv(128)
    out = np.asarray(flash_attention(q, k, v, causal=True, dropout_p=1.0,
                                     seed=jnp.ones((1, 1), jnp.int32)))
    assert np.abs(out).max() == 0.0
