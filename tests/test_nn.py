"""nn.Layer system + layer correctness tests (modelled on the reference's
test_layers.py / per-op OpTest suites)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


def test_linear_matches_numpy():
    l = nn.Linear(4, 3)
    x = paddle.randn([5, 4])
    y = l(x)
    expect = x.numpy() @ l.weight.numpy() + l.bias.numpy()
    np.testing.assert_allclose(y.numpy(), expect, rtol=1e-5)


def test_layer_registry_and_state_dict():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 8)
            self.fc2 = nn.Linear(8, 2)
            self.act = nn.ReLU()

        def forward(self, x):
            return self.fc2(self.act(self.fc1(x)))

    net = Net()
    names = [n for n, _ in net.named_parameters()]
    assert names == ["fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"]
    sd = net.state_dict()
    assert set(sd) == set(names)

    net2 = Net()
    net2.set_state_dict(sd)
    for (n1, p1), (n2, p2) in zip(net.named_parameters(),
                                  net2.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy())


def test_state_dict_save_load_roundtrip(tmp_path):
    net = nn.Sequential(nn.Linear(3, 4), nn.ReLU(), nn.Linear(4, 2))
    path = str(tmp_path / "model.pdparams")
    paddle.save(net.state_dict(), path)
    net2 = nn.Sequential(nn.Linear(3, 4), nn.ReLU(), nn.Linear(4, 2))
    net2.set_state_dict(paddle.load(path))
    x = paddle.randn([2, 3])
    np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(), rtol=1e-6)


def test_conv2d_shape_and_grad():
    conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
    x = paddle.randn([2, 3, 16, 16])
    x.stop_gradient = False
    y = conv(x)
    assert y.shape == [2, 8, 8, 8]
    y.sum().backward()
    assert x.grad is not None and conv.weight.grad is not None
    assert conv.weight.grad.shape == [8, 3, 3, 3]


def test_conv2d_matches_manual():
    # 1x1 conv == matmul over channels
    conv = nn.Conv2D(4, 2, 1, bias_attr=False)
    x = paddle.randn([1, 4, 5, 5])
    y = conv(x)
    w = conv.weight.numpy().reshape(2, 4)
    expect = np.einsum("oc,nchw->nohw", w, x.numpy())
    np.testing.assert_allclose(y.numpy(), expect, rtol=1e-4, atol=1e-5)


def test_depthwise_and_grouped_conv():
    conv = nn.Conv2D(4, 4, 3, groups=4, padding=1)
    y = conv(paddle.randn([1, 4, 8, 8]))
    assert y.shape == [1, 4, 8, 8]
    assert conv.weight.shape == [4, 1, 3, 3]


def test_conv2d_transpose():
    convt = nn.Conv2DTranspose(3, 6, 4, stride=2, padding=1)
    y = convt(paddle.randn([1, 3, 8, 8]))
    assert y.shape == [1, 6, 16, 16]


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(3, momentum=0.5)
    x = paddle.randn([8, 3, 4, 4]) * 2 + 1
    bn.train()
    y = bn(x)
    # normalized output: near zero mean / unit var per channel
    yn = y.numpy()
    assert abs(yn.mean()) < 1e-5
    np.testing.assert_allclose(yn.var(axis=(0, 2, 3)), np.ones(3), rtol=1e-3)
    # running stats moved toward batch stats
    assert float(bn._mean.abs().sum()) > 0
    bn.eval()
    y2 = bn(x)
    assert not np.allclose(y2.numpy(), yn)


def test_layernorm():
    ln = nn.LayerNorm(8)
    x = paddle.randn([4, 8]) * 3 + 2
    y = ln(x).numpy()
    np.testing.assert_allclose(y.mean(axis=-1), np.zeros(4), atol=1e-5)
    np.testing.assert_allclose(y.var(axis=-1), np.ones(4), rtol=1e-3)


def test_groupnorm():
    gn = nn.GroupNorm(2, 4)
    y = gn(paddle.randn([2, 4, 4, 4]))
    assert y.shape == [2, 4, 4, 4]


def test_embedding_padding_idx():
    emb = nn.Embedding(10, 4, padding_idx=0)
    ids = paddle.to_tensor(np.array([[0, 1], [2, 0]]))
    out = emb(ids)
    assert out.shape == [2, 2, 4]
    np.testing.assert_allclose(out.numpy()[0, 0], np.zeros(4))
    np.testing.assert_allclose(out.numpy()[1, 1], np.zeros(4))


def test_dropout_train_eval():
    d = nn.Dropout(0.5)
    x = paddle.ones([100, 100])
    d.train()
    y = d(x)
    frac = (y.numpy() == 0).mean()
    assert 0.4 < frac < 0.6
    # upscale keeps expectation
    assert abs(y.numpy().mean() - 1.0) < 0.05
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), x.numpy())


def test_pools():
    x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    mp = nn.MaxPool2D(2, 2)(x)
    np.testing.assert_allclose(mp.numpy()[0, 0], [[5, 7], [13, 15]])
    ap = nn.AvgPool2D(2, 2)(x)
    np.testing.assert_allclose(ap.numpy()[0, 0], [[2.5, 4.5], [10.5, 12.5]])
    aap = nn.AdaptiveAvgPool2D(1)(x)
    np.testing.assert_allclose(aap.numpy()[0, 0], [[7.5]])
    amp = nn.AdaptiveMaxPool2D(2)(x)
    np.testing.assert_allclose(amp.numpy()[0, 0], [[5, 7], [13, 15]])


def test_activations_values():
    x = paddle.to_tensor([-2.0, -0.5, 0.0, 0.5, 2.0])
    np.testing.assert_allclose(nn.ReLU()(x).numpy(), [0, 0, 0, 0.5, 2])
    np.testing.assert_allclose(
        nn.LeakyReLU(0.1)(x).numpy(), [-0.2, -0.05, 0, 0.5, 2], rtol=1e-6)
    np.testing.assert_allclose(
        nn.Sigmoid()(x).numpy(), 1 / (1 + np.exp(-x.numpy())), rtol=1e-5)
    s = nn.Softmax()(x).numpy()
    np.testing.assert_allclose(s.sum(), 1.0, rtol=1e-6)
    g = F.gelu(x).numpy()
    assert g[2] == 0 and g[4] > 1.9


def test_sequential_and_layerlist():
    seq = nn.Sequential(nn.Linear(2, 3), nn.ReLU(), nn.Linear(3, 1))
    assert len(seq) == 3
    assert isinstance(seq[0], nn.Linear)
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    assert len(list(ll.parameters())) == 8


def test_cross_entropy_matches_manual():
    logits = paddle.randn([4, 5])
    labels = paddle.to_tensor(np.array([0, 2, 4, 1]))
    loss = F.cross_entropy(logits, labels)
    lp = logits.numpy() - logits.numpy().max(axis=1, keepdims=True)
    lp = lp - np.log(np.exp(lp).sum(axis=1, keepdims=True))
    expect = -lp[np.arange(4), labels.numpy()].mean()
    np.testing.assert_allclose(float(loss), expect, rtol=1e-5)


def test_cross_entropy_ignore_index():
    logits = paddle.randn([4, 5])
    labels = paddle.to_tensor(np.array([0, -100, 4, -100]))
    loss = F.cross_entropy(logits, labels, ignore_index=-100)
    lp = logits.numpy() - logits.numpy().max(axis=1, keepdims=True)
    lp = lp - np.log(np.exp(lp).sum(axis=1, keepdims=True))
    expect = -(lp[0, 0] + lp[2, 4]) / 2
    np.testing.assert_allclose(float(loss), expect, rtol=1e-5)


def test_losses():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([1.5, 2.0, 2.0])
    np.testing.assert_allclose(float(nn.MSELoss()(a, b)),
                               np.mean([0.25, 0, 1]), rtol=1e-6)
    np.testing.assert_allclose(float(nn.L1Loss()(a, b)),
                               np.mean([0.5, 0, 1]), rtol=1e-6)
    p = paddle.to_tensor([0.9, 0.1])
    t = paddle.to_tensor([1.0, 0.0])
    np.testing.assert_allclose(float(nn.BCELoss()(p, t)),
                               -np.mean([np.log(0.9), np.log(0.9)]),
                               rtol=1e-4)
    z = paddle.to_tensor([2.0, -1.0])
    bwl = float(nn.BCEWithLogitsLoss()(z, t))
    expect = np.mean([np.log1p(np.exp(-2.0)), np.log1p(np.exp(-1.0))])
    np.testing.assert_allclose(bwl, expect, rtol=1e-5)


def test_multihead_attention_shapes_and_grad():
    mha = nn.MultiHeadAttention(32, 4)
    q = paddle.randn([2, 6, 32])
    out = mha(q, q, q)
    assert out.shape == [2, 6, 32]
    out.sum().backward()
    assert mha.q_proj.weight.grad is not None


def test_mha_causal_mask():
    mha = nn.MultiHeadAttention(16, 2)
    mha.eval()
    x = paddle.randn([1, 4, 16])
    L = 4
    mask = paddle.to_tensor(np.tril(np.ones((1, 1, L, L), bool)))
    y_masked = mha(x, x, x, attn_mask=mask)
    # position 0 attends only to itself; change in later tokens must not
    # affect position 0 output
    x2 = x.clone()
    x2[0, 3] = paddle.randn([16])
    y2 = mha(x2, x2, x2, attn_mask=mask)
    np.testing.assert_allclose(y_masked.numpy()[0, 0], y2.numpy()[0, 0],
                               rtol=2e-3, atol=2e-5)


def test_transformer_encoder_decoder():
    model = nn.Transformer(d_model=32, nhead=4, num_encoder_layers=2,
                           num_decoder_layers=2, dim_feedforward=64)
    src = paddle.randn([2, 5, 32])
    tgt = paddle.randn([2, 3, 32])
    out = model(src, tgt)
    assert out.shape == [2, 3, 32]


def test_lstm_and_gru():
    lstm = nn.LSTM(4, 8)
    x = paddle.randn([2, 6, 4])
    y, (h, c) = lstm(x)
    assert y.shape == [2, 6, 8]
    assert h.shape == [1, 2, 8] and c.shape == [1, 2, 8]
    # final hidden equals last output step for unidirectional lstm
    np.testing.assert_allclose(y.numpy()[:, -1], h.numpy()[0], rtol=1e-5)

    gru = nn.GRU(4, 8, direction="bidirect")
    y2, h2 = gru(x)
    assert y2.shape == [2, 6, 16]
    assert h2.shape == [2, 2, 8]
    y2.sum().backward()
    assert gru.weight_ih_l0.grad is not None


def test_lstm_cell_vs_layer():
    cell = nn.LSTMCell(4, 8)
    rnn = nn.RNN(cell)
    x = paddle.randn([2, 5, 4])
    y, state = rnn(x)
    assert y.shape == [2, 5, 8]


def test_train_eval_propagates():
    net = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
    net.eval()
    assert not net[1].training
    net.train()
    assert net[1].training


def test_interpolate():
    x = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
    y = F.interpolate(x, scale_factor=2, mode="nearest")
    assert y.shape == [1, 1, 4, 4]
    np.testing.assert_allclose(y.numpy()[0, 0, :2, :2], 0)
    b = F.interpolate(x, size=[4, 4], mode="bilinear")
    assert b.shape == [1, 1, 4, 4]


def test_forward_hooks():
    l = nn.Linear(2, 2)
    calls = []
    h = l.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
    l(paddle.randn([1, 2]))
    assert calls == [1]
    h.remove()
    l(paddle.randn([1, 2]))
    assert calls == [1]


def test_cross_entropy_weighted_mean_semantics():
    # ADVICE r1: weighted mean divides by the sum of selected class weights.
    logits = paddle.to_tensor(np.array(
        [[2.0, 1.0, 0.1], [0.5, 2.5, 0.3]], np.float32))
    label = paddle.to_tensor(np.array([0, 1], np.int64))
    weight = paddle.to_tensor(np.array([0.5, 2.0, 1.0], np.float32))
    out = F.cross_entropy(logits, label, weight=weight, reduction="mean")
    logp = np.log(np.exp(np.asarray(logits.data))
                  / np.exp(np.asarray(logits.data)).sum(-1, keepdims=True))
    per = -logp[np.arange(2), [0, 1]] * np.array([0.5, 2.0])
    expect = per.sum() / (0.5 + 2.0)
    np.testing.assert_allclose(float(out), expect, rtol=1e-5)


def test_sublayer_non_persistable_buffer_excluded():
    # ADVICE r1: sublayer non-persistable buffers must not hit state_dict.
    class Sub(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.register_buffer("scratch", paddle.to_tensor(
                np.zeros(2, np.float32)), persistable=False)
            self.register_buffer("kept", paddle.to_tensor(
                np.ones(2, np.float32)), persistable=True)

    class Top(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.sub = Sub()
            self.register_buffer("kept", paddle.to_tensor(
                np.full(2, 2.0, np.float32)), persistable=True)

    sd = Top().state_dict()
    assert "sub.scratch" not in sd
    assert "sub.kept" in sd and "kept" in sd


def test_linear_cross_entropy_matches_unfused():
    import numpy as np
    paddle.seed(33)
    T, H, V = 32, 16, 50
    h = paddle.randn([T, H]); h.stop_gradient = False
    w = paddle.randn([H, V]); w.stop_gradient = False
    b = paddle.zeros([V]); b.stop_gradient = False
    lab = paddle.to_tensor(np.random.RandomState(0).randint(0, V, (T,)))

    fused = F.linear_cross_entropy(h, w, b, lab, chunk=8)
    ref = F.cross_entropy(h.matmul(w) + b, lab)
    np.testing.assert_allclose(float(fused), float(ref), rtol=1e-5)

    fused.backward()
    gh, gw = h.grad.numpy().copy(), w.grad.numpy().copy()
    h2 = h.detach(); h2.stop_gradient = False
    w2 = w.detach(); w2.stop_gradient = False
    b2 = b.detach(); b2.stop_gradient = False
    F.cross_entropy(h2.matmul(w2) + b2, lab).backward()
    np.testing.assert_allclose(gh, h2.grad.numpy(), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(gw, w2.grad.numpy(), rtol=1e-4, atol=1e-6)


def test_linear_cross_entropy_ignore_index():
    import numpy as np
    paddle.seed(34)
    h = paddle.randn([8, 4])
    w = paddle.randn([4, 10])
    b = paddle.zeros([10])
    lab = np.random.RandomState(1).randint(0, 10, (8,))
    lab[::2] = -100
    fused = F.linear_cross_entropy(h, w, b, paddle.to_tensor(lab), chunk=4)
    ref = F.cross_entropy(h.matmul(w) + b, paddle.to_tensor(lab),
                          ignore_index=-100)
    np.testing.assert_allclose(float(fused), float(ref), rtol=1e-5)
