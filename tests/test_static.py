"""paddle.static facade tests (reference analog: test_executor_*.py,
test_program.py, test_inference_model_io.py): a reference-style static
script must build a Program through the shared dispatch point, train via
Executor.run, and round-trip through save/load_inference_model."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, optimizer
from paddle_tpu.vision.models import LeNet


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()
    paddle.static.reset_default_programs()


def test_program_records_ops():
    with paddle.static.program_guard(paddle.static.Program()) as main:
        x = paddle.static.data("x", [None, 4], "float32")
        y = (x * 2.0 + 1.0).sum()
    assert len(main.nodes) >= 2
    assert isinstance(y, paddle.static.Variable)
    assert "x" in main.feed_vars


def test_executor_forward_fetch():
    with paddle.static.program_guard(paddle.static.Program()) as main:
        x = paddle.static.data("x", [None, 3], "float32")
        out = F.relu(x) * 3.0
    exe = paddle.static.Executor()
    arr = np.array([[-1.0, 0.5, 2.0]], np.float32)
    res, = exe.run(main, feed={"x": arr}, fetch_list=[out])
    np.testing.assert_allclose(res, np.maximum(arr, 0) * 3.0)


def test_executor_dynamic_batch():
    """None dims: the same Program serves any batch size (recompiles per
    shape, like the reference's feed shape handling)."""
    with paddle.static.program_guard(paddle.static.Program()) as main:
        x = paddle.static.data("x", [None, 2], "float32")
        out = x.sum(axis=1)
    exe = paddle.static.Executor()
    for bs in (1, 5):
        arr = np.ones((bs, 2), np.float32)
        res, = exe.run(main, feed={"x": arr}, fetch_list=[out])
        assert res.shape == (bs,)


def test_static_lenet_trains():
    """VERDICT round-2 'done' criterion: a LeNet trains through the static
    API verbatim from a reference-style script."""
    paddle.seed(0)
    main, startup = paddle.static.Program(), paddle.static.Program()
    with paddle.static.program_guard(main, startup):
        x = paddle.static.data("x", [None, 1, 28, 28], "float32")
        y = paddle.static.data("y", [None], "int64")
        model = LeNet()
        out = model(x)
        loss = F.cross_entropy(out, y)
        opt = optimizer.Adam(learning_rate=1e-3)
        opt.minimize(loss)

    exe = paddle.static.Executor(paddle.CPUPlace)
    exe.run(startup)

    rng = np.random.RandomState(0)
    xs = rng.standard_normal((16, 1, 28, 28)).astype(np.float32)
    ys = rng.randint(0, 10, (16,)).astype(np.int64)
    losses = []
    for _ in range(30):
        lv, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_static_fc_and_minimize_sgd():
    paddle.seed(1)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 8], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        pred = paddle.static.nn.fc(x, 1)
        loss = F.mse_loss(pred, y)
        optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = paddle.static.Executor()
    rng = np.random.RandomState(1)
    w = rng.standard_normal((8, 1)).astype(np.float32)
    xs = rng.standard_normal((64, 8)).astype(np.float32)
    ys = xs @ w
    first = last = None
    for _ in range(60):
        lv, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        first = first if first is not None else float(lv)
        last = float(lv)
    assert last < first * 0.1, (first, last)


def test_static_cond_records():
    with paddle.static.program_guard(paddle.static.Program()) as main:
        x = paddle.static.data("x", [2], "float32")
        out = paddle.static.nn.cond(x.sum() > 0, lambda: x * 2,
                                    lambda: x - 1)
    exe = paddle.static.Executor()
    res, = exe.run(main, feed={"x": np.array([1.0, 2.0], np.float32)},
                   fetch_list=[out])
    np.testing.assert_allclose(res, [2.0, 4.0])
    res, = exe.run(main, feed={"x": np.array([-1.0, -2.0], np.float32)},
                   fetch_list=[out])
    np.testing.assert_allclose(res, [-2.0, -3.0])


def test_static_param_inside_cond_branch_trains():
    """Params referenced only inside a control-flow branch must be seen by
    the Program and updated by minimize."""
    paddle.seed(4)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 4], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        lin = nn.Linear(4, 1)
        pred = paddle.static.nn.cond(x.sum() > -1e9, lambda: lin(x),
                                     lambda: x.sum(axis=1, keepdim=True))
        loss = F.mse_loss(pred, y)
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    assert lin.weight in main.parameters()
    exe = paddle.static.Executor()
    rng = np.random.RandomState(4)
    xs = rng.standard_normal((32, 4)).astype(np.float32)
    ys = xs @ rng.standard_normal((4, 1)).astype(np.float32)
    l0 = float(exe.run(main, feed={"x": xs, "y": ys},
                       fetch_list=[loss])[0])
    for _ in range(40):
        lv, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    assert float(lv) < l0 * 0.2, (l0, float(lv))


def test_static_stop_gradient_respected():
    paddle.seed(5)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 4], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        lin = nn.Linear(4, 1)
        lin.weight.stop_gradient = True
        lin.weight.trainable = False
        loss = F.mse_loss(lin(x), y)
        optimizer.SGD(learning_rate=0.5).minimize(loss)
    frozen = lin.weight.numpy().copy()
    exe = paddle.static.Executor()
    rng = np.random.RandomState(5)
    xs = rng.standard_normal((8, 4)).astype(np.float32)
    ys = rng.standard_normal((8, 1)).astype(np.float32)
    exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    np.testing.assert_array_equal(lin.weight.numpy(), frozen)
    assert not np.array_equal(lin.bias.numpy(), np.zeros(1))  # bias trained


def test_static_eval_then_minimize_recompiles():
    paddle.seed(6)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 2], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        lin = nn.Linear(2, 1)
        loss = F.mse_loss(lin(x), y)
    exe = paddle.static.Executor()
    xs = np.ones((4, 2), np.float32)
    ys = np.zeros((4, 1), np.float32)
    l_eval, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    with paddle.static.program_guard(main):
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    l_train, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    np.testing.assert_allclose(l_train, l_eval, rtol=1e-6)
    l2, = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    assert float(l2) < float(l_train)


def test_python_if_on_variable_raises_loudly():
    with paddle.static.program_guard(paddle.static.Program()):
        x = paddle.static.data("x", [2], "float32")
        with pytest.raises(TypeError, match="cond"):
            if x.sum() > 0:
                pass


def test_static_while_loop_records():
    with paddle.static.program_guard(paddle.static.Program()) as main:
        x = paddle.static.data("x", [1], "float32")
        n = paddle.static.data("n", [], "int32")
        i, acc = paddle.static.nn.while_loop(
            lambda i, acc: i < n,
            lambda i, acc: (i + 1, acc * x),
            [paddle.zeros([], dtype="int32"), paddle.ones([1])])
    exe = paddle.static.Executor()
    res, = exe.run(main, feed={"x": np.array([3.0], np.float32),
                               "n": np.int32(3)}, fetch_list=[acc])
    np.testing.assert_allclose(res, [27.0])


def test_save_load_inference_model(tmp_path):
    paddle.seed(2)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 4], "float32")
        pred = paddle.static.nn.fc(x, 2, activation="relu")
    exe = paddle.static.Executor()
    arr = np.random.RandomState(3).standard_normal((5, 4)).astype(np.float32)
    want, = exe.run(main, feed={"x": arr}, fetch_list=[pred])

    prefix = os.path.join(str(tmp_path), "m")
    paddle.static.save_inference_model(prefix, [x], [pred], exe)
    prog, feed_names, fetch_names = paddle.static.load_inference_model(
        prefix, exe)
    assert feed_names == ["x"]
    got, = exe.run(prog, feed={"x": arr}, fetch_list=fetch_names)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # shape polymorphism: another batch size without re-export
    got2, = exe.run(prog, feed={"x": arr[:2]}, fetch_list=fetch_names)
    np.testing.assert_allclose(got2, want[:2], rtol=1e-5)
