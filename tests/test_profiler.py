"""paddle.profiler tests (reference analog: test_profiler.py): RecordEvent
spans, per-op host-time accounting, summary table, legacy fluid API."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, optimizer, profiler


def _steps(model, opt, n=3):
    x = paddle.randn([8, 4])
    y = paddle.randn([8, 1])
    for _ in range(n):
        loss = F.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()


def test_profiler_collects_op_stats_and_summary():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    p = profiler.Profiler(timer_only=True)
    p.start()
    with profiler.RecordEvent("train_phase"):
        _steps(model, opt)
    p.stop()

    ops = dict((n, c) for n, c, _ in p.key_averages())
    assert ops.get("linear", 0) >= 6  # 2 linears x 3 steps
    assert "relu" in ops
    text = p.summary(top_k=5)
    assert "train_phase" in text
    assert "linear" in text


def test_profiler_off_means_no_collection():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 1))
    opt = optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    p = profiler.Profiler(timer_only=True)
    _steps(model, opt)          # not started: nothing recorded
    assert p.key_averages() == []


def test_profiler_step_spans():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 1))
    opt = optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    with profiler.Profiler(timer_only=True) as p:
        for _ in range(3):
            p.step()
            _steps(model, opt, n=1)
    spans = [n for n in p._span_stats if n.startswith("ProfileStep#")]
    assert len(spans) == 3


def test_legacy_fluid_profiler_api(tmp_path):
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 1))
    opt = optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    out = str(tmp_path / "prof.txt")
    with profiler.profiler_guard(profile_path=out):
        _steps(model, opt, n=2)
    content = open(out).read()
    assert "linear" in content


def test_record_event_nests_without_profiler():
    # spans must be harmless when no profiler is active
    with profiler.RecordEvent("outer"):
        with profiler.RecordEvent("inner"):
            pass
