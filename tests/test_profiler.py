"""paddle.profiler tests (reference analog: test_profiler.py): RecordEvent
spans, per-op host-time accounting, summary table, legacy fluid API."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, optimizer, profiler


def _steps(model, opt, n=3):
    x = paddle.randn([8, 4])
    y = paddle.randn([8, 1])
    for _ in range(n):
        loss = F.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()


def test_profiler_collects_op_stats_and_summary():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    p = profiler.Profiler(timer_only=True)
    p.start()
    with profiler.RecordEvent("train_phase"):
        _steps(model, opt)
    p.stop()

    ops = dict((n, c) for n, c, _ in p.key_averages())
    assert ops.get("linear", 0) >= 6  # 2 linears x 3 steps
    assert "relu" in ops
    text = p.summary(top_k=5)
    assert "train_phase" in text
    assert "linear" in text


def test_profiler_off_means_no_collection():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 1))
    opt = optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    p = profiler.Profiler(timer_only=True)
    _steps(model, opt)          # not started: nothing recorded
    assert p.key_averages() == []


def test_profiler_step_spans():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 1))
    opt = optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    with profiler.Profiler(timer_only=True) as p:
        for _ in range(3):
            p.step()
            _steps(model, opt, n=1)
    spans = [n for n in p._span_stats if n.startswith("ProfileStep#")]
    assert len(spans) == 3


def test_legacy_fluid_profiler_api(tmp_path):
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 1))
    opt = optimizer.SGD(learning_rate=0.01, parameters=model.parameters())
    out = str(tmp_path / "prof.txt")
    with profiler.profiler_guard(profile_path=out):
        _steps(model, opt, n=2)
    content = open(out).read()
    assert "linear" in content


def test_record_event_nests_without_profiler():
    # spans must be harmless when no profiler is active
    with profiler.RecordEvent("outer"):
        with profiler.RecordEvent("inner"):
            pass


def test_trainstep_capture_produces_xla_trace_dir(tmp_path):
    """Profiler(trace_dir=...) around a TrainStep must leave a non-empty
    XLA trace directory (device/host .trace.json.gz or .xplane.pb from
    jax.profiler) alongside the host span stats (SURVEY 5.1's 'TPU
    equivalent' of the reference timeline)."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import nn, optimizer, profiler
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = TrainStep(net, lambda o, l: F.cross_entropy(o, l), opt)
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(16, 8).astype("float32"))
    y = paddle.to_tensor(rs.randint(0, 4, 16).astype("int64"))
    step(x, y)  # compile outside the capture

    trace_dir = str(tmp_path / "trace")
    p = profiler.Profiler(trace_dir=trace_dir)
    p.start()
    with profiler.RecordEvent("capture_step"):
        loss = step(x, y)
    float(loss)  # device sync inside the capture window
    p.stop()

    # host spans recorded
    assert p._span_stats["capture_step"][0] == 1
    # the XLA trace dir exists and holds real trace artifacts
    import os
    files = []
    for root, _, names in os.walk(trace_dir):
        files += [os.path.join(root, n) for n in names]
    assert files, f"no trace files under {trace_dir}"
    assert any(n.endswith((".xplane.pb", ".trace.json.gz", ".json.gz",
                           ".pb")) for n in files), files
    assert sum(os.path.getsize(f) for f in files) > 0


def test_profiler_sync_ops_mode():
    """Opt-in sync mode: per-op spans block on device completion before
    recording (accurate per-op attribution); default stays async."""
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = optimizer.SGD(learning_rate=0.01, parameters=model.parameters())

    p_async = profiler.Profiler(timer_only=True)
    assert p_async._sync_ops is False  # FLAGS_profiler_sync_ops default

    with profiler.Profiler(timer_only=True, sync_ops=True) as p:
        _steps(model, opt)
    ops = dict((n, c) for n, c, _ in p.key_averages())
    assert ops.get("linear", 0) >= 6  # stats still collected, no crash

    # flag seeds the default
    paddle.set_flags({"FLAGS_profiler_sync_ops": True})
    try:
        assert profiler.Profiler(timer_only=True)._sync_ops is True
    finally:
        paddle.set_flags({"FLAGS_profiler_sync_ops": False})
