"""CI tooling (reference: tools/parallel_UT_rule.py,
tools/check_api_compatible.py)."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


# ------------------------------------------------------------- api spec --
def test_api_spec_is_current_and_compatible():
    """The checked-in spec must match the live API (run --dump when a
    deliberate API change lands)."""
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "check_api_compatible.py")],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "API compatible" in r.stdout


def test_api_checker_detects_breaks(tmp_path):
    sys.path.insert(0, TOOLS)
    try:
        import check_api_compatible as cac
    finally:
        sys.path.remove(TOOLS)
    spec = {"m": {
        "gone": {"type": "function",
                 "sig": [{"name": "x", "kind": "POSITIONAL_OR_KEYWORD",
                          "has_default": False}]},
        "changed": {"type": "function",
                    "sig": [{"name": "a",
                             "kind": "POSITIONAL_OR_KEYWORD",
                             "has_default": False}]},
        "ok": {"type": "function", "sig": []},
    }}
    current = {"m": {
        # 'gone' removed entirely
        "changed": {"type": "function",
                    "sig": [{"name": "b",            # renamed param
                             "kind": "POSITIONAL_OR_KEYWORD",
                             "has_default": False}]},
        "ok": {"type": "function",
               "sig": [{"name": "new",               # added WITH default
                        "kind": "KEYWORD_ONLY", "has_default": True}]},
        "brand_new": {"type": "function", "sig": []},  # additions fine
    }}
    problems = cac.compare(spec, current)
    text = "\n".join(problems)
    assert "m.gone: removed" in text
    assert "parameter 'a' removed" in text
    assert "ok" not in text and "brand_new" not in text

    # a new REQUIRED parameter is a break
    current["m"]["ok"]["sig"] = [{"name": "req",
                                  "kind": "POSITIONAL_OR_KEYWORD",
                                  "has_default": False}]
    problems = cac.compare(spec, current)
    assert any("'req' has no default" in p for p in problems)


# --------------------------------------------------------- parallel UT --
def _write_suite(d, name, body):
    (d / name).write_text(body)


def test_parallel_ut_runs_shards_and_reports(tmp_path):
    _write_suite(tmp_path, "test_alpha.py",
                 "def test_a():\n    assert 1 + 1 == 2\n")
    _write_suite(tmp_path, "test_beta.py",
                 "def test_b():\n    assert True\n")
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "parallel_ut.py"),
         "-j", "2", str(tmp_path)],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK: 2 files" in r.stdout


def test_parallel_ut_detects_failure_and_retries(tmp_path):
    _write_suite(tmp_path, "test_ok.py",
                 "def test_fine():\n    assert True\n")
    _write_suite(tmp_path, "test_bad.py",
                 "def test_broken():\n    assert False\n")
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "parallel_ut.py"),
         "-j", "2", str(tmp_path)],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 1
    assert "retrying" in r.stdout           # serial flaky pass ran
    assert "test_bad.py" in r.stdout.split("FAILED")[-1]


def test_parallel_ut_flaky_passes_on_retry(tmp_path):
    # fails on first (parallel) run, passes on the serial retry
    flaky = tmp_path / "flake_marker"
    _write_suite(tmp_path, "test_flaky.py", f"""
import os
def test_flaky():
    marker = {str(flaky)!r}
    if not os.path.exists(marker):
        open(marker, "w").close()
        assert False, "first run fails"
""")
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "parallel_ut.py"),
         "-j", "1", str(tmp_path)],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout
    assert "retrying" in r.stdout


def test_parallel_ut_collect_only():
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "parallel_ut.py"),
         "--collect-only", "-j", "3"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0
    shards = [ln for ln in r.stdout.splitlines() if ln.startswith("shard")]
    assert 3 <= len(shards) <= 9  # over-partitioned for pool draining
    listed = " ".join(shards)
    assert "test_autograd.py" in listed
