"""Fault-injected robustness: retrying fs, verified checkpoints,
self-healing DataLoader, preemption-safe training.

Reference analogs: framework/io/fs.cc (hdfs retries),
fluid/incubate/checkpoint/auto_checkpoint.py (resume),
fluid/reader.py:91-149 (SIGCHLD worker death handling).  Every recovery
path here is driven by paddle_tpu.testing.fault — deterministic chaos,
not hope."""
import json
import os
import signal
import stat as stat_mod
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.testing import fault
from paddle_tpu.utils import fs, monitor
from paddle_tpu.utils.checkpoint import (CheckpointError, SnapshotStore,
                                         TrainEpochRange)


@pytest.fixture(autouse=True)
def _clean_chaos():
    """Every test starts disarmed with zeroed stats and fast backoff."""
    fault.disarm()
    monitor.stat_reset()
    old = paddle.get_flags(["fs_retry_backoff_s", "fs_retry_times",
                            "fs_retry_deadline_s",
                            "dataloader_batch_retries"])
    paddle.set_flags({"fs_retry_backoff_s": 0.001})
    yield
    fault.disarm()
    paddle.set_flags(old)


# -- fault framework ---------------------------------------------------------

def test_disarmed_point_is_noop_and_adds_no_stats():
    fault.point("fs.open_write", "/nowhere")
    fault.point("anything.at.all")
    assert not fault.is_armed()
    assert not any(k.startswith("fault.") for k in monitor.all_stats())


def test_spec_parse_count_match_exc_and_fire_stats():
    with fault.inject("fs.mv:count=2,exc=OSError,match=special"):
        f = fs.LocalFS()
        # detail doesn't contain 'special': no fire
        tmp = "/tmp/_ft_a"
        open(tmp, "wb").close()
        f.mv(tmp, "/tmp/_ft_b")
        with pytest.raises(OSError, match="injected fault"):
            open("/tmp/_ft_special", "wb").close()
            f.mv("/tmp/_ft_special", "/tmp/_ft_special2")
        assert fault.fire_count("fs.mv") == 1
    assert monitor.get_stat("fault.fired.fs.mv") == 1
    assert not fault.is_armed()          # inject() restored disarmed


def test_probability_is_seed_deterministic():
    def run(seed):
        fired = []
        with fault.inject("p.x:p=0.5", seed=seed):
            for _ in range(32):
                try:
                    fault.point("p.x")
                    fired.append(0)
                except fault.FaultInjected:
                    fired.append(1)
        return fired
    a, b, c = run(11), run(11), run(12)
    assert a == b                        # same seed -> same chaos
    assert a != c                        # different seed -> different
    assert 0 < sum(a) < 32               # actually probabilistic


def test_arm_from_flags_roundtrip():
    paddle.set_flags({"fault_spec": "flag.pt:count=1", "fault_seed": 3})
    try:
        assert fault.arm_from_flags()
        with pytest.raises(fault.FaultInjected):
            fault.point("flag.pt")
        fault.point("flag.pt")           # count exhausted
    finally:
        paddle.set_flags({"fault_spec": ""})
        fault.disarm()


# -- fs retry/backoff --------------------------------------------------------

def test_fs_flake_is_retried_then_succeeds(tmp_path):
    rfs = fs.RetryingFS(fs.LocalFS())
    p = str(tmp_path / "x.bin")
    with fault.inject("fs.open_write:count=2,exc=TransientFSError"):
        with rfs.open_write(p) as f:
            f.write(b"payload")
    assert open(p, "rb").read() == b"payload"
    assert monitor.get_stat("fs.retries") == 2
    assert monitor.get_stat("fs.gave_up") == 0


def test_exhausted_retries_surface_classified_error(tmp_path):
    paddle.set_flags({"fs_retry_times": 3})
    rfs = fs.RetryingFS(fs.LocalFS())
    with fault.inject("fs.open_write:exc=TransientFSError"):
        with pytest.raises(fs.TransientFSError):
            rfs.open_write(str(tmp_path / "y.bin"))
    assert monitor.get_stat("fs.retries") == 2   # attempts 1+2 retried
    assert monitor.get_stat("fs.gave_up") == 1


def test_permanent_error_is_not_retried(tmp_path):
    rfs = fs.RetryingFS(fs.LocalFS())
    with fault.inject("fs.open_read:exc=PermanentFSError"):
        with pytest.raises(fs.PermanentFSError):
            rfs.open_read(str(tmp_path / "absent.bin"))
    assert monitor.get_stat("fs.retries") == 0


def test_retry_deadline_bounds_wall_clock(tmp_path):
    paddle.set_flags({"fs_retry_times": 1000, "fs_retry_deadline_s": 0.2,
                      "fs_retry_backoff_s": 0.05})
    rfs = fs.RetryingFS(fs.LocalFS())
    t0 = time.monotonic()
    with fault.inject("fs.open_write:exc=TransientFSError"):
        with pytest.raises(fs.TransientFSError):
            rfs.open_write(str(tmp_path / "z.bin"))
    assert time.monotonic() - t0 < 5.0
    assert monitor.get_stat("fs.gave_up") == 1


def test_retrying_decorator():
    calls = []

    @fs.retrying("flaky_op")
    def flaky(x):
        calls.append(x)
        if len(calls) < 3:
            raise fs.TransientFSError("blip")
        return x * 2

    assert flaky(21) == 42
    assert len(calls) == 3
    assert monitor.get_stat("fs.retries") == 2


def test_error_classification():
    assert fs.is_transient(fs.TransientFSError("x"))
    assert not fs.is_transient(fs.PermanentFSError("x"))
    assert not fs.is_transient(FileNotFoundError("x"))
    assert not fs.is_transient(PermissionError("x"))
    assert fs.is_transient(ConnectionResetError("x"))
    assert fs.is_transient(TimeoutError("x"))
    assert not fs.is_transient(ValueError("x"))


# -- ShellFS against a fake hadoop CLI --------------------------------------

_FAKE_HADOOP = r"""#!/usr/bin/env bash
# fake 'hadoop fs' CLI backed by $FAKE_HDFS_ROOT; transient-failure
# injection: while .flake_count > 0 every call fails like a net blip
set -u
root="${FAKE_HDFS_ROOT:?}"
flake="$root/.flake_count"
if [ -f "$flake" ]; then
  n=$(cat "$flake")
  if [ "$n" -gt 0 ]; then
    echo $((n-1)) > "$flake"
    echo "java.net.ConnectException: Connection refused" >&2
    exit 255
  fi
fi
shift                       # 'fs'
verb="$1"; shift
map() { local p="${1#*://}"; echo "$root/$p"; }
case "$verb" in
  -cat)   cat "$(map "$1")" 2>/dev/null || {
            echo "cat: No such file or directory: $1" >&2; exit 1; };;
  -put)   shift; shift       # -f -
          dst="$(map "$1")"; mkdir -p "$(dirname "$dst")"; cat > "$dst";;
  -test)  [ -e "$(map "$2")" ];;
  -mkdir) mkdir -p "$(map "$2")";;
  -rm)    for last; do :; done; rm -rf "$(map "$last")";;
  -ls)    p="$(map "$1")"
          for f in "$p"/*; do
            [ -e "$f" ] || continue
            echo "-rw-r--r-- 1 u g 0 2024-01-01 00:00 hdfs://f/$(basename "$f")"
          done;;
  -mv)    mv "$(map "$1")" "$(map "$2")" || exit 1
          # chaos knob: rename COMMITS, then the client sees a timeout
          if [ -f "$root/.mv_commit_fail" ]; then
            rm -f "$root/.mv_commit_fail"
            echo "java.net.SocketTimeoutException: timed out" >&2
            exit 255
          fi;;
  *)      echo "unknown verb $verb" >&2; exit 2;;
esac
"""


@pytest.fixture()
def fake_hadoop(tmp_path, monkeypatch):
    root = tmp_path / "hdfs_root"
    root.mkdir()
    cli = tmp_path / "hadoop"
    cli.write_text(_FAKE_HADOOP)
    cli.chmod(cli.stat().st_mode | stat_mod.S_IEXEC)
    monkeypatch.setenv("FAKE_HDFS_ROOT", str(root))
    return fs.ShellFS(str(cli)), root


def test_shellfs_write_read_exists_list_mv(fake_hadoop):
    sfs, root = fake_hadoop
    with sfs.open_write("hdfs://job/a.bin") as f:
        f.write(b"hello hdfs")
    assert (root / "job" / "a.bin").read_bytes() == b"hello hdfs"
    assert sfs.exists("hdfs://job/a.bin")
    assert not sfs.exists("hdfs://job/missing.bin")
    with sfs.open_read("hdfs://job/a.bin") as f:
        assert f.read() == b"hello hdfs"
    sfs.mkdir("hdfs://job/sub")
    assert sfs.list("hdfs://job") == ["a.bin", "sub"]
    sfs.mv("hdfs://job/a.bin", "hdfs://job/b.bin")
    assert sfs.list("hdfs://job") == ["b.bin", "sub"]
    sfs.remove("hdfs://job/b.bin")
    assert not sfs.exists("hdfs://job/b.bin")


def test_shellfs_transient_cli_failure_is_retried(fake_hadoop):
    sfs, root = fake_hadoop
    with sfs.open_write("hdfs://r/x.bin") as f:
        f.write(b"v1")
    (root / ".flake_count").write_text("2")   # next 2 calls: net blip
    with sfs.open_read("hdfs://r/x.bin") as f:
        assert f.read() == b"v1"
    assert monitor.get_stat("fs.retries") == 2


def test_shellfs_gives_up_after_budget(fake_hadoop):
    sfs, root = fake_hadoop
    paddle.set_flags({"fs_retry_times": 2})
    (root / ".flake_count").write_text("99")
    with pytest.raises(fs.TransientFSError, match="Connection refused"):
        sfs.open_read("hdfs://r/x.bin")
    assert monitor.get_stat("fs.gave_up") == 1


def test_shellfs_missing_file_is_permanent_not_retried(fake_hadoop):
    sfs, _ = fake_hadoop
    with pytest.raises(fs.PermanentFSError, match="No such file"):
        sfs.open_read("hdfs://r/never_written.bin")
    assert monitor.get_stat("fs.retries") == 0


def test_shellfs_mv_commit_then_timeout_is_success(fake_hadoop):
    """Rename is not idempotent: when the CLI times out AFTER the
    server-side rename committed, the retry sees 'no such file' — mv
    must verify the outcome instead of reporting a failed publish."""
    sfs, root = fake_hadoop
    with sfs.open_write("hdfs://j/meta.tmp") as f:
        f.write(b"meta")
    (root / ".mv_commit_fail").write_text("")
    sfs.mv("hdfs://j/meta.tmp", "hdfs://j/meta.json")
    assert sfs.exists("hdfs://j/meta.json")
    assert not sfs.exists("hdfs://j/meta.tmp")


def test_save_load_roundtrip_through_fake_hdfs(fake_hadoop, monkeypatch):
    sfs, _ = fake_hadoop
    fs.register_fs("fakehdfs", sfs)
    try:
        sd = {"w": paddle.ones([3, 2])}
        paddle.save(sd, "fakehdfs://m/model.pdparams")
        out = paddle.load("fakehdfs://m/model.pdparams")
        np.testing.assert_allclose(np.asarray(out["w"].data), 1.0)
    finally:
        fs._REGISTRY.pop("fakehdfs", None)


# -- atomic paddle.save ------------------------------------------------------

def test_save_is_atomic_crash_leaves_no_truncated_file(tmp_path):
    p = str(tmp_path / "m.pdparams")
    paddle.save({"a": paddle.ones([2])}, p)
    v1 = open(p, "rb").read()
    # crash at the publish rename: the old artifact must survive intact
    with fault.inject("fs.mv:count=1"):
        with pytest.raises(fault.FaultInjected):
            paddle.save({"a": paddle.zeros([64, 64])}, p)
    assert open(p, "rb").read() == v1
    leftovers = [f for f in os.listdir(tmp_path) if ".tmp" in f]
    assert leftovers == []               # staging file cleaned up
    out = paddle.load(p)
    np.testing.assert_allclose(np.asarray(out["a"].data), 1.0)


def test_save_crash_before_write_leaves_nothing(tmp_path):
    p = str(tmp_path / "fresh.pdparams")
    with fault.inject("fs.open_write:count=1"):
        with pytest.raises(fault.FaultInjected):
            paddle.save({"a": paddle.ones([2])}, p)
    assert not os.path.exists(p)


# -- checkpoint integrity ----------------------------------------------------

def _mk(seed):
    paddle.seed(seed)
    net = nn.Linear(2, 2)
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    return net, opt


def _run_epochs(d, n_stop, total=6, seed=0, **range_kw):
    """Train; a break DURING epoch ``n_stop`` simulates preemption, so
    the last published snapshot is epoch ``n_stop - 1``."""
    net, opt = _mk(seed)
    r = TrainEpochRange(total, d, model=net, opt=opt, **range_kw)
    seen = []
    for e in r:
        seen.append(e)
        net.weight.data = net.weight.data + 1.0
        if e == n_stop:
            break
    return net, seen


def test_meta_publishes_digests_and_keeps_k_snapshots(tmp_path):
    d = str(tmp_path / "acp")
    _run_epochs(d, 3, keep_checkpoint_max=2)
    meta = json.load(open(os.path.join(d, "range_meta.json")))
    snaps = meta["snapshots"]
    assert [s["epoch"] for s in snaps] == [1, 2]
    for s in snaps:
        assert set(s["digests"]) == {"model.pdparams", "opt.pdparams"}
        for h in s["digests"].values():
            assert len(h) == 64          # sha256 hex
    # pruned dirs are gone, retained dirs exist
    dirs = sorted(x for x in os.listdir(d) if x.startswith("epoch_"))
    assert dirs == ["epoch_1", "epoch_2"]


def test_corrupt_latest_falls_back_to_previous_intact(tmp_path):
    d = str(tmp_path / "acp")
    _run_epochs(d, 2, keep_checkpoint_max=3)      # published: 0 and 1
    with open(os.path.join(d, "epoch_1", "model.pdparams"), "r+b") as f:
        f.write(b"GARBAGE!")
    net2, opt2 = _mk(99)
    with pytest.warns(UserWarning, match="sha256 mismatch"):
        r = TrainEpochRange(6, d, model=net2, opt=opt2)
        resumed = next(iter(r))
    assert resumed == 1                  # epoch_0 intact -> resume at 1
    assert monitor.get_stat("checkpoint.fallbacks") == 1
    assert monitor.get_stat("checkpoint.restores") == 1


def test_missing_snapshot_file_never_part_loads(tmp_path):
    """Regression: _restore used to silently skip missing state files
    and resume half-initialized (mixed-epoch state)."""
    d = str(tmp_path / "acp")
    _run_epochs(d, 1, keep_checkpoint_max=1)      # published: epoch_0
    os.remove(os.path.join(d, "epoch_0", "opt.pdparams"))
    net2, opt2 = _mk(99)
    w_before = net2.weight.numpy().copy()
    with pytest.raises(CheckpointError, match="no intact snapshot"):
        with pytest.warns(UserWarning):
            list(TrainEpochRange(6, d, model=net2, opt=opt2))
    # nothing was applied to the registered objects
    np.testing.assert_array_equal(net2.weight.numpy(), w_before)


def test_object_registered_but_never_saved_is_loud(tmp_path):
    d = str(tmp_path / "acp")
    _run_epochs(d, 1, keep_checkpoint_max=1)
    net2, opt2 = _mk(99)
    extra = nn.Linear(2, 2)
    r = TrainEpochRange(6, d, model=net2, opt=opt2)
    r.register("ema", extra)             # snapshot never contained 'ema'
    with pytest.raises(CheckpointError):
        with pytest.warns(UserWarning, match="never saved"):
            list(r)


def test_v1_meta_without_digests_still_restores(tmp_path):
    d = str(tmp_path / "acp")
    # run epochs 0..1 to completion: epoch_1 is the published snapshot
    net, seen = _run_epochs(d, 99, total=2, keep_checkpoint_max=1)
    assert seen == [0, 1]
    w = net.weight.numpy().copy()
    meta_p = os.path.join(d, "range_meta.json")
    # rewrite as a pre-digest v1 meta
    json.dump({"finished_epoch": 1, "snapshot": "epoch_1",
               "objects": ["model", "opt"]}, open(meta_p, "w"))
    net2, opt2 = _mk(99)
    r = TrainEpochRange(6, d, model=net2, opt=opt2)
    assert next(iter(r)) == 2
    np.testing.assert_array_equal(net2.weight.numpy(), w)


def test_sigterm_saves_at_boundary_and_fresh_range_resumes(tmp_path):
    d = str(tmp_path / "acp")
    net, opt = _mk(0)
    r = TrainEpochRange(6, d, save_checkpoint_inter=10, model=net,
                        opt=opt)
    done = []
    with pytest.raises(SystemExit) as ei:
        for e in r:
            done.append(e)
            net.weight.data = net.weight.data + 1.0
            if e == 1:
                os.kill(os.getpid(), signal.SIGTERM)
            # body continues: the save happens at the epoch BOUNDARY
    assert ei.value.code == 0
    assert done == [0, 1] and r.preempted
    assert monitor.get_stat("checkpoint.preempt_saves") == 1
    w_saved = net.weight.numpy().copy()

    # a fresh range restores exactly the preemption snapshot...
    net2, opt2 = _mk(99)
    r2 = TrainEpochRange(6, d, model=net2, opt=opt2)
    it = iter(r2)
    assert next(it) == 2                 # ...and resumes at epoch 2
    np.testing.assert_array_equal(net2.weight.numpy(), w_saved)
    it.close()


def test_sigterm_handler_restored_after_iteration(tmp_path):
    prev = signal.getsignal(signal.SIGTERM)
    net, opt = _mk(0)
    list(TrainEpochRange(2, str(tmp_path / "acp"), model=net, opt=opt))
    assert signal.getsignal(signal.SIGTERM) == prev


# -- self-healing DataLoader -------------------------------------------------

class _ArangeDs(Dataset):
    def __init__(self, n=64, d=4):
        self.x = np.arange(n * d, dtype=np.float32).reshape(n, d)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], np.int64(i)


class _SleepyDs(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        time.sleep(120)
        return np.zeros(2, np.float32)


def test_killed_worker_respawned_every_batch_once_in_order():
    ds = _ArangeDs()
    # whichever worker picks up batch 3 hard-exits (matching on the
    # batch, not the worker id, is start-order independent)
    fault.arm("mp.worker_batch:count=1,action=exit,code=43,"
              "match=batch=3", seed=0)
    try:
        loader = DataLoader(ds, batch_size=8, num_workers=2,
                            use_shared_memory=True)
        out = [np.asarray(i.data) for _, i in loader]
    finally:
        fault.disarm()
    ids = np.concatenate(out)
    assert list(ids) == list(range(64))  # exactly once, in order
    assert monitor.get_stat("dataloader.worker_restarts") >= 1
    assert monitor.get_stat("dataloader.batch_retries") >= 1
    assert any(code == 43 for _, code in loader._mp_pool.exit_history)
    # healed pool serves the next epoch clean
    assert len(list(loader)) == 8
    loader._mp_pool.close()


def test_batch_that_keeps_killing_workers_exhausts_budget():
    ds = _ArangeDs(n=16)
    paddle.set_flags({"dataloader_batch_retries": 1})
    # respawn=1: replacement workers keep the kill rule -> batch 0 can
    # never survive -> budget exhausted -> loud failure w/ exit codes
    fault.arm("mp.worker_batch:action=exit,code=9,respawn=1", seed=0)
    try:
        loader = DataLoader(ds, batch_size=8, num_workers=1,
                            use_shared_memory=True)
        with pytest.raises(RuntimeError,
                           match="worker-death retries.*exit codes"):
            list(loader)
    finally:
        fault.disarm()
    assert monitor.get_stat("dataloader.worker_restarts") >= 1


def test_dataloader_timeout_configurable_and_diagnostic():
    ds = _SleepyDs()
    loader = DataLoader(ds, batch_size=2, num_workers=1,
                        use_shared_memory=True, timeout=2)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="stalled.*alive"):
        list(loader)
    assert time.monotonic() - t0 < 60


def test_dataloader_timeout_flag_thread_path():
    ds = _SleepyDs()
    paddle.set_flags({"dataloader_timeout": 1})
    try:
        loader = DataLoader(ds, batch_size=2, num_workers=1,
                            use_shared_memory=False)
        with pytest.raises(RuntimeError, match="stalled"):
            list(loader)
    finally:
        paddle.set_flags({"dataloader_timeout": 120})


# -- Checkpoint callback (Model.fit) ----------------------------------------

def test_checkpoint_callback_releases_sigterm_handler_on_crash(tmp_path):
    """A fit() that raises mid-training must not leave the preemption
    handler installed (it would swallow SIGTERM forever)."""
    from paddle_tpu.hapi import Checkpoint, Model
    prev = signal.getsignal(signal.SIGTERM)
    net = nn.Linear(4, 1)
    m = Model(net)

    def exploding_loss(out, label):
        raise ZeroDivisionError("boom")

    m.prepare(optimizer.SGD(learning_rate=0.1,
                            parameters=net.parameters()),
              loss=exploding_loss, jit_compile=False)
    x = np.zeros((4, 4), np.float32)
    y = np.zeros((4, 1), np.float32)
    with pytest.raises(ZeroDivisionError):
        m.fit([(x, y)], epochs=1, verbose=0,
              callbacks=[Checkpoint(str(tmp_path / "crash_ckpt"))])
    assert signal.getsignal(signal.SIGTERM) == prev


def test_checkpoint_callback_saves_restores_and_rotates(tmp_path):
    from paddle_tpu.hapi import Checkpoint, Model
    import paddle_tpu.nn.functional as F
    d = str(tmp_path / "fit_ckpt")
    paddle.seed(5)
    x = np.random.randn(16, 4).astype(np.float32)
    y = np.random.randn(16, 1).astype(np.float32)

    def make_model():
        paddle.seed(6)
        net = nn.Linear(4, 1)
        m = Model(net)
        m.prepare(optimizer.SGD(learning_rate=0.05,
                                parameters=net.parameters()),
                  loss=F.mse_loss, jit_compile=False)
        return m

    m1 = make_model()
    cb = Checkpoint(d, keep_checkpoint_max=2)
    m1.fit(list(zip(x.reshape(4, 4, 4), y.reshape(4, 4, 1))), epochs=3,
           verbose=0, callbacks=[cb])
    w_trained = m1.network.weight.numpy().copy()
    meta = json.load(open(os.path.join(d, "range_meta.json")))
    assert [s["epoch"] for s in meta["snapshots"]] == [1, 2]

    # a fresh Model auto-restores the published weights on fit begin
    m2 = make_model()
    cb2 = Checkpoint(d)
    cb2.set_model(m2)
    cb2.on_train_begin()
    np.testing.assert_array_equal(m2.network.weight.numpy(), w_trained)
    assert cb2.last_restored_epoch == 2
    cb2.on_train_end()


# -- executor injection point ------------------------------------------------

def test_executor_run_fault_point():
    """A fault spec can crash a training step on demand — the drill for
    'preemption mid-step' around the checkpoint/restore path."""
    paddle.enable_static()
    try:
        with paddle.static.program_guard(paddle.static.Program()) as main:
            x = paddle.static.data("x", [None, 2], "float32")
            out = x.sum(axis=1)
        exe = paddle.static.Executor()
        arr = np.ones((2, 2), np.float32)
        with fault.inject("executor.run:count=1"):
            with pytest.raises(fault.FaultInjected):
                exe.run(main, feed={"x": arr}, fetch_list=[out])
            # next step (count exhausted) runs fine
            res, = exe.run(main, feed={"x": arr}, fetch_list=[out])
        np.testing.assert_allclose(res, [2.0, 2.0])
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()


# -- recovery events + chaos smoke ------------------------------------------

def test_recovery_events_visible_in_monitor(tmp_path):
    rfs = fs.RetryingFS(fs.LocalFS())
    with fault.inject("fs.open_write:count=1,exc=TransientFSError"):
        with rfs.open_write(str(tmp_path / "a")) as f:
            f.write(b"x")
    _run_epochs(str(tmp_path / "acp"), 1)
    stats = monitor.all_stats()
    assert stats["fs.retries"] == 1
    assert stats["checkpoint.saves"] >= 1
    assert stats["fault.fired.fs.open_write"] == 1


def test_clean_run_has_no_fault_or_recovery_noise(tmp_path):
    _run_epochs(str(tmp_path / "acp"), 1)          # disarmed, healthy
    net2, opt2 = _mk(1)
    list(TrainEpochRange(3, str(tmp_path / "acp"), model=net2, opt=opt2))
    stats = monitor.all_stats()
    assert not any(k.startswith("fault.") for k in stats)
    assert stats.get("fs.retries", 0) == 0
    assert stats.get("checkpoint.fallbacks", 0) == 0
    assert stats.get("dataloader.worker_restarts", 0) == 0


def test_chaos_smoke_in_process(tmp_path):
    from paddle_tpu.testing import chaos
    assert chaos.main(epochs=3, workdir=str(tmp_path / "smoke")) == 0


# -- ISSUE 13: self-healing training ----------------------------------------
# sleep faults, permanent-errno fast fail, dataloader crash-loop budget,
# step-cadence snapshots, heartbeat/watchdog, TrainingSupervisor.

def test_sleep_fault_action_wedges_then_returns():
    t0 = time.monotonic()
    with fault.inject("slow.point:action=sleep,secs=0.15,count=1"):
        fault.point("slow.point")            # wedges ~0.15s, returns
        fault.point("slow.point")            # count exhausted: instant
    assert time.monotonic() - t0 >= 0.15
    assert monitor.get_stat("fault.fired.slow.point") == 1
    r = fault.parse_spec("x.y:action=sleep,secs=2.5")[0]
    assert r.action == "sleep" and r.secs == 2.5
    assert "secs=2.5" in r.to_spec()         # survives child re-arming


def test_enospc_erofs_fail_fast_as_permanent():
    import errno as _errno
    for eno in (_errno.ENOSPC, _errno.EROFS, _errno.EDQUOT):
        assert not fs.is_transient(OSError(eno, os.strerror(eno)))
    calls = []

    def nospace():
        calls.append(1)
        raise OSError(_errno.ENOSPC, "No space left on device")

    with pytest.raises(fs.PermanentFSError, match="ENOSPC"):
        fs.retry_call("open_write", nospace)
    assert len(calls) == 1                   # zero retries burned
    assert monitor.get_stat("fs.retries") == 0
    assert monitor.get_stat("fs.permanent") == 1
    # ShellFS stderr classification: a full/read-only store is semantic
    from paddle_tpu.utils.fs import (_PERMANENT_MARKERS)
    assert any(m in "no space left on device" for m in _PERMANENT_MARKERS)
    assert any(m in "read-only file system" for m in _PERMANENT_MARKERS)


def test_dataloader_crash_loop_gives_up_with_exit_history():
    from paddle_tpu.io.multiprocess import WorkerCrashLoop
    from paddle_tpu.testing.chaos import SmokeDataset
    old = paddle.get_flags(["dataloader_crashloop_budget",
                            "dataloader_respawn_backoff_s"])
    paddle.set_flags({"dataloader_crashloop_budget": 2,
                      "dataloader_respawn_backoff_s": 0.01,
                      "dataloader_batch_retries": 50})
    loader = DataLoader(SmokeDataset(), batch_size=8, shuffle=False,
                        num_workers=2)
    # respawn=1: replacements die too — a poisoned dataset, not a flake
    fault.arm("mp.worker_batch:action=exit,code=9,respawn=1")
    try:
        with pytest.raises(WorkerCrashLoop, match="crash-looping") as ei:
            for _ in loader:
                pass
        # the ledger names what kept dying, bounded by the budget
        assert len(ei.value.exit_history) >= 3
        assert monitor.get_stat("dataloader.worker_restarts") <= 2
    finally:
        fault.disarm()
        paddle.set_flags(old)
        pool = getattr(loader, "_mp_pool", None)
        if pool is not None:
            pool.close()
            loader._mp_pool = None


def _cadence_build(seed=1234):
    paddle.seed(seed)
    net = nn.Linear(4, 1)
    opt = optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
    return net, opt


_CAD_X = np.random.RandomState(7).randn(32, 4).astype(np.float32)
_CAD_Y = _CAD_X @ np.random.RandomState(8).randn(4, 1).astype(np.float32)


def _cadence_step(net, opt):
    import paddle_tpu.nn.functional as F
    loss = F.mse_loss(net(_CAD_X), _CAD_Y)
    loss.backward()
    opt.step()
    opt.clear_grad()


def test_step_cadence_snapshots_resume_mid_epoch(tmp_path):
    d = str(tmp_path / "acp")
    net, opt = _cadence_build()
    r = TrainEpochRange(1, d, save_every_steps=3, model=net, opt=opt)
    weights = {}
    for _epoch in r:
        for _ in range(10):
            _cadence_step(net, opt)
            g = r.step()
            weights[g] = net.weight.numpy().copy()
            if g == 7:
                break                        # simulated crash mid-epoch
        break                                # (no epoch-boundary save)
    # saves happened at the cadence, published in the background
    assert monitor.get_stat("checkpoint.step_saves") == 2     # 3 and 6
    assert monitor.get_stat("checkpoint.async_saves") == 2
    # the meta records step snapshots with digests, newest = step 6
    meta = SnapshotStore(d).load_meta()
    assert meta["snapshots"][-1]["kind"] == "step"
    assert meta["snapshots"][-1]["step"] == 6
    assert meta["snapshots"][-1]["digests"]

    net2, opt2 = _cadence_build(99)
    r2 = TrainEpochRange(1, d, save_every_steps=3, model=net2, opt=opt2)
    it = iter(r2)
    assert next(it) == 0                     # mid-epoch: re-enter epoch 0
    assert r2.resume_step == 6
    np.testing.assert_array_equal(net2.weight.numpy(), weights[6])
    # resumed training from step 6 reproduces the original trajectory
    for g in range(r2.resume_step, 10):
        _cadence_step(net2, opt2)
        r2.step()
        if g + 1 in weights:
            np.testing.assert_array_equal(net2.weight.numpy(),
                                          weights[g + 1])
    it.close()


def test_sigterm_saves_at_step_boundary_not_epoch(tmp_path):
    d = str(tmp_path / "acp")
    net, opt = _cadence_build()
    r = TrainEpochRange(4, d, save_every_steps=100, model=net, opt=opt)
    with pytest.raises(SystemExit) as ei:
        for _epoch in r:
            for i in range(10):
                _cadence_step(net, opt)
                if i == 4:
                    os.kill(os.getpid(), signal.SIGTERM)
                r.step()                     # <- saves HERE, exits 0
            pytest.fail("step() should have exited at the boundary")
    assert ei.value.code == 0 and r.preempted
    assert monitor.get_stat("checkpoint.preempt_saves") == 1
    w_saved = net.weight.numpy().copy()

    last = SnapshotStore(d).load_meta()["snapshots"][-1]
    assert last["kind"] == "step" and last["step"] == 5

    net2, opt2 = _cadence_build(99)
    r2 = TrainEpochRange(4, d, model=net2, opt=opt2)
    it = iter(r2)
    assert next(it) == 0 and r2.resume_step == 5
    np.testing.assert_array_equal(net2.weight.numpy(), w_saved)
    it.close()


def test_async_publish_failure_warns_and_keeps_older_snapshot(tmp_path):
    d = str(tmp_path / "store")
    net, opt = _cadence_build()
    store = SnapshotStore(d)
    store.save(0, {"model": net})            # intact epoch snapshot
    w0 = net.weight.numpy().copy()
    net.weight.data = net.weight.data + 1.0
    with fault.inject(
            "fs.open_write:count=1,exc=PermanentFSError,match=step_7"):
        import warnings as _w
        with _w.catch_warnings(record=True):
            _w.simplefilter("always")
            store.save_async(0, {"model": net}, step=7)
            assert store.flush(timeout=10)
    # the failed publish is counted, not raised into the step loop
    assert monitor.get_stat("checkpoint.async_errors") == 1
    # and the store still restores the older intact snapshot
    net2, _ = _cadence_build(99)
    assert store.restore({"model": net2}) == 1
    assert store.last_restored["dir"] == "epoch_0"
    np.testing.assert_array_equal(net2.weight.numpy(), w0)


def test_heartbeat_roundtrip_and_torn_write_guard(tmp_path):
    from paddle_tpu.distributed.supervisor import (HeartbeatReader,
                                                   HeartbeatWriter)
    p = str(tmp_path / "hb")
    w = HeartbeatWriter(p)
    rd = HeartbeatReader(p)
    assert HeartbeatReader(str(tmp_path / "missing")).read() is None
    w.beat(-1)
    hb = rd.read()
    assert hb.step == -1 and hb.interval_s == 0.0
    w.beat(1, {"predicted_step_s": 0.25})
    time.sleep(0.02)
    w.beat(2, {"predicted_step_s": 0.25})
    hb = rd.read()
    assert hb.step == 2 and hb.predicted_step_s == 0.25
    assert 0.0 < hb.interval_s < 5.0
    # a compile run's interval is excluded (marked unknown)
    w.beat(3, fresh_compile=True)
    assert rd.read().interval_s == 0.0
    # torn/garbage record: reader returns None instead of nonsense
    with open(p, "r+b") as f:
        f.write(b"\xff" * 17)
    assert rd.read() is None
    w.close()
    rd.close()


def test_watchdog_deadline_predicted_drift_and_p99_fallback():
    from paddle_tpu.distributed.supervisor import Heartbeat, StepWatchdog

    def hb(step, pred, interval):
        return Heartbeat(time.time(), step, pred, interval)

    # predicted path, no drift: deadline = predicted * multiplier
    wd = StepWatchdog(multiplier=10.0, min_deadline_s=0.001,
                      max_deadline_s=1000.0, drift_cap=4.0)
    wd.observe(hb(1, 0.5, 0.5))
    assert wd.deadline_s() == pytest.approx(0.5 * 1.0 * 10.0)
    # observed steps 3x slower than priced: drift widens the deadline
    for i in range(2, 12):
        wd.observe(hb(i, 0.5, 1.5))
    assert wd.drift() == pytest.approx(3.0)
    assert wd.deadline_s() == pytest.approx(0.5 * 3.0 * 10.0)
    # drift clamps at the cap — a wildly slow run is a hang, not drift
    for i in range(12, 40):
        wd.observe(hb(i, 0.5, 50.0))
    assert wd.drift() == 4.0

    # no prediction: rolling p99 of observed intervals * multiplier
    wd = StepWatchdog(multiplier=4.0, min_deadline_s=0.001,
                      max_deadline_s=1000.0)
    # nearest-rank p99 over 100 samples = the 99th smallest
    for i, dt in enumerate([0.1] * 98 + [0.3] * 2):
        wd.observe(hb(i, None, dt))
    assert wd.deadline_s() == pytest.approx(0.3 * 4.0)
    # duplicate reads of one step don't pollute the window
    n = len(wd._intervals)
    wd.observe(hb(99, None, 0.3))
    assert len(wd._intervals) == n

    # nothing known yet: the (clamped) max budget covers first compile
    wd = StepWatchdog(min_deadline_s=1.0, max_deadline_s=30.0)
    assert wd.deadline_s() == 30.0
    # clamping floors a micro-second prediction at min_deadline_s
    wd = StepWatchdog(multiplier=8.0, min_deadline_s=5.0)
    wd.observe(hb(1, 1e-6, 1e-6))
    assert wd.deadline_s() == 5.0
    with pytest.raises(ValueError):
        StepWatchdog(min_deadline_s=2.0, max_deadline_s=1.0)


def test_executor_stamps_heartbeat_per_step(tmp_path):
    from paddle_tpu.core import obs_hook
    from paddle_tpu.distributed.supervisor import (HeartbeatReader,
                                                   HeartbeatWriter)
    paddle.enable_static()
    try:
        main = paddle.static.Program()
        with paddle.static.program_guard(main):
            x = paddle.static.data("x", [None, 4], "float32")
            y = paddle.static.nn.fc(x, 2)
        exe = paddle.static.Executor()
        w = HeartbeatWriter(str(tmp_path / "hb"))
        obs_hook.set_heartbeat(w)
        try:
            feed = {"x": np.zeros((2, 4), np.float32)}
            for _ in range(3):
                exe.run(main, feed=feed, fetch_list=[y])
            hb = HeartbeatReader(str(tmp_path / "hb")).read()
            assert hb is not None and hb.step == 3
            assert hb.interval_s > 0.0       # post-compile steps measure
        finally:
            obs_hook.set_heartbeat(None)
            exe.close()
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()


def test_supervisor_restarts_until_clean_exit(tmp_path):
    from paddle_tpu.distributed.supervisor import TrainingSupervisor
    from paddle_tpu.testing.chaos import _sv_flaky_entry
    sv = TrainingSupervisor(
        _sv_flaky_entry, args=(str(tmp_path / "state"), 2, 5),
        backoff_s=0.05, backoff_max_s=0.2, crash_budget=5,
        workdir=str(tmp_path))
    res = sv.run()
    assert res.clean_exit and res.attempts == 3 and res.restarts == 2
    assert [r["exit_code"] for r in res.exit_history] == [5, 5]
    assert all(r["reason"] == "crash(exit=5)" for r in res.exit_history)
    assert monitor.get_stat("supervisor.starts") == 3
    assert monitor.get_stat("supervisor.restarts") == 2
    assert monitor.get_stat("supervisor.clean_exits") == 1


def test_supervisor_crash_loop_gives_up_with_history(tmp_path):
    from paddle_tpu.distributed.supervisor import (SupervisorGaveUp,
                                                   TrainingSupervisor)
    from paddle_tpu.testing.chaos import _sv_flaky_entry
    sv = TrainingSupervisor(
        _sv_flaky_entry, args=(str(tmp_path / "state"), 10 ** 9, 3),
        backoff_s=0.01, crash_window_s=600.0, crash_budget=1,
        workdir=str(tmp_path))
    with pytest.raises(SupervisorGaveUp, match="giving up") as ei:
        sv.run()
    assert len(ei.value.exit_history) == 2   # budget 1 -> 2nd crash ends it
    assert all(r["exit_code"] == 3 for r in ei.value.exit_history)
    assert monitor.get_stat("supervisor.gave_up") == 1


def test_supervisor_giveup_writes_incident_flight(tmp_path):
    """Satellite (ISSUE 20): a give-up is an incident — the supervisor
    leaves supervisor_giveup.json with the exit history, pointers to
    every child flight dump, and the last heartbeat INLINED (an
    operator reading one JSON must not have to decode the binary
    heartbeat file)."""
    import json as _json

    from paddle_tpu.distributed.supervisor import (SupervisorGaveUp,
                                                   TrainingSupervisor)
    from paddle_tpu.testing.chaos import _sv_flaky_entry
    sv = TrainingSupervisor(
        _sv_flaky_entry, args=(str(tmp_path / "state"), 10 ** 9, 3),
        backoff_s=0.01, crash_window_s=600.0, crash_budget=1,
        workdir=str(tmp_path))
    with pytest.raises(SupervisorGaveUp):
        sv.run()
    box = _json.load(open(str(tmp_path / "supervisor_giveup.json")))
    assert box["reason"] == "supervisor.give_up"
    extra = box["extra"]
    assert extra["attempts"] == 2 and extra["crash_budget"] == 1
    assert [r["exit_code"] for r in extra["exit_history"]] == [3, 3]
    assert all(r["reason"] == "crash(exit=3)"
               for r in extra["exit_history"])
    assert isinstance(extra["child_dumps"], list)
    # the entry never beats, so the inlined heartbeat is None — but
    # the key must be present (the operator contract)
    assert "last_heartbeat" in extra
    # the dump carries a full metrics snapshot like every flight box
    assert box.get("stats") is not None


def test_supervisor_watchdog_kills_hang_and_dumps_flight(tmp_path):
    import json as _json

    from paddle_tpu.distributed.supervisor import (StepWatchdog,
                                                   TrainingSupervisor)
    from paddle_tpu.testing.chaos import _sv_hang_entry
    sv = TrainingSupervisor(
        _sv_hang_entry, args=(str(tmp_path / "state"),),
        watchdog=StepWatchdog(multiplier=6.0, min_deadline_s=0.6,
                              max_deadline_s=8.0),
        hang_grace_s=0.5, poll_s=0.1, backoff_s=0.05, crash_budget=5,
        workdir=str(tmp_path))
    res = sv.run()
    assert res.clean_exit and res.hang_kills == 1 and res.restarts == 1
    assert res.exit_history[0]["reason"] == "hang"
    assert monitor.get_stat("supervisor.hang_kills") == 1
    # the kill-time flight dump names the restart reason
    box = _json.load(open(str(tmp_path / "supervisor_kill_a0.json")))
    assert box["reason"] == "supervisor.hang"
    assert box["extra"]["restart_reason"] == "hang"
    assert box["extra"]["attempt"] == 0
    assert box["extra"]["last_step"] is not None


def test_supervisor_restart_recompile_not_judged_at_step_scale(tmp_path):
    """A restarted child recompiles from scratch: until it produces a
    STEP beat, only startup_timeout_s applies — the interval window
    retained from the previous incarnation (steps of ~0.02s here) must
    not get its quiet 2s start killed as a hang."""
    from paddle_tpu.distributed.supervisor import (StepWatchdog,
                                                   TrainingSupervisor)
    from paddle_tpu.testing.chaos import _sv_slow_start_entry
    sv = TrainingSupervisor(
        _sv_slow_start_entry, args=(str(tmp_path / "state"),),
        watchdog=StepWatchdog(multiplier=2.0, min_deadline_s=0.3,
                              max_deadline_s=5.0),
        startup_timeout_s=60.0, hang_grace_s=0.5, poll_s=0.05,
        backoff_s=0.05, crash_budget=5, workdir=str(tmp_path))
    res = sv.run()
    assert res.clean_exit and res.hang_kills == 0
    assert [r["exit_code"] for r in res.exit_history] == [3]
    assert monitor.get_stat("supervisor.hang_kills") == 0


def test_chaos_supervise_scenario_in_process(tmp_path):
    """tools/chaos_smoke.py --scenario supervise, in-process: injected
    mid-step hang -> watchdog kill -> resume from a step snapshot, then
    injected hard crash -> restart onto mesh dp=4 of 8 via reshard
    restore, loss-trajectory parity with the fault-free run."""
    from paddle_tpu.testing import chaos
    assert chaos.supervise_main(workdir=str(tmp_path)) == 0
