"""SelectedRows / sparse-embedding tests (reference analog:
test_selected_rows.py, test_lookup_table_op.py sparse branch,
test_adam_op.py lazy_mode): sparse grads touch only looked-up rows."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, optimizer
from paddle_tpu.core.selected_rows import SelectedRows


def test_selected_rows_merge_and_dense():
    sr = SelectedRows([2, 0, 2], np.array([[1., 1.], [2., 2.], [3., 3.]],
                                          np.float32), height=4)
    m = sr.merge()
    assert sorted(np.asarray(m.rows).tolist()) == [0, 2]
    d = np.asarray(sr.to_dense())
    np.testing.assert_allclose(d, [[2, 2], [0, 0], [4, 4], [0, 0]])
    # dense + sparse accumulation
    acc = np.asarray(np.ones((4, 2), np.float32) + sr)
    np.testing.assert_allclose(acc, d + 1)
    # sparse + sparse stays sparse
    both = sr + SelectedRows([1], np.array([[5., 5.]], np.float32), 4)
    assert isinstance(both, SelectedRows)
    np.testing.assert_allclose(np.asarray(both.to_dense())[1], [5, 5])


def test_sparse_embedding_grad_is_selected_rows():
    import jax.numpy as jnp
    from paddle_tpu.core.tensor import Parameter
    paddle.seed(0)
    w = Parameter(jnp.ones((16, 4), jnp.float32))
    ids = paddle.to_tensor(np.array([[1, 3], [3, 5]], np.int64))
    out = F.embedding(ids, w, sparse=True)
    out.sum().backward()
    assert isinstance(w._grad_data, SelectedRows)
    dense = np.asarray(w._grad_data.to_dense())
    assert np.all(dense[[1, 5]] == 1.0)
    assert np.all(dense[3] == 2.0)  # row 3 looked up twice
    untouched = np.setdiff1d(np.arange(16), [1, 3, 5])
    assert np.all(dense[untouched] == 0.0)


@pytest.mark.parametrize("opt_name", ["sgd", "adam_lazy", "adam_dense"])
def test_sparse_update_touches_only_rows(opt_name):
    paddle.seed(1)
    emb = nn.Embedding(64, 8, sparse=True)
    if opt_name == "sgd":
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=emb.parameters())
    elif opt_name == "adam_lazy":
        opt = optimizer.Adam(learning_rate=0.1, lazy_mode=True,
                             parameters=emb.parameters())
    else:
        opt = optimizer.Adam(learning_rate=0.1, lazy_mode=False,
                             parameters=emb.parameters())
    before = emb.weight.numpy().copy()
    ids = paddle.to_tensor(np.array([[3, 9, 3]], np.int64))
    loss = emb(ids).sum()
    loss.backward()
    opt.step()
    opt.clear_grad()
    after = emb.weight.numpy()
    untouched = np.setdiff1d(np.arange(64), [3, 9])
    np.testing.assert_array_equal(after[untouched], before[untouched])
    assert not np.allclose(after[[3, 9]], before[[3, 9]])


def test_sparse_sgd_matches_dense_sgd():
    paddle.seed(2)
    ids = paddle.to_tensor(np.array([[0, 2, 2, 7]], np.int64))

    def run(sparse):
        paddle.seed(42)
        emb = nn.Embedding(8, 4, sparse=sparse)
        opt = optimizer.SGD(learning_rate=0.5, parameters=emb.parameters())
        for _ in range(3):
            (emb(ids) ** 2).sum().backward()
            opt.step()
            opt.clear_grad()
        return emb.weight.numpy()

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6)


def test_sparse_grad_user_views_and_hooks():
    paddle.seed(7)
    emb = nn.Embedding(8, 2, sparse=True)
    calls = []
    emb.weight.register_hook(lambda g: calls.append(g.shape))
    ids = paddle.to_tensor(np.array([[1, 2]], np.int64))
    emb(ids).sum().backward()
    # hook fired with the densified grad
    assert calls == [[8, 2]]
    # .grad view densifies; optimizer path stays sparse
    g = emb.weight.grad
    assert g.shape == [8, 2]
    assert float(g.numpy()[1].sum()) == 2.0
    # paddle.grad densifies too
    emb.clear_gradients()
    out = emb(ids).sum()
    gw, = paddle.grad(out, [emb.weight])
    assert gw.shape == [8, 2]


def test_sparse_grad_global_norm_clip():
    """SelectedRows must participate in ClipGradByGlobalNorm (reference:
    fluid/clip.py merge_selected_rows path)."""
    from paddle_tpu.optimizer.clip import ClipGradByGlobalNorm
    paddle.seed(8)
    emb = nn.Embedding(8, 2, sparse=True)
    opt = optimizer.SGD(learning_rate=1.0, parameters=emb.parameters(),
                        grad_clip=ClipGradByGlobalNorm(1e-3))
    before = emb.weight.numpy().copy()
    ids = paddle.to_tensor(np.array([[1]], np.int64))
    (emb(ids).sum() * 1000.0).backward()
    opt.step()
    after = emb.weight.numpy()
    delta = np.abs(after - before).sum()
    # grad magnitude was 1000 per element; clipped global norm 1e-3 bounds
    # the update to ~lr * 1e-3
    assert 0 < delta < 2e-3, delta


def test_sharded_embedding_eager_sparse_grad():
    from paddle_tpu.parallel import ShardedEmbedding
    paddle.seed(3)
    emb = ShardedEmbedding(32, 4, axis="nope_axis")  # no such mesh axis
    ids = paddle.to_tensor(np.array([[1, 2]], np.int64))
    emb(ids).sum().backward()
    assert isinstance(emb.weight._grad_data, SelectedRows)


def test_sharded_embedding_spmd_parity():
    """Row-sharded lookup under the SPMD step matches the eager oracle and
    leaves untouched rows untouched (the dryrun criterion, unit-sized)."""
    import jax.numpy as jnp
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.parallel import ShardedEmbedding, SpmdTrainStep

    paddle.seed(4)
    mesh = init_mesh({"dp": 8})

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = ShardedEmbedding(64, 8, axis="dp")
            self.fc = nn.Linear(8, 4)

        def forward(self, ids):
            return self.fc(self.emb(ids).mean(axis=1))

    net = Net()
    init = {k: np.asarray(v.data).copy()
            for k, v in net.state_dict().items()}
    w0 = np.asarray(net.emb.weight.data).copy()
    ids = jnp.asarray(np.random.RandomState(0).randint(
        0, 16, (8, 4), dtype=np.int32))
    y = jnp.asarray(np.random.RandomState(1).randint(
        0, 4, (8,), dtype=np.int32))
    loss_fn = lambda out, lab: F.cross_entropy(out, lab)

    opt = optimizer.Adam(learning_rate=0.05, parameters=net.parameters())
    step = SpmdTrainStep(net, loss_fn, opt, mesh=mesh)
    spmd_losses = [float(step(ids, y)) for _ in range(2)]

    w_after = np.asarray(net.emb.weight.data)
    untouched = np.setdiff1d(np.arange(64),
                             np.unique(np.asarray(ids).reshape(-1)))
    np.testing.assert_array_equal(w_after[untouched], w0[untouched])

    # oracle: plain dense embedding, single device
    net.set_state_dict(init)
    from paddle_tpu.jit import TrainStep
    opt2 = optimizer.Adam(learning_rate=0.05, parameters=net.parameters())
    local = TrainStep(net, loss_fn, opt2)
    local_losses = [float(local(ids, y)) for _ in range(2)]
    np.testing.assert_allclose(spmd_losses, local_losses, rtol=2e-4)
