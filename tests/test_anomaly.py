"""Data-plane fault tolerance (ISSUE 15): the corrupt fault action, the
in-graph anomaly sentry's mesh-agreed skip, the AnomalyPolicy escalation
ladder, and the supervisor give-up black box.

Runs on the suite's virtual 8-device CPU mesh (conftest.py)."""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import distributed as dist, optimizer
from paddle_tpu.distributed import AnomalyEscalation, AnomalyPolicy
from paddle_tpu.distributed.mesh import init_mesh
from paddle_tpu.testing import fault
from paddle_tpu.utils import monitor


@pytest.fixture
def sentry_on():
    old = paddle.get_flags("anomaly_sentry")
    paddle.set_flags({"anomaly_sentry": True})
    yield
    paddle.set_flags(old)


@pytest.fixture(autouse=True)
def _disarm():
    yield
    fault.disarm()


# ------------------------------------------------------ corrupt action --
def test_corrupt_spec_parse_and_roundtrip():
    spec = ("dataloader.batch:action=corrupt,mode=nan,count=1,after=2;"
            "grad_comm.wire:action=corrupt,mode=inf,n=3,"
            "tensor=*scales*;"
            "executor.grads:action=corrupt,mode=bitflip,p=0.5")
    rules = fault.parse_spec(spec)
    assert [r.mode for r in rules] == ["nan", "inf", "bitflip"]
    assert rules[1].n == 3 and rules[1].tensor == "*scales*"
    again = fault.parse_spec(";".join(r.to_spec() for r in rules))
    assert [r.to_spec() for r in again] == [r.to_spec() for r in rules]
    with pytest.raises(ValueError, match="corrupt mode"):
        fault.parse_spec("x:action=corrupt,mode=zero")


def test_corrupt_host_modes_and_accounting():
    fault.arm("p:action=corrupt,mode=nan,count=1,after=1")
    src = np.ones(4, np.float32)
    # after=1: first hit clean, second poisoned, count exhausts
    assert not np.isnan(fault.corrupt_host("p", src)).any()
    out = fault.corrupt_host("p", src)
    assert np.isnan(out[0]) and not np.isnan(out[1:]).any()
    assert not np.isnan(src).any()          # original never mutated
    assert not np.isnan(fault.corrupt_host("p", src)).any()
    assert fault.fire_count("p") == 1

    # inf + n, tree walk, match= on detail
    fault.arm("p:action=corrupt,mode=inf,n=2,match=batch=3")
    tree = {"x": np.zeros(4, np.float32), "y": (np.zeros(2, np.float32),)}
    clean = fault.corrupt_host("p", tree, "batch=1")
    assert not np.isinf(clean["x"]).any()
    bad = fault.corrupt_host("p", tree, "batch=3")
    assert np.isinf(bad["x"][:2]).all() and np.isinf(bad["y"][0]).all()

    # nan on an int array falls back to a (detectable) bitflip
    fault.arm("p:action=corrupt,mode=nan")
    iv = fault.corrupt_host("p", np.arange(4, dtype=np.int64))
    assert iv[0] != 0 and (iv[1:] == [1, 2, 3]).all()


def test_corrupt_in_graph_window_and_host_mirror():
    fault.arm("g:action=corrupt,mode=inf,count=2,after=1,n=2")

    @jax.jit
    def f(step, x):
        return fault.corrupt_in_graph("g", x, step, tensor="w")

    fired = [bool(np.isinf(np.asarray(
        f(jnp.asarray(s, jnp.int32), jnp.ones(4)))).any())
        for s in range(1, 5)]
    assert fired == [False, True, True, False]   # window (1, 3]
    sites = fault.graph_corrupt_sites([("g", "w"), ("g", "other")])
    assert len(sites) == 2                       # no tensor glob: both
    n0 = monitor.get_stat("fault.fired.g")
    for s in range(1, 5):
        fault.mirror_graph_fires(sites[:1], s)
    assert monitor.get_stat("fault.fired.g") - n0 == 2


def test_corrupt_in_graph_probability_matches_mirror():
    fault.arm("pp:action=corrupt,mode=nan,p=0.4", seed=11)

    @jax.jit
    def f(step, x):
        return fault.corrupt_in_graph("pp", x, step)

    graph = [bool(np.isnan(np.asarray(
        f(jnp.asarray(s, jnp.int32), jnp.ones(3)))).any())
        for s in range(1, 21)]
    sites = fault.graph_corrupt_sites([("pp", "")])
    host = []
    for s in range(1, 21):
        before = fault.fire_count("pp")
        fault.mirror_graph_fires(sites, s)
        host.append(fault.fire_count("pp") > before)
    assert graph == host and any(graph) and not all(graph)


# ------------------------------------------------- sentry: plain path --
def _plain_program(lr=0.1):
    paddle.seed(7)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 4], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        loss = F.mse_loss(paddle.static.nn.fc(x, 1), y)
        optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, loss


def test_sentry_skip_is_bitwise_noop_plain(sentry_on):
    paddle.enable_static()
    try:
        main, loss = _plain_program()
        exe = paddle.static.Executor()
        rng = np.random.RandomState(0)
        xs = rng.standard_normal((8, 4)).astype(np.float32)
        ys = rng.standard_normal((8, 1)).astype(np.float32)
        exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        state = exe._states[main._serial]
        p0 = [np.asarray(a) for a in state.p_arrays]
        s0 = [{k: np.asarray(v) for k, v in s.items()}
              for s in state.opt_state]
        step0 = int(np.asarray(state.aux["step"]))
        xbad = xs.copy()
        xbad[0, 0] = np.nan
        bad = exe.run(main, feed={"x": xbad, "y": ys},
                      fetch_list=[loss])[0]
        assert np.isnan(bad)                  # the fetch shows the NaN
        # ...but every piece of carried state is bitwise untouched
        assert all(np.array_equal(a, b) for a, b in
                   zip(p0, (np.asarray(a) for a in state.p_arrays)))
        for before, after in zip(s0, state.opt_state):
            for k in before:
                assert np.array_equal(before[k], np.asarray(after[k]))
        assert int(np.asarray(state.aux["step"])) == step0
        st = exe.sentry_stats(main)
        assert st["skipped_steps"] == 1 and st["last_flag"] == 1
        assert exe.compile_count == 1         # no recompiles
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()


def test_sentry_flip_recompiles_with_attribution():
    from paddle_tpu.observability import explain_compiles
    paddle.enable_static()
    try:
        main, loss = _plain_program()
        exe = paddle.static.Executor()
        rng = np.random.RandomState(0)
        feed = {"x": rng.standard_normal((8, 4)).astype(np.float32),
                "y": rng.standard_normal((8, 1)).astype(np.float32)}
        exe.run(main, feed=feed, fetch_list=[loss])
        paddle.set_flags({"anomaly_sentry": True})
        try:
            exe.run(main, feed=feed, fetch_list=[loss])
        finally:
            paddle.set_flags({"anomaly_sentry": False})
        exe.run(main, feed=feed, fetch_list=[loss])
        assert exe.compile_count == 2          # flip back = cache hit
        rec = [r for r in explain_compiles("executor")["records"]
               if r["cause"] == "new_sentry"]
        assert rec, "sentry flip not attributed"
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()


# -------------------------------------------- sentry: grad_comm path --
def _int8_program(lr=0.05):
    paddle.seed(7)
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 8], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        h = paddle.static.nn.fc(x, 8)
        loss = F.mse_loss(paddle.static.nn.fc(F.relu(h), 1), y)
        f = dist.fleet
        strat = dist.DistributedStrategy()
        strat.grad_comm = {"dtype": "int8", "error_feedback": True,
                           "scatter_threshold_KB": 0.01,
                           "block_size": 64}
        f.init(is_collective=True, strategy=strat)
        opt = f.distributed_optimizer(optimizer.Adam(learning_rate=lr))
        opt.minimize(loss)
    return main, loss


def _int8_feed(rng):
    xs = rng.standard_normal((64, 8)).astype(np.float32)
    ys = (xs @ rng.standard_normal((8, 1))).astype(np.float32)
    return xs, ys


def test_sentry_mesh_agreement_one_shard_nan(sentry_on):
    """One replica's shard carries the NaN; the psum'd flag makes EVERY
    replica skip, and params stay bitwise identical (and replicated)."""
    paddle.enable_static()
    try:
        init_mesh({"dp": 8})
        main, loss = _int8_program()
        init_mesh({"dp": 8})
        exe = paddle.static.Executor()
        xs, ys = _int8_feed(np.random.RandomState(1))
        feed = {"x": xs, "y": ys}
        for _ in range(2):
            exe.run(main, feed=feed, fetch_list=[loss])
        state = exe._states[main._serial]
        p0 = [np.asarray(a) for a in state.p_arrays]
        r0 = [np.asarray(a) for a in state.aux["grad_comm"]]
        step0 = int(np.asarray(state.aux["step"]))
        # rows 24..31 are shard 3's slice of the dp-sharded batch
        xbad = xs.copy()
        xbad[25, :] = np.nan
        exe.run(main, feed={"x": xbad, "y": ys}, fetch_list=[loss])
        assert exe.sentry_stats(main)["skipped_steps"] == 1
        assert all(np.array_equal(a, np.asarray(b))
                   for a, b in zip(p0, state.p_arrays))
        assert all(np.array_equal(a, np.asarray(b))
                   for a, b in zip(r0, state.aux["grad_comm"]))
        assert int(np.asarray(state.aux["step"])) == step0
        # params are replicated: every device holds the same buffer
        for a in state.p_arrays:
            shards = [np.asarray(s.data) for s in a.addressable_shards]
            assert all(np.array_equal(shards[0], s) for s in shards[1:])
        assert exe.compile_count == 1
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()


def test_sentry_int8_ef_skip_oracle(sentry_on):
    """The int8+error-feedback oracle: a skipped step leaves the EF
    residuals bitwise untouched and the next clean step matches a
    never-faulted run bitwise."""
    paddle.enable_static()
    try:
        rng = np.random.RandomState(3)
        b1 = _int8_feed(rng)
        b2 = _int8_feed(rng)
        bad = (np.full_like(b1[0], np.nan), b1[1])

        def run_sequence(batches):
            init_mesh({"dp": 8})
            main, loss = _int8_program()
            init_mesh({"dp": 8})
            exe = paddle.static.Executor()
            for xs, ys in batches:
                exe.run(main, feed={"x": xs, "y": ys},
                        fetch_list=[loss])
            state = exe._states[main._serial]
            out = ([np.asarray(a) for a in state.p_arrays],
                   [np.asarray(a) for a in state.aux["grad_comm"]],
                   int(np.asarray(state.aux["step"])))
            exe.close()
            paddle.static.reset_default_programs()
            return out

        p_ref, r_ref, step_ref = run_sequence([b1, b2])
        p_got, r_got, step_got = run_sequence([b1, bad, b2])
        assert step_got == step_ref == 2
        assert all(np.array_equal(a, b) for a, b in zip(p_got, p_ref))
        assert all(np.array_equal(a, b) for a, b in zip(r_got, r_ref))
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()


def test_sentry_skip_oracle_hybrid_mesh(sentry_on):
    """ISSUE 17 satellite: the skip oracle holds on a {dp:4, mp:2}
    mesh with an mp-sharded weight — the flagged step is still a
    proven bitwise no-op on params AND EF residuals.  The hybrid
    buckets' device-varying scan contributions are psum'd inside
    reduce_gradients, so the flag stays mesh-agreed across both axes."""
    paddle.enable_static()
    try:
        rng = np.random.RandomState(4)
        b1 = _int8_feed(rng)
        b2 = _int8_feed(rng)
        bad = (np.full_like(b1[0], np.nan), b1[1])
        mesh_shape = {"dp": 4, "mp": 2}

        def run_sequence(batches):
            init_mesh(mesh_shape)
            main, loss = _int8_program()
            wname = next(p.name for p in main.parameters()
                         if p.data.shape == (8, 8))
            main._sharding_rules = [(wname, (None, "mp")), (r".*", ())]
            init_mesh(mesh_shape)
            exe = paddle.static.Executor()
            for xs, ys in batches:
                exe.run(main, feed={"x": xs, "y": ys},
                        fetch_list=[loss])
            sen = exe.sentry_stats(main)
            state = exe._states[main._serial]
            out = ([np.asarray(a) for a in state.p_arrays],
                   [np.asarray(a) for a in state.aux["grad_comm"]],
                   int(np.asarray(state.aux["step"])), sen)
            exe.close()
            paddle.static.reset_default_programs()
            return out

        p_ref, r_ref, step_ref, sen_ref = run_sequence([b1, b2])
        p_got, r_got, step_got, sen_got = run_sequence([b1, bad, b2])
        assert step_got == step_ref == 2
        assert sen_ref["skipped_steps"] == 0
        assert sen_got["skipped_steps"] == 1
        assert all(np.array_equal(a, b) for a, b in zip(p_got, p_ref))
        assert all(np.array_equal(a, b) for a, b in zip(r_got, r_ref))
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()


def test_ef_residuals_ride_snapshot_rollback(sentry_on, tmp_path):
    """Same-mesh rollback restores the error-feedback carry bitwise
    (reshard restores keep starting from a fresh carry)."""
    from paddle_tpu.utils.checkpoint import SnapshotStore
    paddle.enable_static()
    try:
        init_mesh({"dp": 8})
        main, loss = _int8_program()
        init_mesh({"dp": 8})
        exe = paddle.static.Executor()
        xs, ys = _int8_feed(np.random.RandomState(5))
        feed = {"x": xs, "y": ys}
        store = SnapshotStore(str(tmp_path / "ckpt"))
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[loss])
        ss = exe.sharded_state(main)
        store.save(0, {"train": ss}, step=3, kind="step")
        state = exe._states[main._serial]
        r_saved = [np.asarray(a) for a in state.aux["grad_comm"]]
        for _ in range(2):
            exe.run(main, feed=feed, fetch_list=[loss])
        assert any(not np.array_equal(a, np.asarray(b)) for a, b in
                   zip(r_saved, state.aux["grad_comm"]))
        store.restore({"train": ss})
        assert all(np.array_equal(a, np.asarray(b)) for a, b in
                   zip(r_saved, state.aux["grad_comm"]))
        assert int(np.asarray(state.aux["step"])) == 3
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()


# ------------------------------------------------------ policy ladder --
def _vals(flag, nf=0, extra=0, norm2=1.0):
    return (np.asarray(flag, np.int32),
            np.asarray([nf], np.int32),
            np.asarray(extra, np.int32),
            np.asarray(norm2, np.float32))


class _StateObj:
    """state_dict-bearing snapshot object for the policy ladder test."""

    def __init__(self, v):
        self.v = dict(v)

    def state_dict(self):
        return dict(self.v)

    def set_state_dict(self, d):
        self.v = dict(d)


def test_policy_ladder_skip_quarantine_rollback_giveup(tmp_path):
    from paddle_tpu.utils.checkpoint import SnapshotStore
    monitor.stat_reset()
    store = SnapshotStore(str(tmp_path / "ckpt"))
    obj = _StateObj({"w": np.ones(2, np.float32)})
    store.save(0, {"train": obj}, step=4, kind="step")
    obj.v["w"] = np.zeros(2, np.float32)       # drifts after the save

    policy = AnomalyPolicy(store=store, objects={"train": obj},
                           skip_budget=2, rollback_budget=1)
    policy.note_batch(9)
    step = [0]

    def feed(flag, **kw):
        step[0] += 1
        policy.on_step(None, None, step[0], _vals(flag, **kw),
                       ("loss",), (np.asarray(0.5),))
        return policy.poll()

    assert feed(0) == "ok"
    assert feed(1, nf=3) == "skip"
    assert feed(1, nf=3) == "skip"
    assert feed(1, nf=3) == "quarantine"
    assert policy.ledger[0]["batch"] == 9
    assert feed(1, nf=1) == "rollback"
    assert policy.resume_step == 4
    assert np.array_equal(obj.v["w"], np.ones(2))   # state restored
    assert policy.data_seed == 1
    # clean steps reset the ladder
    assert feed(0) == "ok"
    # a fresh incident past the (now exhausted) rollback budget: the
    # ladder runs skip, skip, quarantine, then GIVES UP
    assert feed(1, nf=1) == "skip"
    assert feed(1, nf=1) == "skip"
    assert feed(1, nf=1) == "quarantine"
    with pytest.raises(AnomalyEscalation) as ei:
        feed(1, nf=1)
    assert len(ei.value.ledger) == 2
    stats = monitor.all_stats()
    assert stats["anomaly.skips"] == 4
    assert stats["anomaly.quarantines"] == 2
    assert stats["anomaly.rollbacks"] == 1
    assert stats["anomaly.giveups"] == 1


def test_policy_rollback_without_snapshot_gives_up(tmp_path):
    """An empty store must not count a no-op restore as a rollback —
    replaying onto live (possibly poisoned) weights is a give-up."""
    from paddle_tpu.utils.checkpoint import SnapshotStore
    store = SnapshotStore(str(tmp_path / "empty"))
    obj = _StateObj({"w": np.ones(1, np.float32)})
    policy = AnomalyPolicy(store=store, objects={"train": obj},
                           skip_budget=0, rollback_budget=1)
    policy.on_step(None, None, 1, _vals(1, nf=1), (), ())
    assert policy.poll() == "quarantine"
    with pytest.raises(AnomalyEscalation, match="no published snapshot"):
        policy.on_step(None, None, 2, _vals(1, nf=1), (), ())
    assert policy.rollbacks == 0


def test_policy_deferred_mode_blames_the_step_that_ran(tmp_path):
    """sync=False judges step N while batch N+1 is already noted: the
    quarantine must still blame the batch that produced the flags."""
    policy = AnomalyPolicy(skip_budget=0, sync=False)
    policy.note_batch("poisoned")
    policy.on_step(None, None, 1, _vals(1, nf=1), (), ())
    policy.note_batch("healthy")           # next step already in flight
    policy.on_step(None, None, 2, _vals(0), (), ())
    assert policy.poll() == "quarantine"
    assert policy.ledger[0]["batch"] == "poisoned"


def test_policy_loss_spike_detector():
    monitor.stat_reset()
    policy = AnomalyPolicy(skip_budget=5, spike_window=8,
                           spike_factor=10.0)
    for s in range(6):
        policy.on_step(None, None, s + 1, _vals(0), ("loss",),
                       (np.asarray(1.0 + 0.01 * s),))
        assert policy.poll() == "ok"
    # a finite-but-huge loss (bitflip-class corruption): flag is clean
    # but the spike detector escalates anyway
    policy.on_step(None, None, 7, _vals(0), ("loss",),
                   (np.asarray(1e6),))
    assert policy.poll() == "skip"
    assert monitor.get_stat("anomaly.loss_spikes") == 1
    assert policy.loss_spikes == 1


def test_policy_requires_store_with_objects():
    with pytest.raises(ValueError, match="store AND objects"):
        AnomalyPolicy(store=object())


# -------------------------------------------- dataloader.batch point --
def test_dataloader_corrupt_point_and_fetch_batch_redelivery():
    from paddle_tpu.io import DataLoader

    class DS:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return np.full(2, float(i), np.float32)

    loader = DataLoader(DS(), batch_size=2, shuffle=False)
    fault.arm("dataloader.batch:action=corrupt,mode=nan,count=1,"
              "match=batch=1")
    batches = [np.asarray(b) for b in loader]
    assert np.isnan(batches[1]).any() and not np.isnan(batches[0]).any()
    assert not np.isnan(batches[2]).any()
    # re-delivery goes through the same point; the rule is exhausted,
    # so the retry is clean — the skip-retry contract
    again = np.asarray(loader.fetch_batch(1))
    assert not np.isnan(again).any()
    assert np.array_equal(again, np.stack([np.full(2, 2.0),
                                           np.full(2, 3.0)]))
    assert fault.fire_count("dataloader.batch") == 1


# --------------------------------------------- supervisor black box --
def test_supervisor_giveup_leaves_flight_dump(tmp_path):
    from paddle_tpu.distributed.supervisor import (SupervisorGaveUp,
                                                   TrainingSupervisor)
    from paddle_tpu.testing.chaos import _sv_flaky_entry

    state = str(tmp_path / "n")
    sv = TrainingSupervisor(
        _sv_flaky_entry, args=(state, 99, 5), name="doomed",
        startup_timeout_s=60.0, poll_s=0.05, backoff_s=0.01,
        backoff_max_s=0.02, crash_window_s=60.0, crash_budget=1,
        max_restarts=3, workdir=str(tmp_path))
    with pytest.raises(SupervisorGaveUp) as ei:
        sv.run()
    assert ei.value.exit_history
    dump = tmp_path / "supervisor_giveup.json"
    assert dump.exists(), "give-up left no flight dump"
    box = json.loads(dump.read_text())
    assert box["reason"] == "supervisor.give_up"
    extra = box["extra"]
    assert extra["supervisor"] == "doomed"
    assert extra["exit_history"] == ei.value.exit_history
    assert all(r["exit_code"] == 5 for r in extra["exit_history"])


# --------------------------------------------------- end-to-end drill --
def test_chaos_anomaly_scenario_in_process(tmp_path):
    """The full ISSUE 15 gate: injected NaN feeds, a non-finite grad
    bucket and a corrupted int8 wire payload end in loss-trajectory
    parity with the fault-free run, with skip/quarantine/rollback all
    asserted from anomaly.* stats and the rollback flight dump."""
    from paddle_tpu.testing import chaos
    assert chaos.anomaly_main(workdir=str(tmp_path)) == 0
