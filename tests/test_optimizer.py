"""Optimizer + LR schedule + clip tests (modelled on the reference's
test_sgd_op.py / test_adam_op.py / test_lr_scheduler.py oracles)."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.optimizer import lr as lr_mod


def _make_problem():
    paddle.seed(42)
    net = nn.Linear(4, 1, bias_attr=False)
    X = paddle.randn([32, 4])
    w_true = paddle.to_tensor([[1.0], [-2.0], [0.5], [3.0]])
    Y = X @ w_true
    return net, X, Y


def _train(net, X, Y, opt, steps=300):
    losses = []
    for _ in range(steps):
        loss = ((net(X) - Y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("cls,kw", [
    (optimizer.SGD, dict(learning_rate=0.1)),
    (optimizer.Momentum, dict(learning_rate=0.05, momentum=0.9)),
    (optimizer.Momentum, dict(learning_rate=0.05, momentum=0.9,
                              use_nesterov=True)),
    (optimizer.Adam, dict(learning_rate=0.1)),
    (optimizer.AdamW, dict(learning_rate=0.1, weight_decay=0.001)),
    (optimizer.RMSProp, dict(learning_rate=0.05)),
    (optimizer.Adagrad, dict(learning_rate=0.3)),
    (optimizer.Adadelta, dict(learning_rate=5.0)),
    (optimizer.Adamax, dict(learning_rate=0.1)),
    (optimizer.Lamb, dict(learning_rate=0.03, lamb_weight_decay=0.0)),
    (optimizer.LarsMomentum, dict(learning_rate=0.3, lars_weight_decay=0.0, lars_coeff=0.01)),
])
def test_optimizer_converges(cls, kw):
    net, X, Y = _make_problem()
    opt = cls(parameters=net.parameters(), **kw)
    losses = _train(net, X, Y, opt)
    assert losses[-1] < losses[0] * 0.1, (losses[0], losses[-1])


def test_sgd_matches_manual():
    p = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    opt = optimizer.SGD(learning_rate=0.1, parameters=[p])
    (p * p).sum().backward()
    opt.step()
    np.testing.assert_allclose(p.numpy(), [1 - 0.1 * 2, 2 - 0.1 * 4],
                               rtol=1e-6)


def test_adam_matches_reference_formula():
    p = paddle.to_tensor([1.0], stop_gradient=False)
    opt = optimizer.Adam(learning_rate=0.1, parameters=[p])
    (p * 3).sum().backward()
    opt.step()
    # step1: m=0.1*3=0.3, v=0.001*9=0.009, mhat=3, vhat=9
    expect = 1 - 0.1 * 3 / (3 + 1e-8)
    np.testing.assert_allclose(p.numpy(), [expect], rtol=1e-5)


def test_weight_decay_coupled():
    p = paddle.to_tensor([2.0], stop_gradient=False)
    opt = optimizer.SGD(learning_rate=0.1, parameters=[p],
                        weight_decay=0.5)
    paddle.to_tensor([1.0])
    (p * 0.0).sum().backward()   # zero grad; only decay acts
    opt.step()
    np.testing.assert_allclose(p.numpy(), [2 - 0.1 * 0.5 * 2], rtol=1e-6)


def test_adamw_decoupled_decay():
    p = paddle.to_tensor([2.0], stop_gradient=False)
    opt = optimizer.AdamW(learning_rate=0.1, parameters=[p],
                          weight_decay=0.1)
    (p * 0.0).sum().backward()
    opt.step()
    # decoupled: p -= lr*coeff*p then adam update with g=0
    np.testing.assert_allclose(p.numpy(), [2 * (1 - 0.01)], rtol=1e-5)


def test_grad_clip_global_norm():
    p1 = paddle.to_tensor([3.0], stop_gradient=False)
    p2 = paddle.to_tensor([4.0], stop_gradient=False)
    clip = optimizer.ClipGradByGlobalNorm(1.0)
    opt = optimizer.SGD(learning_rate=1.0, parameters=[p1, p2],
                        grad_clip=clip)
    (p1 * 3.0 + p2 * 4.0).sum().backward()   # grads 3, 4; norm 5
    opt.step()
    np.testing.assert_allclose(p1.numpy(), [3.0 - 3.0 / 5], rtol=1e-5)
    np.testing.assert_allclose(p2.numpy(), [4.0 - 4.0 / 5], rtol=1e-5)


def test_grad_clip_by_value():
    p = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
    opt = optimizer.SGD(learning_rate=1.0, parameters=[p],
                        grad_clip=optimizer.ClipGradByValue(0.5))
    (p * paddle.to_tensor([10.0, 0.1])).sum().backward()
    opt.step()
    np.testing.assert_allclose(p.numpy(), [0.5, 0.9], rtol=1e-5)


def test_lr_scheduler_with_optimizer():
    p = paddle.to_tensor([1.0], stop_gradient=False)
    sched = lr_mod.StepDecay(learning_rate=0.1, step_size=2, gamma=0.1)
    opt = optimizer.SGD(learning_rate=sched, parameters=[p])
    assert abs(opt.get_lr() - 0.1) < 1e-9
    sched.step()
    sched.step()
    assert abs(opt.get_lr() - 0.01) < 1e-9


@pytest.mark.parametrize("sched,expect", [
    (lambda: lr_mod.ExponentialDecay(1.0, 0.5), [1.0, 0.5, 0.25]),
    (lambda: lr_mod.NaturalExpDecay(1.0, 1.0),
     [1.0, np.exp(-1), np.exp(-2)]),
    (lambda: lr_mod.InverseTimeDecay(1.0, 1.0), [1.0, 0.5, 1 / 3]),
    (lambda: lr_mod.StepDecay(1.0, 2, 0.1), [1.0, 1.0, 0.1]),
    (lambda: lr_mod.MultiStepDecay(1.0, [1, 2]), [1.0, 0.1, 0.01]),
    (lambda: lr_mod.PiecewiseDecay([1, 2], [0.1, 0.2, 0.3]),
     [0.1, 0.2, 0.3]),
    (lambda: lr_mod.LambdaDecay(1.0, lambda e: 1 / (e + 1)),
     [1.0, 0.5, 1 / 3]),
])
def test_lr_schedules(sched, expect):
    s = sched()
    got = []
    for _ in expect:
        got.append(s())
        s.step()
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_cosine_annealing():
    s = lr_mod.CosineAnnealingDecay(1.0, T_max=10)
    assert abs(s() - 1.0) < 1e-9
    for _ in range(10):
        s.step()
    assert s() < 1e-9


def test_linear_warmup():
    s = lr_mod.LinearWarmup(0.5, warmup_steps=5, start_lr=0.0, end_lr=0.5)
    vals = []
    for _ in range(7):
        vals.append(s())
        s.step()
    np.testing.assert_allclose(vals[:5], [0.0, 0.1, 0.2, 0.3, 0.4],
                               rtol=1e-6)
    assert vals[5] == 0.5 and vals[6] == 0.5


def test_noam():
    s = lr_mod.NoamDecay(d_model=512, warmup_steps=10, learning_rate=1.0)
    prev = 0
    for i in range(10):
        cur = s()
        if 0 < i:
            assert cur >= prev  # warming up
        prev = cur
        s.step()
    for i in range(20):
        s.step()
    assert s() < prev  # decaying after warmup


def test_reduce_on_plateau():
    s = lr_mod.ReduceOnPlateau(1.0, patience=1, factor=0.5)
    s.step(1.0)
    s.step(1.0)   # bad epoch 1
    s.step(1.0)   # bad epoch 2 > patience -> reduce
    assert abs(s() - 0.5) < 1e-9


def test_optimizer_state_dict_roundtrip():
    net, X, Y = _make_problem()
    opt = optimizer.Adam(learning_rate=0.1, parameters=net.parameters())
    _train(net, X, Y, opt, steps=3)
    sd = opt.state_dict()
    opt2 = optimizer.Adam(learning_rate=0.1, parameters=net.parameters())
    opt2.set_state_dict(sd)
    assert opt2._step_count == 3
    s1 = opt._slots[id(net.parameters()[0])]
    s2 = opt2._slots[id(net.parameters()[0])]
    np.testing.assert_allclose(np.asarray(s1["m"]), np.asarray(s2["m"]))


def test_minimize_api():
    net, X, Y = _make_problem()
    opt = optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
    l0 = None
    for _ in range(60):
        loss = ((net(X) - Y) ** 2).mean()
        opt.minimize(loss)
        opt.clear_grad()
        l0 = l0 or float(loss)
    assert float(loss) < l0 * 0.2


def test_multi_precision_bf16():
    p = paddle.nn.Linear(4, 4).weight
    p.data = p.data.astype(paddle.bfloat16)
    opt = optimizer.Momentum(learning_rate=0.1, parameters=[p],
                             multi_precision=True)
    (p.sum() * 1.0).backward()
    opt.step()
    assert p.dtype == paddle.bfloat16
    assert opt._slots[id(p)]["master"].dtype == np.float32


def test_adamw_apply_decay_param_fun():
    # ADVICE r1: param_name must reach the decay decision in BOTH paths.
    w = paddle.nn.Linear(4, 4).weight
    w.name = "linear_0.w_0"
    b = paddle.nn.Linear(4, 4).bias
    b.name = "linear_0.b_0"
    w0, b0 = np.asarray(w.data).copy(), np.asarray(b.data).copy()
    opt = optimizer.AdamW(
        learning_rate=0.1, parameters=[w, b], weight_decay=0.5,
        apply_decay_param_fun=lambda n: not n.endswith("b_0"))
    # zero grads: only decoupled decay moves params
    w._grad_data = jnp.zeros_like(w.data)
    b._grad_data = jnp.zeros_like(b.data)
    opt.step()
    assert not np.allclose(np.asarray(w.data), w0), "weight must decay"
    np.testing.assert_allclose(np.asarray(b.data), b0, atol=1e-7)


def test_adamw_functional_decay_param_fun():
    w = paddle.nn.Linear(4, 4).weight
    w.name = "w_0"
    b = paddle.nn.Linear(4, 4).bias
    b.name = "b_0"
    opt = optimizer.AdamW(
        learning_rate=0.1, parameters=[w, b], weight_decay=0.5,
        apply_decay_param_fun=lambda n: not n.startswith("b"))
    states = opt.functional_init([w.data, b.data])
    zeros = [jnp.zeros_like(w.data), jnp.zeros_like(b.data)]
    (nw, nb), _ = opt.functional_update(
        [w.data, b.data], zeros, states, 0.1, 1, params_meta=[w, b])
    assert not np.allclose(np.asarray(nw), np.asarray(w.data))
    np.testing.assert_allclose(np.asarray(nb), np.asarray(b.data), atol=1e-7)


def test_eager_clip_before_decay_matches_functional():
    # ADVICE r1: eager step() must clip raw grads first, then regularize —
    # same order as functional_update.
    from paddle_tpu.optimizer.clip import ClipGradByGlobalNorm
    rng = np.random.RandomState(0)
    pa = paddle.nn.Linear(4, 4).weight
    pa.data = jnp.asarray(rng.randn(4, 4).astype(np.float32))
    g = jnp.asarray(rng.randn(4, 4).astype(np.float32) * 10)
    opt1 = optimizer.Momentum(learning_rate=0.1, parameters=[pa],
                              weight_decay=0.1,
                              grad_clip=ClipGradByGlobalNorm(1.0))
    p0 = pa.data
    states = opt1.functional_init([p0])
    (expect,), _ = opt1.functional_update([p0], [g], states, 0.1, 1,
                                          params_meta=[pa])
    pa._grad_data = g
    opt1.step()
    np.testing.assert_allclose(np.asarray(pa.data), np.asarray(expect),
                               rtol=1e-6)
