"""Remote-fs checkpoint hooks + model crypto (VERDICT r4 missing #9).

Reference: framework/io/fs.cc (localfs_*/hdfs_* shell CLI),
framework/io/crypto (AES model encryption).
"""
import io
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.utils import crypto, fs


class MemFS(fs.FileSystem):
    """In-memory FileSystem standing in for a remote store."""

    def __init__(self):
        self.files = {}
        self.dirs = set()

    def open_read(self, path):
        if path not in self.files:
            raise OSError(f"no such file {path}")
        return io.BytesIO(self.files[path])

    def open_write(self, path):
        files = self.files

        class _B(io.BytesIO):
            def close(s):
                files[path] = s.getvalue()
                super().close()

        return _B()

    def exists(self, path):
        return path in self.files or path in self.dirs

    def mkdir(self, path):
        self.dirs.add(path)

    def remove(self, path):
        self.files = {k: v for k, v in self.files.items()
                      if not k.startswith(path)}
        self.dirs.discard(path)

    def list(self, path):
        out = set()
        for k in set(self.files) | self.dirs:
            if k.startswith(path.rstrip("/") + "/"):
                out.add(k[len(path.rstrip("/")) + 1:].split("/")[0])
        return sorted(out)

    def mv(self, src, dst):
        self.files[dst] = self.files.pop(src)


@pytest.fixture()
def memfs():
    m = MemFS()
    fs.register_fs("mem", m)
    yield m
    fs._REGISTRY.pop("mem", None)


def test_save_load_through_registered_fs(memfs):
    net = nn.Linear(4, 3)
    sd = net.state_dict()
    paddle.save(sd, "mem://ckpt/model.pdparams")
    assert "mem://ckpt/model.pdparams" in memfs.files
    loaded = paddle.load("mem://ckpt/model.pdparams")
    np.testing.assert_allclose(np.asarray(loaded["weight"].data),
                               np.asarray(sd["weight"].data))


def test_train_epoch_range_on_remote_fs(memfs):
    """Preemption recovery against a remote store: snapshot, 'crash',
    resume from the published epoch (auto_checkpoint.py semantics)."""
    from paddle_tpu.utils.checkpoint import TrainEpochRange

    paddle.seed(80)
    net = nn.Linear(2, 2)
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    seen = []
    w_published = None
    r = TrainEpochRange(5, "mem://jobs/run1", model=net, opt=opt)
    for epoch in r:
        seen.append(epoch)
        net.weight.data = net.weight.data + 1.0
        if epoch == 1:
            w_published = np.asarray(net.weight.data).copy()
        if epoch == 2:
            # simulated preemption DURING epoch 2 — its snapshot never
            # publishes, so the resume point is after epoch 1
            break

    # new process: fresh objects resume from the last published snapshot
    paddle.seed(81)
    net2 = nn.Linear(2, 2)
    opt2 = optimizer.SGD(learning_rate=0.1, parameters=net2.parameters())
    r2 = TrainEpochRange(5, "mem://jobs/run1", model=net2, opt=opt2)
    resumed = [e for e in r2]
    assert resumed == [2, 3, 4]
    np.testing.assert_allclose(np.asarray(net2.weight.data), w_published)


def test_unregistered_scheme_is_loud():
    with pytest.raises(ValueError, match="register_fs"):
        fs.get_fs("s3://bucket/x")


def test_shellfs_missing_cli_is_loud():
    sf = fs.ShellFS("definitely_not_a_real_binary_xyz")
    with pytest.raises(RuntimeError, match="CLI not found"):
        sf.open_read("hdfs://x/y")


def test_encrypted_save_load_roundtrip(tmp_path):
    net = nn.Linear(3, 3)
    sd = net.state_dict()
    p = str(tmp_path / "enc.pdparams")
    paddle.save(sd, p, encryption_key="secret-key")
    raw = open(p, "rb").read()
    assert crypto.is_encrypted(raw[:8])
    # weights are not visible in the ciphertext
    w = np.asarray(sd["weight"].data).tobytes()
    assert w[:16] not in raw
    loaded = paddle.load(p, encryption_key="secret-key")
    np.testing.assert_allclose(np.asarray(loaded["weight"].data),
                               np.asarray(sd["weight"].data))


def test_wrong_key_and_missing_key_are_loud(tmp_path):
    p = str(tmp_path / "enc2.pdparams")
    paddle.save({"a": paddle.ones([2])}, p, encryption_key="k1")
    with pytest.raises(ValueError, match="encrypted"):
        paddle.load(p)
    with pytest.raises(ValueError, match="wrong key|corrupted"):
        paddle.load(p, encryption_key="k2")


def test_key_file_flow(tmp_path):
    kf = str(tmp_path / "model.key")
    key = crypto.generate_key_file(kf)
    assert len(key) == 32
    p = str(tmp_path / "enc3.pdparams")
    paddle.save({"a": paddle.ones([4])}, p,
                encryption_key=open(kf, "rb").read())
    out = paddle.load(p, encryption_key=open(kf, "rb").read())
    np.testing.assert_allclose(np.asarray(out["a"].data), 1.0)
