"""Tensor basics: creation, metadata, math methods, indexing.

Models the reference's tensor API tests
(python/paddle/fluid/tests/unittests/test_var_base.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_and_numpy():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert x.shape == [2, 2]
    assert x.dtype == paddle.float32
    np.testing.assert_allclose(x.numpy(), [[1, 2], [3, 4]])
    assert x.stop_gradient is True


def test_creation_ops():
    assert paddle.zeros([2, 3]).numpy().sum() == 0
    assert paddle.ones([2, 3]).numpy().sum() == 6
    np.testing.assert_allclose(paddle.full([2], 7.0).numpy(), [7, 7])
    np.testing.assert_allclose(paddle.arange(5).numpy(), np.arange(5))
    e = paddle.eye(3).numpy()
    np.testing.assert_allclose(e, np.eye(3, dtype=np.float32))
    np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(),
                               np.linspace(0, 1, 5), rtol=1e-6)


def test_arithmetic_operators():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
    np.testing.assert_allclose((a - b).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((a * b).numpy(), [4, 10, 18])
    np.testing.assert_allclose((b / a).numpy(), [4, 2.5, 2], rtol=1e-6)
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((2.0 - a).numpy(), [1, 0, -1])
    np.testing.assert_allclose((-a).numpy(), [-1, -2, -3])
    np.testing.assert_allclose((10.0 / a).numpy(), [10, 5, 10 / 3], rtol=1e-6)


def test_matmul():
    a = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    b = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    c = a @ b
    np.testing.assert_allclose(c.numpy(), a.numpy() @ b.numpy())
    d = paddle.matmul(a, b.t(), transpose_y=True)
    np.testing.assert_allclose(d.numpy(), a.numpy() @ b.numpy())
    e = paddle.matmul(a, a, transpose_y=True)
    np.testing.assert_allclose(e.numpy(), a.numpy() @ a.numpy().T)


def test_reductions():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert float(x.sum()) == 66
    assert float(x.mean()) == 5.5
    np.testing.assert_allclose(x.sum(axis=0).numpy(), [12, 15, 18, 21])
    np.testing.assert_allclose(x.max(axis=1).numpy(), [3, 7, 11])
    assert x.sum(axis=1, keepdim=True).shape == [3, 1]
    assert int(x.argmax()) == 11
    np.testing.assert_allclose(x.argmax(axis=0).numpy(), [2, 2, 2, 2])


def test_manipulation():
    x = paddle.arange(24, dtype="float32")
    y = x.reshape([2, 3, 4])
    assert y.shape == [2, 3, 4]
    z = y.transpose([2, 0, 1])
    assert z.shape == [4, 2, 3]
    assert y.flatten().shape == [24]
    assert y.flatten(1, 2).shape == [2, 12]
    assert y.unsqueeze(0).shape == [1, 2, 3, 4]
    assert y.unsqueeze([0, 2]).shape == [1, 2, 1, 3, 4]
    w = paddle.concat([y, y], axis=1)
    assert w.shape == [2, 6, 4]
    s = paddle.stack([x, x])
    assert s.shape == [2, 24]
    parts = paddle.split(paddle.ones([6, 2]), [2, 2, -1], axis=0)
    assert [p.shape for p in parts] == [[2, 2], [2, 2], [2, 2]]


def test_indexing():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose(x[0].numpy(), [0, 1, 2, 3])
    np.testing.assert_allclose(x[1, 2].numpy(), 6)
    np.testing.assert_allclose(x[:, 1].numpy(), [1, 5, 9])
    np.testing.assert_allclose(x[0:2, 1:3].numpy(), [[1, 2], [5, 6]])
    idx = paddle.to_tensor(np.array([0, 2]))
    np.testing.assert_allclose(x[idx].numpy(), x.numpy()[[0, 2]])
    # setitem is functional under the hood but keeps python identity
    x[0, 0] = 100.0
    assert float(x[0, 0]) == 100.0


def test_gather_scatter():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = paddle.to_tensor(np.array([0, 2]))
    g = paddle.gather(x, idx)
    np.testing.assert_allclose(g.numpy(), x.numpy()[[0, 2]])
    upd = paddle.ones([2, 3])
    s = paddle.scatter(x, idx, upd)
    expect = x.numpy().copy()
    expect[[0, 2]] = 1
    np.testing.assert_allclose(s.numpy(), expect)


def test_where_topk_sort():
    x = paddle.to_tensor([3.0, 1.0, 2.0])
    v, i = paddle.topk(x, 2)
    np.testing.assert_allclose(v.numpy(), [3, 2])
    np.testing.assert_allclose(i.numpy(), [0, 2])
    s = paddle.sort(x, descending=True)
    np.testing.assert_allclose(s.numpy(), [3, 2, 1])
    w = paddle.where(x > 1.5, x, paddle.zeros_like(x))
    np.testing.assert_allclose(w.numpy(), [3, 0, 2])


def test_cast_and_dtype():
    x = paddle.ones([2], dtype="float32")
    y = x.astype("int32")
    assert y.dtype == paddle.int32
    z = x.astype(paddle.bfloat16)
    assert z.dtype == paddle.bfloat16


def test_comparison_and_logic():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([3.0, 2.0, 1.0])
    np.testing.assert_array_equal((a < b).numpy(), [True, False, False])
    np.testing.assert_array_equal((a == b).numpy(), [False, True, False])
    assert bool(paddle.allclose(a, a))
    assert not bool(paddle.allclose(a, b))


def test_inplace_style():
    x = paddle.ones([2, 2])
    x.zero_()
    assert x.numpy().sum() == 0
    x.fill_(3.0)
    assert x.numpy().sum() == 12
    x.set_value(np.eye(2, dtype=np.float32))
    np.testing.assert_allclose(x.numpy(), np.eye(2))


def test_random_reproducible():
    import paddle_tpu
    paddle_tpu.seed(7)
    a = paddle.randn([4, 4])
    paddle_tpu.seed(7)
    b = paddle.randn([4, 4])
    np.testing.assert_allclose(a.numpy(), b.numpy())
    c = paddle.rand([1000])
    assert 0.0 <= float(c.min()) and float(c.max()) <= 1.0
    r = paddle.randint(0, 10, [100])
    assert r.numpy().min() >= 0 and r.numpy().max() < 10


def test_save_load(tmp_path):
    path = str(tmp_path / "ckpt.pdparams")
    obj = {"w": paddle.ones([2, 3]), "step": 7, "nested": [paddle.zeros([1])]}
    paddle.save(obj, path)
    loaded = paddle.load(path)
    np.testing.assert_allclose(loaded["w"].numpy(), np.ones((2, 3)))
    assert loaded["step"] == 7
    np.testing.assert_allclose(loaded["nested"][0].numpy(), [0])


def test_pad_short_form_pads_last_dim_first():
    x = paddle.ones([1, 1, 2, 2])
    y = paddle.ops.pad(x, [1, 0, 0, 0])  # pad W left
    assert y.shape == [1, 1, 2, 3]
    z = paddle.ops.pad(x, [0, 0, 1, 1])  # pad H both sides
    assert z.shape == [1, 1, 4, 2]


def test_mode():
    v, i = paddle.ops.mode(paddle.to_tensor([3.0, 3.0, 3.0, 3.0, 7.0, 7.0, 1.0, 2.0]))
    assert float(v) == 3.0


def test_multinomial_batched():
    p = paddle.ones([4, 3])
    s = paddle.multinomial(p, 2, replacement=True)
    assert s.shape == [4, 2]
    s2 = paddle.multinomial(p, 2, replacement=False)
    assert s2.shape == [4, 2]
    row = s2.numpy()
    assert all(len(set(r)) == 2 for r in row)  # no replacement
