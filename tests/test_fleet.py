"""Fleet observatory (ISSUE 20): per-process telemetry spooling, the
cross-process aggregator (merged snapshot / Prometheus / chrome-trace),
distributed request tracing over HTTP, and the /admin fleet surface."""
import http.client
import json
import os
import re
import threading
import time
from urllib.parse import urlparse

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import observability as obs, serving
from paddle_tpu.core import obs_hook
from paddle_tpu.observability import export as obs_export
from paddle_tpu.observability import fleet
from paddle_tpu.testing.chaos import make_dyadic_lm
from paddle_tpu.utils import monitor

# the PR-9 text exposition grammar gate (tools/obs_smoke.py keeps the
# same regex): proc-labelled fleet samples must still parse under it
PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+naif]+$")


@pytest.fixture
def spool(tmp_path):
    """Exporter flags on, pointing at a tmp spool; everything restored
    (and the exporter gone) on the way out."""
    old = paddle.get_flags(["obs_spool_dir", "obs_role",
                            "obs_export_interval_s"])
    d = str(tmp_path / "spool")
    paddle.set_flags({"obs_spool_dir": d, "obs_role": "t",
                      "obs_export_interval_s": 60.0})
    yield d
    obs_export.uninstall_exporter()
    paddle.set_flags(old)
    obs.disable()


@pytest.fixture(autouse=True)
def _clean_tracer():
    yield
    obs_export.uninstall_exporter()
    obs.disable()


# ------------------------------------------------- checksummed spool --
def test_checksum_roundtrip_and_corruption():
    body = {"role": "r", "pid": 1, "nested": {"a": [1, 2]}}
    data = obs_export.checksum_wrap(body)
    assert obs_export.checksum_unwrap(data) == body
    doc = json.loads(data)
    doc["body"]["pid"] = 2          # bit-flip after the digest
    with pytest.raises(ValueError, match="checksum mismatch"):
        obs_export.checksum_unwrap(json.dumps(doc).encode())
    with pytest.raises(ValueError):
        obs_export.checksum_unwrap(b'{"no": "digest"}')


def test_exporter_spools_and_read_spool_roundtrip(spool):
    exp = obs_export.install_exporter()
    assert exp is obs_hook._export and exp.role == "t"
    trc = obs_hook._tracer      # install enables one if none was live
    tid = "trace0001"
    trc.set_trace(tid)
    sid = trc.begin_span("unit.work", trace=tid)
    monitor.stat_add("fleet_test.requests", 5)
    trc.end_span(sid)
    trc.clear_trace()
    assert exp.flush()
    procs = fleet.read_spool(spool)
    assert [p["label"] for p in procs] == [f"t-{os.getpid()}"]
    p = procs[0]
    assert p["role"] == "t" and p["pid"] == os.getpid()
    assert p["corrupt"] == 0 and p["segments"] >= 1
    assert p["meta"]["build"]["jax"]
    assert p["metrics"]["stats"]["fleet_test.requests"] >= 5
    spans = [e for e in p["events"] if e.get("name") == "unit.work"]
    assert spans and spans[0]["trace"] == tid
    # wall-clock stamped so lanes align across monotonic epochs
    assert spans[0]["time"] == pytest.approx(time.time(), abs=120)


def test_read_spool_flags_corrupt_documents_without_raising(spool):
    exp = obs_export.install_exporter()
    obs_hook._tracer.emit("unit", "e1")
    assert exp.flush()
    [p] = fleet.read_spool(spool)
    seg = next(f for f in os.listdir(p["dir"]) if f.startswith("trace-"))
    path = os.path.join(p["dir"], seg)
    raw = json.loads(open(path).read())
    raw["body"]["events"] = []      # tamper: digest no longer matches
    open(path, "w").write(json.dumps(raw))
    [p2] = fleet.read_spool(spool)
    assert p2["corrupt"] == 1 and not p2["events"]


def test_fleet_snapshot_and_prometheus_proc_labels(spool):
    exp = obs_export.install_exporter()
    monitor.stat_add("fleet_test.gauge", 2)
    exp.flush()
    snap = fleet.fleet_snapshot(spool, include_self=False)
    assert set(snap["procs"]) == {f"t-{os.getpid()}"}
    assert snap["build_skew"] == []     # one build -> no skew
    text = fleet.fleet_prometheus_text(spool, include_self=False)
    lines = [ln for ln in text.splitlines()
             if ln and not ln.startswith("#")]
    assert lines
    bad = [ln for ln in lines if not PROM_LINE.match(ln)]
    assert not bad, bad[:3]
    unlabelled = [ln for ln in lines if 'proc="' not in ln]
    assert not unlabelled, unlabelled[:3]
    assert f'proc="t-{os.getpid()}"' in text


def test_merged_chrome_trace_names_one_lane_per_process(spool):
    # two "processes": two exporters with distinct roles sharing the
    # spool (read_spool keys by directory, not by live pid)
    exp_a = obs_export.install_exporter(role="a")
    obs_hook._tracer.emit("unit", "from_a")
    exp_a.flush()
    exp_b = obs_export.install_exporter(role="b")
    obs_hook._tracer.emit("unit", "from_b")
    exp_b.flush()
    merged = fleet.merged_chrome_trace(spool, include_self=False)
    evs = merged["traceEvents"]
    names = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    pid = os.getpid()
    assert {f"a-{pid}", f"b-{pid}"} <= names
    assert all(e["ts"] >= 0 for e in evs if e["ph"] != "M")


# --------------------------------------- distributed request tracing --
@pytest.fixture(scope="module")
def gen_server():
    paddle.seed(3)
    model = make_dyadic_lm()
    eng = serving.GenerationEngine(model, num_slots=2, page_size=4,
                                   max_context=32)
    srv = serving.ServingServer(None, port=0, generation=eng).start()
    yield srv
    srv.close()
    eng.close()


def _raw_generate(srv, headers):
    u = urlparse(srv.url)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=30)
    try:
        body = json.dumps({"prompt": [1, 2], "max_new_tokens": 2})
        conn.request("POST", "/generate", body=body, headers=dict(
            {"Content-Type": "application/json"}, **headers))
        r = conn.getresponse()
        raw = r.read().decode()
        last = json.loads(raw.strip().splitlines()[-1]) if raw else {}
        return r.status, dict(r.getheaders()), last
    finally:
        conn.close()


def test_server_adopts_wellformed_trace_id(gen_server):
    tracer = obs.enable(capacity=4096)
    try:
        status, hdrs, _ = _raw_generate(
            gen_server, {"X-Trace-Id": "req-abc.1", "X-Parent-Span": "7"})
        assert status == 200
        assert hdrs.get("X-Trace-Id") == "req-abc.1"
        # the handler's root span lands right after the last chunk is
        # written — a beat after the client sees the stream end
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            evs = [e for e in tracer.events()
                   if e.get("trace") == "req-abc.1"]
            if "http.generate" in {e["name"] for e in evs}:
                break
            time.sleep(0.02)
        names = {e["name"] for e in evs}
        assert "http.generate" in names     # the handler span adopted it
        # this process's root names the caller's span id
        assert any(e.get("remote_parent") == "7" for e in evs)
    finally:
        obs.disable()


@pytest.mark.parametrize("bad", [
    "spaces are bad", "bang!", "", "x" * 65, "-leadingdash",
    "unicodeé", '"quoted"'])
def test_malformed_trace_id_gets_fresh_id_never_500(gen_server, bad):
    status, hdrs, _ = _raw_generate(gen_server, {"X-Trace-Id": bad})
    assert status == 200
    echoed = hdrs.get("X-Trace-Id")
    assert echoed and echoed != bad     # minted, not adopted
    assert re.fullmatch(r"[0-9a-f]{32}", echoed)


def test_oversized_parent_span_ignored_not_500(gen_server):
    status, hdrs, _ = _raw_generate(
        gen_server, {"X-Trace-Id": "ok-id", "X-Parent-Span": "not-int"})
    assert status == 200 and hdrs.get("X-Trace-Id") == "ok-id"


def test_client_stamps_and_reports_trace_ids(gen_server):
    client = serving.Client(gen_server.url)
    assert client.last_trace_id is None
    client.generate([1, 2], max_new_tokens=2)
    first = client.last_trace_id
    assert first and re.fullmatch(r"[0-9a-f]{32}", first)
    client.generate([1, 2], max_new_tokens=2)
    assert client.last_trace_id != first    # minted per request
    pinned = serving.Client(gen_server.url, trace_id="pin-1")
    pinned.generate([1, 2], max_new_tokens=2)
    pinned.generate([1, 3], max_new_tokens=2)
    assert pinned.last_trace_id == "pin-1"


def test_trace_context_survives_reconnect_retry():
    """The retry loop must replay the SAME X-Trace-Id: headers are
    stamped once before _request, reconnect attempts reuse them."""
    tracer = obs.enable(capacity=4096)
    paddle.seed(3)
    model = make_dyadic_lm()
    eng = serving.GenerationEngine(model, num_slots=2, page_size=4,
                                   max_context=32)
    srv = serving.ServingServer(None, port=0, generation=eng).start()
    port = srv.port
    srv.close()                         # replica goes down
    client = serving.Client(f"http://127.0.0.1:{port}",
                            trace_id="retry-trace")
    client.reconnect_backoff_s = 1.0
    box = {}

    def restart():
        time.sleep(0.1)
        box["srv"] = serving.ServingServer(
            None, port=port, generation=eng).start()

    t = threading.Thread(target=restart)
    t.start()
    try:
        out = client.generate([1, 2], max_new_tokens=2)
        assert isinstance(out, list) and client.reconnects >= 1
        assert client.last_trace_id == "retry-trace"
        evs = [e for e in tracer.events()
               if e.get("trace") == "retry-trace"]
        assert {"client.generate", "http.generate"} <= {
            e["name"] for e in evs}
    finally:
        t.join()
        box["srv"].close()
        eng.close()
        obs.disable()


def test_assemble_trace_connects_client_and_server_spans(spool):
    exp = obs_export.install_exporter()
    model = make_dyadic_lm()
    eng = serving.GenerationEngine(model, num_slots=2, page_size=4,
                                   max_context=32)
    srv = serving.ServingServer(None, port=0, generation=eng).start()
    try:
        client = serving.Client(srv.url, trace_id="asm-1")
        client.generate([1, 2], max_new_tokens=2)
        exp.flush()
        procs = fleet.read_spool(spool)
        asm = fleet.assemble_trace(procs, "asm-1")
        assert asm["connected"] and asm["components"] == 1
        assert asm["events"] >= 3       # client + http + engine spans
        assert "client.generate" in asm["names"]
        assert "http.generate" in asm["names"]
    finally:
        srv.close()
        eng.close()


# ------------------------------------------------------ admin surface --
def test_admin_fleet_aggregates_two_replicas(gen_server):
    paddle.seed(11)
    model = make_dyadic_lm()
    eng_b = serving.GenerationEngine(model, num_slots=2, page_size=4,
                                     max_context=32)
    srv_b = serving.ServingServer(None, port=0,
                                  generation=eng_b).start()
    fv = fleet.FleetView(timeout_s=5.0)
    fv.register("lm", urls=[gen_server.url, srv_b.url])
    gen_server.attach_fleet(fv)
    try:
        client = serving.Client(gen_server.url)
        snap = client._get_json("/admin/fleet")
        lm = snap["fleet"]["lm"]
        assert lm["count"] == 2 and lm["ready"] == 2
        assert all(r["reachable"] for r in lm["replicas"])
    finally:
        gen_server.attach_fleet(None)
        srv_b.close()
        eng_b.close()


def test_admin_trace_returns_merged_chrome_trace(gen_server, spool):
    exp = obs_export.install_exporter()
    client = serving.Client(gen_server.url, trace_id="admin-t")
    client.generate([1, 2], max_new_tokens=2)
    exp.flush()
    raw = client._post("/admin/trace?secs=0", b"",
                       {"Content-Type": "application/json"})
    trace = json.loads(raw)
    assert isinstance(trace["traceEvents"], list) and trace["traceEvents"]
    # bad secs is a 400, not a 500
    u = urlparse(gen_server.url)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=10)
    try:
        conn.request("POST", "/admin/trace?secs=nope")
        assert conn.getresponse().status == 400
    finally:
        conn.close()
