"""Zero-downtime weight hot-swap tests (ISSUE 18): the SnapshotStore
publish/verify roundtrip, WeightWatcher apply / reject / rollback
semantics on live engines, readiness + ``weights_version`` surfacing on
``/healthz`` and Prometheus, and the disabled-path cost contracts
(swap machinery must never touch the per-batch / per-step hot paths).
"""
import os
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference, jit, serving
from paddle_tpu.jit import InputSpec
from paddle_tpu.serving.hotswap import (ARTIFACT_PAYLOAD, PARAMS_PAYLOAD,
                                        WeightWatcher, publish_weights)
from paddle_tpu.testing.chaos import (_scaled_artifact, make_dyadic_lm,
                                      make_dyadic_model)
from paddle_tpu.utils import monitor
from paddle_tpu.utils.checkpoint import SnapshotStore


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    """Two jit.save artifacts of the dyadic model: v2's weights are
    v1's scaled by 0.5 (power of two: outputs stay bitwise-exact), so
    every response is attributable to exactly one version."""
    d = str(tmp_path_factory.mktemp("hotswap_artifacts"))
    return {1: _scaled_artifact(1.0, d, "v1"),
            2: _scaled_artifact(0.5, d, "v2")}


def _engine(prefix, **kw):
    pred = inference.create_predictor(inference.Config(prefix))
    kw.setdefault("max_batch_size", 8)
    kw.setdefault("batch_timeout_ms", 5.0)
    eng = serving.InferenceEngine(pred, **kw)
    eng.warmup()
    return eng, pred


def _gen_params(scale=1.0):
    base = make_dyadic_lm().params
    return {k: (np.asarray(a) * scale).astype(np.asarray(a).dtype)
            for k, a in base.items()}


def _dyadic(rng, n=4, rows=2):
    return [(rng.randint(-8, 9, (rows, 8)) / 4.0).astype(np.float32)
            for _ in range(n)]


def _flip_byte(path, offset=20):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


# ----------------------------------------------------- publish side --
def test_publish_roundtrip(tmp_path, artifacts):
    store = SnapshotStore(str(tmp_path))
    params = _gen_params(0.5)
    meta = publish_weights(store, 7, artifact_prefix=artifacts[1],
                           params=params)
    assert int(meta["step"]) == 7
    digs = meta["digests"]
    assert f"{ARTIFACT_PAYLOAD}.pdparams" in digs
    assert f"{PARAMS_PAYLOAD}.pdparams" in digs
    loaded = store.load_payloads([ARTIFACT_PAYLOAD, PARAMS_PAYLOAD], meta)
    assert loaded is not None
    got = loaded[PARAMS_PAYLOAD]
    assert set(got) == set(params)
    for k in params:
        np.testing.assert_array_equal(np.asarray(got[k]), params[k])
    with open(artifacts[1] + ".pdmodel", "rb") as f:
        raw = f.read()
    assert np.asarray(loaded[ARTIFACT_PAYLOAD]["pdmodel"],
                      np.uint8).tobytes() == raw


def test_publish_needs_a_payload(tmp_path):
    with pytest.raises(ValueError, match="artifact_prefix"):
        publish_weights(SnapshotStore(str(tmp_path)), 1)


# ------------------------------------------------------- apply path --
def test_watcher_applies_both_engines(tmp_path, artifacts):
    monitor.stat_reset()
    eng, pred1 = _engine(artifacts[1])
    pred2 = inference.create_predictor(inference.Config(artifacts[2]))
    gen = serving.GenerationEngine(make_dyadic_lm(), num_slots=2,
                                   page_size=4, max_context=16,
                                   prompt_buckets=[4])
    gen.warmup()
    # the bitwise reference for the swapped generation weights: a model
    # BORN with the scaled params must emit the same tokens (compiles
    # lazily — no recompile assertion is made against it)
    m2 = make_dyadic_lm()
    m2.params = _gen_params(0.5)
    ref_gen = serving.GenerationEngine(m2, num_slots=2, page_size=4,
                                       max_context=16, prompt_buckets=[4])
    try:
        xs = _dyadic(np.random.RandomState(0))
        refs2 = [np.asarray(pred2.run([x])[0]) for x in xs]
        ref_toks = ref_gen.generate_sync([1, 2, 3], timeout=60,
                                         max_new_tokens=6,
                                         temperature=0.7, seed=5)
        store = SnapshotStore(str(tmp_path))
        w = WeightWatcher(store, engine=eng, generation=gen)
        assert w.check_once() is None           # empty store: nothing
        publish_weights(store, 2, artifact_prefix=artifacts[2],
                        params=_gen_params(0.5))
        assert w.check_once() == 2
        assert eng.weights_version == 2
        assert gen.weights_version == 2
        for x, r in zip(xs, refs2):
            np.testing.assert_array_equal(
                eng.infer_sync([x], timeout=30)[0], r)
        toks = gen.generate_sync([1, 2, 3], timeout=60, max_new_tokens=6,
                                 temperature=0.7, seed=5)
        assert toks == ref_toks
        st = eng.stats()
        assert st["recompiles_after_warmup"] == 0
        assert st["counters"]["weight_swaps"] == 1
        assert st["weights_version"] == 2
        gs = gen.stats()
        assert gs["recompiles_after_warmup"] == 0
        assert gs["counters"]["weight_swaps"] == 1
        assert monitor.get_stat("serving.swap.applied") == 1
        assert w.check_once() is None           # already applied: no-op
        assert monitor.get_stat("serving.swap.applied") == 1
    finally:
        eng.close()
        gen.close()
        ref_gen.close()


def test_background_watcher_applies(tmp_path, artifacts):
    eng, _ = _engine(artifacts[1])
    w = None
    try:
        store = SnapshotStore(str(tmp_path))
        w = WeightWatcher(store, engine=eng, poll_s=0.02).start()
        publish_weights(store, 2, artifact_prefix=artifacts[2])
        deadline = time.monotonic() + 60
        while w.version != 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert w.version == 2
        assert eng.weights_version == 2
    finally:
        if w is not None:
            w.stop()
        eng.close()


# --------------------------------------------------- rejection path --
def test_corrupt_snapshot_rejected_and_pinned(tmp_path, artifacts):
    monitor.stat_reset()
    eng, pred1 = _engine(artifacts[1])
    try:
        store = SnapshotStore(str(tmp_path))
        w = WeightWatcher(store, engine=eng)
        snap = publish_weights(store, 2, artifact_prefix=artifacts[2])
        _flip_byte(os.path.join(store.dir, snap["dir"],
                                f"{ARTIFACT_PAYLOAD}.pdparams"))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert w.check_once() is None
        assert w.last_rejected == 2
        assert eng.weights_version == 0
        assert monitor.get_stat("serving.swap.rejected") == 1
        # pinned: the next poll does not re-attempt the bad version
        assert w.check_once() is None
        assert monitor.get_stat("serving.swap.rejected") == 1
        x = (np.ones((2, 8)) / 4.0).astype(np.float32)
        np.testing.assert_array_equal(
            eng.infer_sync([x], timeout=30)[0],
            np.asarray(pred1.run([x])[0]))      # still serving v0
    finally:
        eng.close()


def test_partial_and_foreign_snapshots(tmp_path, artifacts):
    monitor.stat_reset()
    eng, _ = _engine(artifacts[1])
    gen = serving.GenerationEngine(make_dyadic_lm(), num_slots=2,
                                   page_size=4, max_context=64)
    try:
        store = SnapshotStore(str(tmp_path))
        # params-only snapshot, inference-only replica: not a payload
        # this watcher serves — skipped quietly (a training checkpoint
        # sharing the store must not poison the swap loop)
        w_inf = WeightWatcher(store, engine=eng)
        publish_weights(store, 2, params=_gen_params(0.5))
        assert w_inf.check_once() is None
        assert w_inf.last_rejected is None
        assert monitor.get_stat("serving.swap.rejected") == 0
        # same snapshot, replica serving BOTH engines: partial → reject
        w_both = WeightWatcher(store, engine=eng, generation=gen)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert w_both.check_once() is None
        assert w_both.last_rejected == 2
        assert "partial snapshot" in w_both.last_error
        assert eng.weights_version == 0
        assert gen.weights_version == 0
    finally:
        eng.close()
        gen.close()


def test_mismatched_artifact_rejected_before_commit(tmp_path, artifacts):
    """A replacement whose shapes disagree with the serving signature
    must fail in prewarm — off the dispatch path, before any commit."""
    paddle.seed(9)
    m = make_dyadic_model(in_dim=4, hidden=8, out_dim=2)
    prefix = os.path.join(str(tmp_path), "narrow")
    jit.save(m, prefix, input_spec=[InputSpec([None, 4], "float32")])
    eng, pred1 = _engine(artifacts[1])
    try:
        store = SnapshotStore(os.path.join(str(tmp_path), "s"))
        w = WeightWatcher(store, engine=eng)
        publish_weights(store, 2, artifact_prefix=prefix)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert w.check_once() is None
        assert w.last_rejected == 2
        assert "artifact rejected" in w.last_error
        assert eng.weights_version == 0
        x = (np.ones((1, 8)) / 4.0).astype(np.float32)
        np.testing.assert_array_equal(
            eng.infer_sync([x], timeout=30)[0],
            np.asarray(pred1.run([x])[0]))
    finally:
        eng.close()


def test_prewarm_rejects_input_name_mismatch(artifacts):
    class WrongSignature:
        def get_input_names(self):
            return ["a", "b"]

    eng, _ = _engine(artifacts[1])
    try:
        # the name gate fires before any feed is built or run
        with pytest.raises(ValueError, match="replacement artifact"):
            eng.prewarm_predictor(WrongSignature())
    finally:
        eng.close()


def test_swap_on_closed_engine_raises(artifacts):
    eng, pred = _engine(artifacts[1])
    eng.close()
    with pytest.raises(serving.EngineClosed):
        eng.swap_predictor(pred, 1)


# ----------------------------------------------------- rollback path --
def test_rollback_when_generation_apply_fails(tmp_path, artifacts):
    """Artifact verifies and commits to inference, then the generation
    params are rejected (shape mismatch): the replica must never serve
    two versions — the inference commit is rolled back, still warm."""
    monitor.stat_reset()
    eng, pred1 = _engine(artifacts[1])
    gen = serving.GenerationEngine(make_dyadic_lm(), num_slots=2,
                                   page_size=4, max_context=64)
    try:
        store = SnapshotStore(str(tmp_path))
        w = WeightWatcher(store, engine=eng, generation=gen)
        bad = _gen_params(0.5)
        k0 = sorted(bad)[0]
        bad[k0] = bad[k0].reshape(-1)           # wrong shape: rejected
        publish_weights(store, 2, artifact_prefix=artifacts[2],
                        params=bad)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            assert w.check_once() is None
        assert w.last_rejected == 2
        assert "generation apply failed" in w.last_error
        assert eng.weights_version == 0         # rolled back
        assert gen.weights_version == 0
        assert monitor.get_stat("serving.swap.rolled_back") == 1
        assert monitor.get_stat("serving.swap.applied") == 0
        st = eng.stats()
        assert st["counters"]["weight_swaps"] == 2  # commit + rollback
        assert st["recompiles_after_warmup"] == 0   # old pred still warm
        x = (np.ones((2, 8)) / 4.0).astype(np.float32)
        np.testing.assert_array_equal(
            eng.infer_sync([x], timeout=30)[0],
            np.asarray(pred1.run([x])[0]))
    finally:
        eng.close()
        gen.close()


# ------------------------------------------------------ observability --
def test_healthz_and_prometheus_surfaces(tmp_path, artifacts):
    eng, _ = _engine(artifacts[1])
    srv = serving.ServingServer(eng, port=0, ready=False).start()
    try:
        client = serving.Client(srv.url)
        h = client.healthz()
        assert h["status"] == "warming" and h["ready"] is False
        assert client._retry_after > 0          # Retry-After honored
        srv.mark_ready()
        h = client.healthz()
        assert h["ready"] is True and h["weights_version"] == 0
        store = SnapshotStore(str(tmp_path))
        w = WeightWatcher(store, engine=eng)
        publish_weights(store, 5, artifact_prefix=artifacts[2])
        assert w.check_once() == 5
        assert client.healthz()["weights_version"] == 5
        text = client.metrics_text()
        assert "paddle_tpu_serving_weights_version 5" in text
        assert "paddle_tpu_serving_ready 1" in text
        srv.mark_unready()
        assert client.healthz()["status"] == "warming"
        assert "paddle_tpu_serving_ready 0" in client.metrics_text()
    finally:
        srv.close()
        eng.close()


# ------------------------------------------------- cost contracts ----
def test_disabled_path_cost_contracts():
    """Swap support must cost the steady state exactly one attribute
    check in the scheduler loop and NOTHING on the per-batch / per-step
    hot paths; supervised liveness is one heartbeat hook per dispatch."""
    from paddle_tpu.serving.engine import InferenceEngine
    from paddle_tpu.serving.generation import GenerationEngine
    loop = GenerationEngine._loop.__code__.co_names
    assert "_pending_swap" in loop
    assert "_commit_swap_locked" in loop
    for fn in (GenerationEngine._decode_step, GenerationEngine._prefill):
        names = fn.__code__.co_names
        assert "_pending_swap" not in names
        assert "_commit_swap_locked" not in names
        assert "swap_weights" not in names
    exe = InferenceEngine._execute.__code__.co_names
    assert "_pending_swap" not in exe
    assert "swap_predictor" not in exe
    assert "_heartbeat" in exe      # the one supervised-liveness hook
    assert "_heartbeat" in GenerationEngine._decode_step.__code__.co_names
