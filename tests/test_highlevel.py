"""amp / io / metric / distribution / vision / text / hapi.Model tests
(modelled on the reference's test_amp*, test_dataloader*, test_metrics,
test_distribution, test_model.py suites)."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import amp, distribution, hapi, io, metric, nn, optimizer
from paddle_tpu.vision import datasets as vdatasets
from paddle_tpu.vision import models as vmodels
from paddle_tpu.vision import transforms as T


# ---------------- amp ----------------

def test_auto_cast_white_op_bf16():
    a = paddle.randn([4, 4])
    b = paddle.randn([4, 4])
    with amp.auto_cast():
        c = paddle.matmul(a, b)
        d = a + b  # gray op: follows inputs (fp32)
        e = paddle.exp(a)  # black op: fp32
    assert c.dtype == paddle.bfloat16
    assert d.dtype == paddle.float32
    assert e.dtype == paddle.float32
    c2 = paddle.matmul(a, b)
    assert c2.dtype == paddle.float32


def test_auto_cast_custom_lists():
    a = paddle.randn([4, 4])
    with amp.auto_cast(custom_white_list={"exp"}):
        e = paddle.exp(a)
    assert e.dtype == paddle.bfloat16


def test_auto_cast_O2():
    a = paddle.randn([4])
    with amp.auto_cast(level="O2"):
        out = paddle.tanh(a)  # gray op runs low-precision at O2
    assert out.dtype == paddle.bfloat16


def test_grad_scaler_fp16_flow():
    net = nn.Linear(4, 2)
    opt = optimizer.SGD(0.1, parameters=net.parameters())
    scaler = amp.GradScaler(init_loss_scaling=1024.0)
    x = paddle.randn([8, 4])
    y = paddle.randn([8, 2])
    loss = nn.MSELoss()(net(x), y)
    w0 = net.weight.numpy().copy()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.step(opt)
    assert not np.allclose(net.weight.numpy(), w0)


def test_grad_scaler_skips_on_inf():
    p = paddle.to_tensor([1.0], stop_gradient=False)
    opt = optimizer.SGD(0.1, parameters=[p])
    scaler = amp.GradScaler(init_loss_scaling=4.0)
    (p * float("inf")).backward()
    scaler.step(opt)
    scaler.update()
    np.testing.assert_allclose(p.numpy(), [1.0])  # step skipped
    assert scaler._scale == 2.0  # halved


# ---------------- io ----------------

def test_tensor_dataset_dataloader():
    X = np.random.rand(20, 3).astype(np.float32)
    Y = np.arange(20).astype(np.int64)
    ds = io.TensorDataset([X, Y])
    dl = io.DataLoader(ds, batch_size=6, shuffle=False, drop_last=False)
    batches = list(dl)
    assert len(batches) == 4
    xb, yb = batches[0]
    assert xb.shape == [6, 3]
    np.testing.assert_allclose(yb.numpy(), [0, 1, 2, 3, 4, 5])
    assert batches[-1][0].shape == [2, 3]


def test_dataloader_shuffle_covers_all():
    ds = io.TensorDataset([np.arange(10).astype(np.float32)])
    dl = io.DataLoader(ds, batch_size=3, shuffle=True)
    seen = np.sort(np.concatenate([b[0].numpy() for b in dl]))
    np.testing.assert_allclose(seen, np.arange(10))


def test_dataloader_workers_ordered():
    ds = io.TensorDataset([np.arange(30).astype(np.float32)])
    dl = io.DataLoader(ds, batch_size=5, shuffle=False, num_workers=3)
    out = np.concatenate([b[0].numpy() for b in dl])
    np.testing.assert_allclose(out, np.arange(30))


def test_custom_dataset_and_collate():
    class DS(io.Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return {"x": np.full(2, i, np.float32), "y": i}

    dl = io.DataLoader(DS(), batch_size=4)
    b = next(iter(dl))
    assert set(b) == {"x", "y"}
    assert b["x"].shape == [4, 2]


def test_distributed_batch_sampler_shards():
    ds = io.TensorDataset([np.arange(10).astype(np.float32)])
    s0 = io.DistributedBatchSampler(ds, 2, num_replicas=2, rank=0)
    s1 = io.DistributedBatchSampler(ds, 2, num_replicas=2, rank=1)
    i0 = [i for b in s0 for i in b]
    i1 = [i for b in s1 for i in b]
    assert len(i0) == len(i1) == 5
    assert set(i0) | set(i1) == set(range(10))


def test_random_split():
    ds = io.TensorDataset([np.arange(10).astype(np.float32)])
    a, b = io.random_split(ds, [7, 3])
    assert len(a) == 7 and len(b) == 3


# ---------------- metric ----------------

def test_accuracy_metric():
    m = metric.Accuracy()
    pred = paddle.to_tensor([[0.1, 0.9], [0.8, 0.2], [0.6, 0.4]])
    label = paddle.to_tensor(np.array([[1], [1], [0]]))
    correct = m.compute(pred, label)
    m.update(correct)
    assert abs(m.accumulate() - 2 / 3) < 1e-6


def test_precision_recall():
    p = metric.Precision()
    r = metric.Recall()
    preds = paddle.to_tensor([0.9, 0.8, 0.2, 0.7])
    labels = paddle.to_tensor(np.array([1, 0, 1, 1]))
    p.update(preds, labels)
    r.update(preds, labels)
    assert abs(p.accumulate() - 2 / 3) < 1e-6
    assert abs(r.accumulate() - 2 / 3) < 1e-6


def test_auc():
    m = metric.Auc()
    preds = paddle.to_tensor([0.1, 0.4, 0.35, 0.8])
    labels = paddle.to_tensor(np.array([0, 0, 1, 1]))
    m.update(preds, labels)
    assert abs(m.accumulate() - 0.75) < 0.01


def test_functional_accuracy():
    pred = paddle.to_tensor([[0.1, 0.9], [0.8, 0.2]])
    lab = paddle.to_tensor(np.array([1, 1]))
    acc = metric.accuracy(pred, lab)
    assert abs(float(acc) - 0.5) < 1e-6


# ---------------- distribution ----------------

def test_normal_distribution():
    d = distribution.Normal(0.0, 1.0)
    s = d.sample([1000])
    assert abs(float(s.numpy().mean())) < 0.15
    lp = d.log_prob(paddle.to_tensor([0.0]))
    np.testing.assert_allclose(float(lp), -0.5 * np.log(2 * np.pi),
                               rtol=1e-5)
    d2 = distribution.Normal(1.0, 2.0)
    kl = d.kl_divergence(d2)
    assert float(kl.numpy()) > 0


def test_uniform_distribution():
    d = distribution.Uniform(0.0, 2.0)
    s = d.sample([500])
    assert 0 <= float(s.numpy().min()) and float(s.numpy().max()) < 2
    np.testing.assert_allclose(float(d.entropy()), np.log(2), rtol=1e-6)


def test_categorical_distribution():
    logits = paddle.to_tensor([0.0, 0.0, 10.0])
    d = distribution.Categorical(logits)
    s = d.sample([100])
    assert (s.numpy() == 2).mean() > 0.95
    assert float(d.entropy()) < 0.1


# ---------------- vision ----------------

def test_lenet_forward_and_shapes():
    net = vmodels.LeNet()
    out = net(paddle.randn([2, 1, 28, 28]))
    assert out.shape == [2, 10]


def test_resnet18_forward():
    net = vmodels.resnet18(num_classes=10)
    net.eval()
    out = net(paddle.randn([1, 3, 32, 32]))
    assert out.shape == [1, 10]


def test_mobilenet_v2_forward():
    net = vmodels.mobilenet_v2(num_classes=7)
    net.eval()
    out = net(paddle.randn([1, 3, 32, 32]))
    assert out.shape == [1, 7]


def test_mnist_dataset_and_transform():
    tf = T.Compose([T.Normalize(mean=0.5, std=0.5)])
    ds = vdatasets.MNIST(mode="train", transform=tf)
    img, label = ds[0]
    assert img.shape == (1, 28, 28)
    assert 0 <= int(label) < 10
    assert len(ds) == 6000


def test_transforms():
    img = np.random.rand(3, 16, 16).astype(np.float32)
    out = T.CenterCrop(8)(img)
    assert out.shape == (3, 8, 8)
    out = T.Resize((4, 4))(img)
    assert out.shape == (3, 4, 4)
    hwc = np.random.randint(0, 255, (8, 8, 3), np.uint8)
    out = T.ToTensor()(hwc)
    assert out.shape == (3, 8, 8) and out.max() <= 1.0


# ---------------- text ----------------

def test_text_datasets(tmp_path):
    """Real-format fixtures through the public loaders (the deep format
    tests live in test_text_datasets.py)."""
    import io as _io
    import tarfile

    from paddle_tpu.text import Imdb, UCIHousing

    def _add(tf, name, data):
        info = tarfile.TarInfo(name)
        info.size = len(data)
        tf.addfile(info, _io.BytesIO(data))

    p = str(tmp_path / "aclImdb_v1.tar.gz")
    with tarfile.open(p, "w:gz") as tf:
        for i in range(4):
            sub = "pos" if i % 2 == 0 else "neg"
            _add(tf, f"aclImdb/train/{sub}/{i}.txt",
                 b"fine movie " * 8)
    ds = Imdb(data_file=p, mode="train", cutoff=2)
    x, y = ds[0]
    assert x.shape == (16,) and int(y) in (0, 1)

    hp = str(tmp_path / "housing.data")
    rows = np.random.RandomState(0).rand(20, 14)
    with open(hp, "w") as f:
        for r in rows:
            f.write(" ".join(f"{v:.5f}" for v in r) + "\n")
    h = UCIHousing(data_file=hp, mode="test")
    feat, target = h[0]
    assert feat.shape == (13,) and target.shape == (1,)


# ---------------- hapi Model ----------------

def test_model_fit_evaluate_predict(tmp_path):
    paddle.seed(5)
    X = np.random.rand(64, 4).astype(np.float32)
    W = np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)
    Yc = (X @ W > 0.6).astype(np.int64).reshape(-1)
    ds = io.TensorDataset([X, Yc])

    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))
    model = paddle.Model(net)
    model.prepare(optimizer.Adam(0.05, parameters=net.parameters()),
                  nn.CrossEntropyLoss(), metric.Accuracy())
    hist = model.fit(ds, epochs=6, batch_size=16, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    logs = model.evaluate(ds, batch_size=16, verbose=0)
    assert logs["acc"] > 0.8
    preds = model.predict(ds, batch_size=16, stack_outputs=True)
    assert preds[0].shape == (64, 2)

    path = str(tmp_path / "ckpt")
    model.save(path)
    net2 = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))
    m2 = paddle.Model(net2)
    m2.prepare(optimizer.Adam(0.05, parameters=net2.parameters()),
               nn.CrossEntropyLoss())
    m2.load(path)
    np.testing.assert_allclose(net[0].weight.numpy(),
                               net2[0].weight.numpy())


def test_model_early_stopping():
    X = np.random.rand(16, 2).astype(np.float32)
    Y = np.zeros(16, np.int64)
    ds = io.TensorDataset([X, Y])
    net = nn.Linear(2, 2)
    model = paddle.Model(net)
    model.prepare(optimizer.SGD(0.0, parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    es = paddle.hapi.EarlyStopping(monitor="loss", patience=0, min_delta=1e9)
    model.fit(ds, eval_data=ds, epochs=10, batch_size=8, verbose=0,
              callbacks=[es])
    # with huge min_delta nothing "improves" → stops after patience
    assert model.stop_training


def test_summary_counts_params(capsys):
    net = nn.Linear(10, 5)
    info = paddle.summary(net)
    assert info["total_params"] == 55


def test_grad_scaler_no_double_unscale():
    # ADVICE r1: unscale_ then step must not divide grads twice.
    import paddle_tpu as paddle
    from paddle_tpu import optimizer as opt_mod
    p = paddle.nn.Linear(2, 2).weight
    scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
    opt = opt_mod.SGD(learning_rate=1.0, parameters=[p])
    p0 = np.asarray(p.data).copy()
    g = np.ones((2, 2), np.float32)
    p._grad_data = jnp.asarray(g * 4.0)  # pre-scaled grad
    scaler.unscale_(opt)
    scaler.step(opt)       # must NOT unscale again
    scaler.update()
    np.testing.assert_allclose(np.asarray(p.data), p0 - g, rtol=1e-6)
    import pytest as _pytest
    p._grad_data = jnp.asarray(g)
    scaler.unscale_(opt)
    with _pytest.raises(RuntimeError):
        scaler.unscale_(opt)


def test_model_save_inference_and_serve(tmp_path):
    """Model.save(training=False) -> paddle.inference roundtrip (VERDICT
    round-2 weak #12)."""
    import os
    import numpy as np
    from paddle_tpu import inference
    from paddle_tpu.jit import InputSpec

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m = hapi.Model(net, inputs=[InputSpec([None, 4], "float32")])
    prefix = os.path.join(str(tmp_path), "served")
    m.save(prefix, training=False)

    x = np.random.RandomState(0).standard_normal((3, 4)).astype(np.float32)
    net.eval()
    want = net(paddle.to_tensor(x)).numpy()
    pred = inference.create_predictor(inference.Config(prefix))
    got, = pred.run([x])
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3,
                               atol=1e-5)


def test_reduce_lr_on_plateau_callback():
    import numpy as np
    from paddle_tpu.hapi.callbacks import ReduceLROnPlateau

    paddle.seed(1)
    net = nn.Linear(4, 1)
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    m = hapi.Model(net)
    cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2,
                           verbose=0)
    cb.set_model(m)
    m._optimizer = opt
    for loss in [1.0, 0.9, 0.9, 0.9]:   # stalls after step 2
        cb.on_epoch_end(0, {"loss": loss})
    assert abs(opt.get_lr() - 0.05) < 1e-9


def test_visualdl_callback_writes_scalars(tmp_path):
    import json
    from paddle_tpu.hapi.callbacks import VisualDL
    cb = VisualDL(log_dir=str(tmp_path))
    cb.on_train_batch_end(0, {"loss": 1.5})
    cb.on_eval_end({"acc": [0.9]})
    rows = [json.loads(l) for l in
            open(str(tmp_path / "scalars.jsonl"))]
    tags = {r["tag"] for r in rows}
    assert tags == {"train/loss", "eval/acc"}


def test_resnet_data_format_parity():
    """data_format='NHWC' threads through the whole ResNet and matches
    the NCHW model with identical weights."""
    paddle.seed(11)
    m_nchw = vmodels.resnet18(num_classes=7)
    m_nhwc = vmodels.resnet18(num_classes=7, data_format="NHWC")
    m_nhwc.set_state_dict(m_nchw.state_dict())  # same weight layouts
    m_nchw.eval(); m_nhwc.eval()
    x = np.random.RandomState(0).standard_normal((2, 3, 32, 32)).astype(
        np.float32)
    a = m_nchw(paddle.to_tensor(x)).numpy()
    b = m_nhwc(paddle.to_tensor(x.transpose(0, 2, 3, 1))).numpy()
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
