"""Round-4 API-parity additions, audited against the reference's public
alias lists (python/paddle/__init__.py, nn/__init__.py,
nn/functional/__init__.py)."""
import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


def test_top_level_alias_audit():
    """Every alias the reference re-exports at top level must exist
    (whitelist: monkey-patch internals)."""
    src = open("/root/reference/python/paddle/__init__.py").read()
    names = set(re.findall(r"^from \.\S+ import (\w+)", src, re.M))
    names -= {"monkey_patch_variable", "monkey_patch_math_varbase",
              "VarBase"}
    missing = sorted(n for n in names if not hasattr(paddle, n))
    assert not missing, missing


def test_nn_alias_audit():
    src = open("/root/reference/python/paddle/nn/__init__.py").read()
    names = set(re.findall(r"^from \.[\w.]* import (\w+)", src, re.M))
    names = {n for n in names if not n.startswith("_")}
    missing = sorted(n for n in names if not hasattr(nn, n))
    assert not missing, missing


def test_functional_alias_audit():
    src = open(
        "/root/reference/python/paddle/nn/functional/__init__.py").read()
    names = set(re.findall(r"^from \.[\w.]* import (\w+)", src, re.M))
    names = {n for n in names if not n.startswith("_")}
    missing = sorted(n for n in names if not hasattr(F, n))
    assert not missing, missing


def test_places_and_modes():
    p = paddle.CUDAPlace(0)
    assert p == paddle.CUDAPlace(0) and p != paddle.CPUPlace(0)
    paddle.disable_dygraph()
    assert not paddle.in_dygraph_mode()
    paddle.enable_dygraph()
    assert paddle.in_dygraph_mode()
    st = paddle.get_cuda_rng_state()
    paddle.set_cuda_rng_state(st)
    assert paddle.get_cudnn_version() is None


def test_slice_family_oracle():
    x = paddle.to_tensor(np.arange(24).reshape(4, 6).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(paddle.slice(x, [0, 1], [1, 2], [3, 5]).data),
        np.arange(24).reshape(4, 6)[1:3, 2:5])
    np.testing.assert_allclose(
        np.asarray(paddle.strided_slice(x, [1], [0], [6], [2]).data),
        np.arange(24).reshape(4, 6)[:, ::2])
    np.testing.assert_allclose(
        np.asarray(paddle.crop_tensor(x, shape=[2, 3],
                                      offsets=[1, 2]).data),
        np.arange(24).reshape(4, 6)[1:3, 2:5])


def test_shard_index_semantics():
    ids = paddle.to_tensor(np.array([0, 4, 5, 9, 15], np.int64))
    out = np.asarray(paddle.shard_index(ids, 16, 4, 1).data)
    # shard 1 owns [4, 8): local ids 0..3
    np.testing.assert_array_equal(out, [-1, 0, 1, -1, -1])


def test_add_n_mv_inplace_ops():
    a = paddle.to_tensor(np.ones((2, 2), np.float32))
    b = paddle.to_tensor(np.full((2, 2), 2.0, np.float32))
    np.testing.assert_allclose(np.asarray(paddle.add_n([a, b]).data), 3.0)
    m = paddle.to_tensor(np.eye(3, dtype=np.float32) * 2)
    v = paddle.to_tensor(np.arange(3, dtype=np.float32))
    np.testing.assert_allclose(np.asarray(paddle.mv(m, v).data),
                               [0, 2, 4])
    t = paddle.to_tensor(np.zeros(3, np.float32))
    paddle.tanh_(t)
    np.testing.assert_allclose(np.asarray(t.data), 0.0)
    u = paddle.to_tensor(np.ones((3,), np.float32))
    paddle.unsqueeze_(u, 0)
    assert u.shape_tuple == (1, 3)
    paddle.squeeze_(u, 0)
    assert u.shape_tuple == (3,)
    assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
    assert int(paddle.rank(m)) == 2
    np.testing.assert_array_equal(np.asarray(paddle.shape(m).data), [3, 3])


def test_flops_matches_reference_convention():
    net = nn.Sequential(nn.Conv2D(1, 4, 3, padding=1), nn.ReLU(),
                        nn.Flatten(), nn.Linear(4 * 8 * 8, 10))
    got = paddle.flops(net, [1, 1, 8, 8])
    conv = 4 * 8 * 8 * (1 * 9 + 1)     # out_elems * (kernel + bias)
    fc = 1 * (4 * 8 * 8 * 10)
    assert got == conv + fc, (got, conv + fc)


def test_grid_sample_warp_oracle():
    """Shift-by-one warp against a numpy oracle."""
    img = paddle.to_tensor(
        np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    th = paddle.to_tensor(
        np.array([[[1, 0, 2.0 / 3.0], [0, 1, 0]]], np.float32))
    g = F.affine_grid(th, [1, 1, 4, 4])
    out = np.asarray(F.grid_sample(img, g).data)
    base = np.arange(16, dtype=np.float32).reshape(4, 4)
    # x' = x + 1 pixel (2/3 normalized with align_corners over width 4)
    np.testing.assert_allclose(out[0, 0, :, :3], base[:, 1:], atol=1e-5)
    np.testing.assert_allclose(out[0, 0, :, 3], 0.0, atol=1e-5)  # zeros pad


def test_conv_transpose_1d_3d_grad():
    paddle.seed(111)
    c1 = nn.Conv1DTranspose(3, 5, 3, stride=2)
    x = paddle.to_tensor(np.random.randn(2, 3, 8).astype(np.float32))
    out = c1(x)
    assert out.shape_tuple[:2] == (2, 5)
    out.sum().backward()
    assert float(abs(c1.weight.grad.data).sum()) > 0

    c3 = nn.Conv3DTranspose(2, 3, 3, stride=2)
    x3 = paddle.to_tensor(np.random.randn(1, 2, 4, 4, 4).astype(np.float32))
    o3 = c3(x3)
    assert o3.shape_tuple == (1, 3, 9, 9, 9)


def test_hsigmoid_loss_trains():
    paddle.seed(112)
    layer = nn.HSigmoidLoss(8, num_classes=6)
    x = paddle.to_tensor(np.random.randn(16, 8).astype(np.float32))
    y = paddle.to_tensor(np.random.randint(0, 6, (16,)).astype(np.int64))
    from paddle_tpu import optimizer
    opt = optimizer.Adam(learning_rate=0.05,
                         parameters=layer.parameters())
    first = last = None
    for _ in range(12):
        loss = layer(x, y).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss)
        last = float(loss)
    assert last < first * 0.8, (first, last)


def test_misc_new_losses_and_activations():
    p = paddle.to_tensor(np.array([0.9, 0.1], np.float32))
    y = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
    ll = np.asarray(F.log_loss(p, y).data)
    np.testing.assert_allclose(ll, -np.log([0.9 + 1e-4, 0.9 + 1e-4]),
                               rtol=1e-3)
    np.testing.assert_allclose(
        np.asarray(F.square_error_cost(p, y).data),
        [0.01, 0.01], rtol=1e-4)
    x = paddle.to_tensor(np.array([-1.0, 0.5, 2.0], np.float32))
    np.testing.assert_allclose(np.asarray(F.thresholded_relu(x).data),
                               [0, 0, 2.0])
    ls = np.asarray(F.log_sigmoid(x).data)
    np.testing.assert_allclose(ls, np.log(1 / (1 + np.exp(-np.asarray(
        [-1.0, 0.5, 2.0])))), rtol=1e-5)
    # inplace variants mutate
    t = paddle.to_tensor(np.array([-1.0, 1.0], np.float32))
    F.relu_(t)
    np.testing.assert_allclose(np.asarray(t.data), [0, 1.0])


def test_upsampling_pairwise_logsigmoid_layers():
    up = nn.UpsamplingNearest2D(scale_factor=2)
    x = paddle.to_tensor(np.random.randn(1, 2, 3, 3).astype(np.float32))
    assert up(x).shape_tuple == (1, 2, 6, 6)
    ub = nn.UpsamplingBilinear2D(size=[5, 5])
    assert ub(x).shape_tuple == (1, 2, 5, 5)
    pd = nn.PairwiseDistance()
    a = paddle.to_tensor(np.array([[0.0, 0.0]], np.float32))
    b = paddle.to_tensor(np.array([[3.0, 4.0]], np.float32))
    np.testing.assert_allclose(float(pd(a, b)), 5.0, rtol=1e-4)
    assert nn.LogSigmoid()(a).shape_tuple == (1, 2)
    d3 = nn.Dropout3D(p=0.5)
    d3.eval()
    x5 = paddle.to_tensor(np.ones((1, 2, 2, 2, 2), np.float32))
    np.testing.assert_allclose(np.asarray(d3(x5).data), 1.0)


# -- r4 review regressions ------------------------------------------------

def test_inplace_ops_keep_gradient_chain():
    """r4 review: x.data assignment broke the tape; _rebind keeps it."""
    t = paddle.to_tensor(np.array([0.5, 1.0], np.float32),
                         stop_gradient=False)
    h = F.tanh_(t)
    (h * h).sum().backward()
    th = np.tanh([0.5, 1.0])
    expect = 2 * th * (1 - th ** 2)
    np.testing.assert_allclose(np.asarray(t.grad.data), expect, rtol=1e-5)

    x = paddle.to_tensor(np.zeros(4, np.float32), stop_gradient=False)
    upd = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                           stop_gradient=False)
    idx = paddle.to_tensor(np.array([1, 3]))
    paddle.scatter_(x, idx, upd)
    x.sum().backward()
    assert upd.grad is not None
    np.testing.assert_allclose(np.asarray(upd.grad.data), [1.0, 1.0])


def test_grid_sample_boundary_partial_contribution():
    """r4 review: zeros padding must mask per tap, not per sample."""
    img = paddle.to_tensor(
        np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    # sample at fx=3.5, fy=0 (half past the last column)
    gx = (3.5 * 2 / 3 - 1)   # inverse of align_corners mapping (W=4)
    grid = paddle.to_tensor(
        np.array([[[[gx, -1.0]]]], np.float32))
    out = float(np.asarray(F.grid_sample(img, grid).data))
    np.testing.assert_allclose(out, 0.5 * 3.0, rtol=1e-5)


def test_conv1d_transpose_nlc_and_output_size():
    paddle.seed(113)
    x = paddle.to_tensor(np.random.randn(2, 8, 3).astype(np.float32))
    w = paddle.to_tensor(np.random.randn(3, 5, 3).astype(np.float32))
    out = F.conv1d_transpose(x, w, stride=2, data_format="NLC")
    assert out.shape_tuple == (2, 17, 5)
    # output_size picks the longer valid length
    xc = paddle.to_tensor(np.random.randn(2, 3, 8).astype(np.float32))
    o18 = F.conv1d_transpose(xc, w, stride=2, output_size=[18])
    assert o18.shape_tuple == (2, 5, 18)
    with pytest.raises(ValueError, match="not reachable"):
        F.conv1d_transpose(xc, w, stride=2, output_size=[25])


def test_adaptive_pool3d_ndhwc_and_mask_raises():
    x = paddle.to_tensor(np.random.randn(1, 4, 4, 4, 2).astype(np.float32))
    out = F.adaptive_avg_pool3d(x, 2, data_format="NDHWC")
    assert out.shape_tuple == (1, 2, 2, 2, 2)
    xc = paddle.to_tensor(np.random.randn(1, 2, 4, 4, 4).astype(np.float32))
    with pytest.raises(NotImplementedError, match="return_mask"):
        F.adaptive_max_pool3d(xc, 2, return_mask=True)


def test_hsigmoid_custom_table_requires_code():
    x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))
    y = paddle.to_tensor(np.array([0, 1], np.int64))
    w = paddle.to_tensor(np.random.randn(3, 4).astype(np.float32))
    tbl = paddle.to_tensor(np.zeros((2, 2), np.int64))
    with pytest.raises(ValueError, match="path_code"):
        F.hsigmoid_loss(x, y, 4, w, path_table=tbl)


def test_flops_accumulates_shared_layers():
    class Siamese(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            return self.fc(self.fc(x))   # same layer twice

    got = paddle.flops(Siamese(), [1, 4])
    assert got == 2 * (4 * 4), got
