"""Continuous batching with a paged KV cache (ISSUE 7): page pool
accounting, paged-attention op (reference tier vs dense oracle + the
Pallas shape gate), GenerationEngine scheduling (admission-order
bitwise parity, streaming, deadlines, shedding, page reclamation, zero
steady-state recompiles), HTTP streaming + keep-alive client, the cost
rule, and the chaos/smoke gates in-process."""
import json
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops, serving
from paddle_tpu.ops import attention as attention_mod
from paddle_tpu.serving import kv_cache
from paddle_tpu.serving.generation import GenerationError
from paddle_tpu.testing import fault
from paddle_tpu.testing.chaos import make_dyadic_lm

import jax
import jax.numpy as jnp


# ----------------------------------------------------------- fixtures --
@pytest.fixture(scope="module")
def lm():
    return make_dyadic_lm()


@pytest.fixture(scope="module")
def engine(lm):
    """One warmed engine shared by read-only traffic tests."""
    eng = serving.GenerationEngine(lm, num_slots=4, page_size=4,
                                   max_context=32, max_queue=128)
    eng.warmup()
    yield eng
    eng.close()


def _prompts(n, seed=0, vocab=32, lo=1, hi=9):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, vocab, rng.randint(lo, hi)).tolist()
            for _ in range(n)]


# ----------------------------------------------------- page pool ------
def test_page_pool_accounting_and_scratch_page():
    cfg = kv_cache.KVCacheConfig(num_layers=2, num_kv_heads=2,
                                 head_dim=4, page_size=4, num_pages=6,
                                 max_context=16)
    pool = kv_cache.PagePool(cfg)
    assert pool.kv[0].shape == (2, 7, 4, 2, 4)   # +1 scratch page
    a = pool.alloc(4)
    assert len(a) == 4 and 0 not in a            # scratch never granted
    assert pool.in_use == 4 and pool.available == 2
    assert pool.alloc(3) is None                 # all-or-nothing
    assert pool.in_use == 4                      # nothing half-taken
    pool.free(a[:2])
    assert pool.in_use == 2 and pool.available == 4
    with pytest.raises(ValueError):
        pool.free(a[:1])                         # double free
    with pytest.raises(ValueError):
        pool.free([0])                           # scratch is unfreeable
    pool.free(a[2:])
    assert pool.in_use == 0 and pool.available == 6


def test_pages_needed_and_config_geometry():
    assert kv_cache.pages_needed(5, 3, 4) == 2
    assert kv_cache.pages_needed(1, 1, 4) == 1
    assert kv_cache.pages_needed(8, 8, 4) == 4
    cfg = kv_cache.KVCacheConfig(1, 1, 4, page_size=4, num_pages=4,
                                 max_context=10)
    assert cfg.pages_per_seq == 3


def test_write_token_and_prompt_scatter():
    pool = jnp.zeros((1, 4, 2, 1, 3))            # L=1, scratch+3 pages
    vals = jnp.arange(6, dtype=jnp.float32).reshape(2, 1, 3)
    table = jnp.asarray([[2, 3], [1, 3]], jnp.int32)
    pos = jnp.asarray([0, 3], jnp.int32)         # page 0/off 0, page 1/off 1
    out = kv_cache.write_token(pool, 0, vals, table, pos)
    np.testing.assert_array_equal(np.asarray(out[0, 2, 0, 0]), [0, 1, 2])
    np.testing.assert_array_equal(np.asarray(out[0, 3, 1, 0]), [3, 4, 5])
    # prompt write: rows past length land on the scratch page
    pvals = jnp.arange(12, dtype=jnp.float32).reshape(4, 1, 3)
    out2 = kv_cache.write_prompt(pool, 0, pvals,
                                 jnp.asarray([2, 1], jnp.int32),
                                 jnp.int32(3))
    np.testing.assert_array_equal(np.asarray(out2[0, 2, 0, 0]), [0, 1, 2])
    np.testing.assert_array_equal(np.asarray(out2[0, 2, 1, 0]), [3, 4, 5])
    np.testing.assert_array_equal(np.asarray(out2[0, 1, 0, 0]), [6, 7, 8])
    assert np.all(np.asarray(out2[0, 1, 1]) == 0)    # pad went to scratch
    np.testing.assert_array_equal(np.asarray(out2[0, 0, 3, 0]),
                                  [9, 10, 11])


# ----------------------------------------------- paged attention ------
def _dense_oracle(q, k, v, scale):
    s = np.einsum("shd,sthd->sht", q, k) * scale
    w = np.exp(s - s.max(-1, keepdims=True))
    w /= w.sum(-1, keepdims=True)
    return np.einsum("sht,sthd->shd", w, v)


def test_paged_attention_matches_dense_oracle():
    rng = np.random.RandomState(0)
    S, H, D, page, P, N = 3, 2, 4, 4, 3, 8
    lens = np.array([5, 9, 1], np.int32)
    table = np.array([[3, 5, 0], [7, 2, 6], [1, 0, 0]], np.int32)
    kp = np.zeros((N + 1, page, H, D), np.float32)
    vp = np.zeros((N + 1, page, H, D), np.float32)
    dense_k = np.zeros((S, P * page, H, D), np.float32)
    dense_v = np.zeros((S, P * page, H, D), np.float32)
    for s in range(S):
        for t in range(lens[s]):
            kk = rng.randn(H, D).astype(np.float32)
            vv = rng.randn(H, D).astype(np.float32)
            kp[table[s, t // page], t % page] = kk
            vp[table[s, t // page], t % page] = vv
            dense_k[s, t] = kk
            dense_v[s, t] = vv
    q = rng.randn(S, H, D).astype(np.float32)
    scale = 1.0 / np.sqrt(D)
    got = np.asarray(ops.paged_attention(q, kp, vp, table, lens).numpy())
    ref = np.stack([
        _dense_oracle(q[s:s + 1], dense_k[s:s + 1, :lens[s]],
                      dense_v[s:s + 1, :lens[s]], scale)[0]
        for s in range(S)])
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_paged_attention_gqa_and_layer_indexing():
    rng = np.random.RandomState(1)
    S, H, Hkv, D, page, P = 2, 4, 2, 4, 2, 2
    L = 3
    kp = rng.randn(L, 5, page, Hkv, D).astype(np.float32)
    vp = rng.randn(L, 5, page, Hkv, D).astype(np.float32)
    table = np.array([[1, 2], [3, 4]], np.int32)
    lens = np.array([3, 4], np.int32)
    q = rng.randn(S, H, D).astype(np.float32)
    for layer in range(L):
        got = attention_mod.paged_attention_reference(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(lens), layer=layer)
        ref = attention_mod.paged_attention_reference(
            jnp.asarray(q), jnp.asarray(kp[layer]),
            jnp.asarray(vp[layer]), jnp.asarray(table),
            jnp.asarray(lens))
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-6)


def test_pallas_tier_shape_gate(monkeypatch):
    """The hook dispatches to a registered kernel ONLY on TPU with
    aligned shapes; no kernel or wrong shapes -> reference tier."""
    q_shape, pool_shape = (4, 2, 128), (9, 8, 2, 128)
    assert not attention_mod.paged_attention_supported(
        q_shape, pool_shape, jnp.float32, 8)         # no kernel yet
    called = []

    def kernel(q, kp, vp, pt, lens, scale=None):
        called.append(True)
        return jnp.zeros(q.shape, q.dtype)

    attention_mod.register_paged_attention_kernel(kernel)
    try:
        assert not attention_mod.paged_attention_supported(
            q_shape, pool_shape, jnp.float32, 8)     # cpu backend
        monkeypatch.setattr(attention_mod.jax, "default_backend",
                            lambda: "tpu")
        assert attention_mod.paged_attention_supported(
            q_shape, pool_shape, jnp.float32, 8)
        # misaligned head dim / page size stay on the reference tier
        assert not attention_mod.paged_attention_supported(
            (4, 2, 64), (9, 8, 2, 64), jnp.float32, 8)
        assert not attention_mod.paged_attention_supported(
            q_shape, pool_shape, jnp.float32, 6)
        assert not attention_mod.paged_attention_supported(
            q_shape, pool_shape, jnp.int32, 8)
        # dispatch actually reroutes under the gate
        q = jnp.zeros(q_shape, jnp.float32)
        kp = jnp.zeros(pool_shape, jnp.float32)
        pt = jnp.zeros((4, 1), jnp.int32)
        lens = jnp.ones((4,), jnp.int32)
        ops.paged_attention(q, kp, kp, pt, lens)
        assert called
    finally:
        attention_mod.register_paged_attention_kernel(None)


# ------------------------------------------------ engine: tokens ------
def test_generate_sync_and_streaming_agree(engine):
    prompts = _prompts(5, seed=3)
    streams = [engine.generate(p, max_new_tokens=4 + i % 3)
               for i, p in enumerate(prompts)]
    for i, s in enumerate(streams):
        streamed = list(s.tokens(timeout=60))
        assert streamed == s.result(0)
        assert len(streamed) == 4 + i % 3
        assert s.finish_reason == "length"


def test_admission_order_parity_bitwise(lm, engine):
    """Tokens must be identical whether sequences run concurrently (any
    admission order) or strictly one at a time — the dyadic-model
    bitwise gate on the continuous batcher."""
    prompts = _prompts(8, seed=5)
    budgets = [3 + i % 4 for i in range(8)]
    streams = [engine.generate(p, max_new_tokens=b, temperature=0.6,
                               seed=100 + i)
               for i, (p, b) in enumerate(zip(prompts, budgets))]
    conc = [s.result(60) for s in streams]
    # serial runs on a FRESH engine, reversed submission order
    eng2 = serving.GenerationEngine(lm, num_slots=4, page_size=4,
                                    max_context=32)
    serial = [None] * 8
    for i in reversed(range(8)):
        serial[i] = eng2.generate_sync(prompts[i], timeout=60,
                                       max_new_tokens=budgets[i],
                                       temperature=0.6, seed=100 + i)
    eng2.close()
    assert conc == serial


def test_sampling_determinism_and_temperature_variety(engine):
    p = [7, 3, 1]
    a = engine.generate_sync(p, timeout=60, max_new_tokens=6,
                             temperature=0.9, seed=11)
    b = engine.generate_sync(p, timeout=60, max_new_tokens=6,
                             temperature=0.9, seed=11)
    c = engine.generate_sync(p, timeout=60, max_new_tokens=6,
                             temperature=0.9, seed=12)
    assert a == b                       # same seed -> bitwise identical
    assert a != c or len(set(a)) > 1    # different seed decodes freely


def test_eos_finishes_early(engine):
    p = [2, 9, 4]
    kw = dict(max_new_tokens=6, temperature=0.8, seed=21)
    free = engine.generate_sync(p, timeout=60, **kw)
    assert len(free) == 6
    eos = free[2]
    cut = free.index(eos)               # first time eos would appear
    s = engine.generate(p, eos_id=eos, **kw)
    toks = s.result(60)
    assert toks == free[:cut + 1] and toks[-1] == eos
    assert s.finish_reason == "eos"


def test_zero_recompiles_and_page_reclaim_after_traffic(engine):
    stats = engine.stats()
    assert stats["recompiles_after_warmup"] == 0
    assert stats["page_pool"]["in_use"] == 0
    c = stats["counters"]
    assert c["pages_allocated"] == c["pages_freed"]
    assert c["finished"] > 0


# ------------------------------------------- engine: lifecycle --------
def test_queue_deadline_shed_and_validation(lm):
    eng = serving.GenerationEngine(lm, num_slots=1, page_size=4,
                                   max_context=16, max_queue=2,
                                   prompt_buckets=[8])
    eng.pause()
    try:
        # in-queue deadline expiry
        doomed = eng.generate([1, 2], max_new_tokens=2, deadline_ms=1.0)
        time.sleep(0.03)
        with pytest.raises(serving.DeadlineExceeded):
            doomed.result(30)
        # queue-full shedding (expired entries swept first)
        eng.generate([1], max_new_tokens=2)
        eng.generate([2], max_new_tokens=2)
        with pytest.raises(serving.QueueFull):
            eng.generate([3], max_new_tokens=2)
        # malformed requests fail synchronously
        with pytest.raises(ValueError):
            eng.generate([], max_new_tokens=2)
        with pytest.raises(ValueError):
            eng.generate([1], max_new_tokens=0)
        with pytest.raises(ValueError):
            eng.generate([1] * 9, max_new_tokens=2)   # > largest bucket
        with pytest.raises(ValueError):
            eng.generate([1, 2], max_new_tokens=200)  # > max_context
    finally:
        eng.resume()
        eng.close()
    assert eng.page_pool.in_use == 0


def test_mid_generation_deadline_evicts_and_frees(lm):
    eng = serving.GenerationEngine(lm, num_slots=2, page_size=4,
                                   max_context=32, prompt_buckets=[8])
    try:
        s = eng.generate([5, 1], max_new_tokens=24, deadline_ms=1500.0)
        it = s.tokens(timeout=30)
        got = [next(it)]                # generation demonstrably began
        eng.pause()
        time.sleep(1.7)                 # deadline lapses mid-generation
        eng.resume()
        with pytest.raises(serving.DeadlineExceeded):
            for t in it:
                got.append(t)
        assert s.finish_reason == "deadline"
        assert len(got) >= 1
    finally:
        eng.close()
    assert eng.page_pool.in_use == 0
    assert eng.stats()["counters"]["pages_allocated"] \
        == eng.stats()["counters"]["pages_freed"]


def test_page_starved_admissions_serialize(lm):
    """A pool with room for only one sequence at a time must serialize
    admissions instead of deadlocking or leaking."""
    eng = serving.GenerationEngine(lm, num_slots=2, page_size=4,
                                   max_context=16, num_pages=3,
                                   prompt_buckets=[8])
    try:
        streams = [eng.generate([i + 1, 2], max_new_tokens=6)
                   for i in range(3)]   # each needs 2 pages of the 3
        outs = [s.result(60) for s in streams]
        assert all(len(o) == 6 for o in outs)
        st = eng.stats()
        assert st["counters"]["finished"] == 3
    finally:
        eng.close()
    assert eng.page_pool.in_use == 0


def test_close_drains_accepted_work_and_rejects_new(lm):
    eng = serving.GenerationEngine(lm, num_slots=1, page_size=4,
                                   max_context=16, prompt_buckets=[8])
    eng.pause()
    pend = [eng.generate([1], max_new_tokens=2) for _ in range(3)]
    eng.close()                 # close = drain: accepted work finishes
    for s in pend:
        assert s.future.done()
        assert len(s.result(0)) == 2
    with pytest.raises(serving.EngineClosed):
        eng.generate([1], max_new_tokens=1)
    assert eng.page_pool.in_use == 0


def test_drain_completes_accepted_work(lm):
    eng = serving.GenerationEngine(lm, num_slots=2, page_size=4,
                                   max_context=16, prompt_buckets=[8])
    try:
        streams = [eng.generate([i + 1], max_new_tokens=4)
                   for i in range(4)]
        assert eng.drain(timeout=60)
        assert all(s.future.done() for s in streams)
        assert all(len(s.result(0)) == 4 for s in streams)
        with pytest.raises(serving.EngineClosed):
            eng.generate([1], max_new_tokens=1)
    finally:
        eng.close()


# ------------------------------------------------ fault injection -----
def test_decode_flake_is_retried(lm):
    eng = serving.GenerationEngine(lm, num_slots=1, page_size=4,
                                   max_context=16, decode_retries=2,
                                   prompt_buckets=[8])
    fault.arm("serving.decode_step:count=2", seed=0)
    try:
        out = eng.generate_sync([3, 1], timeout=60, max_new_tokens=5)
        assert len(out) == 5
        st = eng.stats()
        assert st["counters"]["decode_retries"] >= 1
        assert st["counters"]["failed"] == 0
    finally:
        fault.disarm()
        eng.close()


def test_decode_retry_exhaustion_fails_cleanly(lm):
    eng = serving.GenerationEngine(lm, num_slots=1, page_size=4,
                                   max_context=16, decode_retries=1,
                                   prompt_buckets=[8])
    fault.arm("serving.decode_step:p=1.0", seed=0)
    try:
        s = eng.generate([3, 1], max_new_tokens=5)
        with pytest.raises(GenerationError):
            s.result(60)
    finally:
        fault.disarm()
    eng.close()
    assert eng.page_pool.in_use == 0


# ------------------------------------------------------- HTTP ---------
def test_http_generate_stream_and_keepalive(lm):
    from paddle_tpu.serving.http import Client, ServingServer
    eng = serving.GenerationEngine(lm, num_slots=2, page_size=4,
                                   max_context=32, prompt_buckets=[8])
    srv = ServingServer(None, port=0, generation=eng).start()
    c = Client(srv.url, timeout=30)
    try:
        blocking = c.generate([1, 2, 3], max_new_tokens=5)
        streamed = list(c.generate_stream([1, 2, 3], max_new_tokens=5))
        assert streamed == blocking and len(blocking) == 5
        sampled = c.generate([4], max_new_tokens=4, temperature=0.8,
                             seed=9)
        assert sampled == eng.generate_sync([4], timeout=30,
                                            max_new_tokens=4,
                                            temperature=0.8, seed=9)
        # error mapping: malformed body -> ServingError(HTTP 400)
        with pytest.raises(serving.ServingError):
            c.generate([], max_new_tokens=2)
        # /metrics carries the generation block, both encodings
        m = c.metrics()
        assert m["generation"]["counters"]["finished"] >= 2
        assert "serving_decode_" in c.metrics_text()
        assert c.healthz()["status"] == "running"
        # keep-alive: the whole conversation rode ONE connection
        assert c.connections_opened == 1
    finally:
        c.close()
        srv.close()
        eng.close()


def test_http_stream_deadline_error_inband(lm):
    from paddle_tpu.serving.http import Client, ServingServer
    eng = serving.GenerationEngine(lm, num_slots=1, page_size=4,
                                   max_context=32, prompt_buckets=[8])
    srv = ServingServer(None, port=0, generation=eng).start()
    c = Client(srv.url, timeout=30)
    try:
        eng.pause()
        gen = c.generate_stream([1], max_new_tokens=4, deadline_ms=1.0)
        time.sleep(0.03)
        eng.resume()
        with pytest.raises(serving.DeadlineExceeded):
            list(gen)
        # connection was dropped mid-stream; next request reconnects
        assert c.generate([2], max_new_tokens=2)
        assert c.connections_opened == 2
    finally:
        c.close()
        srv.close()
        eng.close()


def test_http_predict_501_without_inference_engine(lm):
    from paddle_tpu.serving.http import Client, ServingServer
    eng = serving.GenerationEngine(lm, num_slots=1, page_size=4,
                                   max_context=16, prompt_buckets=[8])
    srv = ServingServer(None, port=0, generation=eng).start()
    c = Client(srv.url, timeout=10)
    try:
        with pytest.raises(serving.ServingError, match="501"):
            c.predict([np.zeros((1, 8), np.float32)])
    finally:
        c.close()
        srv.close()
        eng.close()


# -------------------------------------------------- cost model --------
def test_paged_attention_cost_rule():
    paddle.enable_static()
    try:
        main = paddle.static.Program()
        S, H, D, P, page, N = 4, 2, 8, 4, 4, 16
        with paddle.static.program_guard(main):
            q = paddle.static.data("q", [S, H, D], "float32")
            kp = paddle.static.data("kp", [N, page, H, D], "float32")
            vp = paddle.static.data("vp", [N, page, H, D], "float32")
            pt = paddle.static.data("pt", [S, P], "int32")
            ln = paddle.static.data("ln", [S], "int32")
            out = ops.paged_attention(q, kp, vp, pt, ln)
        rep = main.analyze(fetch_list=[out])
        row = [c for c in rep.per_op
               if c.op_name == "paged_attention"][0]
        T = P * page
        assert row.modeled and row.rule == "attention"
        assert row.flops == 4 * S * H * D * T + 5 * S * H * T
        # input bytes = q + page GATHER (K+V) + table + lengths, NOT
        # the whole physical pool
        gather = 2 * S * P * page * H * D * 4
        assert row.in_bytes == gather + S * H * D * 4 + S * P * 4 + S * 4
        assert rep.totals["unmodeled"]["count"] == 0
    finally:
        paddle.disable_static()
        paddle.static.reset_default_programs()


# ------------------------------------------------ gates in-process ----
def test_serve_smoke_decode_gate_in_process():
    import importlib
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        smoke = importlib.import_module("serve_smoke")
        failures = smoke.run_decode_checks(requests=10, clients=3)
        assert failures == []
    finally:
        sys.path.pop(0)


def test_generation_chaos_in_process(capsys):
    from paddle_tpu.testing import chaos
    assert chaos.generation_main(requests=8, clients=2) == 0


# ---------------------------------------------- SIGTERM mid-stream ----
def test_sigterm_mid_stream_finishes_accepted_and_reclaims_pages(lm):
    """ISSUE 13 satellite: SIGTERM (the preemption notice) arriving
    while streams are mid-generation.  The handler drains + closes the
    engine: every accepted stream either finishes its budget or ends
    with an in-band error — never a hang — no future is stranded, and
    the page pool is fully reclaimed."""
    import os
    import signal

    from paddle_tpu.utils.checkpoint import install_preemption_handler

    eng = serving.GenerationEngine(lm, num_slots=2, page_size=4,
                                   max_context=64, max_queue=32)
    eng.warmup()
    terminated = threading.Event()

    def on_term():
        terminated.set()
        eng.drain(timeout=60)   # accepted work finishes...
        eng.close()             # ...then the engine shuts down

    restore = install_preemption_handler(on_term)
    assert restore is not None
    try:
        streams = [eng.generate([i + 1], max_new_tokens=6, seed=i)
                   for i in range(5)]
        # demonstrably mid-stream: first token of stream 0 consumed
        it = streams[0].tokens(timeout=30)
        first = next(it)
        os.kill(os.getpid(), signal.SIGTERM)
        assert terminated.is_set()
        outcomes = []
        for s in streams:
            try:
                toks = s.result(timeout=30)    # no stranded futures
                assert len(toks) == 6
                outcomes.append("finished")
            except (serving.EngineClosed, GenerationError):
                outcomes.append("in-band-error")
        # close-after-drain semantics: accepted streams FINISH here
        assert outcomes.count("finished") == len(streams), outcomes
        # the mid-consumption iterator also runs to its clean end
        rest = [t for t in it]
        assert [first] + rest == streams[0].result(0)
    finally:
        restore()
        eng.close()
    stats = eng.stats()
    assert eng.page_pool.in_use == 0           # pool fully reclaimed
    assert stats["counters"]["pages_allocated"] \
        == stats["counters"]["pages_freed"]
    with pytest.raises(serving.EngineClosed):
        eng.generate([1], max_new_tokens=1)    # post-SIGTERM admission


def test_sigterm_mid_stream_close_without_drain_fails_in_band(lm):
    """The harsher variant: the handler closes immediately.  Accepted
    streams may finish (close drains what it can) or fail — but always
    in-band, with the pool reclaimed; nothing hangs or leaks."""
    import os
    import signal

    from paddle_tpu.utils.checkpoint import install_preemption_handler

    eng = serving.GenerationEngine(lm, num_slots=1, page_size=4,
                                   max_context=64, max_queue=32)
    eng.warmup()
    eng.pause()                                # queue builds up
    restore = install_preemption_handler(lambda: eng.close(timeout=30))
    try:
        streams = [eng.generate([i + 1], max_new_tokens=4)
                   for i in range(4)]
        os.kill(os.getpid(), signal.SIGTERM)
        for s in streams:
            try:
                toks = s.result(timeout=30)    # resolves either way
                assert len(toks) == 4
            except (serving.EngineClosed, GenerationError):
                pass                           # in-band error is legal
    finally:
        restore()
        eng.close()
    assert eng.page_pool.in_use == 0
