"""to_static / TrainStep / jit.save-load tests (modelled on the reference's
dygraph_to_static suite: static outputs must match eager)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn, optimizer


def _model():
    paddle.seed(1)
    return nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))


def test_to_static_output_parity():
    m = _model()
    x = paddle.randn([6, 4])
    eager = m(x).numpy()
    static_fwd = jit.to_static(m.forward)
    static = static_fwd(x).numpy()
    np.testing.assert_allclose(eager, static, rtol=1e-5)


def test_to_static_grad_parity():
    m = _model()
    x = paddle.randn([6, 4])
    lossf = nn.CrossEntropyLoss()
    y = paddle.to_tensor(np.array([0, 1, 0, 1, 0, 1]))

    static_fwd = jit.to_static(m.forward)
    lossf(static_fwd(x), y).backward()
    gs = m[0].weight.grad.numpy().copy()
    m.clear_gradients()
    lossf(m(x), y).backward()
    ge = m[0].weight.grad.numpy()
    np.testing.assert_allclose(gs, ge, rtol=1e-4, atol=1e-6)


def test_to_static_decorator_on_layer():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(3, 3)

        @jit.to_static
        def forward(self, x):
            return self.fc(x) * 2

    net = Net()
    x = paddle.randn([2, 3])
    out = net(x)
    expect = (x.numpy() @ net.fc.weight.numpy()
              + net.fc.bias.numpy()) * 2
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-5)


def test_to_static_respects_shape_cache():
    m = _model()
    fwd = jit.to_static(m.forward)
    a = fwd(paddle.randn([2, 4]))
    b = fwd(paddle.randn([5, 4]))   # new shape triggers retrace, not error
    assert a.shape == [2, 2] and b.shape == [5, 2]


def test_to_static_batchnorm_buffer_update():
    bn = nn.BatchNorm1D(4)
    bn.train()
    fwd = jit.to_static(bn.forward)
    x = paddle.randn([16, 4]) * 3 + 1
    before = bn._mean.numpy().copy()
    fwd(x)
    after = bn._mean.numpy()
    assert not np.allclose(before, after), "running mean must update via jit"


def test_train_step_converges_and_matches_eager():
    paddle.seed(3)
    m1 = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    m2 = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
    m2.set_state_dict(m1.state_dict())
    X = paddle.randn([16, 4])
    Y = paddle.randn([16, 1])
    lossf = nn.MSELoss()

    o1 = optimizer.SGD(learning_rate=0.1, parameters=m1.parameters())
    o2 = optimizer.SGD(learning_rate=0.1, parameters=m2.parameters())
    step = jit.TrainStep(m1, lossf, o1)
    for _ in range(5):
        l_jit = float(step(X, Y))
        loss = lossf(m2(X), Y)
        loss.backward()
        o2.step()
        o2.clear_grad()
        l_eager = float(loss)
        np.testing.assert_allclose(l_jit, l_eager, rtol=1e-4)
    np.testing.assert_allclose(
        m1[0].weight.numpy(), m2[0].weight.numpy(), rtol=1e-4, atol=1e-6)


def test_train_step_adam_with_clip():
    paddle.seed(4)
    m = _model()
    opt = optimizer.Adam(learning_rate=0.01,
                         parameters=m.parameters(),
                         grad_clip=optimizer.ClipGradByGlobalNorm(1.0))
    step = jit.TrainStep(m, nn.CrossEntropyLoss(), opt)
    X = paddle.randn([32, 4])
    Y = paddle.to_tensor(np.random.randint(0, 2, (32,)))
    losses = [float(step(X, Y)) for _ in range(30)]
    assert losses[-1] < losses[0]


def test_eval_step():
    m = _model()
    m.eval()
    step = jit.TrainStep(m, nn.CrossEntropyLoss(),
                         optimizer.SGD(0.1, parameters=m.parameters()))
    X = paddle.randn([4, 4])
    Y = paddle.to_tensor(np.array([0, 1, 1, 0]))
    loss, out = step.eval_step(X, Y)
    assert out.shape == [4, 2]
    np.testing.assert_allclose(
        float(loss), float(nn.CrossEntropyLoss()(m(X), Y)), rtol=1e-5)


def test_jit_save_load_roundtrip(tmp_path):
    m = _model()
    m.eval()
    path = str(tmp_path / "model")
    jit.save(m, path, input_spec=[jit.InputSpec([None, 4])])
    loaded = jit.load(path)
    x = paddle.randn([7, 4])
    np.testing.assert_allclose(m(x).numpy(), loaded(x).numpy(), rtol=1e-5)
    # polymorphic batch
    x2 = paddle.randn([2, 4])
    np.testing.assert_allclose(m(x2).numpy(), loaded(x2).numpy(), rtol=1e-5)


def test_static_function_with_dropout_varies_but_deterministic_under_seed():
    drop = nn.Dropout(0.5)
    drop.train()
    fwd = jit.to_static(drop.forward)
    x = paddle.ones([100])
    paddle.seed(11)
    a = fwd(x).numpy()
    b = fwd(x).numpy()
    assert not np.allclose(a, b), "different calls draw different masks"
    paddle.seed(11)
    a2 = fwd(x).numpy()
    np.testing.assert_allclose(a, a2)
