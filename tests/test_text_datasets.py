"""paddle.text.datasets real-format parsing: every test writes fixture
bytes in the ORIGINAL archive format (tarballs/zip/gz exactly as the
reference's downloads are laid out) and loads them through the public
API, asserting exact parsed content.

Reference: python/paddle/text/datasets/*.py (formats documented per
class in paddle_tpu/text/datasets.py)."""
import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu.text.datasets import (Conll05st, Imdb, Imikolov, Movielens,
                                      UCIHousing, WMT14, WMT16)


def _add_bytes(tf, name, data):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


# ---------------------------------------------------------------- imdb --
def _make_imdb(path, docs):
    """docs: {(mode, sub, i): text}"""
    with tarfile.open(path, "w:gz") as tf:
        for (mode, sub, i), text in docs.items():
            _add_bytes(tf, f"aclImdb/{mode}/{sub}/{i}.txt",
                       text.encode())


def test_imdb_parses_tar_and_builds_vocab(tmp_path):
    p = str(tmp_path / "aclImdb_v1.tar.gz")
    docs = {}
    # 'good' appears 6x, 'bad' 6x, 'meh' 2x -> cutoff=3 keeps good/bad
    for i in range(3):
        docs[("train", "pos", i)] = "good, good."
        docs[("train", "neg", i)] = "bad! bad?"
    docs[("train", "pos", 3)] = "meh meh good bad"
    docs[("test", "pos", 0)] = "good meh"
    docs[("test", "neg", 0)] = "bad unknownword"
    _make_imdb(p, docs)

    ds = Imdb(data_file=p, mode="train", cutoff=3)
    # vocab sorted by (-freq, word): good=7, bad=7 -> alphabetical
    assert ds.word_idx[b"bad"] == 0 and ds.word_idx[b"good"] == 1
    assert ds.word_idx[b"<unk>"] == 2
    assert len(ds) == 7
    # first pos doc: punctuation stripped, lowercased, mapped
    doc0, label0 = ds[0]
    assert doc0.tolist() == [1, 1] and label0.tolist() == [0]

    dt = Imdb(data_file=p, mode="test", cutoff=3)
    assert len(dt) == 2
    unk = dt.word_idx[b"<unk>"]
    docs_t = {tuple(dt[i][0].tolist()): int(dt[i][1][0])
              for i in range(2)}
    assert docs_t == {(1, unk): 0, (0, unk): 1}


def test_imdb_requires_local_file():
    with pytest.raises(ValueError, match="local archive"):
        Imdb(data_file=None)


# ------------------------------------------------------------ imikolov --
def _make_imikolov(path, train_lines, valid_lines, test_lines=()):
    with tarfile.open(path, "w") as tf:
        _add_bytes(tf, "./simple-examples/data/ptb.train.txt",
                   "\n".join(train_lines).encode() + b"\n")
        _add_bytes(tf, "./simple-examples/data/ptb.valid.txt",
                   "\n".join(valid_lines).encode() + b"\n")
        if test_lines:
            _add_bytes(tf, "./simple-examples/data/ptb.test.txt",
                       "\n".join(test_lines).encode() + b"\n")


def test_imikolov_ngram_and_seq(tmp_path):
    p = str(tmp_path / "simple-examples.tar")
    # 'a' freq 4 (+valid 2 = 6), 'b' 3, <s>/<e> counted per line
    _make_imikolov(p, ["a b a", "a b", "b"], ["a a"], ["a a"])

    ds = Imikolov(data_file=p, data_type="NGRAM", window_size=2,
                  mode="train", min_word_freq=2)
    # freqs: a=5, <s>=4, <e>=4, b=3 (train+valid only — the vocab never
    # sees test; <s>/<e> once per line); freq>2 keeps all four, sorted
    # by (-freq, word)
    wi = ds.word_idx
    assert wi[b"a"] == 0 and wi[b"<e>"] == 1 and wi[b"<s>"] == 2
    assert wi[b"b"] == 3 and wi[b"<unk>"] == 4
    # first line "<s> a b a <e>" -> bigrams
    grams = [tuple(int(x) for x in ds[i]) for i in range(4)]
    assert grams == [(2, 0), (0, 3), (3, 0), (0, 1)]

    seq = Imikolov(data_file=p, data_type="SEQ", window_size=-1,
                   mode="test", min_word_freq=2)
    src, trg = seq[0]   # ptb.test.txt line "a a" (reference: test mode
    #                     reads the TEST split, not valid)
    assert src.tolist() == [wi[b"<s>"], wi[b"a"], wi[b"a"]]
    assert trg.tolist() == [wi[b"a"], wi[b"a"], wi[b"<e>"]]


# ----------------------------------------------------------- movielens --
def _make_movielens(path):
    movies = ("1::Toy Story (1995)::Animation|Comedy\n"
              "2::Heat (1995)::Action\n")
    users = ("1::M::25::3::90210\n"
             "2::F::30::7::10001\n")
    ratings = ("1::1::5::978300760\n"
               "2::2::3::978302109\n")
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("ml-1m/movies.dat", movies.encode("latin-1"))
        z.writestr("ml-1m/users.dat", users.encode("latin-1"))
        z.writestr("ml-1m/ratings.dat", ratings.encode("latin-1"))


def test_movielens_parses_zip(tmp_path):
    p = str(tmp_path / "ml-1m.zip")
    _make_movielens(p)
    ds = Movielens(data_file=p, mode="train", test_ratio=0.0)
    assert len(ds) == 2
    by_uid = {int(ds[i][0][0]): ds[i] for i in range(2)}
    usr1 = by_uid[1]
    # layout: uid, gender, age, job, mov_id, categories, title, rating
    assert usr1[1].tolist() == [0]          # male
    assert usr1[2].tolist() == [25]
    assert usr1[3].tolist() == [3]
    assert usr1[4].tolist() == [1]          # Toy Story
    assert len(usr1[5]) == 2                # two categories
    assert len(usr1[6]) == 2                # "toy story"
    assert usr1[7].tolist() == [5.0]        # 5*2-5
    assert by_uid[2][7].tolist() == [1.0]   # 3*2-5


# ------------------------------------------------------------- conll05 --
def _make_conll05(tmp_path):
    words = "The\ncat\nsat\n\n"
    # props: col0 = verb lemma column, col1 = one predicate's labels
    # (A0* opens the A0 span, *) closes it, (V*) marks the verb
    props = "-\t(A0*\n-\t*)\nsat\t(V*)\n\n"
    buf_w, buf_p = io.BytesIO(), io.BytesIO()
    with gzip.GzipFile(fileobj=buf_w, mode="w") as g:
        g.write(words.encode())
    with gzip.GzipFile(fileobj=buf_p, mode="w") as g:
        g.write(props.encode())
    tar_p = str(tmp_path / "conll05st.tar")
    with tarfile.open(tar_p, "w") as tf:
        _add_bytes(tf, "conll05st-release/test.wsj/words/test.wsj.words.gz",
                   buf_w.getvalue())
        _add_bytes(tf, "conll05st-release/test.wsj/props/test.wsj.props.gz",
                   buf_p.getvalue())
    wd = str(tmp_path / "words.dict")
    open(wd, "w").write("The\ncat\nsat\n")
    vd = str(tmp_path / "verbs.dict")
    open(vd, "w").write("sat\n")
    td = str(tmp_path / "targets.dict")
    open(td, "w").write("B-A0\nI-A0\nB-V\nI-V\nO\n")
    return tar_p, wd, vd, td


def test_conll05_srl_tuples(tmp_path):
    tar_p, wd, vd, td = _make_conll05(tmp_path)
    ds = Conll05st(data_file=tar_p, word_dict_file=wd, verb_dict_file=vd,
                   target_dict_file=td)
    assert len(ds) == 1
    (word_idx, c_n2, c_n1, c_0, c_p1, c_p2, pred_idx, mark,
     label_idx) = ds[0]
    assert word_idx.tolist() == [0, 1, 2]     # The cat sat
    assert pred_idx.tolist() == [0, 0, 0]     # 'sat'
    ld = ds.label_dict
    assert label_idx.tolist() == [ld["B-A0"], ld["I-A0"], ld["B-V"]]
    # verb at position 2: window marks positions 0..4 clipped to n=3
    assert mark.tolist() == [1, 1, 1]
    # context words replicate across the sentence
    assert c_0.tolist() == [2, 2, 2]          # ctx_0 = 'sat'
    assert c_n1.tolist() == [1, 1, 1]         # ctx_n1 = 'cat'
    w, p, lbl = ds.get_dict()
    assert lbl["O"] == max(lbl.values())


# --------------------------------------------------------- uci_housing --
def test_uci_housing_normalisation(tmp_path):
    rows = 10
    rs = np.random.RandomState(0)
    data = rs.rand(rows, 14).astype(np.float64) * 10
    p = str(tmp_path / "housing.data")
    with open(p, "w") as f:
        for r in data:
            f.write(" ".join(f"{v:.6f}" for v in r) + "\n")
    tr = UCIHousing(data_file=p, mode="train")
    te = UCIHousing(data_file=p, mode="test")
    assert len(tr) == 8 and len(te) == 2
    feat, target = tr[0]
    assert feat.shape == (13,) and target.shape == (1,)
    # check normalisation formula on feature 0
    mx, mn, avg = data[:, 0].max(), data[:, 0].min(), data[:, 0].mean()
    expect = (data[0, 0] - avg) / (mx - mn)
    assert feat[0] == pytest.approx(expect, rel=1e-5)
    # target column is NOT normalised
    assert target[0] == pytest.approx(data[0, 13], rel=1e-5)


# --------------------------------------------------------------- wmt14 --
def _make_wmt14(path):
    src_dict = "<s>\n<e>\n<unk>\nhello\nworld\n"
    trg_dict = "<s>\n<e>\n<unk>\nbonjour\nmonde\n"
    train = "hello world\tbonjour monde\nhello novel\tbonjour nouveau\n"
    test = "world\tmonde\n"
    with tarfile.open(path, "w:gz") as tf:
        _add_bytes(tf, "wmt14/src.dict", src_dict.encode())
        _add_bytes(tf, "wmt14/trg.dict", trg_dict.encode())
        _add_bytes(tf, "wmt14/train/train", train.encode())
        _add_bytes(tf, "wmt14/test/test", test.encode())


def test_wmt14_bitext(tmp_path):
    p = str(tmp_path / "wmt14.tgz")
    _make_wmt14(p)
    ds = WMT14(data_file=p, mode="train", dict_size=5)
    assert len(ds) == 2
    src, trg, trg_next = ds[0]
    # <s> hello world <e> / <s> bonjour monde / bonjour monde <e>
    assert src.tolist() == [0, 3, 4, 1]
    assert trg.tolist() == [0, 3, 4]
    assert trg_next.tolist() == [3, 4, 1]
    # OOV maps to UNK_IDX=2
    src2 = ds[1][0]
    assert src2.tolist() == [0, 3, 2, 1]
    sd, td = ds.get_dict()
    assert sd["hello"] == 3 and td["monde"] == 4
    rd, _ = ds.get_dict(reverse=True)
    assert rd[3] == "hello"


# --------------------------------------------------------------- wmt16 --
def _make_wmt16(path):
    # en \t de; 'hallo' frequent in de, 'welt' less
    train = ("hello world\thallo welt\n"
             "hello there\thallo da\n"
             "world cup\twelt pokal\n")
    test = "hello\thallo\n"
    val = "world\twelt\n"
    with tarfile.open(path, "w") as tf:
        _add_bytes(tf, "wmt16/train", train.encode())
        _add_bytes(tf, "wmt16/test", test.encode())
        _add_bytes(tf, "wmt16/val", val.encode())


def test_wmt16_builds_and_caches_dicts(tmp_path):
    p = str(tmp_path / "wmt16.tar")
    _make_wmt16(p)
    cache = tmp_path / "cache"
    cache.mkdir()
    ds = WMT16(data_file=p, mode="test", src_dict_size=5, trg_dict_size=5,
               lang="en", dict_cache_dir=str(cache))
    # dict: <s> <e> <unk> + top-2 by freq: hello(2) world(2)
    assert ds.src_dict["<s>"] == 0 and ds.src_dict["<unk>"] == 2
    assert ds.src_dict["hello"] == 3
    assert ds.trg_dict["hallo"] == 3
    # cache keyed by archive identity, one file per language
    cached = sorted(os.listdir(cache))
    assert len(cached) == 2
    assert all(f.startswith("wmt16_") and f.endswith("_5.dict")
               for f in cached)
    # a DIFFERENT archive at another path must not reuse the cache
    p2 = str(tmp_path / "wmt16b.tar")
    with tarfile.open(p2, "w") as tf:
        _add_bytes(tf, "wmt16/train", b"apple tree\tapfel baum\n")
        _add_bytes(tf, "wmt16/test", b"apple\tapfel\n")
        _add_bytes(tf, "wmt16/val", b"tree\tbaum\n")
    ds2 = WMT16(data_file=p2, mode="test", src_dict_size=5,
                trg_dict_size=5, lang="en", dict_cache_dir=str(cache))
    assert ds2.src_dict.get("apple") == 3
    assert len(os.listdir(cache)) == 4
    src, trg, trg_next = ds[0]
    assert src.tolist() == [0, 3, 1]       # <s> hello <e>
    assert trg.tolist() == [0, 3]
    assert trg_next.tolist() == [3, 1]
    # lang='de' swaps columns
    de = WMT16(data_file=p, mode="val", src_dict_size=5, trg_dict_size=5,
               lang="de", dict_cache_dir=str(cache))
    s2 = de[0][0]
    assert s2.tolist()[1] == de.src_dict.get("welt", 2)


def test_wmt16_get_dict_reverse(tmp_path):
    p = str(tmp_path / "wmt16.tar")
    _make_wmt16(p)
    ds = WMT16(data_file=p, mode="train", src_dict_size=5,
               trg_dict_size=5, dict_cache_dir=str(tmp_path))
    rev = ds.get_dict("en", reverse=True)
    assert rev[3] == "hello"
