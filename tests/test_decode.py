"""Dynamic decode / beam search tests (reference analog: test_rnn_decode
/ test_gather_tree): deterministic toy LM where the optimal beams are
known analytically."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn


class ToyCell(nn.Layer):
    """Deterministic 'LM': next-token logits depend only on the current
    token via a fixed table; state counts steps."""

    def __init__(self, table):
        super().__init__()
        self.table = paddle.to_tensor(table)

    def forward(self, tok, states):
        logits = self.table[tok]
        return logits, states + 1


def test_greedy_beam_follows_argmax_chain():
    V = 5
    # token i deterministically prefers token (i+1) % V; token 4 -> EOS(0)
    tbl = np.full((V, V), -5.0, np.float32)
    for i in range(V):
        tbl[i, (i + 1) % V] = 5.0
    cell = ToyCell(tbl)
    dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=0,
                               beam_size=2)
    inits = paddle.zeros([3], dtype="int32")  # batch of 3 counters
    seq, scores = nn.dynamic_decode(dec, inits, max_step_num=8)
    seq = np.asarray(seq.numpy())
    assert seq.shape == (3, 2, 8)
    # best beam from start=1: 2, 3, 4, 0(EOS)
    np.testing.assert_array_equal(seq[0, 0, :4], [2, 3, 4, 0])
    # all batches identical (same start)
    np.testing.assert_array_equal(seq[0], seq[1])


def test_beams_are_sorted_and_lengths_reported():
    V = 4
    tbl = np.zeros((V, V), np.float32)
    tbl[1, 2] = 3.0   # from start=1: best is 2, then others
    tbl[1, 3] = 1.0
    tbl[2, 0] = 5.0   # 2 -> EOS fast
    tbl[3, 0] = 5.0
    cell = ToyCell(tbl)
    dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=0,
                               beam_size=2)
    seq, scores, lens = nn.dynamic_decode(
        dec, paddle.zeros([1], dtype="int32"), max_step_num=6,
        return_length=True)
    s = np.asarray(scores.numpy())[0]
    assert s[0] >= s[1]                      # sorted best-first
    assert np.asarray(seq.numpy())[0, 0, 0] == 2
    ls = np.asarray(lens.numpy())[0]
    assert ls[0] == 2                        # token + EOS


def test_gather_tree_backtracks():
    import paddle_tpu.nn as pnn
    # T=3, B=1, K=2
    ids = np.array([[[5, 6]], [[7, 8]], [[9, 10]]], np.int32)
    parents = np.array([[[0, 0]], [[1, 0]], [[0, 1]]], np.int32)
    out = pnn.gather_tree(paddle.to_tensor(ids), paddle.to_tensor(parents))
    got = np.asarray(out.numpy())
    # beam 0: t=2 token ids[2,0]=9, parent 0 -> t=1 token ids[1,0]=7,
    # parent 1 -> t=0 token ids[0,1]=6
    np.testing.assert_array_equal(got[:, 0, 0], [6, 7, 9])


def test_decode_with_embedding_and_projection():
    paddle.seed(0)
    H, V = 8, 12

    class GruLM(nn.Layer):
        def __init__(self):
            super().__init__()
            self.cell = nn.GRUCell(H, H)

        def forward(self, x, states):
            out, new = self.cell(x, states)
            return out, new

    emb = nn.Embedding(V, H)
    proj = nn.Linear(H, V)
    lm = GruLM()
    dec = nn.BeamSearchDecoder(lm, start_token=1, end_token=0, beam_size=3,
                               embedding_fn=emb, output_fn=proj)
    h0 = paddle.zeros([2, H])
    seq, scores = nn.dynamic_decode(dec, h0, max_step_num=5)
    assert list(seq.shape) == [2, 3, 5]
    assert np.isfinite(np.asarray(scores.numpy())).all()


def test_early_exit_preserves_distinct_beams():
    """Early loop exit (all beams finish before max_step_num) must not
    collapse non-best beams onto beam 0's tokens, and padding is
    end_token."""
    V = 6
    EOS = 5
    tbl = np.full((V, V), -9.0, np.float32)
    tbl[1, 2] = 2.0    # start=1: best next is 2, second-best 3
    tbl[1, 3] = 1.0
    tbl[2, EOS] = 9.0  # both then finish immediately
    tbl[3, EOS] = 9.0
    cell = ToyCell(tbl)
    dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=EOS,
                               beam_size=2)
    seq, scores = nn.dynamic_decode(
        dec, paddle.zeros([1], dtype="int32"), max_step_num=10)
    s = np.asarray(seq.numpy())[0]
    np.testing.assert_array_equal(s[0, :2], [2, EOS])
    np.testing.assert_array_equal(s[1, :2], [3, EOS])  # distinct beam!
    assert np.all(s[:, 2:] == EOS)  # padding is end_token


def test_gather_tree_hand_computed_trellis():
    """gather_tree against a fully hand-backtracked B=2, K=3, T=4
    trellis (satellite: direct coverage of the backtracking rule
    beams[t-1] = parents[t][beams[t]])."""
    import paddle_tpu.nn as pnn
    # ids[t, b, k], parents[t, b, k]
    ids = np.array(
        [[[10, 11, 12], [20, 21, 22]],
         [[13, 14, 15], [23, 24, 25]],
         [[16, 17, 18], [26, 27, 28]],
         [[19, 30, 31], [29, 32, 33]]], np.int32)
    parents = np.array(
        [[[0, 0, 0], [0, 0, 0]],
         [[2, 0, 1], [1, 2, 0]],
         [[1, 2, 0], [0, 1, 2]],
         [[2, 0, 1], [2, 0, 1]]], np.int32)
    out = pnn.gather_tree(paddle.to_tensor(ids),
                          paddle.to_tensor(parents))
    got = np.asarray(out.numpy())
    # batch 0, beam 0: t=3 token 19 parent 2 -> t=2 token 18 parent 0
    #   -> t=1 token 13 parent 2 -> t=0 token 12
    np.testing.assert_array_equal(got[:, 0, 0], [12, 13, 18, 19])
    # batch 0, beam 1: t=3 token 30 parent 0 -> t=2 token 16 parent 1
    #   -> t=1 token 14 parent 0 -> t=0 token 10
    np.testing.assert_array_equal(got[:, 0, 1], [10, 14, 16, 30])
    # batch 1, beam 2: t=3 token 33 parent 1 -> t=2 token 27 parent 1
    #   -> t=1 token 24 parent 2 -> t=0 token 22
    np.testing.assert_array_equal(got[:, 1, 2], [22, 24, 27, 33])


def test_early_exit_matches_exact_horizon():
    """All beams finish at step 2; decoding with a generous T_max must
    early-exit to the SAME tokens/scores/lengths as the exact-horizon
    run (the loop predicate, not the step budget, ends the loop)."""
    V, EOS = 6, 5
    tbl = np.full((V, V), -9.0, np.float32)
    tbl[1, 2], tbl[1, 3] = 2.0, 1.0
    tbl[2, EOS] = 9.0
    tbl[3, EOS] = 9.0
    cell = ToyCell(tbl)
    dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=EOS,
                               beam_size=2)

    def run(t_max):
        seq, sc, lens = nn.dynamic_decode(
            dec, paddle.zeros([2], dtype="int32"), max_step_num=t_max,
            return_length=True)
        return (np.asarray(seq.numpy()), np.asarray(sc.numpy()),
                np.asarray(lens.numpy()))

    s_big, sc_big, l_big = run(40)
    s_exact, sc_exact, l_exact = run(2)
    np.testing.assert_array_equal(s_big[:, :, :2], s_exact)
    np.testing.assert_allclose(sc_big, sc_exact, rtol=0, atol=0)
    np.testing.assert_array_equal(l_big, l_exact)
    assert np.all(s_big[:, :, 2:] == EOS)   # padding past the exit


def test_cell_step_single_step_api():
    """nn.cell_step is one step of the cell contract: log-softmaxed
    logits + raw-array states (what a token-level scheduler drives)."""
    V = 5
    tbl = np.arange(V * V, dtype=np.float32).reshape(V, V) / 10.0
    dec = nn.BeamSearchDecoder(ToyCell(tbl), start_token=1, end_token=0,
                               beam_size=2)
    states = paddle.zeros([3], dtype="int32")
    toks = np.array([1, 4, 2], np.int32)
    logp, new_states = nn.cell_step(dec, toks, states)
    logp = np.asarray(logp)
    assert logp.shape == (3, V)
    ref = np.asarray(tbl[toks])
    ref = ref - np.log(np.exp(ref - ref.max(-1, keepdims=True)).sum(
        -1, keepdims=True)) - ref.max(-1, keepdims=True)
    np.testing.assert_allclose(logp, ref, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(new_states), [1, 1, 1])


def test_dynamic_decode_cache_replays_one_compile():
    """cache=True: same decoder/shapes reuse one compiled loop; a
    different start token rides the SAME executable (traced input) and
    still decodes its own chain."""
    from paddle_tpu.nn import decode as decode_mod
    V = 5
    tbl = np.full((V, V), -5.0, np.float32)
    for i in range(V):
        tbl[i, (i + 1) % V] = 5.0
    cell = ToyCell(tbl)
    dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=0,
                               beam_size=2)
    inits = paddle.zeros([2], dtype="int32")
    before = len(decode_mod._DECODE_CACHE)
    s1, _ = nn.dynamic_decode(dec, inits, max_step_num=6, cache=True)
    after_first = len(decode_mod._DECODE_CACHE)
    s1b, _ = nn.dynamic_decode(dec, inits, max_step_num=6, cache=True)
    dec.start_token = 2                     # traced: same executable
    s2, _ = nn.dynamic_decode(dec, inits, max_step_num=6, cache=True)
    assert after_first == before + 1
    assert len(decode_mod._DECODE_CACHE) == after_first
    np.testing.assert_array_equal(np.asarray(s1.numpy()),
                                  np.asarray(s1b.numpy()))
    # start=2 follows its own chain: 3, 4, 0(EOS)
    np.testing.assert_array_equal(
        np.asarray(s2.numpy())[0, 0, :3], [3, 4, 0])
    dec.start_token = 1
    # uncached path agrees with cached
    s_ref, _ = nn.dynamic_decode(dec, inits, max_step_num=6)
    np.testing.assert_array_equal(np.asarray(s1.numpy()),
                                  np.asarray(s_ref.numpy()))
